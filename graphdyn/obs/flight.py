"""The always-on flight recorder (ARCHITECTURE.md "Runtime telemetry" →
flight recorder).

A crash without ``--obs-ledger`` used to leave ZERO runtime evidence: the
event ledger is opt-in, and everything the null recorder was told went
nowhere. This module keeps the last :data:`DEFAULT_CAPACITY` counter/gauge
events in a bounded in-memory ring **behind the null recorder** — default
on, no I/O, allocation-bounded by construction (a ``deque(maxlen=N)`` of
small event dicts; regression-tested with tracemalloc) — and dumps them as
a schema-valid post-mortem ledger when a run dies:

- **unhandled driver exception** (the CLI re-raises after dumping),
- **``sweep.nan`` degrade** (the solver continues with the non-convergence
  sentinel, but the poisoned-state evidence is preserved at the moment it
  happened),
- **SIGTERM → exit 75 preemption** (the graceful-shutdown path, reusing
  the resilience hooks — :class:`ShutdownRequested.where` names the
  boundary that honored the signal).

The dump target is ``<workdir>/obs_postmortem.jsonl`` — the same JSONL
schema as a real ledger (``read_ledger``/``python -m graphdyn.obs report``
work on it unchanged): a ``manifest`` stamped ``postmortem: true``, the
ring's tail events, then one final ``obs.crash`` counter event naming the
failure site. When a real recorder IS installed the ledger is already the
evidence: :func:`dump` emits the ``obs.crash`` event into it and writes no
file. A clean run triggers no dump and leaves no file.

``GRAPHDYN_FLIGHT=0`` disarms the ring (the only configuration knob — the
whole point is that nobody has to ask for it).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time

_MONO = time.monotonic

DEFAULT_CAPACITY = 512
ENV_VAR = "GRAPHDYN_FLIGHT"
POSTMORTEM_NAME = "obs_postmortem.jsonl"

_lock = threading.Lock()
_ring: collections.deque = collections.deque(maxlen=DEFAULT_CAPACITY)
_t0 = _MONO()


def armed() -> bool:
    """True unless ``GRAPHDYN_FLIGHT=0`` — the null recorder forwards its
    counter/gauge events into the ring only then."""
    return os.environ.get(ENV_VAR) != "0"


def capacity() -> int:
    return _ring.maxlen or 0


def configure(capacity: int) -> None:
    """Resize the ring (tests; keeps the newest events)."""
    global _ring
    with _lock:
        _ring = collections.deque(_ring, maxlen=int(capacity))


def clear() -> None:
    with _lock:
        _ring.clear()


def snapshot() -> list[dict]:
    """The ring's current contents, oldest first."""
    with _lock:
        return list(_ring)


def record_counter(name: str, inc: int, attrs: dict) -> None:
    """Ring-append one counter event (called by the null recorder)."""
    doc = {"ev": "counter", "t": round(_MONO() - _t0, 6), "name": name,
           "inc": inc}
    if attrs:
        doc["attrs"] = attrs
    with _lock:
        _ring.append(doc)


def record_gauge(name: str, value, attrs: dict) -> None:
    """Ring-append one gauge event (called by the null recorder)."""
    doc = {"ev": "gauge", "t": round(_MONO() - _t0, 6), "name": name,
           "value": value}
    if attrs:
        doc["attrs"] = attrs
    with _lock:
        _ring.append(doc)


def _crash_attrs(reason: str, exc, site) -> dict:
    attrs = {"reason": reason}
    # the last liveness heartbeat (count / boundary / age): the ring holds
    # only the newest 512 events, so a long tail of non-heartbeat noise
    # could rotate the obs.heartbeat gauges out — the crash event itself
    # names the last boundary the run crossed, unconditionally
    try:
        from graphdyn.resilience.supervisor import last_beat

        n, t, where = last_beat()
        if n > 0:
            attrs["heartbeat_n"] = n
            attrs["heartbeat_age_s"] = round(_MONO() - t, 3)
            if where is not None:
                attrs["heartbeat_where"] = where
    except Exception:  # noqa: BLE001 — crash-path telemetry never raises
        pass
    # with the graftrace runtime armed (GRAPHDYN_RACECHECK=1), stamp what
    # every thread currently HOLDS: the per-acquire ring events can rotate
    # out under a long tail, but the crash event itself must name the lock
    # a wedged run died holding (the heartbeat-stamp precedent above)
    try:
        from graphdyn.analysis import racecheck as _rc

        if _rc.installed():
            held = _rc.held_locks()
            if held:
                attrs["locks_held"] = {
                    t: "|".join(st) for t, st in sorted(held.items())
                }
    except Exception:  # noqa: BLE001 — crash-path telemetry never raises
        pass
    if exc is not None:
        attrs["exc_type"] = type(exc).__name__
        attrs["message"] = str(exc)[:500]
        if site is None:
            # the failure site: the innermost frame of the traceback
            tb = getattr(exc, "__traceback__", None)
            if tb is not None:
                import traceback

                frames = traceback.extract_tb(tb)
                if frames:
                    f = frames[-1]
                    site = f"{f.filename}:{f.lineno} in {f.name}"
    if site is not None:
        attrs["site"] = site
    return attrs


def dump(reason: str, *, exc=None, site=None, workdir=None) -> str | None:
    """Persist the flight evidence for a failing run.

    With a real recorder installed, the ``obs.crash`` counter event goes
    into the live ledger (the ledger IS the evidence) and no file is
    written. Otherwise the ring + crash event are written atomically to
    ``<workdir>/obs_postmortem.jsonl`` and the path is returned. Never
    raises — a broken dump must not mask the failure it is documenting —
    and returns None when nothing was written.
    """
    if not armed():
        return None
    try:
        from graphdyn import obs

        attrs = _crash_attrs(reason, exc, site)
        rec = obs.current()
        if rec.enabled:
            rec.counter("obs.crash", **attrs)
            return None
        t = round(_MONO() - _t0, 6)
        run = {"schema": obs.SCHEMA, "pid": os.getpid(),
               "time_unix": time.time(), "postmortem": True,
               "reason": reason}
        try:
            run.update(obs.run_manifest_fields())
        except Exception:  # jax/backend unavailable: identity is best-effort
            pass
        events = [{"ev": "manifest", "t": t, "run": run}]
        events.extend(snapshot())
        events.append({"ev": "counter", "t": t, "name": "obs.crash",
                       "inc": 1, "attrs": attrs})
        from graphdyn.utils.io import write_text_atomic

        path = os.path.join(workdir or os.getcwd(), POSTMORTEM_NAME)
        write_text_atomic(path, "".join(
            json.dumps(e, separators=(",", ":"), default=str) + "\n"
            for e in events
        ))
        return path
    except Exception:  # noqa: BLE001 — crash-path telemetry never raises
        return None
