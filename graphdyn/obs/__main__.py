"""CLI: ``python -m graphdyn.obs <report|check|memcheck|trend> ...``.

- ``report LEDGER`` — render a JSONL event ledger as a span-tree/counter
  summary (``--format=text|json``).
- ``check`` — the roofline obscheck: measure the headline CPU proxies
  against the byte-model bands (:mod:`graphdyn.obs.roofline`). Exit code =
  out-of-band programs. The ``scripts/lint.sh`` obscheck step.
- ``memcheck`` — the device-memory bands (:mod:`graphdyn.obs.memband`):
  measured peak bytes against the ARCHITECTURE.md byte models; on a
  backend without usable ``memory_stats`` every row is an explicit
  null + reason and the gate passes structurally. Exit code = out-of-band
  rows. The ``scripts/lint.sh`` memcheck step.
- ``trend ROW.json`` — the cross-round rate gate
  (:mod:`graphdyn.obs.trend`): diff a bench row against the latest
  comparable committed round; ``--bless`` commits the row's rates to
  ``OBS_TREND.json`` instead. Exit code = unblessed drift findings.

Output contract (PR-6, shared with graftlint/graftcheck): with
``--format=json`` stdout carries exactly ONE JSON document; every
diagnostic goes to stderr.
"""

from __future__ import annotations

import argparse
import json
import sys


def _diag(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m graphdyn.obs",
        description="graphdyn runtime-telemetry tools "
                    "(exit code = number of findings)",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    rep = sub.add_parser("report", help="render a JSONL event ledger")
    rep.add_argument("ledger", help="path to the obs ledger (JSONL)")
    rep.add_argument("--format", choices=("text", "json"), default="text")

    chk = sub.add_parser("check", help="roofline obscheck (CPU proxy bands)")
    chk.add_argument("--format", choices=("text", "json"), default="text")

    mem = sub.add_parser(
        "memcheck", help="device-memory bands (byte models vs measured "
                         "peak; null+reason on stats-less backends)")
    mem.add_argument("--format", choices=("text", "json"), default="text")

    trd = sub.add_parser("trend", help="cross-round bench rate gate")
    trd.add_argument("row", help="bench row JSON file (one object)")
    trd.add_argument("--format", choices=("text", "json"), default="text")
    trd.add_argument("--bless", action="store_true",
                     help="commit this row's rates to OBS_TREND.json as "
                          "the deliberate baseline instead of diffing")
    trd.add_argument("--ledger", default=None,
                     help="trend-ledger path (default: repo-root "
                          "OBS_TREND.json)")
    args = ap.parse_args(argv)

    if args.cmd == "report":
        from graphdyn.obs.report import load_summary, render_text

        doc = load_summary(args.ledger, diag=_diag)
        if args.format == "json":
            print(json.dumps(doc, indent=2, default=str))
        else:
            render_text(doc)
        return 0

    if args.cmd == "check":
        from graphdyn.obs.roofline import run_obscheck

        rows = run_obscheck(diag=_diag)
        bad = [r for r in rows if not r.ok]
        if args.format == "json":
            print(json.dumps([r._asdict() | {"ok": r.ok} for r in rows],
                             indent=2))
        else:
            for r in rows:
                print(f"{r.program}: frac={r.frac:.3f} "
                      f"band=[{r.lo:g},{r.hi:g}] "
                      f"{'ok' if r.ok else 'OUT OF BAND'}")
        if bad:
            _diag(f"obscheck: {len(bad)} program(s) out of band")
        else:
            _diag(f"obscheck: {len(rows)} program(s) within band")
        return min(len(bad), 125)

    if args.cmd == "memcheck":
        from graphdyn.obs.memband import run_memcheck

        rows = run_memcheck(diag=_diag)
        bad = [r for r in rows if not r.ok]
        if args.format == "json":
            print(json.dumps([r._asdict() | {"ok": r.ok} for r in rows],
                             indent=2))
        else:
            for r in rows:
                if r.measured is None:
                    print(f"{r.program}: model={r.model:g}B measured=null "
                          f"({r.reason}) structural-pass")
                else:
                    print(f"{r.program}: frac={r.frac:.3f} "
                          f"band=[{r.lo:g},{r.hi:g}] "
                          f"{'ok' if r.ok else 'OUT OF BAND'}")
        if bad:
            _diag(f"memcheck: {len(bad)} row(s) out of band")
        else:
            _diag(f"memcheck: {len(rows)} row(s) ok")
        return min(len(bad), 125)

    # trend
    from graphdyn.obs.trend import (
        check_trend, load_trend_ledger, write_trend_ledger,
    )

    with open(args.row) as fh:
        row = json.load(fh)
    if args.bless:
        path = write_trend_ledger(row, args.ledger)
        _diag(f"obs trend: blessed rates for backend={row.get('backend')} "
              f"metric={row.get('metric')} into {path}")
        if args.format == "json":
            print(json.dumps({"blessed": True, "ledger": path}))
        return 0
    ledger = load_trend_ledger(args.ledger) if args.ledger else None
    findings, status = check_trend(row, ledger=ledger, diag=_diag)
    if args.format == "json":
        print(json.dumps({
            "status": status,
            "findings": [f._asdict() for f in findings],
        }, indent=2))
    else:
        for f in findings:
            print(f"{f.row}: {f.code} {f.message}")
        print(f"trend: {status}")
    return min(len(findings), 125) if status == "drift" else 0


if __name__ == "__main__":
    sys.exit(main())
