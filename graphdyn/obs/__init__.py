"""graphdyn.obs — structured runtime telemetry (ARCHITECTURE.md "Runtime
telemetry").

PR 6's graftcheck made *program structure* falsifiable off-chip; this
subsystem does the same for *runtime behavior*: where time goes inside a
run, whether measured CPU-proxy rates match the byte model
(:mod:`graphdyn.obs.roofline`), and whether a bench round regressed against
the last same-backend round (:mod:`graphdyn.obs.trend`). Zero third-party
dependencies; one timing idiom for the whole repo (the old
``utils.profiling.StepTimer``/``wall_clock`` and ``bench.py``'s inline
``time.perf_counter`` brackets are shims over / callers of this API —
graftlint GD011 keeps bare timing out of the driver modules).

Surface (all module-level, delegating to the installed recorder):

- :func:`span` — a recording span context manager (nested, monotonic
  clock, wall + process-CPU time). On the default :data:`NULL` recorder it
  returns one shared no-op object: **one attribute check, no allocation**.
- :func:`timed` — an *always-measuring* span: callers that need the
  duration for their own results (bench rates, solver ``elapsed_s``) get
  real numbers whether or not a ledger is being written; the event is
  emitted only when recording.
- :func:`counter` / :func:`gauge` — occurrence counts and point-in-time
  values.
- :func:`manifest` — the per-run identity event (backend, jax version,
  git sha, config).
- :func:`recording` — install a :class:`Recorder` writing the JSONL event
  ledger for a scope (CLI ``--obs-ledger PATH`` / ``GRAPHDYN_OBS=PATH``),
  with compile-cache miss counters captured via the graftcheck
  ``RecompileWatch`` machinery.

Device-side eyes (PR-8), layered on the same surface:

- :mod:`graphdyn.obs.trace` — aligned ``jax.profiler`` capture (CLI
  ``--profile DIR`` / ``GRAPHDYN_PROFILE=DIR``): while profiling, every
  span additionally opens a ``TraceAnnotation`` named with its ledger
  name-path, so the device timeline and the JSONL ledger share one
  vocabulary.
- :mod:`graphdyn.obs.memband` — ``Device.memory_stats()`` gauges
  (``obs.mem.bytes_in_use``/``obs.mem.peak``) at the pipeline chunk
  boundaries, plus the memcheck byte-model bands
  (``python -m graphdyn.obs memcheck``).
- :mod:`graphdyn.obs.flight` — the always-on bounded flight-recorder ring
  behind the null recorder, dumped as ``obs_postmortem.jsonl`` on
  unhandled exception / ``sweep.nan`` degrade / SIGTERM→exit-75, so a
  crash without a ledger still leaves evidence.

Ledger schema and the span/counter taxonomy: :mod:`graphdyn.obs.recorder`
docstring + ARCHITECTURE.md. Render a ledger with
``python -m graphdyn.obs report LEDGER``.
"""

from __future__ import annotations

import contextlib
import os
import subprocess

from graphdyn.obs.recorder import (  # noqa: F401  (re-exports)
    NULL,
    NULL_SPAN,
    SCHEMA,
    NullRecorder,
    Recorder,
    Span,
    read_ledger,
)
from graphdyn.obs import flight, memband, trace  # noqa: F401  (device-side surface)

ENV_VAR = "GRAPHDYN_OBS"

_REC = NULL


def current():
    """The installed recorder (:data:`NULL` unless inside
    :func:`recording`)."""
    return _REC


def enabled() -> bool:
    """True when a real recorder is installed — instrumentation sites gate
    *expensive attribute computation* (device syncs, array reductions) on
    this, never the span call itself."""
    return _REC.enabled


def span(name: str, **attrs):
    """A recording span for the current recorder (no-op + no allocation on
    :data:`NULL`)."""
    return _REC.span(name, **attrs)


def timed(name: str, **attrs) -> Span:
    """An always-measuring span: ``with obs.timed("bench.x") as sp: ...``
    then read ``sp.wall_s``/``sp.cpu_s`` — or imperative
    ``sw = obs.timed(...).start(); ...; sw.stop()``. Emits a span event
    only when a recorder is installed."""
    return Span(_REC if _REC.enabled else None, name, attrs)


def counter(name: str, inc: int = 1, **attrs) -> None:
    _REC.counter(name, inc, **attrs)


def gauge(name: str, value, **attrs) -> None:
    _REC.gauge(name, value, **attrs)


def manifest(**fields):
    """Emit the per-run manifest event; returns the ``run`` dict (or None
    on the null recorder)."""
    return _REC.manifest(**fields)


def git_sha() -> str | None:
    """Best-effort repo sha for the manifest (None outside a checkout)."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=5,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
        )
    except (OSError, subprocess.SubprocessError):
        return None
    sha = out.stdout.strip()
    return sha if out.returncode == 0 and sha else None


def run_manifest_fields(**extra) -> dict:
    """The standard manifest payload: environment identity every driver
    stamps (backend/jax imported lazily — the manifest is emitted after the
    CLI has already chosen a platform)."""
    import platform

    import jax

    return {
        "backend": jax.default_backend(),
        "jax": jax.__version__,
        "python": platform.python_version(),
        "git_sha": git_sha(),
        **extra,
    }


@contextlib.contextmanager
def recording(path: str | None = None):
    """Install a :class:`Recorder` writing to ``path`` for the scope.

    ``path=None`` falls back to the ``GRAPHDYN_OBS`` environment variable;
    when that is unset too, the scope runs on the null recorder (the
    common case — zero cost). Yields the active recorder either way.

    While recording, XLA compile-cache **misses** are counted live: the
    graftcheck ``RecompileWatch`` machinery (``jax_log_compiles`` capture —
    cache hits log nothing, so misses are exact) feeds one
    ``jax.compile`` counter event per compiled program, tagged with the
    entry-point name. Nested ``recording`` scopes are an error only when
    both would install a recorder; re-entering with no path inside an
    active scope keeps the outer recorder.
    """
    global _REC
    path = path or os.environ.get(ENV_VAR) or None
    if path is None or _REC.enabled:
        if path is not None and _REC.enabled:
            raise RuntimeError(
                "nested obs.recording() with an explicit path — one ledger "
                f"per run (active: {getattr(_REC, 'path', '?')!r})"
            )
        yield _REC
        return
    rec = Recorder(path)
    _REC = rec
    try:
        with _compile_counter(rec):
            yield rec
    finally:
        _REC = NULL
        rec.close()


@contextlib.contextmanager
def _compile_counter(rec: Recorder):
    """Emit a ``jax.compile`` counter event per XLA compile-cache miss
    inside the scope (RecompileWatch reuse — see :func:`recording`). Events
    are emitted live, so a preempted run's ledger still carries the misses
    that happened before the signal."""
    try:
        from graphdyn.analysis.graftcheck import RecompileWatch
    except Exception:  # pragma: no cover — analysis layer absent/broken
        yield
        return

    class _EmittingWatch(RecompileWatch):
        class _List(list):
            def append(self, item):
                super().append(item)
                name, _ = item
                rec.counter("jax.compile", fn=name)

        def __init__(self):
            super().__init__()
            self.events = self._List()

    with _EmittingWatch():
        yield
