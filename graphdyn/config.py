"""Config surface — every hand-edited constant block of the reference as
dataclasses (SURVEY.md §5.6; reference `SA_RRG.py:44-56`,
`HPR_pytorch_RRG.py:222-255`, `ER_BDCM_entropy.ipynb:455-482`)."""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field


@dataclass(frozen=True)
class DynamicsConfig:
    """(p,c) backtracking-attractor dynamics parameters."""

    p: int = 1
    c: int = 1
    rule: str = "majority"      # 'majority' | 'minority'
    tie: str = "stay"           # 'stay' | 'change'
    attr_value: int = 1         # pinned attractor endpoint (`HPR:230`)

    @property
    def horizon(self) -> int:
        return self.p + self.c


@dataclass(frozen=True)
class GraphConfig:
    """Ensemble parameters: RRG(n,d) or ER G(n, deg/(n-1))."""

    kind: str = "rrg"           # 'rrg' | 'er'
    n: int = 10_000
    d: int = 4                  # RRG degree
    mean_degree: float = 2.0    # ER mean degree; p = mean_degree/(n-1)
    method: str = "pairing"     # 'pairing'|'numpy'|'networkx'|'native'

    @property
    def er_p(self) -> float:
        return self.mean_degree / (self.n - 1)


@dataclass(frozen=True)
class SAConfig:
    """Simulated-annealing search (`SA_RRG.py:44-56,67-84`)."""

    dynamics: DynamicsConfig = field(default_factory=lambda: DynamicsConfig(p=3, c=1))
    a0_frac: float = 0.015      # a = a0_frac * n  (`SA_RRG.py:67`)
    b0_frac: float = 0.010      # b = b0_frac * n  (`SA_RRG.py:68`)
    par_a: float = 1.0005       # per-step anneal multipliers (`:49-50`)
    par_b: float = 1.0005
    a_cap_frac: float = 4.5     # cap a at 4.5n (`:80`)
    b_cap_frac: float = 5.0     # cap b at 5n  (`:81`)
    max_steps: int | None = None  # default 2n^3 (`:84`); sentinel m_final=2
    n_replicas: int = 1
    seed: int = 0


@dataclass(frozen=True)
class HPRConfig:
    """History-Passing reinforcement (`HPR_pytorch_RRG.py:222-237`)."""

    dynamics: DynamicsConfig = field(default_factory=DynamicsConfig)
    damp: float = 0.4           # damppar (`:229`)
    lmbd: float = 25.0          # effective tilt = lmbd_in/n (`:231` with `/n` at `:39`)
    pie: float = 0.3            # reinforcement π (`:235`)
    gamma: float = 0.1          # reinforcement γ (`:236`)
    max_sweeps: int = 10_000    # TT (`:237`)
    eps_clamp: float = 1e-15    # marginal Z clamp (`:147`)
    n_replicas: int = 1
    seed: int = 0
    dtype: str = "float32"      # messages/marginals/biases dtype. The
                                # reference runs the whole solver in float64
                                # (`HPR_pytorch_RRG.py:11`); 'float64'
                                # reproduces that (requires jax_enable_x64),
                                # 'float32' is the TPU-first throughput
                                # default.


@dataclass(frozen=True)
class EntropyConfig:
    """BDCM entropy λ-sweep (`ER_BDCM_entropy.ipynb:455-482`)."""

    dynamics: DynamicsConfig = field(default_factory=DynamicsConfig)
    lmbd_max: float = 12.0
    lmbd_step: float = 0.1
    eps: float = 1e-6           # fixed-point tolerance (`ipynb:470`)
    damp: float = 0.1           # damppar (`ipynb:471`)
    eps_clamp: float = 0.0      # epsilon floor for Z and chi (`ipynb:473`)
    max_sweeps: int = 1300      # T_max (`ipynb:478`)
    ent_floor: float = -0.05    # early-exit threshold (`ipynb:446`)
    plateau_eps: float = 0.0    # opt-in: stop the ladder when (m_init, ent1)
                                # change less than this for plateau_patience
                                # consecutive λ (0 = off, reference behavior;
                                # T>=3 curves floor at positive ent1 where the
                                # reference's ent_floor exit never fires)
    plateau_patience: int = 3
    num_rep: int = 3
    seed: int = 0
    # checkpoint-fingerprint opt-in fields (graphdyn.utils.io._fingerprint_repr):
    # omitted from the fingerprint at their defaults, so checkpoints written
    # before these fields existed still resume; declared here because the
    # mechanism keys off THIS attribute — without it the skip is dead code
    _fingerprint_optional = ("plateau_eps", "plateau_patience")
    dtype: str = "float32"      # 'float64' matches the reference's precision
                                # (numpy default / `HPR_pytorch_RRG.py:11`);
                                # requires jax_enable_x64


def asdict(cfg) -> dict:
    return dataclasses.asdict(cfg)
