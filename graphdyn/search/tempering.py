"""Replica-exchange (parallel tempering) for the SA initialization search.

The reference search (`SA_RRG.py:58-88`) is ONE Metropolis chain at one
annealing schedule; PRs 1–12 made its rollout wider and cheaper but never
the search itself faster. Optimized-SA practice for spin glasses (PAPERS.md
arXiv:1401.1084) runs a **temperature ladder**: K chains at scaled
Hamiltonians ``H_k = β_k·E`` anneal side by side and periodically attempt
to exchange configurations between adjacent rungs, so cold (greedy) rungs
inherit the hot rungs' barrier crossings instead of waiting out the anneal.

Layout: the K lanes ride the SAME batched replica axis the λ-ladder and
the grouped drivers use (``run_cell_ladder``/``GroupDriver`` are the
template) — one jitted chunk program advances every active lane in
lockstep (the per-lane draw/accept/anneal arithmetic is literally
:func:`graphdyn.models.sa.draw_sa_proposal` +
:func:`graphdyn.models.sa.metropolis_anneal_update`, so a lane's chain law
is the serial solver's by construction), and the **swap move runs at each
chunk boundary inside the same program**: seeded even/odd pairing
(round parity alternates the pairing), acceptance
``u < exp(−Δ)`` with ``Δ = [(a_i−a_j)(S0_j−S0_i) − (b_i−b_j)(Se_j−Se_i)]/n``
(the exact cross-energy difference of the linear objective — no rollout
re-evaluation), configurations (``s``, ``Σs_end``) migrate while the
ladder's (``a``, ``b``, PRNG keys, step counters) stay with their lanes.
Inactive lanes (success or timeout) never swap; per-lane freeze is the
replica-batched solver's existing ``active`` mask.

Durability: chunk boundaries are swap boundaries, and the chunk boundary
is also the snapshot/heartbeat/shutdown-poll site
(:class:`graphdyn.utils.io.ChainCheckpointer` — the PR-9 durable store +
run journal underneath). Snapshots are GLOBAL (lane-layout-agnostic), so a
preempted ladder resumes **bit-exact across lane-shard counts**: a K=8
ladder sharded one-lane-per-device requeues onto 4 devices (two lanes per
device) and finishes identical to the fault-free run — the same
shard-loss requeue contract the halo snapshots carry for the node axis.
Lane sharding composes through :func:`graphdyn.parallel.mesh.shard_stack`
(the lane axis is the group axis); node-axis modes stay with
``sa_sharded`` — a tempering ladder per node-sharded rollout is the
composition ARCHITECTURE.md's mode table routes through the mesh solver's
per-replica ``a0`` ladder.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from graphdyn.config import SAConfig
from graphdyn.ops.dynamics import rule_coefficients


class TemperResult(NamedTuple):
    """Per-lane results + ladder statistics."""

    s: np.ndarray                  # int8[K, n] configuration at stop
    mag_reached: np.ndarray        # f32[K] m(s(0)) at stop
    num_steps: np.ndarray          # int64[K] MCMC steps per lane
    m_final: np.ndarray            # f32[K] (2.0 timeout sentinel)
    t_target: np.ndarray           # int64[K] first-passage step, −1
    betas: np.ndarray              # f64[K] the ladder
    swap_attempts: int
    swap_accepts: int
    swap_acceptance_rate: float    # accepts/attempts (0.0 when 0 attempts)
    steps_to_target: int           # min positive first passage, −1 if none
    target_lane: int               # lane that got there first, −1 if none


class _TemperState(NamedTuple):
    s: jnp.ndarray          # int8[K, n]
    sum_end: jnp.ndarray    # int32[K]
    a: jnp.ndarray          # f[K]
    b: jnp.ndarray          # f[K]
    t: jnp.ndarray          # int[K]
    m_final: jnp.ndarray    # f[K]
    active: jnp.ndarray     # bool[K]
    key: jnp.ndarray        # per-lane PRNG keys [K]
    t_target: jnp.ndarray   # int[K] first step with Σs_end ≥ target, −1
    chunk_t: jnp.ndarray    # int32[]
    swap_round: jnp.ndarray  # int32[]
    swap_att: jnp.ndarray   # int32[] cumulative attempted pair swaps
    swap_acc: jnp.ndarray   # int32[] cumulative accepted pair swaps


#: longest fixed-budget chunk plan a no-sync drive loop will dispatch:
#: past this, thousands of potentially-no-op dispatches cost more than the
#: one scalar readback they save, so auto mode keeps the stop test.
#: Public: the fused driver (graphdyn.search.fused) shares the bound.
MAX_FIXED_PLAN_CHUNKS = 4096


def ladder_betas(n_lanes: int, beta_min: float = 1.0,
                 beta_max: float = 64.0) -> np.ndarray:
    """The default geometric **drive ladder**, reference → greedy. Lane
    ``k``'s Hamiltonian is ``H_k = (a·Σs(0) − β_k·b·Σs_end)/n``: β scales
    the end-state drive ``b`` (initial value AND cap) while the
    initialization penalty ``a`` keeps the reference schedule — scaling
    both uniformly cancels in the acceptance and buys nothing (measured),
    whereas the b/a ratio is the knob that moves time-to-target by an
    order of magnitude (the schedule-shape lever of arXiv:1401.1084).
    β = 1 is the reference chain — careful, finds low-m(0) inits slowly;
    large β climbs ``Σs_end`` greedily and reaches the target fast; swaps
    hand the greedy rungs' configurations down the ladder. ``n_lanes == 1``
    returns the reference's β = 1."""
    if n_lanes < 1:
        raise ValueError(f"n_lanes must be >= 1, got {n_lanes}")
    if n_lanes == 1:
        return np.ones(1)
    return np.geomspace(beta_min, beta_max, n_lanes)


@partial(
    jax.jit,
    static_argnames=("rollout_steps", "R_coef", "C_coef", "max_steps",
                     "swap_interval", "swap_moves", "target_sum",
                     "stop_on_first"),
    donate_argnames=("state",),
)
def _temper_chunk(
    nbr,
    state: _TemperState,
    par_a,
    par_b,
    a_caps,
    b_caps,
    swap_key,
    *,
    rollout_steps: int,
    R_coef: int,
    C_coef: int,
    max_steps: int,
    swap_interval: int,
    swap_moves: bool = True,
    target_sum: int,
    stop_on_first: bool = False,
):
    """One ladder chunk as ONE device program: ≤ ``swap_interval``
    Metropolis steps for every active lane (the serial chain body on the
    lane axis — shared draw/accept/anneal functions, per-lane β-scaled
    caps), then the seeded even/odd swap move. The carry is donated
    (chunk-to-chunk in-place update; graftcheck pins the donation and the
    single-while-loop structure as the ``tempering_ladder`` ledger row)."""
    from graphdyn.models.sa import (
        _batched_end_sum, draw_sa_proposal, metropolis_anneal_update,
    )

    K, n = state.s.shape
    dt = state.a.dtype

    def cond(st: _TemperState):
        go = jnp.any(st.active) & (st.chunk_t < swap_interval)
        if stop_on_first:
            go = go & ~jnp.any(st.t_target >= 0)
        return go

    def body(st: _TemperState):
        i, u = draw_sa_proposal(
            st.key, st.t, None, None,
            injected=False, stream_len=1, n=n, dt=dt,
        )
        kidx = jnp.arange(K)
        s_i = st.s[kidx, i].astype(jnp.int32)
        s_flip = st.s.at[kidx, i].set((-s_i).astype(jnp.int8))
        sum_end_flip = _batched_end_sum(
            nbr, s_flip, rollout_steps, R_coef, C_coef
        )
        do, sum_end_new, a_new, b_new, t_new, m_final, active = (
            metropolis_anneal_update(
                st.active, st.a, st.b, st.t, st.m_final,
                st.sum_end, sum_end_flip, s_i, u,
                par_a=par_a, par_b=par_b, a_cap=a_caps, b_cap=b_caps,
                max_steps=max_steps, n=n,
            )
        )
        s_new = jnp.where(do[:, None], s_flip, st.s)
        hit = st.active & (st.t_target < 0) & (sum_end_new >= target_sum)
        t_target = jnp.where(hit, t_new, st.t_target)
        return st._replace(
            s=s_new, sum_end=sum_end_new, a=a_new, b=b_new, t=t_new,
            m_final=m_final, active=active, t_target=t_target,
            chunk_t=st.chunk_t + 1,
        )

    st = lax.while_loop(cond, body, state)

    if not swap_moves:
        return st._replace(swap_round=st.swap_round + 1)

    # -- the swap move: even/odd adjacent pairing, round parity alternates.
    # Swaps happen ONLY at full chunks (chunk_t == swap_interval): a chunk
    # that exited early — stop_on_first fired mid-chunk, or every lane
    # stopped — is an end-of-run boundary, not a swap boundary, and a swap
    # there would migrate the winning configuration away from target_lane
    # after the fact AND break the "every swap_interval device steps"
    # chain law the checkpoint fingerprint pins.
    full_chunk = st.chunk_t == swap_interval
    parity = st.swap_round % 2
    idx = jnp.arange(K)
    low = (idx - parity) % 2 == 0           # lower member of its pair
    partner = jnp.where(low, idx + 1, idx - 1)
    valid = (partner >= 0) & (partner < K)
    pidx = jnp.clip(partner, 0, K - 1)
    eligible = valid & st.active & st.active[pidx] & full_chunk
    s0_sum = st.s.astype(jnp.int32).sum(axis=1)
    # Δ = [ (a_i−a_j)(S0_j−S0_i) − (b_i−b_j)(Se_j−Se_i) ] / n — symmetric
    # under i↔j, so both pair members compute the identical decision
    delta = (
        (st.a - st.a[pidx]) * (s0_sum[pidx] - s0_sum).astype(dt)
        - (st.b - st.b[pidx]) * (st.sum_end[pidx] - st.sum_end).astype(dt)
    ) / n
    u = jax.random.uniform(
        jax.random.fold_in(swap_key, st.swap_round.astype(jnp.uint32)),
        (K,), dt,
    )
    u_pair = u[jnp.minimum(idx, pidx)]      # one draw per PAIR
    accept = eligible & (u_pair < jnp.exp(-delta))
    perm = jnp.where(accept, pidx, idx)
    s_sw = st.s[perm]
    sum_end_sw = st.sum_end[perm]
    m_final = jnp.where(accept, sum_end_sw.astype(dt) / n, st.m_final)
    hit = st.active & (st.t_target < 0) & (sum_end_sw >= target_sum)
    t_target = jnp.where(hit, st.t, st.t_target)
    n_eligible = eligible.astype(jnp.int32).sum() // 2
    n_accept = accept.astype(jnp.int32).sum() // 2
    return st._replace(
        s=s_sw, sum_end=sum_end_sw, m_final=m_final, t_target=t_target,
        swap_round=st.swap_round + 1,
        swap_att=st.swap_att + n_eligible,
        swap_acc=st.swap_acc + n_accept,
    )


def _assemble_ladder(graph, config: SAConfig, betas, seed: int,
                     max_steps, dtype, mesh, lane_axis: str):
    """Shared assembly of the ladder chunk program's inputs — ONE assembly
    for :func:`temper_search` and :func:`lower_temper_chunk`, so the
    graftcheck-fingerprinted program and the executed program cannot drift
    (the sa_group `_assemble_group` precedent)."""
    from graphdyn.models.sa import _sa_init, prepare_sa_inputs

    n = graph.n
    K = len(betas)
    dyn = config.dynamics
    R_coef, C_coef = rule_coefficients(dyn.rule, dyn.tie)
    rollout = dyn.p + dyn.c - 1
    np_dt = np.float32 if dtype == jnp.float32 else np.float64  # graftlint: disable=GD004  dtype mirror for host staging
    # the DRIVE ladder (see ladder_betas): β scales b and its cap; a keeps
    # the reference schedule on every lane
    a0 = np.ones_like(betas) * config.a0_frac * n
    b0 = betas * config.b0_frac * n
    prep = prepare_sa_inputs(
        graph, config, n_replicas=K, seed=seed, a0=a0, b0=b0,
        max_steps=max_steps,
    )
    (_, seed, s0, a0b, b0b, _, _, max_steps, _, _) = prep
    keys = jax.vmap(jax.random.PRNGKey)(
        np.arange(K, dtype=np.uint32) + np.uint32(seed)
    )

    def place(x):
        x = jnp.asarray(x)
        if mesh is None:
            return x
        from graphdyn.parallel.mesh import shard_stack

        return shard_stack(mesh, x, lane_axis)

    # the neighbor table's leading axis is the NODE axis, not the lane
    # axis: it is shared by every lane and must REPLICATE over the mesh
    # (sharding it would both scatter the table across lane devices and
    # refuse any n not divisible by the shard count)
    if mesh is None:
        nbr_dev = jnp.asarray(graph.nbr)
    else:
        from graphdyn.parallel.mesh import replicate

        nbr_dev = replicate(mesh, jnp.asarray(graph.nbr))
    sa_state = _sa_init(
        nbr_dev, place(s0), place(keys),
        place(a0b.astype(np_dt)), place(b0b.astype(np_dt)),
        rollout_steps=rollout, R_coef=R_coef, C_coef=C_coef,
    )
    state = _TemperState(
        s=sa_state.s, sum_end=sa_state.sum_end, a=sa_state.a, b=sa_state.b,
        t=sa_state.t, m_final=sa_state.m_final, active=sa_state.active,
        key=sa_state.key,
        t_target=place(np.full(K, -1, np.asarray(sa_state.t).dtype)),
        chunk_t=jnp.zeros((), jnp.int32),
        swap_round=jnp.zeros((), jnp.int32),
        swap_att=jnp.zeros((), jnp.int32),
        swap_acc=jnp.zeros((), jnp.int32),
    )
    loop_args = (
        jnp.asarray(np_dt(config.par_a)),
        jnp.asarray(np_dt(config.par_b)),
        place((np.ones_like(betas) * config.a_cap_frac * n).astype(np_dt)),
        place((betas * config.b_cap_frac * n).astype(np_dt)),
        jax.random.fold_in(jax.random.PRNGKey(np.uint32(seed)),
                           np.uint32(0x53574150)),   # b"SWAP"
    )
    static = dict(rollout_steps=rollout, R_coef=R_coef, C_coef=C_coef,
                  max_steps=int(max_steps))
    return nbr_dev, state, loop_args, static, np_dt, place


def lower_temper_chunk(
    graph, config: SAConfig, *, n_lanes: int = 4, seed: int = 0,
    max_steps: int = 200, swap_interval: int = 16, dtype=jnp.float32,
):
    """Lower (without executing) the ladder chunk program — the exact
    :func:`_temper_chunk` invocation :func:`temper_search` dispatches, as a
    ``jax.stages.Lowered`` for graftcheck's ``tempering_ladder`` ledger
    entry (donated carry + while-count band pin the swap-move program
    structure). Shares :func:`_assemble_ladder` with the run path."""
    betas = ladder_betas(n_lanes)
    nbr_dev, state, loop_args, static, _, _ = _assemble_ladder(
        graph, config, betas, seed, max_steps, dtype, None, "lane",
    )
    return _temper_chunk.lower(
        nbr_dev, state, *loop_args,
        swap_interval=int(swap_interval), swap_moves=True,
        target_sum=graph.n, stop_on_first=False, **static,
    )


def temper_search(
    graph,
    config: SAConfig | None = None,
    *,
    n_lanes: int = 8,
    betas=None,
    beta_min: float = 1.0,
    beta_max: float = 64.0,
    seed: int = 0,
    max_steps: int | None = None,
    swap_interval: int = 1000,
    swap_moves: bool = True,
    m_target: float = 1.0,
    stop_on_first: bool = False,
    sync_stop: bool | None = None,
    dtype=jnp.float32,
    checkpoint_path: str | None = None,
    checkpoint_interval_s: float = 30.0,
    mesh=None,
    lane_axis: str = "lane",
) -> TemperResult:
    """Run a K-lane replica-exchange annealing ladder on one graph.

    ``betas`` (default :func:`ladder_betas`) is the **drive ladder**: lane
    k scales the end-state drive — ``b0`` AND ``b_cap`` — by ``β_k`` while
    ``a0``/``a_cap`` keep the reference schedule on every lane (scaling
    both cancels in the acceptance and buys nothing; measured). β = 1 IS
    the reference chain, and with ``swap_moves=False`` the program is
    bit-identical to ``simulated_annealing(n_replicas=K)`` on the same
    per-lane ``(a0, b0)`` in PRNG mode (tested). ``swap_interval`` is part of the chain law
    (swaps happen every ``swap_interval`` device steps), so it rides in
    the checkpoint fingerprint and a resume must keep it.

    ``m_target`` defines the first-passage record ``t_target`` (the
    ``tta_tempering`` bench measures it): the first step a lane's
    rolled-out ``Σs_end ≥ ceil(m_target·n)``. ``stop_on_first`` ends the
    run at the first passage (the time-to-target mode); otherwise lanes
    run to the reference's own stop rule (consensus or timeout).

    ``checkpoint_path`` gives chunk-granular durable snapshots through the
    PR-9 store (journal, versioned retention, mirror) — snapshots are
    global, so a preempted ladder resumes bit-exact under a different
    ``mesh``/lane-shard count. ``mesh`` shards the lane axis via
    ``shard_stack`` (bit-identical to unsharded; tested).

    ``sync_stop`` controls the per-chunk ``bool(jnp.any(...))`` stop test
    of the drive loop (the one sanctioned device→host sync, GD014). The
    default (None) keeps it only where it buys something: ``stop_on_first``
    needs the poll to exit early, checkpointed runs poll inside
    ``ChainCheckpointer.drive``, and an open-ended budget (the 2n³ default)
    cannot be pre-planned. A FIXED-budget swap-free-or-not run
    (``stop_on_first=False``, no checkpoint, plan ≤ 4096 chunks) instead
    dispatches a host-computed chunk plan with NO readback between chunks
    — lanes that stop early make the remaining chunks no-op dispatches
    (the while cond is false immediately), and results are bit-identical
    either way (tested; the ``tta_fixed_budget_sync`` bench row A/Bs the
    saved sync). Forcing ``sync_stop=False`` with ``stop_on_first``, a
    checkpoint, or an unplannable budget is refused.
    """
    config = config or SAConfig()
    n = graph.n
    if betas is None:
        betas = ladder_betas(n_lanes, beta_min, beta_max)
    betas = np.asarray(betas, dtype=np.float64)  # graftlint: disable=GD004  host ladder staging; cast to solver dtype at placement
    K = betas.size
    if not (0.0 < m_target <= 1.0):
        raise ValueError(f"m_target must be in (0, 1], got {m_target}")
    if swap_interval < 1:
        raise ValueError(f"swap_interval must be >= 1, got {swap_interval}")
    if sync_stop is False and checkpoint_path is not None:
        raise ValueError(
            "sync_stop=False is incompatible with checkpoint_path: snapshot "
            "scheduling polls lane liveness at every chunk boundary"
        )
    target_sum = int(np.ceil(m_target * n))

    nbr_dev, state, loop_args, static, np_dt, place = _assemble_ladder(
        graph, config, betas, seed, max_steps, dtype, mesh, lane_axis,
    )
    # a lane whose INITIAL configuration already rolls out past the target
    # records first passage at step 0 (the chromatic driver's convention)
    t0 = np.asarray(state.t_target)
    hit0 = np.asarray(state.sum_end) >= target_sum
    if hit0.any():
        state = state._replace(
            t_target=place(np.where(hit0, 0, t0).astype(t0.dtype)))
    chunk_kwargs = dict(
        swap_interval=int(swap_interval), swap_moves=bool(swap_moves),
        target_sum=target_sum, stop_on_first=bool(stop_on_first), **static,
    )

    def advance(st: _TemperState):
        return _temper_chunk(
            nbr_dev, st._replace(chunk_t=jnp.zeros((), jnp.int32)),
            *loop_args, **chunk_kwargs,
        )

    def running(st: _TemperState) -> bool:
        go = bool(jnp.any(st.active))
        if stop_on_first:
            go = go and not bool(jnp.any(st.t_target >= 0))
        return go

    def payload(st: _TemperState):
        return {
            k: np.asarray(v)
            for k, v in st._asdict().items() if k != "chunk_t"
        }

    if checkpoint_path is not None:
        from graphdyn.utils.io import ChainCheckpointer, run_fingerprint

        ckpt = ChainCheckpointer(
            checkpoint_path, kind="temper_ladder", seed=seed,
            # full run identity incl. the swap law: ladder, swap interval
            # and the target predicate are part of the chain, so a resume
            # under different ones is refused, never spliced
            fp=run_fingerprint(
                graph.edges, config, betas, int(static["max_steps"]),
                int(swap_interval), bool(swap_moves), target_sum,
                bool(stop_on_first), np_dt,
                bool(jax.config.jax_enable_x64),
            ),
            interval_s=checkpoint_interval_s,
            extra_meta={"K": int(K)},
        )
        arrays = ckpt.load_state(check=lambda a: a["s"].shape == (K, n))
        if arrays is not None:
            state = _TemperState(
                s=place(arrays["s"]),
                sum_end=place(arrays["sum_end"]),
                a=place(arrays["a"].astype(np_dt)),
                b=place(arrays["b"].astype(np_dt)),
                t=place(arrays["t"]),
                m_final=place(arrays["m_final"].astype(np_dt)),
                active=place(arrays["active"]),
                key=place(arrays["key"]),
                t_target=place(arrays["t_target"]),
                chunk_t=jnp.zeros((), jnp.int32),
                swap_round=jnp.asarray(arrays["swap_round"]),
                swap_att=jnp.asarray(arrays["swap_att"]),
                swap_acc=jnp.asarray(arrays["swap_acc"]),
            )
        state = ckpt.drive(
            state, advance=advance, active=running, payload=payload,
        )
    else:
        from graphdyn.resilience.shutdown import raise_if_requested

        # fixed-budget plan length: every active lane times out within
        # max_steps + 1 body iterations, and a chunk advances active lanes
        # swap_interval steps — past n_chunks full chunks no lane can be
        # active, so the remaining budget is provably zero
        n_chunks = -(-(int(static["max_steps"]) + 1) // int(swap_interval))
        if sync_stop is None:
            sync = bool(stop_on_first) or n_chunks > MAX_FIXED_PLAN_CHUNKS
        else:
            sync = bool(sync_stop)
            if not sync and stop_on_first:
                raise ValueError(
                    "sync_stop=False is incompatible with stop_on_first: "
                    "early exit IS the per-chunk stop test"
                )
            if not sync and n_chunks > MAX_FIXED_PLAN_CHUNKS:
                raise ValueError(
                    f"sync_stop=False needs a plannable budget: "
                    f"max_steps={static['max_steps']} / swap_interval="
                    f"{swap_interval} is {n_chunks} chunks (> "
                    f"{MAX_FIXED_PLAN_CHUNKS}) — lower max_steps or raise "
                    f"swap_interval"
                )
        if sync:
            while running(state):
                state = advance(state)
                # heartbeat + honor SIGTERM/--deadline at the swap
                # boundary (exit 75; without a checkpoint there is nothing
                # to snapshot — chains re-derive from the seed on requeue)
                raise_if_requested(where="chunk")
        else:
            # the rider fix: a fixed-budget run skips the per-chunk
            # bool(jnp.any) readback entirely — chunks after every lane
            # stops are no-op dispatches (while cond false immediately,
            # swaps need active lanes), so results are bit-identical to
            # the synced loop (tested) with zero device→host transfers
            # between dispatch and the final readback. Each boundary
            # still fences on chunk COMPLETION (a wait, not a transfer):
            # without it async dispatch would enqueue every chunk in
            # milliseconds, the heartbeats would all predate the device
            # work, and a healthy long run would read as wedged to the
            # PR-10 watchdog while SIGTERM went unhonored until the
            # whole budget drained
            for _ in range(n_chunks):
                state = advance(state)
                # graftlint: disable-next-line=GD014  liveness fence: completion wait, zero transfers
                state.chunk_t.block_until_ready()
                raise_if_requested(where="chunk")

    t_target = np.asarray(state.t_target)
    reached = t_target >= 0
    if reached.any():
        target_lane = int(np.argmin(np.where(reached, t_target, np.iinfo(
            t_target.dtype).max)))
        steps_to_target = int(t_target[target_lane])
    else:
        target_lane, steps_to_target = -1, -1
    att = int(state.swap_att)
    acc = int(state.swap_acc)
    s_final = np.asarray(state.s)
    return TemperResult(
        s=s_final,
        mag_reached=(s_final.astype(np.float64).sum(axis=1) / n).astype(np_dt),  # graftlint: disable=GD004  host observable, exact sum
        num_steps=np.asarray(state.t),
        m_final=np.asarray(state.m_final),
        t_target=t_target,
        betas=betas,
        swap_attempts=att,
        swap_accepts=acc,
        swap_acceptance_rate=(acc / att) if att else 0.0,
        steps_to_target=steps_to_target,
        target_lane=target_lane,
    )
