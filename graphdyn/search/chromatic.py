"""Chromatic-sweep annealing driver — the whole-independent-set search.

Drives :mod:`graphdyn.ops.chromatic`: a distance-2 greedy coloring
(deterministic per seed, host NumPy) partitions the graph into χ classes;
each device step proposes and accepts one entire class (~n/χ sites) with
exact per-site ΔE of the SA objective, so a full sweep costs **O(χ) device
steps** instead of the serial chain's n — the dense analogue of the p-bit
Ising machines' independent-set ticks (PAPERS.md arXiv:2110.02481).
Restricted to ``p = c = 1`` (one-step rollout: the interaction radius the
distance-2 coloring covers); other dynamics are refused loudly.

Replicas are free parallelism (32 per packed word): R independent chains
anneal in one program, each recording its first passage to the target
end-state magnetization — the ``tta_chromatic`` bench statistic.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

from graphdyn.config import SAConfig
from graphdyn.ops.chromatic import (
    ChromaticTables,
    ChromState,
    build_chromatic_tables,
    chromatic_chunk,
    replica_end_sums,
)
from graphdyn.ops.packed import WORD, pack_spins, unpack_spins


class ChromaticResult(NamedTuple):
    s: np.ndarray                # int8[R, n] configurations at stop
    m_end: np.ndarray            # f64[R] rolled-out end-state magnetization
    mag_reached: np.ndarray      # f64[R] m(s(0)) at stop
    steps_to_target: np.ndarray  # int64[R] first-passage CLASS steps, −1
    sweeps_to_target: np.ndarray  # f64[R] the same in full sweeps, −1
    chi: int                     # color classes = device steps per sweep
    sweeps: int                  # full sweeps run
    device_steps: int            # class steps run (= sweeps · χ)
    accepted: int                # cumulative accepted flips


def chromatic_anneal(
    graph,
    config: SAConfig | None = None,
    *,
    n_replicas: int = 32,
    seed: int = 0,
    m_target: float = 0.9,
    max_sweeps: int = 5000,
    chunk_sweeps: int = 64,
    stop_on_first: bool = False,
    tables: ChromaticTables | None = None,
) -> ChromaticResult:
    """Anneal R packed replicas by chromatic block sweeps until each reaches
    ``Σs_end ≥ ceil(m_target·n)`` (first passage recorded per replica) or
    ``max_sweeps`` is spent. Seed-deterministic: the coloring, the initial
    replicas and every proposal stream derive from ``seed``, so sweeps are
    bit-reproducible (tested). Pass ``tables`` to amortize the coloring
    across calls on the same graph."""
    config = config or SAConfig()
    dyn = config.dynamics
    if dyn.p + dyn.c - 1 != 1:
        raise ValueError(
            "chromatic sweeps require p = c = 1 (one-step rollout): the "
            "distance-2 coloring covers interaction radius 2 exactly; "
            f"got p={dyn.p}, c={dyn.c} — use temper_search or the serial "
            "solver for longer rollouts"
        )
    if not (0.0 < m_target <= 1.0):
        raise ValueError(f"m_target must be in (0, 1], got {m_target}")
    if chunk_sweeps < 1:
        raise ValueError(f"chunk_sweeps must be >= 1, got {chunk_sweeps}")
    if max_sweeps < 1:
        raise ValueError(f"max_sweeps must be >= 1, got {max_sweeps}")
    n = graph.n
    if tables is None:
        tables = build_chromatic_tables(graph, seed=seed)
    chi = tables.chi
    R = n_replicas
    W = -(-R // WORD)
    Rp = W * WORD
    rng = np.random.default_rng(seed)
    s0 = (2 * rng.integers(0, 2, size=(R, n)) - 1).astype(np.int8)
    sp = jnp.asarray(pack_spins(s0))
    nbr_ext = jnp.asarray(tables.nbr_ext)
    nbr_self = jnp.asarray(tables.nbr_self)
    deg_ext = jnp.asarray(tables.deg_ext)
    masks = jnp.asarray(tables.masks)
    class_sizes = jnp.asarray(tables.class_sizes.astype(np.int32))
    sum_end0 = replica_end_sums(
        sp, nbr_ext, deg_ext, n, tables.dmax, dyn.rule, dyn.tie
    )
    target_sum = int(np.ceil(m_target * n))
    real = np.zeros(Rp, bool)
    real[:R] = True
    # pad replicas (pack_spins zero-fill reads as all −1 spins) freeze at
    # t=0; a pad column can never record a first passage
    active0 = jnp.array(real) & (sum_end0 < target_sum)
    t_target0 = jnp.where(
        jnp.array(real) & (sum_end0 >= target_sum),
        jnp.int32(0), jnp.int32(-1),
    )
    a0 = np.full(Rp, config.a0_frac * n, np.float32)
    b0 = np.full(Rp, config.b0_frac * n, np.float32)
    state = ChromState(
        sp=sp, sum_end=sum_end0,
        a=jnp.asarray(a0), b=jnp.asarray(b0),
        steps=jnp.zeros((), jnp.int32), sweeps=jnp.zeros((), jnp.int32),
        t_target=t_target0, active=active0,
        accepted=jnp.zeros((), jnp.int32),
        chunk_s=jnp.zeros((), jnp.int32),
    )
    key = jax.random.fold_in(jax.random.PRNGKey(np.uint32(seed)),
                             np.uint32(0x43524f4d))     # b"CROM"
    static = dict(
        n=n, dmax=tables.dmax, rule=dyn.rule, tie=dyn.tie,
        par_a=float(config.par_a), par_b=float(config.par_b),
        a_cap=float(config.a_cap_frac * n), b_cap=float(config.b_cap_frac * n),
        target_sum=target_sum, stop_on_first=bool(stop_on_first),
    )

    def running(st: ChromState) -> bool:
        go = bool(jnp.any(st.active))
        if stop_on_first:
            go = go and not bool(jnp.any(st.t_target >= 0))
        return go

    from graphdyn.resilience.shutdown import raise_if_requested

    # the chunk plan is host-side arithmetic: full chunks plus one exact
    # tail, so the sweep budget is honored to the sweep (a chunk never
    # overshoots max_sweeps) and the drive loop needs no per-chunk device
    # readback beyond the bool(jnp.any) stop test (GD014)
    full, tail = divmod(int(max_sweeps), int(chunk_sweeps))
    chunk_plan = [int(chunk_sweeps)] * full + ([tail] if tail else [])
    for cs in chunk_plan:
        if not running(state):
            break
        state = chromatic_chunk(
            state._replace(chunk_s=jnp.zeros((), jnp.int32)), key,
            masks, class_sizes, nbr_ext, nbr_self, deg_ext,
            chunk_sweeps=cs, **static,
        )
        # heartbeat + honor SIGTERM/--deadline at the chunk boundary (the
        # exit-75 contract; nothing to snapshot — sweeps re-derive from
        # the seed, so a requeue simply restarts)
        raise_if_requested(where="chunk")

    s_final = unpack_spins(np.asarray(state.sp), R)
    t_tgt = np.asarray(state.t_target)[:R].astype(np.int64)
    sweeps_tgt = np.where(t_tgt >= 0, t_tgt / chi, -1.0)
    return ChromaticResult(
        s=s_final,
        m_end=np.asarray(state.sum_end)[:R].astype(np.float64) / n,  # graftlint: disable=GD004  host observable, exact ratio
        mag_reached=s_final.astype(np.float64).sum(axis=1) / n,  # graftlint: disable=GD004  host observable, exact sum
        steps_to_target=t_tgt,
        sweeps_to_target=sweeps_tgt,
        chi=chi,
        sweeps=int(state.sweeps),
        device_steps=int(state.steps),
        accepted=int(state.accepted),
    )
