"""graphdyn.search — faster SA search: replica-exchange tempering ladders,
chromatic block sweeps and the fused one-kernel annealer (ROADMAP items 3
and 7; ARCHITECTURE.md "Search acceleration" / "One-kernel annealing")."""

from graphdyn.search.chromatic import ChromaticResult, chromatic_anneal
from graphdyn.search.fused import (
    FusedResult,
    fused_anneal,
    lower_fused_chunk,
)
from graphdyn.search.tempering import (
    TemperResult,
    ladder_betas,
    lower_temper_chunk,
    temper_search,
)

__all__ = [
    "ChromaticResult",
    "FusedResult",
    "TemperResult",
    "chromatic_anneal",
    "fused_anneal",
    "ladder_betas",
    "lower_fused_chunk",
    "lower_temper_chunk",
    "temper_search",
]
