"""graphdyn.search — faster SA search: replica-exchange tempering ladders
and chromatic block sweeps (ROADMAP item 3; ARCHITECTURE.md "Search
acceleration")."""

from graphdyn.search.chromatic import ChromaticResult, chromatic_anneal
from graphdyn.search.tempering import (
    TemperResult,
    ladder_betas,
    lower_temper_chunk,
    temper_search,
)

__all__ = [
    "ChromaticResult",
    "TemperResult",
    "chromatic_anneal",
    "ladder_betas",
    "lower_temper_chunk",
    "temper_search",
]
