"""One-kernel annealing driver — the fused LUT-popcount SA search.

Drives :mod:`graphdyn.ops.pallas_anneal`: the chromatic class-at-a-time
chain with (a) the dynamics rule compiled to a popcount LUT, (b) a
counter-based Threefry stream generated on device (no host key plumbing),
(c) the geometric anneal schedule advanced inside the device while loop,
and (d) — the drive-loop difference from :func:`graphdyn.search.chromatic
.chromatic_anneal` — a **fixed-budget host chunk plan with no per-chunk
device readback**: in the default ``stop_on_first=False`` mode the loop
dispatches its precomputed chunks and reads results back ONCE, so a full
SA run performs zero device→host transfers between snapshot boundaries
(transfer-guard tested — the guard wraps the fence too). Each boundary
fences on chunk COMPLETION (``block_until_ready`` — a wait, not a
transfer) so heartbeats and the SIGTERM/--deadline poll track executed
work, not async dispatch. ``stop_on_first`` — or a plan past the
no-op-dispatch bound — keeps the GD014-sanctioned ``bool(jnp.any(...))``
stop test, which is what early exit costs.

Kernel selection (``kernel=``, the PR-5 convention): ``'auto'`` runs the
single ``pallas_call`` kernel on TPU backends when the VMEM model admits
the shape, else the XLA twin (same chain law, bit-identical — tested);
``'pallas'`` forces the kernel (interpret mode off-TPU, a test mode);
``'xla'`` forces the twin. Runtime lowering failures degrade through the
shared :func:`graphdyn.ops.bdcm.resilient_exec` machinery.

Restricted to ``p = c = 1`` (the distance-2 coloring's interaction
radius), like the chromatic driver. Replicas are packed 32-per-word; an
optional per-replica **drive ladder** (``betas``) scales each replica's
end-state drive ``(b0, b_cap)`` — ROADMAP item 3's ladder riding the
replica axis inside the one kernel (no swap moves; for replica exchange
use :func:`graphdyn.search.temper_search`).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

import jax.numpy as jnp

from graphdyn.config import SAConfig
from graphdyn.ops.chromatic import replica_end_sums
from graphdyn.ops.packed import WORD, pack_spins, unpack_spins
from graphdyn.search.tempering import MAX_FIXED_PLAN_CHUNKS
from graphdyn.ops.pallas_anneal import (
    FusedState,
    FusedTables,
    build_fused_tables,
    fused_chunk,
    fused_chunk_xla,
    resolve_fused_mode,
)


class FusedResult(NamedTuple):
    s: np.ndarray                # int8[R, n] configurations at stop
    m_end: np.ndarray            # f64[R] rolled-out end-state magnetization
    mag_reached: np.ndarray      # f64[R] m(s(0)) at stop
    steps_to_target: np.ndarray  # int64[R] first-passage CLASS steps, −1
    sweeps_to_target: np.ndarray  # f64[R] the same in full sweeps, −1
    chi: int                     # color classes = device steps per sweep
    sweeps: int                  # full sweeps run
    device_steps: int            # class steps run
    accepted: int                # cumulative accepted flips
    kernel_used: str             # 'pallas' | 'pallas-interpret' | 'xla'


def _assemble_fused(graph, config: SAConfig, *, n_replicas: int, seed: int,
                    m_target: float, betas, tables: FusedTables | None):
    """Shared assembly of the fused chunk program's inputs — ONE assembly
    for :func:`fused_anneal` and :func:`lower_fused_chunk`, so the
    graftcheck-fingerprinted program and the executed program cannot
    drift (the ``_assemble_ladder`` precedent)."""
    dyn = config.dynamics
    if dyn.p + dyn.c - 1 != 1:
        raise ValueError(
            "fused annealing requires p = c = 1 (one-step rollout: the "
            "distance-2 coloring covers interaction radius 2 exactly); "
            f"got p={dyn.p}, c={dyn.c} — use temper_search or the serial "
            "solver for longer rollouts"
        )
    if not (0.0 < m_target <= 1.0):
        raise ValueError(f"m_target must be in (0, 1], got {m_target}")
    n = graph.n
    if tables is None:
        tables = build_fused_tables(graph, config, seed=seed)
    R = n_replicas
    W = -(-R // WORD)
    Rp = W * WORD
    if betas is not None:
        betas = np.asarray(betas, np.float64)  # graftlint: disable=GD004  host ladder staging; cast to f32 below
        if betas.shape != (R,):
            raise ValueError(
                f"betas must be one per replica ([{R}]), got {betas.shape}"
            )
    rng = np.random.default_rng(seed)
    s0 = (2 * rng.integers(0, 2, size=(R, n)) - 1).astype(np.int8)
    sp = jnp.asarray(pack_spins(s0))
    sp_ext = jnp.concatenate([sp, jnp.zeros((1, W), jnp.uint32)], axis=0)
    chrom = tables.chrom
    nbr_ext = jnp.asarray(chrom.nbr_ext)
    nbr_self = jnp.asarray(chrom.nbr_self)
    sum_end0 = replica_end_sums(
        sp, nbr_ext, jnp.asarray(chrom.deg_ext), n, tables.dmax,
        dyn.rule, dyn.tie,
    )
    target_sum = int(np.ceil(m_target * n))
    real = np.zeros(Rp, bool)
    real[:R] = True
    active0 = jnp.array(real) & (sum_end0 < target_sum)
    t_target0 = jnp.where(
        jnp.array(real) & (sum_end0 >= target_sum),
        jnp.int32(0), jnp.int32(-1),
    )
    beta_p = np.ones(Rp, np.float32)
    if betas is not None:
        beta_p[:R] = betas.astype(np.float32)
    a0 = np.full(Rp, config.a0_frac * n, np.float32)
    b0 = (np.full(Rp, config.b0_frac * n, np.float32) * beta_p)
    a_caps = jnp.asarray(np.full(Rp, config.a_cap_frac * n, np.float32))
    b_caps = jnp.asarray(
        np.full(Rp, config.b_cap_frac * n, np.float32) * beta_p
    )
    state = FusedState(
        sp_ext=sp_ext,
        sum_end=sum_end0,
        a=jnp.asarray(a0),
        b=jnp.asarray(b0),
        t_target=t_target0,
        active=active0,
        steps=jnp.zeros((), jnp.int32),
        accepted=jnp.zeros((), jnp.int32),
    )
    facs = np.stack([tables.fac_a, tables.fac_b], axis=1)
    tables_dev = (
        jnp.asarray(tables.masks_ext),
        jnp.asarray(facs),
        nbr_ext,
        nbr_self,
        jnp.asarray(tables.lut_masks),
        a_caps,
        b_caps,
    )
    static = dict(n=n, dmax=tables.dmax, chi=tables.chi,
                  target_sum=target_sum)
    return state, tables_dev, static, tables, R, W, Rp


def lower_fused_chunk(
    graph, config: SAConfig | None = None, *, n_replicas: int = 32,
    seed: int = 0, m_target: float = 0.9, chunk_sweeps: int = 4,
    stop_on_first: bool = False,
):
    """Lower (without executing) the fused XLA chunk program — the exact
    :func:`graphdyn.ops.pallas_anneal.fused_chunk_xla` invocation
    :func:`fused_anneal` dispatches on the CPU gate, as a
    ``jax.stages.Lowered`` for graftcheck's ``fused_anneal`` ledger entry
    (ONE while loop via the GC106 band, donated carry via GC001, every
    table an argument so GC003/GC105 stay quiet). Shares
    :func:`_assemble_fused` with the run path."""
    config = config or SAConfig()
    state, tables_dev, static, tables, _, _, _ = _assemble_fused(
        graph, config, n_replicas=n_replicas, seed=seed,
        m_target=m_target, betas=None, tables=None,
    )
    return fused_chunk_xla.lower(
        state, jnp.uint32(seed), *tables_dev,
        chunk_steps=int(chunk_sweeps) * tables.chi,
        stop_on_first=bool(stop_on_first), **static,
    )


def _run_plan(state: FusedState, seed, tables_dev, holder, plan, *,
              stop_on_first: bool, sync: bool, chi: int,
              static) -> FusedState:
    """The fused drive loop: dispatch the host-computed chunk plan. In
    fixed-budget mode (``sync=False``) there is NO per-chunk device
    readback — chunks whose lanes have all frozen cost one no-op
    dispatch (the while cond is false immediately), which is what buying
    zero host transfers between snapshot boundaries costs. Each boundary
    still carries a **liveness fence**: ``block_until_ready`` on the
    chunk's step counter is a completion WAIT, not a device→host
    transfer (the transfer guard stays clean), so the heartbeat and the
    SIGTERM/--deadline poll fire when the chunk has actually executed —
    without it, async dispatch would enqueue the whole plan in
    milliseconds, every beat would predate the work, and a healthy long
    run would read as wedged to the PR-10 watchdog. ``sync=True``
    (``stop_on_first``, or a plan past the no-op-dispatch bound) adds
    the sanctioned ``bool(jnp.any(…))`` early-exit test."""
    from graphdyn.ops.bdcm import resilient_exec
    from graphdyn.resilience.shutdown import raise_if_requested

    for cs in plan:
        if sync:
            # the sanctioned per-chunk sync (GD014): early exit is the
            # one thing a fixed plan cannot express
            if not bool(jnp.any(state.active)) or (
                    stop_on_first and bool(jnp.any(state.t_target >= 0))):
                break
        st_in = state
        state = resilient_exec(holder, lambda spec: fused_chunk(
            st_in, seed, tables_dev, spec,
            chunk_steps=cs * chi, stop_on_first=stop_on_first,
            **static,
        ))
        if not sync:
            # graftlint: disable-next-line=GD014  liveness fence: completion wait, zero transfers
            state.steps.block_until_ready()
        raise_if_requested(where="chunk")
    return state


def fused_anneal(
    graph,
    config: SAConfig | None = None,
    *,
    n_replicas: int = 32,
    seed: int = 0,
    m_target: float = 0.9,
    max_sweeps: int = 5000,
    chunk_sweeps: int = 256,
    stop_on_first: bool = False,
    kernel: str = "auto",
    betas=None,
    tables: FusedTables | None = None,
    layout: str = "auto",
) -> FusedResult:
    """Anneal R packed replicas by fused LUT class sweeps until each
    reaches ``Σs_end ≥ ceil(m_target·n)`` (first passage recorded per
    replica) or ``max_sweeps`` is spent.

    Seed-deterministic and resume-invariant: every proposal stream derives
    from the counter RNG at ``(seed, site, global step)``, so splitting
    the run into chunks — or restarting the process — cannot change the
    chain (tested). ``chunk_sweeps`` sets the heartbeat/shutdown
    granularity only; the whole budget runs as a host-planned sequence of
    device programs with no readback between them — each boundary fences
    on completion (a wait, not a transfer) so liveness tracks real work
    (``stop_on_first=True``, or a plan longer than 4096 chunks, adds the
    sanctioned per-chunk stop test). Pass ``tables`` to amortize the
    coloring + LUT build across calls on the same graph.

    ``layout`` (``'auto'`` | ``'padded'`` | ``'bucketed'``) follows the
    :func:`graphdyn.models.sa.simulated_annealing` convention: ``'auto'``
    consults :func:`graphdyn.ops.bucketed.auto_layout`, and a degree CV
    at or above the bucketed threshold relabels the graph bucket-major
    before the coloring/LUT build (degree-sorted gathers), mapping the
    returned configurations back to the caller's ids. The seeded chain
    realization is labeling-dependent (sites index nodes), so the
    relabeled run is a different, equally distributed chain; prebuilt
    ``tables`` pin the caller's labeling and require ``layout='padded'``.
    """
    config = config or SAConfig()
    if layout not in ("auto", "padded", "bucketed"):
        raise ValueError(
            f"layout must be 'auto', 'padded' or 'bucketed', got {layout!r}"
        )
    if layout == "auto":
        from graphdyn.ops.bucketed import auto_layout

        layout = "padded" if tables is not None else auto_layout(graph.deg)
    if layout == "bucketed":
        if tables is not None:
            raise ValueError(
                "prebuilt FusedTables pin the caller's node labeling: "
                "pass layout='padded' (or tables=None) to relabel"
            )
        from graphdyn.graphs import degree_buckets, permute_nodes

        g_b, inv = permute_nodes(graph, degree_buckets(graph).order)
        res = fused_anneal(
            g_b, config, n_replicas=n_replicas, seed=seed,
            m_target=m_target, max_sweeps=max_sweeps,
            chunk_sweeps=chunk_sweeps, stop_on_first=stop_on_first,
            kernel=kernel, betas=betas, layout="padded",
        )
        return res._replace(s=res.s[..., inv])
    if chunk_sweeps < 1:
        raise ValueError(f"chunk_sweeps must be >= 1, got {chunk_sweeps}")
    if max_sweeps < 1:
        raise ValueError(f"max_sweeps must be >= 1, got {max_sweeps}")
    state, tables_dev, static, tables, R, W, Rp = _assemble_fused(
        graph, config, n_replicas=n_replicas, seed=seed,
        m_target=m_target, betas=betas, tables=tables,
    )
    n = graph.n
    chi = tables.chi
    spec = resolve_fused_mode(kernel, n=n, W=W, chi=chi, dmax=tables.dmax)
    holder = {"spec": spec}
    full, tail = divmod(int(max_sweeps), int(chunk_sweeps))
    plan = [int(chunk_sweeps)] * full + ([tail] if tail else [])
    # a plan past the bound would pay millions of potentially-no-op
    # dispatches for the one saved scalar readback — past it, fall back
    # to the sanctioned per-chunk stop test (tempering's auto rule; the
    # zero-transfer contract holds for every plannable budget)
    sync = bool(stop_on_first) or len(plan) > MAX_FIXED_PLAN_CHUNKS
    state = _run_plan(
        state, jnp.uint32(seed), tables_dev, holder, plan,
        stop_on_first=bool(stop_on_first), sync=sync, chi=chi,
        static=static,
    )

    s_final = unpack_spins(np.asarray(state.sp_ext[:n]), R)
    t_tgt = np.asarray(state.t_target)[:R].astype(np.int64)
    sweeps_tgt = np.where(t_tgt >= 0, t_tgt / chi, -1.0)
    steps = int(state.steps)
    mode = holder["spec"].pallas[0]
    return FusedResult(
        s=s_final,
        m_end=np.asarray(state.sum_end)[:R].astype(np.float64) / n,  # graftlint: disable=GD004  host observable, exact ratio
        mag_reached=s_final.astype(np.float64).sum(axis=1) / n,  # graftlint: disable=GD004  host observable, exact sum
        steps_to_target=t_tgt,
        sweeps_to_target=sweeps_tgt,
        chi=chi,
        sweeps=steps // chi,
        device_steps=steps,
        accepted=int(state.accepted),
        kernel_used={"tpu": "pallas", "interpret": "pallas-interpret",
                     "": "xla"}[mode],
    )
