"""Bounded host-side prefetcher — the host/device overlap half of the
ensemble pipeline (ARCHITECTURE.md "Ensemble pipeline").

The serial ensemble drivers alternate two idle phases: the device waits
while the host samples a graph (NetworkX/NumPy pairing, edge tables), then
the host waits while the chain runs on device. Here a single background
thread builds repetition ``k+1 .. k+depth`` while the device computes the
current group, hiding the host build time entirely once the pipeline fills.

Determinism is structural, not hoped-for: every build is a pure function of
its repetition index (graphs and RNG streams derive from ``seed + k``), so
*when* a build happens cannot change *what* it produces — ``prefetch=0``
(fully synchronous) and ``prefetch=4`` are bit-identical by construction
(tested). The queue is bounded (``depth`` items), so an ensemble of
thousands of graphs never materializes more than ``depth`` neighbor tables
on the host at once.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable, Iterable

from graphdyn import obs

log = logging.getLogger("graphdyn.pipeline")

_SENTINEL = object()


class HostPrefetcher:
    """Build ``build(k)`` for each ``k`` in ``keys`` (in order) on a
    background thread, at most ``depth`` items ahead of the consumer.

    ``depth=0`` degrades to a synchronous call per :meth:`get` — no thread,
    no queue — which is both the determinism baseline for tests and the
    fallback for callers that cannot tolerate a helper thread.

    Exceptions raised by ``build`` are captured and re-raised from the
    consumer's matching :meth:`get` call, so a failing build surfaces on the
    driver thread with its original traceback as ``__cause__``.

    Use as a context manager (or call :meth:`close`): the worker thread is
    a daemon *and* interruptible — ``close()`` unblocks a worker stuck on a
    full queue, so a driver that dies mid-ensemble (preemption, injected
    fault) never leaks a thread that keeps building graphs.
    """

    def __init__(self, build: Callable[[int], object], keys: Iterable[int],
                 depth: int = 2):
        if depth < 0:
            raise ValueError(f"prefetch depth must be >= 0, got {depth}")
        self._build = build
        self._keys = list(keys)
        self.depth = depth
        self._pos = 0
        # overlap accounting (the obs utilization gauge): how long builds
        # took on the worker vs how long the consumer actually blocked —
        # a full pipeline hides the builds entirely (wait ≈ 0)
        self._build_s = 0.0
        self._wait_s = 0.0
        self._stop = threading.Event()
        self._q: queue.Queue | None = None
        self._thread: threading.Thread | None = None
        if depth > 0 and self._keys:
            self._q = queue.Queue(maxsize=depth)
            self._thread = threading.Thread(
                target=self._worker, name="graphdyn-prefetch", daemon=True
            )
            self._thread.start()

    def _worker(self) -> None:
        for k in self._keys:
            if self._stop.is_set():
                return
            t0 = time.monotonic()
            try:
                item = (k, self._build(k), None)
            except BaseException as e:  # noqa: BLE001 — re-raised in get()
                item = (k, None, e)
            self._build_s += time.monotonic() - t0
            # bounded put that stays responsive to close(): a consumer that
            # died mid-ensemble must not leave this thread blocked forever
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.1)
                    break
                except queue.Full:
                    continue
            if item[2] is not None:
                return                      # a failed build ends the stream

    def get(self, k: int):
        """The built item for repetition ``k``. Calls MUST follow the
        ``keys`` order (the driver's group loop does) — enforced, because an
        out-of-order get against a threaded prefetcher would silently pair
        repetitions with the wrong builds."""
        if self._pos >= len(self._keys) or self._keys[self._pos] != k:
            raise ValueError(
                f"prefetcher consumed out of order: expected "
                f"{self._keys[self._pos] if self._pos < len(self._keys) else '<end>'}, "
                f"got {k}"
            )
        self._pos += 1
        if self._q is None:
            t0 = time.monotonic()
            out = self._build(k)
            self._build_s += time.monotonic() - t0
            self._wait_s = self._build_s    # synchronous: no overlap
            return out
        t0 = time.monotonic()
        got_k, value, exc = self._q.get()
        self._wait_s += time.monotonic() - t0
        assert got_k == k, f"prefetch stream desync: {got_k} != {k}"
        if exc is not None:
            raise RuntimeError(
                f"prefetch build for repetition {k} failed"
            ) from exc
        return value

    #: how long :meth:`close` waits for the worker before declaring it
    #: hung (tests shrink this; a build stuck in C code ignores _stop)
    JOIN_TIMEOUT_S = 5.0

    def close(self, timeout_s: float | None = None) -> None:
        """Stop the worker and release the queue. Idempotent. When an obs
        recorder is active, emits the overlap-utilization gauge: the
        fraction of host build time hidden behind device compute
        (1 − wait/build; 1.0 = fully overlapped, 0.0 = serial).

        A worker that outlives the join window is a **wedged daemon
        thread** (a build stuck in a syscall or native code cannot see
        ``_stop``): it is reported loudly — warning + the
        ``pipeline.prefetch.hung`` counter — instead of silently abandoned,
        so the watchdog's flight post-mortem can name the stalled
        prefetcher instead of an innocent device boundary."""
        if obs.enabled() and self._build_s > 0 and not self._stop.is_set():
            obs.gauge(
                "pipeline.prefetch.overlap_util",
                max(0.0, 1.0 - self._wait_s / self._build_s),
                build_s=round(self._build_s, 6),
                wait_s=round(self._wait_s, 6),
                depth=self.depth, items=self._pos,
            )
        self._stop.set()
        if self._q is not None:
            while True:                     # drain so a blocked put exits
                try:
                    self._q.get_nowait()
                except queue.Empty:
                    break
        if self._thread is not None:
            timeout_s = self.JOIN_TIMEOUT_S if timeout_s is None else timeout_s
            self._thread.join(timeout=timeout_s)
            if self._thread.is_alive():
                log.warning(
                    "prefetch worker %s is HUNG: still alive %.3gs after "
                    "close() (a build is stuck past the stop flag) — "
                    "abandoning the daemon thread; built %d item(s), "
                    "depth %d", self._thread.name, timeout_s, self._pos,
                    self.depth,
                )
                obs.counter(
                    "pipeline.prefetch.hung", depth=self.depth,
                    items=self._pos, timeout_s=timeout_s,
                )
            self._thread = None

    def __enter__(self) -> "HostPrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
