"""Batched multi-graph HPr ensembles — the reinforced-BP half of the
pipeline (ARCHITECTURE.md "Ensemble pipeline").

The serial driver (`graphdyn.models.hpr.hpr_ensemble`) runs ``n_rep``
chains one after another, each on its own freshly sampled RRG: the host
builds edge tables and factor tensors while the device idles, then one
``[2E, K, K]`` sweep runs per iteration while every other repetition
waits. Here a group of ``G`` repetitions runs as ONE compiled program: the
per-repetition BDCM index tables stack to ``[G, Ed, ...]`` (the
:class:`graphdyn.ops.bdcm.EnsembleBDCM` layout), chi carries a leading
group axis, and the sweep / marginals / reinforcement / rollout stop-test
all vmap over the group.

Element-wise identity with the serial path is structural:
:func:`graphdyn.models.hpr.hpr_solve` itself advances its chain through
this module's shared group program (:class:`HPRGroupExec` at G=1), so the
serial driver (a loop of ``hpr_solve``) and the grouped driver run the
SAME compiled body — per-repetition RNG streams (host init AND the device
reinforcement keys) derive from ``seed + k``, finished chains freeze under
per-repetition masks, and per-member float schedules are invariant under
the leading group extent (tested). That sharing is load-bearing: two
*differently structured* loop programs computing the same chain law (e.g.
a fused while-loop vs its own op-by-op restatement) differ at the ulp
level under XLA CPU fusion, and an 800-sweep reinforcement chain
eventually amplifies one ulp into a flipped marginal comparison (observed;
regression-anchored in tests). Tested element-wise against the serial
driver for several group sizes, including 1 and non-divisors of
``n_rep``.

Checkpoint/fault semantics are the group-boundary protocol of
:mod:`graphdyn.pipeline.groups` — snapshots interchangeable with the
serial driver's, ``rep.boundary`` firing per repetition in order.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from graphdyn import obs
from graphdyn.config import HPRConfig
from graphdyn.ops.bdcm import (
    class_update,
    resilient_exec,
    resolve_group_pallas_modes,
)
from graphdyn.ops.dynamics import batched_rollout_impl, rule_coefficients
from graphdyn.resilience import faults as _faults


class _HPRGroupSpec(NamedTuple):
    """Hashable static configuration of one grouped HPr program (everything
    traced is an argument of the module-level executor, so every group of
    the same shape reuses ONE compiled program)."""

    T: int
    K: int
    n: int
    damp: float
    eps: float            # marginal ε-clamp (`HPR:147`)
    TT: int
    rollout_steps: int
    R_coef: int
    C_coef: int
    class_ds: tuple       # per-edge-class incoming-message count d
    pallas: tuple = ()    # per-class kernel mode: '' (XLA) | 'tpu' |
    #                       'interpret' (resolve_group_pallas_modes; the
    #                       runtime Pallas→XLA fallback swaps this tuple)


class _HPRGroupState(NamedTuple):
    chi: jnp.ndarray      # f[G, 2E, K, K]
    biases: jnp.ndarray   # f[G, n, 2]
    s: jnp.ndarray        # int8[G, n]
    keys: jnp.ndarray     # [G] PRNG keys
    t: jnp.ndarray        # int32[] — shared sweep clock (all chains start
    #                       together; frozen chains ignore it)
    m_final: jnp.ndarray  # f32[G]
    active: jnp.ndarray   # bool[G]
    steps: jnp.ndarray    # int32[G] — per-chain stop sweep


def _group_m_of_end(nbr_stack, s, spec: _HPRGroupSpec):
    """Per-repetition rollout magnetization, each on its OWN graph — the
    serial solver's ``m_of_end`` vmapped over stacked neighbor tables."""

    def one(nb, sv):
        return batched_rollout_impl(
            nb, sv[None], spec.rollout_steps, spec.R_coef, spec.C_coef
        )[0]

    s_end = jax.vmap(one)(nbr_stack, s)
    return (
        s_end.astype(jnp.int32).sum(axis=1).astype(jnp.float32) / spec.n
    )


@partial(jax.jit, static_argnames=("spec",))
def _hpr_group_init_m(nbr_stack, s0, real, *, spec: _HPRGroupSpec):
    m0 = _group_m_of_end(nbr_stack, s0, spec)
    return m0, (m0 < 1.0) & real


@partial(
    jax.jit,
    static_argnames=("spec",),
    # group-to-group carry reuse: the ensemble driver only reads the final
    # (s, m_final, steps); chi/biases update in place across chunks
    donate_argnums=(0,),
)
def _hpr_group_loop(
    state: _HPRGroupState,
    t_end,
    lmbd,
    pie,
    gamma,
    x0f,
    sel_plus_b,
    sel_plus_f,
    src,
    rev,
    out_edges,
    nbr_stack,
    tables,
    *,
    spec: _HPRGroupSpec,
):
    """Advance all chains of the group until every one stops or the sweep
    clock reaches ``t_end`` (the shutdown-poll granularity). The body is
    `hpr_solve`'s iteration on a group axis: same sweep core, same
    marginal/reinforcement arithmetic, per-repetition tables throughout."""
    T, K, n = spec.T, spec.K, spec.n
    dt = x0f.dtype
    tilt = jnp.exp(-lmbd * x0f)                      # [K], shared λ

    def bias_to_edge_one(biases_g, src_g):
        # bias of the source node at its trajectory's initial value
        # (`positions_biases`, `HPR:120-133`): [2E, K]
        return jnp.where(
            sel_plus_b[None, :], biases_g[src_g, 0, None],
            biases_g[src_g, 1, None],
        )

    def sweep_one(chi_g, bias_edge_g, *tabs):
        # the serial _sweep_core for the HPr variant (with_bias=True,
        # mask_invalid_src=False, eps_clamp=0) on one member's tables
        for d, A, (idx, in_edges) in zip(
            spec.class_ds, [t[2] for t in tables], zip(*[iter(tabs)] * 2)
        ):
            chi_in = chi_g[in_edges]                 # [Ed, d, K, K]
            chi_in = chi_in * bias_edge_g[in_edges][:, :, :, None]
            upd = class_update(
                chi_in, A, tilt, chi_g[idx], d=d, T=T, K=K,
                damp=spec.damp, eps_clamp=0.0,
            )
            chi_g = chi_g.at[idx].set(upd)
        return chi_g

    def marginals_one(chi_g, rev_g, out_g):
        # make_marginals body (`HPR:147-167` semantics), per member
        P = chi_g * jnp.swapaxes(chi_g[rev_g], 1, 2)
        Zp = (P * sel_plus_f[None, :, None]).sum(axis=(1, 2))
        Zm = (P * (1.0 - sel_plus_f)[None, :, None]).sum(axis=(1, 2))
        Zp = jnp.maximum(Zp, spec.eps)
        Zm = jnp.maximum(Zm, spec.eps)
        tot = Zp + Zm
        Zp, Zm = Zp / tot, Zm / tot
        Zp_ext = jnp.concatenate([Zp, jnp.ones((1,), Zp.dtype)])
        Zm_ext = jnp.concatenate([Zm, jnp.ones((1,), Zm.dtype)])
        mp = jnp.prod(Zp_ext[out_g], axis=1)
        mm = jnp.prod(Zm_ext[out_g], axis=1)
        marg = jnp.stack([mp, mm], axis=1)
        return marg / marg.sum(axis=1, keepdims=True)

    flat_tables = [a for t in tables for a in (t[0], t[1])]
    vsweep = jax.vmap(
        sweep_one, in_axes=(0, 0) + (0,) * len(flat_tables)
    )
    vmarg = jax.vmap(marginals_one)
    vbias = jax.vmap(bias_to_edge_one)

    if any(spec.pallas):
        # Pallas-mode sweep: the fused grouped kernel with the rep axis as
        # the leading grid dimension (never a vmap of kernel launches —
        # graftlint GD009); λ is shared across reps, so A_tilted is the
        # SHARED variant and one broadcast row block serves every rep.
        # Classes that fail the grouped gate keep the vmapped XLA core
        # inside the same sweep. Grouped == serial stays structural:
        # hpr_solve runs the G=1 instance of this same program.
        from graphdyn.ops.pallas_bdcm import dp_contract_grouped

        def gather(arrs, tab):
            return jax.vmap(lambda a, t_: a[t_])(arrs, tab)

        def group_sweep(chi, bias_edge):
            for (d, mode), (idx, in_edges, A) in zip(
                zip(spec.class_ds, spec.pallas), tables
            ):
                chi_in = gather(chi, in_edges)       # [G, Ed, d, K, K]
                chi_in = chi_in * gather(bias_edge, in_edges)[..., None]
                chi_old = gather(chi, idx)
                if mode:
                    # trace-time site: a firing plan stands in for a real
                    # kernel lowering/compile failure on this backend
                    _faults.maybe_fail("pallas.lower", key=f"d={d}")
                    upd = dp_contract_grouped(
                        chi_in, A * tilt[:, None, None], chi_old,
                        d=d, T=T, damp=spec.damp, eps_clamp=0.0,
                        interpret=mode == "interpret",
                    ).astype(chi.dtype)
                else:
                    upd = jax.vmap(
                        lambda ci, co, A=A, d=d: class_update(
                            ci, A, tilt, co, d=d, T=T, K=K,
                            damp=spec.damp, eps_clamp=0.0,
                        )
                    )(chi_in, chi_old)
                chi = jax.vmap(lambda c, i, u: c.at[i].set(u))(chi, idx, upd)
            return chi

        def run_sweep(chi, bias_edge):
            return group_sweep(chi, bias_edge)
    else:

        def run_sweep(chi, bias_edge):
            return vsweep(chi, bias_edge, *flat_tables)

    def cond(st: _HPRGroupState):
        return jnp.any(st.active) & (st.t < t_end)

    def body(st: _HPRGroupState):
        bias_edge = vbias(st.biases, src)
        chi_new = run_sweep(st.chi, bias_edge)
        marg = vmarg(chi_new, rev, out_edges)        # [G, n, 2]
        # reinforcement (`new_biases_i`, `HPR:137-145`), per repetition
        minus_wins = marg[..., 1] >= marg[..., 0]
        new_bias = jnp.where(
            minus_wins[..., None],
            jnp.stack([pie, 1 - pie]),
            jnp.stack([1 - pie, pie]),
        )
        ks = jax.vmap(jax.random.split)(st.keys)     # [G, 2, key]
        keys_new, ku = ks[:, 0], ks[:, 1]
        u = jax.vmap(lambda k: jax.random.uniform(k, (n,), dt))(ku)
        update = u < 1.0 - (1.0 + st.t.astype(dt)) ** (-gamma)
        biases_new = jnp.where(update[..., None], new_bias, st.biases)
        s_new = jnp.where(
            biases_new[..., 0] > biases_new[..., 1], 1, -1
        ).astype(jnp.int8)
        t_new = st.t + 1
        m_new = jnp.where(
            t_new > spec.TT, 2.0, _group_m_of_end(nbr_stack, s_new, spec)
        )
        an = st.active                               # frozen chains keep state
        return _HPRGroupState(
            chi=jnp.where(an[:, None, None, None], chi_new, st.chi),
            biases=jnp.where(an[:, None, None], biases_new, st.biases),
            s=jnp.where(an[:, None], s_new, st.s),
            keys=jnp.where(an[:, None], keys_new, st.keys),
            t=t_new,
            m_final=jnp.where(an, m_new, st.m_final),
            active=an & (jnp.where(an, m_new, st.m_final) < 1.0)
            & (t_new <= spec.TT),
            steps=jnp.where(an, t_new, st.steps),
        )

    return lax.while_loop(cond, body, state)


class HPRGroupResult(NamedTuple):
    s: np.ndarray          # int8[G, n]
    num_steps: np.ndarray  # int32[G]
    m_final: np.ndarray    # f32[G]


def _build_rep(n, d, config: HPRConfig, rep_seed: int, graph_method: str):
    """Host build for ONE repetition — everything that depends only on
    ``seed + k``, so the prefetch thread can run it ahead: graph, edge
    tables, BDCM factor data, and the serial solver's exact host init
    (chi drawn first, then biases, from one ``default_rng(seed + k)``
    stream — `hpr_solve`'s order)."""
    from graphdyn.graphs import build_edge_tables, random_regular_graph
    from graphdyn.ops.bdcm import BDCMData

    dyn = config.dynamics
    g = random_regular_graph(n, d, seed=rep_seed, method=graph_method)
    tables = build_edge_tables(g)
    data = BDCMData(
        g, tables, p=dyn.p, c=dyn.c, attr_value=dyn.attr_value,
        rule=dyn.rule, tie=dyn.tie, dtype=jnp.dtype(config.dtype),
    )
    rng = np.random.default_rng(rep_seed)
    chi0 = rng.random((data.num_directed, data.K, data.K))
    chi0 /= chi0.sum(axis=(1, 2), keepdims=True)
    biases0 = rng.random((n, 2))
    biases0 /= biases0.sum(axis=1, keepdims=True)
    np_dt = np.dtype(config.dtype)
    chi0 = chi0.astype(np_dt)
    biases0 = biases0.astype(np_dt)
    # trial solution from the CAST biases — the dtype the device compares
    s0 = np.where(biases0[:, 0] > biases0[:, 1], 1, -1).astype(np.int8)
    return g, data, chi0, biases0, s0


class HPRGroupExec:
    """Compiled-program handle for one (padded) group of congruent HPr
    chains — stacked tables, static spec, init and chunked advance. The
    SINGLE program family every HPr chain in the drivers runs through:
    ``hpr_solve`` executes a G=1 instance and the grouped ensemble driver
    a G=``group_size`` instance of the same vmapped body. That sharing is
    what makes serial-vs-grouped parity structural: per-member float
    schedules are invariant under the leading group extent (tested),
    whereas two *differently structured* loop programs — e.g. a fused
    while-loop vs its own op-by-op restatement — differ at the ulp level
    under XLA fusion and eventually flip a chain decision.

    ``kernel`` selects the sweep core per degree class (ARCHITECTURE.md
    "Kernel selection"): ``'auto'`` (default) fuses qualifying classes
    into the grouped Pallas kernel on TPU backends (rep axis as a Pallas
    grid dimension, shared ``A_tilted`` — one λ across reps);
    ``'pallas'`` forces it (interpret off-TPU, for tests); ``'xla'``
    keeps the pure-XLA path. Pallas-vs-XLA is an approximate mode (~1e-3
    max rel err, PALLAS_TPU.json); grouped == serial holds bit-exactly
    WITHIN a mode because ``hpr_solve`` runs the G=1 instance. A kernel
    lowering/compile failure at run time degrades the program to XLA via
    :func:`graphdyn.ops.bdcm.pallas_fallback_spec` (logged, run
    continues)."""

    def __init__(self, items, config: HPRConfig, *,
                 group_size: int | None = None, kernel: str = "auto"):
        G_real = len(items)
        G = group_size or G_real
        if G < G_real:
            raise ValueError(f"group_size={G} < group population {G_real}")
        dyn = config.dynamics
        R_coef, C_coef = rule_coefficients(dyn.rule, dyn.tie)
        datas = [it[1] for it in items]
        d0 = datas[0]
        sig = [(c.d, c.idx.shape[0]) for c in d0.edge_classes]
        for dd in datas[1:]:
            if (dd.n != d0.n or dd.K != d0.K
                    or [(c.d, c.idx.shape[0]) for c in dd.edge_classes] != sig):
                raise ValueError(
                    "grouped HPr repetitions must be structurally congruent "
                    "(same n and degree-class signature — RRG ensembles are)"
                )
        if d0.leaf_idx.size:
            raise ValueError(
                "the batched HPr program does not cover leaf edges "
                "(degree-1 nodes)"
            )
        from graphdyn.graphs import stack_graphs

        def pad(rows):
            return rows + [rows[0]] * (G - G_real)

        self.G, self.G_real, self.d0 = G, G_real, d0
        self._pad = pad
        self._state = {"spec": _HPRGroupSpec(
            T=d0.T, K=d0.K, n=d0.n, damp=float(config.damp),
            eps=float(config.eps_clamp), TT=int(config.max_sweeps),
            rollout_steps=dyn.p + dyn.c - 1, R_coef=R_coef, C_coef=C_coef,
            class_ds=tuple(c.d for c in d0.edge_classes),
            # one λ across reps -> the SHARED A_tilted variant
            pallas=resolve_group_pallas_modes(
                [c.d for c in d0.edge_classes],
                [c.idx.shape[0] for c in d0.edge_classes],
                T=d0.T, dtype=d0.dtype, kernel=kernel, G=G,
                per_group_a=False,
            ),
        )}
        dt = d0.dtype
        padded = pad(list(items))
        self.tables = tuple(
            (
                jnp.asarray(np.stack([dd[1].edge_classes[k].idx
                                      for dd in padded])),
                jnp.asarray(np.stack([dd[1].edge_classes[k].in_edges
                                      for dd in padded])),
                jnp.asarray(cls.A, dt),
            )
            for k, cls in enumerate(d0.edge_classes)
        )
        twoE = d0.num_directed
        self.src = jnp.asarray(np.stack([
            np.asarray(dd[1].tables.src) for dd in padded
        ]))
        self.rev = jnp.asarray(np.stack([
            dd[1].tables.rev(np.arange(twoE)) for dd in padded
        ]).astype(np.int32))
        self.out_edges = jnp.asarray(np.stack([
            np.asarray(dd[1].tables.node_out_edges) for dd in padded
        ]))
        self.nbr_stack = jnp.asarray(
            stack_graphs([dd[0] for dd in padded]).nbr
        )
        self.consts = (
            jnp.asarray(config.lmbd, dt),
            jnp.asarray(config.pie, dt),
            jnp.asarray(config.gamma, dt),
            jnp.asarray(d0.x0, dt),
            jnp.asarray(d0.x0 == 1),
            jnp.asarray(d0.x0 == 1, dt),
        )

    @property
    def spec(self) -> _HPRGroupSpec:
        """The CURRENT static spec — the runtime Pallas→XLA fallback swaps
        the held spec, and every later chunk must see the rebuilt one."""
        return self._state["spec"]

    def init_state(self, chi0, biases0, s0, rep_seeds, *, t=0, m_final=None,
                   steps=None) -> _HPRGroupState:
        """State from per-member host arrays (length ``G_real`` lists; pad
        rows are appended here and start frozen). ``m_final=None`` runs
        the initial rollout stop-test — exactly the serial solver's
        ``m_of_end(s0)``; a resume passes the snapshot's values through."""
        pad = self._pad
        chi = jnp.asarray(np.stack(pad(list(chi0))))
        biases = jnp.asarray(np.stack(pad(list(biases0))))
        s = jnp.asarray(np.stack(pad(list(s0))))
        # per-member root keys: exactly hpr_solve's PRNGKey(seed + k) when
        # given ints; a resume passes raw key arrays through unchanged
        keys_in = pad(list(rep_seeds))
        if np.ndim(keys_in[0]) == 0:
            keys = jax.vmap(jax.random.PRNGKey)(
                np.asarray([np.uint32(sd) for sd in keys_in], np.uint32)
            )
        else:
            keys = jnp.asarray(np.stack([np.asarray(k) for k in keys_in]))
        real = np.zeros(self.G, bool)
        real[:self.G_real] = True
        # jnp.array (NOT asarray): `real` is a mutated host buffer — the
        # mutation precedes the crossing today, but the GD010 discipline is
        # to copy at every mutable-buffer crossing so a reorder can never
        # reintroduce the PR-4 alias race
        real_dev = jnp.array(real)
        if m_final is None:
            m0, active0 = _hpr_group_init_m(
                self.nbr_stack, s, real_dev, spec=self.spec
            )
        else:
            m0 = jnp.asarray(np.asarray(pad(list(m_final)), np.float32))
            active0 = (m0 < 1.0) & real_dev
        steps0 = (jnp.full((self.G,), int(t), jnp.int32) if steps is None
                  else jnp.asarray(np.asarray(pad(list(steps)), np.int32)))
        return _HPRGroupState(
            chi=chi, biases=biases, s=s, keys=keys,
            t=jnp.int32(t), m_final=m0, active=active0, steps=steps0,
        )

    def lower_loop(self, state: _HPRGroupState, t_end):
        """Lower (without executing) the chunked loop program for this
        group's shapes — the exact :func:`_hpr_group_loop` invocation
        :meth:`advance` dispatches, as a ``jax.stages.Lowered`` for
        :mod:`graphdyn.analysis.graftcheck` fingerprinting. Kept next to
        ``advance`` so a loop refactor updates the fingerprinted surface in
        the same place."""
        return _hpr_group_loop.lower(
            state, jnp.int32(t_end), *self.consts,
            self.src, self.rev, self.out_edges, self.nbr_stack, self.tables,
            spec=self.spec,
        )

    def advance(self, state: _HPRGroupState, t_end) -> _HPRGroupState:
        """One bounded chunk of the shared loop program (donates the
        carry). A Pallas lowering/compile failure degrades the program to
        the XLA path at runtime (:func:`graphdyn.ops.bdcm.resilient_exec`
        — logged; safe to retry because both the injected fault and a real
        Mosaic failure fire at trace/compile time, before the donated
        buffers are consumed)."""
        return resilient_exec(self._state, lambda sp: _hpr_group_loop(
            state, jnp.int32(t_end), *self.consts,
            self.src, self.rev, self.out_edges, self.nbr_stack, self.tables,
            spec=sp,
        ))

    def run(self, state: _HPRGroupState, *, chunk_sweeps: int = 200,
            on_chunk=None) -> _HPRGroupState:
        """Advance until every member stops, ``chunk_sweeps`` per device
        call; ``on_chunk`` is polled between chunks (the graceful-shutdown
        hook — it may raise)."""
        rec = obs.current()
        chunk_i = 0
        while bool(np.asarray(jnp.any(state.active))):
            t_start = int(state.t)
            t_end = min(t_start + int(chunk_sweeps), self.spec.TT + 2)
            # per-chunk span (ARCHITECTURE.md "Runtime telemetry"): cold
            # marks the compile-paying first chunk; recording adds a device
            # fence so wall_s is execute time — the null recorder leaves
            # the async dispatch untouched
            with rec.span("pipeline.hpr.chunk", chunk=chunk_i,
                          cold=chunk_i == 0) as sp:
                state = self.advance(state, t_end)
                if rec.enabled:
                    jax.block_until_ready(state)
                    sp.set(sweeps_advanced=int(state.t) - t_start,
                           active=int(np.sum(np.asarray(state.active))))
            if rec.enabled:
                # device-memory gauges at the chunk boundary (obs.mem.*)
                obs.memband.emit_memory_gauges(loop="hpr.chunk",
                                               chunk=chunk_i)
            chunk_i += 1
            if on_chunk is not None:
                on_chunk()
        return state


def run_hpr_group(
    items,
    rep_seeds,
    config: HPRConfig,
    *,
    group_size: int | None = None,
    chunk_sweeps: int = 200,
    on_chunk=None,
    kernel: str = "auto",
) -> HPRGroupResult:
    """Run one group of HPr chains (one per freshly sampled graph) as a
    single device program. ``items`` are :func:`_build_rep` outputs;
    ``group_size`` pads with inactive rows for shape stability;
    ``on_chunk`` is polled between device chunks (the graceful-shutdown
    hook — it may raise); ``kernel`` selects the sweep core (see
    :class:`HPRGroupExec`)."""
    ex = HPRGroupExec(items, config, group_size=group_size, kernel=kernel)
    state = ex.init_state(
        [it[2] for it in items], [it[3] for it in items],
        [it[4] for it in items], rep_seeds,
    )
    state = ex.run(state, chunk_sweeps=chunk_sweeps, on_chunk=on_chunk)
    return HPRGroupResult(
        s=np.asarray(state.s)[:ex.G_real],
        num_steps=np.asarray(state.steps)[:ex.G_real],
        m_final=np.asarray(state.m_final)[:ex.G_real],
    )



def hpr_ensemble_grouped(
    n: int,
    d: int,
    config: HPRConfig | None = None,
    *,
    n_rep: int = 1,
    seed: int = 0,
    graph_method: str = "pairing",
    save_path: str | None = None,
    checkpoint_path: str | None = None,
    checkpoint_interval_s: float = 30.0,
    group_size: int = 8,
    prefetch: int = 2,
    chunk_sweeps: int = 200,
    kernel: str = "auto",
):
    """The grouped HPr experiment driver: ``n_rep`` repetitions on fresh
    RRG(n, d) instances, ``group_size`` at a time as one vmapped device
    program, with the next group's graphs/tables/factor data built on a
    background thread while the current group computes. Element-wise
    identical to the serial :func:`graphdyn.models.hpr.hpr_ensemble`; see
    the module docstring for the identity and checkpoint/fault contracts.

    Per-repetition wall-clock (the reference's ``time`` array) is the
    group's wall-clock divided evenly — per-chain attribution does not
    exist inside one device program."""
    from graphdyn.graphs import random_regular_graph
    from graphdyn.models.hpr import HPREnsembleResult
    from graphdyn.pipeline.groups import GroupDriver, group_ranges
    from graphdyn.pipeline.prefetch import HostPrefetcher
    from graphdyn.utils.io import save_results_npz

    config = config or HPRConfig()
    mag = np.empty(n_rep, np.float64)  # graftlint: disable=GD004  host result buffer
    conf = np.empty((n_rep, n), np.int8)
    steps = np.empty(n_rep, np.int64)
    graphs = np.empty((n_rep, n, d), np.int32)
    times = np.empty(n_rep, np.float64)  # graftlint: disable=GD004  host wall-clock

    def payload():
        return {"mag_reached": mag, "conf": conf, "num_steps": steps,
                "time": times}

    run_id = {"seed": seed, "n_rep": n_rep, "n": n, "d": d,
              "graph_method": graph_method, "config": repr(config)}
    drv = GroupDriver(checkpoint_path, checkpoint_interval_s, run_id, payload)
    start_k = drv.resume_into(payload())

    def build(k):
        return _build_rep(n, d, config, seed + k, graph_method)

    with HostPrefetcher(build, range(start_k, n_rep), depth=prefetch) as pf:
        for ks in group_ranges(start_k, n_rep, group_size):
            # the ONE timing idiom (obs.timed — graftlint GD011 keeps bare
            # perf_counter brackets out of the driver modules); the span
            # also lands in the event ledger when recording
            with obs.timed("pipeline.hpr.group", reps=len(ks)) as sw:
                items = [pf.get(i) for i in ks]
                res = run_hpr_group(
                    items, [seed + i for i in ks], config,
                    group_size=group_size, chunk_sweeps=chunk_sweeps,
                    on_chunk=lambda k0=ks[0]: drv.chunk_poll(k0),
                    kernel=kernel,
                )
            elapsed = sw.wall_s
            for j, i in enumerate(ks):
                conf[i] = res.s[j]
                # the serial result's f32 mean, widened into the f64 array
                # graftlint: disable-next-line=GD004  host observable, exact sum
                mag[i] = np.float32(res.s[j].astype(np.float64).mean())
                steps[i] = res.num_steps[j]
                m = items[j]
                graphs[i] = m[0].nbr
                times[i] = elapsed / len(ks)
                drv.rep_boundary(i)
    for k in range(start_k):    # resumed prefix: graphs re-derive from seed+k
        graphs[k] = random_regular_graph(
            n, d, seed=seed + k, method=graph_method
        ).nbr
    drv.finish()
    out = HPREnsembleResult(mag, conf, steps, graphs, times)
    if save_path:
        save_results_npz(
            save_path,
            mag_reached=out.mag_reached,
            conf=out.conf,
            num_steps=out.num_steps,
            graphs=out.graphs,
            time=out.time,
        )
    return out
