"""graphdyn.pipeline — batched multi-graph ensembles with host/device
prefetch overlap (ARCHITECTURE.md "Ensemble pipeline").

Three pieces close the gap between per-kernel rates and end-to-end driver
rates:

- **Batched multi-graph execution**: a disorder ensemble's repetitions run
  ``group_size`` at a time as ONE vmapped compiled program over stacked
  per-repetition tables (:mod:`~graphdyn.pipeline.sa_group`,
  :mod:`~graphdyn.pipeline.hpr_group`), element-wise identical to the
  serial drivers because per-repetition RNG streams still derive from
  ``seed + k``.
- **Host/device prefetch overlap**: a bounded background thread builds the
  next group's graphs while the current group computes
  (:mod:`~graphdyn.pipeline.prefetch`) — deterministic by construction.
- **Persistent compile cache**: opt-in ``jax_compilation_cache_dir`` wiring
  (:func:`graphdyn.utils.platform.apply_compile_cache`,
  ``GRAPHDYN_COMPILE_CACHE`` / CLI ``--compile-cache``) so re-runs and
  resumed jobs skip the multi-second XLA compile.
"""

from graphdyn.pipeline.entropy_group import EntropyCellExec, run_cell_ladder
from graphdyn.pipeline.groups import GroupDriver, group_ranges
from graphdyn.pipeline.prefetch import HostPrefetcher

__all__ = [
    "EntropyCellExec",
    "GroupDriver",
    "HostPrefetcher",
    "group_ranges",
    "run_cell_ladder",
]
