"""Group-boundary driver protocol shared by the grouped ensemble drivers.

One implementation of the bookkeeping every grouped driver needs — resume
prefix, interval-gated snapshots, the ``rep.boundary`` fault site, and the
graceful-shutdown poll — so the SA and HPr pipelines cannot drift from each
other or from the serial drivers' PR-2 resilience contract.

Semantics relative to the serial drivers (``sa_ensemble``/``hpr_ensemble``):

- Snapshots carry the SAME metadata (``run_id`` + ``next_rep``), so a
  checkpoint written by the serial path resumes under the grouped path and
  vice versa, and a resume may use a different ``group_size`` — results are
  per-repetition deterministic (``seed + k``), so regrouping cannot change
  them.
- The ``rep.boundary`` fault site and the shutdown poll fire once per
  repetition, in repetition order, at each **group boundary** (after the
  group's device program returns) — a fault plan written against the serial
  driver observes the same hit sequence.
- Mid-group, the device program is chunked and
  :func:`~graphdyn.resilience.shutdown.shutdown_requested` is polled
  between chunks: a SIGTERM during a long group snapshots the completed
  prefix (``next_rep`` = the group's first repetition) and exits 75; the
  resumed run re-runs the interrupted group from its start, bit-exactly.
"""

from __future__ import annotations

from typing import Iterator

from graphdyn import obs
from graphdyn.resilience import faults as _faults
from graphdyn.resilience.shutdown import raise_if_requested, shutdown_requested
from graphdyn.resilience.supervisor import beat as _beat


def group_ranges(start: int, stop: int, size: int) -> Iterator[list[int]]:
    """Partition ``range(start, stop)`` into consecutive groups of at most
    ``size`` repetitions (the tail group may be shorter; the group runners
    pad it back to ``size`` with inactive rows for shape stability)."""
    if size < 1:
        raise ValueError(f"group_size must be >= 1, got {size}")
    k = start
    while k < stop:
        ks = list(range(k, min(k + size, stop)))
        yield ks
        k = ks[-1] + 1


class GroupDriver:
    """Checkpoint/fault/shutdown bookkeeping for one grouped ensemble run.

    ``payload()`` must return the driver's result-array dict (the completed
    prefix is what matters; rows past ``next_rep`` are garbage exactly as in
    the serial drivers). ``run_id`` is the identity dict stamped into every
    snapshot and validated on resume."""

    def __init__(self, checkpoint_path: str | None, interval_s: float,
                 run_id: dict, payload):
        from graphdyn.utils.io import PeriodicCheckpointer, open_checkpoint

        self.path = checkpoint_path
        self.run_id = run_id
        self.payload = payload
        self.ck = open_checkpoint(checkpoint_path) if checkpoint_path else None
        self.pc = (
            PeriodicCheckpointer(checkpoint_path, interval_s=interval_s)
            if checkpoint_path else None
        )

    def resume_prefix(self):
        """(arrays, start_rep) from a validated snapshot, or None."""
        from graphdyn.utils.io import load_resume_prefix

        if self.ck is None:
            return None
        return load_resume_prefix(self.ck, self.run_id)

    def resume_into(self, dest: dict) -> int:
        """Restore the completed-repetition prefix of a validated snapshot
        into the driver arrays (``dest`` is the payload dict — keys match
        by construction) and return the first repetition to run."""
        resumed = self.resume_prefix()
        if resumed is None:
            return 0
        arrays, start_rep = resumed
        for key, arr in dest.items():
            arr[:start_rep] = arrays[key][:start_rep]
        return start_rep

    def chunk_poll(self, next_rep: int) -> None:
        """Between device chunks of an in-flight group: heartbeat, then
        honor a pending graceful shutdown with a prefix snapshot (the group
        re-runs from ``next_rep`` on resume)."""
        _beat("chunk")
        if shutdown_requested():
            obs.counter("resilience.shutdown", where="chunk",
                        next_rep=next_rep)
            if self.pc is not None:
                self.pc.save_now(self.payload(), {**self.run_id,
                                                  "next_rep": next_rep})
            raise_if_requested(where="chunk")

    def rep_boundary(self, k: int) -> None:
        """After repetition ``k``'s results land in the driver arrays:
        heartbeat, interval-gated snapshot, the ``rep.boundary`` fault
        site, and the shutdown poll — the serial drivers' exact
        per-repetition sequence. The heartbeat leads, so a snapshot that
        hangs (dead NFS) is itself a detectable stall."""
        _beat("rep")
        if self.path is not None:
            # a SERIAL-path run preempted mid-repetition leaves its
            # in-flight chain snapshot at <path>_chain<k>; this repetition
            # just recomputed under the grouped path, so the stale file
            # must go — a later serial run reusing this checkpoint path
            # would otherwise hit its fingerprint check and refuse to
            # resume, wedging mid-ensemble
            from graphdyn.utils.io import open_checkpoint

            open_checkpoint(f"{self.path}_chain{k}").remove()
        if self.pc is not None:
            self.pc.maybe_save(self.payload(), {**self.run_id,
                                                "next_rep": k + 1})
        obs.counter("pipeline.rep.boundary", rep=k)
        _faults.maybe_fail("rep.boundary", key=f"rep={k}")
        if shutdown_requested():
            obs.counter("resilience.shutdown", where="rep", next_rep=k + 1)
            if self.pc is not None:
                self.pc.save_now(self.payload(), {**self.run_id,
                                                  "next_rep": k + 1})
            raise_if_requested(where="rep")

    def finish(self) -> None:
        if self.ck is not None:
            self.ck.remove()
