"""Cell-parallel BDCM λ-ladders — the entropy half of the ensemble pipeline
(ARCHITECTURE.md "Ensemble pipeline").

The entropy grid (`graphdyn.models.entropy.entropy_grid`) is the repo's
slowest workload: every (deg, rep) cell runs a warm-started λ-ladder of
~10² fixed-point sweeps per λ, and the serial driver runs the cells one
after another — the ladder is sequential *in λ* (each λ warm-starts from
the previous fixed point; there is nothing to batch along that axis) but
embarrassingly parallel *across cells*, the same replica-parallel structure
the TPU Ising literature exploits (Yang et al., arXiv:1903.11714; Isakov
et al., arXiv:1401.1084). Here a group of ``G`` cells advances as ONE
compiled program: the per-cell BDCM index tables stack to ``[G, Ed_max,
…]`` (:func:`graphdyn.ops.bdcm.stack_bdcm` — ragged edge counts pad to
``Ed_max`` with the existing ghost-row machinery), chi carries a leading
cell axis, and each cell solves its OWN λ (a per-cell λ vector — cells sit
at different ladder positions).

The group program runs in bounded **sweep chunks** rather than joint
fixed-point barriers: each device call advances every unfinished lane by
at most ``chunk_sweeps`` sweeps, a lane that reaches ITS OWN fixed point
freezes mid-chunk (the per-lane while-loop cond — so its sweep count and
final state are bit-identical to the serial ladder's), and at the chunk
boundary the host moves converged cells on to their next λ (leaf write +
carry reset) while slower cells keep iterating. Without this, a joint
barrier would cost ``G·max(t)`` sweeps per λ against the serial path's
``sum(t)`` — the chunk scheme bounds the lockstep waste at
``chunk_sweeps`` per cell per λ. Converged/stopped cells are frozen by an
active mask (the same pad-row freeze trick as ``sa_group``); plateau /
entropy-floor / non-convergence exits are evaluated per cell on the host
at ladder boundaries, exactly as the serial ladder evaluates them.

Element-wise identity with the serial path is structural, the PR-3 lesson:
:func:`graphdyn.models.entropy.entropy_sweep` itself advances through this
module's group program at G=1 (as ``hpr_solve`` advances through
``HPRGroupExec``), so serial-vs-grouped parity is one-program-family
parity, not a maintained coincidence — the per-row sweep arithmetic
(:func:`graphdyn.ops.bdcm.class_update`) is row-independent, the per-cell
convergence delta is a max (reassociation-immune), and the observables
(φ, m_init) run per cell through the SAME serial executors on the cell's
own ``chi[:2E]`` slice, never through a re-derived stacked reduction whose
float schedule could drift at the ulp level. Tested element-wise against
the pre-refactor serial values (regression anchor) and across group sizes
including 1 and non-divisors of the cell count.

Checkpoint/fault semantics at ladder boundaries mirror the serial ladder:
``lambda.boundary`` fires once per cell per visited λ (key
``lmbd=<value>`` — a plan written against the serial ladder matches the
same λ); the ``sweep.nan`` site is checked once per completed fixed point
per cell; shutdown is polled at every chunk boundary and a pending
graceful shutdown snapshots λ-granularly (each cell's last-boundary chi)
and raises — see ``entropy_grid`` for the snapshot format shared (and
interchangeable) with the serial path.
"""

from __future__ import annotations

import logging
from functools import partial
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from graphdyn import obs
from graphdyn.resilience import faults as _faults
from graphdyn.resilience.shutdown import raise_if_requested, shutdown_requested
from graphdyn.resilience.supervisor import beat as _heartbeat
from graphdyn.ops.bdcm import (
    StackedBDCM,
    class_update,
    make_free_entropy,
    make_mean_m_init,
    resilient_exec,
    resolve_group_pallas_modes,
    stack_bdcm,
)

log = logging.getLogger("graphdyn.pipeline")


class _CellSpec(NamedTuple):
    """Hashable static configuration of one cell-group program. Everything
    traced (chi, λ, active mask, carry, index tables) is an argument of the
    module-level executors, so groups whose stacked table shapes coincide
    share ONE compiled program (shapes are cell-count + class-population
    maxima; ``class_bucket`` keeps those stable across ER instances)."""

    T: int
    K: int
    damp: float
    eps_clamp: float
    eps: float            # fixed-point tolerance (per-cell max|Δchi|)
    t_max: int            # max_sweeps
    chunk: int            # sweep budget per device call
    class_ds: tuple       # union degree-class neighbor counts d
    pallas: tuple = ()    # per-class kernel mode: '' (XLA) | 'tpu' |
    #                       'interpret' (resolve_group_pallas_modes; the
    #                       runtime Pallas→XLA fallback swaps this tuple)


@partial(jax.jit, static_argnames=("spec",))
# warm-start ladders replay chi through leaf-set + fixed-point variants;
# donation would invalidate their input buffer (same contract as the
# serial _fixed_point_exec had)
# graftlint: disable-next-line=GD006  callers reuse chi across variants
def _cell_chunk_exec(chi, lmbd, active, delta0, t0, valid, x0, tables,
                     spec: _CellSpec):
    """One bounded chunk of every cell's fixed point, vmapped over the cell
    axis: lane g iterates ITS OWN λ's sweep from carry ``(chi_g, delta0_g,
    t0_g)`` until ``max|Δchi| < eps``, ``t_max``, or ``t0_g + chunk``
    sweeps. Per-lane freezing is the while_loop batching rule itself — a
    lane whose cond is False keeps its state bit-for-bit while other lanes
    advance, so a cell's sweep trajectory is identical to the serial
    ladder's, merely sliced into chunks. Pad rows past a cell's own 2E are
    never indexed by its tables, so they stay constant and contribute 0 to
    the per-cell delta; the ghost row 2E_max is concatenated per sweep,
    scattered with pad-member garbage, and sliced off — exactly the serial
    ghost mechanism.

    With any Pallas class mode set (``spec.pallas``), the chunk runs the
    JOINT restatement (:func:`_cell_chunk_pallas`) instead: the fused
    grouped kernel needs the cell axis as a Pallas grid dimension, which a
    per-lane ``vmap`` cannot provide. Kernel choice is a numeric MODE
    (Pallas-vs-XLA ≈ documented tolerance), never silently mixed: the
    identity contract is grouped == serial *within the same mode*, and the
    serial ladder (``entropy_sweep`` → G=1 instance of this same program)
    follows the mode with it."""
    if any(spec.pallas):
        return _cell_chunk_pallas(
            chi, lmbd, active, delta0, t0, valid, x0, tables, spec
        )
    K = spec.K
    flat = [t for (idx, ie, _) in tables for t in (idx, ie)]
    As = [A for (_, _, A) in tables]

    def one(c0, lm, act, d0, tt0, *tabs):
        tilt = jnp.exp(-lm * x0)
        cap = tt0 + spec.chunk

        def sweep(c):
            ghost = jnp.full((1,) + c.shape[1:], 1.0 / (K * K), c.dtype)
            ce = jnp.concatenate([c, ghost], axis=0)
            for d, A, (idx, ie) in zip(
                spec.class_ds, As, zip(*[iter(tabs)] * 2)
            ):
                chi_in = ce[ie] * valid[None, None, :, None]
                upd = class_update(
                    chi_in, A, tilt, ce[idx], d=d, T=spec.T, K=K,
                    damp=spec.damp, eps_clamp=spec.eps_clamp,
                )
                ce = ce.at[idx].set(upd)
            return ce[:-1]

        def cond(st):
            _, delta, t = st
            return (
                act & (delta > spec.eps) & (t < spec.t_max) & (t < cap)
            )

        def body(st):
            c, _, t = st
            new = sweep(c)
            return new, jnp.abs(new - c).max(), t + 1

        c, delta, t = lax.while_loop(cond, body, (c0, d0, tt0))
        return c, t, delta

    return jax.vmap(one, in_axes=(0, 0, 0, 0, 0) + (0,) * len(flat))(
        chi, lmbd, active, delta0, t0, *flat
    )


def _cell_chunk_pallas(chi, lmbd, active, delta0, t0, valid, x0, tables,
                       spec: _CellSpec):
    """The Pallas-mode cell chunk: one JOINT while_loop whose body sweeps
    every live lane through the fused grouped kernel
    (:func:`graphdyn.ops.pallas_bdcm.dp_contract_grouped` — cell axis as
    the leading grid dimension, per-cell λ-tilt carried as the
    group-resident ``A_tilted`` stack) and freezes finished lanes by
    select, which is exactly the transform ``vmap`` applies to the XLA
    path's per-lane while_loop — so a lane's sweep count and freeze
    semantics match the XLA chunk one-for-one, while the sweep arithmetic
    is the kernel's (tolerance-based vs XLA, bit-exact across group
    extents). Classes whose shape fails the grouped VMEM gate stay on the
    vmapped :func:`class_update` inside the same sweep (mixed-mode
    programs are still one program family at every G)."""
    from graphdyn.ops.pallas_bdcm import dp_contract_grouped

    K = spec.K
    tilt = jnp.exp(-lmbd[:, None] * x0[None, :])        # [G, K] per-cell
    cap = t0 + spec.chunk

    def gather(ce, tab):
        return jax.vmap(lambda c, t: c[t])(ce, tab)

    # named apart from the XLA path's nested `sweep`: graftlint's GD009
    # call-graph is module-local by bare name, and THIS one reaches
    # pallas_call (via dp_contract_grouped) while the XLA one must stay
    # freely vmappable
    def fused_sweep(c):
        ghost = jnp.full(
            (c.shape[0], 1) + c.shape[2:], 1.0 / (K * K), c.dtype
        )
        ce = jnp.concatenate([c, ghost], axis=1)
        for (d, mode), (idx, ie, A) in zip(
            zip(spec.class_ds, spec.pallas), tables
        ):
            chi_in = gather(ce, ie) * valid[None, None, None, :, None]
            chi_old = gather(ce, idx)
            if mode:
                # trace-time site: a firing plan here stands in for a real
                # kernel lowering/compile failure on this backend
                _faults.maybe_fail("pallas.lower", key=f"d={d}")
                a_stack = A[None] * tilt[:, :, None, None]   # [G, K, K, M]
                upd = dp_contract_grouped(
                    chi_in, a_stack, chi_old, d=d, T=spec.T,
                    damp=spec.damp, eps_clamp=spec.eps_clamp,
                    interpret=mode == "interpret",
                ).astype(c.dtype)
            else:
                upd = jax.vmap(
                    lambda ci, co, tl, A=A, d=d: class_update(
                        ci, A, tl, co, d=d, T=spec.T, K=K,
                        damp=spec.damp, eps_clamp=spec.eps_clamp,
                    )
                )(chi_in, chi_old, tilt)
            ce = jax.vmap(lambda c_, i_, u_: c_.at[i_].set(u_))(ce, idx, upd)
        return ce[:, :-1]

    def live_lanes(delta, t):
        return active & (delta > spec.eps) & (t < spec.t_max) & (t < cap)

    def cond(st):
        _, delta, t = st
        return jnp.any(live_lanes(delta, t))

    def body(st):
        c, delta, t = st
        live = live_lanes(delta, t)
        new = fused_sweep(c)
        d_new = jnp.abs(new - c).max(axis=(1, 2, 3))
        return (
            jnp.where(live[:, None, None, None], new, c),
            jnp.where(live, d_new, delta),
            jnp.where(live, t + 1, t),
        )

    c, delta, t = lax.while_loop(cond, body, (chi, delta0, t0))
    return c, t, delta


@partial(jax.jit, static_argnames=("K",))
def _cell_set_leaves_exec(chi, lmbd, active, leaf01, x0, leaf_idx, K: int):
    """Per-cell closed-form leaf messages at the cell's OWN λ; lanes not in
    ``active`` keep their chi untouched (frozen warm-start or mid-sweep
    state). Pad leaf slots target the ghost row, which is sliced off."""

    def one(c, lm, act, li):
        t = leaf01 * jnp.exp(-lm * x0)[:, None]
        t = t / t.sum()
        ghost = jnp.full((1,) + c.shape[1:], 1.0 / (K * K), c.dtype)
        ce = jnp.concatenate([c, ghost], axis=0).at[li].set(t[None])
        return jnp.where(act, ce[:-1], c)

    return jax.vmap(one, in_axes=(0, 0, 0, 0))(chi, lmbd, active, leaf_idx)


class EntropyCellExec:
    """Compiled-program handle for one (padded) group of entropy λ-ladder
    cells — stacked ragged tables, static spec, the vmapped leaf-set /
    chunked fixed-point executors, and per-cell serial observables. The
    SINGLE program family every entropy ladder runs through:
    :func:`graphdyn.models.entropy.entropy_sweep` executes a G=1 instance
    and the grouped ``entropy_grid`` a G=``group_size`` instance of the
    same vmapped body, which is what makes serial-vs-grouped parity
    structural (module docstring).

    ``cells``: list of ``(BDCMData, n_total, n_iso)`` per REAL cell (the
    isolate-removed graph's tables plus the analytic isolate terms).
    ``group_size`` pads the stack with inactive copies of cell 0 so a short
    tail group reuses the full group's compiled program. ``mesh`` shards
    the CELL axis over ``cell_axis`` via
    :func:`graphdyn.parallel.mesh.shard_stack` — cells are independent, so
    the partitioned program is communication-free except the per-lane
    while-loop stop test; results are bit-identical to the unsharded
    program (tested).

    ``kernel`` selects the sweep core per union degree class
    (ARCHITECTURE.md "Kernel selection"): ``'auto'`` (default) fuses the
    class's DP + contraction into the grouped Pallas kernel on TPU
    backends when the group-resident spec fits
    (:func:`graphdyn.ops.bdcm.resolve_group_pallas_modes` — the cell axis
    becomes a Pallas grid dimension, each cell's λ-tilt carried in the
    resident ``A_tilted`` stack); ``'pallas'`` forces it (interpret mode
    off-TPU, for tests); ``'xla'`` keeps the pure-XLA path. Pallas-vs-XLA
    is an approximate mode (~1e-3 max rel err, PALLAS_TPU.json); grouped
    == serial holds bit-exactly WITHIN a mode because ``entropy_sweep``
    runs the G=1 instance of this same program. A kernel
    lowering/compile failure at run time degrades the program to XLA via
    the shared :func:`graphdyn.ops.bdcm.pallas_fallback_spec` machinery
    (logged, run continues); a spec the VMEM model rejects never selects
    Pallas in the first place. The mesh path keeps the XLA core
    (``kernel='pallas'`` with a mesh is refused: a Pallas launch inside a
    GSPMD-partitioned cell axis is not a supported composition)."""

    def __init__(self, cells, config, *, group_size: int | None = None,
                 chunk_sweeps: int = 64, mesh=None, cell_axis: str = "cell",
                 kernel: str = "auto"):
        G_real = len(cells)
        G = group_size or G_real
        if G < G_real:
            raise ValueError(f"group_size={G} < group population {G_real}")
        if chunk_sweeps < 1:
            raise ValueError(f"chunk_sweeps must be >= 1, got {chunk_sweeps}")
        if mesh is not None:
            n_dev = int(np.prod(list(mesh.shape.values())))
            if G % n_dev:
                raise ValueError(
                    f"group size {G} not divisible by the mesh's "
                    f"{n_dev} devices"
                )
        if mesh is not None and kernel == "pallas":
            raise ValueError(
                "kernel='pallas' is incompatible with mesh= (a Pallas "
                "launch inside the GSPMD-partitioned cell axis is not a "
                "supported composition); use kernel='auto' or 'xla'"
            )
        padded = list(cells) + [cells[0]] * (G - G_real)
        stk = stack_bdcm([c[0] for c in padded])
        self.stk: StackedBDCM = stk
        self.G, self.G_real = G, G_real
        self.dtype = stk.dtype
        self._state = {"spec": _CellSpec(
            T=stk.T, K=stk.K, damp=float(config.damp),
            eps_clamp=float(config.eps_clamp), eps=float(config.eps),
            t_max=int(config.max_sweeps), chunk=int(chunk_sweeps),
            class_ds=tuple(d for d, _, _, _ in stk.edge_classes),
            # per-cell λ-tilts ride the group-resident A_tilted stack
            pallas=resolve_group_pallas_modes(
                [d for d, _, _, _ in stk.edge_classes],
                [idx.shape[1] for _, idx, _, _ in stk.edge_classes],
                T=stk.T, dtype=stk.dtype,
                kernel="xla" if mesh is not None else kernel,
                G=G, per_group_a=True,
            ),
        )}

        if mesh is None:
            place_g = place_r = jnp.asarray
        else:
            from graphdyn.parallel.mesh import replicate, shard_stack

            def place_g(x):
                return shard_stack(mesh, jnp.asarray(x), cell_axis)

            def place_r(x):
                return replicate(mesh, jnp.asarray(x))

        self._place_g = place_g
        self.tables = tuple(
            (place_g(idx), place_g(ie), place_r(np.asarray(A, stk.dtype)))
            for _, idx, ie, A in stk.edge_classes
        )
        self.valid = place_r(stk.valid)
        self.x0 = place_r(np.asarray(stk.x0, stk.dtype))
        self.leaf01 = place_r(np.asarray(stk.leaf01, stk.dtype))
        self.leaf_idx = place_g(stk.leaf_idx)
        self._act1 = jnp.ones((1,), bool)
        # per-cell serial observables — the SAME executors the serial ladder
        # calls, on the cell's own chi slice: grouped observables are
        # bit-identical to serial by construction, not by float luck
        self._observe = [
            (
                make_free_entropy(
                    data, n_total=n_total, n_iso=n_iso,
                    eps_clamp=config.eps_clamp,
                ),
                make_mean_m_init(
                    data, n_total=n_total, n_iso=n_iso,
                    eps_clamp=config.eps_clamp,
                ),
            )
            for data, n_total, n_iso in cells
        ]

    @property
    def spec(self) -> _CellSpec:
        """The CURRENT static spec — the runtime Pallas→XLA fallback swaps
        the held spec, and every later chunk must see the rebuilt one."""
        return self._state["spec"]

    # -- stacked (group) surface ----------------------------------------

    def stack_chi(self, chi_list) -> jnp.ndarray:
        """[G, 2E_max, K, K] from per-REAL-cell chi arrays (pad lanes get
        copies of cell 0's chi — inert: their lane is never active)."""
        padded = list(chi_list) + [chi_list[0]] * (self.G - self.G_real)
        return self._place_g(np.asarray(self.stk.stack_chi(padded)))

    def set_leaves(self, chi, lmbd_vec, active):
        return _cell_set_leaves_exec(
            chi, lmbd_vec, active, self.leaf01, self.x0, self.leaf_idx,
            self.spec.K,
        )

    def fixed_point_chunk(self, chi, lmbd_vec, active, delta0, t0):
        """``(chi', t[G], delta[G])`` after at most ``chunk_sweeps`` more
        sweeps per unfinished lane (carry resumes exactly). A Pallas
        lowering/compile failure degrades the program to the XLA path at
        runtime (:func:`graphdyn.ops.bdcm.resilient_exec` — logged, the
        rebuilt spec sticks for all later chunks)."""
        return resilient_exec(self._state, lambda sp: _cell_chunk_exec(
            chi, lmbd_vec, active, delta0, t0, self.valid, self.x0,
            self.tables, sp,
        ))

    def lower_chunk(self, chi, lmbd_vec, active, delta0, t0):
        """Lower (without executing) the chunk program for this group's
        shapes — the exact :func:`_cell_chunk_exec` invocation
        :meth:`fixed_point_chunk` dispatches, as a ``jax.stages.Lowered``
        for :mod:`graphdyn.analysis.graftcheck` fingerprinting. Kept next
        to ``fixed_point_chunk`` so a chunk refactor updates the
        fingerprinted surface in the same place."""
        return _cell_chunk_exec.lower(
            chi, lmbd_vec, active, delta0, t0, self.valid, self.x0,
            self.tables, self.spec,
        )

    def poison_cell(self, chi, g: int):
        """The ``sweep.nan`` fault payload for cell ``g`` — one NaN seeded
        into its carry (the serial :func:`~graphdyn.ops.bdcm.poison_nan`
        position)."""
        return chi.at[g, 0, 0, 0].set(jnp.nan)

    def unstack_chi(self, chi, g: int) -> jnp.ndarray:
        """Cell ``g``'s own ``[2E_g, K, K]`` slice of the stacked chi."""
        return chi[g, : int(self.stk.twoE[g])]

    def observe(self, chi, g: int, lmbd):
        """(φ, m_init) of cell ``g`` via its serial executors."""
        phi_fn, m_fn = self._observe[g]
        cg = self.unstack_chi(chi, g)
        return phi_fn(cg, lmbd), m_fn(cg)

    def observe_fns(self, g: int):
        return self._observe[g]

    # -- G=1 (serial-ladder) surface ------------------------------------

    def set_leaves1(self, chi, lmbd):
        return self.set_leaves(chi[None], jnp.reshape(lmbd, (1,)),
                               self._act1)[0]

    def fixed_point1(self, chi, lmbd):
        """The single cell's FULL fixed point — the serial ladder's
        ``(chi, lmbd) -> (chi*, sweeps, delta)`` surface, advanced through
        the group program at G=1 in host-driven chunks. Fault site
        ``sweep.nan`` is checked once per completed fixed point (the
        serial contract) and poisons the carry for NaN-path tests."""
        c = chi[None]
        lm = jnp.reshape(lmbd, (1,))
        delta = jnp.full((1,), jnp.inf, self.dtype)
        t = jnp.zeros((1,), jnp.int32)
        while True:
            c, t, delta = self.fixed_point_chunk(c, lm, self._act1, delta, t)
            d = float(delta[0])
            if not (d > self.spec.eps) or int(t[0]) >= self.spec.t_max:
                break
        if _faults.transform_spec("sweep.nan", "nan") is not None:
            c = self.poison_cell(c, 0)
            delta = jnp.full_like(delta, jnp.nan)
        return c[0], t[0], delta[0]


class CellLadderResult(NamedTuple):
    """Per-cell ladder outputs (lists indexed by real cell)."""

    lambdas: list          # visited λ values per cell
    ent: list              # φ rows per cell
    m_init: list
    ent1: list
    sweeps: list
    nonconverged: np.ndarray   # [G_real] — λ whose fixed point failed, or 0
    chi: list              # final [2E_g, K, K] resume state per cell


def run_cell_ladder(
    ex: EntropyCellExec,
    chi_list,
    lambdas: np.ndarray,
    *,
    eps: float,
    ent_floor: float,
    k0=None,
    plateau_eps: float = 0.0,
    plateau_patience: int = 3,
    prev_rows=None,
    record=None,
    boundary=None,
    verbose: bool = False,
) -> CellLadderResult:
    """Advance every cell of the group through ITS OWN remaining ladder
    positions — the grouped restatement of the serial ``_run_ladder`` host
    loop, chunk-pipelined so a converged cell moves on to its next λ while
    slower cells keep iterating (module docstring).

    ``k0[g]`` is cell g's first unvisited ladder index (a resumed cell may
    start mid-ladder); ``prev_rows[g] = (m_init_rows, ent1_rows)`` is its
    restored prefix for plateau-streak reconstruction (None on cold
    starts). ``record(g, k, lmbd, phi, m0, e1, sweeps, failed)`` fires per
    cell per visited λ; ``boundary(stopping, active_info)`` fires at each
    chunk boundary where at least one cell crossed a λ boundary (and at
    every chunk when a shutdown is pending), BEFORE the shutdown raise and
    the per-cell ``lambda.boundary`` faults — ``active_info`` lists
    ``{"g", "visited", "lmbd", "failed", "chi"}`` per still-unfinished
    cell, where ``chi`` is the cell's LAST-BOUNDARY state (captured only
    when a ``boundary`` callback is given), so a snapshot resumes
    λ-granularly and bit-exactly.
    """
    G, Gr = ex.G, ex.G_real
    L = int(np.asarray(lambdas).size)
    lambdas = np.asarray(lambdas, float)
    plateau_patience = max(1, int(plateau_patience))
    k = np.zeros(G, np.int64)
    if k0 is not None:
        k[:Gr] = np.asarray(k0, np.int64)
    active = np.zeros(G, bool)
    active[:Gr] = k[:Gr] < L

    rows_l = [[] for _ in range(Gr)]
    rows_e = [[] for _ in range(Gr)]
    rows_m = [[] for _ in range(Gr)]
    rows_e1 = [[] for _ in range(Gr)]
    rows_t = [[] for _ in range(Gr)]
    nonconv = np.zeros(Gr)
    streak = np.zeros(Gr, np.int64)
    prev_m: list = [None] * Gr
    prev_e: list = [None] * Gr
    if plateau_eps > 0 and prev_rows is not None:
        # reconstruct each cell's plateau streak from its restored prefix,
        # exactly as the serial ladder does — a resumed cell exits at the
        # λ an uninterrupted run would
        for g in range(Gr):
            pr = prev_rows[g] if g < len(prev_rows) else None
            if pr is None or len(pr[0]) == 0:
                continue
            pm, pe = (np.asarray(r) for r in pr)
            for i in range(1, len(pm)):
                moved = max(float(np.max(np.abs(pm[i] - pm[i - 1]))),
                            float(np.max(np.abs(pe[i] - pe[i - 1]))))
                streak[g] = streak[g] + 1 if moved < plateau_eps else 0
            prev_m[g], prev_e[g] = pm[-1], pe[-1]
            if streak[g] >= plateau_patience:
                active[g] = False     # already exited inside the prefix

    chi = ex.stack_chi(chi_list)
    capture = boundary is not None
    # each cell's last-λ-BOUNDARY chi (the λ-granular snapshot payload);
    # before a cell's first crossing this is its start state — exactly
    # what a resume at its current cursor needs
    bchi: list = [
        (np.asarray(c) if capture else None) for c in chi_list
    ]
    np_dt = np.dtype(ex.dtype)
    lam_h = np.zeros(G, np_dt)
    lam_h[:Gr] = lambdas[np.minimum(k[:Gr], L - 1)]
    delta_h = np.full(G, np.inf, np_dt)
    t_h = np.zeros(G, np.int32)
    need_leaf = active.copy()          # lanes entering a fresh λ

    def info_active():
        return [
            {"g": g, "visited": int(k[g]),
             "lmbd": float(lambdas[max(k[g] - 1, 0)]),
             "failed": False, "chi": bchi[g]}
            for g in range(Gr) if active[g]
        ]

    rec = obs.current()
    chunk_i = 0
    while active[:Gr].any():
        # jnp.array (NOT asarray): on the CPU backend asarray may ALIAS the
        # numpy buffer, and these host arrays are mutated below while the
        # async device computation still reads them — an explicit copy is
        # the difference between determinism and a data race (observed)
        lm_dev = jnp.array(lam_h)
        if need_leaf.any():
            chi = ex.set_leaves(chi, lm_dev, jnp.array(need_leaf))
            delta_h[need_leaf] = np.inf
            t_h[need_leaf] = 0
            need_leaf[:] = False
        t_before = t_h.copy() if rec.enabled else None
        # per-chunk span: the np.asarray reads below are the device-sync
        # boundary (they drain the whole chunk program), so wall_s is
        # execute time; cold marks the compile-paying first chunk
        with rec.span("pipeline.entropy.chunk", chunk=chunk_i,
                      cold=chunk_i == 0) as sp:
            chi, t_v, delta_v = ex.fixed_point_chunk(
                chi, lm_dev, jnp.array(active),
                jnp.array(delta_h), jnp.array(t_h),
            )
            t_h_new, delta_h_new = np.asarray(t_v), np.asarray(delta_v)
            if rec.enabled:
                sp.set(
                    sweeps_advanced=int(
                        np.sum(t_h_new[active] - t_before[active])
                    ),
                    active=int(np.sum(active[:Gr])),
                )
        if rec.enabled:
            # device-memory gauges at the chunk boundary (obs.mem.*)
            obs.memband.emit_memory_gauges(loop="entropy.chunk",
                                           chunk=chunk_i)
        chunk_i += 1
        t_h[active] = t_h_new[active]
        delta_h[active] = delta_h_new[active]

        # a lane is at its λ boundary when its own fixed point finished:
        # converged (delta <= eps — note a NaN delta reads `> eps` as
        # False, the poison path) or out of sweep budget
        crossed = [
            g for g in range(Gr)
            if active[g] and (
                not (float(delta_h[g]) > eps) or int(t_h[g]) >= ex.spec.t_max
            )
        ]
        poisoned_now: dict = {}
        for g in crossed:
            # serial contract: one sweep.nan check per completed fixed
            # point per cell
            if _faults.transform_spec("sweep.nan", "nan") is not None:
                chi = ex.poison_cell(chi, g)
                delta_h[g] = np.nan
                poisoned_now[g] = True
        # dispatch every crossed cell's observables BEFORE the first
        # blocking host read — the per-cell executors queue asynchronously,
        # so the boundary pays one pipeline drain instead of one sync per
        # cell
        observed = {g: ex.observe(chi, g, lm_dev[g]) for g in crossed}
        fired = []
        for g in crossed:
            lmv = float(lambdas[k[g]])
            phi, m0 = observed[g]
            phi, m0 = np.asarray(phi), np.asarray(m0)
            e1 = phi + lmv * m0
            t_g = int(t_h[g])
            failed = float(delta_h[g]) > eps
            poisoned = bool(
                np.isnan(float(delta_h[g]))
                or np.isnan(phi).any() or np.isnan(m0).any()
            ) or poisoned_now.get(g, False)
            if poisoned and not failed:
                failed = True
            if poisoned:
                log.warning(
                    "non-finite sweep state at lambda=%g (cell %d, "
                    "delta=%r) — recording non-convergence and stopping "
                    "the cell's ladder", lmv, g, delta_h[g],
                )
                rec.counter("pipeline.sweep.nan", cell=g, lmbd=lmv)
                # preserve the flight evidence at the poison (see the
                # serial ladder's dump site in models/entropy.py)
                from graphdyn.obs import flight

                flight.dump("sweep.nan",
                            site=f"entropy cell={g} lambda={lmv:g}")
            if failed:
                nonconv[g] = lmv
            rec.counter("pipeline.lambda.boundary", cell=g, lmbd=lmv,
                        sweeps=t_g, failed=failed)
            rows_l[g].append(lmv)
            rows_e[g].append(phi)
            rows_m[g].append(m0)
            rows_e1[g].append(e1)
            rows_t[g].append(t_g)
            if record is not None:
                record(g, int(k[g]), lmv, phi, m0, e1, t_g, failed)
            if verbose:
                m_s = (f"{m0:.5f}" if np.ndim(m0) == 0
                       else f"{np.mean(m0):.5f}(mean)")
                print(f"cell={g} lambda={lmv:.2f} t={t_g} m_init={m_s}")
            if capture:
                bchi[g] = np.asarray(ex.unstack_chi(chi, g))
            fired.append((g, lmv))

            # per-cell exits, then the next ladder position
            k[g] += 1
            if bool(np.all(np.asarray(e1) < ent_floor)) or failed:
                active[g] = False
                continue
            if k[g] >= L:
                active[g] = False
                continue
            if plateau_eps > 0:
                if prev_m[g] is not None:
                    moved = max(
                        float(np.max(np.abs(m0 - prev_m[g]))),
                        float(np.max(np.abs(e1 - prev_e[g]))),
                    )
                    streak[g] = streak[g] + 1 if moved < plateau_eps else 0
                    if streak[g] >= plateau_patience:
                        active[g] = False
                prev_m[g], prev_e[g] = m0, e1
                if not active[g]:
                    continue
            lam_h[g] = lambdas[k[g]]
            need_leaf[g] = True

        _heartbeat("lambda")
        stopping = shutdown_requested()
        if boundary is not None and (fired or stopping):
            boundary(stopping, info_active())
        if stopping:
            raise_if_requested(where="lambda")
        for g, lmv in fired:
            _faults.maybe_fail("lambda.boundary", key=f"lmbd={lmv:g}")

    return CellLadderResult(
        lambdas=[np.array(r) for r in rows_l],
        ent=[np.array(r) for r in rows_e],
        m_init=[np.array(r) for r in rows_m],
        ent1=[np.array(r) for r in rows_e1],
        sweeps=[np.array(r, np.int64) for r in rows_t],
        nonconverged=nonconv,
        chi=[np.asarray(ex.unstack_chi(chi, g)) for g in range(Gr)],
    )
