"""Batched multi-graph SA ensembles — the device half of the pipeline.

The serial driver (`graphdyn.models.sa.sa_ensemble`) runs ``n_stat``
single-replica chains one after another, each on its own freshly sampled
RRG; the device computes a ``[1, n]`` rollout per MCMC step while every
other repetition waits. Here a *group* of ``G`` repetitions runs as ONE
compiled program: the neighbor tables stack to ``nbr[G, n, dmax]``
(:func:`graphdyn.graphs.stack_graphs`), the chain state carries a leading
group axis, and the candidate rollout is the same hot kernel
(:func:`graphdyn.ops.dynamics.batched_rollout_impl`) vmapped over the
per-repetition tables.

Element-wise identity with the serial path is structural: the per-replica
draw (:func:`graphdyn.models.sa.draw_sa_proposal`), the Metropolis/anneal
arithmetic (:func:`graphdyn.models.sa.metropolis_anneal_update`) and the
integer rollout are the *same functions* the serial solver runs, on the
same per-repetition values — RNG streams still derive from ``seed + k``,
finished chains freeze under the same ``active`` mask the replica-batched
solver already uses, and inactive pad rows (shape-stabilizing the tail
group so every group reuses one compiled program) start frozen. Tested
element-wise against the serial driver for several group sizes, including
1 and non-divisors of ``n_stat``.

Checkpointing moves from per-repetition chain files to **group-boundary
snapshots**: the driver persists the completed-repetition prefix exactly as
the serial driver does (same metadata, same ``next_rep`` key — snapshots
are interchangeable between the serial and grouped paths, and between
different group sizes), and a preempted in-flight group simply re-runs from
its start on resume (bit-exact: graphs and streams re-derive from
``seed + k``). The PR-2 contract — SIGTERM → snapshot → exit 75 → resume →
bit-exact completion, and the ``rep.boundary`` fault site — is preserved,
with faults and shutdown polls firing in repetition order at each group
boundary.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from graphdyn.config import SAConfig
from graphdyn.ops.dynamics import batched_rollout_impl, rule_coefficients


class _SAGroupState(NamedTuple):
    s: jnp.ndarray         # int8[G, n]
    sum_end: jnp.ndarray   # int32[G]
    a: jnp.ndarray         # f[G]
    b: jnp.ndarray         # f[G]
    t: jnp.ndarray         # int[G]
    m_final: jnp.ndarray   # f[G]
    active: jnp.ndarray    # bool[G]
    key: jnp.ndarray       # PRNG key per repetition [G]
    chunk_t: jnp.ndarray   # int32[] — steps taken in the current chunk


def _group_end_sum(nbr_stack, s, steps: int, R_coef: int, C_coef: int):
    """Σ_i s_endstate(s)_i per repetition, each on its OWN graph: the shared
    hot kernel vmapped over the stacked neighbor tables. Integer dynamics —
    exactly the serial solver's per-repetition sums."""

    def one(nb, sv):
        return batched_rollout_impl(nb, sv[None], steps, R_coef, C_coef)[0]

    s_end = jax.vmap(one)(nbr_stack, s)
    return s_end.astype(jnp.int32).sum(axis=1)


@partial(jax.jit, static_argnames=("rollout_steps", "R_coef", "C_coef"))
def _sa_group_init(nbr_stack, s0, key0, a0, b0, real, *, rollout_steps: int,
                   R_coef: int, C_coef: int) -> _SAGroupState:
    G, n = s0.shape
    dt = a0.dtype
    sum_end0 = _group_end_sum(nbr_stack, s0, rollout_steps, R_coef, C_coef)
    m0 = sum_end0.astype(dt) / n
    return _SAGroupState(
        s=s0,
        sum_end=sum_end0,
        a=a0,
        b=b0,
        t=jnp.zeros((G,), jnp.int64 if jax.config.jax_enable_x64 else jnp.int32),
        m_final=m0,
        active=(m0 < 1.0) & real,
        key=key0,
        chunk_t=jnp.zeros((), jnp.int32),
    )


@partial(
    jax.jit,
    static_argnames=("rollout_steps", "R_coef", "C_coef", "max_steps",
                     "chunk_steps"),
    # group-to-group carry reuse: the previous chunk's state is never read
    # again after the call (group checkpoints snapshot the DRIVER arrays,
    # not the device carry), so the big [G, n] buffers update in place
    donate_argnums=(1,),
)
def _sa_group_loop(
    nbr_stack,
    state: _SAGroupState,
    par_a,
    par_b,
    a_cap,
    b_cap,
    *,
    rollout_steps: int,
    R_coef: int,
    C_coef: int,
    max_steps: int,
    chunk_steps: int | None = None,
):
    """Advance all chains of the group until every one stops (or for at most
    ``chunk_steps`` more steps — the shutdown-poll granularity). The body is
    the serial solver's body on a group axis: same draw, same accept/anneal
    arithmetic, per-repetition neighbor tables in the rollout."""
    from graphdyn.models.sa import draw_sa_proposal, metropolis_anneal_update

    G, n = state.s.shape
    dt = state.a.dtype

    def cond(st: _SAGroupState):
        go = jnp.any(st.active)
        if chunk_steps is not None:
            go = go & (st.chunk_t < chunk_steps)
        return go

    def body(st: _SAGroupState):
        i, u = draw_sa_proposal(
            st.key, st.t, None, None,
            injected=False, stream_len=1, n=n, dt=dt,
        )
        gidx = jnp.arange(G)
        s_i = st.s[gidx, i].astype(jnp.int32)
        s_flip = st.s.at[gidx, i].set((-s_i).astype(jnp.int8))
        sum_end_flip = _group_end_sum(
            nbr_stack, s_flip, rollout_steps, R_coef, C_coef
        )
        do, sum_end_new, a_new, b_new, t_new, m_final, active = (
            metropolis_anneal_update(
                st.active, st.a, st.b, st.t, st.m_final,
                st.sum_end, sum_end_flip, s_i, u,
                par_a=par_a, par_b=par_b, a_cap=a_cap, b_cap=b_cap,
                max_steps=max_steps, n=n,
            )
        )
        s_new = jnp.where(do[:, None], s_flip, st.s)
        return _SAGroupState(
            s_new, sum_end_new, a_new, b_new, t_new, m_final, active, st.key,
            st.chunk_t + 1,
        )

    return lax.while_loop(cond, body, state)


class SAGroupResult(NamedTuple):
    s: np.ndarray          # int8[G, n]
    num_steps: np.ndarray  # int[G]
    m_final: np.ndarray    # f[G]


def _assemble_group(
    graphs, preps, rep_seeds, config: SAConfig, *,
    dtype, group_size, mesh, group_axis,
):
    """The group-program argument assembly shared by :func:`run_sa_group`
    and :func:`lower_group_loop`: stacked/padded tables, the initial device
    state, the loop constants, and the static loop parameters — ONE
    assembly, so the lowered-for-fingerprinting program and the executed
    program cannot drift apart."""
    from graphdyn.graphs import stack_graphs

    G_real = len(graphs)
    G = group_size or G_real
    if G < G_real:
        raise ValueError(f"group_size={G} < group population {G_real}")
    if mesh is not None and G % int(np.prod(list(mesh.shape.values()))):
        raise ValueError(
            f"group size {G} not divisible by the mesh's "
            f"{int(np.prod(list(mesh.shape.values())))} devices"
        )
    dyn = config.dynamics
    R_coef, C_coef = rule_coefficients(dyn.rule, dyn.tie)
    rollout = dyn.p + dyn.c - 1
    np_dt = np.float32 if dtype == jnp.float32 else np.float64  # graftlint: disable=GD004  dtype mirror for host staging
    n = graphs[0].n

    max_steps = {int(p[7]) for p in preps}
    if len(max_steps) != 1:
        raise ValueError(f"group mixes step budgets: {sorted(max_steps)}")
    max_steps = max_steps.pop()

    def pad(rows):
        return rows + [rows[0]] * (G - G_real)

    nbr_stack = stack_graphs(pad(list(graphs))).nbr
    s0 = np.concatenate(pad([p[2] for p in preps]))
    a0 = np.concatenate(pad([p[3] for p in preps])).astype(np_dt)
    b0 = np.concatenate(pad([p[4] for p in preps])).astype(np_dt)
    # per-repetition root keys: exactly the serial solver's derivation for
    # R=1, seed=seed+k (np.arange(1, uint32) + uint32(seed+k) == [seed+k])
    key_seeds = np.asarray(pad([np.uint32(s) for s in rep_seeds]), np.uint32)
    keys = jax.vmap(jax.random.PRNGKey)(key_seeds)
    real = np.zeros(G, bool)
    real[:G_real] = True
    # jnp.array (NOT asarray): `real` is a mutated host buffer — the GD010
    # discipline is to copy at every such crossing so a reorder can never
    # reintroduce the PR-4 alias race (mirrors hpr_group.init_state)
    real_dev = jnp.array(real)

    def place(x):
        x = jnp.asarray(x)
        if mesh is None:
            return x
        from graphdyn.parallel.mesh import shard_stack

        return shard_stack(mesh, x, group_axis)

    nbr_dev = place(nbr_stack)
    state = _sa_group_init(
        nbr_dev, place(s0), place(keys),
        place(a0), place(b0), place(real_dev),
        rollout_steps=rollout, R_coef=R_coef, C_coef=C_coef,
    )
    loop_args = (
        jnp.asarray(np_dt(config.par_a)),
        jnp.asarray(np_dt(config.par_b)),
        jnp.asarray(np_dt(config.a_cap_frac * n)),
        jnp.asarray(np_dt(config.b_cap_frac * n)),
    )
    static = dict(rollout_steps=rollout, R_coef=R_coef, C_coef=C_coef,
                  max_steps=max_steps)
    return G_real, nbr_dev, state, loop_args, static


def lower_group_loop(
    graphs, preps, rep_seeds, config: SAConfig, *,
    dtype=jnp.float32, group_size: int | None = None,
    chunk_steps: int = 100_000,
):
    """Lower (without executing) the grouped SA loop program for these
    repetitions' shapes — the exact :func:`_sa_group_loop` invocation
    :func:`run_sa_group` dispatches, as a ``jax.stages.Lowered`` for
    :mod:`graphdyn.analysis.graftcheck` fingerprinting. Shares
    :func:`_assemble_group` with the run path, so the fingerprinted program
    is the executed program by construction."""
    _, nbr_dev, state, loop_args, static = _assemble_group(
        graphs, preps, rep_seeds, config,
        dtype=dtype, group_size=group_size, mesh=None, group_axis="group",
    )
    return _sa_group_loop.lower(
        nbr_dev, state, *loop_args, chunk_steps=int(chunk_steps), **static
    )


def run_sa_group(
    graphs,
    preps,
    rep_seeds,
    config: SAConfig,
    *,
    dtype=jnp.float32,
    group_size: int | None = None,
    chunk_steps: int = 100_000,
    on_chunk=None,
    mesh=None,
    group_axis: str = "group",
) -> SAGroupResult:
    """Run one group of single-replica SA chains as a single device program.

    ``graphs``/``preps``/``rep_seeds`` are per-repetition: the sampled
    graph, the :func:`graphdyn.models.sa.prepare_sa_inputs` tuple for
    ``n_replicas=1, seed=seed+k``, and ``seed+k`` itself. ``group_size``
    pads the batch with inactive rows so a short tail group reuses the full
    group's compiled program. ``on_chunk`` is polled between device chunks
    (the graceful-shutdown hook — it may raise). With a ``mesh``, the
    stacked tables and carry shard over ``group_axis`` (repetitions are
    independent, so the partitioned program is communication-free except
    the stop test); results are bit-identical to the unsharded program.
    """
    from graphdyn import obs

    G_real, nbr_dev, state, loop_args, static = _assemble_group(
        graphs, preps, rep_seeds, config,
        dtype=dtype, group_size=group_size, mesh=mesh, group_axis=group_axis,
    )
    rec = obs.current()
    chunk_i = 0
    while bool(jnp.any(state.active)):
        # per-chunk span: the first chunk pays the XLA compile (cold=True
        # separates it from steady-state execute time); when recording, the
        # chunk is fenced with a device sync so wall_s is execute time, not
        # dispatch time — with the null recorder no sync happens and the
        # loop's async dispatch behavior is untouched
        with rec.span("pipeline.sa.chunk", chunk=chunk_i,
                      cold=chunk_i == 0) as sp:
            state = _sa_group_loop(
                nbr_dev, state._replace(chunk_t=jnp.zeros((), jnp.int32)),
                *loop_args,
                chunk_steps=int(chunk_steps), **static,
            )
            if rec.enabled:
                jax.block_until_ready(state)
                sp.set(steps_advanced=int(state.chunk_t),
                       active=int(np.sum(np.asarray(state.active))))
        if rec.enabled:
            # device-memory gauges at the chunk boundary (obs.mem.*;
            # one explicit unavailable+reason gauge on stats-less backends)
            obs.memband.emit_memory_gauges(loop="sa.chunk", chunk=chunk_i)
        chunk_i += 1
        if on_chunk is not None:
            on_chunk()

    return SAGroupResult(
        s=np.asarray(state.s)[:G_real],
        num_steps=np.asarray(state.t)[:G_real],
        m_final=np.asarray(state.m_final)[:G_real],
    )


def sa_ensemble_grouped(
    n: int,
    d: int,
    config: SAConfig | None = None,
    *,
    n_stat: int = 5,
    seed: int = 0,
    graph_method: str = "pairing",
    max_steps: int | None = None,
    save_path: str | None = None,
    backend: str = "jax_tpu",
    checkpoint_path: str | None = None,
    checkpoint_interval_s: float = 30.0,
    group_size: int = 8,
    prefetch: int = 2,
    chunk_steps: int = 100_000,
    mesh=None,
    group_axis: str = "group",
):
    """The grouped SA experiment driver: ``n_stat`` repetitions on fresh
    RRG(n, d) instances, executed ``group_size`` at a time as one vmapped
    device program, with graph ``k+1..k+G`` built on a background thread
    while group ``k`` computes (``prefetch`` bounds the build-ahead depth;
    0 disables the thread). Element-wise identical to the serial
    :func:`graphdyn.models.sa.sa_ensemble` — see the module docstring for
    the identity and checkpoint/fault contracts."""
    from graphdyn.graphs import random_regular_graph
    from graphdyn.models.sa import SAEnsembleResult, prepare_sa_inputs
    from graphdyn.pipeline.groups import GroupDriver, group_ranges
    from graphdyn.pipeline.prefetch import HostPrefetcher
    from graphdyn.utils.io import save_results_npz

    config = config or SAConfig()
    mag = np.empty(n_stat, np.float64)  # graftlint: disable=GD004  host result buffer
    steps = np.empty(n_stat, np.int64)
    conf = np.empty((n_stat, n), np.int8)
    graphs = np.empty((n_stat, n, d), np.int32)
    m_final = np.empty(n_stat, np.float64)  # graftlint: disable=GD004  host result buffer

    def payload():
        return {"mag_reached": mag, "num_steps": steps,
                "conf": conf, "m_final": m_final}

    # identical identity metadata to the serial driver: snapshots are
    # interchangeable between the serial and grouped paths and between
    # group sizes (per-repetition results depend only on seed + k)
    run_id = {"seed": seed, "n_stat": n_stat, "n": n, "d": d,
              "max_steps": max_steps, "graph_method": graph_method,
              "config": repr(config), "backend": backend}
    drv = GroupDriver(checkpoint_path, checkpoint_interval_s, run_id, payload)
    start_k = drv.resume_into(payload())

    def build(k):
        g = random_regular_graph(n, d, seed=seed + k, method=graph_method)
        prep = prepare_sa_inputs(
            g, config, n_replicas=1, seed=seed + k, max_steps=max_steps
        )
        return g, prep

    from graphdyn import obs

    with HostPrefetcher(build, range(start_k, n_stat), depth=prefetch) as pf:
        for ks in group_ranges(start_k, n_stat, group_size):
            with obs.timed("pipeline.sa.group", reps=len(ks)) as sw:
                items = [pf.get(i) for i in ks]
                res = run_sa_group(
                    [it[0] for it in items], [it[1] for it in items],
                    [seed + i for i in ks], config,
                    group_size=group_size, chunk_steps=chunk_steps,
                    on_chunk=lambda k0=ks[0]: drv.chunk_poll(k0),
                    mesh=mesh, group_axis=group_axis,
                )
            if obs.enabled():
                # spin-updates/s through the driver — the same number
                # bench.py's ensemble_rate row reports (candidate rollouts
                # re-roll the full graph: n spins per accepted step)
                obs.gauge(
                    "ops.rollout.rate",
                    n * int(np.sum(res.num_steps)) / max(sw.wall_s, 1e-9),
                    solver="sa_group", reps=len(ks),
                )
            for j, i in enumerate(ks):
                conf[i] = res.s[j]
                # exact f64 sum, then the serial result's f32 cast — the
                # driver array holds the same widened-f32 value either way
                # graftlint: disable-next-line=GD004  host observable, exact sum
                mag[i] = np.float32(res.s[j].astype(np.float64).sum() / n)
                steps[i] = res.num_steps[j]
                m_final[i] = res.m_final[j]
                graphs[i] = items[j][0].nbr
                drv.rep_boundary(i)
    for k in range(start_k):    # resumed prefix: graphs re-derive from seed+k
        graphs[k] = random_regular_graph(
            n, d, seed=seed + k, method=graph_method
        ).nbr
    drv.finish()
    out = SAEnsembleResult(mag, steps, conf, graphs, m_final)
    if save_path:
        save_results_npz(
            save_path,
            mag_reached=out.mag_reached,
            num_steps=out.num_steps,
            conf=out.conf,
            graphs=out.graphs,
        )
    return out
