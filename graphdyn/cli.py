"""Config-driven experiment runner: ``python -m graphdyn <solver> [flags]``.

The reference's "config system" is hand-edited constant blocks at the top of
each script (`SA_RRG.py:44-56`, `HPR_pytorch_RRG.py:222-255`,
`ER_BDCM_entropy.ipynb:455-482` — SURVEY.md §5.6). Here the same parameter
surface is a CLI over the dataclass configs, running the matching experiment
driver and persisting reference-key npz results.

Examples::

    python -m graphdyn sa --n 10000 --d 4 --p 3 --c 1 --n-stat 5 --out mcmc.npz
    python -m graphdyn hpr --n 10000 --d 4 --n-rep 1 --out hpr_d4_p1.npz
    python -m graphdyn entropy --n 1000 --deg 1.0 1.5 2.0 --num-rep 3 --out er_p1.npz
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np

from graphdyn.config import DynamicsConfig, EntropyConfig, HPRConfig, SAConfig

from graphdyn.utils.platform import apply_force_platform

apply_force_platform()


def _add_dynamics_flags(ap: argparse.ArgumentParser, p_default: int = 1):
    ap.add_argument("--p", type=int, default=p_default, help="transient length")
    ap.add_argument("--c", type=int, default=1, help="cycle length")
    ap.add_argument("--rule", choices=["majority", "minority"], default="majority")
    ap.add_argument("--tie", choices=["stay", "change"], default="stay")
    ap.add_argument("--attr-value", type=int, choices=[1, -1], default=1)


def _dynamics(args) -> DynamicsConfig:
    return DynamicsConfig(
        p=args.p, c=args.c, rule=args.rule, tie=args.tie, attr_value=args.attr_value
    )


def _add_dtype_flag(ap, help_text: str) -> None:
    """The shared --dtype axis (one definition; float64 requires x64, which
    main() enables before building any config)."""
    ap.add_argument(
        "--dtype", choices=["float32", "float64"], default="float32",
        help=help_text,
    )


def _add_resilience_flags(ap: argparse.ArgumentParser) -> None:
    """The shared runtime-resilience knobs of the long-running commands
    (ARCHITECTURE.md "Resilience")."""
    ap.add_argument(
        "--max-save-retries", type=int, default=None, metavar="N",
        help="retry a failed checkpoint save up to N times (exponential "
             "backoff) before DEGRADING to skip-save with a logged warning "
             "— the run keeps computing either way (default: 2)",
    )


def _add_sa_schedule_flags(ap: argparse.ArgumentParser) -> None:
    """The reference SA annealing schedule (`SA_RRG.py:44-52`) — one
    definition shared by the serial search (``sa``), the tempering ladder
    (``temper``) and the chromatic sweeps (``chromatic``)."""
    ap.add_argument("--a0-frac", type=float, default=0.015)
    ap.add_argument("--b0-frac", type=float, default=0.010)
    ap.add_argument("--par-a", type=float, default=1.0005)
    ap.add_argument("--par-b", type=float, default=1.0005)
    ap.add_argument("--a-cap-frac", type=float, default=4.5)
    ap.add_argument("--b-cap-frac", type=float, default=5.0)


def _sa_config(args) -> SAConfig:
    return SAConfig(
        dynamics=_dynamics(args),
        a0_frac=args.a0_frac, b0_frac=args.b0_frac,
        par_a=args.par_a, par_b=args.par_b,
        a_cap_frac=args.a_cap_frac, b_cap_frac=args.b_cap_frac,
    )


def _add_pipeline_flags(ap: argparse.ArgumentParser) -> None:
    """The shared ensemble-pipeline knobs (ARCHITECTURE.md "Ensemble
    pipeline")."""
    ap.add_argument(
        "--group-size", type=int, default=None, metavar="G",
        help="run G repetitions at a time as ONE batched device program "
             "(element-wise identical to the serial loop; default: auto, "
             "min(reps, 8); 0 forces the legacy serial repetition loop)",
    )
    ap.add_argument(
        "--prefetch", type=int, default=2, metavar="D",
        help="build up to D upcoming graphs on a background thread while "
             "the current group computes (deterministic; 0 disables)",
    )


def _add_kernel_flag(ap: argparse.ArgumentParser) -> None:
    """The shared sweep-core axis of the BDCM-backed commands
    (ARCHITECTURE.md "Kernel selection")."""
    ap.add_argument(
        "--kernel", choices=["auto", "xla", "pallas"], default="auto",
        help="BDCM sweep core: 'auto' fuses qualifying degree classes into "
             "the grouped Pallas DP+contraction kernel on TPU backends "
             "(group axis as a Pallas grid dimension); 'xla' forces the "
             "pure-XLA sweep; 'pallas' forces the kernel (interpret mode "
             "off-TPU — for tests, not a throughput mode). Pallas-vs-XLA "
             "is an approximate mode (~1e-3 max rel err, PALLAS_TPU.json); "
             "grouped and serial paths stay bit-identical WITHIN a mode",
    )


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="graphdyn",
        # abbreviation OFF at the top level: the subcommands' exact "--c"
        # (cycle length / ER mean degree) would otherwise be *classified*
        # as an ambiguous abbreviation of --ckpt-mirror/--ckpt-keep/
        # --compile-cache during the main parser's argv scan, before the
        # subparser ever sees it (subparsers keep their own abbreviation)
        allow_abbrev=False,
        epilog="Exit codes: 0 success; 75 (EX_TEMPFAIL) graceful preemption "
               "shutdown — SIGTERM/SIGINT, a --deadline expiry, or the "
               "--stall-timeout watchdog checkpointed at the next chunk "
               "boundary, safe for a scheduler to requeue; 130 hard abort — "
               "a SECOND signal during the grace window (the operator "
               "asking twice outranks the checkpoint: nothing is written) "
               "or a wedged run the watchdog gave up on; 86 a supervised "
               "run quarantined after a crash loop (run-supervised; do NOT "
               "requeue); anything else is a real failure. See "
               "ARCHITECTURE.md 'Resilience' + 'Supervised execution'. "
               "Search modes: `sa` is the reference serial chain, `temper` "
               "runs a replica-exchange ladder on the batched replica axis "
               "(lane-shardable, swap moves at chunk boundaries), "
               "`chromatic` updates a whole color class per device step, "
               "`fused` is the one-kernel annealer (LUT update + "
               "counter RNG + schedule in ONE device program, "
               "--kernel auto|xla|pallas) — which modes compose with node "
               "sharding and lightcone is the mode-selection table in "
               "ARCHITECTURE.md 'Node-axis sharding & halo exchange' / "
               "'Search acceleration' / 'One-kernel annealing'. "
               "`serve` runs the multi-tenant job service over a durable "
               "filesystem spool (submit/status/result need no live "
               "server; a restarted server recovers its queue from disk; "
               "oversized jobs are refused by the committed byte models; "
               "overstaying jobs are checkpoint-evicted and requeued; "
               "crash-looping tenant jobs are quarantined) — "
               "ARCHITECTURE.md 'Serving'.",
    )
    ap.add_argument(
        "--ckpt-mirror", default=None, metavar="DIR",
        help="replicate every checkpoint save into a second directory "
             "(write-behind — the hot path pays one extra atomic rename); "
             "when the primary checkpoint directory is unreadable or fails "
             "checksum verification, resume fails over to the mirror. Also "
             "honored from the GRAPHDYN_CKPT_MIRROR environment variable "
             "(this flag wins). ARCHITECTURE.md 'Durable checkpoint store'",
    )
    ap.add_argument(
        "--ckpt-keep", type=int, default=None, metavar="K",
        help="retain the last K checkpoint versions (<ckpt>.v<N>.npz) next "
             "to the published snapshot, so a torn write or silent bit rot "
             "falls back to the newest verifiable version instead of "
             "restarting the run (default: 2; also honored from "
             "GRAPHDYN_CKPT_KEEP, this flag wins)",
    )
    ap.add_argument(
        "--stall-timeout", type=float, default=None, metavar="SECS",
        help="liveness watchdog: when no chunk/rep/lambda boundary "
             "heartbeat arrives for SECS, request a graceful shutdown "
             "(snapshot at the next boundary, exit 75); a run that stays "
             "wedged past the grace window is hard-aborted (exit 130) with "
             "a flight-recorder post-mortem naming the stalled boundary. "
             "Also honored from GRAPHDYN_STALL_TIMEOUT (this flag wins). "
             "ARCHITECTURE.md 'Supervised execution'",
    )
    ap.add_argument(
        "--deadline", type=float, default=None, metavar="SECS",
        help="run time budget: after SECS, take the same graceful "
             "snapshot + exit-75 path a SIGTERM takes — preemption "
             "semantics on a timer, so a resumed/requeued run continues "
             "from the snapshot. Also honored from GRAPHDYN_DEADLINE "
             "(this flag wins)",
    )
    ap.add_argument(
        "--compile-cache", default=None, metavar="DIR",
        help="persistent XLA compile cache directory "
             "(jax_compilation_cache_dir): re-runs and resumed jobs skip "
             "the multi-second compile; also honored from the "
             "GRAPHDYN_COMPILE_CACHE environment variable (this flag wins)",
    )
    ap.add_argument(
        "--obs-ledger", default=None, metavar="PATH",
        help="write a structured-telemetry event ledger (append-only "
             "JSONL: run manifest, nested spans, counters, gauges — "
             "ARCHITECTURE.md 'Runtime telemetry') for this run; also "
             "honored from the GRAPHDYN_OBS environment variable (this "
             "flag wins). Render with `python -m graphdyn.obs report PATH`",
    )
    ap.add_argument(
        "--profile", default=None, metavar="DIR",
        help="capture a jax.profiler device trace of the run into DIR "
             "(TensorBoard profile tab / Perfetto); while profiling, every "
             "obs span also opens a TraceAnnotation named with its ledger "
             "name-path, so the device timeline and --obs-ledger share one "
             "vocabulary (ARCHITECTURE.md 'Runtime telemetry'); also "
             "honored from the GRAPHDYN_PROFILE environment variable "
             "(this flag wins)",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    sa = sub.add_parser("sa", help="SA initialization search (`SA_RRG.py`)")
    sa.add_argument("--n", type=int, default=10_000)
    sa.add_argument("--d", type=int, default=4)
    _add_dynamics_flags(sa, p_default=3)
    _add_sa_schedule_flags(sa)
    sa.add_argument("--n-stat", type=int, default=5)
    sa.add_argument("--max-steps", type=int, default=None)
    sa.add_argument("--seed", type=int, default=0)
    sa.add_argument("--backend", default="jax_tpu")
    sa.add_argument("--out", default=None, help="npz path (`SA_RRG.py:92` keys)")
    sa.add_argument(
        "--checkpoint", default=None,
        help="path prefix for preemption-safe exact resume (driver + chain); "
             "SIGTERM then checkpoints at the next chunk boundary and exits "
             "75 (EX_TEMPFAIL)",
    )
    sa.add_argument("--checkpoint-interval", type=float, default=30.0)
    _add_resilience_flags(sa)
    _add_pipeline_flags(sa)
    sa.add_argument(
        "--rollout-mode", choices=["full", "lightcone"], default="full",
        help="candidate evaluation: full graph re-roll (reference cost "
             "structure) or O(ball) light-cone roll vs a cached trajectory "
             "(bit-identical chains). lightcone keeps whole replicas — and "
             "tempering lanes, which ride the same replica axis — per "
             "device, so it excludes --shards node partitioning; see the "
             "mode-selection table in ARCHITECTURE.md 'Node-axis sharding "
             "& halo exchange' and 'Search acceleration' for which "
             "search/sharding modes compose",
    )
    sa.add_argument(
        "--sharded", action="store_true",
        help="run the multi-chip solver (replica x node mesh over all "
             "visible devices) instead of the per-repetition driver",
    )
    sa.add_argument(
        "--n-replicas", type=int, default=32,
        help="replica count for --sharded; with --ladder the per-replica a0 "
             "spans [a0-frac, ladder-max-frac] linearly across replicas",
    )
    sa.add_argument(
        "--chunk-steps", type=int, default=100_000, metavar="K",
        help="with --sharded and --checkpoint: advance at most K MCMC "
             "steps per device call — the resume granularity (snapshots "
             "and shutdown/heartbeat polls happen at chunk boundaries; "
             "splitting the loop cannot change the chain)",
    )
    sa.add_argument(
        "--shards", type=int, default=None, metavar="P",
        help="with --sharded: partition the graph's NODE axis into P parts "
             "(graphs.partition_graph: BFS-grow + boundary refinement) and "
             "run the halo-exchange solver — each device owns one part and "
             "per-step collective traffic is the partition's boundary "
             "spin words, not the full state (parallel/halo.py; bit-exact "
             "to the unsharded chains; P=1 keeps the single-shard node "
             "axis). Snapshots stay global, so a run may resume under a "
             "different --shards after a shard loss",
    )
    sa.add_argument(
        "--ladder-max-frac", type=float, default=None,
        help="enable a temperature ladder on the replica axis: per-replica "
             "a0 = linspace(a0-frac, this, n-replicas) * n (no swap moves "
             "— for replica exchange use `graphdyn temper`)",
    )
    sa.add_argument(
        "--layout", choices=["auto", "padded", "bucketed", "streamed"],
        default="auto",
        help="node layout of the per-repetition driver (models/sa.py): "
             "auto routes high-degree-CV graphs bucket-major; streamed "
             "evaluates every candidate end-sum through the out-of-core "
             "chunked rollout (ops/streamed) — the route when padded "
             "tables exceed the device budget; non-padded layouts run "
             "the serial repetition loop",
    )
    sa.add_argument(
        "--stream-chunks", type=int, default=4, metavar="K",
        help="with --layout streamed: host-resident chunk count of the "
             "stream plan (two chunks device-resident at a time)",
    )

    strm = sub.add_parser(
        "stream",
        help="out-of-core streamed rollout: dynamics on a graph larger "
             "than the device budget, with double-buffered host→device "
             "chunk gathers and optional live edge churn (ops/streamed; "
             "ARCHITECTURE.md 'Out-of-core streaming & edge churn')",
    )
    strm.add_argument("--n", type=int, default=4096)
    strm.add_argument(
        "--gamma", type=float, default=2.5,
        help="power-law degree exponent of the generated graph",
    )
    strm.add_argument("--dmin", type=int, default=2,
                      help="power-law minimum degree")
    strm.add_argument("--graph-seed", type=int, default=0)
    strm.add_argument("--rule", choices=["majority", "minority"],
                      default="majority")
    strm.add_argument("--tie", choices=["stay", "change"], default="stay")
    strm.add_argument("--steps", type=int, default=32,
                      help="synchronous update steps")
    strm.add_argument("--replicas", type=int, default=32,
                      help="bit-packed replica count (32 per uint32 word)")
    strm.add_argument("--seed", type=int, default=0,
                      help="initial-spin seed (also the run identity seed)")
    strm.add_argument(
        "--chunks", type=int, default=4, metavar="K",
        help="host-resident chunk count (ignored when --device-budget is "
             "given)",
    )
    strm.add_argument(
        "--device-budget", type=int, default=None, metavar="BYTES",
        help="pack chunks greedily so two fit in BYTES (the double-buffer "
             "peak) instead of a fixed --chunks count",
    )
    strm.add_argument(
        "--prefetch-depth", type=int, default=2, metavar="D",
        help="host-prefetch lookahead; 0 forces synchronous gathers (the "
             "overlap A/B baseline)",
    )
    strm.add_argument(
        "--shards", type=int, default=1, metavar="P",
        help="shard the chunk walk over P devices (parallel/stream): each "
             "shard owns a part-major chunk run and streams it on its own "
             "prefetch lane; boundary words + hub partials ride the halo "
             "ppermute/ring schedule; --chunks / --device-budget apply PER "
             "SHARD (bit-exact to --shards 1 at any P)",
    )
    strm.add_argument(
        "--hub-threshold", type=int, default=None, metavar="D",
        help="with --shards >= 2: vertex-cut replicate nodes of degree >= "
             "D, and let churn re-partition live (a churned node crossing "
             "D is promoted to a hub at the chunk boundary, journaled as "
             "stream.repartition)",
    )
    strm.add_argument(
        "--churn-rate", type=float, default=0.0, metavar="R",
        help="live edge churn: Poisson(R/2) adds + drops per step, applied "
             "at chunk boundaries with incremental table rebuild "
             "(seeded_churn — pure in (--n, --steps, R, --churn-seed))",
    )
    strm.add_argument("--churn-seed", type=int, default=0)
    strm.add_argument(
        "--checkpoint", default=None,
        help="path prefix for preemption-safe exact resume; applied churn "
             "is journaled (stream.churn) so a requeued run replays the "
             "past bit-exactly from the journal alone; SIGTERM "
             "checkpoints at the next chunk boundary and exits 75 "
             "(EX_TEMPFAIL)",
    )
    strm.add_argument("--checkpoint-interval", type=float, default=30.0)
    _add_resilience_flags(strm)
    strm.add_argument("--out", default=None,
                      help="npz path (conf int8[R, n] + per-replica m_end)")

    tmp = sub.add_parser(
        "temper",
        help="replica-exchange (parallel tempering) SA search: K lanes on "
             "the batched replica axis anneal in lockstep with seeded "
             "even/odd swap moves at chunk boundaries "
             "(graphdyn.search.tempering; ARCHITECTURE.md 'Search "
             "acceleration')",
    )
    tmp.add_argument("--n", type=int, default=10_000)
    tmp.add_argument("--d", type=int, default=3)
    _add_dynamics_flags(tmp, p_default=1)
    _add_sa_schedule_flags(tmp)
    tmp.add_argument(
        "--lanes", type=int, default=8,
        help="temperature-ladder lanes K (one batched device program)",
    )
    tmp.add_argument(
        "--beta-min", type=float, default=1.0,
        help="drive ladder lower rung: lane k scales (b0, b-cap) by "
             "beta_k in geomspace(beta-min, beta-max, lanes); beta=1 is "
             "the reference chain",
    )
    tmp.add_argument("--beta-max", type=float, default=64.0)
    tmp.add_argument(
        "--swap-interval", type=int, default=1000, metavar="K",
        help="MCMC steps between swap moves — also the chunk/snapshot/"
             "heartbeat granularity; part of the chain law (rides in the "
             "checkpoint fingerprint)",
    )
    tmp.add_argument(
        "--no-swaps", action="store_true",
        help="disable swap moves (a plain batched ladder — bit-identical "
             "to `sa`'s replica ladder at the same a0/b0)",
    )
    tmp.add_argument(
        "--m-target", type=float, default=1.0,
        help="first-passage record: the step a lane's rolled-out end-state "
             "magnetization first reaches this (1.0 = consensus)",
    )
    tmp.add_argument(
        "--stop-on-first", action="store_true",
        help="stop the whole ladder at the first lane reaching --m-target "
             "(the time-to-target mode the tta_tempering bench row uses)",
    )
    tmp.add_argument("--max-steps", type=int, default=None)
    tmp.add_argument("--seed", type=int, default=0)
    tmp.add_argument(
        "--lane-shards", type=int, default=None, metavar="P",
        help="shard the K lanes over P devices (lane axis via shard_stack; "
             "bit-identical to unsharded). Snapshots are GLOBAL, so a "
             "preempted ladder may requeue under a different P after a "
             "device loss",
    )
    tmp.add_argument(
        "--checkpoint", default=None,
        help="path prefix for chunk-granular durable snapshots (swap "
             "boundaries; PR-9 store + run journal); SIGTERM checkpoints "
             "at the next boundary and exits 75 (EX_TEMPFAIL)",
    )
    tmp.add_argument("--checkpoint-interval", type=float, default=30.0)
    _add_resilience_flags(tmp)
    tmp.add_argument("--out", default=None, help="npz path (per-lane arrays)")

    chrom = sub.add_parser(
        "chromatic",
        help="chromatic block-sweep annealing: a distance-2 coloring "
             "partitions the graph into chi classes and each device step "
             "proposes/accepts a whole independent set — O(chi) device "
             "steps per sweep instead of n (graphdyn.search.chromatic; "
             "p=c=1 only)",
    )
    chrom.add_argument("--n", type=int, default=10_000)
    chrom.add_argument("--d", type=int, default=3)
    _add_dynamics_flags(chrom, p_default=1)
    _add_sa_schedule_flags(chrom)
    chrom.add_argument("--replicas", type=int, default=32,
                       help="independent packed chains (32 per uint32 word)")
    chrom.add_argument("--m-target", type=float, default=0.9)
    chrom.add_argument("--max-sweeps", type=int, default=5000)
    chrom.add_argument(
        "--chunk-sweeps", type=int, default=64, metavar="S",
        help="full sweeps per device call (the freeze/stop-poll and "
             "heartbeat granularity)",
    )
    chrom.add_argument("--stop-on-first", action="store_true")
    chrom.add_argument("--seed", type=int, default=0)
    chrom.add_argument("--out", default=None,
                       help="npz path (per-replica arrays)")

    fus = sub.add_parser(
        "fused",
        help="one-kernel annealing: the chromatic class-at-a-time chain "
             "with the rule compiled to a popcount LUT, counter-based "
             "in-kernel RNG, and the anneal schedule advanced inside ONE "
             "device program — a fixed-budget run performs zero host "
             "round-trips between snapshot boundaries "
             "(graphdyn.search.fused; ARCHITECTURE.md 'One-kernel "
             "annealing'; p=c=1 only)",
    )
    fus.add_argument("--n", type=int, default=10_000)
    fus.add_argument("--d", type=int, default=3)
    _add_dynamics_flags(fus, p_default=1)
    _add_sa_schedule_flags(fus)
    fus.add_argument("--replicas", type=int, default=32,
                     help="independent packed chains (32 per uint32 word)")
    fus.add_argument("--m-target", type=float, default=0.9)
    fus.add_argument("--max-sweeps", type=int, default=5000)
    fus.add_argument(
        "--chunk-sweeps", type=int, default=256, metavar="S",
        help="full sweeps per device call — the heartbeat/shutdown "
             "granularity ONLY (the chunk plan is host-side; no device "
             "readback between chunks, and the counter RNG makes splits "
             "chain-invariant)",
    )
    fus.add_argument("--stop-on-first", action="store_true",
                     help="stop at the first replica reaching --m-target "
                          "(adds the sanctioned per-chunk stop test)")
    fus.add_argument(
        "--kernel", choices=["auto", "xla", "pallas"], default="auto",
        help="fused-annealer engine: 'auto' runs the single-pallas_call "
             "kernel on TPU backends when the VMEM model admits the "
             "shape, else the XLA twin; 'pallas' forces the kernel "
             "(interpret mode off-TPU — for tests); 'xla' forces the "
             "twin. Both engines run the SAME chain bit-for-bit (tested) "
             "— the knob moves throughput, never results",
    )
    fus.add_argument(
        "--ladder-beta-max", type=float, default=None, metavar="B",
        help="per-replica drive ladder riding the packed replica axis: "
             "replica r scales (b0, b-cap) by geomspace(1, B, replicas)[r] "
             "(no swap moves — for replica exchange use `graphdyn temper`)",
    )
    fus.add_argument("--seed", type=int, default=0)
    fus.add_argument("--out", default=None,
                     help="npz path (per-replica arrays)")

    hpr = sub.add_parser("hpr", help="HPr reinforced BP (`HPR_pytorch_RRG.py`)")
    hpr.add_argument("--n", type=int, default=10_000)
    hpr.add_argument("--d", type=int, default=4)
    _add_dynamics_flags(hpr)
    hpr.add_argument("--damp", type=float, default=0.4)
    hpr.add_argument("--lmbd", type=float, default=25.0)
    hpr.add_argument("--pie", type=float, default=0.3)
    hpr.add_argument("--gamma", type=float, default=0.1)
    hpr.add_argument("--max-sweeps", type=int, default=10_000)
    hpr.add_argument("--n-rep", type=int, default=1)
    hpr.add_argument("--seed", type=int, default=0)
    hpr.add_argument("--out", default=None, help="npz path (`HPR:377` keys)")
    hpr.add_argument(
        "--checkpoint", default=None,
        help="path prefix for preemption-safe exact resume (driver + chain); "
             "SIGTERM then checkpoints at the next chunk boundary and exits "
             "75 (EX_TEMPFAIL)",
    )
    hpr.add_argument("--checkpoint-interval", type=float, default=30.0)
    _add_resilience_flags(hpr)
    _add_pipeline_flags(hpr)
    _add_kernel_flag(hpr)
    _add_dtype_flag(hpr, "float64 matches the reference's solver precision "
                          "(`HPR_pytorch_RRG.py:11`; enables x64)")
    hpr.add_argument(
        "--batch-replicas", type=int, default=0, metavar="R",
        help="run R independent chains on ONE graph as a single batched "
             "device program (hpr_solve_batch) instead of --n-rep "
             "fresh-graph repetitions",
    )
    hpr.add_argument(
        "--device-init", action="store_true",
        help="with --batch-replicas: build union tables and the initial "
             "state on device (nothing union-sized crosses the host link; "
             "incompatible with --checkpoint)",
    )

    cons = sub.add_parser(
        "consensus",
        help="forward opinion-consensus m(0) sweep (the phenomenon the "
             "entropy curves quantify — `ER_BDCM_entropy.ipynb:113-123`)",
    )
    cons.add_argument("--n", type=int, default=100_000)
    cons.add_argument(
        "--graph", choices=["er", "rrg"], default="er",
        help="ensemble: ER G(n, c/n) (config-3) or random d-regular "
             "(the SA search's ensemble — random-init threshold there is "
             "~10x the SA-constructed m(0), see rrg_threshold_r05.json)",
    )
    cons.add_argument("--c", type=float, default=6.0, help="ER mean degree")
    cons.add_argument("--d", type=int, default=4, help="RRG degree")
    cons.add_argument("--rule", choices=["majority", "minority"],
                      default="majority")
    cons.add_argument("--tie", choices=["stay", "change"], default="stay")
    cons.add_argument("--replicas", type=int, default=512)
    cons.add_argument(
        "--m0", type=float, nargs="+",
        default=[0.0, 0.01, 0.02, 0.03, 0.05, 0.07, 0.1, 0.15, 0.2, 0.3],
        help="initial-magnetization grid",
    )
    cons.add_argument("--max-steps", type=int, default=2000)
    cons.add_argument(
        "--chunk", type=int, default=10,
        help="steps per consensus check (= first-passage resolution)",
    )
    cons.add_argument(
        "--near-eps", type=float, default=0.01,
        help="near-consensus threshold: |m_final| >= 1 - near_eps",
    )
    cons.add_argument("--seed", type=int, default=0, help="graph seed")
    cons.add_argument(
        "--sharded", action="store_true",
        help="shard the packed word axis over all visible devices (zero "
             "per-step collectives; bit-identical to unsharded)",
    )
    cons.add_argument("--out", default=None, help="json path for the curve")
    cons.add_argument(
        "--plot", default=None, metavar="PNG",
        help="render consensus fraction + first-passage vs m(0)",
    )

    ent = sub.add_parser("entropy", help="BDCM entropy λ-sweep (notebook)")
    ent.add_argument("--n", type=int, default=1000)
    ent.add_argument("--deg", type=float, nargs="+", default=[1.0, 1.5, 2.0])
    _add_dynamics_flags(ent)
    ent.add_argument("--lmbd-max", type=float, default=12.0)
    ent.add_argument("--lmbd-step", type=float, default=0.1)
    ent.add_argument("--eps", type=float, default=1e-6)
    ent.add_argument("--damp", type=float, default=0.1)
    ent.add_argument("--max-sweeps", type=int, default=1300)
    ent.add_argument("--ent-floor", type=float, default=-0.05)
    ent.add_argument(
        "--plateau-eps", type=float, default=0.0,
        help="stop the ladder when (m_init, ent1) move less than this for "
        "--plateau-patience consecutive lambda (0 = off, reference behavior; "
        "useful at p+c>=3 where the curve floors at positive ent1)")
    ent.add_argument("--plateau-patience", type=int, default=3)
    ent.add_argument("--num-rep", type=int, default=3)
    ent.add_argument("--seed", type=int, default=0)
    ent.add_argument("--verbose", action="store_true")
    ent.add_argument("--out", default=None, help="npz path (`ipynb:515` keys)")
    ent.add_argument(
        "--checkpoint", default=None,
        help="path prefix for time-triggered saves + exact λ-granular "
             "resume; SIGTERM then checkpoints at the next λ and exits 75 "
             "(EX_TEMPFAIL)",
    )
    ent.add_argument("--checkpoint-interval", type=float, default=30.0)
    _add_resilience_flags(ent)
    ent.add_argument(
        "--group-size", type=int, default=None, metavar="G",
        help="advance G grid cells' λ-ladders at a time as ONE batched "
             "device program over stacked ragged BDCM tables (element-wise "
             "identical to the serial cell loop; default: auto, "
             "min(cells, 8); 0 forces the legacy serial cell loop)",
    )
    ent.add_argument(
        "--prefetch", type=int, default=2, metavar="D",
        help="build up to D upcoming grid cells' ER graphs + BDCM tables "
             "on a background thread while the current cells sweep "
             "(deterministic; 0 disables)",
    )
    _add_kernel_flag(ent)
    _add_dtype_flag(ent, "float64 matches the reference's precision "
                          "(enables x64)")
    ent.add_argument(
        "--plot", default=None, metavar="PNG",
        help="render the s(m_init) curve family (one per degree) to this file",
    )
    ent.add_argument(
        "--union", type=int, default=None, metavar="G",
        help="instead of the deg x rep grid, run each degree as ONE "
             "disjoint-union device program over G ER instances "
             "(entropy_ensemble_union — per-member phi/m_init via segment "
             "sums); npz keys gain a member axis",
    )

    srv = sub.add_parser(
        "serve",
        help="the multi-tenant job service over a durable filesystem "
             "spool (graphdyn.serve): run a worker, or submit/inspect "
             "jobs — submissions need no live server, and a restarted "
             "server recovers its queue from disk alone",
    )
    srv.add_argument(
        "action", choices=["run", "submit", "status", "result", "queue"],
        help="run: serve the spool (admission by committed byte models, "
             "shape-class bucketing with AOT warm-up, per-job "
             "timeout→evict→requeue, per-tenant crash quarantine); "
             "submit: durably enqueue a job; status/result: one job's "
             "record / finished arrays; queue: counts per state",
    )
    srv.add_argument("job", nargs="?", default=None,
                     help="job id (status/result) — give it immediately "
                          "after the action (argparse does not backfill "
                          "a trailing positional past options)")
    srv.add_argument("--root", required=True, metavar="DIR",
                     help="spool directory (created if missing)")
    srv.add_argument("--tenant", default="default",
                     help="tenant name stamped on submissions (quarantine "
                          "and crash containment are keyed per tenant)")
    srv.add_argument("--job-timeout", type=float, default=None, metavar="S",
                     help="per-job deadline (submit: this job; run: "
                          "default for jobs without one) — overstaying "
                          "jobs are checkpoint-evicted and requeued with "
                          "a 4x-escalated slice")
    srv.add_argument("--max-jobs", type=int, default=None, metavar="N",
                     help="run: exit 0 after settling N jobs")
    srv.add_argument("--idle-exit", type=float, default=None, metavar="S",
                     help="run: exit 0 after S seconds with an empty "
                          "queue (default: serve forever)")
    srv.add_argument("--no-warm", action="store_true",
                     help="run: skip boot-time AOT warm-up of hot shape "
                          "classes")
    for flag, typ, hlp in (
            ("--n", int, "graph size"), ("--d", int, "degree"),
            ("--graph-seed", int, "graph realization seed"),
            ("--seed", int, "chain seed"),
            ("--rule", str, "dynamics rule (majority|minority)"),
            ("--tie", str, "tie-break (stay|random)"),
            ("--replicas", int, "replica count (packed 32/word)"),
            ("--m-target", float, "target magnetization"),
            ("--max-sweeps", int, "sweep budget"),
            ("--chunk-sweeps", int, "sweeps per device chunk"),
            ("--solver", str, "engine: fused (annealer on an RRG), "
             "bucketed (degree-bucketed rollout on a power-law graph, "
             "priced edge-proportionally), or streamed (out-of-core "
             "chunked rollout, priced per chunk — runs shapes the "
             "resident engines refuse)"),
            ("--edges", int, "declared edge count (required for "
             "--solver bucketed/streamed: prices admission by the "
             "edge-proportional/per-chunk byte model; worker-validated "
             "against the built graph)"),
            ("--dmax", int, "declared worst hub degree (--solver "
             "streamed: the single-node-chunk feasibility floor; "
             "worker-validated against the built graph)"),
            ("--gamma", float, "power-law exponent of the served graph "
             "(--solver bucketed/streamed; --d is dmin)"),
            ("--degree-cv", float, "declared degree coefficient of "
             "variation (informational; does not affect admission)")):
        srv.add_argument(flag, type=typ, default=None,
                         help=f"submit: {hlp} (default: spool default)")

    sup = sub.add_parser(
        "run-supervised",
        help="wrap a graphdyn command under the resilience supervisor "
             "(python -m graphdyn.resilience.supervisor): heartbeat "
             "watchdog, per-episode deadline, bounded auto-restart with "
             "crash-loop quarantine — see that module's --help for the "
             "policy flags",
    )
    sup.add_argument(
        "command", nargs=argparse.REMAINDER,
        help="supervisor flags, then the command to supervise "
             "(conventionally after '--'): graphdyn run-supervised "
             "--stall-timeout 300 -- sa --n 100000 --checkpoint ck/run",
    )

    return ap


def main(argv=None) -> int:
    """Parse flags and run the matching experiment driver under the
    graceful-shutdown protocol: SIGTERM/SIGINT checkpoints at the next
    chunk/rep/λ boundary (when ``--checkpoint`` is set) and exits
    ``EX_TEMPFAIL`` (75) so schedulers can requeue a preempted run instead
    of marking it failed."""
    from graphdyn.resilience import (
        EX_ABORT, EX_TEMPFAIL, ShutdownRequested, graceful_shutdown,
        set_save_retry,
    )

    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "run-supervised":
        # delegate to the supervisor's own entry point BEFORE any parsing
        # or run machinery (signal scope, recorder, watchdog): the
        # supervisor is the parent of runs, never inside one — and
        # argparse's REMAINDER cannot carry the supervisor's own leading
        # flags, so the handoff happens on raw argv
        from graphdyn.resilience.supervisor import main as supervisor_main

        cmd = argv[1:]
        if cmd and cmd[0] == "--":
            cmd = cmd[1:]
        return supervisor_main(cmd)

    args = build_parser().parse_args(argv)

    if args.cmd == "run-supervised":
        # the registered-subparser path: top-level flags preceded the
        # subcommand, so they were parsed HERE — forward them instead of
        # silently dropping them (a dropped --stall-timeout would run the
        # child with no watchdog: the exact silent-liveness gap this
        # subsystem exists to close). Watchdog knobs go to the supervisor,
        # the other top-level flags back onto the child command line.
        from graphdyn.resilience.supervisor import main as supervisor_main

        cmd = list(args.command)
        if cmd and cmd[0] == "--":
            cmd = cmd[1:]
        sup_flags: list = []
        if args.stall_timeout is not None:
            sup_flags += ["--stall-timeout", str(args.stall_timeout)]
        if args.deadline is not None:
            sup_flags += ["--deadline", str(args.deadline)]
        child_pre: list = []
        for flag, val in (("--ckpt-mirror", args.ckpt_mirror),
                          ("--ckpt-keep", args.ckpt_keep),
                          ("--compile-cache", args.compile_cache),
                          ("--obs-ledger", args.obs_ledger),
                          ("--profile", args.profile)):
            if val is not None:
                child_pre += [flag, str(val)]
        return supervisor_main(sup_flags + ["--"] + child_pre + cmd)

    # opt-in persistent compile cache (flag wins over the env variable);
    # must apply before anything traces
    from graphdyn.utils.platform import apply_compile_cache

    apply_compile_cache(args.compile_cache)

    if getattr(args, "dtype", None) == "float64":
        import jax

        jax.config.update("jax_enable_x64", True)
    if getattr(args, "max_save_retries", None) is not None:
        set_save_retry(args.max_save_retries)

    # durable-store knobs (flag wins over env; set BOTH every run so one
    # in-process invocation cannot leak its mirror into the next — the soak
    # harness re-enters main() dozens of times per process)
    import os as _os

    from graphdyn.resilience.store import _env_keep, configure_store

    # _env_keep is the ONE parser of GRAPHDYN_CKPT_KEEP (tolerates garbage
    # by falling back to the default — a typo'd env var must not crash an
    # otherwise-valid run before it starts)
    configure_store(
        mirror=args.ckpt_mirror or _os.environ.get("GRAPHDYN_CKPT_MIRROR")
        or None,
        keep=args.ckpt_keep if args.ckpt_keep is not None else _env_keep(),
    )

    # GRAPHDYN_RACECHECK=1: wrap the inventoried module locks in the
    # graftrace runtime proxy (graphdyn.analysis.racecheck) BEFORE any
    # driver thread spawns — per-thread acquisition sequences land in the
    # flight ring (a post-mortem names the lock a wedged run died waiting
    # on), the observed lock order is asserted against the committed
    # CONCURRENCY_LEDGER.json, and GRAPHDYN_RACEFUZZ=<seed> adds the
    # deterministic schedule jitter. Off (the default) costs exactly this
    # env check — the module is not even imported and the locks stay
    # plain threading objects.
    if _os.environ.get("GRAPHDYN_RACECHECK") == "1":
        from graphdyn.analysis.racecheck import maybe_install

        maybe_install()

    # GRAPHDYN_SANITIZE=alias: run the whole driver under the host-aliasing
    # sanitizer (graphdyn.analysis.sanitize) — a mutated host buffer whose
    # device alias is still alive becomes a deterministic AliasRaceError
    # naming the crossing, instead of nondeterministic results
    from graphdyn.analysis.sanitize import maybe_alias_sanitizer

    from graphdyn import obs
    from graphdyn.obs import flight, trace

    # supervised-execution knobs (flag wins over env): the watchdog thread
    # exists only when one of them is set — an unsupervised run pays only
    # the per-boundary heartbeat gauge
    from graphdyn.resilience.supervisor import env_float, supervision

    stall_timeout = (args.stall_timeout if args.stall_timeout is not None
                     else env_float("GRAPHDYN_STALL_TIMEOUT"))
    deadline = (args.deadline if args.deadline is not None
                else env_float("GRAPHDYN_DEADLINE"))

    try:
        with graceful_shutdown(), maybe_alias_sanitizer(), \
                obs.recording(args.obs_ledger) as rec, \
                trace.profiling(args.profile), \
                supervision(stall_timeout, deadline):
            if rec.enabled:
                # the per-run manifest event: everything needed to read
                # the rest of the ledger offline (backend, jax version,
                # git sha, the full parsed config)
                rec.manifest(**obs.run_manifest_fields(
                    cmd=args.cmd, argv=list(argv) if argv is not None
                    else sys.argv[1:],
                    config={k: v for k, v in sorted(vars(args).items())},
                ))
            # the dump sites live INSIDE the recording scope so flight.dump
            # can route the evidence: live ledger -> obs.crash event lands
            # there; no ledger -> obs_postmortem.jsonl in the workdir
            try:
                with rec.span("run", cmd=args.cmd):
                    return _run(args)
            except ShutdownRequested as e:
                flight.dump("preempt", exc=e, site=e.where)
                raise
            except KeyboardInterrupt as e:
                # the second-signal hard abort (graceful_shutdown): the
                # operator asking twice outranks the checkpoint — nothing
                # is saved, but the flight ring still names where the run
                # died (innermost frame as the site)
                flight.dump("abort", exc=e)
                raise
            except Exception as e:
                flight.dump("exception", exc=e)
                raise
    except ShutdownRequested as e:
        print(f"graphdyn: {e} — exiting {EX_TEMPFAIL} (requeue me)",
              file=sys.stderr)
        return EX_TEMPFAIL
    except KeyboardInterrupt:
        print(f"graphdyn: second signal — hard abort, no snapshot written; "
              f"exiting {EX_ABORT}", file=sys.stderr)
        return EX_ABORT


def _run(args) -> int:
    if args.cmd == "sa":
        cfg = _sa_config(args)
        if args.shards is not None and not args.sharded:
            # a silently ignored sharding request would run the serial
            # driver while the operator believes the pod job sharded
            raise SystemExit(
                "--shards partitions the node axis of the MESH solver; "
                "pass --sharded as well (the per-repetition driver has no "
                "node axis to shard)"
            )
        if args.sharded and args.layout not in ("auto", "padded", "streamed"):
            raise SystemExit(
                f"--layout {args.layout} selects a per-repetition driver "
                "layout; the mesh solver shards the padded node axis or "
                "streams part-major chunk runs (drop --sharded, or "
                "--layout auto/padded/streamed)"
            )
        if args.sharded:
            import jax

            from graphdyn.graphs import random_regular_graph
            from graphdyn.parallel.mesh import make_mesh
            from graphdyn.parallel.sa_sharded import sa_sharded
            from graphdyn.utils.io import save_results_npz

            n_dev = len(jax.devices())
            node_mode = "gather"
            if args.shards is not None:
                if args.rollout_mode == "lightcone":
                    raise SystemExit(
                        "--shards partitions the node axis; --rollout-mode "
                        "lightcone keeps whole replicas — and tempering "
                        "lanes, which ride the same replica axis — per "
                        "device, so there is no node axis to shard (mode-"
                        "selection table: ARCHITECTURE.md 'Node-axis "
                        "sharding & halo exchange' / 'Search acceleration')"
                    )
                if args.shards < 1:
                    raise SystemExit("--shards must be >= 1")
                if args.shards > n_dev:
                    raise SystemExit(
                        f"--shards {args.shards} > {n_dev} visible devices"
                    )
                node_shards = args.shards
                if node_shards >= 2 and args.layout != "streamed":
                    # layout='streamed' runs its own halo composition
                    # inside the sharded streamed engine
                    node_mode = "halo"
            # lightcone needs whole replicas per device (replica-only mesh);
            # full mode splits the node axis when it can
            elif args.rollout_mode == "lightcone":
                node_shards = 1
            else:
                node_shards = 2 if n_dev % 2 == 0 and n_dev > 1 else 1
            mesh = make_mesh(
                (max(n_dev // node_shards, 1), node_shards), ("replica", "node")
            )
            g = random_regular_graph(args.n, args.d, seed=args.seed)
            a0 = None
            if args.ladder_max_frac is not None:
                import numpy as _np

                a0 = _np.linspace(
                    args.a0_frac, args.ladder_max_frac, args.n_replicas
                ) * args.n
            res = sa_sharded(
                g, cfg, mesh=mesh, n_replicas=args.n_replicas, a0=a0,
                seed=args.seed, max_steps=args.max_steps,
                checkpoint_path=args.checkpoint,
                checkpoint_interval_s=args.checkpoint_interval,
                rollout_mode=args.rollout_mode,
                node_mode=node_mode,
                chunk_steps=args.chunk_steps,
                layout="streamed" if args.layout == "streamed" else "padded",
                stream_chunks=args.stream_chunks,
            )
            if args.out:
                save_results_npz(
                    args.out, mag_reached=res.mag_reached,
                    num_steps=res.num_steps, conf=res.s, m_final=res.m_final,
                )
            print(json.dumps({
                "solver": "sa_sharded",
                "mesh": dict(mesh.shape),
                "node_mode": node_mode,
                "mag_reached": res.mag_reached.tolist(),
                "num_steps": res.num_steps.tolist(),
                "m_final": res.m_final.tolist(),
                "out": args.out,
            }))
            return 0
        from graphdyn.models.sa import sa_ensemble

        out = sa_ensemble(
            args.n, args.d, cfg, n_stat=args.n_stat, seed=args.seed,
            max_steps=args.max_steps, save_path=args.out, backend=args.backend,
            checkpoint_path=args.checkpoint,
            checkpoint_interval_s=args.checkpoint_interval,
            rollout_mode=args.rollout_mode,
            group_size=args.group_size, prefetch=args.prefetch,
            layout=args.layout, stream_chunks=args.stream_chunks,
        )
        print(json.dumps({
            "solver": "sa",
            "mag_reached": out.mag_reached.tolist(),
            "num_steps": out.num_steps.tolist(),
            "m_final": out.m_final.tolist(),
            "out": args.out,
        }))
    elif args.cmd == "stream":
        from graphdyn.graphs import powerlaw_graph
        from graphdyn.ops.packed import pack_spins, unpack_spins
        from graphdyn.ops.streamed import seeded_churn, streamed_rollout
        from graphdyn.utils.io import save_results_npz

        g = powerlaw_graph(args.n, gamma=args.gamma, dmin=args.dmin,
                           seed=args.graph_seed)
        rng = np.random.default_rng(args.seed)
        s0 = (2 * rng.integers(0, 2, size=(args.replicas, args.n)) - 1
              ).astype(np.int8)
        churn = (seeded_churn(args.n, args.steps, rate=args.churn_rate,
                              seed=args.churn_seed)
                 if args.churn_rate > 0 else None)
        stats: dict = {}
        if args.shards < 1:
            raise SystemExit("--shards must be >= 1")
        if args.shards >= 2:
            import jax

            from graphdyn.parallel.stream import sharded_streamed_rollout

            n_dev = len(jax.devices())
            if args.shards > n_dev:
                raise SystemExit(
                    f"--shards {args.shards} > {n_dev} visible devices"
                )
            sp_end = sharded_streamed_rollout(
                g, pack_spins(s0), args.steps, n_shards=args.shards,
                rule=args.rule, tie=args.tie,
                n_chunks=(None if args.device_budget is not None
                          else args.chunks),
                device_budget_bytes=args.device_budget,
                hub_threshold=args.hub_threshold,
                prefetch_depth=args.prefetch_depth, churn=churn,
                checkpoint_path=args.checkpoint,
                checkpoint_interval_s=args.checkpoint_interval,
                seed=args.seed, stats_out=stats,
            )
        else:
            sp_end = streamed_rollout(
                g, pack_spins(s0), args.steps,
                rule=args.rule, tie=args.tie,
                n_chunks=(None if args.device_budget is not None
                          else args.chunks),
                device_budget_bytes=args.device_budget,
                prefetch_depth=args.prefetch_depth, churn=churn,
                checkpoint_path=args.checkpoint,
                checkpoint_interval_s=args.checkpoint_interval,
                seed=args.seed, stats_out=stats,
            )
        s_end = unpack_spins(sp_end, args.replicas)
        m_end = s_end.astype(np.float64).sum(axis=1) / args.n  # graftlint: disable=GD004  host observable, exact sum
        if args.out:
            save_results_npz(args.out, conf=s_end, m_end=m_end)
        print(json.dumps({
            "solver": "stream", "n": args.n, "steps": args.steps,
            "shards": args.shards,
            "chunks": stats.get("chunks"),
            "overlap_frac": stats.get("overlap_frac"),
            "h2d_bytes": stats.get("h2d_bytes"),
            "d2h_bytes": stats.get("d2h_bytes"),
            "mutations": stats.get("mutations"),
            "repartitions": stats.get("repartitions"),
            "m_end_mean": float(m_end.mean()),
            "out": args.out,
        }))
    elif args.cmd == "temper":
        from graphdyn.search.tempering import ladder_betas, temper_search
        from graphdyn.utils.io import save_results_npz

        cfg = _sa_config(args)
        mesh = None
        if args.lane_shards is not None:
            if args.lane_shards < 1:
                raise SystemExit("--lane-shards must be >= 1")
            if args.lanes % args.lane_shards:
                raise SystemExit(
                    f"--lane-shards {args.lane_shards} must divide "
                    f"--lanes {args.lanes}"
                )
            from graphdyn.parallel.mesh import device_pool, make_mesh

            mesh = make_mesh(
                (args.lane_shards,), ("lane",),
                devices=device_pool(args.lane_shards),
            )
        from graphdyn.graphs import random_regular_graph

        g = random_regular_graph(args.n, args.d, seed=args.seed)
        res = temper_search(
            g, cfg,
            betas=ladder_betas(args.lanes, args.beta_min, args.beta_max),
            seed=args.seed, max_steps=args.max_steps,
            swap_interval=args.swap_interval,
            swap_moves=not args.no_swaps,
            m_target=args.m_target, stop_on_first=args.stop_on_first,
            checkpoint_path=args.checkpoint,
            checkpoint_interval_s=args.checkpoint_interval,
            mesh=mesh,
        )
        if args.out:
            save_results_npz(
                args.out, conf=res.s, mag_reached=res.mag_reached,
                num_steps=res.num_steps, m_final=res.m_final,
                t_target=res.t_target, betas=res.betas,
            )
        print(json.dumps({
            "solver": "temper",
            "lanes": int(res.betas.size),
            "lane_shards": args.lane_shards,
            "betas": res.betas.tolist(),
            "num_steps": res.num_steps.tolist(),
            "m_final": res.m_final.tolist(),
            "t_target": res.t_target.tolist(),
            "steps_to_target": res.steps_to_target,
            "target_lane": res.target_lane,
            "swap_attempts": res.swap_attempts,
            "swap_accepts": res.swap_accepts,
            "swap_acceptance_rate": res.swap_acceptance_rate,
            "out": args.out,
        }))
    elif args.cmd == "chromatic":
        from graphdyn.graphs import random_regular_graph
        from graphdyn.search.chromatic import chromatic_anneal
        from graphdyn.utils.io import save_results_npz

        g = random_regular_graph(args.n, args.d, seed=args.seed)
        res = chromatic_anneal(
            g, _sa_config(args), n_replicas=args.replicas, seed=args.seed,
            m_target=args.m_target, max_sweeps=args.max_sweeps,
            chunk_sweeps=args.chunk_sweeps,
            stop_on_first=args.stop_on_first,
        )
        if args.out:
            save_results_npz(
                args.out, conf=res.s, mag_reached=res.mag_reached,
                m_end=res.m_end, steps_to_target=res.steps_to_target,
            )
        print(json.dumps({
            "solver": "chromatic",
            "chi": res.chi,
            "sweeps": res.sweeps,
            "device_steps": res.device_steps,
            "accepted": res.accepted,
            "m_end": res.m_end.tolist(),
            "steps_to_target": res.steps_to_target.tolist(),
            "sweeps_to_target": res.sweeps_to_target.tolist(),
            "out": args.out,
        }))
    elif args.cmd == "fused":
        import numpy as _np

        from graphdyn.graphs import random_regular_graph
        from graphdyn.search.fused import fused_anneal
        from graphdyn.utils.io import save_results_npz

        betas = None
        if args.ladder_beta_max is not None:
            if args.ladder_beta_max < 1.0:
                raise SystemExit("--ladder-beta-max must be >= 1.0")
            betas = _np.geomspace(1.0, args.ladder_beta_max, args.replicas)
        g = random_regular_graph(args.n, args.d, seed=args.seed)
        res = fused_anneal(
            g, _sa_config(args), n_replicas=args.replicas, seed=args.seed,
            m_target=args.m_target, max_sweeps=args.max_sweeps,
            chunk_sweeps=args.chunk_sweeps,
            stop_on_first=args.stop_on_first,
            kernel=args.kernel, betas=betas,
        )
        if args.out:
            save_results_npz(
                args.out, conf=res.s, mag_reached=res.mag_reached,
                m_end=res.m_end, steps_to_target=res.steps_to_target,
            )
        print(json.dumps({
            "solver": "fused",
            "kernel": res.kernel_used,
            "chi": res.chi,
            "sweeps": res.sweeps,
            "device_steps": res.device_steps,
            "accepted": res.accepted,
            "m_end": res.m_end.tolist(),
            "steps_to_target": res.steps_to_target.tolist(),
            "sweeps_to_target": res.sweeps_to_target.tolist(),
            "out": args.out,
        }))
    elif args.cmd == "hpr":
        cfg = HPRConfig(
            dynamics=_dynamics(args),
            damp=args.damp, lmbd=args.lmbd, pie=args.pie, gamma=args.gamma,
            max_sweeps=args.max_sweeps, dtype=args.dtype,
        )
        if args.batch_replicas < 0:
            raise SystemExit("--batch-replicas must be >= 1")
        if args.device_init and not args.batch_replicas:
            raise SystemExit("--device-init requires --batch-replicas")
        if args.device_init and args.checkpoint:
            raise SystemExit(
                "--device-init is incompatible with --checkpoint (snapshots "
                "pull the union state back over the host link every interval)"
            )
        if args.batch_replicas:
            from graphdyn.graphs import random_regular_graph
            from graphdyn.models.hpr import hpr_solve_batch

            g = random_regular_graph(args.n, args.d, seed=args.seed)
            res = hpr_solve_batch(
                g, cfg, n_replicas=args.batch_replicas, seed=args.seed,
                checkpoint_path=args.checkpoint,
                checkpoint_interval_s=args.checkpoint_interval,
                device_init=args.device_init, kernel=args.kernel,
            )
            if args.out:
                from graphdyn.utils.io import save_results_npz

                save_results_npz(
                    args.out, conf=res.s, mag_reached=res.mag_reached,
                    num_steps=res.num_steps, m_final=res.m_final,
                    time=res.elapsed_s,
                )
            print(json.dumps({
                "solver": "hpr_batch",
                "mag_reached": res.mag_reached.tolist(),
                "num_steps": res.num_steps.tolist(),
                "m_final": res.m_final.tolist(),
                "elapsed_s": res.elapsed_s,
                "out": args.out,
            }))
            return 0
        from graphdyn.models.hpr import hpr_ensemble

        out = hpr_ensemble(
            args.n, args.d, cfg, n_rep=args.n_rep, seed=args.seed,
            save_path=args.out,
            checkpoint_path=args.checkpoint,
            checkpoint_interval_s=args.checkpoint_interval,
            group_size=args.group_size, prefetch=args.prefetch,
            kernel=args.kernel,
        )
        print(json.dumps({
            "solver": "hpr",
            "mag_reached": out.mag_reached.tolist(),
            "num_steps": out.num_steps.tolist(),
            "time": out.time.tolist(),
            "out": args.out,
        }))
    elif args.cmd == "consensus":
        from graphdyn.models.consensus import (
            consensus_curve,
            consensus_doc,
            er_consensus_ensemble,
        )

        if args.plot:
            import importlib.util

            if importlib.util.find_spec("matplotlib") is None:
                raise SystemExit(
                    "--plot requires matplotlib, which is not installed"
                )
        if args.graph == "rrg":
            from graphdyn.models.consensus import rrg_consensus_ensemble

            g, n_iso, nbr_dev, deg_dev = rrg_consensus_ensemble(
                args.n, d=args.d, seed=args.seed
            )
        else:
            g, n_iso, nbr_dev, deg_dev = er_consensus_ensemble(
                args.n, c=args.c, seed=args.seed
            )
        mesh = None
        if args.sharded:
            import jax

            from graphdyn.parallel.mesh import make_mesh

            mesh = make_mesh((len(jax.devices()),), ("replica",))
        rows = consensus_curve(
            g, args.replicas, args.m0, args.max_steps, chunk=args.chunk,
            nbr_dev=nbr_dev, deg_dev=deg_dev, rule=args.rule, tie=args.tie,
            near_eps=args.near_eps, mesh=mesh, graph_seed=args.seed,
        )
        doc = consensus_doc(
            g, n_iso, rows, c=args.c, seed=args.seed, rule=args.rule,
            tie=args.tie, near_eps=args.near_eps, solver="consensus",
            kind=("random_regular" if args.graph == "rrg"
                  else "erdos_renyi"),
            d=args.d,
        )
        if args.out:
            from graphdyn.utils.io import write_json_atomic

            write_json_atomic(args.out, doc, indent=1)
        if args.plot:
            from graphdyn.plotting import plot_consensus_curve

            plot_consensus_curve(
                rows,
                title=(f"RRG d={args.d}" if args.graph == "rrg"
                       else f"ER c={args.c:g}")
                + f", N={g.n}, R={args.replicas}, {args.rule}",
                save_path=args.plot,
            )
        print(json.dumps(doc))
    elif args.cmd == "entropy":
        from graphdyn.models.entropy import entropy_grid

        if args.plot:
            # fail fast BEFORE the (possibly hours-long) sweep if the plot
            # cannot be written at the end
            import importlib.util

            if importlib.util.find_spec("matplotlib") is None:
                raise SystemExit(
                    "--plot requires matplotlib, which is not installed"
                )
        cfg = EntropyConfig(
            dynamics=_dynamics(args),
            lmbd_max=args.lmbd_max, lmbd_step=args.lmbd_step,
            eps=args.eps, damp=args.damp, max_sweeps=args.max_sweeps,
            ent_floor=args.ent_floor, num_rep=args.num_rep,
            plateau_eps=args.plateau_eps,
            plateau_patience=args.plateau_patience,
            dtype=args.dtype,
        )
        if args.union is not None:
            from graphdyn.graphs import erdos_renyi_graph
            from graphdyn.models.entropy import entropy_ensemble_union
            from graphdyn.utils.io import save_results_npz

            per_deg = []                       # indexed by degree position
            for di, deg in enumerate(args.deg):
                graphs = [
                    erdos_renyi_graph(
                        args.n, deg / (args.n - 1),
                        seed=args.seed + 1000 * di + k,
                    )
                    for k in range(args.union)
                ]
                ck = (
                    f"{args.checkpoint}_deg{di}" if args.checkpoint else None
                )
                per_deg.append(entropy_ensemble_union(
                    graphs, cfg, seed=args.seed + 1000 * di,
                    checkpoint_path=ck,
                    checkpoint_interval_s=args.checkpoint_interval,
                    verbose=args.verbose,
                ))
            if args.out:
                save_results_npz(
                    args.out,
                    deg=np.asarray(args.deg),
                    **{
                        f"{k}_deg{di}": getattr(per_deg[di], k)
                        for di in range(len(args.deg))
                        for k in ("lambdas", "ent", "m_init", "ent1", "sweeps")
                    },
                )
            if args.plot:
                from types import SimpleNamespace

                from graphdyn.plotting import masked_mean, plot_entropy_curve

                ax = None
                for di, deg in enumerate(args.deg):
                    r = per_deg[di]
                    ok = np.isfinite(r.m_init) & np.isfinite(r.ent1)
                    mean = SimpleNamespace(   # member mean over jointly
                        m_init=masked_mean(r.m_init, ok, axis=1),  # finite
                        ent1=masked_mean(r.ent1, ok, axis=1),      # members;
                    )                         # all-degraded λ rows -> NaN
                    ax = plot_entropy_curve(mean, ax=ax, label=f"deg={deg:g}")
                ax.figure.tight_layout()
                ax.figure.savefig(args.plot)
            print(json.dumps({
                "solver": "entropy_union",
                "deg": list(args.deg),
                "members": args.union,
                "ent1_first_lambda": {
                    str(deg): per_deg[di].ent1[0].tolist()
                    for di, deg in enumerate(args.deg)
                },
                "nonconverged": {
                    str(deg): per_deg[di].nonconverged
                    for di, deg in enumerate(args.deg)
                },
                "out": args.out,
                "plot": args.plot,
            }))
            return 0
        out = entropy_grid(
            args.n, np.asarray(args.deg), cfg, seed=args.seed,
            verbose=args.verbose, save_path=args.out,
            checkpoint_path=args.checkpoint,
            checkpoint_interval_s=args.checkpoint_interval,
            prefetch=args.prefetch, group_size=args.group_size,
            kernel=args.kernel,
        )
        if args.plot:
            from graphdyn.plotting import plot_entropy_grid

            plot_entropy_grid(out, save_path=args.plot)
        print(json.dumps({
            "solver": "entropy",
            "deg": out.deg.tolist(),
            "ent1_first_lambda": out.ent1[:, :, 0].tolist(),
            "counts": out.counts.tolist(),
            "out": args.out,
            "plot": args.plot,
        }))
    elif args.cmd == "serve":
        from graphdyn.serve import api as serve_api

        if args.action == "run":
            from graphdyn.serve.lifecycle import run_service

            return run_service(
                args.root, job_timeout_s=args.job_timeout,
                max_jobs=args.max_jobs, idle_exit_s=args.idle_exit,
                warm=not args.no_warm,
            )
        if args.action == "submit":
            spec = {k: v for k, v in (
                ("solver", args.solver),
                ("n", args.n), ("d", args.d),
                ("graph_seed", args.graph_seed), ("seed", args.seed),
                ("rule", args.rule), ("tie", args.tie),
                ("replicas", args.replicas), ("m_target", args.m_target),
                ("max_sweeps", args.max_sweeps),
                ("chunk_sweeps", args.chunk_sweeps),
                ("edges", args.edges),
                ("dmax", args.dmax),
                ("gamma", args.gamma),
                ("degree_cv", args.degree_cv)) if v is not None}
            job_id = serve_api.submit(args.root, spec, args.tenant,
                                      timeout_s=args.job_timeout)
            print(json.dumps({"job": job_id, "root": args.root,
                              "tenant": args.tenant}))
            return 0
        if args.action == "queue":
            print(json.dumps(serve_api.queue(args.root)))
            return 0
        if args.job is None:
            raise SystemExit(f"serve {args.action} needs a job id")
        if args.action == "status":
            print(json.dumps(serve_api.status(args.root, args.job)))
            return 0
        res = serve_api.result(args.root, args.job)      # action: result
        print(json.dumps({
            "job": args.job,
            "keys": sorted(res),
            "m_end_mean": float(np.mean(res["m_end"])),
            # bucketed-rollout results have no target-reached notion
            "mag_reached": (int(np.sum(res["mag_reached"]))
                            if "mag_reached" in res else None),
            "result": serve_api.status(args.root, args.job)["result"],
        }))
    return 0
