"""BDCM entropy λ-sweep (L5 solver) — the notebook's procedure, jit-compiled.

Reproduces `BDCM_entropy_procedure_GENERAL_ER` + driver
(`ER_BDCM_entropy.ipynb:394-515`): for each λ in a ladder, (a) write the
closed-form leaf messages, (b) iterate the BDCM sweep to a fixed point
warm-started from the previous λ (the load-bearing trick that keeps sweep
counts at ~130-160 instead of cold-start, SURVEY.md §3.3), (c) record the
Bethe free entropy φ, the BP mean initial magnetization, and the tilted
(Legendre) entropy ``s(m_init) = φ + λ·m_init``; stop early when the entropy
crosses ``ent_floor`` (no such initializations exist) or on non-convergence
(the reference's ``counts`` sentinel, `ipynb:429-431,446-447`).

TPU-first: the whole fixed-point iteration is one ``lax.while_loop`` around
the jitted sweep — λ is a traced scalar, so the entire ladder reuses a single
compiled program per graph structure; only the host-side ladder loop and
early-exit logic remain in Python.
"""

from __future__ import annotations

import logging
from functools import partial
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from graphdyn.config import EntropyConfig
from graphdyn.resilience import faults as _faults
from graphdyn.resilience.shutdown import raise_if_requested, shutdown_requested
from graphdyn.resilience.supervisor import beat as _heartbeat
from graphdyn.graphs import Graph, erdos_renyi_graph, remove_isolates
from graphdyn.ops.bdcm import BDCMData, make_leaf_setter

log = logging.getLogger("graphdyn.models")


def lambda_ladder(config: EntropyConfig) -> np.ndarray:
    """The configured λ ladder 0..lmbd_max in lmbd_step increments
    (`ipynb:480-482`); rounded count so e.g. (0.3, 0.1) gives 4 points."""
    return np.linspace(
        0.0, config.lmbd_max, int(round(config.lmbd_max / config.lmbd_step)) + 1
    )


class EntropyResult(NamedTuple):
    lambdas: np.ndarray    # ladder values actually visited [count]
    ent: np.ndarray        # φ per λ
    m_init: np.ndarray     # BP mean initial magnetization per λ
    ent1: np.ndarray       # tilted entropy φ + λ·m_init per λ
    sweeps: np.ndarray     # fixed-point sweep counts per λ
    nonconverged: float    # the reference's `counts`: the λ that failed, or 0
    chi: np.ndarray        # final messages (resume state)


@partial(jax.jit, static_argnames=("spec", "eps", "t_max"))
# warm-start ladders and the sharded-vs-unsharded parity tests replay the
# same chi through multiple fixed-point variants; donation would
# invalidate their input buffer
# graftlint: disable-next-line=GD006  callers reuse chi across variants
def _fixed_point_exec(chi, lmbd, valid, x0, tables, spec, eps: float, t_max: int):
    """Module-level fixed-point executor: graphs whose sweep shapes coincide
    (same degree-class signature, e.g. via ``BDCMData(class_bucket=...)``)
    share ONE compiled while_loop instead of recompiling per instance."""
    from graphdyn.ops.bdcm import _sweep_core

    def cond(st):
        _, delta, t = st
        return (delta > eps) & (t < t_max)

    def body(st):
        chi, _, t = st
        new = _sweep_core(chi, lmbd, None, valid, x0, tables, spec)
        return new, jnp.abs(new - chi).max(), t + 1

    chi, delta, t = lax.while_loop(
        cond, body, (chi, jnp.asarray(jnp.inf, chi.dtype), 0)
    )
    return chi, t, delta


def make_fixed_point(data: BDCMData, config: EntropyConfig):
    """``(chi, lmbd) -> (chi*, sweeps, delta)``: iterate the sweep until
    ``max|Δchi| < eps`` or ``max_sweeps`` (`ipynb:420-432`), via the shared
    executor. A Pallas lowering/compile failure degrades the program to the
    XLA path (logged, results unchanged) instead of aborting the ladder;
    fault site ``sweep.nan`` poisons the carry for NaN-path tests."""
    from graphdyn.ops.bdcm import _sweep_args, poison_nan, resilient_exec

    valid, x0, tables, spec = _sweep_args(
        data, damp=config.damp, eps_clamp=config.eps_clamp,
        mask_invalid_src=True, with_bias=False, use_pallas="auto",
    )
    eps_f, t_max = float(config.eps), int(config.max_sweeps)
    state = {"spec": spec}

    def fixed_point(chi, lmbd):
        out = resilient_exec(state, lambda sp: _fixed_point_exec(
            chi, lmbd, valid, x0, tables, sp, eps_f, t_max
        ))
        if _faults.transform_spec("sweep.nan", "nan") is not None:
            chi_out, t, _ = out
            out = (poison_nan(chi_out), t, jnp.asarray(jnp.nan, chi_out.dtype))
        return out

    return fixed_point


def _ensemble_stop_fn(config: EntropyConfig, ent_floor_mode: str):
    """Shared ent-floor exit for per-member e1 vectors: 'all' members (or
    'any') must cross the floor. Validates the mode."""
    if ent_floor_mode not in ("all", "any"):
        raise ValueError(
            f"ent_floor_mode must be 'all' or 'any', got {ent_floor_mode!r}"
        )

    def stop_fn(e1):
        crossed = e1 < config.ent_floor
        return bool(crossed.all() if ent_floor_mode == "all" else crossed.any())

    return stop_fn


def _run_ladder(
    lambdas,
    chi,
    dtype,
    *,
    set_leaves,
    fixed_point,
    observe,
    eps: float,
    stop_fn,
    checkpointer=None,
    checkpoint_meta: dict | None = None,
    checkpoint_extra_arrays: dict | None = None,
    verbose: bool = False,
    plateau_eps: float = 0.0,
    plateau_patience: int = 3,
    prev_rows=None,
):
    """The shared λ-ladder loop (`ipynb:394-451` semantics) used by every
    entropy solver: leaf write → warm-started fixed point → observables →
    Legendre transform → checkpoint → early exits. ``observe(chi, lm)``
    returns (φ, m_init) as scalars or per-member arrays; ``stop_fn(e1)``
    decides the entropy-floor exit. ``plateau_eps > 0`` adds an opt-in
    exit: stop when every member's (m_init, ent1) moved less than
    plateau_eps for plateau_patience consecutive λ — T>=3 curves floor at
    positive ent1, where the reference's ent_floor exit never fires and
    the remaining ladder re-converges an unchanged fixed point.
    ``prev_rows = (m_init_rows, ent1_rows)`` is the already-visited prefix
    when resuming a λ subset: the plateau streak is reconstructed from it
    so a resumed run exits at exactly the λ an uninterrupted run would.
    Returns ``(visited, ents, m_inits, ent1s, sweeps, nonconverged, chi)``."""
    ents, m_inits, ent1s, sweeps, visited = [], [], [], [], []
    nonconverged = 0.0
    plateau_patience = max(1, int(plateau_patience))  # 0/negative would
    plateau_streak = 0                                # exit unconditionally
    prev_m = prev_e = None
    if plateau_eps > 0 and prev_rows is not None and len(prev_rows[0]) > 0:
        pm, pe = (np.asarray(r) for r in prev_rows)
        for i in range(1, len(pm)):
            moved = max(float(np.max(np.abs(pm[i] - pm[i - 1]))),
                        float(np.max(np.abs(pe[i] - pe[i - 1]))))
            plateau_streak = plateau_streak + 1 if moved < plateau_eps else 0
        prev_m, prev_e = pm[-1], pe[-1]
        if plateau_streak >= plateau_patience:
            # the uninterrupted run had already exited inside the prefix
            return visited, ents, m_inits, ent1s, sweeps, nonconverged, chi
    for lmbd in lambdas:
        # graftlint: disable-next-line=GD008  one SCALAR λ per ladder step — the ladder is sequential in λ (warm starts); the CELL axis is what batches, via pipeline.entropy_group (entropy_grid group_size)
        lm = jnp.asarray(lmbd, dtype)
        chi = set_leaves(chi, lm)
        chi, t, delta = fixed_point(chi, lm)
        t = int(t)
        phi, m0 = observe(chi, lm)
        phi, m0 = np.asarray(phi), np.asarray(m0)
        e1 = phi + float(lmbd) * m0
        visited.append(float(lmbd))
        ents.append(phi)
        m_inits.append(m0)
        ent1s.append(e1)
        sweeps.append(t)
        failed = float(delta) > eps
        # NaN anywhere in the carry/observables is poison, not a value
        # (−inf is a legitimate degraded φ — empty attractor set — and
        # flows through): degrade explicitly to the reference's
        # non-convergence sentinel and stop, never emit NaN rows silently.
        # NB a NaN delta makes `delta > eps` FALSE — without this check a
        # poisoned fixed point would read as converged.
        poisoned = bool(
            np.isnan(float(delta)) or np.isnan(phi).any() or np.isnan(m0).any()
        )
        if poisoned and not failed:
            failed = True
        if poisoned:
            log.warning(
                "non-finite sweep state at lambda=%g (delta=%r) — recording "
                "non-convergence and stopping the ladder", float(lmbd), delta,
            )
            # the degrade is survivable (sentinel + stop), but the evidence
            # is not: preserve the flight-recorder tail at the moment the
            # poison was detected (post-mortem file, or the live ledger's
            # obs.crash event when one is recording)
            from graphdyn.obs import flight

            flight.dump("sweep.nan",
                        site=f"entropy ladder lambda={float(lmbd):g}")
        if failed:
            nonconverged = float(lmbd)
        if verbose:
            m_s = f"{m0:.5f}" if np.ndim(m0) == 0 else f"{np.mean(m0):.5f}(mean)"
            e_s = f"{e1:.5f}" if np.ndim(e1) == 0 else f"{np.mean(e1):.5f}(mean)"
            print(f"lambda={lmbd:.2f} t={t} m_init={m_s} ent1={e_s}")
        _heartbeat("lambda")
        stopping = shutdown_requested()
        if checkpointer is not None and (stopping or checkpointer.due()):
            payload = {
                "chi": np.asarray(chi),
                "ent": np.array(ents),
                "m_init": np.array(m_inits),
                "ent1": np.array(ent1s),
                "sweeps": np.array(sweeps),
                "lambdas": np.array(visited),
                **(checkpoint_extra_arrays or {}),
            }
            meta = {"lmbd": float(lmbd), "failed": bool(failed),
                    **(checkpoint_meta or {})}
            if stopping:
                checkpointer.save_now(payload, meta)  # bypass interval gate
            else:
                checkpointer.maybe_save(payload, meta)
        if stopping:
            raise_if_requested(where="lambda")
        _faults.maybe_fail("lambda.boundary", key=f"lmbd={float(lmbd):g}")
        if stop_fn(e1) or failed:
            break
        if plateau_eps > 0:
            if prev_m is not None:
                moved = max(
                    float(np.max(np.abs(m0 - prev_m))),
                    float(np.max(np.abs(e1 - prev_e))),
                )
                plateau_streak = (
                    plateau_streak + 1 if moved < plateau_eps else 0
                )
                if plateau_streak >= plateau_patience:
                    if verbose:
                        print(f"plateau exit at lambda={lmbd:.2f} "
                              f"(<{plateau_eps:g} movement for "
                              f"{plateau_patience} consecutive lambda)")
                    break
            prev_m, prev_e = m0, e1
    return visited, ents, m_inits, ent1s, sweeps, nonconverged, chi


def entropy_sweep(
    graph: Graph,
    config: EntropyConfig | None = None,
    *,
    n_total: int | None = None,
    seed: int = 0,
    chi0=None,
    lambdas: np.ndarray | None = None,
    verbose: bool = False,
    checkpointer=None,
    class_bucket: int | None = None,
    prev_rows=None,
    kernel: str = "auto",
) -> EntropyResult:
    """Run the λ ladder on one graph instance.

    ``class_bucket``: round degree-class sizes up to a multiple of this
    (ghost padding) so different graph instances of the same ensemble land on
    identical compiled programs — pays a few % padded FLOPs to avoid a full
    XLA recompile per instance (see ``BDCMData``).

    ``graph`` may contain isolated nodes; they are removed here and folded in
    analytically (φ gets ``−λ·n_iso/n``, m_init gets ``+n_iso/n``,
    `ipynb:283-291,338`). ``n_total`` overrides the density normalization
    (defaults to ``graph.n`` including isolates).

    ``checkpointer``: optional :class:`graphdyn.utils.io.PeriodicCheckpointer`
    — the notebook's time-triggered intermediate-save sketch
    (`ipynb:439-445,475-476`) made live: after each λ the warm-start state
    (chi) and the results so far are offered for saving; resume by passing the
    restored ``chi`` as ``chi0`` and the remaining ladder as ``lambdas``
    (plus, when ``config.plateau_eps > 0``, the visited prefix's
    ``(m_init, ent1)`` rows as ``prev_rows`` so the plateau streak resumes
    where it left off).

    The ladder advances through the ensemble pipeline's shared cell-group
    program (:class:`graphdyn.pipeline.entropy_group.EntropyCellExec` with
    G=1; ARCHITECTURE.md "Ensemble pipeline"): the grouped ``entropy_grid``
    driver runs the SAME vmapped body at G=``group_size``, which is what
    makes serial-vs-grouped cell results element-wise identical — the PR-3
    lesson that two *differently structured* loop programs computing the
    same chain law diverge at the ulp level under XLA fusion. Regression-
    anchored against the pre-refactor serial values.

    ``kernel`` selects the sweep core (``'auto'``/``'xla'``/``'pallas'``,
    ARCHITECTURE.md "Kernel selection"): on TPU the default fuses each
    qualifying degree class's DP + contraction into the grouped Pallas
    kernel — the same kernel the grouped ``entropy_grid`` runs, at G=1, so
    grouped == serial stays structural under EITHER core. Pallas-vs-XLA is
    an approximate mode (~1e-3 max rel err, PALLAS_TPU.json).
    """
    config = config or EntropyConfig()
    dyn = config.dynamics
    n_total = n_total or graph.n
    sub, n_iso = remove_isolates(graph)

    from graphdyn.pipeline.entropy_group import EntropyCellExec

    data = BDCMData(
        sub,
        p=dyn.p,
        c=dyn.c,
        attr_value=dyn.attr_value,
        rule=dyn.rule,
        tie=dyn.tie,
        class_bucket=class_bucket,
        dtype=config.dtype,
    )
    ex = EntropyCellExec([(data, n_total, n_iso)], config, kernel=kernel)
    fixed_point = ex.fixed_point1
    set_leaves = ex.set_leaves1
    phi_fn, minit_fn = ex.observe_fns(0)

    if lambdas is None:
        lambdas = lambda_ladder(config)
    chi = data.init_messages(seed) if chi0 is None else jnp.asarray(chi0, data.dtype)

    visited, ents, m_inits, ent1s, sweeps, nonconverged, chi = _run_ladder(
        lambdas, chi, data.dtype,
        set_leaves=set_leaves,
        fixed_point=fixed_point,
        observe=lambda c, lm: (phi_fn(c, lm), minit_fn(c)),
        eps=config.eps,
        # early exits (`ipynb:446-447`)
        stop_fn=lambda e1: bool(e1 < config.ent_floor),
        checkpointer=checkpointer,
        checkpoint_meta={"seed": seed},
        verbose=verbose,
        plateau_eps=config.plateau_eps,
        plateau_patience=config.plateau_patience,
        prev_rows=prev_rows,
    )
    return EntropyResult(
        lambdas=np.array(visited),
        ent=np.array(ents),
        m_init=np.array(m_inits),
        ent1=np.array(ent1s),
        sweeps=np.array(sweeps),
        nonconverged=nonconverged,
        chi=np.asarray(chi),
    )


_LADDER_ROW_KEYS = ("lambdas", "ent", "m_init", "ent1", "sweeps")


def _ladder_rows(out):
    """Convert a :func:`_run_ladder` 7-tuple into ``(rows dict,
    nonconverged, chi)`` — the one place that mapping lives."""
    visited, ents, m_inits, ent1s, sweeps, nonconverged, chi = out
    rows = dict(zip(
        _LADDER_ROW_KEYS,
        (np.array(visited), np.array(ents), np.array(m_inits),
         np.array(ent1s), np.array(sweeps)),
    ))
    return rows, nonconverged, chi


def _run_managed_ladder(
    checkpoint_path,
    interval_s,
    *,
    id_key,
    id_value,
    what,
    lambdas,
    stop_fn,
    chi_init,
    dtype,
    ladder_fn,
    base_meta,
    extra_arrays=None,
):
    """The managed λ-ladder resume protocol shared by the ensemble entropy
    solvers: identity-validated load (:func:`graphdyn.utils.io
    .load_validated`), re-entry at the first unvisited λ with the saved
    warm-start chi, prefix stitching that survives repeated interruptions
    (snapshots carry the already-stitched earlier segments as ``prev_*``),
    and removal on completion.

    ``ladder_fn(lambdas_rest, chi, checkpointer, meta, extra_arrays,
    prev_rows)`` runs the solver-specific :func:`_run_ladder` call and
    returns its 7-tuple — ``prev_rows`` is ``(m_init_rows, ent1_rows)`` of
    the restored prefix (None on a cold start) so the plateau streak
    survives the resume boundary; ``chi_init()`` builds the cold-start
    messages. Returns ``(rows dict, nonconverged, chi)`` with rows keyed
    by :data:`_LADDER_ROW_KEYS`.
    """
    from graphdyn.utils.io import PeriodicCheckpointer, load_validated

    lambdas = np.asarray(lambdas, float)
    prefix = load_validated(checkpoint_path, id_key, id_value, what)
    checkpointer = PeriodicCheckpointer(checkpoint_path, interval_s=interval_s)
    meta = {**base_meta, id_key: id_value}

    k0 = 0
    pre = None
    if prefix is not None:
        arrays, pmeta = prefix
        chi = jnp.asarray(arrays["chi"], dtype)
        seg = {k: np.asarray(arrays[k]) for k in _LADDER_ROW_KEYS}
        if "prev_lambdas" in arrays:
            # twice-interrupted: the snapshot carries the earlier stitched
            # segments alongside the current one
            pre = {
                k: np.concatenate([np.asarray(arrays["prev_" + k]), seg[k]])
                for k in seg
            }
        else:
            pre = seg
        k0 = int(pre["lambdas"].size)
        failed_prev = bool(pmeta.get("failed", False))
        if failed_prev or stop_fn(pre["ent1"][-1]) or k0 >= lambdas.size:
            checkpointer.remove()
            return pre, (float(pmeta["lmbd"]) if failed_prev else 0.0), chi
    else:
        chi = chi_init()

    out = ladder_fn(
        lambdas[k0:], chi, checkpointer, meta,
        {
            **(extra_arrays or {}),
            **({f"prev_{k}": v for k, v in pre.items()} if pre is not None else {}),
        },
        (pre["m_init"], pre["ent1"]) if pre is not None else None,
    )
    checkpointer.remove()

    rows, nonconverged, chi = _ladder_rows(out)
    if pre is not None:
        if rows["lambdas"].size == 0:
            # resumed past the run's own exit (e.g. a plateau streak that
            # completed inside the prefix): nothing new to stitch, and the
            # empty 1-D segment must not be concatenated onto 2-D prefix rows
            rows = pre
        else:
            rows = {k: np.concatenate([pre[k], rows[k]]) for k in rows}
    return rows, nonconverged, chi


class EnsembleEntropyResult(NamedTuple):
    lambdas: np.ndarray    # ladder values visited [count]
    ent: np.ndarray        # φ [count, G]
    m_init: np.ndarray     # [count, G]
    ent1: np.ndarray       # [count, G]
    sweeps: np.ndarray     # joint fixed-point sweep counts [count]
    nonconverged: float    # λ whose joint fixed point failed, or 0 — the
                           # serial path's sentinel (`ipynb:429-431`); entries
                           # at that λ are not fixed-point values
    chi: np.ndarray        # [G, 2E, K, K] resume state


def entropy_ensemble(
    graphs,
    config: EntropyConfig | None = None,
    *,
    seed: int = 0,
    lambdas: np.ndarray | None = None,
    ent_floor_mode: str = "all",
    chi0=None,
    checkpoint_path: str | None = None,
    checkpoint_interval_s: float = 30.0,
    mesh=None,
    graph_axis: str = "graph",
) -> EnsembleEntropyResult:
    """The λ ladder over a *structurally congruent* graph ensemble (e.g.
    RRG(n, d) instances) as ONE vmapped device program — the BASELINE
    config-4 shape (G graphs × λ ladder) without per-graph dispatch or
    recompilation.

    ``mesh``: shard the GRAPH axis over the mesh's ``graph_axis`` —
    instances are independent (the reference's deg×rep host loop,
    `ipynb:496-497`), so the vmapped program partitions embarrassingly:
    chi ``[G, 2E, K, K]`` is placed ``P(graph_axis)`` and GSPMD keeps every
    per-graph sweep on its shard; the only cross-device traffic is the
    scalar convergence/observable reductions. Results match the unsharded
    path to roundoff (tested).

    The fixed point iterates until every instance satisfies
    ``max|Δchi| < eps`` (converged instances sit at their fixed point, so
    extra sweeps are no-ops within eps). Early exit on the entropy floor uses
    ``all`` (default) or ``any`` instance crossing, per ``ent_floor_mode``.
    Isolated nodes are not supported here — use :func:`entropy_sweep`
    per-graph for ensembles with isolates.

    ``chi0`` warm-starts from a previous result's ``chi``;
    ``checkpoint_path`` enables the managed exact λ-granular auto-resume
    shared with :func:`entropy_ensemble_union` (identity-validated restart,
    prefix stitching across repeated interruptions, removal on completion).
    """
    from graphdyn.ops.bdcm import (
        EnsembleBDCM,
        make_ensemble_free_entropy,
        make_ensemble_leaf_setter,
        make_ensemble_m_init,
        make_ensemble_sweep,
    )

    config = config or EntropyConfig()
    stop_fn = _ensemble_stop_fn(config, ent_floor_mode)   # fail-fast validation
    dyn = config.dynamics
    for g in graphs:
        if (g.deg == 0).any():
            raise ValueError("entropy_ensemble requires isolate-free graphs")
    datas = [
        BDCMData(g, p=dyn.p, c=dyn.c, attr_value=dyn.attr_value,
                 rule=dyn.rule, tie=dyn.tie, dtype=config.dtype)
        for g in graphs
    ]
    ens = EnsembleBDCM(datas)
    sweep = make_ensemble_sweep(ens, damp=config.damp, eps_clamp=config.eps_clamp)
    set_leaves = make_ensemble_leaf_setter(ens)
    phi_fn = make_ensemble_free_entropy(ens, eps_clamp=config.eps_clamp)
    minit_fn = make_ensemble_m_init(ens, eps_clamp=config.eps_clamp)

    eps, T_max = config.eps, config.max_sweeps

    @jax.jit
    # graftlint: disable-next-line=GD006  callers reuse chi across variants
    def fixed_point(chi, lmbd):
        def cond(st):
            _, delta, t = st
            return (delta > eps) & (t < T_max)

        def body(st):
            chi, _, t = st
            new = sweep(chi, lmbd)
            return new, jnp.abs(new - chi).max(), t + 1

        chi, delta, t = lax.while_loop(
            cond, body, (chi, jnp.asarray(jnp.inf, chi.dtype), 0)
        )
        return chi, t, delta

    if lambdas is None:
        lambdas = lambda_ladder(config)

    def chi_init():
        return (
            ens.init_messages(seed) if chi0 is None
            else jnp.asarray(chi0, ens.dtype)
        )

    if mesh is not None:
        shards = int(mesh.shape[graph_axis])
        if len(graphs) % shards:
            raise ValueError(
                f"entropy_ensemble(mesh=...) needs the graph count divisible "
                f"by the {graph_axis!r} axis ({len(graphs)} graphs, "
                f"{shards} shards) — pad the ensemble or shrink the mesh"
            )

    def ladder_fn(lam, chi, ck, meta, xtra, prev_rows=None):
        if mesh is not None:
            # placed here (not in chi_init) so a checkpoint-restored warm
            # start is re-placed on the mesh too
            from jax.sharding import NamedSharding, PartitionSpec

            chi = jax.device_put(
                chi, NamedSharding(mesh, PartitionSpec(graph_axis))
            )
        return _run_ladder(
            lam, chi, ens.dtype,
            set_leaves=set_leaves,
            fixed_point=fixed_point,
            observe=lambda c, lm: (phi_fn(c, lm), minit_fn(c)),
            eps=config.eps,
            stop_fn=stop_fn,
            checkpointer=ck,
            checkpoint_meta=meta,
            checkpoint_extra_arrays=xtra,
            plateau_eps=config.plateau_eps,
            plateau_patience=config.plateau_patience,
            prev_rows=prev_rows,
        )

    if checkpoint_path is not None:
        from graphdyn.utils.io import run_fingerprint

        ens_id = run_fingerprint(
            *[g.edges for g in graphs], [int(g.n) for g in graphs], config,
            seed, np.asarray(lambdas, float), ent_floor_mode,
            None if chi0 is None else np.asarray(chi0),
        )
        rows, nonconverged, chi = _run_managed_ladder(
            checkpoint_path, checkpoint_interval_s,
            id_key="ens_id", id_value=ens_id, what="congruent-ensemble",
            lambdas=lambdas, stop_fn=stop_fn, chi_init=chi_init,
            dtype=ens.dtype, ladder_fn=ladder_fn, base_meta={"seed": seed},
        )
        return EnsembleEntropyResult(
            **rows, nonconverged=nonconverged, chi=np.asarray(chi),
        )

    rows, nonconverged, chi = _ladder_rows(ladder_fn(
        np.asarray(lambdas, float), chi_init(), None, None, None
    ))
    return EnsembleEntropyResult(
        **rows,
        nonconverged=nonconverged,
        chi=np.asarray(chi),
    )


@partial(jax.jit, static_argnames=("G", "eps_clamp"))
def _union_observables_exec(zi, zij, mterms, lmbd, node_gid, edge_gid,
                            n_iso_v, n_tot_v, G: int, eps_clamp: float = 0.0):
    """Per-member (φ, m_init) from union-graph partition functions by
    segment reduction. Module-level jit: calls with identical shapes (the
    chi0-resume and checkpointer-restore flows) share one compile."""
    import jax.ops

    phi = (
        jax.ops.segment_sum(jnp.log(zi), node_gid, num_segments=G)
        - jax.ops.segment_sum(jnp.log(zij), edge_gid, num_segments=G)
        - lmbd * n_iso_v
    ) / n_tot_v
    # per-member empty-attractor guard: φ_g = −inf, not NaN (see
    # ops.bdcm._phi_exec; a vanished Z sits AT the clamp floor). Edgeless
    # members have no nodes either (their isolates were removed), so
    # segment_min's identity (+inf) keeps them on the analytic branch.
    zi_min = jax.ops.segment_min(zi, node_gid, num_segments=G)
    phi = jnp.where(zi_min <= eps_clamp, -jnp.inf, phi)
    m0 = (
        jax.ops.segment_sum(mterms, edge_gid, num_segments=G) + n_iso_v
    ) / n_tot_v
    return phi, m0


class UnionEnsembleEntropyResult(NamedTuple):
    """Per-member λ-ladder results of :func:`entropy_ensemble_union`.

    Unlike :class:`EnsembleEntropyResult`, members may differ in edge count,
    so ``chi`` is the UNION resume state ``[2E_union, K, K]`` (pass it back
    as ``chi0`` to resume); ``edge_gid[e]`` maps undirected union edge ``e``
    to its member index for any per-member slicing."""

    lambdas: np.ndarray    # ladder values visited [count]
    ent: np.ndarray        # φ [count, G]
    m_init: np.ndarray     # [count, G]
    ent1: np.ndarray       # [count, G]
    sweeps: np.ndarray     # joint fixed-point sweep counts [count]
    nonconverged: float    # λ whose joint fixed point failed, or 0
    chi: np.ndarray        # [2E_union, K, K] union resume state
    edge_gid: np.ndarray   # int[E_union] — member index per undirected edge


def entropy_ensemble_union(
    graphs,
    config: EntropyConfig | None = None,
    *,
    seed: int = 0,
    chi0=None,
    lambdas: np.ndarray | None = None,
    ent_floor_mode: str = "all",
    checkpointer=None,
    checkpoint_path: str | None = None,
    checkpoint_interval_s: float = 30.0,
    verbose: bool = False,
    mesh=None,
    edge_axis: str = "edge",
) -> UnionEnsembleEntropyResult:
    """The λ ladder over an ARBITRARY graph ensemble as one device program,
    via the disjoint union (:func:`graphdyn.graphs.disjoint_union`).

    Unlike :func:`entropy_ensemble` (vmapped, congruent members only — and a
    batch axis XLA pads to 128 lanes on TPU), the union concatenates members
    into one big graph: heterogeneous degree signatures merge into one set
    of degree classes, isolated nodes are allowed (handled per member with
    the analytic ``−λ·n_iso/n`` / ``+n_iso/n`` terms, `ipynb:283-291,338`),
    and the edge axis stays the single TPU lane dimension. Per-member φ and
    m_init come from segment sums of the per-node/per-edge partition
    functions. This is the BASELINE config-4 shape (64 ER instances × the
    λ ladder) done natively. ``chi0`` resumes from a previous result's union
    ``chi``; ``checkpointer`` (a
    :class:`graphdyn.utils.io.PeriodicCheckpointer`) saves the warm-start
    state + results-so-far after a λ point at most every ``interval_s`` for
    callers that manage resume themselves.

    ``checkpoint_path`` is the managed alternative (mutually exclusive with
    ``checkpointer``): exact λ-granular auto-resume with the same contract
    as :func:`entropy_grid` — an identity-validated restart re-enters the
    ladder at the first unvisited λ with the saved warm-start chi, a
    mismatched run is refused, and the file is removed on completion.

    ``mesh``: run every fixed point edge-sharded over the mesh's
    ``edge_axis`` (:func:`graphdyn.parallel.sharded.make_sharded_fixed_point`
    — the per-class DP tensors, the memory/FLOP hot spot, split across
    devices; chi stays replicated). The ~10² sweeps per λ dominate the
    ladder, so the once-per-λ observables run unsharded; results match the
    single-device path to roundoff (tested on the 8-device CPU mesh).
    """
    from graphdyn.graphs import disjoint_union
    from graphdyn.ops.bdcm import (
        make_edge_partition,
        make_m_init_edge_terms,
        make_node_partition,
    )

    config = config or EntropyConfig()
    stop_fn = _ensemble_stop_fn(config, ent_floor_mode)   # fail-fast validation
    dyn = config.dynamics
    G = len(graphs)
    subs, n_isos, n_totals = [], [], []
    for g in graphs:
        sub, n_iso = remove_isolates(g)
        subs.append(sub)
        n_isos.append(n_iso)
        n_totals.append(g.n)
    gu, node_gid, edge_gid = disjoint_union(subs)

    if lambdas is None:
        lambdas = lambda_ladder(config)

    # managed checkpoint_path mode: identity-validated λ-granular auto-resume
    # (the shared protocol, :func:`_run_managed_ladder`). Identity computed
    # before the all-edgeless shortcut so the contract (mutual exclusion,
    # foreign-checkpoint refusal, removal on completion) holds there too.
    managed = checkpoint_path is not None
    union_id = None
    if managed:
        if checkpointer is not None:
            raise ValueError(
                "pass either checkpoint_path (managed resume) or "
                "checkpointer (caller-managed), not both"
            )
        from graphdyn.utils.io import run_fingerprint

        union_id = run_fingerprint(
            *[g.edges for g in graphs], [int(g.n) for g in graphs], config,
            seed, np.asarray(lambdas, float), ent_floor_mode,
            None if chi0 is None else np.asarray(chi0),
        )

    if gu.num_edges == 0:
        # every member is edgeless (all isolates): the analytic closed form
        # IS the whole answer — φ_g = −λ·n_iso/n, m_init = 1 per member
        n_iso_a = np.asarray(n_isos, float)
        n_tot_a = np.asarray(n_totals, float)
        lam = np.asarray(lambdas, float)
        ent = -lam[:, None] * n_iso_a[None, :] / n_tot_a[None, :]
        m0 = np.broadcast_to(n_iso_a / n_tot_a, (lam.size, G)).copy()
        K = 2 ** (dyn.p + dyn.c)
        if managed:
            from graphdyn.utils.io import load_validated, open_checkpoint

            load_validated(checkpoint_path, "union_id", union_id,
                           "union-ensemble")
            open_checkpoint(checkpoint_path).remove()
        return UnionEnsembleEntropyResult(
            lambdas=lam,
            ent=ent,
            m_init=m0,
            ent1=ent + lam[:, None] * m0,
            sweeps=np.zeros(lam.size, int),
            nonconverged=0.0,
            chi=np.zeros((0, K, K)),
            edge_gid=edge_gid,
        )

    data = BDCMData(
        gu, p=dyn.p, c=dyn.c, attr_value=dyn.attr_value,
        rule=dyn.rule, tie=dyn.tie, dtype=config.dtype,
    )
    if mesh is not None:
        from graphdyn.parallel.sharded import make_sharded_fixed_point

        fixed_point = make_sharded_fixed_point(
            data, mesh, damp=config.damp, eps=float(config.eps),
            max_sweeps=int(config.max_sweeps),
            eps_clamp=config.eps_clamp, edge_axis=edge_axis,
        )
    else:
        fixed_point = make_fixed_point(data, config)
    set_leaves = make_leaf_setter(data)
    zi_fn = make_node_partition(data, eps_clamp=config.eps_clamp)
    zij_fn = make_edge_partition(data, eps_clamp=config.eps_clamp)
    mterm_fn = make_m_init_edge_terms(data, eps_clamp=config.eps_clamp)

    edge_gid_np = edge_gid
    node_gid = jnp.asarray(node_gid)
    edge_gid = jnp.asarray(edge_gid)
    n_iso_v = jnp.asarray(n_isos, data.dtype)
    n_tot_v = jnp.asarray(n_totals, data.dtype)

    def observables(chi, lmbd):
        # composed of module-level jitted executors (zi/zij/m-terms and the
        # segment reduce below) — repeat calls on same shapes share compiles
        return _union_observables_exec(
            zi_fn(chi, lmbd), zij_fn(chi), mterm_fn(chi),
            lmbd, node_gid, edge_gid, n_iso_v, n_tot_v, G,
            eps_clamp=float(config.eps_clamp),
        )

    def chi_init():
        return (
            data.init_messages(seed) if chi0 is None
            else jnp.asarray(chi0, data.dtype)
        )

    def ladder_fn(lam, chi, ck, meta, xtra, prev_rows=None):
        return _run_ladder(
            lam, chi, data.dtype,
            set_leaves=set_leaves,
            fixed_point=fixed_point,
            observe=observables,
            eps=config.eps,
            stop_fn=stop_fn,
            checkpointer=ck,
            checkpoint_meta=meta,
            checkpoint_extra_arrays=xtra,
            verbose=verbose,
            plateau_eps=config.plateau_eps,
            plateau_patience=config.plateau_patience,
            prev_rows=prev_rows,
        )

    if managed:
        rows, nonconverged, chi = _run_managed_ladder(
            checkpoint_path, checkpoint_interval_s,
            id_key="union_id", id_value=union_id, what="union-ensemble",
            lambdas=lambdas, stop_fn=stop_fn, chi_init=chi_init,
            dtype=data.dtype, ladder_fn=ladder_fn, base_meta={"seed": seed},
            extra_arrays={"edge_gid": edge_gid_np},
        )
    else:
        rows, nonconverged, chi = _ladder_rows(ladder_fn(
            np.asarray(lambdas, float), chi_init(), checkpointer,
            {"seed": seed}, {"edge_gid": edge_gid_np},
        ))

    return UnionEnsembleEntropyResult(
        **rows,
        nonconverged=nonconverged,
        chi=np.asarray(chi),
        edge_gid=edge_gid_np,
    )


class _GridCheckpointAdapter:
    """Injects grid coordinates into the per-sweep checkpoint metadata (so a
    resumed run knows which (deg, rep, λ) cell to continue from) and the
    grid result arrays into the payload (so completed cells survive the
    restart). ``extra_arrays`` holds live references — the driver mutates
    the grids in place, so each save captures their current state."""

    def __init__(self, checkpointer, extra_meta: dict, extra_arrays: dict):
        self._ck = checkpointer
        self._extra = extra_meta
        self._extra_arrays = extra_arrays
        self.ckpt = checkpointer.ckpt

    def due(self) -> bool:
        return self._ck.due()

    def maybe_save(self, arrays, meta) -> bool:
        return self._ck.maybe_save(
            {**arrays, **self._extra_arrays}, {**meta, **self._extra}
        )

    def save_now(self, arrays, meta) -> bool:
        """Shutdown snapshot: same coordinate/grid injection, no interval
        gate — the restored cell must know which (deg, rep, λ) it was."""
        return self._ck.save_now(
            {**arrays, **self._extra_arrays}, {**meta, **self._extra}
        )


class EntropyGridResult(NamedTuple):
    """The notebook driver's result grids (`ipynb:484-492`)."""

    deg: np.ndarray            # mean-degree grid
    ent: np.ndarray            # [deg, rep, λ]
    m_init: np.ndarray
    ent1: np.ndarray
    nodes_isolated: np.ndarray  # [deg, rep]
    mean_degrees: np.ndarray
    max_degrees: np.ndarray
    mean_degrees_total: np.ndarray
    counts: np.ndarray          # [deg, rep] — the λ at which BP failed to
                                # converge, or 0 (the reference's `counts`,
                                # `ipynb:429-431`)
    n_lambda: np.ndarray | None = None
                                # [deg, rep] — number of λ ladder points
                                # actually visited (early exits leave the
                                # tail untouched); the explicit mask for
                                # grid averaging, instead of inferring
                                # visitedness from exact-zero sentinels.
                                # None on grids built by pre-r4 callers


def _next_cell_after(cell, num_rep: int):
    """The (deg, rep) cell after ``cell`` in grid iteration order."""
    di, rep = cell
    return (di, rep + 1) if rep + 1 < num_rep else (di + 1, 0)


def _load_grid_resume(checkpoint_path, grid_id, grids, lambdas, max_sweeps):
    """Load + normalize an entropy-grid snapshot into ``(start_cell,
    resume_cells, done_cells)`` — the ONE reader both execution paths use.

    Two writer formats, interchangeable by construction:

    - the SERIAL in-flight-cell format (``deg_index``/``rep``/
      ``lmbd_offset`` + the cell's λ-segment arrays + ``chi``) written by
      the per-cell ladder's :class:`_GridCheckpointAdapter`;
    - the GROUPED format (``cells`` = per-in-flight-cell ``[di, rep,
      visited, failed]`` + per-cell ``chi_<di>_<rep>`` arrays +
      ``done_cells``), which ALSO carries the serial keys for its first
      in-flight cell, so a ``group_size=0`` rerun can resume a grouped
      snapshot (and vice versa — per-cell results depend only on the cell
      seed and its λ cursor, so regrouping cannot change them).
    """
    from graphdyn.utils.io import load_validated

    loaded = load_validated(checkpoint_path, "grid_id", grid_id,
                            "entropy grid")
    if loaded is None:
        return (0, 0), {}, set()
    arrays, meta = loaded
    for key, arr in grids.items():
        if key in arrays:
            arr[:] = arrays[key]
    resume: dict = {}
    done: set = set()
    ent1 = grids["grid_ent1"]
    if "cells" in meta:
        start = tuple(int(v) for v in meta["next_cell"])
        for di, rep, vis, failed in meta["cells"]:
            di, rep, vis = int(di), int(rep), int(vis)
            if vis < 1:
                continue                      # never visited: cold start
            resume[(di, rep)] = {
                "chi": arrays[f"chi_{di}_{rep}"],
                "visited": vis,
                "last_lmbd": float(lambdas[vis - 1]),
                "last_e1": float(ent1[di, rep, vis - 1]),
                "failed": bool(failed),
            }
        for di, rep in meta.get("done_cells", []):
            done.add((int(di), int(rep)))
    else:
        start = (int(meta["deg_index"]), int(meta["rep"]))
        # the interrupted cell: λ points [k_off, k_off+seg) of the ladder
        # live in the sweep-local arrays; earlier segments of a
        # twice-interrupted cell are already in the grid rows
        k_off = int(meta.get("lmbd_offset", 0))
        seg = int(arrays["lambdas"].size)
        sl = slice(k_off, k_off + seg)
        grids["grid_ent"][start[0], start[1], sl] = arrays["ent"]
        grids["grid_m_init"][start[0], start[1], sl] = arrays["m_init"]
        ent1[start[0], start[1], sl] = arrays["ent1"]
        if "grid_sweeps" in grids:
            # keep the restored cell's per-λ sweep counts truthful for any
            # later grouped snapshot's compat "sweeps" segment
            grids["grid_sweeps"][start[0], start[1], sl] = arrays["sweeps"]
        resume[start] = {
            "chi": arrays["chi"],
            "visited": k_off + seg,
            "last_lmbd": float(arrays["lambdas"][-1]),
            "last_e1": float(arrays["ent1"][-1]),
            # the recorded flag, not a sweeps>=max inference — a fixed
            # point that converges on exactly the last allowed sweep is
            # NOT a failure (legacy snapshots without the flag fall back
            # to the inference)
            "failed": bool(meta.get(
                "failed", int(arrays["sweeps"][-1]) >= max_sweeps,
            )),
        }
    return start, resume, done


def entropy_grid(
    n: int,
    deg_grid: np.ndarray,
    config: EntropyConfig | None = None,
    *,
    seed: int = 0,
    graph_method: str = "numpy",
    verbose: bool = False,
    save_path: str | None = None,
    checkpoint_path: str | None = None,
    checkpoint_interval_s: float = 30.0,
    class_bucket: int | None = 64,
    prefetch: int = 2,
    group_size: int | None = None,
    kernel: str = "auto",
) -> EntropyGridResult:
    """The notebook's full experiment driver: deg-grid × repetitions × λ
    ladder on fresh ER instances (`ipynb:496-513`); ``save_path`` persists
    the result grids npz-style (the commented save at `ipynb:515`).

    ``group_size`` selects the execution pipeline (ARCHITECTURE.md
    "Ensemble pipeline"). Default (None → ``min(cells, 8)``): the grid's
    (deg, rep) cells advance through their λ-ladders ``group_size`` at a
    time as ONE vmapped device program over stacked ragged BDCM tables
    (:mod:`graphdyn.pipeline.entropy_group`) — the ladder is sequential in
    λ but embarrassingly parallel across cells; each cell keeps its own λ
    cursor, warm-start chi, and early exits, frozen by an active mask once
    stopped. Element-wise identical to the serial loop (one shared program
    family — ``entropy_sweep`` runs the G=1 instance). ``group_size=0``
    forces the legacy serial cell loop.

    ``kernel`` selects the sweep core for both paths
    (``'auto'``/``'xla'``/``'pallas'``, ARCHITECTURE.md "Kernel
    selection"): on TPU the default runs each qualifying degree class
    through the fused grouped Pallas kernel with the cell axis as a
    Pallas grid dimension; grouped == serial still holds bit-exactly
    within a mode (one program family), while Pallas-vs-XLA is the
    documented ~1e-3 tolerance mode.

    ``prefetch`` overlaps the host-side ER sampling (and, grouped, the
    BDCM table builds) of upcoming grid cells with the current cells'
    device sweeps (a bounded background thread — 0 disables it). Each
    cell's graph depends only on its ``seed + 1000·di + rep``, so the
    overlap cannot change results. For device-batched ER ensembles of a
    single degree use :func:`entropy_ensemble_union` (the ``--union`` CLI
    path).

    ``checkpoint_path`` enables time-triggered intermediate saves every
    ``checkpoint_interval_s`` seconds (the notebook's ``saving_time=30``
    sketch, `ipynb:439-445,475-476`) — **and exact resume**: a rerun
    pointing at an existing checkpoint restores every completed grid cell,
    re-enters each interrupted cell at its first unvisited λ with its
    saved warm-start chi (λ-granular — exactly the state the
    uninterrupted run would carry, so the continuation is bit-exact), and
    refuses a checkpoint whose run identity (n, grid, config, seed,
    sampler) mismatches. Snapshots are interchangeable between the serial
    and grouped paths and across group sizes (see :func:`_load_grid_resume`).
    Fitting, given that the reference notebook's own stored run ends in a
    KeyboardInterrupt (`ipynb:47-49`). The file is removed on completion."""
    config = config or EntropyConfig()
    dyn = config.dynamics
    lambdas = lambda_ladder(config)
    L = lambdas.size
    D, Rr = len(deg_grid), config.num_rep
    if group_size is None:
        group_size = min(max(D * Rr, 1), 8)

    ent = np.zeros((D, Rr, L))
    m_init = np.zeros((D, Rr, L))
    ent1 = np.zeros((D, Rr, L))
    nodes_isolated = np.zeros((D, Rr))
    mean_degrees = np.zeros((D, Rr))
    max_degrees = np.zeros((D, Rr))
    mean_degrees_total = np.zeros((D, Rr))
    counts = np.zeros((D, Rr))
    n_lambda = np.zeros((D, Rr), np.int64)
    sweeps_grid = np.zeros((D, Rr, L), np.int64)    # snapshot payloads only
    grids = {
        "grid_ent": ent, "grid_m_init": m_init, "grid_ent1": ent1,
        "grid_counts": counts, "grid_nodes_isolated": nodes_isolated,
        "grid_mean_degrees": mean_degrees, "grid_max_degrees": max_degrees,
        "grid_mean_degrees_total": mean_degrees_total,
        "grid_n_lambda": n_lambda,
        # persisted so a twice-interrupted grouped run's compat "sweeps"
        # segment stays truthful across resumes (serial-written snapshots
        # predate this key; the loader's `if key in arrays` guard copes)
        "grid_sweeps": sweeps_grid,
    }

    checkpointer = None
    grid_id = None
    resume_cells: dict = {}
    done_cells: set = set()
    start_cell = (0, 0)
    if checkpoint_path is not None:
        from graphdyn.utils.io import PeriodicCheckpointer, run_fingerprint

        grid_id = run_fingerprint(
            n, np.asarray(deg_grid, float), config, seed, graph_method,
            class_bucket,
        )
        start_cell, resume_cells, done_cells = _load_grid_resume(
            checkpoint_path, grid_id, grids, lambdas, config.max_sweeps,
        )
        checkpointer = PeriodicCheckpointer(
            checkpoint_path, interval_s=checkpoint_interval_s
        )

    # resume cells that had already stopped (failed / entropy floor / full
    # ladder): record and retire them before any execution
    for cell, rc in list(resume_cells.items()):
        if rc["failed"] or rc["last_e1"] < config.ent_floor \
                or rc["visited"] >= L:
            di, rep = cell
            if rc["failed"]:
                counts[di, rep] = rc["last_lmbd"]
            n_lambda[di, rep] = rc["visited"]
            done_cells.add(cell)
            del resume_cells[cell]

    from graphdyn.pipeline.prefetch import HostPrefetcher

    pending = [
        (di, rep)
        for di in range(D) for rep in range(Rr)
        if (di, rep) >= start_cell and (di, rep) not in done_cells
    ]

    def cell_stats(g, di, rep):
        live = g.deg[g.deg > 0]
        nodes_isolated[di, rep] = g.n - live.size
        mean_degrees[di, rep] = live.mean() if live.size else 0.0
        max_degrees[di, rep] = g.deg.max(initial=0)
        mean_degrees_total[di, rep] = g.deg.mean()

    if group_size == 0:
        # legacy serial cell loop: one warm-started ladder at a time
        def build_cell(ci):
            di, rep = pending[ci]
            return erdos_renyi_graph(
                n, deg_grid[di] / (n - 1), seed=seed + 1000 * di + rep,
                method=graph_method,
            )

        with HostPrefetcher(build_cell, range(len(pending)),
                            depth=prefetch) as pf:
            for ci, (di, rep) in enumerate(pending):
                gseed = seed + 1000 * di + rep
                g = pf.get(ci)
                cell_stats(g, di, rep)
                rc = resume_cells.get((di, rep))
                k0 = rc["visited"] if rc is not None else 0
                chi0 = rc["chi"] if rc is not None else None

                ck = None
                if checkpointer is not None:
                    ck = _GridCheckpointAdapter(
                        checkpointer,
                        {"deg_index": di, "rep": rep, "lmbd_offset": k0,
                         "grid_id": grid_id},
                        grids,
                    )
                res = entropy_sweep(
                    g, config, seed=gseed, lambdas=lambdas[k0:], chi0=chi0,
                    verbose=verbose, checkpointer=ck,
                    class_bucket=class_bucket, kernel=kernel,
                    # restored prefix rows keep the plateau streak (if
                    # enabled) identical to an uninterrupted run's
                    prev_rows=(m_init[di, rep, :k0], ent1[di, rep, :k0])
                    if k0 > 0 else None,
                )
                k = res.lambdas.size
                sl = slice(k0, k0 + k)
                ent[di, rep, sl] = res.ent
                m_init[di, rep, sl] = res.m_init
                ent1[di, rep, sl] = res.ent1
                sweeps_grid[di, rep, sl] = res.sweeps
                counts[di, rep] = res.nonconverged
                n_lambda[di, rep] = k0 + k
    else:
        from graphdyn.pipeline.entropy_group import (
            EntropyCellExec, run_cell_ladder,
        )
        from graphdyn.pipeline.groups import group_ranges

        def build_group_cell(ci):
            # everything that depends only on the cell coordinates, so the
            # prefetch thread can run it ahead: ER sample + BDCM tables
            di, rep = pending[ci]
            g = erdos_renyi_graph(
                n, deg_grid[di] / (n - 1), seed=seed + 1000 * di + rep,
                method=graph_method,
            )
            sub, n_iso = remove_isolates(g)
            data = BDCMData(
                sub, p=dyn.p, c=dyn.c, attr_value=dyn.attr_value,
                rule=dyn.rule, tie=dyn.tie, class_bucket=class_bucket,
                dtype=config.dtype,
            )
            return g, data, n_iso

        with HostPrefetcher(build_group_cell, range(len(pending)),
                            depth=prefetch) as pf:
            for ks in group_ranges(0, len(pending), group_size):
                items = [pf.get(ci) for ci in ks]
                cellmap = [pending[ci] for ci in ks]
                cells, k0s, chis, prevs = [], [], [], []
                for (di, rep), (g, data, n_iso) in zip(cellmap, items):
                    cell_stats(g, di, rep)
                    cells.append((data, g.n, n_iso))
                    rc = resume_cells.get((di, rep))
                    k0 = rc["visited"] if rc is not None else 0
                    if k0 > 0:
                        # the restored prefix counts as visited even when
                        # the cell exits immediately (plateau in prefix)
                        n_lambda[di, rep] = k0
                    k0s.append(k0)
                    chis.append(
                        np.asarray(rc["chi"])
                        if rc is not None
                        else np.asarray(
                            data.init_messages(seed + 1000 * di + rep)
                        )
                    )
                    prevs.append(
                        (m_init[di, rep, :k0], ent1[di, rep, :k0])
                        if k0 > 0 else None
                    )
                ex = EntropyCellExec(
                    cells, config, group_size=group_size, kernel=kernel
                )

                def record(gi, kk, lmv, phi, m0, e1, sw, failed,
                           _cm=cellmap):
                    di, rep = _cm[gi]
                    ent[di, rep, kk] = phi
                    m_init[di, rep, kk] = m0
                    ent1[di, rep, kk] = e1
                    sweeps_grid[di, rep, kk] = sw
                    n_lambda[di, rep] = kk + 1
                    if failed:
                        counts[di, rep] = lmv

                def boundary(stopping, info, _cm=cellmap):
                    if checkpointer is None or not (
                        stopping or checkpointer.due()
                    ):
                        return
                    inflight = sorted(info, key=lambda d_: _cm[d_["g"]])
                    visited = [d_ for d_ in inflight if d_["visited"] >= 1]
                    if inflight and not visited:
                        # nothing recorded yet for any in-flight cell: a
                        # snapshot would carry no resumable state beyond
                        # the previous one — skip (cold starts re-derive)
                        return
                    if inflight:
                        next_cell = _cm[inflight[0]["g"]]
                        # serial-FORMAT keys describing the FIRST in-flight
                        # cell (== next_cell, so they can never point past
                        # a still-running earlier cell). They are
                        # DIAGNOSTIC legibility only — resume interop, in
                        # both directions, goes through
                        # _load_grid_resume's normalized "cells" branch,
                        # never through these keys
                        lead = inflight[0]
                        di0, rep0 = next_cell
                        vis0 = lead["visited"]
                    else:
                        # the whole group retired at this boundary: mark
                        # the next grid cell and keep the last group cell
                        # as the (complete) serial-compat in-flight record
                        next_cell = _next_cell_after(max(_cm), Rr)
                        di0, rep0 = max(_cm)
                        vis0 = int(n_lambda[di0, rep0])
                        lead = None
                    arrays = dict(grids)
                    for d_ in inflight:
                        di, rep = _cm[d_["g"]]
                        arrays[f"chi_{di}_{rep}"] = d_["chi"]
                    arrays["chi"] = (
                        lead["chi"] if lead is not None
                        else arrays[f"chi_{di0}_{rep0}"]
                        if f"chi_{di0}_{rep0}" in arrays else
                        np.zeros((0,), np.float32)
                    )
                    arrays["lambdas"] = lambdas[:vis0]
                    arrays["ent"] = ent[di0, rep0, :vis0].copy()
                    arrays["m_init"] = m_init[di0, rep0, :vis0].copy()
                    arrays["ent1"] = ent1[di0, rep0, :vis0].copy()
                    arrays["sweeps"] = sweeps_grid[di0, rep0, :vis0].copy()
                    inflight_set = {_cm[d_["g"]] for d_ in inflight}
                    known_done = done_cells | (set(_cm) - inflight_set)
                    meta = {
                        "grid_id": grid_id,
                        "deg_index": di0, "rep": rep0, "lmbd_offset": 0,
                        "lmbd": (lead["lmbd"] if lead is not None
                                 else float(lambdas[max(vis0 - 1, 0)])),
                        "failed": bool(lead["failed"]) if lead is not None
                        else bool(counts[di0, rep0]),
                        "next_cell": list(next_cell),
                        "cells": [
                            [*_cm[d_["g"]], d_["visited"],
                             bool(d_["failed"])]
                            for d_ in visited
                        ],
                        "done_cells": sorted(
                            [list(c) for c in known_done
                             if c >= next_cell]
                        ),
                    }
                    if stopping:
                        checkpointer.save_now(arrays, meta)
                    else:
                        checkpointer.maybe_save(arrays, meta)

                run_cell_ladder(
                    ex, chis, lambdas,
                    eps=config.eps, ent_floor=config.ent_floor,
                    k0=k0s, plateau_eps=config.plateau_eps,
                    plateau_patience=config.plateau_patience,
                    prev_rows=prevs, record=record,
                    # no callback at all without checkpointing: the runner
                    # keys its per-boundary chi device→host captures off
                    # `boundary is not None`, and an uncheckpointed run
                    # must not pay one [2E, K, K] transfer per cell per λ
                    boundary=boundary if checkpointer is not None else None,
                    verbose=verbose,
                )
                done_cells.update(cellmap)

    out = EntropyGridResult(
        deg=np.asarray(deg_grid),
        ent=ent,
        m_init=m_init,
        ent1=ent1,
        nodes_isolated=nodes_isolated,
        mean_degrees=mean_degrees,
        max_degrees=max_degrees,
        mean_degrees_total=mean_degrees_total,
        counts=counts,
        n_lambda=n_lambda,
    )
    if save_path:
        from graphdyn.utils.io import save_results_npz

        save_results_npz(save_path, **out._asdict())
    # remove the checkpoint only after the results are durably persisted —
    # a failed final save must leave the checkpoint for another resume
    if checkpointer is not None:
        checkpointer.remove()
    return out
