"""Forward opinion-consensus experiment: which initial magnetizations m(0)
flow to consensus, and how fast.

This is the forward-dynamics side of the thesis question (SURVEY.md §0.3):
the reference quantifies the attractor landscape via BDCM entropy curves
(`ER_BDCM_entropy.ipynb:113-123` — the biased-initialization axis) and
searches initializations with SA/HPr; this driver measures the phenomenon
those curves predict, directly, with the bit-packed replica kernel — sweep
m(0), record the fraction of replicas reaching consensus, the first-passage
time, and the final magnetization.

Everything device-resident: biased packed draw, chunked consensus scan in
one jitted `lax.while_loop` (`graphdyn.ops.packed.packed_consensus_scan`),
per-point host traffic limited to a handful of scalars per replica.

Two consensus notions are tracked per replica (both returned):

- ``strict``: the absorbing homogeneous state, all spins equal — blocked on
  sparse ER at an O(1) rate by frozen/blinking small components (a pair of
  degree-1 nodes locked opposite, say), i.e. by component statistics rather
  than the dynamics under study;
- ``near``: |m_final| ≥ 1 − near_eps (default 0.99) — the giant component
  has consensed; robust to those small components.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np


def er_consensus_ensemble(n: int, c: float = 6.0, seed: int = 0):
    """The standard opinion-dynamics ensemble — ER G(n, c/n) with isolates
    removed, mirroring the reference's analytic isolate treatment
    (`ER_BDCM_entropy.ipynb:283-291`). Returns
    ``(graph, n_isolates, nbr_device, deg_device)``; the device tables are
    uploaded exactly once for a whole sweep."""
    import jax.numpy as jnp

    from graphdyn.graphs import erdos_renyi_graph, remove_isolates

    g, n_iso = remove_isolates(erdos_renyi_graph(n, c / n, seed=seed))
    return g, n_iso, jnp.asarray(g.nbr), jnp.asarray(g.deg)


def rrg_consensus_ensemble(n: int, d: int = 4, seed: int = 0):
    """RRG variant of :func:`er_consensus_ensemble` — the SA search's own
    graph ensemble (`SA_RRG.py:45-46`: random d-regular), for measuring the
    RANDOM-initialization consensus threshold that the SA/HPr-constructed
    initializations beat. No isolates by construction. Returns the same
    ``(graph, 0, nbr_device, deg_device)`` tuple shape."""
    import jax.numpy as jnp

    from graphdyn.graphs import random_regular_graph

    g = random_regular_graph(n, d, seed=seed)
    return g, 0, jnp.asarray(g.nbr), jnp.asarray(g.deg)


def consensus_point(g, R: int, m0: float, max_steps: int, chunk: int = 10,
                    seed: int = 1000, nbr_dev=None, deg_dev=None,
                    rule: str = "majority", tie: str = "stay",
                    near_eps: float = 0.01, mesh=None) -> dict:
    """One m(0) point: biased device-resident init, chunked consensus scan,
    per-replica statistics reduced to a plain dict. Callers sweeping many
    points pass ``nbr_dev``/``deg_dev`` once — re-uploading the multi-MB
    neighbor table per point is tunnel traffic the TPU link cannot
    sustain.

    ``mesh`` (any 1-axis jax Mesh) shards the packed WORD axis across
    devices: every gather in the scan indexes the node axis, so each
    device rolls its own 32·(W/n_dev) replicas with zero per-step
    collectives — GSPMD inserts only the tiny [W]-flag reductions for the
    early-exit test. The biased draw lands directly in the sharding and is
    seed-deterministic, so sharded and unsharded runs are bit-identical
    (tested)."""
    import jax.numpy as jnp

    from graphdyn.ops.packed import draw_packed_biased, packed_consensus_scan

    W = -(-R // 32)
    out_shardings = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        (axis,) = mesh.axis_names
        if W % mesh.devices.size:
            raise ValueError(
                f"the mesh size {mesh.devices.size} must divide the packed "
                f"word count W={W} (R={R}): each device owns whole words "
                "(32 replicas each)"
            )
        out_shardings = NamedSharding(mesh, PartitionSpec(None, axis))
    from graphdyn import obs

    sp = draw_packed_biased(seed, g.n, W, m0, out_shardings=out_shardings)
    nbr_dev = jnp.asarray(g.nbr) if nbr_dev is None else nbr_dev
    deg_dev = jnp.asarray(g.deg) if deg_dev is None else deg_dev
    # per-segment rollout span: one m(0) point = one chunked scan; the
    # gauge reports the same spin-updates/s unit bench.py's headline uses
    with obs.timed("ops.packed.scan", m0=float(m0), R=W * 32) as sw:
        out = packed_consensus_scan(
            nbr_dev, deg_dev, sp, R=W * 32, max_steps=max_steps, chunk=chunk,
            near_eps=near_eps, rule=rule, tie=tie,
        )
        steps_run = int(np.asarray(out["steps_run"]))
    if obs.enabled():
        obs.gauge("ops.rollout.rate",
                  g.n * W * 32 * steps_run / max(sw.wall_s, 1e-9),
                  solver="consensus", m0=float(m0), steps=steps_run)
        # device-memory gauges after the (possibly mesh-sharded) rollout
        # scan — the packed spin state is the byte model's packed_state row
        obs.memband.emit_memory_gauges(loop="consensus.scan", m0=float(m0))
    near = np.asarray(out["near"])[:R]
    near_step = np.asarray(out["near_step"])[:R]
    m_final = np.asarray(out["m_final"])[:R]
    n_near = int(near.sum())
    return {
        "m0": float(m0),
        "consensus_fraction": n_near / R,
        "strict_fraction": float(np.asarray(out["strict"])[:R].mean()),
        "mean_steps_to_consensus": (
            float(near_step[near].mean()) if n_near else None
        ),
        "mean_abs_m_final": float(np.abs(m_final).mean()),
        "max_steps": int(max_steps),
        "step_resolution": int(chunk),
        "replicas": int(R),
    }


def consensus_curve_ensemble(n: int, R: int, m0_list: Sequence[float],
                             max_steps: int, *, c: float = 6.0,
                             graph: str = "er", d: int = 4,
                             graph_seeds: Sequence[int] = (0, 1, 2),
                             chunk: int = 10, rule: str = "majority",
                             tie: str = "stay", near_eps: float = 0.01,
                             mesh=None, progress=None):
    """The consensus curve over an ENSEMBLE of graph instances: one
    :func:`consensus_curve` per graph seed, plus per-m(0) aggregates
    (mean and instance spread) — the same instance-spread discipline as
    the entropy golden anchors. ``graph`` picks the ensemble: ``"er"``
    (G(n, c/n), isolates removed) or ``"rrg"`` (d-regular — the SA
    search's ensemble). Returns ``(per_seed, aggregate)`` where
    ``per_seed`` is a list of {graph_seed, n, isolates_removed, rows} and
    ``aggregate`` one row per m(0) with mean/std/min/max of the consensus
    fraction and the mean first-passage over instances."""
    per_seed = []
    for s in graph_seeds:
        if graph == "er":
            g, n_iso, nbr_dev, deg_dev = er_consensus_ensemble(n, c=c, seed=s)
        elif graph == "rrg":
            g, n_iso, nbr_dev, deg_dev = rrg_consensus_ensemble(n, d=d, seed=s)
        else:
            raise ValueError(f"graph must be 'er' or 'rrg', got {graph!r}")
        rows = consensus_curve(
            g, R, m0_list, max_steps, chunk, nbr_dev=nbr_dev,
            deg_dev=deg_dev, rule=rule, tie=tie, near_eps=near_eps,
            mesh=mesh, graph_seed=s,
            progress=(lambda pt, s=s: progress(s, pt)) if progress else None,
        )
        per_seed.append({"graph_seed": int(s), "n": g.n,
                         "isolates_removed": n_iso, "rows": rows})
    aggregate = []
    for j, m0 in enumerate(m0_list):
        fr = np.array([ps["rows"][j]["consensus_fraction"]
                       for ps in per_seed])
        steps = [ps["rows"][j]["mean_steps_to_consensus"]
                 for ps in per_seed]
        steps = [x for x in steps if x is not None]
        aggregate.append({
            "m0": float(m0),
            "consensus_fraction_mean": float(fr.mean()),
            # None (not 0.0) for a single instance: no spread was MEASURED,
            # and the plotter keys its error-bar branch on this
            "consensus_fraction_std": float(fr.std(ddof=1))
            if len(fr) > 1 else None,
            "consensus_fraction_min": float(fr.min()),
            "consensus_fraction_max": float(fr.max()),
            "mean_steps_to_consensus": (float(np.mean(steps))
                                        if steps else None),
            "instances": len(per_seed),
            # alias for single-run consumers (collector, plotter)
            "consensus_fraction": float(fr.mean()),
        })
    return per_seed, aggregate


def consensus_ensemble_doc(n: int, per_seed: list[dict],
                           aggregate: list[dict], *, c: float = 6.0,
                           rule: str = "majority", tie: str = "stay",
                           near_eps: float = 0.01,
                           kind: str = "erdos_renyi", d: int | None = None,
                           **extra) -> dict:
    """Artifact schema for a multi-instance sweep: ``rows`` carries the
    per-m(0) aggregates (with instance spread), ``per_seed`` the raw
    curves. Same top-level keys the session collector reads; same
    kind/d provenance axis as :func:`consensus_doc`."""
    import jax

    ens = "ER" if kind == "erdos_renyi" else f"RRG-d{d}"
    return {
        "what": (f"{ens}-{rule} consensus fraction & first-passage vs "
                 f"m(0), {len(per_seed)}-instance ensemble"),
        # n = REQUESTED size; per-instance post-isolate sizes alongside so
        # tooling never compares pre- vs post-isolate counts (the
        # single-run doc records the post-isolate g.n)
        "graph": {"kind": kind, "n": n,
                  **({"c": c} if kind == "erdos_renyi" else {"d": d}),
                  "graph_seeds": [ps["graph_seed"] for ps in per_seed],
                  "n_kept": [ps["n"] for ps in per_seed],
                  "isolates_removed": [ps["isolates_removed"]
                                       for ps in per_seed]},
        "dynamics": {"rule": rule, "tie": tie,
                     "update": "parallel/synchronous"},
        "near_consensus_def": f"|m_final| >= {1.0 - near_eps:g}",
        "backend": jax.default_backend(),
        "rows": aggregate,
        "per_seed": per_seed,
        **extra,
    }


def m_half(aggregate: Sequence[dict]):
    """The half-consensus bias: first upward 0.5-crossing of the mean
    consensus fraction over an aggregate curve (linear interpolation in
    m0). None when the curve starts at/above 0.5 (the crossing is below
    the grid — e.g. a fluctuation baseline) or never crosses. The ONE
    definition of the m_c observable, shared by the FSS and phase-sweep
    capture scripts."""
    m0s = [r["m0"] for r in aggregate]
    fr = [r["consensus_fraction_mean"] for r in aggregate]
    if fr and fr[0] >= 0.5:
        return None
    for j in range(1, len(fr)):
        if fr[j - 1] < 0.5 <= fr[j]:
            t = (0.5 - fr[j - 1]) / (fr[j] - fr[j - 1])
            return m0s[j - 1] + t * (m0s[j] - m0s[j - 1])
    return None


def consensus_doc(g, n_iso: int, rows: list[dict], *, c: float = 6.0,
                  seed: int = 0, rule: str = "majority", tie: str = "stay",
                  near_eps: float = 0.01, kind: str = "erdos_renyi",
                  d: int | None = None, **extra) -> dict:
    """The one artifact schema for a consensus sweep — shared by the CLI
    and `scripts/physics_consensus.py` so the two writers cannot drift
    (the session collector reads ``backend`` from this doc)."""
    import jax

    ens = "ER" if kind == "erdos_renyi" else f"RRG-d{d}"
    return {
        "what": f"{ens}-{rule} consensus fraction & first-passage vs m(0)",
        "graph": {"kind": kind, "n": g.n,
                  **({"c": c} if kind == "erdos_renyi" else {"d": d}),
                  "isolates_removed": n_iso, "seed": seed},
        "dynamics": {"rule": rule, "tie": tie,
                     "update": "parallel/synchronous"},
        "near_consensus_def": f"|m_final| >= {1.0 - near_eps:g}",
        "backend": jax.default_backend(),
        "rows": rows,
        **extra,
    }


def draw_seed(graph_seed: int, k: int) -> int:
    """The replica-draw seed for curve point ``k`` on graph instance
    ``graph_seed``: both coordinates folded through a SeedSequence (stable,
    platform-independent mixing — NOT Python's process-randomized
    ``hash``), so every (instance, point) pair draws an independent initial
    replica set. The pre-fix derivation (``1000 + k`` alone) gave every
    ensemble instance the SAME initial spins at each m(0) — instance
    spread was graph-only, under-measuring the replica noise."""
    return int(np.random.SeedSequence([int(graph_seed), 1000 + int(k)])
               .generate_state(1)[0])


def consensus_curve(g, R: int, m0_list: Sequence[float], max_steps: int,
                    chunk: int = 10, nbr_dev=None, deg_dev=None,
                    rule: str = "majority", tie: str = "stay",
                    near_eps: float = 0.01, mesh=None,
                    progress=None, graph_seed: int = 0) -> list[dict]:
    """The m(0)→consensus curve as a list of row dicts (one per m(0); the
    replica-draw seed folds ``(graph_seed, k)`` via :func:`draw_seed`, so
    points are independent of each other AND of other ensemble instances).
    ``progress`` is an optional per-row callback (e.g. a print); ``mesh``
    word-shards every point (see :func:`consensus_point`)."""
    rows = []
    for k, m0 in enumerate(m0_list):
        pt = consensus_point(
            g, R, m0, max_steps, chunk, seed=draw_seed(graph_seed, k),
            nbr_dev=nbr_dev, deg_dev=deg_dev, rule=rule, tie=tie,
            near_eps=near_eps, mesh=mesh,
        )
        rows.append(pt)
        if progress is not None:
            progress(pt)
    return rows
