"""History-Passing reinforcement (HPr) — the reinforced-BP solver (L5).

Reproduces the reference's HPr loop (`HPR_pytorch_RRG.py:342-356`): iterate
the bias-weighted BDCM sweep, compute node marginals, reinforce per-node
biases toward the marginal winner with probability ``1−(1+t)^{−γ}``
("cedrics paper, eq. (24)" per the comment at `HPR:135`), read off the trial
solution ``s = argmax bias``, and stop when ``s`` flows to the all-+1
attractor under the (p,c) rollout, or after ``TT`` sweeps (sentinel
``m_final = 2``, `HPR:355`).

TPU-first redesign (SURVEY.md §3.2): the reference crosses the host/device
boundary every DP combo via string-parsing ``order_gpu`` (`HPR:46-61`) and
scalar ``A_i_sums`` calls; here the entire iteration — sweep, marginals,
reinforcement, rollout stop-test — is ONE jitted ``lax.while_loop`` body with
table-driven factor tensors; zero host round-trips until the loop exits.

Faithful quirk-preservation (capabilities stay, accidents go — SURVEY §7):
the λ-tilt is ``exp(−λ_eff·x_i(0))`` with λ_eff = ``lmbd_in/n`` = 25
(`HPR:231,39`); the DP does *not* mask invalid-endpoint source trajectories
(unlike the entropy sweep) — their chi entries decay under damping instead;
marginals are ε-clamped at 1e-15 (`HPR:147`). The hard-coded `.to('cuda')`
(`HPR:347`) and CPU-side ``torch.rand`` mask (`HPR:142`) are bugs, not
capabilities, and are not reproduced.
"""

from __future__ import annotations

import time
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from graphdyn.config import HPRConfig
from graphdyn.graphs import Graph, build_edge_tables
from graphdyn.ops.bdcm import BDCMData, make_marginals, make_sweep
from graphdyn.ops.dynamics import batched_rollout_impl, rule_coefficients


class HPRResult(NamedTuple):
    s: np.ndarray            # int8[n] — trial solution at stop
    mag_reached: np.ndarray  # f32 scalar — m(s) at stop (`HPR:359`)
    num_steps: int           # sweeps taken (`HPR:360`)
    m_final: float           # 1.0 success, 2.0 timeout sentinel
    biases: np.ndarray       # f32[n, 2] — final reinforcement biases
    chi: np.ndarray          # final messages
    elapsed_s: float         # wall-clock seconds (`HPR:257,364` — persisted
                             # as `time` in the reference npz, `HPR:377`)


def hpr_solve(
    graph: Graph,
    config: HPRConfig | None = None,
    *,
    seed: int = 0,
    chi0=None,
) -> HPRResult:
    """Run one HPr chain on one graph instance."""
    t_start = time.perf_counter()
    config = config or HPRConfig()
    dyn = config.dynamics
    n = graph.n
    tables = build_edge_tables(graph)
    data = BDCMData(
        graph,
        tables,
        p=dyn.p,
        c=dyn.c,
        attr_value=dyn.attr_value,
        rule=dyn.rule,
        tie=dyn.tie,
    )
    sweep = make_sweep(
        data, damp=config.damp, eps_clamp=0.0, mask_invalid_src=False, with_bias=True
    )
    marginals = make_marginals(data, eps=config.eps_clamp)
    R_coef, C_coef = rule_coefficients(dyn.rule, dyn.tie)
    rollout_steps = dyn.p + dyn.c - 1

    src = jnp.asarray(tables.src.astype(np.int64))
    sel_plus = jnp.asarray(data.x0 == 1)
    nbr = jnp.asarray(graph.nbr)
    lmbd = jnp.float32(config.lmbd)
    pie = jnp.float32(config.pie)
    gamma = jnp.float32(config.gamma)
    TT = int(config.max_sweeps)

    def m_of_end(s):
        s_end_sum = (
            batched_rollout_impl(nbr, s[None], rollout_steps, R_coef, C_coef)
            .astype(jnp.int32)
            .sum()
        )
        return s_end_sum.astype(jnp.float32) / n

    def bias_to_edge(biases):
        # bias of the *source* node at its trajectory's initial value
        # (`positions_biases`, `HPR:120-133`): [2E, K]
        return jnp.where(sel_plus[None, :], biases[src, 0, None], biases[src, 1, None])

    @jax.jit
    def run(chi, biases, key):
        s0 = jnp.where(biases[:, 0] > biases[:, 1], 1, -1).astype(jnp.int8)

        def cond(st):
            _, _, _, _, t, m_final = st
            return m_final < 1.0

        def body(st):
            chi, biases, s, key, t, _ = st
            chi = sweep(chi, lmbd, bias_to_edge(biases))
            marg = marginals(chi)
            # reinforcement (`new_biases_i`, `HPR:137-145`)
            minus_wins = marg[:, 1] >= marg[:, 0]
            new_bias = jnp.where(
                minus_wins[:, None],
                jnp.array([pie, 1 - pie]),
                jnp.array([1 - pie, pie]),
            )
            key, ku = jax.random.split(key)
            u = jax.random.uniform(ku, (n,))
            update = u < 1.0 - (1.0 + t.astype(jnp.float32)) ** (-gamma)
            biases = jnp.where(update[:, None], new_bias, biases)
            s = jnp.where(biases[:, 0] > biases[:, 1], 1, -1).astype(jnp.int8)
            t = t + 1
            m_final = jnp.where(t > TT, 2.0, m_of_end(s))
            return chi, biases, s, key, t, m_final

        state = (chi, biases, s0, key, jnp.int32(0), m_of_end(s0))
        return lax.while_loop(cond, body, state)

    rng = np.random.default_rng(seed)
    if chi0 is None:
        # one stream for both draws — keeps chi and biases independent
        chi0 = data.init_messages(rng)
    biases0 = rng.random((n, 2))
    biases0 /= biases0.sum(axis=1, keepdims=True)
    key = jax.random.PRNGKey(seed)

    chi, biases, s, _, t, m_final = run(
        jnp.asarray(chi0), jnp.asarray(biases0, jnp.float32), key
    )
    s = np.asarray(s)
    return HPRResult(
        s=s,
        mag_reached=np.float32(s.astype(np.float64).mean()),
        num_steps=int(t),
        m_final=float(m_final),
        biases=np.asarray(biases),
        chi=np.asarray(chi),
        elapsed_s=time.perf_counter() - t_start,
    )


class HPREnsembleResult(NamedTuple):
    """The reference driver's per-repetition arrays
    (`HPR_pytorch_RRG.py:251-255,359-362`)."""

    mag_reached: np.ndarray  # f[n_rep]
    conf: np.ndarray         # int8[n_rep, n]
    num_steps: np.ndarray    # int[n_rep]
    graphs: np.ndarray       # int32[n_rep, n, d]
    time: np.ndarray         # f[n_rep] wall-clock seconds (`HPR:364,370`)


def hpr_ensemble(
    n: int,
    d: int,
    config: HPRConfig | None = None,
    *,
    n_rep: int = 1,
    seed: int = 0,
    graph_method: str = "pairing",
    save_path: str | None = None,
) -> HPREnsembleResult:
    """The reference's experiment driver (`HPR_pytorch_RRG.py:259-377`):
    ``n_rep`` repetitions, each on a freshly sampled RRG(n, d); pass
    ``save_path`` to persist the npz with the reference's key names
    (`HPR:377` — the only live persistence in the reference repo)."""
    from graphdyn.graphs import random_regular_graph
    from graphdyn.utils.io import save_results_npz

    config = config or HPRConfig()
    mag = np.empty(n_rep, np.float64)
    conf = np.empty((n_rep, n), np.int8)
    steps = np.empty(n_rep, np.int64)
    graphs = np.empty((n_rep, n, d), np.int32)
    times = np.empty(n_rep, np.float64)
    for k in range(n_rep):
        g = random_regular_graph(n, d, seed=seed + k, method=graph_method)
        res = hpr_solve(g, config, seed=seed + k)
        mag[k] = float(res.mag_reached)
        conf[k] = res.s
        steps[k] = res.num_steps
        graphs[k] = g.nbr
        times[k] = res.elapsed_s
    out = HPREnsembleResult(mag, conf, steps, graphs, times)
    if save_path:
        save_results_npz(
            save_path,
            mag_reached=out.mag_reached,
            conf=out.conf,
            num_steps=out.num_steps,
            graphs=out.graphs,
            time=out.time,
        )
    return out
