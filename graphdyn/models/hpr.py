"""History-Passing reinforcement (HPr) — the reinforced-BP solver (L5).

Reproduces the reference's HPr loop (`HPR_pytorch_RRG.py:342-356`): iterate
the bias-weighted BDCM sweep, compute node marginals, reinforce per-node
biases toward the marginal winner with probability ``1−(1+t)^{−γ}``
("cedrics paper, eq. (24)" per the comment at `HPR:135`), read off the trial
solution ``s = argmax bias``, and stop when ``s`` flows to the all-+1
attractor under the (p,c) rollout, or after ``TT`` sweeps (sentinel
``m_final = 2``, `HPR:355`).

TPU-first redesign (SURVEY.md §3.2): the reference crosses the host/device
boundary every DP combo via string-parsing ``order_gpu`` (`HPR:46-61`) and
scalar ``A_i_sums`` calls; here the entire iteration — sweep, marginals,
reinforcement, rollout stop-test — is ONE jitted ``lax.while_loop`` body with
table-driven factor tensors; zero host round-trips until the loop exits.

Faithful quirk-preservation (capabilities stay, accidents go — SURVEY §7):
the λ-tilt is ``exp(−λ_eff·x_i(0))`` with λ_eff = ``lmbd_in/n`` = 25
(`HPR:231,39`); the DP does *not* mask invalid-endpoint source trajectories
(unlike the entropy sweep) — their chi entries decay under damping instead;
marginals are ε-clamped at 1e-15 (`HPR:147`). The hard-coded `.to('cuda')`
(`HPR:347`) and CPU-side ``torch.rand`` mask (`HPR:142`) are bugs, not
capabilities, and are not reproduced.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from graphdyn.config import HPRConfig
from graphdyn.graphs import Graph, build_edge_tables
from graphdyn.ops.bdcm import BDCMData, make_marginals, make_sweep
from graphdyn.ops.dynamics import batched_rollout_impl, rule_coefficients


class HPRResult(NamedTuple):
    s: np.ndarray            # int8[n] — trial solution at stop
    mag_reached: np.ndarray  # f32 scalar — m(s) at stop (`HPR:359`)
    num_steps: int           # sweeps taken (`HPR:360`)
    m_final: float           # 1.0 success, 2.0 timeout sentinel
    biases: np.ndarray       # f32[n, 2] — final reinforcement biases
    chi: np.ndarray          # final messages


def hpr_solve(
    graph: Graph,
    config: HPRConfig | None = None,
    *,
    seed: int = 0,
    chi0=None,
) -> HPRResult:
    """Run one HPr chain on one graph instance."""
    config = config or HPRConfig()
    dyn = config.dynamics
    n = graph.n
    tables = build_edge_tables(graph)
    data = BDCMData(
        graph,
        tables,
        p=dyn.p,
        c=dyn.c,
        attr_value=dyn.attr_value,
        rule=dyn.rule,
        tie=dyn.tie,
    )
    sweep = make_sweep(
        data, damp=config.damp, eps_clamp=0.0, mask_invalid_src=False, with_bias=True
    )
    marginals = make_marginals(data, eps=config.eps_clamp)
    R_coef, C_coef = rule_coefficients(dyn.rule, dyn.tie)
    rollout_steps = dyn.p + dyn.c - 1

    src = jnp.asarray(tables.src.astype(np.int64))
    sel_plus = jnp.asarray(data.x0 == 1)
    nbr = jnp.asarray(graph.nbr)
    lmbd = jnp.float32(config.lmbd)
    pie = jnp.float32(config.pie)
    gamma = jnp.float32(config.gamma)
    TT = int(config.max_sweeps)

    def m_of_end(s):
        s_end_sum = (
            batched_rollout_impl(nbr, s[None], rollout_steps, R_coef, C_coef)
            .astype(jnp.int32)
            .sum()
        )
        return s_end_sum.astype(jnp.float32) / n

    def bias_to_edge(biases):
        # bias of the *source* node at its trajectory's initial value
        # (`positions_biases`, `HPR:120-133`): [2E, K]
        return jnp.where(sel_plus[None, :], biases[src, 0, None], biases[src, 1, None])

    @jax.jit
    def run(chi, biases, key):
        s0 = jnp.where(biases[:, 0] > biases[:, 1], 1, -1).astype(jnp.int8)

        def cond(st):
            _, _, _, _, t, m_final = st
            return m_final < 1.0

        def body(st):
            chi, biases, s, key, t, _ = st
            chi = sweep(chi, lmbd, bias_to_edge(biases))
            marg = marginals(chi)
            # reinforcement (`new_biases_i`, `HPR:137-145`)
            minus_wins = marg[:, 1] >= marg[:, 0]
            new_bias = jnp.where(
                minus_wins[:, None],
                jnp.array([pie, 1 - pie]),
                jnp.array([1 - pie, pie]),
            )
            key, ku = jax.random.split(key)
            u = jax.random.uniform(ku, (n,))
            update = u < 1.0 - (1.0 + t.astype(jnp.float32)) ** (-gamma)
            biases = jnp.where(update[:, None], new_bias, biases)
            s = jnp.where(biases[:, 0] > biases[:, 1], 1, -1).astype(jnp.int8)
            t = t + 1
            m_final = jnp.where(t > TT, 2.0, m_of_end(s))
            return chi, biases, s, key, t, m_final

        state = (chi, biases, s0, key, jnp.int32(0), m_of_end(s0))
        return lax.while_loop(cond, body, state)

    rng = np.random.default_rng(seed)
    if chi0 is None:
        # one stream for both draws — keeps chi and biases independent
        chi0 = data.init_messages(rng)
    biases0 = rng.random((n, 2))
    biases0 /= biases0.sum(axis=1, keepdims=True)
    key = jax.random.PRNGKey(seed)

    chi, biases, s, _, t, m_final = run(
        jnp.asarray(chi0), jnp.asarray(biases0, jnp.float32), key
    )
    s = np.asarray(s)
    return HPRResult(
        s=s,
        mag_reached=np.float32(s.astype(np.float64).mean()),
        num_steps=int(t),
        m_final=float(m_final),
        biases=np.asarray(biases),
        chi=np.asarray(chi),
    )
