"""History-Passing reinforcement (HPr) — the reinforced-BP solver (L5).

Reproduces the reference's HPr loop (`HPR_pytorch_RRG.py:342-356`): iterate
the bias-weighted BDCM sweep, compute node marginals, reinforce per-node
biases toward the marginal winner with probability ``1−(1+t)^{−γ}``
("cedrics paper, eq. (24)" per the comment at `HPR:135`), read off the trial
solution ``s = argmax bias``, and stop when ``s`` flows to the all-+1
attractor under the (p,c) rollout, or after ``TT`` sweeps (sentinel
``m_final = 2``, `HPR:355`).

TPU-first redesign (SURVEY.md §3.2): the reference crosses the host/device
boundary every DP combo via string-parsing ``order_gpu`` (`HPR:46-61`) and
scalar ``A_i_sums`` calls; here the entire iteration — sweep, marginals,
reinforcement, rollout stop-test — is ONE jitted ``lax.while_loop`` body with
table-driven factor tensors; zero host round-trips until the loop exits.

Faithful quirk-preservation (capabilities stay, accidents go — SURVEY §7):
the λ-tilt is ``exp(−λ_eff·x_i(0))`` with λ_eff = ``lmbd_in/n`` = 25
(`HPR:231,39`); the DP does *not* mask invalid-endpoint source trajectories
(unlike the entropy sweep) — their chi entries decay under damping instead;
marginals are ε-clamped at 1e-15 (`HPR:147`). The hard-coded `.to('cuda')`
(`HPR:347`) and CPU-side ``torch.rand`` mask (`HPR:142`) are bugs, not
capabilities, and are not reproduced.
"""

from __future__ import annotations

import time
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from graphdyn.config import HPRConfig
from graphdyn.graphs import Graph, build_edge_tables
from graphdyn.ops.bdcm import BDCMData, make_marginals, make_sweep
from graphdyn.ops.dynamics import batched_rollout_impl, rule_coefficients


# chain-checkpoint state fields, in state-tuple order (one constant per
# solver: the restore and the save payload cannot drift apart)
_HPR_CHAIN_FIELDS = ("chi", "biases", "s", "key", "t", "m_final")
_HPR_BATCH_FIELDS = ("chi", "biases", "s", "keys", "t", "m_final", "active",
                     "steps")


class HPRResult(NamedTuple):
    s: np.ndarray            # int8[n] — trial solution at stop
    mag_reached: np.ndarray  # f32 scalar — m(s) at stop (`HPR:359`)
    num_steps: int           # sweeps taken (`HPR:360`)
    m_final: float           # 1.0 success, 2.0 timeout sentinel
    biases: np.ndarray       # f32[n, 2] — final reinforcement biases
    chi: np.ndarray          # final messages
    elapsed_s: float         # wall-clock seconds (`HPR:257,364` — persisted
                             # as `time` in the reference npz, `HPR:377`)


class _HPRSetup(NamedTuple):
    """Shared per-graph preparation of both HPr solvers — one place carries
    the reference-faithful quirks (eps_clamp=0, unmasked invalid sources,
    the bias-to-edge gather; see the module docstring)."""

    data: BDCMData
    sweep: object
    marginals: object
    bias_to_edge: object
    m_of_end_batch: object   # int8[R, n] -> f32[R]
    lmbd: jnp.ndarray
    pie: jnp.ndarray
    gamma: jnp.ndarray
    TT: int
    n: int


def _prep(graph: Graph, config: HPRConfig, *, use_pallas="auto") -> _HPRSetup:
    dyn = config.dynamics
    n = graph.n
    tables = build_edge_tables(graph)
    data = BDCMData(
        graph, tables, p=dyn.p, c=dyn.c, attr_value=dyn.attr_value,
        rule=dyn.rule, tie=dyn.tie,
    )
    sweep = make_sweep(
        data, damp=config.damp, eps_clamp=0.0, mask_invalid_src=False,
        with_bias=True, use_pallas=use_pallas,
    )
    marginals = make_marginals(data, eps=config.eps_clamp)
    R_coef, C_coef = rule_coefficients(dyn.rule, dyn.tie)
    rollout_steps = dyn.p + dyn.c - 1

    src = jnp.asarray(tables.src.astype(np.int64))
    sel_plus = jnp.asarray(data.x0 == 1)
    nbr = jnp.asarray(graph.nbr)

    def bias_to_edge(biases):
        # bias of the *source* node at its trajectory's initial value
        # (`positions_biases`, `HPR:120-133`): [2E, K]
        return jnp.where(sel_plus[None, :], biases[src, 0, None], biases[src, 1, None])

    def m_of_end_batch(s):
        s_end = batched_rollout_impl(nbr, s, rollout_steps, R_coef, C_coef)
        return s_end.astype(jnp.int32).sum(axis=1).astype(jnp.float32) / n

    return _HPRSetup(
        data=data,
        sweep=sweep,
        marginals=marginals,
        bias_to_edge=bias_to_edge,
        m_of_end_batch=m_of_end_batch,
        lmbd=jnp.float32(config.lmbd),
        pie=jnp.float32(config.pie),
        gamma=jnp.float32(config.gamma),
        TT=int(config.max_sweeps),
        n=n,
    )


def hpr_solve(
    graph: Graph,
    config: HPRConfig | None = None,
    *,
    seed: int = 0,
    chi0=None,
    checkpoint_path: str | None = None,
    checkpoint_interval_s: float = 30.0,
    chunk_sweeps: int = 200,
) -> HPRResult:
    """Run one HPr chain on one graph instance.

    ``checkpoint_path`` enables exact chain resume (SURVEY.md §5.4): the
    device loop runs in ``chunk_sweeps``-bounded chunks (the bound is a
    traced absolute sweep index, so every chunk reuses one compiled program)
    and the full chain state (chi, biases, s, PRNG key, t) is snapshotted
    atomically at most every ``checkpoint_interval_s`` seconds; a rerun
    pointing at the checkpoint continues bit-for-bit. Removed on completion.
    """
    t_start = time.perf_counter()
    config = config or HPRConfig()
    setup = _prep(graph, config)
    data, sweep, marginals = setup.data, setup.sweep, setup.marginals
    bias_to_edge = setup.bias_to_edge
    lmbd, pie, gamma, TT, n = setup.lmbd, setup.pie, setup.gamma, setup.TT, setup.n

    def m_of_end(s):
        return setup.m_of_end_batch(s[None])[0]

    @jax.jit
    def run_chunk(chi, biases, s, key, t, m_final, t_end):
        def cond(st):
            _, _, _, _, t, m_final = st
            return (m_final < 1.0) & (t < t_end)

        def body(st):
            chi, biases, s, key, t, _ = st
            chi = sweep(chi, lmbd, bias_to_edge(biases))
            marg = marginals(chi)
            # reinforcement (`new_biases_i`, `HPR:137-145`)
            minus_wins = marg[:, 1] >= marg[:, 0]
            new_bias = jnp.where(
                minus_wins[:, None],
                jnp.array([pie, 1 - pie]),
                jnp.array([1 - pie, pie]),
            )
            key, ku = jax.random.split(key)
            u = jax.random.uniform(ku, (n,))
            update = u < 1.0 - (1.0 + t.astype(jnp.float32)) ** (-gamma)
            biases = jnp.where(update[:, None], new_bias, biases)
            s = jnp.where(biases[:, 0] > biases[:, 1], 1, -1).astype(jnp.int8)
            t = t + 1
            m_final = jnp.where(t > TT, 2.0, m_of_end(s))
            return chi, biases, s, key, t, m_final

        return lax.while_loop(cond, body, (chi, biases, s, key, t, m_final))

    ckpt = None
    state = None
    if checkpoint_path is not None:
        from graphdyn.utils.io import ChainCheckpointer, run_fingerprint

        if chunk_sweeps < 1:
            raise ValueError(f"chunk_sweeps must be >= 1, got {chunk_sweeps}")
        ckpt = ChainCheckpointer(
            checkpoint_path, kind="hpr_chain", seed=seed,
            fp=run_fingerprint(graph.edges, config),
            interval_s=checkpoint_interval_s,
        )
        arrays = ckpt.load_state(
            check=lambda a: a["s"].shape == (n,)
            and a["chi"].shape == (data.num_directed, data.K, data.K)
        )
        if arrays is not None:
            state = tuple(jnp.asarray(arrays[k]) for k in _HPR_CHAIN_FIELDS)

    if state is None:
        rng = np.random.default_rng(seed)
        if chi0 is None:
            # one stream for both draws — keeps chi and biases independent
            chi0 = data.init_messages(rng)
        biases0 = rng.random((n, 2))
        biases0 /= biases0.sum(axis=1, keepdims=True)
        biases0 = jnp.asarray(biases0, jnp.float32)
        s0 = jnp.where(biases0[:, 0] > biases0[:, 1], 1, -1).astype(jnp.int8)
        state = (
            jnp.asarray(chi0), biases0, s0, jax.random.PRNGKey(seed),
            jnp.int32(0), m_of_end(s0),
        )

    if ckpt is None:
        state = run_chunk(*state, jnp.int32(TT + 2))
    else:
        state = ckpt.drive(
            state,
            advance=lambda st: run_chunk(
                *st, jnp.minimum(st[4] + jnp.int32(chunk_sweeps), TT + 2)
            ),
            active=lambda st: bool(st[5] < 1.0),
            payload=lambda st: {
                k: np.asarray(v) for k, v in zip(_HPR_CHAIN_FIELDS, st)
            },
        )

    chi, biases, s, _, t, m_final = state
    s = np.asarray(s)
    return HPRResult(
        s=s,
        mag_reached=np.float32(s.astype(np.float64).mean()),
        num_steps=int(t),
        m_final=float(m_final),
        biases=np.asarray(biases),
        chi=np.asarray(chi),
        elapsed_s=time.perf_counter() - t_start,
    )


class HPRBatchResult(NamedTuple):
    """Per-chain results of the replica-batched solver."""

    s: np.ndarray            # int8[R, n]
    mag_reached: np.ndarray  # f32[R]
    num_steps: np.ndarray    # int32[R] — sweeps until that chain stopped
    m_final: np.ndarray      # f32[R] — 1.0 success, 2.0 timeout sentinel
    elapsed_s: float


def hpr_solve_batch(
    graph: Graph,
    config: HPRConfig | None = None,
    *,
    n_replicas: int | None = None,
    seed: int = 0,
    mesh=None,
    replica_axis: str = "replica",
    checkpoint_path: str | None = None,
    checkpoint_interval_s: float = 30.0,
    chunk_sweeps: int = 200,
) -> HPRBatchResult:
    """Run R independent HPr chains on ONE graph as a single batched device
    program — the BASELINE config-2 replica axis (`N=1e5, 256 replicas`).

    The reference runs one chain per process (`HPR_pytorch_RRG.py:342-356`).
    Here chains batch as a DISJOINT-UNION graph
    (:func:`graphdyn.graphs.replicate_disjoint` — R structural copies side
    by side): chi stays ``[R·2E, K, K]`` with the edge axis as the one big
    TPU lane dimension, so memory scales linearly in R. A leading-axis
    ``vmap`` instead makes XLA pick the replica axis as the 128-lane dim —
    every R < 128 pads to 128 (measured: R-independent 2.3 GB input copies
    at n=1e5, OOM). Chains stay independent (no edges between copies);
    finished chains freeze via per-replica masks gathered to the node/edge
    axes, inside one ``lax.while_loop``. Pass a ``mesh`` to split the
    edge/node-blocked state over devices; note the directed-edge layout
    ([all forward | all reverse]) puts a replica's two blocks on different
    shards, so GSPMD inserts gathers for reverse-edge reads — the sharding
    trades some ICI traffic for HBM capacity rather than being
    communication-free.

    ``checkpoint_path``: exact-resume checkpointing with the same contract
    as :func:`hpr_solve` (chunked loop, full state snapshot, fingerprint-
    validated resume, removed on completion). chi dominates the snapshot
    size (``R·2E·K²`` floats), so pick ``checkpoint_interval_s``
    accordingly at config-2 scale.
    """
    t_start = time.perf_counter()
    config = config or HPRConfig()
    R = n_replicas if n_replicas is not None else config.n_replicas
    n = graph.n
    E = graph.num_edges
    dyn = config.dynamics
    R_coef, C_coef = rule_coefficients(dyn.rule, dyn.tie)
    rollout_steps = dyn.p + dyn.c - 1

    from graphdyn.graphs import replicate_disjoint

    gu = replicate_disjoint(graph, R)
    setup = _prep(gu, config)
    data, bias_to_edge = setup.data, setup.bias_to_edge
    lmbd, pie, gamma, TT = setup.lmbd, setup.pie, setup.gamma, setup.TT

    nbr_u = jnp.asarray(gu.nbr)
    # replica of union node i is i // n; directed union edges are all
    # forward copies [r·E, (r+1)·E) then all reverses at +R·E
    node_rep = jnp.asarray(np.repeat(np.arange(R), n))
    edge_rep = jnp.asarray(
        np.concatenate([np.repeat(np.arange(R), E)] * 2)
    )

    def m_per_replica(s_u):
        s_end = batched_rollout_impl(
            nbr_u, s_u[None], rollout_steps, R_coef, C_coef
        )[0]
        return (
            s_end.astype(jnp.int32).reshape(R, n).sum(axis=1).astype(jnp.float32)
            / n
        )

    @jax.jit
    def run_chunk(chi, biases, s, keys, t, m_final, active, steps, t_end):
        def cond(st):
            return jnp.any(st[6]) & (st[4] < t_end)

        def body(st):
            chi, biases, s, keys, t, m_final, active, steps = st
            chi_new = setup.sweep(chi, lmbd, bias_to_edge(biases))
            marg = setup.marginals(chi_new)                  # [R·n, 2]
            minus_wins = marg[:, 1] >= marg[:, 0]
            new_bias = jnp.where(
                minus_wins[:, None],
                jnp.array([pie, 1 - pie]),
                jnp.array([1 - pie, pie]),
            )
            ks = jax.vmap(jax.random.split)(keys)            # [R, 2, key]
            keys_new, ku = ks[:, 0], ks[:, 1]
            u = jax.vmap(lambda k: jax.random.uniform(k, (n,)))(ku).reshape(R * n)
            update = u < 1.0 - (1.0 + t.astype(jnp.float32)) ** (-gamma)
            biases_new = jnp.where(update[:, None], new_bias, biases)
            s_new = jnp.where(
                biases_new[:, 0] > biases_new[:, 1], 1, -1
            ).astype(jnp.int8)
            t_new = t + 1
            m_new = jnp.where(t_new > TT, 2.0, m_per_replica(s_new))
            # frozen chains keep their final state
            ae = active[edge_rep]
            an = active[node_rep]
            chi = jnp.where(ae[:, None, None], chi_new, chi)
            biases = jnp.where(an[:, None], biases_new, biases)
            s = jnp.where(an, s_new, s)
            keys = jnp.where(active[:, None], keys_new, keys)
            m_final = jnp.where(active, m_new, m_final)
            steps = jnp.where(active, t_new, steps)
            active = active & (m_final < 1.0) & (t_new <= TT)
            return chi, biases, s, keys, t_new, m_final, active, steps

        return lax.while_loop(
            cond, body, (chi, biases, s, keys, t, m_final, active, steps)
        )

    ckpt = None
    state = None
    if checkpoint_path is not None:
        from graphdyn.utils.io import ChainCheckpointer, run_fingerprint

        if chunk_sweeps < 1:
            raise ValueError(f"chunk_sweeps must be >= 1, got {chunk_sweeps}")
        ckpt = ChainCheckpointer(
            checkpoint_path, kind="hpr_batch_chain", seed=seed,
            fp=run_fingerprint(graph.edges, config, R),
            interval_s=checkpoint_interval_s,
        )
        arrays = ckpt.load_state(check=lambda a: a["s"].shape == (R * n,))
        if arrays is not None:
            state = tuple(jnp.asarray(arrays[k]) for k in _HPR_BATCH_FIELDS)

    if state is None:
        rng = np.random.default_rng(seed)
        chi0 = jnp.asarray(data.init_messages(rng))
        biases0 = rng.random((R * n, 2))
        biases0 /= biases0.sum(axis=1, keepdims=True)
        biases0 = jnp.asarray(biases0, jnp.float32)
        # one root key per chain: distinct seeds give fully disjoint streams
        keys = jax.random.split(jax.random.PRNGKey(seed), R)
        s0 = jnp.where(biases0[:, 0] > biases0[:, 1], 1, -1).astype(jnp.int8)
        m0 = m_per_replica(s0)
        state = (
            chi0, biases0, s0, keys, jnp.int32(0), m0,
            m0 < 1.0, jnp.zeros((R,), jnp.int32),
        )

    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        shard = NamedSharding(mesh, P(replica_axis))
        state = (
            jax.device_put(state[0], shard),       # chi [R·2E, K, K]
            jax.device_put(state[1], shard),       # biases [R·n, 2]
            jax.device_put(state[2], shard),       # s [R·n]
            jax.device_put(state[3], shard),       # keys [R]
            *state[4:],
        )

    if ckpt is None:
        state = run_chunk(*state, jnp.int32(TT + 2))
    else:
        state = ckpt.drive(
            state,
            advance=lambda st: run_chunk(
                *st, jnp.minimum(st[4] + jnp.int32(chunk_sweeps), TT + 2)
            ),
            active=lambda st: bool(jnp.any(st[6])),
            payload=lambda st: {
                k: np.asarray(v) for k, v in zip(_HPR_BATCH_FIELDS, st)
            },
        )

    _, _, s_u, _, _, m_final, _, steps = state
    s = np.asarray(s_u).reshape(R, n)
    return HPRBatchResult(
        s=s,
        mag_reached=s.astype(np.float64).mean(axis=1).astype(np.float32),
        num_steps=np.asarray(steps),
        m_final=np.asarray(m_final),
        elapsed_s=time.perf_counter() - t_start,
    )


class HPREnsembleResult(NamedTuple):
    """The reference driver's per-repetition arrays
    (`HPR_pytorch_RRG.py:251-255,359-362`)."""

    mag_reached: np.ndarray  # f[n_rep]
    conf: np.ndarray         # int8[n_rep, n]
    num_steps: np.ndarray    # int[n_rep]
    graphs: np.ndarray       # int32[n_rep, n, d]
    time: np.ndarray         # f[n_rep] wall-clock seconds (`HPR:364,370`)


def hpr_ensemble(
    n: int,
    d: int,
    config: HPRConfig | None = None,
    *,
    n_rep: int = 1,
    seed: int = 0,
    graph_method: str = "pairing",
    save_path: str | None = None,
    checkpoint_path: str | None = None,
    checkpoint_interval_s: float = 30.0,
) -> HPREnsembleResult:
    """The reference's experiment driver (`HPR_pytorch_RRG.py:259-377`):
    ``n_rep`` repetitions, each on a freshly sampled RRG(n, d); pass
    ``save_path`` to persist the npz with the reference's key names
    (`HPR:377` — the only live persistence in the reference repo).

    ``checkpoint_path`` makes the driver preemption-safe, exactly as in
    :func:`graphdyn.models.sa.sa_ensemble`: completed repetitions snapshot
    with the next repetition index, the in-flight chain checkpoints at
    ``<path>_chain`` (exact resume), graphs re-derive from ``seed + k``."""
    from graphdyn.graphs import random_regular_graph
    from graphdyn.utils.io import Checkpoint, load_resume_prefix, save_results_npz

    config = config or HPRConfig()
    mag = np.empty(n_rep, np.float64)
    conf = np.empty((n_rep, n), np.int8)
    steps = np.empty(n_rep, np.int64)
    graphs = np.empty((n_rep, n, d), np.int32)
    times = np.empty(n_rep, np.float64)

    start_k = 0
    ck = Checkpoint(checkpoint_path) if checkpoint_path else None
    run_id = {"seed": seed, "n_rep": n_rep, "n": n, "d": d,
              "graph_method": graph_method, "config": repr(config)}
    if ck is not None:
        resumed = load_resume_prefix(ck, run_id)
        if resumed is not None:
            arrays, start_k = resumed
            mag[:start_k] = arrays["mag_reached"][:start_k]
            conf[:start_k] = arrays["conf"][:start_k]
            steps[:start_k] = arrays["num_steps"][:start_k]
            times[:start_k] = arrays["time"][:start_k]

    for k in range(start_k, n_rep):
        g = random_regular_graph(n, d, seed=seed + k, method=graph_method)
        res = hpr_solve(
            g, config, seed=seed + k,
            checkpoint_path=(checkpoint_path + "_chain") if checkpoint_path else None,
            checkpoint_interval_s=checkpoint_interval_s,
        )
        mag[k] = float(res.mag_reached)
        conf[k] = res.s
        steps[k] = res.num_steps
        graphs[k] = g.nbr
        times[k] = res.elapsed_s
        if ck is not None:
            ck.save(
                {"mag_reached": mag, "conf": conf, "num_steps": steps,
                 "time": times},
                {**run_id, "next_rep": k + 1},
            )
    for k in range(start_k):
        graphs[k] = random_regular_graph(
            n, d, seed=seed + k, method=graph_method
        ).nbr
    if ck is not None:
        ck.remove()
    out = HPREnsembleResult(mag, conf, steps, graphs, times)
    if save_path:
        save_results_npz(
            save_path,
            mag_reached=out.mag_reached,
            conf=out.conf,
            num_steps=out.num_steps,
            graphs=out.graphs,
            time=out.time,
        )
    return out
