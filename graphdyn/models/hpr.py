"""History-Passing reinforcement (HPr) — the reinforced-BP solver (L5).

Reproduces the reference's HPr loop (`HPR_pytorch_RRG.py:342-356`): iterate
the bias-weighted BDCM sweep, compute node marginals, reinforce per-node
biases toward the marginal winner with probability ``1−(1+t)^{−γ}``
("cedrics paper, eq. (24)" per the comment at `HPR:135`), read off the trial
solution ``s = argmax bias``, and stop when ``s`` flows to the all-+1
attractor under the (p,c) rollout, or after ``TT`` sweeps (sentinel
``m_final = 2``, `HPR:355`).

TPU-first redesign (SURVEY.md §3.2): the reference crosses the host/device
boundary every DP combo via string-parsing ``order_gpu`` (`HPR:46-61`) and
scalar ``A_i_sums`` calls; here the entire iteration — sweep, marginals,
reinforcement, rollout stop-test — is ONE jitted ``lax.while_loop`` body with
table-driven factor tensors; zero host round-trips until the loop exits.

Faithful quirk-preservation (capabilities stay, accidents go — SURVEY §7):
the λ-tilt is ``exp(−λ_eff·x_i(0))`` with λ_eff = ``lmbd_in/n`` = 25
(`HPR:231,39`); the DP does *not* mask invalid-endpoint source trajectories
(unlike the entropy sweep) — their chi entries decay under damping instead;
marginals are ε-clamped at 1e-15 (`HPR:147`). The hard-coded `.to('cuda')`
(`HPR:347`) and CPU-side ``torch.rand`` mask (`HPR:142`) are bugs, not
capabilities, and are not reproduced.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from graphdyn import obs
from graphdyn.config import HPRConfig
from graphdyn.graphs import Graph, build_edge_tables
from graphdyn.ops.bdcm import BDCMData, make_marginals, make_sweep
from graphdyn.ops.dynamics import batched_rollout_impl, rule_coefficients
from graphdyn.parallel.mesh import shard_map


# chain-checkpoint state fields, in state-tuple order (one constant per
# solver: the restore and the save payload cannot drift apart)
_HPR_CHAIN_FIELDS = ("chi", "biases", "s", "key", "t", "m_final")
_HPR_BATCH_FIELDS = ("chi", "biases", "s", "keys", "t", "m_final", "active",
                     "steps")


class HPRResult(NamedTuple):
    s: np.ndarray            # int8[n] — trial solution at stop
    mag_reached: np.ndarray  # f32 scalar — m(s) at stop (`HPR:359`)
    num_steps: int           # sweeps taken (`HPR:360`)
    m_final: float           # 1.0 success, 2.0 timeout sentinel
    biases: np.ndarray       # f32[n, 2] — final reinforcement biases
    chi: np.ndarray          # final messages
    elapsed_s: float         # wall-clock seconds (`HPR:257,364` — persisted
                             # as `time` in the reference npz, `HPR:377`)


class _HPRSetup(NamedTuple):
    """Shared per-graph preparation of both HPr solvers — one place carries
    the reference-faithful quirks (eps_clamp=0, unmasked invalid sources,
    the bias-to-edge gather; see the module docstring)."""

    data: BDCMData
    sweep: object
    marginals: object
    bias_to_edge: object
    m_of_end_batch: object   # int8[R, n] -> f32[R]
    lmbd: jnp.ndarray
    pie: jnp.ndarray
    gamma: jnp.ndarray
    TT: int
    n: int
    dtype: jnp.dtype         # messages/marginals/biases dtype
                             # (HPRConfig.dtype; the reference is f64,
                             # `HPR_pytorch_RRG.py:11`)


def _prep(
    graph: Graph, config: HPRConfig, *, tables: object = None,
    use_pallas="auto", data: BDCMData | None = None,
) -> _HPRSetup:
    dyn = config.dynamics
    n = graph.n
    tables = tables if tables is not None else build_edge_tables(graph)
    dtype = jnp.dtype(config.dtype)
    if data is None:
        data = BDCMData(
            graph, tables, p=dyn.p, c=dyn.c, attr_value=dyn.attr_value,
            rule=dyn.rule, tie=dyn.tie, dtype=dtype,
        )
    sweep = make_sweep(
        data, damp=config.damp, eps_clamp=0.0, mask_invalid_src=False,
        with_bias=True, use_pallas=use_pallas,
    )
    marginals = make_marginals(data, eps=config.eps_clamp)
    R_coef, C_coef = rule_coefficients(dyn.rule, dyn.tie)
    rollout_steps = dyn.p + dyn.c - 1

    src = jnp.asarray(
        tables.src.astype(np.int64) if isinstance(tables.src, np.ndarray)
        else tables.src            # device tables are int32 (range-guarded)
    )
    sel_plus = jnp.asarray(data.x0 == 1)
    nbr = jnp.asarray(graph.nbr)

    def bias_to_edge(biases):
        # bias of the *source* node at its trajectory's initial value
        # (`positions_biases`, `HPR:120-133`): [2E, K]
        return jnp.where(sel_plus[None, :], biases[src, 0, None], biases[src, 1, None])

    def m_of_end_batch(s):
        s_end = batched_rollout_impl(nbr, s, rollout_steps, R_coef, C_coef)
        return s_end.astype(jnp.int32).sum(axis=1).astype(jnp.float32) / n

    return _HPRSetup(
        data=data,
        sweep=sweep,
        marginals=marginals,
        bias_to_edge=bias_to_edge,
        m_of_end_batch=m_of_end_batch,
        lmbd=jnp.asarray(config.lmbd, dtype),
        pie=jnp.asarray(config.pie, dtype),
        gamma=jnp.asarray(config.gamma, dtype),
        TT=int(config.max_sweeps),
        n=n,
        dtype=dtype,
    )


def hpr_solve(
    graph: Graph,
    config: HPRConfig | None = None,
    *,
    seed: int = 0,
    chi0=None,
    checkpoint_path: str | None = None,
    checkpoint_interval_s: float = 30.0,
    chunk_sweeps: int = 200,
    kernel: str = "auto",
) -> HPRResult:
    """Run one HPr chain on one graph instance.

    ``checkpoint_path`` enables exact chain resume (SURVEY.md §5.4): the
    device loop runs in ``chunk_sweeps``-bounded chunks (the bound is a
    traced absolute sweep index, so every chunk reuses one compiled program)
    and the full chain state (chi, biases, s, PRNG key, t) is snapshotted
    atomically at most every ``checkpoint_interval_s`` seconds; a rerun
    pointing at the checkpoint continues bit-for-bit. Removed on completion.

    The chain advances through the ensemble pipeline's shared group program
    (:class:`graphdyn.pipeline.hpr_group.HPRGroupExec` with G=1;
    ARCHITECTURE.md "Ensemble pipeline"): the grouped ``hpr_ensemble``
    driver runs the SAME vmapped body at G=``group_size``, which is what
    makes serial-vs-grouped driver results element-wise identical — two
    *differently structured* loop programs (e.g. a fused while-loop vs its
    own op-by-op restatement) differ at the ulp level under XLA fusion and
    eventually flip a chain decision, so sharing one program family is the
    only robust identity. ``kernel`` selects the chain's sweep core
    (``'auto'``/``'xla'``/``'pallas'`` — on TPU the default fuses
    qualifying classes into the grouped Pallas kernel at G=1, the same
    kernel the grouped driver runs; ARCHITECTURE.md "Kernel selection").
    """
    # the one timing idiom (graftlint GD011): an always-measuring obs
    # span — the wall clock feeds the result's elapsed_s, and the span
    # event lands in the ledger when a recorder is active
    _sw = obs.timed("solver.hpr").start()
    try:
        config = config or HPRConfig()
        from graphdyn.pipeline.hpr_group import HPRGroupExec

        dyn = config.dynamics
        n = graph.n
        dtype = jnp.dtype(config.dtype)
        tables = build_edge_tables(graph)
        data = BDCMData(
            graph, tables, p=dyn.p, c=dyn.c, attr_value=dyn.attr_value,
            rule=dyn.rule, tie=dyn.tie, dtype=dtype,
        )
        ex = HPRGroupExec([(graph, data)], config, kernel=kernel)
        TT = int(config.max_sweeps)

        ckpt = None
        state = None
        if checkpoint_path is not None:
            from graphdyn.utils.io import ChainCheckpointer, run_fingerprint

            if chunk_sweeps < 1:
                raise ValueError(f"chunk_sweeps must be >= 1, got {chunk_sweeps}")
            ckpt = ChainCheckpointer(
                checkpoint_path, kind="hpr_chain", seed=seed,
                fp=run_fingerprint(graph.edges, config),
                interval_s=checkpoint_interval_s,
            )
            arrays = ckpt.load_state(
                check=lambda a: a["s"].shape == (n,)
                and a["chi"].shape == (data.num_directed, data.K, data.K)
            )
            if arrays is not None:
                t_res = int(np.asarray(arrays["t"]))
                state = ex.init_state(
                    [arrays["chi"]], [arrays["biases"]], [arrays["s"]],
                    [np.asarray(arrays["key"])], t=t_res,
                    m_final=[np.float32(arrays["m_final"])],
                )

        if state is None:
            rng = np.random.default_rng(seed)
            if chi0 is None:
                # one stream for both draws — keeps chi and biases independent
                chi0 = data.init_messages(rng)
            biases0 = rng.random((n, 2))
            biases0 /= biases0.sum(axis=1, keepdims=True)
            biases0 = np.asarray(biases0, dtype)
            s0 = np.where(biases0[:, 0] > biases0[:, 1], 1, -1).astype(np.int8)
            state = ex.init_state([np.asarray(chi0)], [biases0], [s0], [seed])

        def payload(st):
            return dict(zip(_HPR_CHAIN_FIELDS, (
                np.asarray(st.chi[0]), np.asarray(st.biases[0]),
                np.asarray(st.s[0]), np.asarray(st.keys[0]),
                np.asarray(st.t), np.asarray(st.m_final[0]),
            )))

        if ckpt is None:
            state = ex.run(state, chunk_sweeps=TT + 2)   # one device call
        else:
            state = ckpt.drive(
                state,
                advance=lambda st: ex.advance(
                    st, min(int(st.t) + int(chunk_sweeps), TT + 2)
                ),
                active=lambda st: bool(np.asarray(st.active)[0]),
                payload=payload,
            )

        s = np.asarray(state.s[0])
        return HPRResult(
            s=s,
            # graftlint: disable-next-line=GD004  host observable, exact sum
            mag_reached=np.float32(s.astype(np.float64).mean()),
            num_steps=int(np.asarray(state.steps)[0]),
            m_final=float(np.asarray(state.m_final)[0]),
            biases=np.asarray(state.biases[0]),
            chi=np.asarray(state.chi[0]),
            elapsed_s=_sw.stop().wall_s,
        )
    finally:
        _sw.stop()      # exception path: close + unwind the span


class HPRBatchResult(NamedTuple):
    """Per-chain results of the replica-batched solver."""

    s: np.ndarray            # int8[R, n]
    mag_reached: np.ndarray  # f32[R]
    num_steps: np.ndarray    # int32[R] — sweeps until that chain stopped
    m_final: np.ndarray      # f32[R] — 1.0 success, 2.0 timeout sentinel
    elapsed_s: float


def union_setup(
    graph: Graph, config: HPRConfig, R: int, *, device: bool = False,
    use_pallas="auto",
) -> _HPRSetup:
    """R-replica disjoint-union HPr setup in the REPLICA-MAJOR edge layout
    (:func:`graphdyn.graphs.replicate_edge_tables`): replica ``r``'s directed
    edges occupy the contiguous rows ``[r·2E, (r+1)·2E)``, so every gather in
    the sweep, marginals, and bias scatter stays inside one replica's block
    and a 1-D replica sharding of the state is communication-free.

    ``device=True`` builds the union tables ON DEVICE by offset-tiling the
    base tables (:func:`graphdyn.ops.bdcm.replicate_bdcm_device`) — the
    host→device link then carries ~10 MB instead of ~4 GB at config-2 scale,
    which a tunneled TPU transport cannot sustain. Single-device placement
    only (the mesh path shards per-replica blocks itself)."""
    if device:
        from graphdyn.ops.bdcm import BDCMData, replicate_bdcm_device

        dyn = config.dynamics
        base = BDCMData(
            graph, p=dyn.p, c=dyn.c, attr_value=dyn.attr_value,
            rule=dyn.rule, tie=dyn.tie, dtype=jnp.dtype(config.dtype),
        )
        data_u = replicate_bdcm_device(base, R)
        return _prep(data_u.graph, config, tables=data_u.tables, data=data_u,
                     use_pallas=use_pallas)
    from graphdyn.graphs import replicate_disjoint, replicate_edge_tables

    gu = replicate_disjoint(graph, R)
    tabs = replicate_edge_tables(build_edge_tables(graph), R, graph.n)
    return _prep(gu, config, tables=tabs, use_pallas=use_pallas)


def _draw_union_chi(rng, R: int, twoE: int, K: int, np_dt) -> np.ndarray:
    """Row-normalized random chi for the R-replica union, drawn replica-by-
    replica straight into the target dtype. ``init_messages`` would draw the
    whole union in float64 before casting — ~20 GB host at config-2 scale."""
    out = np.empty((R * twoE, K, K), np_dt)
    for r in range(R):
        blk = rng.random((twoE, K, K))
        blk /= blk.sum(axis=(1, 2), keepdims=True)
        out[r * twoE : (r + 1) * twoE] = blk
    return out


def _make_hpr_batch_body(setup: _HPRSetup, graph: Graph, R_blk: int):
    """One HPr iteration over an ``R_blk``-replica union block: sweep,
    marginals, reinforcement, per-replica rollout stop-test, freeze masks.
    Shared verbatim by the single-device program and the per-shard body of
    the mesh path (each shard's block IS such a union), so the sharded and
    unsharded solvers cannot drift.

    The sweep clock ``t`` is carried as an all-equal ``int32[R_blk]`` vector
    rather than a scalar: the sharded path can then declare every carried
    array replica-sharded (no scalar outputs whose replication ``shard_map``
    cannot express)."""
    n = graph.n
    dyn_steps = setup.data.p + setup.data.c - 1
    R_coef, C_coef = rule_coefficients(setup.data.rule, setup.data.tie)
    twoE = setup.data.num_directed // R_blk
    node_rep = jnp.asarray(np.repeat(np.arange(R_blk), n))
    edge_rep = jnp.asarray(np.repeat(np.arange(R_blk), twoE))
    nbr_b = jnp.asarray(graph.nbr)
    lmbd, pie, gamma, TT = setup.lmbd, setup.pie, setup.gamma, setup.TT

    def m_per_replica(s_u):
        # chains are structural copies of the BASE graph — roll them as a
        # batch over its neighbor table instead of one union-wide rollout
        s_end = batched_rollout_impl(
            nbr_b, s_u.reshape(R_blk, n), dyn_steps, R_coef, C_coef
        )
        return s_end.astype(jnp.int32).sum(axis=1).astype(jnp.float32) / n

    def body(chi, biases, s, keys, t, m_final, active, steps):
        chi_new = setup.sweep(chi, lmbd, setup.bias_to_edge(biases))
        marg = setup.marginals(chi_new)                  # [R_blk·n, 2]
        minus_wins = marg[:, 1] >= marg[:, 0]
        new_bias = jnp.where(
            minus_wins[:, None],
            jnp.stack([pie, 1 - pie]),
            jnp.stack([1 - pie, pie]),
        )
        ks = jax.vmap(jax.random.split)(keys)            # [R_blk, 2, key]
        keys_new, ku = ks[:, 0], ks[:, 1]
        u = jax.vmap(
            lambda k: jax.random.uniform(k, (n,), biases.dtype)
        )(ku).reshape(R_blk * n)
        update = u < 1.0 - (1.0 + t[0].astype(biases.dtype)) ** (-gamma)
        biases_new = jnp.where(update[:, None], new_bias, biases)
        s_new = jnp.where(
            biases_new[:, 0] > biases_new[:, 1], 1, -1
        ).astype(jnp.int8)
        t_new = t + 1
        m_new = jnp.where(t_new[0] > TT, 2.0, m_per_replica(s_new))
        # frozen chains keep their final state
        ae = active[edge_rep]
        an = active[node_rep]
        chi = jnp.where(ae[:, None, None], chi_new, chi)
        biases = jnp.where(an[:, None], biases_new, biases)
        s = jnp.where(an, s_new, s)
        keys = jnp.where(active[:, None], keys_new, keys)
        m_final = jnp.where(active, m_new, m_final)
        steps = jnp.where(active, t_new, steps)
        active = active & (m_final < 1.0) & (t_new[0] <= TT)
        return chi, biases, s, keys, t_new, m_final, active, steps

    return body, m_per_replica


def _kernel_to_use_pallas(kernel: str):
    """Map the drivers' ``kernel`` axis onto the serial sweep's
    ``use_pallas`` knob (one vocabulary at the CLI, both program
    families)."""
    try:
        return {"auto": "auto", "xla": False, "pallas": True}[kernel]
    except KeyError:
        raise ValueError(
            f"kernel must be 'auto', 'xla' or 'pallas', got {kernel!r}"
        ) from None


def make_hpr_batch_chunk(
    graph: Graph,
    config: HPRConfig,
    Rtot: int,
    *,
    mesh=None,
    replica_axis: str = "replica",
    device_tables: bool = False,
    kernel: str = "auto",
):
    """Build the jitted chunk program ``(chi, biases, s, keys, t, m_final,
    active, steps, t_end) -> same-shape state`` advancing ``Rtot`` batched
    HPr chains until all stop or the sweep clock reaches ``t_end``.

    With a ``mesh``, the program is a ``shard_map`` over the ``replica``
    axis: each device runs its own ``Rtot/n_shards``-replica union block
    with purely local gathers (the replica-major layout guarantees
    block-diagonal index tables); the only communication is one scalar
    ``psum`` per sweep keeping the mesh-wide stop test in lockstep — the
    TPU-first answer to the reference's one-chain-per-process replica loop
    (`HPR_pytorch_RRG.py:259`). Exposed for the config-2 benchmark so it
    measures the exact shipped program.
    """
    if device_tables and mesh is not None:
        raise ValueError(
            "device_tables=True is incompatible with mesh= (the mesh path "
            "host-shards its per-device union blocks)"
        )
    use_pallas = _kernel_to_use_pallas(kernel)
    if mesh is None:
        setup = union_setup(graph, config, Rtot, device=device_tables,
                            use_pallas=use_pallas)
        body, m_per_replica = _make_hpr_batch_body(setup, graph, Rtot)

        @jax.jit
        # graftlint: disable-next-line=GD006  checkpoint path reuses the carry
        def run_chunk(chi, biases, s, keys, t, m_final, active, steps, t_end):
            def cond(st):
                return jnp.any(st[6]) & (st[4][0] < t_end)

            def bdy(st):
                return body(*st)

            return lax.while_loop(
                cond, bdy, (chi, biases, s, keys, t, m_final, active, steps)
            )

        return run_chunk, setup

    from jax.sharding import PartitionSpec as P

    shards = int(mesh.shape[replica_axis])
    if Rtot % shards:
        raise ValueError(f"Rtot={Rtot} not divisible by {shards} replica shards")
    R_local = Rtot // shards
    setup_l = union_setup(graph, config, R_local, use_pallas=use_pallas)
    body_l, _ = _make_hpr_batch_body(setup_l, graph, R_local)
    rep = P(replica_axis)

    def chunk_l(chi, biases, s, keys, t, m_final, active, steps, t_end):
        def cond(st):
            return (st[8] > 0) & (st[4][0] < t_end)

        def bdy(st):
            out = body_l(*st[:8])
            live = lax.psum(jnp.any(out[6]).astype(jnp.int32), replica_axis)
            return (*out, live)

        live0 = lax.psum(jnp.any(active).astype(jnp.int32), replica_axis)
        out = lax.while_loop(
            cond, bdy, (chi, biases, s, keys, t, m_final, active, steps, live0)
        )
        return out[:8]

    run_chunk = jax.jit(
        shard_map(
            chunk_l,
            mesh=mesh,
            in_specs=(rep,) * 8 + (P(),),
            out_specs=(rep,) * 8,
            check_vma=False,
        )
    )
    return run_chunk, setup_l


def hpr_solve_batch(
    graph: Graph,
    config: HPRConfig | None = None,
    *,
    n_replicas: int | None = None,
    seed: int = 0,
    mesh=None,
    replica_axis: str = "replica",
    checkpoint_path: str | None = None,
    checkpoint_interval_s: float = 30.0,
    chunk_sweeps: int = 200,
    device_init: bool = False,
    kernel: str = "auto",
) -> HPRBatchResult:
    """Run R independent HPr chains on ONE graph as a single batched device
    program — the BASELINE config-2 replica axis (`N=1e5, 256 replicas`).

    The reference runs one chain per process (`HPR_pytorch_RRG.py:342-356`).
    Here chains batch as a DISJOINT-UNION graph in the replica-major edge
    layout (:func:`union_setup`): chi stays ``[R·2E, K, K]`` with the edge
    axis as the one big TPU lane dimension (memory linear in R; a
    leading-axis ``vmap`` instead pads the replica axis to 128 lanes —
    measured R-independent 2.3 GB copies at n=1e5, OOM), and replica ``r``
    owns the contiguous rows ``[r·2E, (r+1)·2E)``. Chains stay independent;
    finished chains freeze via per-replica masks, inside one
    ``lax.while_loop``. With a ``mesh``, replicas round up to the shard
    count (padding chains start frozen) and the loop runs under
    ``shard_map`` with purely local gathers and one scalar ``psum`` per
    sweep (:func:`make_hpr_batch_chunk`) — results are bit-identical to the
    unsharded program (tested), because every shard block computes exactly
    the unsharded per-replica arithmetic.

    ``checkpoint_path``: exact-resume checkpointing with the same contract
    as :func:`hpr_solve` (chunked loop, full state snapshot, fingerprint-
    validated resume, removed on completion). Snapshots store the UNPADDED
    R chains, so a run may resume on a different mesh shape. chi dominates
    the snapshot size (``R·2E·K²`` floats), so pick
    ``checkpoint_interval_s`` accordingly at config-2 scale.

    ``device_init=True`` builds the union tables AND the initial state
    (chi, biases, keys) on device — nothing union-sized ever crosses the
    host↔device link, which a tunneled TPU transport cannot sustain at
    config-2 scale. The device streams differ from the host ``seed``
    streams (both are valid random inits). Incompatible with ``mesh``
    (host-sharded placement) and ``checkpoint_path`` (snapshots pull chi
    back to host every interval — the same link problem in reverse).
    """
    _sw = obs.timed("solver.hpr_batch").start()   # GD011: one timing idiom
    try:
        config = config or HPRConfig()
        R = n_replicas if n_replicas is not None else config.n_replicas
        n = graph.n
        E = graph.num_edges
        twoE = 2 * E
        dyn = config.dynamics
        T = dyn.p + dyn.c
        K = 2**T
        np_dt = np.dtype(config.dtype)

        if device_init and mesh is not None:
            raise ValueError("device_init=True is incompatible with mesh=")
        if device_init and checkpoint_path is not None:
            raise ValueError("device_init=True is incompatible with checkpoint_path=")

        shards = int(mesh.shape[replica_axis]) if mesh is not None else 1
        R_pad = (-R) % shards
        Rtot = R + R_pad

        run_chunk, setup = make_hpr_batch_chunk(
            graph, config, Rtot, mesh=mesh, replica_axis=replica_axis,
            device_tables=device_init, kernel=kernel,
        )
        TT = setup.TT

        ckpt = None
        arrays = None
        if checkpoint_path is not None:
            from graphdyn.utils.io import ChainCheckpointer, run_fingerprint

            if chunk_sweeps < 1:
                raise ValueError(f"chunk_sweeps must be >= 1, got {chunk_sweeps}")
            ckpt = ChainCheckpointer(
                checkpoint_path, kind="hpr_batch_chain", seed=seed,
                fp=run_fingerprint(graph.edges, config, R),
                interval_s=checkpoint_interval_s,
            )
            # t must be the all-equal [R] sweep-clock vector (scalar in pre-r4
            # snapshots — those are refused by the fingerprint already, this
            # keeps the refusal a clean ValueError rather than an index error)
            arrays = ckpt.load_state(
                check=lambda a: a["s"].shape == (R * n,) and a["t"].shape == (R,)
            )

        if arrays is None:
            if device_init:
                dt = setup.dtype
                # one root, three fold_in-derived purposes: chi, biases, and the
                # per-chain update keys come from independent streams (sharing
                # the root key across purposes would make the chains' key
                # material a prefix of chi's bit stream)
                from graphdyn.ops.bdcm import draw_chi_device

                root = jax.random.key(seed)
                chi0 = draw_chi_device(
                    jax.random.fold_in(root, 0), R * twoE, K, dt
                )
                k_bias = jax.random.fold_in(root, 1)

                @jax.jit
                def _draw_bias():
                    b = jax.random.uniform(k_bias, (R * n, 2), dt)
                    b = b / b.sum(axis=1, keepdims=True)
                    return b, jnp.where(b[:, 0] > b[:, 1], 1, -1).astype(jnp.int8)

                biases0, s0 = _draw_bias()
                keys0 = jax.random.split(
                    jax.random.fold_in(jax.random.PRNGKey(seed), 2), R
                )
            else:
                rng = np.random.default_rng(seed)
                chi0 = _draw_union_chi(rng, R, twoE, K, np_dt)
                biases0 = rng.random((R * n, 2))
                biases0 /= biases0.sum(axis=1, keepdims=True)
                biases0 = biases0.astype(np_dt)
                # one root key per chain: distinct seeds give fully disjoint
                # streams
                keys0 = np.asarray(jax.random.split(jax.random.PRNGKey(seed), R))
                s0 = np.where(biases0[:, 0] > biases0[:, 1], 1, -1).astype(np.int8)
            arrays = {
                "chi": chi0, "biases": biases0, "s": s0, "keys": keys0,
                "t": np.zeros(R, np.int32), "m_final": None, "active": None,
                "steps": np.zeros(R, np.int32),
            }

        def pad_rows(x, blk, fill):
            """Append R_pad frozen-chain blocks of ``blk`` rows each."""
            if not R_pad:
                return x
            pad = np.full((R_pad * blk,) + x.shape[1:], fill, x.dtype)
            return np.concatenate([x, pad])

        chi_h = pad_rows(arrays["chi"], twoE, 1.0 / (K * K))
        biases_h = pad_rows(arrays["biases"], n, 0.5)
        s_h = pad_rows(arrays["s"], n, 1)
        keys_h = pad_rows(arrays["keys"], 1, 0)
        # pad chains carry the REAL sweep clock: each shard's while-loop cond
        # reads its local t[0], so a resumed run with t=0 pad rows would leave
        # the pad shard looping past the others' exit — straight into a psum
        # with no partners
        t_h = pad_rows(arrays["t"], 1, int(arrays["t"][0]) if R else 0)
        steps_h = pad_rows(arrays["steps"], 1, 0)

        def place(x):
            x = jnp.asarray(x)
            if mesh is None:
                return x
            from jax.sharding import NamedSharding, PartitionSpec as P

            return jax.device_put(x, NamedSharding(mesh, P(replica_axis)))

        if arrays["m_final"] is None:
            # initial stop-test: the same base-graph batched rollout the body
            # uses, run once host-driven on the unpadded chains. Only the [R]
            # sum vector crosses device->host (the [R, n] end state stays on
            # device); the f64 division happens on host, as always
            R_coef, C_coef = rule_coefficients(dyn.rule, dyn.tie)
            s_end = jax.jit(batched_rollout_impl, static_argnums=(2, 3, 4))(
                jnp.asarray(graph.nbr),
                jnp.asarray(arrays["s"]).reshape(R, n),
                dyn.p + dyn.c - 1, R_coef, C_coef,
            )
            sums = np.asarray(
                jax.jit(lambda se: se.astype(jnp.int32).sum(axis=1))(s_end)
            )
            m0 = (sums.astype(np.int64) / n).astype(np.float32)
            arrays["m_final"] = m0
            arrays["active"] = m0 < 1.0

        m_final_h = pad_rows(arrays["m_final"].astype(np.float32), 1, 1.0)
        active_h = pad_rows(arrays["active"].astype(bool), 1, False)

        state = tuple(
            place(x)
            for x in (chi_h, biases_h, s_h, keys_h, t_h, m_final_h, active_h, steps_h)
        )

        def snapshot(st):
            sl = {"chi": R * twoE, "biases": R * n, "s": R * n}
            return {
                k: np.asarray(v)[: sl.get(k, R)]
                for k, v in zip(_HPR_BATCH_FIELDS, st)
            }

        if ckpt is None:
            state = run_chunk(*state, jnp.int32(TT + 2))
        else:
            state = ckpt.drive(
                state,
                advance=lambda st: run_chunk(
                    *st, jnp.minimum(st[4][0] + jnp.int32(chunk_sweeps), TT + 2)
                ),
                active=lambda st: bool(np.asarray(st[6])[:R].any()),
                payload=snapshot,
            )

        _, _, s_u, _, _, m_final, _, steps = state
        s = np.asarray(s_u)[: R * n].reshape(R, n)
        return HPRBatchResult(
            s=s,
            # graftlint: disable-next-line=GD004  host observable, exact sum
            mag_reached=s.astype(np.float64).mean(axis=1).astype(np.float32),
            num_steps=np.asarray(steps)[:R],
            m_final=np.asarray(m_final)[:R],
            elapsed_s=_sw.stop().wall_s,
        )
    finally:
        _sw.stop()      # exception path: close + unwind the span


class HPREnsembleResult(NamedTuple):
    """The reference driver's per-repetition arrays
    (`HPR_pytorch_RRG.py:251-255,359-362`)."""

    mag_reached: np.ndarray  # f[n_rep]
    conf: np.ndarray         # int8[n_rep, n]
    num_steps: np.ndarray    # int[n_rep]
    graphs: np.ndarray       # int32[n_rep, n, d]
    time: np.ndarray         # f[n_rep] wall-clock seconds (`HPR:364,370`)


def hpr_ensemble(
    n: int,
    d: int,
    config: HPRConfig | None = None,
    *,
    n_rep: int = 1,
    seed: int = 0,
    graph_method: str = "pairing",
    save_path: str | None = None,
    checkpoint_path: str | None = None,
    checkpoint_interval_s: float = 30.0,
    group_size: int | None = None,
    prefetch: int = 2,
    kernel: str = "auto",
) -> HPREnsembleResult:
    """The reference's experiment driver (`HPR_pytorch_RRG.py:259-377`):
    ``n_rep`` repetitions, each on a freshly sampled RRG(n, d); pass
    ``save_path`` to persist the npz with the reference's key names
    (`HPR:377` — the only live persistence in the reference repo).

    ``group_size`` selects the execution pipeline (ARCHITECTURE.md
    "Ensemble pipeline"): the default (None) runs repetitions
    ``group_size``-at-a-time as ONE vmapped device program over stacked
    BDCM tables, with the next group's graphs/tables built on a background
    thread (``prefetch`` bounds the build-ahead; 0 disables the thread) —
    element-wise identical to the serial path (per-repetition streams
    derive from ``seed + k``). ``group_size=0`` forces the legacy serial
    repetition loop.

    ``checkpoint_path`` makes the driver preemption-safe, exactly as in
    :func:`graphdyn.models.sa.sa_ensemble`: completed repetitions snapshot
    with the next repetition index; under the serial path the in-flight
    chain additionally checkpoints at ``<path>_chain<k>`` (exact resume),
    while the grouped path checkpoints at group boundaries (an interrupted
    group re-runs from its start, bit-exactly; snapshots are
    interchangeable between paths and group sizes). Graphs re-derive from
    ``seed + k``; graceful shutdown snapshots the completed-rep prefix
    before propagating :class:`~graphdyn.resilience.ShutdownRequested`,
    and fault site ``rep.boundary`` fires once per repetition in
    repetition order (at group boundaries under the grouped path)."""
    if group_size is None:
        group_size = min(max(n_rep, 1), 8)
    if group_size:
        from graphdyn.pipeline.hpr_group import hpr_ensemble_grouped

        return hpr_ensemble_grouped(
            n, d, config, n_rep=n_rep, seed=seed, graph_method=graph_method,
            save_path=save_path, checkpoint_path=checkpoint_path,
            checkpoint_interval_s=checkpoint_interval_s,
            group_size=group_size, prefetch=prefetch, kernel=kernel,
        )
    from graphdyn.graphs import random_regular_graph
    from graphdyn.resilience import faults as _faults
    from graphdyn.resilience.shutdown import (
        ShutdownRequested, raise_if_requested, shutdown_requested,
    )
    from graphdyn.resilience.supervisor import beat as _heartbeat
    from graphdyn.utils.io import (
        PeriodicCheckpointer, load_resume_prefix, open_checkpoint,
        save_results_npz,
    )

    config = config or HPRConfig()
    mag = np.empty(n_rep, np.float64)  # graftlint: disable=GD004  host result buffer
    conf = np.empty((n_rep, n), np.int8)
    steps = np.empty(n_rep, np.int64)
    graphs = np.empty((n_rep, n, d), np.int32)
    times = np.empty(n_rep, np.float64)  # graftlint: disable=GD004  host wall-clock

    start_k = 0
    ck = open_checkpoint(checkpoint_path) if checkpoint_path else None
    # driver snapshots share the chain checkpoint's interval (the conf array
    # is [n_rep, n]; unconditional per-rep writes would dominate fast reps)
    pc = (PeriodicCheckpointer(checkpoint_path, interval_s=checkpoint_interval_s)
          if checkpoint_path else None)
    run_id = {"seed": seed, "n_rep": n_rep, "n": n, "d": d,
              "graph_method": graph_method, "config": repr(config)}
    if ck is not None:
        resumed = load_resume_prefix(ck, run_id)
        if resumed is not None:
            arrays, start_k = resumed
            mag[:start_k] = arrays["mag_reached"][:start_k]
            conf[:start_k] = arrays["conf"][:start_k]
            steps[:start_k] = arrays["num_steps"][:start_k]
            times[:start_k] = arrays["time"][:start_k]

    for k in range(start_k, n_rep):
        g = random_regular_graph(n, d, seed=seed + k, method=graph_method)

        def driver_payload():
            return {"mag_reached": mag, "conf": conf, "num_steps": steps,
                    "time": times}

        try:
            res = hpr_solve(
                g, config, seed=seed + k,
                # per-rep chain path — see sa_ensemble: interval-gated driver
                # snapshots can lag the in-flight rep, and a shared chain file
                # from a later rep would wedge the earlier rep's resume
                checkpoint_path=(checkpoint_path + f"_chain{k}") if checkpoint_path else None,
                checkpoint_interval_s=checkpoint_interval_s,
                kernel=kernel,
            )
        except ShutdownRequested:
            # the in-flight chain checkpointed itself; persist the
            # completed-rep prefix before the CLI exits 75
            if pc is not None:
                pc.save_now(driver_payload(), {**run_id, "next_rep": k})
            raise
        mag[k] = float(res.mag_reached)
        conf[k] = res.s
        steps[k] = res.num_steps
        graphs[k] = g.nbr
        times[k] = res.elapsed_s
        _heartbeat("rep")
        if pc is not None:
            pc.maybe_save(driver_payload(), {**run_id, "next_rep": k + 1})
        _faults.maybe_fail("rep.boundary", key=f"rep={k}")
        if shutdown_requested():
            if pc is not None:
                pc.save_now(driver_payload(), {**run_id, "next_rep": k + 1})
            raise_if_requested(where="rep")
    for k in range(start_k):
        graphs[k] = random_regular_graph(
            n, d, seed=seed + k, method=graph_method
        ).nbr
    if ck is not None:
        ck.remove()
    out = HPREnsembleResult(mag, conf, steps, graphs, times)
    if save_path:
        save_results_npz(
            save_path,
            mag_reached=out.mag_reached,
            conf=out.conf,
            num_steps=out.num_steps,
            graphs=out.graphs,
            time=out.time,
        )
    return out
