"""L5 solvers ("model families"): SA-MCMC initialization search, HPr
reinforced BP, BDCM entropy λ-sweep, forward opinion-consensus sweep."""
