"""Simulated-annealing search for strategic initializations (L5 solver).

Reproduces the semantics of the reference SA chain (`SA_RRG.py:58-88`):
Metropolis over single-spin flips of the *initial* configuration, energy
``E = (a·Σs(0) − b·Σs(end))/n``, per-step annealing ``a ← par_a·a`` capped at
``a_cap`` (cap checked *before* the multiply, as at `SA_RRG.py:80-81`), stop
when the rolled-out end state hits all-+1, timeout after ``max_steps`` with the
sentinel ``m_final = 2`` (`SA_RRG.py:84`).

TPU-first redesign (SURVEY.md §3.1 "hot loop"):

- The reference performs **three** full (p+c−1)-step rollouts per MCMC step
  (`E_delta` twice at `SA_RRG.py:33,36`, stop test at `:85`). Here the
  end-state sum of the *current* configuration is carried in the loop state, so
  each step costs exactly **one** rollout (of the flipped candidate) — a 3×
  algorithmic win before any hardware speedup.
- Replicas (and temperature-ladder points) are a batched leading axis: the
  rollout is one ``[R, n, d]`` gather+sum per dynamics step, masked per-replica
  so finished chains stop changing while the batch runs to completion
  (`lax.while_loop`, no host round-trips).
- Two randomness modes: native JAX PRNG (``fold_in`` per step), or injected
  proposal/uniform streams — common random numbers for bit-parity tests
  against the numpy oracle (SURVEY.md §4.2).

Acceptance arithmetic is float32 by default (`dtype` arg); the numpy oracle
mirrors the same dtype so chains are bit-identical under shared streams.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from graphdyn.config import SAConfig
from graphdyn.ops.dynamics import rule_coefficients


class SAResult(NamedTuple):
    """Per-replica results, mirroring the reference's result arrays
    (`SA_RRG.py:53-56,86-88`)."""

    s: np.ndarray            # int8[R, n] — configuration at stop
    mag_reached: np.ndarray  # f32[R] — m(s(0)) at stop (`SA_RRG.py:86`)
    num_steps: np.ndarray    # int64[R] — MCMC steps taken (`:87`)
    m_final: np.ndarray      # f32[R] — 1.0 on success, 2.0 sentinel on timeout


class _SAState(NamedTuple):
    s: jnp.ndarray         # int8[R, n]
    sum_end: jnp.ndarray   # int32[R]
    a: jnp.ndarray         # f[R]
    b: jnp.ndarray         # f[R]
    t: jnp.ndarray         # int64[R]
    m_final: jnp.ndarray   # f[R]
    active: jnp.ndarray    # bool[R]
    key: jnp.ndarray       # PRNG key per replica [R]
    chunk_t: jnp.ndarray   # int32[] — steps taken in the current chunk (see
    #                        `simulated_annealing(checkpoint_path=...)`)
    traj: jnp.ndarray      # int8[R, T+1, n+2] cached trajectory + ghost and
    #                        trash columns (light-cone mode; [R, 0, 0] in
    #                        full mode)


def _batched_end_sum(nbr, s, steps: int, R_coef: int, C_coef: int):
    """Σ_i s_endstate(s)_i for a batch of spin configurations, via the shared
    hot kernel :func:`graphdyn.ops.dynamics.batched_rollout_impl`."""
    from graphdyn.ops.dynamics import batched_rollout_impl

    s_end = batched_rollout_impl(nbr, s, steps, R_coef, C_coef)
    return s_end.astype(jnp.int32).sum(axis=1)


def draw_sa_proposal(key, t, proposals, uniforms, *, injected, stream_len, n, dt):
    """Per-replica proposal ``(i, u)`` — injected-stream mode reads the
    caller's streams at the per-replica step index; PRNG mode derives from
    ``fold_in(key, t)``. One implementation shared by the unsharded and
    sharded solvers, so their bit-parity is structural at the draw layer."""
    if injected:
        tt = jnp.minimum(t, stream_len - 1).astype(jnp.int32)
        i = jnp.take_along_axis(proposals, tt[:, None], axis=1)[:, 0]
        u = jnp.take_along_axis(uniforms, tt[:, None], axis=1)[:, 0].astype(dt)
    else:
        step_keys = jax.vmap(jax.random.fold_in)(key, t.astype(jnp.uint32))
        ki, ku = jnp.split(jax.vmap(jax.random.split)(step_keys), 2, axis=1)
        i = jax.vmap(lambda k: jax.random.randint(k[0], (), 0, n))(ki)
        u = jax.vmap(lambda k: jax.random.uniform(k[0], (), dt))(ku)
    return i, u


def metropolis_anneal_update(
    active, a, b, t, m_final, sum_end, sum_end_flip, s_i, u,
    *, par_a, par_b, a_cap, b_cap, max_steps, n,
):
    """The per-replica Metropolis accept + anneal + sentinel arithmetic
    (`SA_RRG.py:32-37,74-85`), on vectors of any sharding. Shared by
    :func:`simulated_annealing` and the mesh solver — a change here changes
    both, keeping their advertised bit-parity structural.

    Returns ``(do, sum_end_new, a_new, b_new, t_new, m_final_new,
    active_new)`` where ``do`` masks replicas whose flip was accepted this
    step (the caller applies it to its spin layout)."""
    dt = a.dtype
    # ΔH = (−2a·s_i(0) + b·(Σs_end − Σs_end_flip))/n  (`SA_RRG.py:32-37`)
    delta_H = (
        -2.0 * a * s_i.astype(dt) + b * (sum_end - sum_end_flip).astype(dt)
    ) / n
    accept = u < jnp.exp(-delta_H)
    do = active & accept
    sum_end_new = jnp.where(do, sum_end_flip, sum_end)
    # anneal (cap checked before multiply, `SA_RRG.py:80-81`)
    a_new = jnp.where(a < a_cap, a * par_a, a)
    b_new = jnp.where(b < b_cap, b * par_b, b)
    a_new = jnp.where(active, a_new, a)
    b_new = jnp.where(active, b_new, b)
    t_new = jnp.where(active, t + 1, t)
    timeout = t_new > max_steps
    m_new = jnp.where(timeout, jnp.asarray(2.0, dt), sum_end_new.astype(dt) / n)
    m_final_new = jnp.where(active, m_new, m_final)
    active_new = active & (m_final_new < 1.0) & ~timeout
    return do, sum_end_new, a_new, b_new, t_new, m_final_new, active_new


@partial(
    jax.jit,
    static_argnames=("rollout_steps", "R_coef", "C_coef", "lightcone"),
)
def _sa_init(nbr, s0, key0, a0, b0, *, rollout_steps: int, R_coef: int,
             C_coef: int, lightcone: bool = False):
    R, n = s0.shape
    dt = a0.dtype
    if lightcone:
        from graphdyn.ops.lightcone import batched_trajectory

        traj = batched_trajectory(nbr, s0, rollout_steps, R_coef, C_coef)
        sum_end0 = traj[:, rollout_steps, :n].astype(jnp.int32).sum(axis=1)
    else:
        traj = jnp.zeros((R, 0, 0), jnp.int8)
        sum_end0 = _batched_end_sum(nbr, s0, rollout_steps, R_coef, C_coef)
    m0 = sum_end0.astype(dt) / n
    return _SAState(
        s=s0,
        sum_end=sum_end0,
        a=a0,
        b=b0,
        t=jnp.zeros((R,), jnp.int64 if jax.config.jax_enable_x64 else jnp.int32),
        m_final=m0,
        active=m0 < 1.0,
        key=key0,
        chunk_t=jnp.zeros((), jnp.int32),
        traj=traj,
    )


@partial(
    jax.jit,
    static_argnames=(
        "rollout_steps", "R_coef", "C_coef", "max_steps", "injected",
        "stream_len", "chunk_steps",
    ),
)
# the chunked exact-resume path snapshots the pre-chunk state to the
# checkpoint — donating it would invalidate the buffer being saved
# graftlint: disable-next-line=GD006  checkpoint path reuses the carry
def _sa_loop(
    nbr,
    state: _SAState,
    par_a,
    par_b,
    a_cap,
    b_cap,
    proposals,
    uniforms,
    *,
    rollout_steps: int,
    R_coef: int,
    C_coef: int,
    max_steps: int,
    injected: bool,
    stream_len: int,
    chunk_steps: int | None = None,
    lc_tables=None,
):
    """Run the SA while-loop from ``state`` until every replica stops — or,
    with ``chunk_steps``, for at most that many more steps (the state is then
    a host-visible exact-resume point: re-entering with it continues the
    chain bit-for-bit, since the loop body is step-index-driven).

    With ``lc_tables`` (a :class:`graphdyn.ops.lightcone.LightconeTables`),
    candidate flips are evaluated by rolling only the flip's light cone
    against the cached trajectory in ``state.traj`` — O(ball) per step
    instead of O(n·d) — with bit-identical chain decisions (integer
    dynamics; tested)."""
    R, n = state.s.shape
    dt = state.a.dtype
    lightcone = lc_tables is not None
    if lightcone:
        from graphdyn.ops.lightcone import lightcone_accept, lightcone_flip_delta

    def cond(st: _SAState):
        go = jnp.any(st.active)
        if chunk_steps is not None:
            go = go & (st.chunk_t < chunk_steps)
        return go

    def body(st: _SAState):
        i, u = draw_sa_proposal(
            st.key, st.t, proposals, uniforms,
            injected=injected, stream_len=stream_len, n=n, dt=dt,
        )
        ridx = jnp.arange(R)
        if lightcone:
            # st.s is carried UNCHANGED (stale after the first accept): a
            # live [R, n] spin copy per step would defeat the O(ball)
            # design, so current spins live in traj[:, 0]; readers go
            # through current_s() in simulated_annealing
            s_i = st.traj[ridx, 0, i].astype(jnp.int32)
            delta, vstack = lightcone_flip_delta(
                lc_tables, st.traj, i, R_coef, C_coef, rollout_steps
            )
            sum_end_flip = st.sum_end + delta
        else:
            s_i = st.s[ridx, i].astype(jnp.int32)
            s_flip = st.s.at[ridx, i].set((-s_i).astype(jnp.int8))
            sum_end_flip = _batched_end_sum(
                nbr, s_flip, rollout_steps, R_coef, C_coef
            )

        do, sum_end_new, a_new, b_new, t_new, m_final, active = (
            metropolis_anneal_update(
                st.active, st.a, st.b, st.t, st.m_final,
                st.sum_end, sum_end_flip, s_i, u,
                par_a=par_a, par_b=par_b, a_cap=a_cap, b_cap=b_cap,
                max_steps=max_steps, n=n,
            )
        )
        if lightcone:
            traj_new = lightcone_accept(lc_tables, st.traj, i, vstack, do)
            s_new = st.s                              # stays the placeholder
        else:
            traj_new = st.traj
            s_new = jnp.where(do[:, None], s_flip, st.s)
        return _SAState(
            s_new, sum_end_new, a_new, b_new, t_new, m_final, active, st.key,
            st.chunk_t + 1, traj_new,
        )

    return lax.while_loop(cond, body, state)


def prepare_sa_inputs(
    graph,
    config: SAConfig,
    *,
    n_replicas=None,
    seed=None,
    s0=None,
    a0=None,
    b0=None,
    proposals=None,
    uniforms=None,
    max_steps=None,
):
    """Shared host-side preparation of SA solver inputs — defaults, replica
    broadcast of the (a0, b0) temperature ladder, the step-budget sentinel
    threshold (int64 under x64, clamped to int32 otherwise — `SA_RRG.py:84`),
    and injected-stream normalization. One implementation serves the
    unsharded solver (:func:`simulated_annealing`) and the mesh solver
    (:func:`graphdyn.parallel.sa_sharded.sa_sharded`) so their parity cannot
    drift at the prep layer.

    Returns ``(R, seed, s0, a0, b0, proposals, uniforms, max_steps,
    stream_len, injected)``.
    """
    n = graph.n
    if seed is None:
        seed = config.seed
    if n_replicas is None:
        n_replicas = config.n_replicas if s0 is None else np.shape(s0)[0]
    R = n_replicas

    rng = np.random.default_rng(seed)
    if s0 is None:
        s0 = (2 * rng.integers(0, 2, size=(R, n)) - 1).astype(np.int8)
    s0 = np.asarray(s0, dtype=np.int8).reshape(R, n)

    a0 = np.broadcast_to(
        np.asarray(config.a0_frac * n if a0 is None else a0, dtype=np.float64), (R,)  # graftlint: disable=GD004  host staging; cast to solver dtype on device
    )
    b0 = np.broadcast_to(
        np.asarray(config.b0_frac * n if b0 is None else b0, dtype=np.float64), (R,)  # graftlint: disable=GD004  host staging; cast to solver dtype on device
    )
    if max_steps is None:
        max_steps = config.max_steps if config.max_steps is not None else 2 * n**3
    # under x64 the device counter is int64 and the reference's 2n³ sentinel
    # (`SA_RRG.py:84`) is held exactly; with x64 off the counter canonicalizes
    # to int32, so clamp the threshold (2·10¹² is unreachable wall-clock)
    if not jax.config.jax_enable_x64:
        max_steps = min(int(max_steps), 2**31 - 2)
    max_steps = int(max_steps)

    injected = proposals is not None
    if injected:
        proposals = np.asarray(proposals, dtype=np.int32).reshape(R, -1)
        uniforms = np.asarray(uniforms, dtype=np.float64).reshape(R, -1)  # graftlint: disable=GD004  injected streams keep full precision until the device cast
        stream_len = proposals.shape[1]
        max_steps = min(max_steps, stream_len)
    else:
        stream_len = 1
        proposals = np.zeros((R, 1), np.int32)
        uniforms = np.zeros((R, 1), np.float64)  # graftlint: disable=GD004  placeholder stream, host only
    return R, seed, s0, a0, b0, proposals, uniforms, max_steps, stream_len, injected


def simulated_annealing(
    graph,
    config: SAConfig | None = None,
    *,
    n_replicas: int | None = None,
    seed: int | None = None,
    s0: np.ndarray | None = None,
    a0: np.ndarray | float | None = None,
    b0: np.ndarray | float | None = None,
    proposals: np.ndarray | None = None,
    uniforms: np.ndarray | None = None,
    max_steps: int | None = None,
    dtype=jnp.float32,
    backend: str = "jax_tpu",
    checkpoint_path: str | None = None,
    checkpoint_interval_s: float = 30.0,
    chunk_steps: int = 100_000,
    rollout_mode: str = "full",
    lc_tables=None,
    kernel: str = "auto",
    layout: str = "auto",
    stream_chunks: int = 4,
) -> SAResult:
    """Run batched SA chains.

    ``layout`` selects the node layout (``'auto'`` | ``'padded'`` |
    ``'bucketed'`` | ``'streamed'``): ``'auto'`` routes through
    :func:`graphdyn.ops.bucketed.auto_layout` — a degree CV at or above
    the bucketed threshold (power-law graphs; an RRG sits at 0) relabels
    the graph bucket-major (:func:`graphdyn.graphs.degree_buckets`) so
    the padded tables gather in degree-sorted order, and the returned
    configurations are mapped back to the caller's node ids. The chain
    LAW is label-equivariant but the seeded realization is not (site
    proposals index nodes by id), so a relabeled run is a different —
    equally distributed — chain; injected ``proposals``/``uniforms`` and
    prebuilt ``lc_tables`` are node-indexed and therefore require
    ``layout='padded'``. ``'streamed'`` keeps the caller's labeling but
    evaluates every candidate end-sum through the out-of-core streamed
    rollout (:func:`graphdyn.ops.streamed.streamed_rollout`, chunked over
    ``stream_chunks`` host-resident chunks) — the route for graphs whose
    padded tables exceed the device budget; injected streams stay
    allowed (no relabel), and the chain is bit-identical to
    ``layout='padded'`` (shared draw + Metropolis helpers, integer
    end-sums are engine-independent — tested).

    ``kernel`` selects the anneal execution engine (the PR-5 kernel-knob
    convention, ARCHITECTURE.md "Kernel selection"): ``'auto'`` and
    ``'xla'`` both run THIS solver's XLA while-loop program — the serial
    single-flip chain law, whose schedule already advances inside the
    device loop. ``'pallas'`` is REFUSED here and routes to
    :func:`graphdyn.search.fused_anneal`: the fused one-kernel annealer
    runs a class-parallel chain (a whole distance-2 color class per step),
    which is a *different Markov chain* — silently swapping it in under
    the serial solver's name would change results, and kernel choice in
    this repo moves throughput, never results.

    ``rollout_mode``:

    - ``"full"`` (default): every candidate flip re-rolls the whole graph
      (the reference's cost structure, one rollout per step after the
      3-to-1 fold).
    - ``"lightcone"``: candidates roll only the flip's radius-``(p+c−1)``
      ball against a cached trajectory (:mod:`graphdyn.ops.lightcone`) —
      O(ball) ≈ O(d^(p+c−1)) per step instead of O(n·d), bit-identical
      chain decisions (integer dynamics; parity-tested). Host-side table
      build is O(n·ball); best for the reference regimes n ≲ 1e5. Pass
      ``lc_tables`` (from :func:`graphdyn.ops.lightcone
      .build_lightcone_tables`) to amortize the build across calls on the
      same graph.

    ``a0``/``b0`` may be per-replica arrays — that is the temperature-ladder
    axis of BASELINE.json config 5. The replica-exchange upgrade of that
    axis (seeded swap moves between rungs at chunk boundaries, an
    order-of-magnitude fewer device steps to target — measured) is
    :func:`graphdyn.search.temper_search`, whose swap-free mode is
    bit-exact to this solver on the same ``a0``/``b0`` (tested); the
    whole-independent-set alternative at p=c=1 is
    :func:`graphdyn.search.chromatic_anneal` (ARCHITECTURE.md "Search
    acceleration"). ``proposals``/``uniforms`` (``[R, L]``) switch to
    injected-stream mode for parity testing. ``backend='cpu'`` runs
    the numpy oracle.

    ``checkpoint_path`` enables **exact chain resume** (SURVEY.md §5.4: the
    reference's only persistence is end-of-run `np.savez`, `SA_RRG.py:92`;
    preemption recovery is a new capability): the device loop runs in
    ``chunk_steps``-bounded chunks, the full chain state (spins, cached
    end-sums, annealing weights, step counters, PRNG keys) is snapshotted
    atomically at most every ``checkpoint_interval_s`` seconds, and a rerun
    pointing at an existing checkpoint continues bit-for-bit — the loop body
    is step-index-driven, so splitting it across while-loops cannot change
    the chain. The file is deleted on successful completion.
    """
    if kernel not in ("auto", "xla"):
        if kernel == "pallas":
            raise ValueError(
                "kernel='pallas' on the serial SA solver: the fused "
                "one-kernel annealer is a class-parallel chain, not this "
                "chain — run graphdyn.search.fused_anneal (CLI `graphdyn "
                "fused`) for the LUT-popcount kernel, or keep "
                "kernel='auto'/'xla' here"
            )
        raise ValueError(
            f"kernel must be 'auto', 'xla' or 'pallas', got {kernel!r}"
        )
    if layout not in ("auto", "padded", "bucketed", "streamed"):
        raise ValueError(
            f"layout must be 'auto', 'padded', 'bucketed' or 'streamed', "
            f"got {layout!r}"
        )
    if layout == "auto":
        from graphdyn.ops.bucketed import auto_layout

        layout = auto_layout(graph.deg)
        if layout == "bucketed" and checkpoint_path is not None:
            # resume identity: run_fingerprint hashes the run's edge list,
            # so a bucket-major relabel would orphan every checkpoint
            # written under the caller's labeling (including all pre-layout
            # checkpoints). Auto-routed checkpointed runs therefore pin the
            # padded path; an EXPLICIT layout='bucketed' stays allowed —
            # degree_buckets is deterministic, so its checkpoints are
            # self-consistent across reruns.
            layout = "padded"
    if layout == "bucketed":
        if proposals is not None or uniforms is not None:
            raise ValueError(
                "injected proposals/uniforms are node-indexed: pass "
                "layout='padded' to keep the caller's labeling"
            )
        if lc_tables is not None:
            raise ValueError(
                "prebuilt lightcone tables are node-indexed: pass "
                "layout='padded' to keep the caller's labeling"
            )
        from graphdyn.graphs import degree_buckets, permute_nodes

        order = degree_buckets(graph).order
        g_b, inv = permute_nodes(graph, order)
        res = simulated_annealing(
            g_b, config, n_replicas=n_replicas, seed=seed,
            s0=None if s0 is None else np.asarray(s0)[..., order],
            a0=a0, b0=b0, max_steps=max_steps, dtype=dtype,
            backend=backend, checkpoint_path=checkpoint_path,
            checkpoint_interval_s=checkpoint_interval_s,
            chunk_steps=chunk_steps, rollout_mode=rollout_mode,
            kernel=kernel, layout="padded",
        )
        return res._replace(s=res.s[..., inv])
    if layout == "streamed":
        if backend == "cpu":
            raise ValueError(
                "layout='streamed' is the out-of-core device route; the "
                "numpy oracle is fully resident by construction — drop "
                "backend='cpu' or use layout='padded'"
            )
        if checkpoint_path is not None:
            raise ValueError(
                "layout='streamed' has no chunked-chain resume (the chain "
                "is host-stepped; the streamed rollout's own checkpoints "
                "cover serve jobs, not this chain) — use layout='padded' "
                "for checkpointed SA chains"
            )
        if rollout_mode != "full":
            raise ValueError(
                "rollout_mode='lightcone' caches a device-resident "
                "trajectory, which is exactly what the out-of-core "
                "streamed layout exists to avoid — use rollout_mode='full'"
            )
        # injected proposals/uniforms stay ALLOWED: the streamed layout
        # keeps the caller's node labeling (chunks address global ids),
        # which is the bit-parity lever against layout='padded'
        return _sa_streamed(
            graph, config or SAConfig(), n_replicas=n_replicas, seed=seed,
            s0=s0, a0=a0, b0=b0, proposals=proposals, uniforms=uniforms,
            max_steps=max_steps, dtype=dtype, stream_chunks=stream_chunks,
        )
    config = config or SAConfig()
    n = graph.n
    dyn = config.dynamics
    R_coef, C_coef = rule_coefficients(dyn.rule, dyn.tie)
    rollout = dyn.p + dyn.c - 1

    prep = prepare_sa_inputs(
        graph, config, n_replicas=n_replicas, seed=seed, s0=s0, a0=a0, b0=b0,
        proposals=proposals, uniforms=uniforms, max_steps=max_steps,
    )
    (R, seed, s0, a0, b0, proposals, uniforms,
     max_steps, stream_len, injected) = prep

    if rollout_mode not in ("full", "lightcone"):
        raise ValueError(
            f"rollout_mode must be 'full' or 'lightcone', got {rollout_mode!r}"
        )
    if backend == "cpu":
        if checkpoint_path is not None:
            raise ValueError(
                "checkpoint_path requires the jax backend (the numpy oracle "
                "has no chunked resume); drop --checkpoint or use backend='jax'"
            )
        if rollout_mode != "full":
            raise ValueError(
                "rollout_mode='lightcone' is a device-path optimization; the "
                "numpy oracle always evaluates candidates with the full "
                "rollout (chains are bit-identical either way)"
            )
        np_scalar = np.float32 if dtype == jnp.float32 else np.float64  # graftlint: disable=GD004  oracle precision mirrors the solver dtype
        return _sa_reference_numpy(
            graph, config, s0, a0, b0, proposals if injected else None,
            uniforms if injected else None, max_steps, np_scalar, seed,
        )

    np_dt = np.float32 if dtype == jnp.float32 else np.float64  # graftlint: disable=GD004  dtype mirror for host results
    nbr = jnp.asarray(graph.nbr)
    keys = jax.vmap(jax.random.PRNGKey)(np.arange(R, dtype=np.uint32) + np.uint32(seed))

    if rollout_mode == "lightcone":
        from graphdyn.ops.lightcone import (
            batched_trajectory, resolve_lightcone_tables,
        )

        lc_tables = resolve_lightcone_tables(graph, rollout, lc_tables)
    else:
        lc_tables = None

    ckpt = None
    state = None
    if checkpoint_path is not None:
        from graphdyn.utils.io import ChainCheckpointer, run_fingerprint

        if chunk_steps < 1:
            raise ValueError(f"chunk_steps must be >= 1, got {chunk_steps}")
        ckpt = ChainCheckpointer(
            checkpoint_path, kind="sa_chain", seed=seed,
            # full run identity: same graph, config, budget, dtype, x64
            # mode — and, in injected mode, the caller-supplied streams
            # themselves (a resume under different streams would otherwise
            # pass validation and splice a chimera chain)
            fp=run_fingerprint(
                graph.edges, config, int(max_steps), bool(injected),
                np_dt, bool(jax.config.jax_enable_x64),
                *((np.asarray(proposals), np.asarray(uniforms))
                  if injected else ()),
            ),
            interval_s=checkpoint_interval_s,
            extra_meta={"R": int(R)},
        )
        arrays = ckpt.load_state(check=lambda a: a["s"].shape == (R, n))
        if arrays is not None:
            s_res = jnp.asarray(arrays["s"])
            # traj is a pure function of s — recomputed, never persisted
            traj_res = (
                batched_trajectory(nbr, s_res, rollout, R_coef, C_coef)
                if lc_tables is not None else jnp.zeros((R, 0, 0), jnp.int8)
            )
            state = _SAState(
                s=s_res,
                sum_end=jnp.asarray(arrays["sum_end"]),
                a=jnp.asarray(arrays["a"].astype(np_dt)),
                b=jnp.asarray(arrays["b"].astype(np_dt)),
                t=jnp.asarray(arrays["t"]),
                m_final=jnp.asarray(arrays["m_final"].astype(np_dt)),
                active=jnp.asarray(arrays["active"]),
                key=jnp.asarray(arrays["key"]),
                chunk_t=jnp.zeros((), jnp.int32),
                traj=traj_res,
            )

    if state is None:
        state = _sa_init(
            nbr, jnp.asarray(s0), keys,
            jnp.asarray(a0.astype(np_dt)), jnp.asarray(b0.astype(np_dt)),
            rollout_steps=rollout, R_coef=R_coef, C_coef=C_coef,
            lightcone=lc_tables is not None,
        )

    loop_kwargs = dict(
        rollout_steps=rollout, R_coef=R_coef, C_coef=C_coef,
        max_steps=int(max_steps), injected=injected, stream_len=stream_len,
        lc_tables=lc_tables,
    )
    loop_args = (
        jnp.asarray(np_dt(config.par_a)),
        jnp.asarray(np_dt(config.par_b)),
        jnp.asarray(np_dt(config.a_cap_frac * n)),
        jnp.asarray(np_dt(config.b_cap_frac * n)),
        jnp.asarray(proposals),
        jnp.asarray(uniforms.astype(np_dt)),
    )
    def current_s(st):
        """In light-cone mode the carried ``s`` is loop-invariant (spins
        live in traj[:, 0] to avoid an O(R·n) copy per step)."""
        return st.traj[:, 0, :n] if lc_tables is not None else st.s

    def payload(st):
        out = {
            k: np.asarray(v)
            for k, v in st._asdict().items()
            if k not in ("chunk_t", "traj", "s")  # traj: derived, recomputed
        }
        out["s"] = np.asarray(current_s(st))
        return out

    if ckpt is None:
        state = _sa_loop(nbr, state, *loop_args, **loop_kwargs)
    else:
        state = ckpt.drive(
            state,
            advance=lambda st: _sa_loop(
                nbr, st._replace(chunk_t=jnp.zeros((), jnp.int32)),
                *loop_args, chunk_steps=int(chunk_steps), **loop_kwargs,
            ),
            active=lambda st: bool(jnp.any(st.active)),
            payload=payload,
        )

    s_final = np.asarray(current_s(state))
    mag = s_final.astype(np.float64).sum(axis=1) / n  # graftlint: disable=GD004  host observable, exact sum
    return SAResult(
        s=s_final,
        mag_reached=mag.astype(np_dt),
        num_steps=np.asarray(state.t),
        m_final=np.asarray(state.m_final),
    )


def _sa_streamed(
    graph, config, *, n_replicas, seed, s0, a0, b0, proposals, uniforms,
    max_steps, dtype, stream_chunks,
):
    """``layout='streamed'``: the SAME serial Metropolis chain law, with
    every candidate end-sum computed by the out-of-core streamed rollout
    (:func:`graphdyn.ops.streamed.streamed_rollout`) instead of a
    device-resident gather — the SA route for graphs whose padded tables
    exceed the device budget.

    The chain is host-stepped (one streamed rollout per MCMC step);
    proposal draws and the Metropolis/anneal arithmetic go through the
    SAME shared helpers as the device loop (:func:`draw_sa_proposal`,
    :func:`metropolis_anneal_update`), so bit-parity with
    ``layout='padded'`` is structural: integer end-sums are
    engine-independent (the streamed rollout is bit-exact to the packed
    kernel), and the acceptance arithmetic is literally the same code on
    the same dtype. Node labeling is the caller's throughout."""
    from graphdyn.ops.packed import WORD, pack_spins, unpack_spins
    from graphdyn.ops.streamed import build_stream_plan, streamed_rollout

    n = graph.n
    dyn = config.dynamics
    rollout = dyn.p + dyn.c - 1
    prep = prepare_sa_inputs(
        graph, config, n_replicas=n_replicas, seed=seed, s0=s0, a0=a0,
        b0=b0, proposals=proposals, uniforms=uniforms, max_steps=max_steps,
    )
    (R, seed, s0, a0, b0, proposals, uniforms,
     max_steps, stream_len, injected) = prep
    np_dt = np.float32 if dtype == jnp.float32 else np.float64  # graftlint: disable=GD004  dtype mirror for host results
    W = -(-R // WORD)
    plan = build_stream_plan(graph, W=W, n_chunks=stream_chunks)

    def end_sums(s_batch):
        """Integer Σ_i s_end_i per replica via the streamed engine —
        exact, so chain decisions cannot depend on the engine."""
        out = streamed_rollout(
            graph, pack_spins(np.asarray(s_batch)), rollout,
            rule=dyn.rule, tie=dyn.tie, plan=plan,
        )
        return jnp.asarray(unpack_spins(out, R).astype(np.int32).sum(axis=1))

    s = jnp.asarray(s0)
    a_v = jnp.asarray(a0.astype(np_dt))
    b_v = jnp.asarray(b0.astype(np_dt))
    dt = a_v.dtype
    key = jax.vmap(jax.random.PRNGKey)(
        np.arange(R, dtype=np.uint32) + np.uint32(seed))
    sum_end = end_sums(s0)
    m0 = sum_end.astype(dt) / n
    t = jnp.zeros((R,), jnp.int64 if jax.config.jax_enable_x64 else jnp.int32)
    m_final = m0
    active = m0 < 1.0
    par_a = jnp.asarray(np_dt(config.par_a))
    par_b = jnp.asarray(np_dt(config.par_b))
    a_cap = jnp.asarray(np_dt(config.a_cap_frac * n))
    b_cap = jnp.asarray(np_dt(config.b_cap_frac * n))
    prop_j = jnp.asarray(proposals)
    unif_j = jnp.asarray(uniforms.astype(np_dt))
    ridx = jnp.arange(R)
    # graftlint: disable-next-line=GD015  streamed layout: state pages through host RAM between proposals, so the chain is host-stepped by design — the per-step readback IS the chunk boundary; layout='padded' keeps the fused on-device annealer
    while bool(jnp.any(active)):
        i, u = draw_sa_proposal(
            key, t, prop_j, unif_j,
            injected=injected, stream_len=stream_len, n=n, dt=dt,
        )
        s_i = s[ridx, i].astype(jnp.int32)
        s_flip = s.at[ridx, i].set((-s_i).astype(jnp.int8))
        sum_end_flip = end_sums(s_flip)
        do, sum_end, a_v, b_v, t, m_final, active = metropolis_anneal_update(
            active, a_v, b_v, t, m_final, sum_end, sum_end_flip, s_i, u,
            par_a=par_a, par_b=par_b, a_cap=a_cap, b_cap=b_cap,
            max_steps=max_steps, n=n,
        )
        s = jnp.where(do[:, None], s_flip, s)
    s_final = np.asarray(s)
    mag = s_final.astype(np.float64).sum(axis=1) / n  # graftlint: disable=GD004  host observable, exact sum
    return SAResult(
        s=s_final,
        mag_reached=mag.astype(np_dt),
        num_steps=np.asarray(t),
        m_final=np.asarray(m_final),
    )


def energy(
    graph,
    s,
    a: float,
    b: float,
    p: int,
    c: int,
    rule: str = "majority",
    tie: str = "stay",
    backend: str = "jax_tpu",
) -> float:
    """The SA objective ``E = (a·Σs(0) − b·Σs(end))/n`` (`SA_RRG.py:28-30` —
    defined there but driven only through its flip-delta; exposed here as a
    first-class observable). Batched ``s`` returns one energy per replica."""
    from graphdyn.ops.dynamics import end_state

    s = np.asarray(s)
    batched = s.ndim == 2
    s2 = s if batched else s[None]
    if backend in ("jax", "jax_tpu"):
        # end_state dispatches batched input to the shared batched hot kernel
        s_end = np.asarray(end_state(graph, s2.astype(np.int8), p, c, rule, tie, backend))
    else:
        # the cpu/torch oracles are single-configuration; roll rows one by one
        s_end = np.stack(
            [np.asarray(end_state(graph, row, p, c, rule, tie, backend)) for row in s2]
        )
    n = s2.shape[-1]
    e = (
        a * s2.astype(np.float64).sum(axis=-1)  # graftlint: disable=GD004  host energy oracle, reference f64
        - b * s_end.astype(np.float64).sum(axis=-1)  # graftlint: disable=GD004  host energy oracle, reference f64
    ) / n
    return e if batched else float(e[0])


class SAEnsembleResult(NamedTuple):
    """The reference driver's per-repetition arrays (`SA_RRG.py:53-56,86-88`):
    a FRESH graph is sampled per repetition; ``graphs`` stacks the neighbor
    tables exactly as the reference records them."""

    mag_reached: np.ndarray  # f[N_stat]
    num_steps: np.ndarray    # int[N_stat]
    conf: np.ndarray         # int8[N_stat, n]
    graphs: np.ndarray       # int32[N_stat, n, d]
    m_final: np.ndarray      # f[N_stat]


def sa_ensemble(
    n: int,
    d: int,
    config: SAConfig | None = None,
    *,
    n_stat: int = 5,
    seed: int = 0,
    graph_method: str = "pairing",
    max_steps: int | None = None,
    save_path: str | None = None,
    backend: str = "jax_tpu",
    checkpoint_path: str | None = None,
    checkpoint_interval_s: float = 30.0,
    rollout_mode: str = "full",
    group_size: int | None = None,
    prefetch: int = 2,
    layout: str = "auto",
    stream_chunks: int = 4,
) -> SAEnsembleResult:
    """The reference's experiment driver (`SA_RRG.py:58-92`): ``n_stat``
    repetitions, each on a freshly sampled RRG(n, d). Pass ``save_path`` to
    persist the npz with the reference's key names (`SA_RRG.py:92`).

    ``group_size`` selects the execution pipeline (ARCHITECTURE.md
    "Ensemble pipeline"): the default (None) runs repetitions
    ``group_size``-at-a-time as ONE vmapped device program over stacked
    neighbor tables, with the next group's graphs prefetched on a
    background thread (``prefetch`` bounds the build-ahead; 0 disables the
    thread) — element-wise identical to the serial path, since every
    repetition's RNG streams still derive from ``seed + k``.
    ``group_size=0`` forces the legacy serial repetition loop (always used
    for ``backend='cpu'`` and ``rollout_mode='lightcone'``, which the
    grouped program does not cover).

    ``checkpoint_path`` makes the whole driver preemption-safe: completed
    repetitions are snapshotted (with the next repetition index). Under the
    serial path the in-flight chain additionally checkpoints its own state
    at ``<path>_chain<k>`` (exact resume — see
    :func:`simulated_annealing`); under the grouped path checkpointing is
    group-boundary-granular — an interrupted group re-runs from its start
    on resume, bit-exactly, and snapshots are interchangeable between the
    two paths and between group sizes. Graphs re-derive from ``seed + k``,
    so a resumed run records identical graphs. A graceful shutdown (SIGTERM
    under :func:`graphdyn.resilience.graceful_shutdown`) snapshots the
    completed-rep prefix before propagating
    :class:`~graphdyn.resilience.ShutdownRequested`; fault site
    ``rep.boundary`` fires once per repetition in repetition order (at
    group boundaries under the grouped path).

    ``layout`` is forwarded to each repetition's
    :func:`simulated_annealing`; non-default layouts (``'bucketed'`` /
    ``'streamed'``) run the serial repetition loop — the grouped program
    stacks padded neighbor tables and covers only that layout."""
    if layout not in ("auto", "padded", "bucketed", "streamed"):
        raise ValueError(
            f"layout must be 'auto', 'padded', 'bucketed' or 'streamed', "
            f"got {layout!r}"
        )
    serial_only = (backend == "cpu" or rollout_mode != "full"
                   or layout not in ("auto", "padded"))
    if group_size is None:
        group_size = 0 if serial_only else min(max(n_stat, 1), 8)
    if group_size and serial_only:
        raise ValueError(
            "group_size >= 1 requires the jax backend, "
            "rollout_mode='full' and a padded-family layout (pass "
            "group_size=0 for the serial loop)"
        )
    if group_size:
        from graphdyn.pipeline.sa_group import sa_ensemble_grouped

        return sa_ensemble_grouped(
            n, d, config, n_stat=n_stat, seed=seed,
            graph_method=graph_method, max_steps=max_steps,
            save_path=save_path, backend=backend,
            checkpoint_path=checkpoint_path,
            checkpoint_interval_s=checkpoint_interval_s,
            group_size=group_size, prefetch=prefetch,
        )
    from graphdyn.graphs import random_regular_graph
    from graphdyn.resilience import faults as _faults
    from graphdyn.resilience.shutdown import (
        ShutdownRequested, raise_if_requested, shutdown_requested,
    )
    from graphdyn.resilience.supervisor import beat as _heartbeat
    from graphdyn.utils.io import (
        PeriodicCheckpointer, load_resume_prefix, open_checkpoint,
        save_results_npz,
    )

    config = config or SAConfig()
    mag = np.empty(n_stat, np.float64)  # graftlint: disable=GD004  host result buffer
    steps = np.empty(n_stat, np.int64)
    conf = np.empty((n_stat, n), np.int8)
    graphs = np.empty((n_stat, n, d), np.int32)
    m_final = np.empty(n_stat, np.float64)  # graftlint: disable=GD004  host result buffer

    start_k = 0
    ck = open_checkpoint(checkpoint_path) if checkpoint_path else None
    # driver snapshots share the chain checkpoint's interval: the payload
    # includes the [n_stat, n] conf array, so unconditional per-rep writes
    # would dominate fast-rep runs; a lost tail of completed reps simply
    # recomputes on resume
    pc = (PeriodicCheckpointer(checkpoint_path, interval_s=checkpoint_interval_s)
          if checkpoint_path else None)
    run_id = {"seed": seed, "n_stat": n_stat, "n": n, "d": d,
              "max_steps": max_steps, "graph_method": graph_method,
              "config": repr(config), "backend": backend}
    if ck is not None:
        resumed = load_resume_prefix(ck, run_id)
        if resumed is not None:
            arrays, start_k = resumed
            mag[:start_k] = arrays["mag_reached"][:start_k]
            steps[:start_k] = arrays["num_steps"][:start_k]
            conf[:start_k] = arrays["conf"][:start_k]
            m_final[:start_k] = arrays["m_final"][:start_k]

    for k in range(start_k, n_stat):
        g = random_regular_graph(n, d, seed=seed + k, method=graph_method)
        chain_ckpt = (
            checkpoint_path + f"_chain{k}"
            if checkpoint_path and backend != "cpu"
            and layout != "streamed" else None
        )   # driver-level resume still works for the numpy-oracle backend
        # and the host-stepped streamed layout (which refuses chain
        # checkpoints).
        # Per-rep chain paths: driver snapshots are interval-gated, so
        # next_rep may lag the in-flight rep after a preemption — a SHARED
        # chain path would then hold a later rep's snapshot, which the
        # earlier rep's fingerprint check refuses (resume permanently
        # wedged). Per-rep files are either resumed when their rep re-runs
        # or removed on that rep's completion.
        def driver_payload():
            return {
                "mag_reached": mag, "num_steps": steps,
                "conf": conf, "m_final": m_final,
            }

        try:
            res = simulated_annealing(
                g, config, n_replicas=1, seed=seed + k,
                max_steps=max_steps, backend=backend,
                checkpoint_path=chain_ckpt,
                checkpoint_interval_s=checkpoint_interval_s,
                rollout_mode=rollout_mode,  # cpu+lightcone raises there, loudly
                layout=layout, stream_chunks=stream_chunks,
            )
        except ShutdownRequested:
            # the in-flight chain already checkpointed itself at its chunk
            # boundary; persist the completed-rep prefix too (the periodic
            # driver snapshot may lag), then let the CLI exit 75
            if pc is not None:
                pc.save_now(driver_payload(), {**run_id, "next_rep": k})
            raise
        mag[k] = res.mag_reached[0]
        steps[k] = res.num_steps[0]
        conf[k] = res.s[0]
        graphs[k] = g.nbr
        m_final[k] = res.m_final[0]
        _heartbeat("rep")
        if pc is not None:
            pc.maybe_save(driver_payload(), {**run_id, "next_rep": k + 1})
        _faults.maybe_fail("rep.boundary", key=f"rep={k}")
        if shutdown_requested():
            if pc is not None:
                pc.save_now(driver_payload(), {**run_id, "next_rep": k + 1})
            raise_if_requested(where="rep")
    # graphs for reps completed before a resume re-derive from seed + k
    for k in range(start_k):
        graphs[k] = random_regular_graph(
            n, d, seed=seed + k, method=graph_method
        ).nbr
    if ck is not None:
        ck.remove()
    out = SAEnsembleResult(mag, steps, conf, graphs, m_final)
    if save_path:
        save_results_npz(
            save_path,
            mag_reached=out.mag_reached,
            num_steps=out.num_steps,
            conf=out.conf,
            graphs=out.graphs,
        )
    return out


def _sa_reference_numpy(
    graph, config, s0, a0, b0, proposals, uniforms, max_steps, np_dt, seed
) -> SAResult:
    """Single-threaded numpy oracle with the reference's exact step structure
    (three conceptual rollouts folded to one via the same end-sum cache; the
    chain law is identical). Acceptance arithmetic in ``np_dt`` to match the
    device path bit-for-bit under injected streams."""
    from graphdyn.ops.dynamics import rule_coefficients

    dyn = config.dynamics
    R_coef, C_coef = rule_coefficients(dyn.rule, dyn.tie)
    rollout = dyn.p + dyn.c - 1
    nbr = np.asarray(graph.nbr)
    n = graph.n
    R = s0.shape[0]

    def end_sum(s):
        s_cur = s.astype(np.int64)
        s_ext = np.zeros(n + 1, dtype=np.int64)
        for _ in range(rollout):
            s_ext[:-1] = s_cur
            sums = s_ext[nbr].sum(axis=1)
            s_cur = R_coef * np.sign(2 * sums + C_coef * s_cur)
        return int(s_cur.sum())

    rng = np.random.default_rng(seed)
    out_s = np.empty_like(s0)
    out_mag = np.empty(R, np.float64)  # graftlint: disable=GD004  host result buffer
    out_t = np.empty(R, np.int64)
    out_m = np.empty(R, np.float64)  # graftlint: disable=GD004  host result buffer

    for r in range(R):
        s = s0[r].copy()
        a = np_dt(a0[r])
        b = np_dt(b0[r])
        par_a, par_b = np_dt(config.par_a), np_dt(config.par_b)
        a_cap, b_cap = np_dt(config.a_cap_frac * n), np_dt(config.b_cap_frac * n)
        t = 0
        se = end_sum(s)
        m_final = np_dt(se) / np_dt(n)
        while m_final < 1:
            if proposals is not None:
                i = int(proposals[r, min(t, proposals.shape[1] - 1)])
                u = np_dt(uniforms[r, min(t, uniforms.shape[1] - 1)])
            else:
                i = int(rng.integers(0, n))
                u = np_dt(rng.random())
            s_flip = s.copy()
            s_flip[i] = -s[i]
            se_flip = end_sum(s_flip)
            delta_H = (
                np_dt(-2.0) * a * np_dt(s[i]) + b * np_dt(se - se_flip)
            ) / np_dt(n)
            if u < np.exp(-delta_H):
                s = s_flip
                se = se_flip
            if a < a_cap:
                a = a * par_a
            if b < b_cap:
                b = b * par_b
            t += 1
            if t > max_steps:
                m_final = np_dt(2.0)
            else:
                m_final = np_dt(se) / np_dt(n)
        out_s[r] = s
        out_mag[r] = s.astype(np.float64).sum() / n  # graftlint: disable=GD004  host observable, exact sum
        out_t[r] = t
        out_m[r] = m_final

    return SAResult(
        s=out_s,
        mag_reached=out_mag.astype(np_dt),
        num_steps=out_t,
        m_final=out_m.astype(np_dt),
    )
