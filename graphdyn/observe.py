"""L4 observables: magnetization, consensus, entropy functionals, throughput.

The observable set preserved from the reference (SURVEY.md §5.5): ``m``,
``m_final``/consensus fraction, ``mag_reached``, ``num_steps``, Bethe free
entropy ``φ``, BP mean initial magnetization ``m_init``, tilted entropy
``s(m) = φ + λ·m`` (`ER_BDCM_entropy.ipynb:436`), per-graph stats. Mesh-wide
variants reduce with ``lax.psum`` (see ``graphdyn.parallel``).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def magnetization(s) -> jnp.ndarray:
    """m(s) = Σ s_i / n (`SA_RRG.py:39-40`); works on batched spins
    (reduces the trailing axis)."""
    s = jnp.asarray(s)
    return jnp.mean(s.astype(jnp.float32), axis=-1)


def consensus_fraction(s_end, target: int = 1) -> jnp.ndarray:
    """Fraction of replicas whose end state is the homogeneous ``target``
    consensus (``target`` matches ``DynamicsConfig.attr_value``).

    ``s_end``: int[..., n]; reduces the trailing (node) axis to a bool per
    replica, then averages the leading axes.
    """
    s_end = jnp.asarray(s_end)
    reached = jnp.all(s_end == target, axis=-1)
    return jnp.mean(reached.astype(jnp.float32))


def consensus_fraction_psum(s_end, axis_name: str, target: int = 1) -> jnp.ndarray:
    """Mesh-wide consensus fraction: mean over the local batch, then
    ``lax.pmean`` over the named mesh axis (ICI collective)."""
    local = consensus_fraction(s_end, target)
    return lax.pmean(local, axis_name)


def tilted_entropy(phi, lmbd, m_init) -> jnp.ndarray:
    """Legendre transform s(m_init) = φ + λ·m_init (`ipynb:436`)."""
    return phi + lmbd * m_init


def spin_updates_per_sec(n_spins: int, n_replicas: int, steps: int, seconds: float) -> float:
    """The BASELINE.json headline metric: spin-updates/sec/chip."""
    return n_spins * n_replicas * steps / seconds
