"""Static byte-model admission: refuse an oversized job, never OOM.

The serve worker is shared — one tenant's monster shape must not OOM the
device every other tenant is using. Admission therefore happens BEFORE a
job reaches the device, from the committed byte models alone:

- :func:`graphdyn.ops.pallas_anneal.fused_vmem_bytes` — the fused
  kernel's resident-set model, evaluated at a conservative static
  chromatic bound (``χ ≤ d² + 1``: a distance-2 greedy coloring of a
  degree-``d`` graph never needs more — the real χ, known only after the
  coloring runs, can only be smaller, so admission never under-admits);
- :func:`graphdyn.obs.memband.bucketed_state_bytes` — for
  ``solver='bucketed'`` jobs only: those run the degree-bucketed packed
  rollout (:mod:`graphdyn.ops.bucketed`) on a power-law graph, whose
  resident set genuinely IS edge-count proportional, so the declared
  ``edges`` price the program that executes. The declaration is
  **re-validated by the worker** against the built graph's real table
  (:attr:`graphdyn.graphs.DegreeBuckets.table_entries` vs the admitted
  bound) before any device dispatch — an under-declared job is refused
  at that rung (:class:`DeclaredShapeMismatch`), never run. Fused jobs
  are NEVER priced by this model: the fused annealer's tables are
  padded-``dmax``/χ-bound whatever the node labeling (a bucket-major
  relabel is an isomorphism), so only the fused formula above prices
  them — a model below the program's real resident set is how a shared
  worker OOMs, the exact failure admission exists to prevent;
- :func:`graphdyn.obs.memband.streamed_state_bytes` — for
  ``solver='streamed'`` jobs: the out-of-core rollout
  (:mod:`graphdyn.ops.streamed`) keeps only two chunks device-resident,
  so the model prices the per-chunk working set at the smallest chunk
  count that fits the budget — the route that turns "refused: oversized"
  into "admitted: streamed" (declared ``edges`` required, ``dmax``
  optional for the single-hub feasibility floor; both re-validated by
  the worker against the built graph before dispatch);
- the device memory budget — the plugin's reported ``bytes_limit``
  (:func:`graphdyn.obs.memband.device_memory_stats`) when a device can
  speak for itself, else the ``GRAPHDYN_SERVE_HBM_BUDGET`` env override,
  else a conservative CPU-host default.

A refusal carries the model's numbers in its reason string (modeled bytes
vs budget), so "why was my job refused" is answerable from the job record
alone. The decision also selects the engine: a shape whose model exceeds
the VMEM budget but fits the device budget is ADMITTED on the XLA twin
(same chain law, bit-identical — the degrade moves throughput, never
results).

The ``serve.admit`` fault site injects a **reject storm** (every decision
refuses, with an "injected" reason) — the client-visible failure mode of
an overloaded admission tier, exercised without any real pressure.
"""

from __future__ import annotations

import os
from typing import NamedTuple

from graphdyn.resilience.faults import InjectedFault, maybe_fail

#: fallback device budget when no device reports bytes_limit and no env
#: override is set — deliberately conservative for a shared CPU host
DEFAULT_HBM_BUDGET = 1 << 30


class AdmissionDecision(NamedTuple):
    admitted: bool
    kernel: str         # 'auto' (pallas fits) | 'xla' | 'bucketed' | ''
    reason: str | None  # refusal reason (None when admitted)
    model_bytes: int    # resident-set model of the engine that will run
    budget_bytes: int   # the device budget the model was held against


class DeclaredShapeMismatch(Exception):
    """A ``solver='bucketed'`` job's built graph needs more table entries
    than its declared ``edges`` admitted — the job was under-priced.
    Raised by the worker's pre-dispatch validation; the job is refused
    with this message, never dispatched."""


def chi_bound(d: int) -> int:
    """Static upper bound on the distance-2 chromatic number of a
    degree-``d`` graph (greedy: Δ(G²) + 1 ≤ d² + 1)."""
    return d * d + 1


def device_budget_bytes() -> int:
    """The budget admitted jobs must fit: device-reported ``bytes_limit``
    when available, else ``GRAPHDYN_SERVE_HBM_BUDGET``, else the
    conservative default."""
    env = os.environ.get("GRAPHDYN_SERVE_HBM_BUDGET", "").strip()
    if env:
        try:
            v = int(float(env))
            if v > 0:
                return v
        except ValueError:
            pass
    try:
        from graphdyn.obs.memband import device_memory_stats

        stats, _ = device_memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
    except Exception:  # noqa: BLE001 — admission must never crash the worker
        pass
    return DEFAULT_HBM_BUDGET


def admit(spec: dict, *, key: str = "") -> AdmissionDecision:
    """One admission decision from the committed models — no compilation,
    no device allocation, no exception escapes (a malformed spec is a
    refusal with a reason, not a worker crash)."""
    from graphdyn.ops.packed import WORD
    from graphdyn.ops.pallas_anneal import (
        FUSED_VMEM_BUDGET,
        fused_vmem_bytes,
    )

    budget = device_budget_bytes()
    try:
        maybe_fail("serve.admit", key=key)
    except InjectedFault as e:
        # the injected reject storm: admission stays up but refuses —
        # exactly what clients of an overloaded admission tier observe
        return AdmissionDecision(False, "", f"injected reject storm: {e}",
                                 0, budget)
    try:
        n, d, R = int(spec["n"]), int(spec["d"]), int(spec["replicas"])
        if n < 2 or d < 1 or d >= n or R < 1:
            return AdmissionDecision(
                False, "", f"malformed shape: n={n} d={d} replicas={R}",
                0, budget)
        solver = str(spec.get("solver", "fused"))
        if solver not in ("fused", "bucketed", "streamed"):
            return AdmissionDecision(
                False, "", f"unknown solver {spec.get('solver')!r} "
                "(this service runs the fused annealer, the bucketed "
                "rollout, and the streamed rollout)", 0, budget)
        W = -(-R // WORD)
        if solver == "streamed":
            # the out-of-core ENGINE: only two chunks of the graph are
            # device-resident at once (:mod:`graphdyn.ops.streamed`), so
            # the model prices the per-chunk working set at the smallest
            # chunk count that fits — a shape the resident models refuse
            # is ADMITTED here as long as host RAM holds the tables. The
            # worker re-validates the declared edges/dmax against the
            # built graph before any dispatch (DeclaredShapeMismatch).
            from graphdyn.obs.memband import (
                streamed_chunk_count,
                streamed_min_bytes,
                streamed_state_bytes,
            )

            n_edges = spec.get("edges")
            if n_edges is None:
                return AdmissionDecision(
                    False, "",
                    "streamed solver requires a declared edge count "
                    "('edges'): the per-chunk byte model has no other "
                    "static input", 0, budget)
            n_edges = int(n_edges)
            if n_edges < 0 or n_edges > n * (n - 1) // 2:
                return AdmissionDecision(
                    False, "", f"malformed shape: edges={n_edges} "
                    f"(simple graph on n={n} nodes)", 0, budget)
            dmax = int(spec.get("dmax", min(n - 1, n_edges)))
            if not 0 <= dmax <= n - 1:
                return AdmissionDecision(
                    False, "", f"malformed shape: dmax={dmax} (simple "
                    f"graph on n={n} nodes)", 0, budget)
            floor = 2 * streamed_min_bytes(dmax, W)
            if floor > budget:
                return AdmissionDecision(
                    False, "",
                    f"modeled streamed floor {floor} B (a single-node "
                    f"chunk holding the declared dmax={dmax} hub, double-"
                    f"buffered) exceeds the device budget {budget} B — "
                    "no chunking can stream this shape", floor, budget)
            shards = spec.get("shards", 1)
            try:
                shards = int(shards)
            except (TypeError, ValueError):
                return AdmissionDecision(
                    False, "", f"malformed shards declaration "
                    f"{spec.get('shards')!r} (want an int >= 1)", 0, budget)
            if shards < 1:
                return AdmissionDecision(
                    False, "", f"malformed shards declaration "
                    f"shards={shards} (want an int >= 1)", 0, budget)
            if shards > 1:
                try:
                    import jax

                    n_dev = len(jax.devices())
                except Exception:  # noqa: BLE001 — no backend = 1 device
                    n_dev = 1
                if shards > n_dev:
                    return AdmissionDecision(
                        False, "",
                        f"declared shards={shards} but this worker has "
                        f"{n_dev} devices — the sharded streamed engine "
                        "needs one device per shard", 0, budget)
            # the PER-SHARD byte model (ISSUE 20): each of the S shards
            # owns ~n/S nodes and ~edges/S adjacency, chunked against ITS
            # device's budget — so the admission frontier scales ~S× with
            # the shard count. The single-node floor stays GLOBAL: hubs
            # are vertex-cut replicated, but a non-hub chunk must still
            # hold its widest row on one device.
            n_p = -(-n // shards)
            e_p = -(-n_edges // shards)
            chunks = streamed_chunk_count(n_p, W, e_p, budget)
            if chunks is None:
                return AdmissionDecision(
                    False, "",
                    f"modeled per-shard streamed resident set "
                    f"{streamed_state_bytes(n_p, W, e_p, max(n_p, 1))} B "
                    f"at one-node chunks still exceeds the device budget "
                    f"{budget} B (n={n}, edges={n_edges}, replicas={R}, "
                    f"shards={shards})",
                    streamed_state_bytes(n_p, W, e_p, max(n_p, 1)), budget)
            model = streamed_state_bytes(n_p, W, e_p, chunks)
            return AdmissionDecision(True, "streamed", None, model, budget)
        if solver == "bucketed":
            # the edge-proportional ENGINE: the worker builds a power-law
            # graph, lays it out in degree buckets, and runs the
            # ops/bucketed rollout — the one serve program whose resident
            # set tracks the edge count, so the declared edges price what
            # actually runs (and the worker re-validates the declaration
            # against the built table before dispatch). Fused jobs never
            # take this price: their tables are padded-dmax/chi-bound
            # regardless of node labeling.
            from graphdyn.obs.memband import (
                bucketed_state_bytes,
                bucketed_table_entries_bound,
            )

            n_edges = spec.get("edges")
            if n_edges is None:
                return AdmissionDecision(
                    False, "",
                    "bucketed solver requires a declared edge count "
                    "('edges'): the edge-proportional byte model has no "
                    "other static input", 0, budget)
            n_edges = int(n_edges)
            if n_edges < 0 or n_edges > n * (n - 1) // 2:
                return AdmissionDecision(
                    False, "", f"malformed shape: edges={n_edges} "
                    f"(simple graph on n={n} nodes)", 0, budget)
            model = bucketed_state_bytes(
                n, W, bucketed_table_entries_bound(n, n_edges))
            if model > budget:
                return AdmissionDecision(
                    False, "",
                    f"modeled bucketed resident set {model} B exceeds the "
                    f"device budget {budget} B (n={n}, edges={n_edges}, "
                    f"replicas={R}: refuse at admission, never OOM the "
                    "shared worker)",
                    model, budget)
            return AdmissionDecision(True, "bucketed", None, model, budget)
        # the fused annealer's price is the padded formula whatever the
        # job declares: a bucket-major relabel is an isomorphism (same
        # dmax, same chi, same nbr_ext/LUT/CSA shapes), so no declaration
        # can shrink this program's resident set
        model = fused_vmem_bytes(n, W, chi_bound(d), d)
    except (KeyError, TypeError, ValueError) as e:
        return AdmissionDecision(False, "", f"malformed spec: {e}", 0,
                                 budget)
    if model > budget:
        return AdmissionDecision(
            False, "",
            f"modeled resident set {model} B exceeds the device budget "
            f"{budget} B (n={n}, replicas={R}: refuse at admission, "
            "never OOM the shared worker)",
            model, budget)
    # within budget: the kernel knob stays 'auto' when the VMEM model
    # admits the fused Pallas kernel, else the XLA twin carries the job
    kernel = "auto" if model <= FUSED_VMEM_BUDGET else "xla"
    return AdmissionDecision(True, kernel, None, model, budget)
