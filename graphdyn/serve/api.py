"""The client face of the service: submit / status / result over the spool.

Deliberately a library over the durable spool rather than a socket
protocol: the filesystem IS the API surface (atomic whole-record reads,
the run journal as the audit log), so a client needs no live server to
submit — jobs enqueued against a dead server are served the moment one
boots. Everything here is jax-free; importing the client costs nothing.
"""

from __future__ import annotations

import os

from graphdyn.serve.spool import DONE, Spool


def submit(root: str, spec: dict, tenant: str = "default", *,
           timeout_s: float | None = None) -> str:
    """Durably enqueue one job; returns its id (usable immediately for
    :func:`status` / :func:`result`, even before any server boots)."""
    return Spool(root).submit(spec, tenant, timeout_s=timeout_s)


def status(root: str, job_id: str) -> dict:
    """The job's full record — state, spec, requeue/crash counts, and the
    reason string for any refusal/requeue/quarantine."""
    return Spool(root).load(job_id)


def queue(root: str) -> dict:
    """Queue-depth summary: job counts per state."""
    return Spool(root).counts()


def result(root: str, job_id: str) -> dict:
    """The finished job's arrays (``conf``, ``m_end``, ``mag_reached``,
    ``steps_to_target``). Raises if the job is not done — the record's
    state and reason say why."""
    from graphdyn.utils.io import load_results_npz

    rec = Spool(root).load(job_id)
    if rec["state"] != DONE:
        raise RuntimeError(
            f"job {job_id} is {rec['state']!r}, not done"
            + (f" (reason: {rec['reason']})" if rec.get("reason") else ""))
    path = rec["result"]
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"job {job_id} is done but its result file is missing: {path}")
    return load_results_npz(path)
