"""Service lifecycle: boot → recover → warm → serve → drain.

The one entry point behind both ``python -m graphdyn.serve`` and
``graphdyn serve run``. Boot order is the robustness story in miniature:

1. **recover** — any job a killed worker left ``running`` is requeued
   before anything else happens (the spool is the queue; a restarted
   server owes its tenants exactly the jobs the dead one was holding);
2. **warm** — AOT warm-up of the hottest shape classes among the
   recovered queue, so the first post-restart job pays a bucket hit, not
   a cold compile;
3. **serve** — the worker loop runs on the MAIN thread (it is the one
   consumer of the process-wide shutdown flag: SIGTERM lands at the next
   fused chunk boundary, the in-flight job is requeued, and the process
   exits 75 for the supervisor to restart — the PR-10 ladder, serving
   edition).

``max_jobs`` / ``idle_exit_s`` bound the loop for tests and the soak
harness; a production server passes neither and runs until preempted.
"""

from __future__ import annotations

import time

from graphdyn.resilience.shutdown import (
    EX_TEMPFAIL,
    ShutdownRequested,
    shutdown_requested,
)
from graphdyn.serve.bucketing import BucketCache
from graphdyn.serve.spool import PENDING, Spool
from graphdyn.serve.worker import Worker


def run_service(root: str, *, job_timeout_s: float | None = None,
                max_jobs: int | None = None,
                idle_exit_s: float | None = None,
                warm: bool = True, poll_s: float = 0.05) -> int:
    """Serve the spool at ``root``; returns the process exit code
    (0 = drained/idle-exited cleanly, 75 = preempted mid-serve with the
    in-flight job safely requeued)."""
    from graphdyn import obs

    spool = Spool(root)
    recovered = spool.recover()
    if recovered:
        obs.counter("serve.recovered", jobs=len(recovered))
    cache = BucketCache()
    # warm only what admission would admit: an oversized pending spec must
    # be refused by the byte model, not compiled by the warm-up
    from graphdyn.serve.admission import admit

    pending = [r["spec"] for r in spool.jobs()
               if r["state"] == PENDING and admit(r["spec"]).admitted]
    if warm and pending:
        with obs.timed("serve.boot_warm", jobs=len(pending)):
            cache.warm(pending)
    worker = Worker(spool, cache=cache, default_timeout_s=job_timeout_s,
                    poll_s=poll_s)
    served = 0
    idle_since = time.monotonic()
    try:
        while not shutdown_requested():
            if worker.step():
                served += 1
                idle_since = time.monotonic()
                if max_jobs is not None and served >= max_jobs:
                    return 0
                continue
            if idle_exit_s is not None and (
                    time.monotonic() - idle_since) >= idle_exit_s:
                return 0
            # graftrace: disable-next-line=GT005  idle poll of the durable queue between submissions — the spool is a filesystem, there is no condition variable
            time.sleep(poll_s)
    except ShutdownRequested:
        # the in-flight job was requeued by the worker before the
        # re-raise; exit 75 tells the supervisor "restart me"
        return EX_TEMPFAIL
    return EX_TEMPFAIL
