"""``python -m graphdyn.serve`` — the standalone service process.

The thin wrapper: argparse, the graceful-shutdown scope (SIGTERM/SIGINT
land at fused chunk boundaries), and :func:`graphdyn.serve.run_service`.
The full-featured entry point (obs recording, profiles, supervision of
the server itself) is ``graphdyn serve run`` in :mod:`graphdyn.cli`; this
one exists so a bare container can serve with nothing but the package.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    from graphdyn.resilience.shutdown import graceful_shutdown
    from graphdyn.serve.lifecycle import run_service

    p = argparse.ArgumentParser(
        prog="python -m graphdyn.serve",
        description="serve a durable job spool (exit 0 drained/idle, "
                    "75 preempted with the in-flight job requeued)")
    p.add_argument("root", help="spool directory (created if missing)")
    p.add_argument("--job-timeout", type=float, default=None, metavar="S",
                   help="default per-job deadline: overstaying jobs are "
                        "checkpoint-evicted and requeued with an "
                        "escalated slice")
    p.add_argument("--max-jobs", type=int, default=None, metavar="N",
                   help="exit 0 after settling N jobs (tests/soak)")
    p.add_argument("--idle-exit", type=float, default=None, metavar="S",
                   help="exit 0 after S seconds with an empty queue "
                        "(default: serve forever)")
    p.add_argument("--no-warm", action="store_true",
                   help="skip boot-time AOT warm-up of hot shape classes")
    args = p.parse_args(argv)
    with graceful_shutdown():
        return run_service(
            args.root, job_timeout_s=args.job_timeout,
            max_jobs=args.max_jobs, idle_exit_s=args.idle_exit,
            warm=not args.no_warm)


if __name__ == "__main__":
    sys.exit(main())
