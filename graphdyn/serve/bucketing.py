"""Shape-class bucketing: one resident compiled program serves many users.

The pod-scale throughput recipe is multiplexing independent problems onto
one warm program. Two costs stand between a submitted job and device
sweeps: the host-side table build (distance-2 coloring + LUT masks,
:func:`graphdyn.ops.pallas_anneal.build_fused_tables` — identical for
every job on the same graph) and the XLA compile (identical for every job
whose traced SHAPES match). This module buckets jobs accordingly:

- the **table cache** is keyed by the full graph identity
  ``(n, d, graph_seed, rule, tie)`` — a repeat job on the same graph skips
  the coloring entirely;
- the **shape class** ``(n, d, rule, tie, W)`` names the compiled-program
  bucket (χ and the table shapes are a function of the graph identity;
  the packed word count ``W`` is the replica axis after 32-per-word
  packing — concurrent tenants land in one class when their jobs trace
  the same program, which is what keeps the device busy for everyone);
- **AOT warm-up** at boot runs a one-sweep probe of the hottest classes
  among the recovered queue, so the first tenant job after a restart pays
  a bucket hit, not a cold compile (the persistent compile cache —
  ``--compile-cache`` — is the cross-process backbone; this is the
  in-process half).

Hit/miss counters feed the ``serve_bucket_hit_rate`` bench row and the
``serve.bucket`` obs counter.
"""

from __future__ import annotations

import threading


def graph_key(spec: dict) -> tuple:
    """Full graph identity — the table cache key. The solver leads the
    tuple (fused jobs build an RRG + fused tables, bucketed jobs a
    power-law graph + degree-bucket layout — same ``(n, d, seed)`` names
    different graphs per engine), and bucketed identities carry the
    power-law exponent."""
    solver = str(spec.get("solver", "fused"))
    key = (solver, int(spec["n"]), int(spec["d"]), int(spec["graph_seed"]),
           str(spec["rule"]), str(spec["tie"]))
    if solver in ("bucketed", "streamed"):
        key += (float(spec.get("gamma", 2.5)),)
    return key


def shape_key(spec: dict) -> tuple:
    """The compiled-program shape class: graph identity minus the seed
    (same-shape graphs trace the same program), plus the packed replica
    word count (the device-side replica axis)."""
    from graphdyn.ops.packed import WORD

    W = -(-int(spec["replicas"]) // WORD)
    return (str(spec.get("solver", "fused")), int(spec["n"]),
            int(spec["d"]), str(spec["rule"]), str(spec["tie"]), W)


class BucketCache:
    """Graph + table cache with hit accounting. One per server; the worker
    thread and the boot-time warm-up share it under one lock (declared in
    CONCURRENCY_LEDGER.json)."""

    def __init__(self, max_graphs: int = 32):
        self.max_graphs = max_graphs
        self._lock = threading.Lock()
        self._graphs: dict = {}     # graph_key -> (Graph, FusedTables)
        self._hits = 0
        self._misses = 0

    def stats(self) -> dict:
        with self._lock:
            total = self._hits + self._misses
            return {
                "hits": self._hits, "misses": self._misses,
                "hit_rate": (self._hits / total) if total else None,
                "resident_graphs": len(self._graphs),
            }

    def tables_for(self, spec: dict):
        """``(graph, tables)`` for the job — cached per graph identity.
        Insertion-ordered eviction keeps the resident set bounded (a
        multi-tenant server must not accumulate every graph it ever
        served)."""
        from graphdyn import obs

        gk = graph_key(spec)
        with self._lock:
            hit = gk in self._graphs
            if hit:
                self._hits += 1
                pair = self._graphs[gk]
            else:
                self._misses += 1
        obs.counter("serve.bucket", hit=int(hit), n=gk[1], d=gk[2])
        if hit:
            return pair
        pair = self._build(spec)
        with self._lock:
            while len(self._graphs) >= self.max_graphs:
                self._graphs.pop(next(iter(self._graphs)))
            self._graphs[gk] = pair
        return pair

    def _build(self, spec: dict):
        from graphdyn.config import DynamicsConfig, SAConfig
        from graphdyn.graphs import random_regular_graph
        from graphdyn.ops.pallas_anneal import build_fused_tables

        from graphdyn import obs

        solver = str(spec.get("solver", "fused"))
        if solver == "streamed":
            # the out-of-core engine caches only the GRAPH: the chunk
            # plan depends on the job's replica word count (W sets the
            # slab bytes), so the worker builds it per job against the
            # live device budget — the graph build is the heavy part
            from graphdyn.graphs import powerlaw_graph

            with obs.timed("serve.tables_build", n=int(spec["n"]),
                           d=int(spec["d"])):
                g = powerlaw_graph(
                    int(spec["n"]), gamma=float(spec.get("gamma", 2.5)),
                    dmin=int(spec["d"]), seed=int(spec["graph_seed"]))
                return g, None

        if solver == "bucketed":
            # the edge-proportional engine's "tables" are the graph plus
            # its degree-bucket layout: a power-law realization (d = dmin,
            # seeded) laid out by degree_buckets — no coloring, no LUT
            # masks, and a resident set the admission byte model actually
            # describes
            from graphdyn.graphs import degree_buckets, powerlaw_graph

            with obs.timed("serve.tables_build", n=int(spec["n"]),
                           d=int(spec["d"])):
                g = powerlaw_graph(
                    int(spec["n"]), gamma=float(spec.get("gamma", 2.5)),
                    dmin=int(spec["d"]), seed=int(spec["graph_seed"]))
                return g, degree_buckets(g)

        with obs.timed("serve.tables_build", n=int(spec["n"]),
                       d=int(spec["d"])):
            g = random_regular_graph(int(spec["n"]), int(spec["d"]),
                                     seed=int(spec["graph_seed"]))
            cfg = SAConfig(dynamics=DynamicsConfig(
                p=1, c=1, rule=str(spec["rule"]), tie=str(spec["tie"])))
            # the COLORING seed is the graph's, not the job's: the
            # distance-2 coloring inside the tables is seeded, and these
            # tables are shared by every job on this graph — keying the
            # coloring off one job's chain seed would make a served
            # result depend on which tenant's job happened to build the
            # cache entry (observed as a soak parity failure). The chain
            # seed stays the job's own, passed to fused_anneal directly
            tables = build_fused_tables(g, cfg,
                                        seed=int(spec["graph_seed"]))
        return g, tables

    def warm(self, specs: list[dict], *, top_k: int = 2) -> list[tuple]:
        """AOT warm-up of the hottest shape classes in ``specs`` (the
        recovered queue at boot): build tables and run a one-sweep probe
        so the compile happens before the first tenant job. Returns the
        warmed class keys."""
        from collections import Counter

        from graphdyn import obs

        # warm-up probes dispatch the fused annealer; bucketed-solver
        # jobs compile on first dispatch instead (their rollout program
        # is far cheaper to trace than the fused chain)
        specs = [s for s in specs
                 if str(s.get("solver", "fused")) == "fused"]
        by_class = Counter(shape_key(s) for s in specs)
        warmed = []
        for cls, _ in by_class.most_common(top_k):
            probe = next(s for s in specs if shape_key(s) == cls)
            with obs.timed("serve.warmup", n=cls[1], d=cls[2]):
                from graphdyn.config import DynamicsConfig, SAConfig
                from graphdyn.search.fused import fused_anneal

                g, tables = self.tables_for(probe)
                cfg = SAConfig(dynamics=DynamicsConfig(
                    p=1, c=1, rule=str(probe["rule"]),
                    tie=str(probe["tie"])))
                # one FULL-SIZE chunk (the job's own chunk_sweeps): the
                # chunk step count is a static arg of the fused program,
                # so a probe at a different chunk size would warm the
                # wrong compile — this is exactly the program the class's
                # jobs dispatch
                cs = int(probe["chunk_sweeps"])
                fused_anneal(
                    g, cfg, n_replicas=int(probe["replicas"]),
                    seed=int(probe["seed"]), max_sweeps=cs,
                    chunk_sweeps=cs, kernel="auto", tables=tables,
                )
            warmed.append(cls)
        return warmed
