"""The durable filesystem job spool — the serve queue that survives kills.

One JSON record per job under ``<root>/jobs/``, written atomically
(temp + ``os.replace`` through :func:`graphdyn.utils.io.write_json_atomic`
— the GD007 discipline), so a reader or a restarted server sees either the
old record or the new one, never a torn job. The spool IS the queue: a
server restarted against an existing root recovers every pending job from
disk alone, and any job left ``running`` by a killed worker is requeued on
recovery (the job's result is a pure function of its spec — the fused
chain's counter RNG makes a replayed job bit-exact, so requeue-from-zero
is exact resume).

Job state machine (ARCHITECTURE.md "Serving")::

    pending ──claim──▶ running ──finish──▶ done
       ▲                  │
       │   requeue        │ evict (per-job timeout) /
       └──────────────────┤ requeue (dispatch retry exhausted, preempt,
                          │          crash below the quarantine bar)
                          ├──────▶ quarantined (N same-site crashes)
    pending ──refuse──▶ refused   (admission: byte model over budget)
    running ──refuse──▶ refused   (bucketed engine: built graph exceeds
                                   the declared edge count's admitted
                                   model — under-priced, never dispatched)

Every transition lands in the run journal (``run_journal.jsonl``,
:func:`graphdyn.resilience.store.journal_event`) under the ``serve.*`` ops
— the PR-9 evidence trail grows a serving chapter.
"""

from __future__ import annotations

import json
import os
import threading

from graphdyn.resilience.store import JOURNAL_NAME, journal_event
from graphdyn.utils.io import write_json_atomic

#: job states (the record's ``state`` field)
PENDING = "pending"
RUNNING = "running"
DONE = "done"
REFUSED = "refused"
QUARANTINED = "quarantined"

STATES = (PENDING, RUNNING, DONE, REFUSED, QUARANTINED)

#: job-record schema version, stamped in every record
SPOOL_SCHEMA = 1

#: spec defaults — a submitted spec is normalized ONCE at submit time, so
#: the on-disk record (not the server's code version) defines the job
SPEC_DEFAULTS: dict = {
    # 'fused' (the annealer on an RRG) or 'bucketed' (the degree-bucketed
    # packed rollout on a power-law graph — the edge-proportional engine;
    # graphdyn.serve.admission prices each by the model of the program it
    # actually runs)
    "solver": "fused",
    "n": 64,
    "d": 3,                  # fused: RRG degree; bucketed: power-law dmin
    "graph_seed": 0,
    "seed": 0,
    "rule": "majority",
    "tie": "stay",
    "replicas": 32,
    "m_target": 0.9,
    "max_sweeps": 64,
    "chunk_sweeps": 16,
    # bucketed-solver declarations: 'edges' (REQUIRED for
    # solver='bucketed') prices admission with the edge-proportional byte
    # model, and the worker re-validates it against the built graph's
    # table before dispatch; 'gamma' is the power-law exponent of the
    # served graph. Both are inert on fused jobs — the fused annealer's
    # resident set is padded-dmax-bound whatever a tenant declares, so no
    # declaration can discount its price. ('degree_cv' is retained so
    # pre-existing on-disk records still parse; it no longer affects
    # admission.)
    "edges": None,
    "degree_cv": 0.0,
    "gamma": 2.5,
    # streamed-solver declaration: the worst hub degree, input to the
    # single-node-chunk feasibility floor (solver='streamed'; optional —
    # admission defaults to the min(n-1, edges) worst case). Worker-
    # validated against the built graph like 'edges'.
    "dmax": None,
    # streamed-solver shard count: the job is priced by the PER-SHARD
    # streamed_state_bytes model (each of S shards owns ~n/S nodes and
    # ~edges/S adjacency against its own device budget, so the admission
    # frontier scales ~S×); the worker re-validates the built shard
    # plan's double-buffered peak against that promise before any device
    # work, and refuses declarations exceeding the worker's device count.
    "shards": 1,
}


def normalize_spec(spec: dict) -> dict:
    """Fill defaults and reject unknown keys — the one spec parser, shared
    by submit (CLI/API) and the worker's replay path, so a record written
    by an older server still means the same job."""
    unknown = sorted(set(spec) - set(SPEC_DEFAULTS))
    if unknown:
        raise ValueError(
            f"unknown job spec key(s) {unknown}; known: "
            f"{sorted(SPEC_DEFAULTS)}"
        )
    out = dict(SPEC_DEFAULTS)
    out.update(spec)
    return out


class Spool:
    """The filesystem job store. All mutation goes through atomic
    whole-record replacement under one in-process lock; cross-process
    consumers (a status poll racing the worker) read consistent records by
    construction. One worker per spool root is the deployment contract —
    the restart-recovery path (not file locking) is what makes a killed
    worker's jobs safe."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.jobs_dir = os.path.join(self.root, "jobs")
        self.results_dir = os.path.join(self.root, "results")
        self.journal = os.path.join(self.root, JOURNAL_NAME)
        self._lock = threading.Lock()
        os.makedirs(self.jobs_dir, exist_ok=True)
        os.makedirs(self.results_dir, exist_ok=True)

    # -- paths ------------------------------------------------------------

    def record_path(self, job_id: str) -> str:
        return os.path.join(self.jobs_dir, job_id + ".json")

    def result_path(self, job_id: str) -> str:
        return os.path.join(self.results_dir, job_id + ".npz")

    # -- reads ------------------------------------------------------------

    def load(self, job_id: str) -> dict:
        with open(self.record_path(job_id), encoding="utf-8") as f:
            return json.load(f)

    def jobs(self) -> list[dict]:
        """Every job record, submit-ordered (ids embed the sequence)."""
        out = []
        for name in sorted(os.listdir(self.jobs_dir)):
            if name.endswith(".json"):
                out.append(self.load(name[:-len(".json")]))
        return out

    def counts(self) -> dict:
        c: dict = {s: 0 for s in STATES}
        for rec in self.jobs():
            c[rec["state"]] = c.get(rec["state"], 0) + 1
        return c

    # -- transitions ------------------------------------------------------

    def _write(self, rec: dict) -> None:
        write_json_atomic(self.record_path(rec["id"]), rec, indent=1)

    def submit(self, spec: dict, tenant: str, *,
               timeout_s: float | None = None) -> str:
        """Durably enqueue one job; returns its id. The record on disk is
        the submission — a server that boots later serves it."""
        spec = normalize_spec(spec)
        with self._lock:
            seqs = [int(n[1:7]) for n in os.listdir(self.jobs_dir)
                    if n.endswith(".json") and n[1:7].isdigit()]
            job_id = f"j{(max(seqs) + 1 if seqs else 1):06d}-{tenant}"
            self._write({
                "schema": SPOOL_SCHEMA, "id": job_id, "tenant": tenant,
                "state": PENDING, "spec": spec,
                "timeout_s": timeout_s, "requeues": 0, "crashes": 0,
                "reason": None, "result": self.result_path(job_id),
            })
        journal_event(self.journal, "serve.submit",
                      job=job_id, tenant=tenant)
        return job_id

    def claim(self) -> dict | None:
        """Lowest-id pending job → running, or None when drained."""
        with self._lock:
            for rec in self.jobs():
                if rec["state"] == PENDING:
                    rec["state"] = RUNNING
                    self._write(rec)
                    return rec
        return None

    def _transition(self, job_id: str, state: str, *, reason=None,
                    bump_requeues=False, bump_crashes=False) -> dict:
        with self._lock:
            rec = self.load(job_id)
            rec["state"] = state
            if reason is not None:
                rec["reason"] = reason
            if bump_requeues:
                rec["requeues"] += 1
            if bump_crashes:
                rec["crashes"] += 1
            self._write(rec)
            return rec

    def finish(self, job_id: str) -> dict:
        rec = self._transition(job_id, DONE)
        journal_event(self.journal, "serve.done",
                      job=job_id, tenant=rec["tenant"],
                      requeues=rec["requeues"])
        return rec

    def refuse(self, job_id: str, reason: str) -> dict:
        rec = self._transition(job_id, REFUSED, reason=reason)
        journal_event(self.journal, "serve.refuse",
                      job=job_id, tenant=rec["tenant"], reason=reason)
        return rec

    def requeue(self, job_id: str, reason: str, *,
                crashed: bool = False) -> dict:
        rec = self._transition(job_id, PENDING, reason=reason,
                               bump_requeues=True, bump_crashes=crashed)
        journal_event(self.journal, "serve.requeue",
                      job=job_id, tenant=rec["tenant"],
                      requeues=rec["requeues"], reason=reason)
        return rec

    def quarantine(self, job_id: str, site: str, crashes: int) -> dict:
        rec = self._transition(
            job_id, QUARANTINED,
            reason=f"{crashes} crash(es) at {site}")
        journal_event(self.journal, "serve.quarantine",
                      job=job_id, tenant=rec["tenant"],
                      site=site, crashes=crashes)
        return rec

    # -- restart recovery --------------------------------------------------

    def recover(self) -> list[str]:
        """Requeue every job a killed worker left ``running`` — the boot
        path of a restarted server. Returns the requeued ids."""
        requeued = []
        for rec in self.jobs():
            if rec["state"] == RUNNING:
                self.requeue(rec["id"],
                             "recovered: worker died while running")
                requeued.append(rec["id"])
        return requeued
