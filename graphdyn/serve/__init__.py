"""graphdyn.serve — the always-on multi-tenant job service.

ROADMAP item 2's missing piece: every engine component exists (the fused
zero-sync annealer, durable checkpoints, the exit-75/130/86 supervision
ladder, the graftrace concurrency gate) but nothing *serves*. This package
is the long-lived process that accepts jobs, survives bad ones, and keeps
the device busy for everyone else — the pod-scale Ising throughput recipe
(one resident program fed many independent problems) with the robustness
ladder wrapped around every job.

Layering (ARCHITECTURE.md "Serving"):

- :mod:`~graphdyn.serve.spool` — the durable filesystem job store
  (submit/status/result survive a server restart from disk alone);
- :mod:`~graphdyn.serve.admission` — static byte-model admission: an
  oversized job is refused with a reason, never OOMs the worker;
- :mod:`~graphdyn.serve.bucketing` — (graph, rule, solver, params) shape
  classes with table reuse and AOT warm-up of hot classes at boot;
- :mod:`~graphdyn.serve.worker` — the persistent worker loop: per-job
  timeout → checkpoint-eviction → requeue, per-tenant crash quarantine,
  heartbeats at job boundaries;
- :mod:`~graphdyn.serve.lifecycle` — boot/recover/drain orchestration
  behind ``python -m graphdyn.serve`` and ``graphdyn serve``;
- :mod:`~graphdyn.serve.api` — the thin client face over the spool.

Everything heavy (jax, the solvers) is imported lazily inside functions —
submitting a job to a spool costs no device runtime.
"""

from graphdyn.serve.spool import (  # noqa: F401
    DONE,
    PENDING,
    QUARANTINED,
    REFUSED,
    RUNNING,
    Spool,
    normalize_spec,
)
from graphdyn.serve.admission import AdmissionDecision, admit  # noqa: F401
from graphdyn.serve.bucketing import BucketCache, shape_key  # noqa: F401
from graphdyn.serve.worker import Worker  # noqa: F401
from graphdyn.serve.lifecycle import run_service  # noqa: F401
