"""The persistent worker loop: every job wrapped in the robustness ladder.

One job's journey through the ladder (ARCHITECTURE.md "Serving"):

1. **admission** — the committed byte models refuse an oversized shape
   with a reason before it touches the device (``serve.admit`` fault site
   injects the reject storm);
2. **dispatch** — the ``serve.dispatch`` fault site models the transient
   infrastructure failure in front of the device (a coordinator blip, a
   compile-cache NFS hiccup): retried with the PR-9 seeded-backoff
   :class:`~graphdyn.resilience.retry.RetryPolicy`, keyed per job so
   concurrent tenants' retries de-correlate; exhausted retries requeue
   the job, they do not kill the server;
3. **run** — the solver (the fused annealer, or the degree-bucketed
   rollout for ``solver='bucketed'`` jobs — which first re-validates the
   declared edge count against the built graph's table, refusing an
   under-priced job before any device work) under a per-job deadline
   watchdog
   (:func:`~graphdyn.resilience.supervisor.supervision`): the job's
   chunk boundaries heartbeat, and a job that overstays its ``timeout_s``
   is **checkpoint-evicted** — the durable store records the eviction
   (tenant, attempt, spec) and the job is requeued with an escalated
   timeout. Replay is exact: the fused chain's counter RNG makes a
   rerun-from-spec bit-identical to an uninterrupted run, so eviction
   never trades latency for correctness. Kernel-lowering failures degrade
   pallas→xla inside the solver (``resilient_exec``), invisible here;
4. **crash containment** — an organic exception is dumped to the flight
   recorder (``obs.crash`` names the site), counted per
   ``(tenant, site)``, and the job is requeued with backoff — until the
   same tenant crashes the same site ``quarantine_after`` times, at which
   point the JOB is quarantined (journal ``serve.quarantine``) and the
   worker moves on: one tenant's poison job cannot crash-loop the shared
   worker;
5. **heartbeats** at every job boundary (``beat("serve.job")``) — the
   PR-10 watchdog supervises the server itself.

The loop runs synchronously (:meth:`Worker.run_until_drained` — the
service main thread, tests, bench) or on the declared background thread
``graphdyn-serve-worker`` (:meth:`Worker.start`/:meth:`Worker.stop`, for
embedding next to a live submit API).
"""

from __future__ import annotations

import os
import threading
import time

from graphdyn.resilience.faults import (
    InjectedFault,
    InjectedPreemption,
    InjectedUnavailable,
    maybe_fail,
)
from graphdyn.resilience.retry import RetryPolicy
from graphdyn.resilience.shutdown import (
    ShutdownRequested,
    clear_shutdown,
    shutdown_requested,
)
from graphdyn.serve.admission import DeclaredShapeMismatch, admit
from graphdyn.serve.bucketing import BucketCache
from graphdyn.serve.spool import Spool

#: an evicted job's next attempt gets a longer slice — a deterministic
#: replay under the same timeout would evict forever
EVICT_TIMEOUT_ESCALATION = 4.0

#: same-(tenant, site) crashes before the job is quarantined
QUARANTINE_AFTER = 2


class Worker:
    """The serve loop over one :class:`~graphdyn.serve.spool.Spool`."""

    def __init__(self, spool: Spool, *, cache: BucketCache | None = None,
                 retry: RetryPolicy | None = None,
                 quarantine_after: int = QUARANTINE_AFTER,
                 default_timeout_s: float | None = None,
                 poll_s: float = 0.05):
        self.spool = spool
        self.default_timeout_s = default_timeout_s
        self.cache = cache or BucketCache()
        #: dispatch retry: seeded full jitter so tenants' retries
        #: de-correlate (the PR-9 storm argument, applied to serving)
        self.retry = retry or RetryPolicy(
            tries=3, base_delay_s=0.01, max_delay_s=0.1, jitter=True)
        self.quarantine_after = quarantine_after
        self.poll_s = poll_s
        #: (tenant, site) -> consecutive crash count (the quarantine key)
        self._crashes: dict = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- the background-thread face (GT003: bounded join in stop()) -------

    def start(self) -> "Worker":
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="graphdyn-serve-worker", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 30.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            if not self.step():
                # drained: idle-wait for new submissions
                # graftrace: disable-next-line=GT005  idle poll of the durable queue — the spool is a filesystem, there is no condition variable to wait on
                time.sleep(self.poll_s)

    # -- the synchronous face ---------------------------------------------

    def run_until_drained(self, *, max_jobs: int | None = None) -> int:
        """Process until the queue is empty (or ``max_jobs`` done);
        returns the number of jobs that left the pending state. The
        service main loop and every in-process consumer (tests, bench,
        the soak children) drive this."""
        done = 0
        while max_jobs is None or done < max_jobs:
            if not self.step():
                return done
            done += 1
        return done

    # -- one job ----------------------------------------------------------

    def step(self) -> bool:
        """Claim and settle one job (any terminal-or-requeued outcome
        counts as settled). False when the queue is drained. External
        preemption (SIGTERM / the server watchdog) re-raises after the
        in-flight job is safely requeued."""
        from graphdyn.resilience.supervisor import beat

        rec = self.spool.claim()
        if rec is None:
            return False
        beat("serve.job")
        job_id, tenant, spec = rec["id"], rec["tenant"], rec["spec"]

        decision = admit(spec, key=job_id)
        if not decision.admitted:
            self.spool.refuse(job_id, decision.reason or "refused")
            return True

        if not self._dispatch(job_id):
            self.spool.requeue(
                job_id, "dispatch retries exhausted (transient "
                "infrastructure failure in front of the device)")
            return True

        try:
            self._run_job(rec, decision.kernel)
        except DeclaredShapeMismatch as e:
            # the bucketed engine's pre-dispatch validation: the built
            # graph outgrew the declared edge count's admitted byte model
            # — an under-priced job is refused, never dispatched (the
            # admission guarantee holds against the REAL table)
            self.spool.refuse(job_id, str(e))
        except ShutdownRequested as e:
            self._on_shutdown(rec, e)
        except InjectedPreemption:
            # a hard kill is a hard kill: the record stays RUNNING on
            # disk and restart recovery requeues it — exactly what a
            # SIGKILLed worker leaves behind
            raise
        except Exception as e:  # noqa: BLE001 — contained per tenant
            self._on_crash(rec, e)
        else:
            self.spool.finish(job_id)
        beat("serve.job")
        return True

    def _dispatch(self, job_id: str) -> bool:
        """The transient-failure seam in front of the device: retried with
        seeded backoff, keyed per job. True = dispatched."""
        delays = list(self.retry.delays(key=f"serve.dispatch:{job_id}"))
        for attempt in range(self.retry.tries):
            try:
                maybe_fail("serve.dispatch", key=job_id)
                return True
            except InjectedUnavailable:
                from graphdyn import obs

                if attempt >= len(delays):
                    return False
                obs.counter("serve.dispatch_retry", job=job_id,
                            attempt=attempt + 1)
                # graftrace: disable-next-line=GT005  the retry policy's seeded backoff delay — the de-correlation IS the sleep
                time.sleep(delays[attempt])
        return False

    def _run_job(self, rec: dict, kernel: str) -> None:
        from graphdyn import obs
        from graphdyn.config import DynamicsConfig, SAConfig
        from graphdyn.resilience.supervisor import supervision
        from graphdyn.search.fused import fused_anneal
        from graphdyn.utils.io import save_results_npz

        spec = rec["spec"]
        g, tables = self.cache.tables_for(spec)
        cfg = SAConfig(dynamics=DynamicsConfig(
            p=1, c=1, rule=str(spec["rule"]), tie=str(spec["tie"])))
        timeout = rec.get("timeout_s")
        if timeout is None:
            timeout = self.default_timeout_s
        # escalation: attempt k runs under timeout * 4^evictions so a
        # deterministic replay cannot evict forever
        if timeout is not None:
            timeout = float(timeout) * (
                EVICT_TIMEOUT_ESCALATION ** rec.get("requeues", 0))
        self._job_t0 = time.monotonic()
        self._job_timeout = timeout
        with supervision(None, timeout):
            with obs.timed("serve.job", job=rec["id"], tenant=rec["tenant"],
                           n=int(spec["n"]), replicas=int(spec["replicas"])):
                if kernel == "bucketed":
                    # the edge-proportional engine (admission priced THIS
                    # program): validate + roll the bucketed kernel
                    payload = self._run_bucketed(spec, g, tables)
                elif kernel == "streamed":
                    # the out-of-core engine: validate + stream chunks
                    payload = self._run_streamed(spec, g)
                else:
                    res = fused_anneal(
                        g, cfg, n_replicas=int(spec["replicas"]),
                        seed=int(spec["seed"]),
                        m_target=float(spec["m_target"]),
                        max_sweeps=int(spec["max_sweeps"]),
                        chunk_sweeps=int(spec["chunk_sweeps"]),
                        kernel=kernel, tables=tables,
                    )
                    payload = {
                        "conf": res.s, "mag_reached": res.mag_reached,
                        "m_end": res.m_end,
                        "steps_to_target": res.steps_to_target,
                    }
        save_results_npz(rec["result"], **payload)

    def _run_bucketed(self, spec: dict, g, buckets) -> dict:
        """One ``solver='bucketed'`` job: re-validate the declared edge
        count against the BUILT graph's table (the admitted byte model
        must cover what runs — :class:`DeclaredShapeMismatch` refuses an
        under-declared job before any device work), then roll the packed
        degree-bucketed kernel for the sweep budget over seeded random
        initial replicas."""
        import numpy as np

        from graphdyn.obs.memband import bucketed_table_entries_bound
        from graphdyn.ops.bucketed import bucketed_rollout_global
        from graphdyn.ops.packed import pack_spins, unpack_spins

        n_edges = int(spec["edges"])
        bound = bucketed_table_entries_bound(g.n, n_edges)
        if buckets.table_entries > bound:
            raise DeclaredShapeMismatch(
                f"declared edges={n_edges} admit {bound} table entries "
                f"but the built graph needs {buckets.table_entries}: the "
                "job was under-priced at admission — resubmit with the "
                "real edge count")
        R = int(spec["replicas"])
        rng = np.random.default_rng(int(spec["seed"]))
        s0 = (2 * rng.integers(0, 2, size=(R, g.n)) - 1).astype(np.int8)
        out = bucketed_rollout_global(
            g, pack_spins(s0), int(spec["max_sweeps"]),
            rule=str(spec["rule"]), tie=str(spec["tie"]), buckets=buckets)
        s = unpack_spins(out, R)
        return {
            "conf": s,
            # graftlint: disable-next-line=GD004  host observable, exact sum
            "m_end": s.astype(np.float64).mean(axis=1),
            "steps": np.asarray(int(spec["max_sweeps"])),
        }

    def _run_streamed(self, spec: dict, g) -> dict:
        """One ``solver='streamed'`` job: re-validate the declared
        edges/dmax against the BUILT graph (the admitted per-chunk model
        must cover what runs — :class:`DeclaredShapeMismatch` refuses an
        under-declared job before any device work), chunk the graph
        against the live device budget, and stream the rollout — the
        route that runs the shapes the resident engines refuse."""
        import numpy as np

        from graphdyn.ops.packed import WORD, pack_spins, unpack_spins
        from graphdyn.ops.streamed import (
            build_stream_plan,
            streamed_rollout,
        )
        from graphdyn.serve.admission import device_budget_bytes

        n_edges = int(spec["edges"])
        if g.num_edges > n_edges:
            raise DeclaredShapeMismatch(
                f"declared edges={n_edges} but the built graph has "
                f"{g.num_edges}: the job was under-priced at admission — "
                "resubmit with the real edge count")
        declared_dmax = spec.get("dmax")
        if declared_dmax is not None and g.dmax > int(declared_dmax):
            raise DeclaredShapeMismatch(
                f"declared dmax={int(declared_dmax)} but the built graph "
                f"has dmax={g.dmax}: the admitted feasibility floor was "
                "under-priced — resubmit with the real hub degree")
        R = int(spec["replicas"])
        W = -(-R // WORD)
        budget = device_budget_bytes()
        shards = int(spec.get("shards", 1))
        if shards < 1:
            raise DeclaredShapeMismatch(
                f"malformed shards declaration shards={shards} "
                "(want an int >= 1)")
        rng = np.random.default_rng(int(spec["seed"]))
        s0 = (2 * rng.integers(0, 2, size=(R, g.n)) - 1).astype(np.int8)
        stats: dict = {}
        if shards > 1:
            # the sharded composition (ISSUE 20): the job was PRICED by
            # the per-shard streamed_state_bytes model, so re-validate
            # that the built plan actually fits that promise — a built
            # shard whose double-buffered chunk peak exceeds the
            # per-device budget means the declaration under-priced the
            # job (refuse before any device work, PR-18 bucketed pattern)
            import jax

            from graphdyn.graphs import partition_graph
            from graphdyn.parallel.stream import (
                build_shard_stream_plan,
                shard_plan_device_bytes,
                sharded_streamed_rollout,
            )

            n_dev = len(jax.devices())
            if shards > n_dev:
                raise DeclaredShapeMismatch(
                    f"declared shards={shards} but this worker has "
                    f"{n_dev} devices — the sharded streamed engine "
                    "needs one device per shard")
            partition = partition_graph(g, shards, seed=int(spec["seed"]))
            try:
                plan = build_shard_stream_plan(
                    g, W=W, partition=partition,
                    device_budget_bytes=budget)
            except ValueError as e:
                raise DeclaredShapeMismatch(str(e)) from e
            if shard_plan_device_bytes(plan, W) > budget:
                raise DeclaredShapeMismatch(
                    f"built shard plan peaks at "
                    f"{shard_plan_device_bytes(plan, W)} B per device, "
                    f"over the {budget} B budget the per-shard model "
                    "admitted — resubmit with the real shape")
            out = sharded_streamed_rollout(
                g, pack_spins(s0), int(spec["max_sweeps"]),
                n_shards=shards, rule=str(spec["rule"]),
                tie=str(spec["tie"]), device_budget_bytes=budget,
                partition=partition, seed=int(spec["seed"]),
                stats_out=stats)
            chunks = stats.get("chunks", plan.K)
        else:
            try:
                plan = build_stream_plan(
                    g, W=W, device_budget_bytes=budget)
            except ValueError as e:
                # a hub the byte budget cannot hold even alone: the floor
                # check at admission was under-declared
                raise DeclaredShapeMismatch(str(e)) from e
            out = streamed_rollout(
                g, pack_spins(s0), int(spec["max_sweeps"]),
                rule=str(spec["rule"]), tie=str(spec["tie"]), plan=plan,
                stats_out=stats)
            chunks = stats.get("chunks", plan.K)
        s = unpack_spins(out, R)
        return {
            "conf": s,
            # graftlint: disable-next-line=GD004  host observable, exact sum
            "m_end": s.astype(np.float64).mean(axis=1),
            "steps": np.asarray(int(spec["max_sweeps"])),
            "chunks": np.asarray(int(chunks)),
            "shards": np.asarray(int(shards)),
        }

    # -- ladder rungs ------------------------------------------------------

    def _on_shutdown(self, rec: dict, e: ShutdownRequested) -> None:
        """Disambiguate the one shutdown flag: the per-job deadline firing
        is an EVICTION (requeue, clear, keep serving); anything else is
        real preemption (requeue, re-raise — the server is being told to
        die)."""
        timeout = self._job_timeout
        elapsed = time.monotonic() - self._job_t0
        if timeout is not None and elapsed >= 0.9 * timeout:
            self._evict(rec, elapsed)
            clear_shutdown()
            if shutdown_requested():     # pragma: no cover — signal raced
                raise e
            return
        self.spool.requeue(rec["id"], f"preempted at {e.where or 'chunk'} "
                           "boundary (server shutdown)")
        raise e

    def _evict(self, rec: dict, elapsed: float) -> None:
        """Checkpoint-eviction: the durable store records the eviction
        evidence (who, which attempt, the full replayable spec — replay
        is exact by the counter-RNG contract), the journal carries
        ``serve.evict``, and the job goes back to pending with an
        escalated slice."""
        import numpy as np

        from graphdyn.resilience.store import DurableCheckpoint, journal_event

        ck = DurableCheckpoint(
            os.path.join(self.spool.root, "evict", rec["id"]))
        ck.save(
            {"requeues": np.asarray(rec.get("requeues", 0)),
             "elapsed_s": np.asarray(elapsed)},
            {"job": rec["id"], "tenant": rec["tenant"],
             "spec": rec["spec"], "evicted": True},
        )
        journal_event(self.spool.journal, "serve.evict",
                      job=rec["id"], tenant=rec["tenant"],
                      requeues=rec.get("requeues", 0),
                      elapsed_s=round(elapsed, 3))
        self.spool.requeue(
            rec["id"], f"evicted after {elapsed:.3f}s (per-job timeout); "
            "replay is exact (counter-RNG chain)")

    def _on_crash(self, rec: dict, e: Exception) -> None:
        """Per-tenant crash containment: dump the post-mortem, count per
        (tenant, site), requeue below the bar, quarantine at it."""
        from graphdyn.obs import flight

        site = f"serve.job:{type(e).__name__}"
        if isinstance(e, InjectedFault):
            site = "serve.job:injected"
        flight.dump("exception", exc=e, site=site)
        key = (rec["tenant"], site)
        self._crashes[key] = self._crashes.get(key, 0) + 1
        crashes = self._crashes[key]
        if crashes >= self.quarantine_after:
            self.spool.quarantine(rec["id"], site, crashes)
            return
        self.spool.requeue(
            rec["id"], f"crash at {site} ({e}); attempt {crashes} of "
            f"{self.quarantine_after} before quarantine", crashed=True)
