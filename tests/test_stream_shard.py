"""Sharded out-of-core streaming (ISSUE 20): the chunk walk × halo
exchange composition. The contract: bit-exact to BOTH the single-device
streamed kernel and the resident halo kernel at P ∈ {1, 2, 4} on RRG and
power-law (hub-split) graphs; chunk ownership is part-major (every shard
owns its partition segment exactly once, hubs vertex-cut replicated and
never chunked); churn-driven hub promotion/demotion repartitions live at
the chunk boundary and journals the decision (``stream.repartition``) so
a preempted run requeued onto a DIFFERENT shard count replays bit-exactly
from the journal alone; the shard-mapped exchange body ships only
``ppermute`` traffic (no all-gather — graftlint GD013, ledger-pinned by
the graftcheck ``streamed_halo`` row)."""

import json
import os

import numpy as np
import pytest

from graphdyn.graphs import (
    partition_graph,
    powerlaw_graph,
    random_regular_graph,
)
from graphdyn.ops.packed import pack_spins, packed_rollout
from graphdyn.ops.streamed import (
    ChurnBatch,
    build_stream_plan,
    seeded_churn,
    streamed_rollout,
)
from graphdyn.parallel.halo import halo_rollout
from graphdyn.parallel.mesh import device_pool, make_mesh
from graphdyn.parallel.stream import (
    ShardStreamPlan,
    build_shard_stream_plan,
    lower_stream_exchange,
    make_stream_exchange,
    shard_plan_device_bytes,
    sharded_streamed_rollout,
)
from graphdyn.resilience import FaultPlan
from graphdyn.resilience.faults import FaultSpec, InjectedPreemption
from graphdyn.resilience.store import journal_path_for, validate_journal

THR = 12    # hub threshold for the power-law cases


def _graph(kind, n=200, seed=5):
    if kind == "rrg":
        return random_regular_graph(n, 3, seed=seed)
    return powerlaw_graph(n, gamma=2.3, dmin=2, seed=seed)


def _sp0(n, R, seed):
    rng = np.random.default_rng(seed)
    return pack_spins(
        (2 * rng.integers(0, 2, size=(R, n)) - 1).astype(np.int8))


def _churn_with_repartition(g, steps=5, seed=3):
    """Background random churn plus two targeted batches: one pushes a
    near-threshold node over THR (hub promotion), one strips an original
    hub below THR (demotion) — so the repartition leg actually fires."""
    deg = g.deg.astype(int)
    v = int(np.argmax((deg < THR) & (deg >= THR - 6)))
    others = [u for u in range(g.n) if u != v][: (THR - deg[v]) + 4]
    adds = np.array([[v, u] for u in others], np.int64)
    hub = int(np.argmax(deg))
    nbrs = g.nbr[hub, : deg[hub]].astype(np.int64)
    drops = np.array(
        [[hub, int(u)] for u in nbrs[: deg[hub] - THR + 3]], np.int64)
    empty = np.empty((0, 2), np.int64)
    return sorted(
        seeded_churn(g.n, steps, rate=6.0, seed=seed)
        + [ChurnBatch(step=1, adds=adds, drops=empty),
           ChurnBatch(step=3, adds=empty, drops=drops)],
        key=lambda b: b.step)


# ---------------------------------------------------------------------------
# bit-parity: composed engine vs streamed kernel vs resident halo kernel
# ---------------------------------------------------------------------------


# tier-1 keeps one leg per distinct program family (P=1 dispatch
# identity, P=2 hubless, P=2 hub-split); the remaining grid combos are
# the same compiled programs at more devices and ride the slow tier
@pytest.mark.parametrize("P,kind", [
    (1, "rrg"),
    (2, "rrg"),
    (2, "powerlaw"),
    pytest.param(1, "powerlaw", marks=pytest.mark.slow),
    pytest.param(4, "rrg", marks=pytest.mark.slow),
    pytest.param(4, "powerlaw", marks=pytest.mark.slow),
])
def test_sharded_streamed_matches_both_engines(kind, P):
    g = _graph(kind)
    sp = _sp0(g.n, 32, seed=11)
    thr = THR if kind == "powerlaw" else None
    got = sharded_streamed_rollout(
        g, sp, 3, n_shards=P, n_chunks=3, hub_threshold=thr)
    ref_s = streamed_rollout(g, sp, 3, rule="majority", tie="stay",
                             n_chunks=3)
    np.testing.assert_array_equal(got, ref_s)
    if P >= 2:
        part = partition_graph(g, P, seed=0, hub_threshold=thr)
        ref_h = np.asarray(halo_rollout(
            g.nbr, g.deg, sp, 3, partition=part))
        np.testing.assert_array_equal(got, ref_h)
    else:
        ref_p = np.asarray(packed_rollout(
            g.nbr, g.deg, sp, 3, "majority", "stay"))
        np.testing.assert_array_equal(got, ref_p)


@pytest.mark.parametrize("rule,tie", [
    ("majority", "change"),
    pytest.param("minority", "stay", marks=pytest.mark.slow),
])
def test_sharded_streamed_rule_tie_matrix(rule, tie):
    g = _graph("powerlaw")
    sp = _sp0(g.n, 32, seed=7)
    got = sharded_streamed_rollout(
        g, sp, 3, n_shards=2, n_chunks=2, hub_threshold=THR,
        rule=rule, tie=tie)
    ref = streamed_rollout(g, sp, 3, rule=rule, tie=tie, n_chunks=2)
    np.testing.assert_array_equal(got, ref)


# ---------------------------------------------------------------------------
# plan structure: part-major chunk ownership, per-shard budget
# ---------------------------------------------------------------------------


def test_shard_plan_partitions_chunks_part_major():
    g = _graph("powerlaw", n=300)
    part = partition_graph(g, 4, seed=0, hub_threshold=THR)
    plan = build_stream_plan(g, W=2, n_chunks=3, partition=part)
    assert isinstance(plan, ShardStreamPlan)
    assert plan.P == 4 and plan.K >= 4
    hubs = set(part.hubs.tolist())
    seen = []
    for p, chunks in enumerate(plan.shard_chunks):
        owned = set(
            part.order[part.offsets[p]:part.offsets[p + 1]].tolist())
        mine = np.concatenate([c.nodes for c in chunks]) if chunks else \
            np.empty(0, np.int64)
        # every chunked node is owned by THIS shard, never a hub
        assert set(mine.tolist()) == owned
        assert not hubs.intersection(mine.tolist())
        seen.append(mine)
    # global coverage: each non-hub node chunked exactly once
    allc = np.sort(np.concatenate(seen))
    np.testing.assert_array_equal(allc, np.sort(part.order))


def test_shard_plan_budget_mode_is_per_shard():
    g = _graph("powerlaw", n=300)
    part = partition_graph(g, 2, seed=0, hub_threshold=THR)
    # a budget small enough to force several chunks per shard
    tight = build_shard_stream_plan(
        g, W=2, partition=part, device_budget_bytes=8_000)
    assert all(len(cs) >= 2 for cs in tight.shard_chunks)
    assert shard_plan_device_bytes(tight, 2) <= 8_000
    sp = _sp0(g.n, 64, seed=1)
    got = sharded_streamed_rollout(
        g, sp, 2, n_shards=2, device_budget_bytes=8_000,
        hub_threshold=THR, partition=part)
    ref = streamed_rollout(g, sp, 2, rule="majority", tie="stay",
                           n_chunks=2)
    np.testing.assert_array_equal(got, ref)


# ---------------------------------------------------------------------------
# churn + live repartition
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def churn_oracle():
    """The fault-free single-device reference for the pinned churn
    schedule — shared across the repartition-parity and requeue tests
    (identical workload, one oracle computation per module)."""
    g = _graph("powerlaw")
    sp = _sp0(g.n, 32, seed=2)
    churn = _churn_with_repartition(g)
    ref = streamed_rollout(g, sp, 5, rule="majority", tie="stay",
                           n_chunks=3, churn=churn)
    return g, sp, churn, np.asarray(ref)


@pytest.mark.parametrize("P", [
    2, pytest.param(4, marks=pytest.mark.slow),
])
def test_churn_repartition_bit_exact(P, churn_oracle):
    g, sp, churn, ref = churn_oracle
    stats = {}
    got = sharded_streamed_rollout(
        g, sp, 5, n_shards=P, n_chunks=3, hub_threshold=THR,
        churn=churn, stats_out=stats)
    np.testing.assert_array_equal(got, ref)
    # both the promotion and the demotion boundary actually repartitioned,
    # and the incremental rebuild touched a strict subset of all chunk
    # builds a full rebuild-per-boundary would have done
    assert stats["repartitions"] >= 2
    assert stats["mutations"] > 0
    assert stats["chunks_rebuilt"] >= stats["chunks"]


def test_churn_without_threshold_never_repartitions():
    g = _graph("rrg")
    sp = _sp0(g.n, 32, seed=2)
    churn = seeded_churn(g.n, 4, rate=6.0, seed=9)
    ref = streamed_rollout(g, sp, 4, rule="majority", tie="stay",
                           n_chunks=3, churn=churn)
    stats = {}
    got = sharded_streamed_rollout(
        g, sp, 4, n_shards=2, n_chunks=3, churn=churn, stats_out=stats)
    np.testing.assert_array_equal(got, ref)
    assert stats["repartitions"] == 0 and stats["mutations"] > 0


# ---------------------------------------------------------------------------
# preempt / requeue onto a different shard count: journal-alone replay
# ---------------------------------------------------------------------------


# the shrink direction (4 -> 2) is the soak matrix's CLI story
# (`stream_shard_requeue`), so tier-1 keeps the grow direction here
@pytest.mark.parametrize("p_before,p_after", [
    (2, 4), pytest.param(4, 2, marks=pytest.mark.slow),
])
def test_requeue_across_shard_count_bit_exact(tmp_path, p_before, p_after,
                                              churn_oracle):
    g, sp, churn, ref = churn_oracle
    ck = str(tmp_path / "run.ckpt")
    with pytest.raises(InjectedPreemption):
        with FaultPlan([FaultSpec("chunk.boundary", "preempt", at=4)]):
            sharded_streamed_rollout(
                g, sp, 5, n_shards=p_before, n_chunks=3,
                hub_threshold=THR, churn=churn, checkpoint_path=ck,
                checkpoint_interval_s=0.0)
    # requeue onto a DIFFERENT shard count: the snapshot is global and
    # the journal replays the churn history, so the resumed run is
    # bit-exact to the fault-free oracle
    got = sharded_streamed_rollout(
        g, sp, 5, n_shards=p_after, n_chunks=3, hub_threshold=THR,
        churn=churn, checkpoint_path=ck)
    np.testing.assert_array_equal(got, ref)
    jp = journal_path_for(ck)
    ops = {json.loads(l).get("op") for l in open(jp)}
    assert "stream.churn" in ops and "stream.repartition" in ops
    _, problems = validate_journal(jp)
    assert problems == []


def test_resume_onto_streamed_single_device(tmp_path):
    """The checkpoint identity matches the single-device streamed engine
    (global snapshot, same fingerprint), so a sharded run's checkpoint
    resumes under plain ``streamed_rollout`` too — engine portability,
    not just shard-count portability."""
    g = _graph("rrg")
    sp = _sp0(g.n, 32, seed=6)
    ref = streamed_rollout(g, sp, 6, rule="majority", tie="stay",
                           n_chunks=3)
    ck = str(tmp_path / "run.ckpt")
    with pytest.raises(InjectedPreemption):
        with FaultPlan([FaultSpec("chunk.boundary", "preempt", at=5)]):
            sharded_streamed_rollout(
                g, sp, 6, n_shards=2, n_chunks=3, checkpoint_path=ck,
                checkpoint_interval_s=0.0)
    got = streamed_rollout(g, sp, 6, rule="majority", tie="stay",
                           n_chunks=3, checkpoint_path=ck)
    np.testing.assert_array_equal(got, ref)


# ---------------------------------------------------------------------------
# the exchange program: ppermute-only body, donated carry
# ---------------------------------------------------------------------------


def test_exchange_program_is_ppermute_only():
    g = _graph("powerlaw", n=300)
    part = partition_graph(g, 2, seed=0, hub_threshold=THR)
    mesh = make_mesh((2,), ("node",), devices=device_pool(2))
    lowered = lower_stream_exchange(
        mesh, g, part, W=2, rule="majority", tie="stay",
        node_axis="node")
    txt = lowered.as_text()
    assert "collective_permute" in txt
    assert "all_gather" not in txt
    assert "all_reduce" not in txt


def test_exchange_requires_something_to_exchange():
    from graphdyn.parallel.halo import build_halo_tables

    # one hubless part: no schedule, no hubs -> nothing to build
    g = _graph("rrg", n=40)
    part = partition_graph(g, 1, seed=0)
    tables = build_halo_tables(g, part)
    mesh = make_mesh((1,), ("node",), devices=device_pool(1))
    with pytest.raises(ValueError, match="nothing to exchange"):
        make_stream_exchange(mesh, tables)


# ---------------------------------------------------------------------------
# driver surface: stats, gauges, refusals
# ---------------------------------------------------------------------------


def test_stats_and_per_shard_overlap(tmp_path):
    from graphdyn import obs
    from graphdyn.obs.recorder import read_ledger

    g = _graph("rrg")
    sp = _sp0(g.n, 32, seed=1)
    ledger = str(tmp_path / "obs.jsonl")
    stats = {}
    with obs.recording(ledger):
        sharded_streamed_rollout(
            g, sp, 2, n_shards=2, n_chunks=3, stats_out=stats)
    assert stats["shards"] == 2 and stats["steps"] == 2
    assert stats["chunks"] == 6
    assert len(stats["per_shard_overlap"]) == 2
    assert stats["h2d_bytes"] > 0 and stats["d2h_bytes"] > 0
    events, _ = read_ledger(ledger)
    gauges = [e for e in events
              if e.get("ev") == "gauge"
              and e.get("name") == "stream.overlap_util"]
    assert {e["attrs"]["shard"] for e in gauges} == {0, 1}


def test_driver_refusals():
    g = _graph("rrg", n=40)
    sp = _sp0(g.n, 32, seed=1)
    with pytest.raises(ValueError, match="n_shards"):
        sharded_streamed_rollout(g, sp, 1, n_shards=0, n_chunks=2)
    with pytest.raises(ValueError, match="sp must be"):
        sharded_streamed_rollout(g, sp[:-1], 1, n_shards=2, n_chunks=2)
    part = partition_graph(g, 2, seed=0)
    with pytest.raises(ValueError, match="P=2"):
        sharded_streamed_rollout(
            g, sp, 1, n_shards=4, n_chunks=2, partition=part)
    with pytest.raises(ValueError, match="exactly one of"):
        sharded_streamed_rollout(g, sp, 1, n_shards=2)


# ---------------------------------------------------------------------------
# sa_sharded layout='streamed': the SA route of the composed engine
# ---------------------------------------------------------------------------


def test_sa_sharded_streamed_bit_parity():
    from graphdyn.config import SAConfig
    from graphdyn.models.sa import simulated_annealing
    from graphdyn.parallel.sa_sharded import sa_sharded

    g = random_regular_graph(40, 3, seed=5)
    rng = np.random.default_rng(6)
    R, L = 4, 2000
    s0 = (2 * rng.integers(0, 2, size=(R, g.n)) - 1).astype(np.int8)
    proposals = rng.integers(0, g.n, size=(R, L)).astype(np.int32)
    uniforms = rng.random(size=(R, L))
    cfg = SAConfig()
    ref = simulated_annealing(
        g, cfg, s0=s0, proposals=proposals, uniforms=uniforms, max_steps=30)
    mesh = make_mesh((1, 2), ("replica", "node"),
                     devices=device_pool(2))
    got = sa_sharded(
        g, cfg, mesh=mesh, s0=s0, proposals=proposals, uniforms=uniforms,
        max_steps=30, layout="streamed", stream_chunks=2)
    np.testing.assert_array_equal(got.s, ref.s)
    np.testing.assert_array_equal(got.num_steps, ref.num_steps)
    np.testing.assert_array_equal(got.m_final, ref.m_final)


def test_sa_sharded_streamed_refusals():
    from graphdyn.config import SAConfig
    from graphdyn.parallel.sa_sharded import sa_sharded

    g = random_regular_graph(40, 3, seed=5)
    mesh = make_mesh((1, 2), ("replica", "node"), devices=device_pool(2))
    kw = dict(mesh=mesh, n_replicas=2, seed=0, max_steps=5)
    with pytest.raises(ValueError, match="layout must be"):
        sa_sharded(g, SAConfig(), layout="bucketed", **kw)
    with pytest.raises(ValueError, match="chunked-chain resume"):
        sa_sharded(g, SAConfig(), layout="streamed",
                   checkpoint_path="/tmp/x.ckpt", **kw)
    with pytest.raises(ValueError, match="rollout_mode='full'"):
        sa_sharded(g, SAConfig(), layout="streamed",
                   rollout_mode="lightcone", **kw)
    with pytest.raises(ValueError, match="halo composition"):
        sa_sharded(g, SAConfig(), layout="streamed",
                   node_mode="halo", **kw)


# ---------------------------------------------------------------------------
# bench row contract
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_bench_stream_shard_scaling_contract():
    """The measured path (this harness forces 8 devices): per-P rates,
    P=1 = the unsharded streamed program on the same per-shard budget,
    and a positive efficiency. Slow tier: lint.sh's benchcheck runs the
    same row in the real smoke document; tier-1 keeps the null-reason
    contract below."""
    import bench

    row = bench.stream_shard_scaling_row(True, n_per=96, R=64, steps=3,
                                         iters=1)
    assert row["stream_shard_efficiency"] > 0
    rates = row["stream_shard_rate_by_shards"]
    assert set(rates) == {"1", "2", "4", "8"}
    assert all(v > 0 for v in rates.values())
    assert row["stream_shard_workload"]["P_max"] == 8
    assert row["stream_shard_workload"]["budget_per_shard_bytes"] > 0


def test_bench_stream_shard_rows_null_reason_single_device(monkeypatch):
    """Fewer than 2 devices -> null + reason on BOTH sharded rows, never
    0.0 (the benchcheck contract)."""
    import bench

    import jax

    real_devices = jax.devices

    def one_device(*args):
        return real_devices()[:1]

    monkeypatch.setattr(jax, "devices", one_device)
    row = bench.stream_shard_scaling_row(True)
    assert row["stream_shard_efficiency"] is None
    assert ">= 2 devices" in row["stream_shard_efficiency_skipped_reason"]
    row = bench.churn_repartition_rate_row(True)
    assert row["churn_repartition_rate"] is None
    assert ">= 2 devices" in row["churn_repartition_rate_skipped_reason"]


@pytest.mark.slow
def test_bench_churn_repartition_rate_contract():
    """The measured path: a positive applied-mutations rate with the
    dynamics never stalled, and the repartition counters wired through
    from the sharded engine's stats. Slow tier, same reasoning as the
    scaling contract above."""
    import bench

    row = bench.churn_repartition_rate_row(True, n=192, R=64, steps=5,
                                           churn_per_step=24.0)
    assert row["churn_repartition_rate"] > 0
    det = row["churn_repartition_rate_detail"]
    assert det["applied_mutations"] > 0
    assert det["spin_update_rate"] > 0
    assert det["shards"] == 2
    assert det["repartitions"] >= 0
    assert det["chunks_rebuilt"] >= 0
