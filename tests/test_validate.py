"""Sanitizers (SURVEY.md §5.2): checkify/debug_nans variants, and
determinism of the sharded programs across mesh layouts and repeated runs
(psum order-independence)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from graphdyn.graphs import random_regular_graph
from graphdyn.parallel.mesh import device_pool, make_mesh
from graphdyn.parallel.sharded import (
    make_sharded_rollout,
    pad_nodes,
    place_sharded,
)
from graphdyn.utils.validate import checked, debug_nans


def test_checked_passes_clean_fn():
    f = checked(jax.jit(lambda x: jnp.log(x + 1.0).sum()))
    assert np.isfinite(float(f(jnp.ones(8))))


def test_checked_raises_on_nan():
    f = checked(jax.jit(lambda x: jnp.log(x).sum()))
    with pytest.raises(Exception, match="nan"):
        f(jnp.full((4,), -1.0))


def test_debug_nans_restores_config():
    prev = jax.config.jax_debug_nans
    with debug_nans():
        assert jax.config.jax_debug_nans is True
    assert jax.config.jax_debug_nans == prev


def test_debug_nans_restores_config_on_exception():
    """The finally-branch contract: an exception escaping the body must not
    leave the (expensive, re-run-every-op) debug mode enabled."""
    prev = jax.config.jax_debug_nans

    class Boom(RuntimeError):
        pass

    with pytest.raises(Boom):
        with debug_nans():
            assert jax.config.jax_debug_nans is True
            raise Boom()
    assert jax.config.jax_debug_nans == prev

    # and the nested/disable form restores too
    with pytest.raises(Boom):
        with debug_nans(False):
            raise Boom()
    assert jax.config.jax_debug_nans == prev


def test_checked_flags_seeded_nan_inside_jitted_loop_body():
    """checkify compiles the float checks INTO the program: a NaN produced
    inside a jitted lax.fori_loop body — where a Python-level assert can
    never run — must surface as a raised error, and the same loop without
    the seed must pass."""
    from jax import lax

    def roll(x, seed_nan: bool):
        def body(i, s):
            s = s * 0.5 + 1.0
            if seed_nan:
                # inject inf - inf = nan at iteration 3 only
                s = jnp.where(i == 3, s + jnp.inf - jnp.inf, s)
            return s

        return lax.fori_loop(0, 8, body, x).sum()

    clean = checked(jax.jit(lambda x: roll(x, False)))
    assert np.isfinite(float(clean(jnp.ones(16))))

    seeded = checked(jax.jit(lambda x: roll(x, True)))
    with pytest.raises(Exception, match="nan"):
        seeded(jnp.ones(16))


def test_sweep_values_finite_under_checkify():
    """The BDCM sweep's safe-denominator normalization admits no NaNs even
    from an all-zero message row."""
    from graphdyn.ops.bdcm import BDCMData, make_sweep

    g = random_regular_graph(60, 3, seed=0)
    data = BDCMData(g, p=1, c=1)
    sweep = make_sweep(data, damp=0.3, use_pallas=False)
    chi = data.init_messages(seed=0)
    chi = chi.at[0].set(0.0)                      # degenerate row
    out = checked(lambda c: sweep(c, jnp.float32(0.5)))(chi)
    assert np.all(np.isfinite(np.asarray(out)))


@pytest.mark.parametrize("layout", [(4, 2), (2, 4), (1, 8)])
def test_rollout_invariant_across_mesh_layouts(layout):
    """The same program on different (replica, node) mesh factorizations must
    produce bit-identical spins — integer dynamics make this exact; the test
    pins the collective layout independence against the unsharded-node
    baseline (8, 1)."""
    g = random_regular_graph(240, 4, seed=5)

    def run(shape):
        mesh = make_mesh(shape, ("replica", "node"), devices=device_pool(8))
        nbr_pad, n_pad = pad_nodes(g, shape[1])
        s = np.ones((8, n_pad), np.int8)
        rng = np.random.default_rng(2)  # same spins for every layout
        s[:, : g.n] = 2 * rng.integers(0, 2, size=(8, g.n), dtype=np.int64) - 1
        rollout = make_sharded_rollout(mesh, n_real=g.n, steps=4)
        nbr_d = place_sharded(mesh, jnp.asarray(nbr_pad), P("node", None))
        s_d = place_sharded(mesh, jnp.asarray(s), P("replica", "node"))
        return np.asarray(rollout(nbr_d, s_d))[:, : g.n]

    np.testing.assert_array_equal(run((8, 1)), run(layout))


def test_sharded_sweep_run_to_run_deterministic():
    """Two executions of the compiled edge-sharded sweep on identical inputs
    are bit-identical (no nondeterministic reduction paths)."""
    from graphdyn.ops.bdcm import BDCMData
    from graphdyn.parallel.sharded import make_sharded_sweep

    g = random_regular_graph(200, 4, seed=1)
    data = BDCMData(g, p=1, c=1)
    mesh = make_mesh((8,), ("edge",), devices=device_pool(8))
    sweep = make_sharded_sweep(data, mesh, damp=0.2)
    chi = data.init_messages(seed=3)
    lam = jnp.float32(0.4)
    r1 = np.asarray(sweep(chi, lam))
    r2 = np.asarray(sweep(chi, lam))
    np.testing.assert_array_equal(r1, r2)
