"""Device-side obs (PR-8): aligned profiler traces, memory bands, and the
always-on flight recorder.

Three contracts under test (ISSUE 8 acceptance criteria):

- a profiled smoke run (``--profile`` + ``--obs-ledger``) produces a trace
  directory whose annotation names match the ledger's span name-paths —
  one vocabulary across the JSONL ledger and the device timeline;
- ``python -m graphdyn.obs memcheck`` passes on this container with an
  explicit null + reason per CPU-unavailable memory stat (the structural
  pass that goes live the first chip round);
- a crashed run with NO ``--obs-ledger`` leaves a parseable
  ``obs_postmortem.jsonl`` whose last events name the failure site
  (unhandled exception / ``sweep.nan`` degrade / SIGTERM→exit-75), while a
  clean run leaves none and a recorded run keeps the evidence in its
  ledger instead.
"""

import glob
import gzip
import json
import os
import tracemalloc

import pytest

from graphdyn import obs
from graphdyn.cli import main
from graphdyn.config import DynamicsConfig, EntropyConfig
from graphdyn.graphs import erdos_renyi_graph
from graphdyn.models.entropy import entropy_sweep
from graphdyn.obs import flight, memband, trace
from graphdyn.obs.recorder import read_ledger
from graphdyn.obs.report import summarize
from graphdyn.resilience import FaultPlan, FaultSpec
from graphdyn.resilience.shutdown import EX_TEMPFAIL

DYN11 = DynamicsConfig(p=1, c=1)

SA_SMOKE = [
    "sa", "--n", "40", "--d", "3", "--p", "1", "--c", "1",
    "--n-stat", "1", "--seed", "0", "--max-steps", "2000",
]


@pytest.fixture(autouse=True)
def _fresh_ring():
    """Every test starts with an empty, default-capacity flight ring (the
    ring is process-global by design — it must survive everything short of
    the process)."""
    flight.configure(flight.DEFAULT_CAPACITY)
    flight.clear()
    yield
    flight.configure(flight.DEFAULT_CAPACITY)
    flight.clear()


def _postmortem_events(tmp_path):
    path = tmp_path / flight.POSTMORTEM_NAME
    assert path.exists(), "crash left no obs_postmortem.jsonl"
    events, torn = read_ledger(str(path))
    assert torn == 0                      # atomic dump: never a torn line
    return events


def _assert_crash_shape(events, reason):
    """The post-mortem contract: manifest first (stamped postmortem),
    ``obs.crash`` last, naming the failure."""
    assert events[0]["ev"] == "manifest"
    assert events[0]["run"]["postmortem"] is True
    assert events[0]["run"]["reason"] == reason
    last = events[-1]
    assert last["ev"] == "counter" and last["name"] == "obs.crash"
    assert last["attrs"]["reason"] == reason
    return last["attrs"]


# ---------------------------------------------------------------------------
# aligned profiler capture: one vocabulary for ledger + device timeline
# ---------------------------------------------------------------------------


def test_profiled_smoke_annotations_match_ledger_paths(tmp_path, capsys):
    """The acceptance smoke: ``--profile`` + ``--obs-ledger`` on a real CLI
    run; every span name-path in the ledger appears verbatim as a trace
    annotation in the profiler's trace-event output."""
    pdir = str(tmp_path / "prof")
    ledger = str(tmp_path / "run.jsonl")
    out = str(tmp_path / "sa.npz")
    rc = main(["--profile", pdir, "--obs-ledger", ledger,
               *SA_SMOKE, "--out", out])
    assert rc == 0
    capsys.readouterr()

    events, _ = read_ledger(ledger)
    ledger_paths = set(summarize(events)["spans"])
    assert "run" in ledger_paths          # at least the root span recorded

    traces = glob.glob(os.path.join(pdir, "**", "*.trace.json.gz"),
                       recursive=True)
    assert traces, f"--profile produced no trace-event file under {pdir}"
    annotation_names = set()
    for t in traces:
        doc = json.loads(gzip.open(t).read())
        annotation_names |= {e.get("name") for e in doc.get("traceEvents", [])}
    missing = ledger_paths - annotation_names
    assert not missing, (
        f"ledger span paths absent from the device trace: {missing} "
        f"(vocabulary fork — obs.trace alignment broken)"
    )


def test_span_annotation_paths_via_capture_stub(monkeypatch, tmp_path):
    """Unit-level alignment (no real profiler): nested spans open
    annotations named with the ledger's ``" > "``-joined name paths, and
    the name stack unwinds with the spans."""
    import jax

    captured = []

    class StubAnnotation:
        def __init__(self, name):
            captured.append(name)

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False

    monkeypatch.setattr(jax.profiler, "TraceAnnotation", StubAnnotation)
    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)

    with trace.profiling(str(tmp_path / "p")):
        with obs.span("run"):
            with obs.span("pipeline.sa.chunk"):
                pass
            with obs.span("pipeline.sa.chunk"):
                pass
    assert captured == [
        "run",
        "run > pipeline.sa.chunk",
        "run > pipeline.sa.chunk",
    ]
    assert not trace.active()
    # after the scope, spans are back to the one shared no-op object
    from graphdyn.obs.recorder import NULL_SPAN

    assert obs.span("x") is NULL_SPAN


def test_profiling_scope_noop_without_dir(monkeypatch):
    monkeypatch.delenv(trace.ENV_VAR, raising=False)
    with trace.profiling() as d:
        assert d is None and not trace.active()


def test_nested_profiling_with_two_dirs_is_an_error(monkeypatch, tmp_path):
    import jax

    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    with trace.profiling(str(tmp_path / "a")):
        with pytest.raises(RuntimeError, match="one device trace per run"):
            with trace.profiling(str(tmp_path / "b")):
                pass
        # dir-less re-entry keeps the outer capture (recording() mirror)
        with trace.profiling() as d:
            assert d == str(tmp_path / "a")


def test_dirless_reentry_keeps_outer_even_with_env_set(monkeypatch,
                                                       tmp_path):
    """The env fallback names the OUTER trace: a dir-less re-entry inside
    an active scope keeps that capture even while GRAPHDYN_PROFILE is set
    — it must not resolve the env var into a second directory and trip
    the nesting error."""
    import jax

    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    monkeypatch.setenv(trace.ENV_VAR, str(tmp_path / "env"))
    with trace.profiling(str(tmp_path / "a")):
        with trace.profiling() as d:
            assert d == str(tmp_path / "a")


# ---------------------------------------------------------------------------
# memory bands: memcheck structural pass + the bench column contract
# ---------------------------------------------------------------------------


def test_memcheck_structural_pass_on_cpu():
    """On this container every row is measured=None with an explicit
    backend reason, the model bytes still evaluate, and the gate passes —
    the acceptance criterion's null+reason contract."""
    from graphdyn.analysis.graftcost import DERIVED_MEM_BANDS

    rows = memband.run_memcheck()
    assert {r.program for r in rows} == (
        set(memband.MEM_BANDS) | set(DERIVED_MEM_BANDS)
    )
    for r in rows:
        assert r.ok, r
        assert r.measured is None and r.frac is None
        assert r.reason and "memory_stats" in r.reason
        assert r.model > 0                # the byte model itself evaluated


def test_memrow_band_logic():
    row = memband._row("packed_state", 10 ** 6, 10 ** 6 / 2)
    assert row.frac == pytest.approx(2.0) and row.ok
    lo, hi = memband.MEM_BANDS["packed_state"]
    too_big = memband._row("packed_state", int(10 ** 6 * hi * 4), 10 ** 6)
    assert not too_big.ok
    # a null row WITHOUT a reason must not pass — a skip has to say why
    silent = memband.MemRow("packed_state", None, 1.0, None, lo, hi, None)
    assert not silent.ok


def test_peak_hbm_bytes_null_plus_reason_on_cpu():
    peak, reason = memband.peak_hbm_bytes()
    assert peak is None and reason      # never a silent absence or fake 0


def test_mem_gauges_unavailable_once_per_recording_scope(tmp_path):
    p = str(tmp_path / "mem.jsonl")
    with obs.recording(p):
        memband.emit_memory_gauges(loop="t.chunk", chunk=0)
        memband.emit_memory_gauges(loop="t.chunk", chunk=1)
    events, _ = read_ledger(p)
    unavailable = [e for e in events if e.get("name") == "obs.mem.unavailable"]
    assert len(unavailable) == 1        # one reason per scope, not per chunk
    assert "memory_stats" in unavailable[0]["attrs"]["reason"]


def test_chip_bands_cover_the_proxy_programs():
    """The v5e seeds (ROADMAP item 5 remainder) band the same programs as
    the CPU proxy and stay inert on this backend."""
    from graphdyn.obs import roofline

    for prof in roofline.CHIP_BANDS.values():
        assert set(prof["bands"]) == set(roofline.BANDS)
        assert prof["hbm_bytes_per_s"] > 0
    assert roofline.chip_profile() is None       # CPU: host-proxy anchor


def test_uncalibrated_tpu_obscheck_passes_structurally(monkeypatch,
                                                       tmp_path):
    """A TPU whose device_kind has no CHIP_BANDS entry must not gate chip
    rates against the host-proxy bands (guaranteed red, no blessing path):
    run_obscheck returns no gated rows and emits an explicit
    ``obs.roofline.uncalibrated`` gauge naming the part."""
    import jax

    from graphdyn.obs import roofline

    class FakeDevice:
        device_kind = "TPU v9 prototype"

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(jax, "local_devices", lambda: [FakeDevice()])
    assert roofline.chip_profile() is None       # no committed anchor
    notices = []
    p = str(tmp_path / "led.jsonl")
    with obs.recording(p):
        rows = roofline.run_obscheck(diag=notices.append)
    assert rows == []
    assert any("structural pass" in n for n in notices)
    events, _ = read_ledger(p)
    unc = [e for e in events if e.get("name") == "obs.roofline.uncalibrated"]
    assert len(unc) == 1
    assert unc[0]["attrs"]["device_kind"] == "TPU v9 prototype"


# ---------------------------------------------------------------------------
# flight recorder: the ring
# ---------------------------------------------------------------------------


def test_ring_bounded_fifo():
    flight.configure(8)
    for i in range(20):
        obs.counter("tick", i=i)        # null recorder → ring
    snap = flight.snapshot()
    assert len(snap) == 8
    assert [e["attrs"]["i"] for e in snap] == list(range(12, 20))


def test_ring_allocation_bounded_tracemalloc():
    """Ring churn retains only the ring itself (the 'allocation-bounded by
    construction' contract, PR-7 tracemalloc style): after 60× capacity
    worth of events, live allocations are bounded by the last-N event
    dicts, not by the event count."""
    flight.configure(64)
    for i in range(flight.capacity() + 16):      # reach steady state
        obs.counter("tick", i=i)
    tracemalloc.start()
    base = tracemalloc.take_snapshot()
    for i in range(4000):
        obs.counter("tick", i=i)
        obs.gauge("level", i)
    diff = tracemalloc.take_snapshot().compare_to(base, "filename")
    tracemalloc.stop()
    leaked = sum(d.size_diff for d in diff if d.size_diff > 0)
    # 4000 unbounded ~150 B events would retain ~600 KB; 64 ring slots of
    # replaced dicts sit well under 16 KB
    assert leaked < 16_384, f"flight ring retained {leaked} B in steady state"


def test_ring_disarmed_by_env(monkeypatch, tmp_path):
    monkeypatch.setenv(flight.ENV_VAR, "0")
    obs.counter("tick")
    assert flight.snapshot() == []
    assert flight.dump("exception", workdir=str(tmp_path)) is None
    assert not (tmp_path / flight.POSTMORTEM_NAME).exists()


# ---------------------------------------------------------------------------
# flight recorder: the three dump paths
# ---------------------------------------------------------------------------


def test_unhandled_cli_exception_leaves_postmortem(tmp_path, monkeypatch,
                                                   capsys):
    """Crash path (c): an unhandled driver exception on a run with no
    ledger dumps the ring tail + the failure site, then re-raises."""
    monkeypatch.chdir(tmp_path)
    monkeypatch.delenv(obs.ENV_VAR, raising=False)
    with FaultPlan([FaultSpec("rep.boundary", action="raise", at=1)]):
        with pytest.raises(Exception, match="rep.boundary"):
            main([*SA_SMOKE, "--out", str(tmp_path / "sa.npz")])
    capsys.readouterr()
    events = _postmortem_events(tmp_path)
    attrs = _assert_crash_shape(events, "exception")
    assert attrs["exc_type"] == "InjectedFault"
    assert "site" in attrs              # innermost traceback frame named


def test_sigterm_preempt_exits_75_and_leaves_postmortem(tmp_path,
                                                        monkeypatch,
                                                        capsys):
    """Crash path (b): the graceful-shutdown preemption (the 'signal'
    fault delivers the request exactly as the SIGTERM handler would) exits
    EX_TEMPFAIL and the post-mortem names the boundary that honored it."""
    monkeypatch.chdir(tmp_path)
    monkeypatch.delenv(obs.ENV_VAR, raising=False)
    with FaultPlan([FaultSpec("rep.boundary", action="signal", at=1)]):
        rc = main([*SA_SMOKE, "--out", str(tmp_path / "sa.npz")])
    capsys.readouterr()
    assert rc == EX_TEMPFAIL
    events = _postmortem_events(tmp_path)
    attrs = _assert_crash_shape(events, "preempt")
    assert attrs["site"] == "rep"       # ShutdownRequested.where
    assert attrs["exc_type"] == "ShutdownRequested"


def test_sweep_nan_degrade_preserves_flight_evidence(tmp_path, monkeypatch):
    """Crash path (a): the ``sweep.nan`` degrade is survivable (sentinel +
    stop) but the evidence is dumped at the moment of the poison, ring
    tail included."""
    monkeypatch.chdir(tmp_path)
    obs.counter("marker.before_poison", k=7)     # ring tail must survive
    g = erdos_renyi_graph(60, 1.5 / 59, seed=0)
    cfg = EntropyConfig(dynamics=DYN11, lmbd_max=0.3, lmbd_step=0.1,
                        max_sweeps=300, eps=1e-5)
    with FaultPlan([FaultSpec("sweep.nan", action="nan", at=2)]):
        res = entropy_sweep(g, cfg, seed=0)      # degrades, no raise
    assert res.nonconverged is not None
    events = _postmortem_events(tmp_path)
    attrs = _assert_crash_shape(events, "sweep.nan")
    assert "lambda" in attrs["site"]
    names = [e.get("name") for e in events]
    assert "marker.before_poison" in names       # the ring's tail made it


def test_clean_run_leaves_no_postmortem(tmp_path, monkeypatch, capsys):
    monkeypatch.chdir(tmp_path)
    rc = main([*SA_SMOKE, "--out", str(tmp_path / "sa.npz")])
    capsys.readouterr()
    assert rc == 0
    assert not (tmp_path / flight.POSTMORTEM_NAME).exists()


def test_cli_crash_with_ledger_records_obs_crash_in_ledger(tmp_path,
                                                           monkeypatch,
                                                           capsys):
    """The other half of the routing contract, end to end through the CLI:
    with ``--obs-ledger`` the crash evidence lands IN the ledger (the
    ``obs.crash`` event, ShutdownRequested's boundary as ``site``) and no
    post-mortem file is written."""
    monkeypatch.chdir(tmp_path)
    ledger = str(tmp_path / "run.jsonl")
    with FaultPlan([FaultSpec("rep.boundary", action="signal", at=1)]):
        rc = main(["--obs-ledger", ledger,
                   *SA_SMOKE, "--out", str(tmp_path / "sa.npz")])
    capsys.readouterr()
    assert rc == EX_TEMPFAIL
    assert not (tmp_path / flight.POSTMORTEM_NAME).exists()
    events, _ = read_ledger(ledger)
    crash = [e for e in events if e.get("name") == "obs.crash"]
    assert len(crash) == 1
    assert crash[0]["attrs"]["reason"] == "preempt"
    assert crash[0]["attrs"]["site"] == "rep"


def test_dump_with_live_recorder_goes_to_ledger_not_file(tmp_path):
    """When a ledger IS being written it already carries the evidence: the
    crash event lands there and no post-mortem file appears."""
    p = str(tmp_path / "live.jsonl")
    with obs.recording(p):
        assert flight.dump("sweep.nan", site="cell=3",
                           workdir=str(tmp_path)) is None
    assert not (tmp_path / flight.POSTMORTEM_NAME).exists()
    events, _ = read_ledger(p)
    crash = [e for e in events if e.get("name") == "obs.crash"]
    assert len(crash) == 1 and crash[0]["attrs"]["site"] == "cell=3"


def test_postmortem_is_report_renderable(tmp_path, monkeypatch):
    """The dump is a schema-valid ledger: ``summarize`` (the report
    command's engine) aggregates it unchanged."""
    monkeypatch.chdir(tmp_path)
    obs.counter("tick", i=1)
    obs.gauge("level", 0.5)
    flight.dump("exception", exc=ValueError("boom"))
    events = _postmortem_events(tmp_path)
    doc = summarize(events)
    assert doc["manifest"]["postmortem"] is True
    assert doc["counters"]["obs.crash"]["total"] == 1
    assert "tick" in doc["counters"] and "level" in doc["gauges"]
