"""CLI experiment runner: flag surface → configs → drivers → npz output."""

import json

import numpy as np
import pytest

from graphdyn.cli import main
from graphdyn.utils.io import load_results_npz


def test_cli_sa(tmp_path, capsys):
    out = str(tmp_path / "mcmc.npz")
    rc = main([
        "sa", "--n", "40", "--d", "3", "--p", "1", "--c", "1",
        "--n-stat", "2", "--seed", "0", "--max-steps", "20000", "--out", out,
    ])
    assert rc == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["solver"] == "sa" and len(line["m_final"]) == 2
    assert set(load_results_npz(out)) == {"mag_reached", "num_steps", "conf", "graphs"}


def test_cli_hpr(tmp_path, capsys):
    out = str(tmp_path / "hpr.npz")
    rc = main([
        "hpr", "--n", "40", "--d", "4", "--max-sweeps", "1500",
        "--n-rep", "1", "--out", out,
    ])
    assert rc == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["solver"] == "hpr" and len(line["time"]) == 1
    assert "time" in load_results_npz(out)


def test_cli_hpr_batch_device_init(tmp_path, capsys):
    """--batch-replicas runs hpr_solve_batch (one graph, R chains);
    --device-init selects the device-resident union/init path."""
    import numpy as np

    out = str(tmp_path / "hprb.npz")
    rc = main([
        "hpr", "--n", "60", "--d", "3", "--p", "1", "--c", "1",
        "--max-sweeps", "1500", "--batch-replicas", "2", "--device-init",
        "--out", out,
    ])
    assert rc == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["solver"] == "hpr_batch" and len(line["m_final"]) == 2
    saved = np.load(out)
    assert saved["conf"].shape == (2, 60)

    with pytest.raises(SystemExit, match="batch-replicas"):
        main(["hpr", "--n", "40", "--device-init"])
    with pytest.raises(SystemExit, match="checkpoint"):
        main(["hpr", "--n", "40", "--batch-replicas", "2", "--device-init",
              "--checkpoint", "/tmp/ck"])


def test_cli_entropy(tmp_path, capsys):
    out = str(tmp_path / "er.npz")
    rc = main([
        "entropy", "--n", "50", "--deg", "1.2", "--num-rep", "1",
        "--lmbd-max", "0.1", "--lmbd-step", "0.1", "--out", out,
    ])
    assert rc == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["solver"] == "entropy"
    saved = load_results_npz(out)
    assert "ent1" in saved and "counts" in saved
    assert np.asarray(saved["ent1"]).shape[0] == 1


def test_cli_sa_sharded(tmp_path, capsys):
    out = str(tmp_path / "sa_sharded.npz")
    rc = main([
        "sa", "--sharded", "--n", "80", "--d", "3", "--n-replicas", "4",
        "--max-steps", "3000", "--out", out,
    ])
    assert rc == 0
    import json

    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["solver"] == "sa_sharded"
    assert len(line["m_final"]) == 4
    import numpy as np

    with np.load(out) as f:
        assert f["conf"].shape == (4, 80)


def test_cli_entropy_dtype_f64(tmp_path, capsys):
    import jax

    try:
        rc = main([
            "entropy", "--n", "120", "--deg", "1.0", "--num-rep", "1",
            "--lmbd-max", "0.2", "--lmbd-step", "0.1", "--dtype", "float64",
        ])
    finally:
        jax.config.update("jax_enable_x64", False)
    assert rc == 0
    import json

    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["solver"] == "entropy"


def test_cli_entropy_union(tmp_path, capsys):
    """`entropy --union G` runs each degree as one disjoint-union program
    and persists per-degree member-axis grids."""
    import json

    from graphdyn.cli import main
    from graphdyn.utils.io import load_results_npz

    p = str(tmp_path / "union.npz")
    rc = main([
        "entropy", "--n", "50", "--deg", "1.0", "1.4", "--union", "3",
        "--lmbd-max", "0.2", "--out", p,
    ])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert doc["solver"] == "entropy_union"
    assert len(doc["ent1_first_lambda"]["1.0"]) == 3      # member axis
    saved = load_results_npz(p)
    assert saved["ent1_deg0"].shape[1] == 3
    assert saved["ent1_deg1"].shape[1] == 3


def test_cli_consensus(tmp_path, capsys):
    """The forward opinion-consensus driver: m(0) sweep with json + plot
    artifacts and monotone physics (more bias, no less consensus)."""
    pytest.importorskip("matplotlib")
    out = str(tmp_path / "cons.json")
    png = str(tmp_path / "cons.png")
    rc = main([
        "consensus", "--n", "2000", "--replicas", "64",
        "--m0", "0.0", "0.1", "0.3", "--max-steps", "200",
        "--out", out, "--plot", png,
    ])
    assert rc == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["solver"] == "consensus"
    fracs = [r["consensus_fraction"] for r in line["rows"]]
    assert len(fracs) == 3 and fracs[1] <= fracs[2] and fracs[2] >= 0.9
    with open(out) as f:
        assert json.load(f)["rows"] == line["rows"]
    import os

    assert os.path.getsize(png) > 0
