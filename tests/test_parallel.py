"""Mesh-sharding tests on the 8-device simulated CPU mesh (SURVEY.md §4.4):
sharded == unsharded, pad-basis correctness, psum observables."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from graphdyn.graphs import erdos_renyi_graph, random_regular_graph
from graphdyn.ops.dynamics import run_dynamics
from graphdyn.parallel.mesh import device_pool, make_mesh
from graphdyn.parallel.sharded import (
    make_sharded_rollout,
    make_sharded_sa_step,
    make_sharded_sweep,
    pad_nodes,
    place_sharded,
)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh((4, 2), ("replica", "node"), devices=device_pool(8))


def _setup(n, d, R, node_shards=2, seed=0):
    g = random_regular_graph(n, d, seed=seed)
    nbr_pad, n_pad = pad_nodes(g, node_shards)
    rng = np.random.default_rng(seed + 1)
    s = np.ones((R, n_pad), dtype=np.int8)
    s[:, : g.n] = (2 * rng.integers(0, 2, size=(R, g.n)) - 1).astype(np.int8)
    return g, nbr_pad, n_pad, s


@pytest.mark.parametrize("n", [256, 253])  # 253: n not divisible by shards
def test_sharded_rollout_matches_unsharded(mesh, n):
    g, nbr_pad, n_pad, s = _setup(n, 4, R=8)
    nbr_d = place_sharded(mesh, jnp.asarray(nbr_pad), P("node", None))
    s_d = place_sharded(mesh, jnp.asarray(s), P("replica", "node"))
    rollout = make_sharded_rollout(mesh, n_real=g.n, steps=5)
    out = np.asarray(rollout(nbr_d, s_d))[:, : g.n]
    for r in range(s.shape[0]):
        want = run_dynamics(g, s[r, : g.n], 5, backend="cpu")
        np.testing.assert_array_equal(out[r], want)


@pytest.mark.parametrize("tie", ["stay", "change"])
def test_pad_rows_frozen(mesh, tie):
    g, nbr_pad, n_pad, s = _setup(253, 4, R=8)
    assert n_pad > g.n
    nbr_d = place_sharded(mesh, jnp.asarray(nbr_pad), P("node", None))
    s_d = place_sharded(mesh, jnp.asarray(s), P("replica", "node"))
    rollout = make_sharded_rollout(mesh, n_real=g.n, steps=3, tie=tie)
    out = np.asarray(rollout(nbr_d, s_d))
    np.testing.assert_array_equal(out[:, g.n :], s[:, g.n :])


def test_sharded_sa_step_pad_free_sums(mesh):
    g, nbr_pad, n_pad, s = _setup(253, 4, R=8, seed=3)
    nbr_d = place_sharded(mesh, jnp.asarray(nbr_pad), P("node", None))
    s_d = place_sharded(mesh, jnp.asarray(s), P("replica", "node"))
    R = s.shape[0]
    # seed the cached end-sums pad-free via the sharded rollout
    rollout = make_sharded_rollout(mesh, n_real=g.n, steps=1)
    s_end = np.asarray(rollout(nbr_d, s_d))[:, : g.n]
    sum_end = jnp.asarray(s_end.astype(np.int64).sum(axis=1), jnp.int32)

    step = make_sharded_sa_step(mesh, rollout_steps=1, n_real=g.n)
    keys = jax.vmap(jax.random.PRNGKey)(np.arange(R, dtype=np.uint32))
    out = step(
        nbr_d, s_d,
        place_sharded(mesh, sum_end, P("replica")),
        place_sharded(mesh, jnp.full((R,), 0.01 * g.n, jnp.float32), P("replica")),
        place_sharded(mesh, jnp.full((R,), 0.01 * g.n, jnp.float32), P("replica")),
        place_sharded(mesh, keys, P("replica")),
        place_sharded(mesh, jnp.zeros((R,), jnp.int32), P("replica")),
        jnp.float32(1.0005), jnp.float32(1.0005),
        jnp.float32(4.5 * g.n), jnp.float32(5.0 * g.n),
    )
    s_new, sum_end_new, *_, consensus = out
    s_new = np.asarray(s_new)
    # returned end-sums must equal the pad-free rollout of the returned state
    want = np.asarray(rollout(nbr_d, jnp.asarray(s_new)))[:, : g.n]
    np.testing.assert_array_equal(
        np.asarray(sum_end_new), want.astype(np.int64).sum(axis=1)
    )
    # consensus flag basis check: no replica is at consensus here
    assert float(consensus) == 0.0
    # pads untouched
    np.testing.assert_array_equal(s_new[:, g.n :], s[:, g.n :])


@pytest.mark.parametrize("kind", ["rrg", "er"])
def test_sharded_sweep_matches_unsharded(kind):
    """Edge-sharded GSPMD sweep == single-device sweep, on ragged ER (class
    sizes not divisible by the mesh) and regular RRG."""
    from graphdyn.ops.bdcm import BDCMData, make_sweep

    if kind == "rrg":
        g = random_regular_graph(200, 4, seed=2)
    else:
        g = erdos_renyi_graph(300, 3.0 / 299, seed=2)
    data = BDCMData(g, p=1, c=1)
    emesh = make_mesh((8,), ("edge",), devices=device_pool(8))
    sw_ref = make_sweep(data, damp=0.2, use_pallas=False)
    sw_sh = make_sharded_sweep(data, emesh, damp=0.2)
    chi = data.init_messages(seed=4)
    lam = jnp.float32(0.7)
    c_ref, c_sh = chi, chi
    for _ in range(4):
        c_ref = sw_ref(c_ref, lam)
        c_sh = sw_sh(c_sh, lam)
    np.testing.assert_allclose(
        np.asarray(c_sh), np.asarray(c_ref), rtol=2e-5, atol=1e-7
    )


def test_sharded_sweep_f64_matches_unsharded():
    """The edge-sharded sweep honors BDCMData(dtype=float64): constants cast
    to f64 and shard/unshard agreement holds at f64 tolerance."""
    import jax

    from graphdyn.ops.bdcm import BDCMData, make_sweep

    prev_x64 = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", True)
    try:
        g = erdos_renyi_graph(200, 2.5 / 199, seed=3)
        data = BDCMData(g, p=1, c=1, dtype=jnp.float64)
        emesh = make_mesh((8,), ("edge",), devices=device_pool(8))
        sw_ref = make_sweep(data, damp=0.2, use_pallas=False)
        sw_sh = make_sharded_sweep(data, emesh, damp=0.2)
        chi = data.init_messages(seed=4)
        assert chi.dtype == jnp.float64
        lam = jnp.float64(0.7)
        c_ref = sw_ref(chi, lam)
        c_sh = sw_sh(chi, lam)
        assert c_sh.dtype == jnp.float64
        np.testing.assert_allclose(
            np.asarray(c_sh), np.asarray(c_ref), rtol=1e-12, atol=1e-14
        )
    finally:
        jax.config.update("jax_enable_x64", prev_x64)


def test_union_entropy_mesh_matches_unsharded():
    """entropy_ensemble_union(mesh=...) — every fixed point edge-sharded via
    make_sharded_fixed_point — reproduces the single-device ladder
    (BASELINE config 4 under mesh parallelism)."""
    from graphdyn.config import EntropyConfig
    from graphdyn.models.entropy import entropy_ensemble_union

    graphs = [erdos_renyi_graph(60, 1.8 / 59, seed=k) for k in range(4)]
    cfg = EntropyConfig(lmbd_max=1.0, lmbd_step=0.5, max_sweeps=300)
    base = entropy_ensemble_union(graphs, cfg, seed=0)
    emesh = make_mesh((8,), ("edge",), devices=device_pool(8))
    sh = entropy_ensemble_union(graphs, cfg, seed=0, mesh=emesh)
    np.testing.assert_array_equal(base.lambdas, sh.lambdas)
    # reduction orders differ by roundoff, so a fixed point sitting within
    # roundoff of eps can converge one sweep apart between the paths
    assert np.all(np.abs(base.sweeps - sh.sweeps) <= 1)
    np.testing.assert_allclose(base.ent, sh.ent, rtol=2e-5, atol=1e-7)
    np.testing.assert_allclose(base.m_init, sh.m_init, rtol=2e-5, atol=1e-7)
    np.testing.assert_allclose(base.ent1, sh.ent1, rtol=2e-5, atol=1e-7)


def test_vmapped_entropy_mesh_matches_unsharded():
    """entropy_ensemble(mesh=...) — the congruent-ensemble GRAPH axis
    sharded over the mesh — reproduces the single-device ladder."""
    from graphdyn.config import EntropyConfig
    from graphdyn.models.entropy import entropy_ensemble

    graphs = [random_regular_graph(24, 3, seed=k) for k in range(8)]
    cfg = EntropyConfig(lmbd_max=1.0, lmbd_step=0.5, max_sweeps=300)
    base = entropy_ensemble(graphs, cfg, seed=0)
    gmesh = make_mesh((8,), ("graph",), devices=device_pool(8))
    sh = entropy_ensemble(graphs, cfg, seed=0, mesh=gmesh)
    np.testing.assert_array_equal(base.lambdas, sh.lambdas)
    assert np.all(np.abs(base.sweeps - sh.sweeps) <= 1)
    np.testing.assert_allclose(base.ent, sh.ent, rtol=2e-5, atol=1e-7)
    np.testing.assert_allclose(base.m_init, sh.m_init, rtol=2e-5, atol=1e-7)
    np.testing.assert_allclose(base.ent1, sh.ent1, rtol=2e-5, atol=1e-7)


def test_multihost_helpers_single_process():
    """init_multihost is an idempotent no-op single-process; make_hybrid_mesh
    degrades to an ordinary mesh with a size-1 DCN axis, and a solver program
    runs on it unchanged (the same text scales to a pod slice, where the DCN
    axis takes jax.process_count())."""
    import pytest

    from graphdyn.parallel.mesh import init_multihost, make_hybrid_mesh

    assert init_multihost() == 1
    assert init_multihost() == 1                    # idempotent

    m = make_hybrid_mesh((8,), ("host", "replica"), dcn_axis="host")
    assert dict(m.shape) == {"host": 1, "replica": 8}
    m3 = make_hybrid_mesh((2, 4), ("replica", "node", "host"), dcn_axis="host")
    assert dict(m3.shape) == {"replica": 2, "node": 4, "host": 1}

    with pytest.raises(ValueError, match="not in axis_names"):
        make_hybrid_mesh((8,), ("a", "b"), dcn_axis="c")
    with pytest.raises(ValueError, match="one size per"):
        make_hybrid_mesh((2, 4), ("a", "b"), dcn_axis="a")
    # per-host ICI shape must cover the local devices exactly — the same
    # fit create_hybrid_device_mesh enforces multi-process
    with pytest.raises(ValueError, match="per-host device count"):
        make_hybrid_mesh((4,), ("host", "replica"), dcn_axis="host")

    # a sharded observable runs on the hybrid mesh's ICI axis
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    x = jax.device_put(
        jnp.arange(16.0).reshape(8, 2), NamedSharding(m, P("replica", None))
    )
    total = jax.jit(lambda v: v.sum())(x)
    assert float(total) == 120.0


def test_consensus_scan_word_sharded_bit_parity():
    """The forward consensus driver's multi-device path: packed words
    sharded over the replica axis (all gathers index the node axis, so
    per-device work is purely local). Sharded and unsharded points must be
    bit-identical — the draw is seed-deterministic and the scan exact."""
    from graphdyn.models.consensus import consensus_point, er_consensus_ensemble
    from graphdyn.parallel.mesh import make_mesh

    g, _, nbr, deg = er_consensus_ensemble(800, seed=3)
    mesh = make_mesh((8,), ("replica",))
    kw = dict(nbr_dev=nbr, deg_dev=deg, max_steps=120, chunk=10)
    for m0 in (0.0, 0.1):
        un = consensus_point(g, 256, m0, **kw)
        sh = consensus_point(g, 256, m0, mesh=mesh, **kw)
        assert un == sh

    # indivisible word counts are refused up front, not silently resharded
    import pytest

    with pytest.raises(ValueError, match="must divide"):
        consensus_point(g, 32, 0.1, mesh=mesh, **kw)   # W=1 on 8 devices
