"""Graph-layer tests: ensemble validity, table consistency."""

import numpy as np
import pytest

from graphdyn.graphs import (
    Graph,
    build_edge_tables,
    degree_classes,
    erdos_renyi_graph,
    graph_from_edges,
    random_regular_graph,
    remove_isolates,
)


def _assert_simple(g: Graph):
    e = g.edges
    assert np.all(e[:, 0] != e[:, 1]), "self-loop"
    code = np.minimum(e[:, 0], e[:, 1]) * g.n + np.maximum(e[:, 0], e[:, 1])
    assert np.unique(code).size == code.size, "multi-edge"


@pytest.mark.parametrize("n,d", [(10, 3), (100, 4), (501, 2), (2000, 5)])
def test_rrg_is_simple_and_regular(n, d):
    g = random_regular_graph(n, d, seed=7)
    assert g.n == n
    assert np.all(g.deg == d)
    _assert_simple(g)
    assert g.num_edges == n * d // 2


def test_rrg_matches_networkx_degree_structure():
    g = random_regular_graph(60, 3, seed=0, method="networkx")
    assert np.all(g.deg == 3)
    _assert_simple(g)


def test_er_mean_degree():
    n, mean_deg = 4000, 3.0
    g = erdos_renyi_graph(n, mean_deg / (n - 1), seed=3)
    _assert_simple(g)
    assert abs(g.deg.mean() - mean_deg) < 0.3


def test_er_networkx_backend():
    g = erdos_renyi_graph(300, 2.0 / 299, seed=5, method="networkx")
    _assert_simple(g)


def test_neighbor_table_round_trip():
    edges = np.array([[0, 1], [1, 2], [2, 0], [2, 3]])
    g = graph_from_edges(5, edges)
    assert g.n == 5
    assert list(g.deg) == [2, 2, 3, 1, 0]
    # ghost-padded rows
    assert g.nbr.shape == (5, 3)
    assert set(g.nbr[2]) == {0, 1, 3}
    assert g.nbr[3, 0] == 2 and g.nbr[3, 1] == 5 and g.nbr[3, 2] == 5
    assert np.all(g.nbr[4] == 5)


def test_edge_tables_consistency():
    g = random_regular_graph(40, 4, seed=11)
    t = build_edge_tables(g)
    E = g.num_edges
    assert t.src.shape == (2 * E,)
    # reverse convention
    np.testing.assert_array_equal(t.src[:E], t.dst[E:])
    np.testing.assert_array_equal(t.dst[:E], t.src[E:])
    ghost = 2 * E
    for e in range(2 * E):
        i, j = t.src[e], t.dst[e]
        rows = t.in_edges[e]
        real = rows[rows != ghost]
        assert real.size == t.edge_deg[e] == g.deg[i] - 1
        for k_e in real:
            assert t.dst[k_e] == i, "incoming message must end at src"
            assert t.src[k_e] != j, "must exclude the reverse edge"
        # distinct sources
        assert np.unique(t.src[real]).size == real.size


def test_node_edge_tables():
    g = erdos_renyi_graph(200, 2.5 / 199, seed=9)
    t = build_edge_tables(g)
    ghost = 2 * g.num_edges
    for i in range(g.n):
        ins = t.node_in_edges[i]
        ins = ins[ins != ghost]
        outs = t.node_out_edges[i]
        outs = outs[outs != ghost]
        assert ins.size == outs.size == g.deg[i]
        assert np.all(t.dst[ins] == i)
        assert np.all(t.src[outs] == i)


def test_degree_classes_partition():
    g = erdos_renyi_graph(500, 2.0 / 499, seed=2)
    t = build_edge_tables(g)
    classes = degree_classes(t.edge_deg)
    total = sum(v.size for v in classes.values())
    assert total == 2 * g.num_edges
    for d, idx in classes.items():
        assert np.all(t.edge_deg[idx] == d)


def test_remove_isolates():
    edges = np.array([[0, 2], [2, 4]])
    g = graph_from_edges(6, edges)
    sub, n_iso = remove_isolates(g)
    assert n_iso == 3
    assert sub.n == 3
    assert sub.num_edges == 2
    assert sorted(sub.deg.tolist()) == [1, 1, 2]


@pytest.mark.parametrize("n,d", [(6, 5), (10, 8), (20, 15), (9, 6)])
def test_rrg_dense_degrees(n, d):
    g = random_regular_graph(n, d, seed=1)
    assert np.all(g.deg == d)
    _assert_simple(g)


def test_er_dense_p():
    g = erdos_renyi_graph(300, 0.999, seed=4)
    _assert_simple(g)
    assert g.num_edges > 0.99 * 300 * 299 / 2
    g2 = erdos_renyi_graph(50, 1.0, seed=4)
    assert g2.num_edges == 50 * 49 // 2
    g3 = erdos_renyi_graph(50, 0.0, seed=4)
    assert g3.num_edges == 0


def test_graph_from_edges_dmax_validation():
    with pytest.raises(ValueError, match="dmax"):
        graph_from_edges(4, np.array([[0, 1], [0, 2], [0, 3]]), dmax=2)


def test_consensus_fraction_target():
    from graphdyn.observe import consensus_fraction

    s = -np.ones((4, 10), dtype=np.int8)
    assert float(consensus_fraction(s)) == 0.0
    assert float(consensus_fraction(s, target=-1)) == 1.0


def test_bfs_order_permutation_and_equivariance():
    """bfs_order is a true permutation and dynamics commute with the
    relabeling: rolling the permuted graph on permuted spins equals
    permuting the original rollout."""
    from graphdyn.graphs import bfs_order, erdos_renyi_graph, permute_nodes
    from graphdyn.ops.dynamics import end_state

    g = erdos_renyi_graph(300, 2.5 / 299, seed=8)   # multi-component, ragged
    order = bfs_order(g)
    assert np.array_equal(np.sort(order), np.arange(g.n))
    g2, inv = permute_nodes(g, order)
    rng = np.random.default_rng(0)
    s = (2 * rng.integers(0, 2, size=g.n) - 1).astype(np.int8)
    out1 = end_state(g, s, p=3, c=1, backend="cpu")
    out2 = end_state(g2, s[order], p=3, c=1, backend="cpu")
    np.testing.assert_array_equal(out2, out1[order])


def test_replicate_disjoint_sweep_equivalence():
    """The disjoint-union replica batch computes the same messages as
    running the sweep independently per copy (block-structured chi)."""
    import jax.numpy as jnp

    from graphdyn.graphs import random_regular_graph, replicate_disjoint
    from graphdyn.ops.bdcm import BDCMData, make_sweep

    g = random_regular_graph(30, 3, seed=4)
    R = 3
    gu = replicate_disjoint(g, R)
    assert gu.n == R * g.n and gu.num_edges == R * g.num_edges
    data1 = BDCMData(g, p=1, c=1)
    dataR = BDCMData(gu, p=1, c=1)
    sw1 = make_sweep(data1, damp=0.3, use_pallas=False)
    swR = make_sweep(dataR, damp=0.3, use_pallas=False)
    rng = np.random.default_rng(0)
    chis = [np.asarray(data1.init_messages(rng)) for _ in range(R)]
    E2 = 2 * g.num_edges
    # union directed-edge order: forward edges of all copies, then reverses
    fw = np.concatenate([c[: g.num_edges] for c in chis])
    bw = np.concatenate([c[g.num_edges :] for c in chis])
    chiU = jnp.asarray(np.concatenate([fw, bw]))
    outU = np.asarray(swR(chiU, jnp.float32(0.7)))
    for r in range(R):
        out1 = np.asarray(sw1(jnp.asarray(chis[r]), jnp.float32(0.7)))
        np.testing.assert_allclose(
            outU[r * g.num_edges : (r + 1) * g.num_edges], out1[: g.num_edges],
            rtol=1e-6, atol=1e-7,
        )
        np.testing.assert_allclose(
            outU[R * g.num_edges + r * g.num_edges : R * g.num_edges + (r + 1) * g.num_edges],
            out1[g.num_edges :], rtol=1e-6, atol=1e-7,
        )


def test_replicate_disjoint_equals_graph_from_edges():
    """The direct-tiling union equals graph_from_edges over the shifted edge
    list field-for-field (incident order preserved), on ragged ER and RRG."""
    from graphdyn.graphs import (
        erdos_renyi_graph,
        graph_from_edges,
        random_regular_graph,
        replicate_disjoint,
    )

    for g in (
        random_regular_graph(40, 3, seed=1),
        erdos_renyi_graph(60, 2.5 / 59, seed=2),     # ragged + maybe isolates
    ):
        R = 3
        gu = replicate_disjoint(g, R)
        noff = (np.arange(R, dtype=np.int64) * g.n)[:, None, None]
        edges = (g.edges.astype(np.int64)[None] + noff).reshape(-1, 2)
        want = graph_from_edges(R * g.n, edges, dmax=g.dmax)
        np.testing.assert_array_equal(gu.nbr, want.nbr)
        np.testing.assert_array_equal(gu.deg, want.deg)
        np.testing.assert_array_equal(gu.edges, want.edges)


# ---------------------------------------------------------------------------
# greedy coloring + power graph (the chromatic-kernel contract)
# ---------------------------------------------------------------------------


class TestGreedyColoring:
    """The colorcheck contract (scripts/lint.sh): no monochromatic edge,
    chi <= dmax+1, deterministic per seed — and the distance-2 variant
    (power_graph(g, 2)) proper on G^2, which is what licenses the
    chromatic kernel's whole-class parallel update."""

    def test_valid_and_bounded_rrg_er(self):
        from graphdyn.graphs import (
            erdos_renyi_graph, greedy_coloring, random_regular_graph,
            validate_coloring,
        )

        for g in (random_regular_graph(256, 3, seed=0),
                  erdos_renyi_graph(200, 5.0 / 199, seed=1)):
            c = greedy_coloring(g, seed=0)
            assert validate_coloring(g, c) == []
            assert int(c.max()) + 1 <= g.dmax + 1

    def test_deterministic_per_seed(self):
        from graphdyn.graphs import greedy_coloring, random_regular_graph

        g = random_regular_graph(512, 4, seed=2)
        a = greedy_coloring(g, seed=7)
        b = greedy_coloring(g, seed=7)
        np.testing.assert_array_equal(a, b)

    def test_distance2_coloring_proper_on_square(self):
        from graphdyn.graphs import (
            erdos_renyi_graph, greedy_coloring, power_graph,
            random_regular_graph, validate_coloring,
        )

        for g in (random_regular_graph(128, 3, seed=0),
                  erdos_renyi_graph(100, 4.0 / 99, seed=3)):
            g2 = power_graph(g, 2)
            c2 = greedy_coloring(g2, seed=0)
            assert validate_coloring(g2, c2) == []
            # same-class nodes at pairwise distance >= 3: no class member
            # inside another member's radius-2 ball
            nbr_ext = np.concatenate(
                [g.nbr.astype(np.int64),
                 np.full((1, g.dmax), g.n, np.int64)], axis=0)
            for i in range(g.n):
                ball = nbr_ext[i]
                ball = np.concatenate([ball, nbr_ext[ball].reshape(-1)])
                ball = np.unique(ball[(ball != g.n) & (ball != i)])
                assert (c2[ball] != c2[i]).all(), i

    def test_power_graph_radius1_identity_and_path_distances(self):
        from graphdyn.graphs import graph_from_edges, power_graph

        path = graph_from_edges(5, np.array([[0, 1], [1, 2], [2, 3], [3, 4]]))
        assert power_graph(path, 1) is path
        p2 = power_graph(path, 2)
        want = {(0, 1), (0, 2), (1, 2), (1, 3), (2, 3), (2, 4), (3, 4)}
        got = {tuple(sorted(e)) for e in p2.edges.tolist()}
        assert got == want
        with pytest.raises(ValueError, match="radius"):
            power_graph(path, 0)

    def test_validate_coloring_catches_problems(self):
        from graphdyn.graphs import random_regular_graph, validate_coloring

        g = random_regular_graph(32, 3, seed=0)
        assert any("monochromatic" in p
                   for p in validate_coloring(g, np.zeros(g.n, np.int32)))
        assert any("shape" in p
                   for p in validate_coloring(g, np.zeros(3, np.int32)))
        bad_chi = np.arange(g.n, dtype=np.int32) % (g.dmax + 9)
        assert validate_coloring(g, bad_chi) != []


# ---------------------------------------------------------------------------
# power-law fast path: edge-list ingest + degree-bucketed layout (ISSUE 18)
# ---------------------------------------------------------------------------


class TestFromEdgelist:
    def test_round_trip_reproduces_tables(self):
        from graphdyn.graphs import from_edgelist, powerlaw_graph

        for g in (random_regular_graph(80, 3, seed=1),
                  erdos_renyi_graph(120, 4.0 / 119, seed=2),
                  powerlaw_graph(150, gamma=2.4, dmin=2, seed=3)):
            h = from_edgelist(g.edges, n=g.n)
            assert h.n == g.n
            assert np.array_equal(h.edges, g.edges)
            assert np.array_equal(h.nbr, g.nbr)
            assert np.array_equal(h.deg, g.deg)

    def test_sanitizes_self_loops_and_duplicates(self):
        from graphdyn.graphs import from_edgelist

        g = from_edgelist(
            [(0, 1), (1, 1), (1, 0), (2, 0), (0, 2), (1, 2)], n=4)
        _assert_simple(g)
        assert g.num_edges == 3            # (0,1), (2,0), (1,2) survive
        assert np.array_equal(g.edges[0], [0, 1])
        assert np.array_equal(g.edges[1], [2, 0])   # first occurrence kept
        assert g.deg[3] == 0               # isolated id below n stays

    def test_empty_list_needs_n(self):
        from graphdyn.graphs import from_edgelist

        with pytest.raises(ValueError, match="n explicitly"):
            from_edgelist([])
        g = from_edgelist([], n=5)
        assert g.n == 5 and g.num_edges == 0

    def test_accepts_array_and_infers_n(self):
        from graphdyn.graphs import from_edgelist

        e = np.array([[0, 3], [3, 1]], np.int64)
        g = from_edgelist(e)
        assert g.n == 4 and g.num_edges == 2

    def test_out_of_range_and_negative_ids_always_rejected(self):
        from graphdyn.graphs import from_edgelist

        with pytest.raises(ValueError, match=r"outside \[0, 4\)") as ei:
            from_edgelist([(0, 1), (2, 7)], n=4)
        assert "row(s) [1]" in str(ei.value)      # pointed at the input row
        with pytest.raises(ValueError, match="negative node id"):
            from_edgelist([(0, 1), (-2, 3)])      # inferred n
        with pytest.raises(ValueError, match=r"outside \[0, 4\)"):
            from_edgelist([(0, 1), (-2, 3)], n=4)  # explicit n, same error

    def test_strict_rejects_self_loops_naming_rows(self):
        from graphdyn.graphs import from_edgelist

        with pytest.raises(ValueError, match="self-loop") as ei:
            from_edgelist([(0, 1), (2, 2), (3, 3)], n=4, strict=True)
        msg = str(ei.value)
        assert "2 self-loop(s)" in msg and "row(s) [1, 2]" in msg
        assert "strict=False" in msg              # the remedy is named

    def test_strict_rejects_duplicates_either_orientation(self):
        from graphdyn.graphs import from_edgelist

        with pytest.raises(ValueError, match="duplicate") as ei:
            from_edgelist([(0, 1), (2, 3), (1, 0)], n=4, strict=True)
        assert "[[0, 1]]" in str(ei.value)        # the duplicated pair
        with pytest.raises(ValueError, match="duplicate"):
            from_edgelist([(0, 1), (0, 1)], n=2, strict=True)

    def test_strict_round_trip_on_simple_graphs(self):
        from graphdyn.graphs import from_edgelist, powerlaw_graph

        # the documented contract: any simple Graph's edge list passes
        # strict and reproduces the tables exactly
        for g in (random_regular_graph(60, 3, seed=4),
                  powerlaw_graph(90, gamma=2.3, dmin=2, seed=5)):
            h = from_edgelist(g.edges, n=g.n, strict=True)
            assert np.array_equal(h.nbr, g.nbr)
            assert np.array_equal(h.deg, g.deg)
            assert np.array_equal(h.edges, g.edges)


class TestPowerlawGraph:
    def test_validation(self):
        from graphdyn.graphs import powerlaw_graph

        with pytest.raises(ValueError, match="n"):
            powerlaw_graph(1)
        with pytest.raises(ValueError, match="dmin"):
            powerlaw_graph(50, dmin=0)
        with pytest.raises(ValueError, match="gamma"):
            powerlaw_graph(50, gamma=1.0)
        with pytest.raises(ValueError, match="dmax"):
            powerlaw_graph(50, dmin=5, dmax=3)

    def test_deterministic_simple_heavy_tailed(self):
        from graphdyn.graphs import degree_cv, powerlaw_graph

        a = powerlaw_graph(800, gamma=2.3, dmin=2, seed=11)
        b = powerlaw_graph(800, gamma=2.3, dmin=2, seed=11)
        assert np.array_equal(a.edges, b.edges)
        _assert_simple(a)
        assert (a.deg >= 1).all()          # configuration repair keeps degrees
        # the tail is the point: CV crosses the bucketed-routing threshold
        assert degree_cv(a.deg) >= 1.0
        assert a.dmax >= 8 * np.median(a.deg)

    def test_ba_method(self):
        from graphdyn.graphs import powerlaw_graph

        g = powerlaw_graph(300, dmin=2, seed=4, method="ba")
        _assert_simple(g)
        assert (g.deg[2:] >= 2).all()

    def test_stub_parity_respects_dmax(self):
        """The parity bump lands on a node below dmax, so the documented
        [dmin, dmax] degree support holds even when the bumped draw sat at
        the cutoff (sweep enough seeds that the parity branch fires on
        dmax-heavy draws)."""
        from graphdyn.graphs import powerlaw_graph

        for seed in range(24):
            g = powerlaw_graph(30, gamma=1.5, dmin=2, dmax=3, seed=seed)
            assert int(g.deg.max()) <= 3, seed
        # degenerate single-point support with odd total: sheds one stub
        # instead of looping or breaching dmax
        g = powerlaw_graph(5, gamma=2.0, dmin=3, dmax=3, seed=1)
        assert int(g.deg.max()) <= 3


class TestDegreeBuckets:
    def test_layout_invariants(self):
        from graphdyn.graphs import degree_buckets, powerlaw_graph

        g = powerlaw_graph(500, gamma=2.3, dmin=2, seed=6)
        b = degree_buckets(g)
        # widths are powers of two; every node's degree fits half-open
        assert all(w & (w - 1) == 0 for w in b.widths)
        for i, deg_b in enumerate(b.deg):
            w = b.widths[i]
            assert (deg_b <= w).all()
            if w > 1:
                assert (deg_b > w // 2).all()
        # order/inv are inverse permutations; blocks tile the node set
        assert np.array_equal(np.sort(b.order), np.arange(g.n))
        assert np.array_equal(b.order[b.inv], np.arange(g.n))
        assert b.offsets[-1] == g.n
        assert b.table_entries == sum(
            t.shape[0] * t.shape[1] for t in b.nbr)
        # edge-proportional: tight blocks beat the padded n·dmax table
        assert b.table_entries <= 4 * g.num_edges + g.n
        assert b.table_entries < g.n * g.dmax

    def test_neighbor_sets_preserved(self):
        from graphdyn.graphs import degree_buckets, powerlaw_graph

        g = powerlaw_graph(200, gamma=2.5, dmin=2, seed=8)
        b = degree_buckets(g)
        for i, blk in enumerate(b.nbr):
            for k in range(blk.shape[0]):
                new = b.offsets[i] + k
                old = b.order[new]
                d = int(g.deg[old])
                got = blk[k]
                assert (got[d:] == g.n).all()       # ghost-padded tail
                want = sorted(b.inv[g.nbr[old][:d]])
                assert sorted(got[:d]) == want      # bucketed neighbor ids

    def test_seeded_shuffle_stays_in_bucket(self):
        from graphdyn.graphs import degree_buckets, powerlaw_graph

        g = powerlaw_graph(300, gamma=2.4, dmin=2, seed=9)
        a = degree_buckets(g)
        c = degree_buckets(g, seed=3)
        assert a.widths == c.widths
        assert np.array_equal(a.offsets, c.offsets)
        for i in range(len(a.widths)):
            lo, hi = a.offsets[i], a.offsets[i + 1]
            assert set(a.order[lo:hi]) == set(c.order[lo:hi])


def test_degree_cv_reference_values():
    from graphdyn.graphs import degree_cv

    assert degree_cv(np.full(100, 7)) == pytest.approx(0.0)
    assert degree_cv(np.array([], np.int64)) == 0.0
    deg = np.array([1, 1, 1, 1, 96])
    assert degree_cv(deg) == pytest.approx(np.std(deg) / np.mean(deg))


def test_permute_nodes_round_trip():
    from graphdyn.graphs import permute_nodes, powerlaw_graph

    g = powerlaw_graph(120, gamma=2.5, dmin=2, seed=2)
    order = np.random.default_rng(0).permutation(g.n)
    h, inv = permute_nodes(g, order)
    assert np.array_equal(inv[order], np.arange(g.n))
    assert np.array_equal(np.sort(h.deg), np.sort(g.deg))
    # edges relabel consistently: endpoint degree multiset is preserved
    assert np.array_equal(
        np.sort(g.deg[g.edges].ravel()), np.sort(h.deg[h.edges].ravel()))
