"""ops/lut: the per-degree update-LUT generator and its packed application.

The contract (ISSUE 14 satellite): :func:`update_lut` is exhaustively
oracle-exact against :func:`graphdyn.ops.dynamics.step_spins` over ALL
(degree ≤ dmax, popcount ≤ degree, spin) triples for every (rule, tie)
pair — the oracle is the reference's ``R·sign(2Σ + C·s)`` integer form run
through the shipped kernel on star graphs, not the LUT formula itself —
and :func:`lut_one_step` is bit-identical to the hand-derived packed
comparator step on RRG and ragged ER degree sequences. This is the
groundwork ROADMAP item 4's rule axis compiles into."""

import numpy as np
import pytest

import jax.numpy as jnp

from graphdyn.config import DynamicsConfig, SAConfig
from graphdyn.graphs import erdos_renyi_graph, random_regular_graph
from graphdyn.ops.dynamics import Rule, TieBreak, step_spins
from graphdyn.ops.lut import lut_node_masks, lut_one_step, update_lut

ALL_PAIRS = [(r, t) for r in ("majority", "minority")
             for t in ("stay", "change")]


@pytest.mark.parametrize("rule,tie", ALL_PAIRS)
@pytest.mark.parametrize("dmax", [1, 3, 4, 6])
def test_update_lut_exhaustive_star_oracle(rule, tie, dmax):
    """Every (deg, cnt, spin) entry equals one synchronous step of the
    shipped dynamics kernel on a star: node 0 has exactly ``deg``
    neighbors of which ``cnt`` are +1."""
    lut = update_lut(dmax, rule, tie)
    assert lut.shape == (dmax + 1, dmax + 1, 2)
    for deg in range(dmax + 1):
        for cnt in range(deg + 1):
            for b in (0, 1):
                n = max(deg, 1) + 1
                nbr = np.full((n, max(deg, 1)), n, np.int32)
                if deg:
                    nbr[0, :deg] = np.arange(1, deg + 1)
                s = -np.ones(n, np.int8)
                s[0] = 2 * b - 1
                if deg:
                    s[1:1 + cnt] = 1
                out = int(np.asarray(
                    step_spins(jnp.asarray(nbr), jnp.asarray(s), rule, tie)
                )[0])
                want = 1 if lut[deg, cnt, b] else -1
                assert out == want, (rule, tie, deg, cnt, b)


def test_update_lut_validations_and_mask_shapes():
    with pytest.raises(ValueError, match="dmax"):
        update_lut(-1)
    lut = update_lut(3)
    deg_ext = np.array([3, 2, 0, 3, 0], np.int64)   # last row = ghost
    masks = lut_node_masks(deg_ext, lut)
    assert masks.shape == (4, 2, 5)
    assert set(np.unique(masks)) <= {0, 0xFFFFFFFF}
    # the ghost row's masks are forced zero regardless of the table
    assert (masks[:, :, -1] == 0).all()
    # a degree above the table's dmax is refused, not silently clamped
    with pytest.raises(ValueError, match="exceeds"):
        lut_node_masks(np.array([5, 0]), lut)


@pytest.mark.parametrize("rule,tie", ALL_PAIRS)
@pytest.mark.parametrize("gname", ["rrg", "er"])
def test_lut_one_step_matches_comparator_step(rule, tie, gname):
    """The LUT application is bit-identical to the hand-derived packed
    comparator step (``ops.chromatic._one_step`` over the shared
    ``ops.packed`` helpers) on regular AND ragged degree sequences — the
    structural bridge that lets the fused annealer swap rules without new
    word logic."""
    from graphdyn.ops.chromatic import _one_step, _threshold_words
    from graphdyn.ops.packed import pack_spins

    g = (random_regular_graph(64, 3, seed=0) if gname == "rrg"
         else erdos_renyi_graph(50, 4.0 / 49, seed=1))
    n, dmax = g.n, g.nbr.shape[1]
    nbr_ext = jnp.asarray(np.concatenate(
        [g.nbr, np.full((1, dmax), n, g.nbr.dtype)], axis=0
    ).astype(np.int32))
    deg_ext = np.concatenate([g.deg, [0]]).astype(np.int32)
    rng = np.random.default_rng(2)
    s = (2 * rng.integers(0, 2, size=(40, n)) - 1).astype(np.int8)
    sp = pack_spins(s)
    sp_ext = jnp.concatenate(
        [jnp.asarray(sp), jnp.zeros((1, sp.shape[1]), jnp.uint32)], axis=0
    )
    lm = jnp.asarray(lut_node_masks(deg_ext, update_lut(dmax, rule, tie)))
    got = lut_one_step(sp_ext, nbr_ext, lm, n=n, dmax=dmax)
    n_planes = max(int(dmax).bit_length(), 1)
    thr_bits, even = _threshold_words(jnp.asarray(deg_ext), n_planes)
    want = _one_step(sp_ext, nbr_ext, thr_bits, even, n, dmax,
                     Rule(rule), TieBreak(tie))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_fused_tables_compile_config_rule():
    """build_fused_tables compiles the CONFIG's (rule, tie) into the
    masks: a minority/change table differs from majority/stay on the same
    graph, and the anneal factors are par**|class| per class."""
    from graphdyn.ops.pallas_anneal import build_fused_tables

    g = random_regular_graph(48, 3, seed=0)
    maj = build_fused_tables(
        g, SAConfig(dynamics=DynamicsConfig(p=1, c=1)), seed=0)
    mino = build_fused_tables(
        g, SAConfig(dynamics=DynamicsConfig(
            p=1, c=1, rule="minority", tie="change")), seed=0)
    assert not np.array_equal(maj.lut_masks, mino.lut_masks)
    np.testing.assert_array_equal(maj.masks_ext, mino.masks_ext)
    cfg = SAConfig(dynamics=DynamicsConfig(p=1, c=1))
    sizes = maj.chrom.class_sizes
    np.testing.assert_allclose(
        maj.fac_a, (cfg.par_a ** sizes.astype(np.float64)).astype(np.float32))
    assert maj.masks_ext.shape == (maj.chi, g.n + 1)
    assert (maj.masks_ext[:, -1] == 0).all()
