"""graphdyn.resilience.store — the durable checkpoint contract, unit level.

What the soak harness proves end to end (tests/test_soak.py), this module
pins piece by piece: checksum-verified loads that detect silent bit rot
100% of the time, keep-last-K retention with an atomic promote, the
monotonic quarantine suffix with its retention cap, write-behind mirror
replication with checksum-verified failover, degraded-mirror semantics, and
the run journal's schema. Carries the ``faultinject`` marker: the two new
fault sites (``checkpoint.bitrot``, ``mirror.write``) live here, so
``scripts/lint.sh`` faultcheck exercises them standalone.
"""

import json
import logging
import os

import numpy as np
import pytest

from graphdyn.resilience import FaultPlan, FaultSpec, InjectedPreemption
from graphdyn.resilience import faults as faults_mod
from graphdyn.resilience.store import (
    DurableCheckpoint,
    configure_store,
    flush_mirror,
    journal_path_for,
    validate_journal,
)
from graphdyn.utils.io import Checkpoint, open_checkpoint

pytestmark = pytest.mark.faultinject


@pytest.fixture(autouse=True)
def _default_store_config():
    """Every test starts from the defaults (no mirror, keep=2) and cannot
    leak its config into the next."""
    configure_store(mirror=None, keep=2)
    yield
    configure_store(mirror=None, keep=2)


def _save_n(ck, n, base=0):
    for i in range(n):
        ck.save({"x": np.arange(6) + base + i, "y": np.float64(i)},
                {"step": base + i})


# ---------------------------------------------------------------------------
# layout: versions, manifests, promote, retention
# ---------------------------------------------------------------------------


def test_save_publishes_current_plus_versions_and_manifests(tmp_path):
    ck = open_checkpoint(str(tmp_path / "ck"))
    assert isinstance(ck, DurableCheckpoint)       # the factory routes here
    _save_n(ck, 3)
    names = sorted(os.listdir(tmp_path))
    assert "ck.npz" in names and "ck.manifest.json" in names
    # keep=2: versions 2 and 3 retained, version 1 pruned
    assert "ck.v2.npz" in names and "ck.v3.npz" in names
    assert "ck.v1.npz" not in names
    assert "ck.v2.manifest.json" in names and "ck.v3.manifest.json" in names
    arrays, meta = ck.load()
    np.testing.assert_array_equal(arrays["x"], np.arange(6) + 2)
    assert meta == {"step": 2}
    # the published file and the newest version are the same bytes (the
    # promote is a hard link of the immutable version file)
    assert os.path.samefile(str(tmp_path / "ck.npz"),
                            str(tmp_path / "ck.v3.npz"))


def test_retention_honors_keep(tmp_path):
    configure_store(keep=3)
    ck = open_checkpoint(str(tmp_path / "ck"))
    _save_n(ck, 6)
    versions = sorted(f for f in os.listdir(tmp_path)
                      if f.startswith("ck.v") and f.endswith(".npz"))
    assert versions == ["ck.v4.npz", "ck.v5.npz", "ck.v6.npz"]


def test_version_numbering_survives_requeue(tmp_path):
    """A fresh DurableCheckpoint instance on the same path (a requeued
    process) continues the version sequence — it never re-publishes an old
    version number (the journal's exactly-once check depends on this)."""
    path = str(tmp_path / "ck")
    _save_n(open_checkpoint(path), 2)
    _save_n(open_checkpoint(path), 1, base=2)
    events, problems = validate_journal(journal_path_for(path))
    assert problems == []
    saves = [e["version"] for e in events if e.get("op") == "save"]
    assert saves == [1, 2, 3]


def test_remove_cleans_everything_but_quarantines(tmp_path):
    ck = open_checkpoint(str(tmp_path / "ck"))
    _save_n(ck, 3)
    faults_mod.flip_npz_bytes(str(tmp_path / "ck.npz"), seed=0)
    ck.load()                                       # quarantines the current
    ck.remove()
    left = sorted(os.listdir(tmp_path))
    assert left == ["ck.corrupt.1.npz", "run_journal.jsonl"]


# ---------------------------------------------------------------------------
# checksum layer: silent bit rot is detected 100% of the time
# ---------------------------------------------------------------------------


def test_flip_npz_bytes_keeps_container_valid_but_changes_data(tmp_path):
    """The fault payload models SILENT rot: np.load succeeds (CRCs are
    recomputed) and returns different bytes — exactly the corruption class
    PR-2's zipfile-error quarantine could never see."""
    p = str(tmp_path / "s")
    Checkpoint(p).save({"x": np.arange(64.0)}, {"t": 1})
    faults_mod.flip_npz_bytes(p + ".npz", seed=3)
    arrays, meta = Checkpoint(p)._read_npz(p + ".npz")  # no structural error
    assert meta == {"t": 1}                             # meta member intact
    assert not np.array_equal(arrays["x"], np.arange(64.0))


@pytest.mark.parametrize("seed", range(8))
def test_bitrot_never_resumes_wrong_state(tmp_path, seed):
    """Across seeds: a bit-rotted current snapshot is ALWAYS detected on
    load — the result is either the intact previous version or None, never
    the corrupted arrays."""
    ck = open_checkpoint(str(tmp_path / "ck"))
    good = np.arange(512) * 7
    ck.save({"x": good}, {"t": 1})
    faults_mod.flip_npz_bytes(str(tmp_path / "ck.npz"), seed=seed)
    loaded = ck.load()
    assert os.path.exists(str(tmp_path / "ck.corrupt.1.npz"))
    assert loaded is not None                      # v1 survived the rewrite
    np.testing.assert_array_equal(loaded[0]["x"], good)


def test_checkpoint_bitrot_fault_site_fires_and_recovers(tmp_path, caplog):
    ck = open_checkpoint(str(tmp_path / "ck"))
    _save_n(ck, 2)
    with caplog.at_level(logging.WARNING, logger="graphdyn.resilience"):
        with FaultPlan([FaultSpec("checkpoint.bitrot", action="bitrot")]):
            arrays, meta = ck.load()
    np.testing.assert_array_equal(arrays["x"], np.arange(6) + 1)  # last save
    assert "quarantined" in caplog.text and "FAILOVER" in caplog.text
    events, problems = validate_journal(journal_path_for(str(tmp_path / "ck")))
    assert problems == []
    ops = [e.get("op") for e in events if e.get("ev") == "journal"]
    assert "quarantine" in ops and "failover" in ops
    q = next(e for e in events if e.get("op") == "quarantine")
    assert "Checksum" in q["reason"]


def test_stale_manifest_is_rejected_not_trusted(tmp_path):
    """A current manifest that disagrees with the current snapshot (crash
    between promote and manifest write, or manifest rot) must fail closed:
    fall back to a version whose own manifest verifies."""
    ck = open_checkpoint(str(tmp_path / "ck"))
    _save_n(ck, 2)
    man_path = str(tmp_path / "ck.manifest.json")
    with open(man_path) as f:
        doc = json.load(f)
    doc["meta_sha256"] = "0" * 64                  # stale/corrupt manifest
    from graphdyn.utils.io import write_json_atomic

    write_json_atomic(man_path, doc)
    arrays, meta = ck.load()
    assert meta == {"step": 1}                     # recovered via v2
    ops = [e.get("op") for e in
           validate_journal(journal_path_for(str(tmp_path / "ck")))[0]
           if e.get("ev") == "journal"]
    assert "quarantine" in ops                     # self-sha caught it


def test_legacy_plain_snapshot_loads_unverified(tmp_path):
    """Format compatibility: a plain-Checkpoint snapshot (no manifest, no
    versions) still loads through the durable store — and the journal says
    it was unverified."""
    p = str(tmp_path / "ck")
    Checkpoint(p).save({"x": np.arange(4)}, {"t": 9})
    arrays, meta = open_checkpoint(p).load()
    assert meta == {"t": 9}
    loads = [e for e in validate_journal(journal_path_for(p))[0]
             if e.get("op") == "load"]
    assert loads and loads[-1]["verified"] is False


def test_durable_snapshot_readable_by_plain_checkpoint(tmp_path):
    """The inverse interop: the published <path>.npz keeps the exact PR-2
    format (snapshot formats unchanged — the acceptance criterion)."""
    p = str(tmp_path / "ck")
    open_checkpoint(p).save({"x": np.arange(4)}, {"t": 5})
    arrays, meta = Checkpoint(p).load()
    assert meta == {"t": 5}
    np.testing.assert_array_equal(arrays["x"], np.arange(4))


def test_transient_oserror_propagates_from_durable_load(tmp_path, monkeypatch):
    """The PR-2 policy survives the durable wrapper: a transient OSError on
    every candidate re-raises — no quarantine, no silent fresh start."""
    import graphdyn.utils.io as io_mod

    ck = open_checkpoint(str(tmp_path / "ck"))
    _save_n(ck, 2)
    monkeypatch.setattr(
        io_mod.np, "load",
        lambda *a, **k: (_ for _ in ()).throw(OSError(5, "EIO")))
    with pytest.raises(OSError):
        ck.load()
    monkeypatch.undo()
    assert ck.load()[1] == {"step": 1}             # intact after the blip
    assert not any(f.startswith("ck.corrupt") for f in os.listdir(tmp_path))


def test_current_missing_falls_back_to_version(tmp_path):
    """Crash between the version write and the promote: the published file
    is gone (or old) but the version + manifest are on disk — the load
    finds it instead of restarting."""
    ck = open_checkpoint(str(tmp_path / "ck"))
    _save_n(ck, 2)
    os.remove(str(tmp_path / "ck.npz"))
    os.remove(str(tmp_path / "ck.manifest.json"))
    arrays, meta = ck.load()
    assert meta == {"step": 1}


# ---------------------------------------------------------------------------
# quarantine: monotonic suffix + bounded retention (satellite)
# ---------------------------------------------------------------------------


def test_quarantine_suffix_is_monotonic_and_capped(tmp_path):
    """A second corruption must not overwrite the first's evidence; an
    unattended requeue loop must not fill the disk — at most 5 quarantines
    are retained, oldest removed first."""
    p = str(tmp_path / "s")
    ck = Checkpoint(p)
    for i in range(7):
        ck.save({"x": np.arange(4) + i}, {"i": i})
        with open(p + ".npz", "wb") as f:          # structural corruption
            f.write(b"not a zip %d" % i)
        assert ck.load() is None
    names = sorted(f for f in os.listdir(tmp_path) if ".corrupt." in f)
    # 7 corruptions → suffixes 1..7 were used, only the last 5 retained
    assert names == [f"s.corrupt.{i}.npz" for i in (3, 4, 5, 6, 7)]


# ---------------------------------------------------------------------------
# mirror: write-behind replication, failover, degraded mirror
# ---------------------------------------------------------------------------


def test_mirror_replicates_write_behind_and_fails_over(tmp_path):
    mirror = str(tmp_path / "mirror")
    configure_store(mirror=mirror)
    p = str(tmp_path / "primary" / "ck")
    ck = open_checkpoint(p)
    _save_n(ck, 2)
    flush_mirror()
    # the mirror namespace is one subdirectory per primary directory (so
    # same-named checkpoints of different jobs sharing one mirror cannot
    # collide), resolved by _mirror_base
    mbase = ck._mirror_base()
    assert os.path.dirname(os.path.dirname(mbase)) == mirror
    mnames = sorted(os.listdir(os.path.dirname(mbase)))
    assert "ck.npz" in mnames and "ck.manifest.json" in mnames
    assert "ck.v2.npz" in mnames
    # the primary directory dies wholesale — journal and all
    import shutil

    shutil.rmtree(str(tmp_path / "primary"))
    arrays, meta = ck.load()
    assert meta == {"step": 1}
    np.testing.assert_array_equal(arrays["x"], np.arange(6) + 1)
    events, problems = validate_journal(journal_path_for(p))
    assert problems == []
    fo = [e for e in events if e.get("op") == "failover"]
    assert fo and fo[-1]["source"] == "mirror"


def test_mirror_write_fault_degrades_primary_proceeds(tmp_path, caplog):
    """The mirror.write site: mirror-path ENOSPC must not fail the save —
    the primary publishes, the journal records the degraded mirror."""
    mirror = str(tmp_path / "mirror")
    configure_store(mirror=mirror)
    p = str(tmp_path / "primary" / "ck")
    ck = open_checkpoint(p)
    with caplog.at_level(logging.WARNING, logger="graphdyn.resilience"):
        with FaultPlan([FaultSpec("mirror.write", count=99)]):
            _save_n(ck, 2)
    flush_mirror()
    assert ck.load()[1] == {"step": 1}             # primary intact
    assert not os.path.exists(ck._mirror_base() + ".npz")
    assert "DEGRADED" in caplog.text
    events, problems = validate_journal(journal_path_for(p))
    assert problems == []
    assert sum(1 for e in events if e.get("op") == "mirror.degraded") == 2
    # the episode over, mirroring recovers on the next save
    ck.save({"x": np.arange(6), "y": np.float64(0)}, {"step": 9})
    flush_mirror()
    assert os.path.exists(ck._mirror_base() + ".npz")


def test_mirror_preempt_is_a_hard_kill(tmp_path):
    configure_store(mirror=str(tmp_path / "mirror"))
    ck = open_checkpoint(str(tmp_path / "primary" / "ck"))
    with FaultPlan([FaultSpec("mirror.write", "preempt")]):
        with pytest.raises(InjectedPreemption):
            ck.save({"x": np.arange(3)}, {})


def test_remove_cleans_mirror_too(tmp_path):
    mirror = str(tmp_path / "mirror")
    configure_store(mirror=mirror)
    ck = open_checkpoint(str(tmp_path / "primary" / "ck"))
    _save_n(ck, 2)
    flush_mirror()
    ck.remove()
    mdir = os.path.dirname(ck._mirror_base())
    assert not any(f.startswith("ck") for f in os.listdir(mdir))


# ---------------------------------------------------------------------------
# run journal: schema, sealing, exactly-once
# ---------------------------------------------------------------------------


def test_journal_is_read_ledger_parseable_and_schema_valid(tmp_path):
    p = str(tmp_path / "ck")
    ck = open_checkpoint(p)
    _save_n(ck, 2)
    ck.load()
    ck.remove()
    events, problems = validate_journal(journal_path_for(p))
    assert problems == []
    ops = [e["op"] for e in events if e.get("ev") == "journal"]
    assert ops == ["save", "save", "load", "remove"]
    assert events[0]["ev"] == "manifest"           # the process stamp


def test_journal_seals_torn_tail_of_a_killed_run(tmp_path):
    """A hard-killed process dies mid-journal-line; the next (requeued)
    process must seal the fragment so its own events survive parsing —
    the obs recorder's seam contract, reused."""
    from graphdyn.resilience import store as store_mod

    p = str(tmp_path / "ck")
    ck = open_checkpoint(p)
    _save_n(ck, 1)
    jpath = journal_path_for(p)
    with open(jpath, "a", encoding="utf-8") as f:
        f.write('{"ev": "journal", "t_unix": 1, "op": "sa')   # torn mid-line
    store_mod._reset_journal_state()               # simulate a new process
    _save_n(ck, 1, base=1)
    events, problems = validate_journal(jpath)
    assert problems == [
        "1 torn line(s) (sealed seams are tolerated)"
    ]
    assert [e["version"] for e in events if e.get("op") == "save"] == [1, 2]
    # two process stamps: the original and the requeue
    assert sum(1 for e in events if e.get("ev") == "manifest") == 2


def test_validate_journal_flags_unknown_ops_and_replayed_versions(tmp_path):
    jpath = str(tmp_path / "run_journal.jsonl")
    lines = [
        {"ev": "manifest", "t": 0.0, "run": {"journal": True}},
        {"ev": "journal", "t_unix": 1.0, "pid": 1, "op": "save",
         "path": "ck", "version": 2},
        {"ev": "journal", "t_unix": 2.0, "pid": 1, "op": "save",
         "path": "ck", "version": 2},              # replayed version
        {"ev": "journal", "t_unix": 3.0, "pid": 1, "op": "frobnicate",
         "path": "ck"},                            # unknown op
    ]
    with open(jpath, "w", encoding="utf-8") as f:
        f.writelines(json.dumps(e) + "\n" for e in lines)
    _, problems = validate_journal(jpath)
    assert any("re-published version" in p for p in problems)
    assert any("frobnicate" in p for p in problems)


# ---------------------------------------------------------------------------
# config plumbing
# ---------------------------------------------------------------------------


def test_atexit_flushes_queued_mirror_writes(tmp_path):
    """A run that saves and exits immediately must not drop its queued
    write-behind replicas: the module registers an atexit flush_mirror, so
    a normal interpreter exit drains the queue BEFORE daemon threads die.
    Proven end to end in a subprocess — the exact save-then-exit shape the
    write-behind race loses without the hook."""
    import subprocess
    import sys

    mirror = tmp_path / "mirror"
    primary = tmp_path / "primary"
    script = (
        "import numpy as np\n"
        "from graphdyn.resilience.store import DurableCheckpoint, "
        "configure_store\n"
        f"configure_store(mirror={str(mirror)!r})\n"
        f"ck = DurableCheckpoint({str(primary / 'ck')!r})\n"
        # several sizable saves so the write-behind queue is realistically
        # non-empty at exit — then fall off the end of the script
        "for i in range(4):\n"
        "    ck.save({'a': np.arange(200_000) + i}, {'v': i})\n"
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        timeout=120, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    # the mirror namespace is <mirror>/<dirhash8>/ck.npz
    replicas = list(mirror.glob("*/ck.npz"))
    assert replicas, (
        "queued mirror writes were dropped at exit "
        f"(mirror tree: {list(mirror.rglob('*'))})"
    )
    # the published replica is the LAST save (the queue drained fully)
    with np.load(replicas[0]) as f:
        assert f["a"][0] == 3


def test_flush_mirror_timeout_abandons_wedged_queue(monkeypatch, caplog):
    """The atexit flush is bounded: a mirror job wedged on a dead
    filesystem is logged and abandoned, never a hung interpreter exit."""
    import threading

    from graphdyn.resilience import store as store_mod

    release = threading.Event()
    store_mod._ensure_mirror_worker()
    store_mod._mirror_q.put(lambda: release.wait(20))
    try:
        with caplog.at_level(logging.WARNING, logger="graphdyn.resilience"):
            t0 = __import__("time").monotonic()
            flush_mirror(timeout_s=0.2)
            assert __import__("time").monotonic() - t0 < 5.0
        assert any("mirror flush timed out" in r.message
                   for r in caplog.records)
    finally:
        release.set()
        flush_mirror()                  # drain for the next test


def test_cli_flags_configure_the_store(tmp_path, capsys):
    """--ckpt-mirror/--ckpt-keep reach the singleton on every invocation —
    and are RESET on the next one (no leakage between in-process runs)."""
    from graphdyn.cli import main
    from graphdyn.resilience.store import CONFIG

    out = str(tmp_path / "r.npz")
    mirror = str(tmp_path / "m")
    rc = main(["--ckpt-mirror", mirror, "--ckpt-keep", "4",
               "sa", "--n", "40", "--d", "3", "--p", "1", "--c", "1",
               "--n-stat", "1", "--max-steps", "20000", "--seed", "0",
               "--checkpoint", str(tmp_path / "ck"), "--out", out])
    capsys.readouterr()
    assert rc == 0
    assert CONFIG.mirror == mirror and CONFIG.keep == 4
    rc = main(["sa", "--n", "40", "--d", "3", "--p", "1", "--c", "1",
               "--n-stat", "1", "--max-steps", "20000", "--seed", "0"])
    capsys.readouterr()
    assert rc == 0
    assert CONFIG.mirror is None and CONFIG.keep == 2
