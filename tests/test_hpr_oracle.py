"""BP parity oracle for the HPr message update and marginals.

The framework's sweep (`graphdyn.ops.bdcm.make_sweep` with
``with_bias=True, mask_invalid_src=False``) must agree *message-level* with
the reference algorithm `HPr_dp` (`HPR_pytorch_RRG.py:183-218`) and
`marginals_comp` (`HPR_pytorch_RRG.py:147-167`). Rather than transcribing the
reference's neighbor DP, the oracle here evaluates the defining sum directly —
brute force over all K^(d-1) assignments of incoming source trajectories:

    chi'_(i,j)[x_i, x_j] = sum_{(x_k)_{k in di\\j}}
        A(x_i, x_j, rho=sum_k x_k; lambda)
        * prod_k  b_k(x_k(0)) * chi_(k,i)[x_k, x_i]

followed by per-edge normalization and damping
(`HPR_pytorch_RRG.py:209-215`), where A is the reference's `A_i_sums`
(`HPR:38-39`): exp(-lambda*x_i(0)) * atr_condition * traj_condition *
attr_fix. An independent evaluation of the same mathematical object is a
stronger cross-check than re-running the same DP twice: any indexing,
rho-lattice, gather-table, or bias-wiring bug in the framework breaks it.

Marginals oracle (`marginals_comp` semantics): per directed edge (i,k),
Z+-(i,k) = sum over {x_i : x_i(0)=+-1} x {x_k} of
chi^(ik)[x_i,x_k]*chi^(ki)[x_k,x_i], eps-clamped and normalized; the node
marginal is the product of Z+- over i's outgoing edges, normalized.
"""

import itertools

import numpy as np
import pytest

import jax.numpy as jnp

from graphdyn.attractors import trajectories01
from graphdyn.graphs import build_edge_tables, random_regular_graph
from graphdyn.ops.bdcm import BDCMData, make_marginals, make_sweep

from tests.test_bdcm import ref_atr, ref_traj


def scalar_A(xi, xj, rho, p, c, attr_value, lmbd):
    """The reference's A_i_sums (`HPR_pytorch_RRG.py:38-39`) evaluated
    scalar-wise: lambda-tilt on the initial spin times the three indicator
    conditions."""
    return (
        np.exp(-lmbd * xi[0])
        * ref_atr(xi, xj, rho, p, c)
        * ref_traj(xi, xj, rho, p, c)
        * (xi[p + c - 1] == attr_value)
    )


def oracle_sweep(chi, biases, tables, *, p, c, lmbd, damp, attr_value=1):
    """One bias-weighted BDCM sweep by brute-force assignment enumeration
    (float64). ``chi``: [2E, K, K]; ``biases``: [n, 2] (col 0 = +1)."""
    T = p + c
    K = 2**T
    X = 2 * trajectories01(T) - 1                 # [K, T] in +-1
    E2 = tables.num_directed
    new = np.zeros_like(chi, dtype=np.float64)
    for e in range(E2):
        d_in = int(tables.edge_deg[e])
        in_e = [int(ee) for ee in tables.in_edges[e][:d_in]]
        for a in range(K):
            for b in range(K):
                tot = 0.0
                for assign in itertools.product(range(K), repeat=d_in):
                    w = 1.0
                    rho = np.zeros(T)
                    for slot, kk in enumerate(assign):
                        ee = in_e[slot]
                        k_node = int(tables.src[ee])
                        bk = biases[k_node, 0] if X[kk][0] == 1 else biases[k_node, 1]
                        w *= bk * chi[ee, kk, a]
                        rho = rho + X[kk]
                    tot += scalar_A(X[a], X[b], rho, p, c, attr_value, lmbd) * w
                new[e, a, b] = tot
    z = new.sum(axis=(1, 2), keepdims=True)
    new = new / np.maximum(z, np.finfo(np.float64).tiny)
    return damp * new + (1.0 - damp) * chi


def oracle_marginals(chi, tables, n, *, eps=1e-15):
    """Node marginals per `marginals_comp` (`HPR_pytorch_RRG.py:147-167`)."""
    K = chi.shape[1]
    T = int(np.log2(K))
    X = 2 * trajectories01(T) - 1
    E2 = tables.num_directed
    E = E2 // 2
    Zp = np.zeros(E2)
    Zm = np.zeros(E2)
    for e in range(E2):
        rev = (e + E) % E2
        for a in range(K):
            for b in range(K):
                v = chi[e, a, b] * chi[rev, b, a]
                if X[a][0] == 1:
                    Zp[e] += v
                else:
                    Zm[e] += v
    Zp = np.maximum(Zp, eps)
    Zm = np.maximum(Zm, eps)
    z = Zp + Zm
    Zp, Zm = Zp / z, Zm / z
    marg = np.zeros((n, 2))
    for i in range(n):
        out_e = [int(ee) for ee in tables.node_out_edges[i] if ee < E2]
        marg[i, 0] = np.prod(Zp[out_e])
        marg[i, 1] = np.prod(Zm[out_e])
    return marg / marg.sum(axis=1, keepdims=True)


def _setup(n, d, p, c, seed):
    g = random_regular_graph(n, d, seed=seed)
    tables = build_edge_tables(g)
    data = BDCMData(g, tables, p=p, c=c)
    rng = np.random.default_rng(seed + 1)
    chi = np.asarray(data.init_messages(rng), np.float64)
    biases = rng.random((n, 2))
    biases /= biases.sum(axis=1, keepdims=True)
    # the HPr bias gather: incoming message weighted by its source node's
    # bias at the trajectory's initial value (`HPR:120-133`)
    sel_plus = data.x0 == 1
    bias_edge = np.where(sel_plus[None, :], biases[tables.src, 0, None],
                         biases[tables.src, 1, None])
    return g, tables, data, chi, biases, bias_edge


@pytest.mark.parametrize(
    "n,d,p,c,lmbd",
    [(16, 4, 1, 1, 25.0), (16, 4, 1, 1, 1.0), (14, 3, 2, 1, 2.0)],
)
def test_sweep_matches_bruteforce_oracle(n, d, p, c, lmbd):
    """Message-level parity after one sweep, HPr semantics (bias-weighted,
    unmasked invalid sources, eps_clamp=0, damp=0.4 as `HPR:229`)."""
    damp = 0.4
    g, tables, data, chi, biases, bias_edge = _setup(n, d, p, c, seed=3)
    sweep = make_sweep(data, damp=damp, eps_clamp=0.0,
                       mask_invalid_src=False, with_bias=True)
    got = np.asarray(
        sweep(jnp.asarray(chi, jnp.float32), jnp.float32(lmbd),
              jnp.asarray(bias_edge, jnp.float32))
    )
    want = oracle_sweep(chi, biases, tables, p=p, c=c, lmbd=lmbd, damp=damp)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=1e-6)


def test_iterated_sweep_matches_oracle():
    """Parity holds through N=4 iterated sweeps (errors do not compound
    beyond f32 accumulation — the framework is running the same fixed-point
    map as the reference algorithm, not a lookalike)."""
    n, d, p, c, lmbd, damp = 16, 4, 1, 1, 25.0, 0.4
    g, tables, data, chi, biases, bias_edge = _setup(n, d, p, c, seed=9)
    sweep = make_sweep(data, damp=damp, eps_clamp=0.0,
                       mask_invalid_src=False, with_bias=True)
    got = jnp.asarray(chi, jnp.float32)
    want = chi
    for _ in range(4):
        got = sweep(got, jnp.float32(lmbd), jnp.asarray(bias_edge, jnp.float32))
        want = oracle_sweep(want, biases, tables, p=p, c=c, lmbd=lmbd, damp=damp)
    np.testing.assert_allclose(np.asarray(got), want, rtol=5e-3, atol=1e-6)


def test_marginals_match_oracle():
    n, d, p, c = 16, 4, 1, 1
    g, tables, data, chi, biases, bias_edge = _setup(n, d, p, c, seed=5)
    marginals = make_marginals(data, eps=1e-15)
    got = np.asarray(marginals(jnp.asarray(chi, jnp.float32)))
    want = oracle_marginals(chi, tables, n)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-7)


def test_marginals_epsilon_clamp_path():
    """The eps=1e-15 clamp (`HPR:147,157-158`) engages on an all-mass-on-one-
    side chi without NaNs/zeros in the output."""
    n, d, p, c = 12, 3, 1, 1
    g, tables, data, chi, _, _ = _setup(n, d, p, c, seed=7)
    chi = np.zeros_like(chi)
    chi[:, 0, 0] = 1.0            # all mass on the all-ones pair
    marginals = make_marginals(data, eps=1e-15)
    got = np.asarray(marginals(jnp.asarray(chi, jnp.float32)))
    want = oracle_marginals(chi, tables, n)
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-8)
