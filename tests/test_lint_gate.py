"""The lint gate as a tier-1 test: no CI service needed — the tier-1 pytest
command enforces ``scripts/lint.sh`` (and therefore graftlint) on every PR.

Kept *not-slow* on purpose: the gate is the cheapest test in the suite
(pure-AST, no jax import in the linted process beyond the package itself)
and the one that catches perf-invariant regressions nothing else can.
"""

import json
import os
import re
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

#: hatches the gate is KNOWN to carry — a floor, not the inventory: the
#: test below enumerates the real set from lint.sh itself, so a new step
#: cannot ship a silent hatch, and removing one of these fails loudly
KNOWN_HATCHES = {
    "GRAPHDYN_SKIP_FAULTCHECK", "GRAPHDYN_SKIP_SOAKCHECK",
    "GRAPHDYN_SKIP_PALLASCHECK", "GRAPHDYN_SKIP_HLOCHECK",
    "GRAPHDYN_SKIP_COSTCHECK",
    "GRAPHDYN_SKIP_OBSCHECK", "GRAPHDYN_SKIP_MEMCHECK",
    "GRAPHDYN_SKIP_COLORCHECK", "GRAPHDYN_SKIP_BENCHCHECK",
    "GRAPHDYN_SKIP_RACECHECK", "GRAPHDYN_SKIP_TRENDGATE",
    "GRAPHDYN_SKIP_SERVECHECK",
}


def skip_hatches() -> list[str]:
    """Every ``GRAPHDYN_SKIP_*`` escape hatch lint.sh consults — the
    inventory is derived from the script itself, so this test generalizes
    to steps that do not exist yet."""
    text = (REPO / "scripts" / "lint.sh").read_text()
    return sorted(set(re.findall(r"GRAPHDYN_SKIP_[A-Z]+", text)))


def test_skip_hatch_inventory_is_known():
    """The hatch set grows only deliberately: every hatch lint.sh consults
    is in the known list (add new ones HERE, with the step that owns
    them), and every known hatch still exists in the script.
    GRAPHDYN_SKIP_TRENDGATE is consulted by bench.py inside the benchcheck
    step rather than by lint.sh — it is asserted separately below."""
    in_script = set(skip_hatches())
    assert in_script <= KNOWN_HATCHES, (
        f"lint.sh grew undeclared skip hatches: "
        f"{sorted(in_script - KNOWN_HATCHES)} — add them to KNOWN_HATCHES "
        "and make the owning step announce itself when skipped"
    )
    missing = KNOWN_HATCHES - in_script - {"GRAPHDYN_SKIP_TRENDGATE"}
    assert not missing, f"known hatches vanished from lint.sh: {missing}"
    assert "GRAPHDYN_SKIP_TRENDGATE" in (REPO / "bench.py").read_text()


def test_lint_sh_gate_passes_and_every_skipped_step_announces():
    """scripts/lint.sh exits 0 on the repo (ruff/mypy skip gracefully when
    absent; graftlint always gates). EVERY step with a ``GRAPHDYN_SKIP_*``
    hatch is skipped here — the corresponding subsets (faultinject,
    pallas_interpret, graftcheck, racecheck, the soak matrix, the bench
    contract…) already run in this very suite, so re-running them nested
    would multiply the gate's cost for no extra coverage — and every
    skipped step must ANNOUNCE itself (``<HATCH>=1`` on stdout): a silent
    hatch is indistinguishable from a step that never existed, which is
    exactly how a gate rots."""
    hatches = [h for h in skip_hatches()]
    env = {**os.environ, **{h: "1" for h in hatches}}
    proc = subprocess.run(
        ["bash", str(REPO / "scripts" / "lint.sh")],
        cwd=REPO, capture_output=True, text=True, timeout=300, env=env,
    )
    assert proc.returncode == 0, (
        f"lint gate failed:\n{proc.stdout}\n{proc.stderr}"
    )
    assert "lint gate: OK" in proc.stdout
    for h in hatches:
        assert f"{h}=1" in proc.stdout, (
            f"the step guarded by {h} did not announce itself when "
            f"skipped — every hatch must print '<step>: {h}=1 — SKIPPED'"
        )


def test_graftlint_clean_on_package_json():
    """The acceptance-criterion invocation: ``python -m graphdyn.analysis
    graphdyn/ --format=json`` exits 0 (all remaining findings are explicitly
    disabled with reasons in-source) and emits valid JSON."""
    proc = subprocess.run(
        [sys.executable, "-m", "graphdyn.analysis", "graphdyn/",
         "--format=json"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    findings = json.loads(proc.stdout)
    assert proc.returncode == 0, f"undisabled findings: {findings}"
    assert findings == []


def test_gd007_active_in_gate(tmp_path):
    """GD007 (non-atomic persistence) is live in the gating linter: a
    direct np.savez to a non-temp path is a finding."""
    bad = tmp_path / "writer.py"
    bad.write_text(
        "import numpy as np\n\n"
        "def persist(path, arr):\n"
        "    np.savez(path, arr=arr)\n"   # GD007
    )
    proc = subprocess.run(
        [sys.executable, "-m", "graphdyn.analysis", str(bad),
         "--format=json"],
        cwd=REPO, capture_output=True, text=True, timeout=60,
    )
    findings = json.loads(proc.stdout)
    assert proc.returncode == 1, findings
    assert [f["code"] for f in findings] == ["GD007"]


def test_graftlint_exit_code_counts_findings(tmp_path):
    """exit code == number of findings (the documented CLI contract)."""
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\nimport numpy as np\n\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return np.tanh(x)\n"   # GD001
    )
    proc = subprocess.run(
        [sys.executable, "-m", "graphdyn.analysis", str(bad)],
        cwd=REPO, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 1, proc.stdout
    assert "GD001" in proc.stdout
