"""The lint gate as a tier-1 test: no CI service needed — the tier-1 pytest
command enforces ``scripts/lint.sh`` (and therefore graftlint) on every PR.

Kept *not-slow* on purpose: the gate is the cheapest test in the suite
(pure-AST, no jax import in the linted process beyond the package itself)
and the one that catches perf-invariant regressions nothing else can.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def test_lint_sh_gate_passes():
    """scripts/lint.sh exits 0 on the repo (ruff/mypy skip gracefully when
    absent; graftlint always gates). The faultcheck, pallascheck, hlocheck
    and benchcheck steps are skipped here — the faultinject,
    pallas_interpret and graftcheck subsets and the bench JSON contract
    all already run in this very suite (tests/test_graftcheck.py,
    tests/test_bench_contract.py); re-running them nested would multiply
    the gate's cost for no extra coverage."""
    proc = subprocess.run(
        ["bash", str(REPO / "scripts" / "lint.sh")],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "GRAPHDYN_SKIP_FAULTCHECK": "1",
             "GRAPHDYN_SKIP_BENCHCHECK": "1",
             "GRAPHDYN_SKIP_PALLASCHECK": "1",
             "GRAPHDYN_SKIP_HLOCHECK": "1",
             "GRAPHDYN_SKIP_OBSCHECK": "1",
             "GRAPHDYN_SKIP_MEMCHECK": "1",
             "GRAPHDYN_SKIP_COLORCHECK": "1",
             "GRAPHDYN_SKIP_SOAKCHECK": "1"},
    )
    assert proc.returncode == 0, (
        f"lint gate failed:\n{proc.stdout}\n{proc.stderr}"
    )
    assert "lint gate: OK" in proc.stdout
    assert "faultcheck" in proc.stdout    # the step exists and announced itself
    # the soakcheck hatch: the step exists, announced itself, and honored
    # the skip variable (the bounded soak matrix runs in-suite instead)
    assert "soakcheck: GRAPHDYN_SKIP_SOAKCHECK=1" in proc.stdout
    assert "benchcheck" in proc.stdout    # likewise for the bench contract
    assert "pallascheck" in proc.stdout   # likewise for the kernel parity set
    assert "hlocheck" in proc.stdout      # likewise for the program auditor
    assert "obscheck" in proc.stdout      # likewise for the roofline bands
    # the memcheck hatch: the step exists, announced itself, and honored
    # the skip variable (the device-memory check runs in-suite instead)
    assert "memcheck: GRAPHDYN_SKIP_MEMCHECK=1" in proc.stdout
    # the colorcheck hatch: likewise (the greedy-coloring validity
    # contract runs in-suite via tests/test_graphs.py)
    assert "colorcheck: GRAPHDYN_SKIP_COLORCHECK=1" in proc.stdout


def test_graftlint_clean_on_package_json():
    """The acceptance-criterion invocation: ``python -m graphdyn.analysis
    graphdyn/ --format=json`` exits 0 (all remaining findings are explicitly
    disabled with reasons in-source) and emits valid JSON."""
    proc = subprocess.run(
        [sys.executable, "-m", "graphdyn.analysis", "graphdyn/",
         "--format=json"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    findings = json.loads(proc.stdout)
    assert proc.returncode == 0, f"undisabled findings: {findings}"
    assert findings == []


def test_gd007_active_in_gate(tmp_path):
    """GD007 (non-atomic persistence) is live in the gating linter: a
    direct np.savez to a non-temp path is a finding."""
    bad = tmp_path / "writer.py"
    bad.write_text(
        "import numpy as np\n\n"
        "def persist(path, arr):\n"
        "    np.savez(path, arr=arr)\n"   # GD007
    )
    proc = subprocess.run(
        [sys.executable, "-m", "graphdyn.analysis", str(bad),
         "--format=json"],
        cwd=REPO, capture_output=True, text=True, timeout=60,
    )
    findings = json.loads(proc.stdout)
    assert proc.returncode == 1, findings
    assert [f["code"] for f in findings] == ["GD007"]


def test_graftlint_exit_code_counts_findings(tmp_path):
    """exit code == number of findings (the documented CLI contract)."""
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\nimport numpy as np\n\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return np.tanh(x)\n"   # GD001
    )
    proc = subprocess.run(
        [sys.executable, "-m", "graphdyn.analysis", str(bad)],
        cwd=REPO, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 1, proc.stdout
    assert "GD001" in proc.stdout
