"""Wedge-resume contract of scripts/run_baseline_configs.py.

The aggregator is how full-scale chip configs get captured across TPU-relay
wedges (the relay drops unpredictably mid-session): completed configs must
survive any kill, re-runs must resume rather than re-measure, and results
from a differently-configured environment must never be mixed in or
silently destroyed. Subprocess spawning and device probing are stubbed —
this pins the aggregation/resume logic itself.
"""

import importlib.util
import json
import os
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "run_baseline_configs", os.path.join(ROOT, "scripts", "run_baseline_configs.py"))
rbc = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(rbc)


def _entry(name, rc=0):
    metrics = [{"metric": f"m_{name}", "value": 1.0}] if rc == 0 else []
    return {"config": name, "rc": rc, "elapsed_s": 0.1, "metrics": metrics}


@pytest.fixture
def run(monkeypatch, tmp_path):
    """Run the aggregator main() with stubbed subprocess stages.

    ``fail`` names configs whose (stubbed) run should report rc=-1; returns
    (exit_code, parsed_doc, calls) where ``calls`` lists the configs that
    were actually (re)measured rather than resumed.
    """
    out = str(tmp_path / "configs.json")

    def _run(argv=(), fail=(), env=()):
        calls = []

        def fake_run_config(name, full, timeout_s):
            calls.append(name)
            return _entry(name, rc=-1 if name in fail else 0)

        monkeypatch.setattr(rbc, "run_config", fake_run_config)
        monkeypatch.setattr(rbc, "probe_device_info", lambda *a, **k: ("stub", ["dev0"]))
        for k in ("GRAPHDYN_FORCE_PLATFORM", "JAX_PLATFORMS", "XLA_FLAGS"):
            monkeypatch.delenv(k, raising=False)
        for k, v in dict(env).items():
            monkeypatch.setenv(k, v)
        monkeypatch.setattr(sys, "argv",
                            ["run_baseline_configs.py", "--out", out, *argv])
        with pytest.raises(SystemExit) as exc:
            rbc.main()
        with open(out) as f:
            doc = json.load(f)
        return exc.value.code, doc, calls

    _run.out = out
    return _run


def test_fresh_run_writes_complete_doc(run):
    code, doc, calls = run()
    assert code == 0 and doc["ok"] is True
    assert [c["config"] for c in doc["configs"]] == rbc.CONFIGS
    assert calls == rbc.CONFIGS
    assert doc["backend"] == "stub"
    for k in ("mode", "platform_forced", "jax_platforms", "xla_flags"):
        assert k in doc


def test_resume_skips_completed_and_retries_failed(run):
    code, doc, _ = run(fail=("config2_hpr",))
    assert code == 1 and doc["ok"] is False
    # second run: the failed config is re-measured, the others resumed
    code, doc, calls = run()
    assert calls == ["config2_hpr"]
    assert code == 0 and doc["ok"] is True
    assert all(c["rc"] == 0 for c in doc["configs"])


def test_only_subset_preserves_other_cached_entries(run):
    run(argv=["--only", "config3_er_majority"])
    code, doc, calls = run(argv=["--only", "config1_sa_rrg"])
    assert calls == ["config1_sa_rrg"]
    got = {c["config"] for c in doc["configs"]}
    # the config3 result from the first run must survive the config1 rerun
    assert got == {"config3_er_majority", "config1_sa_rrg"}
    assert code == 0


def test_platform_key_mismatch_backs_up_never_resumes(run):
    run(env={"GRAPHDYN_FORCE_PLATFORM": "cpu"})
    code, doc, calls = run(env={"GRAPHDYN_FORCE_PLATFORM": "axon"})
    # every config re-measured; the cpu doc moved aside, not destroyed
    assert calls == rbc.CONFIGS
    assert doc["platform_forced"] == "axon"
    backups = [p for p in os.listdir(os.path.dirname(run.out))
               if os.path.basename(p).startswith("configs.json.prior-")]
    assert backups, "mismatched prior doc must be backed up"
    with open(os.path.join(os.path.dirname(run.out), backups[0])) as f:
        prior = json.load(f)
    assert prior["platform_forced"] == "cpu" and prior["ok"] is True


def test_legacy_doc_without_key_fields_never_resumes(run):
    code, doc, _ = run()
    with open(run.out) as f:
        legacy = json.load(f)
    for k in ("platform_forced", "jax_platforms", "xla_flags"):
        legacy.pop(k)
    with open(run.out, "w") as f:
        json.dump(legacy, f)
    _, _, calls = run()
    assert calls == rbc.CONFIGS  # nothing resumed from the legacy doc


def test_fresh_flag_remeasures_everything(run):
    run()
    _, _, calls = run(argv=["--fresh"])
    assert calls == rbc.CONFIGS


def test_doc_on_disk_keeps_cached_entries_from_first_flush(run, monkeypatch):
    """Kill-at-any-point safety: with a cached entry present, the file on
    disk must contain it from the very first flush, before any config of
    the second run executes."""
    run(argv=["--only", "config3_er_majority"])

    seen = {}

    def exploding_run_config(name, full, timeout_s):
        with open(run.out) as f:
            seen["doc"] = json.load(f)
        raise KeyboardInterrupt  # simulate the wedge kill mid-config-1

    monkeypatch.setattr(rbc, "run_config", exploding_run_config)
    monkeypatch.setattr(sys, "argv",
                        ["run_baseline_configs.py", "--out", run.out])
    with pytest.raises(KeyboardInterrupt):
        rbc.main()
    cfgs = {c["config"] for c in seen["doc"]["configs"]}
    assert "config3_er_majority" in cfgs
    # and the on-disk doc still holds it after the crash
    with open(run.out) as f:
        assert {c["config"] for c in json.load(f)["configs"]} == cfgs
