"""graphdyn.obs — structured runtime telemetry (ARCHITECTURE.md "Runtime
telemetry").

Covers the PR-7 acceptance criteria: clean AND fault-injected grouped
entropy-grid runs produce schema-valid JSONL ledgers (including under
SIGTERM mid-chunk → exit 75), the roofline obscheck passes on the CPU
container, and the cross-round bench trend gate fails an artificially
slowed headline row with a pointed message while a ledger-blessed
deliberate change passes.
"""

import json
import os
import subprocess
import sys
import time
import tracemalloc

import numpy as np
import pytest

from graphdyn import obs
from graphdyn.obs.recorder import (
    EVENT_KINDS, NULL, NULL_SPAN, Recorder, read_ledger,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _assert_schema_valid(events):
    """Every event is a complete object of a known kind with the kind's
    required fields — the ledger schema contract."""
    assert events, "empty ledger"
    for e in events:
        assert e["ev"] in EVENT_KINDS, e
        assert isinstance(e["t"], (int, float)), e
        if e["ev"] == "span":
            assert {"name", "id", "t0", "wall_s", "cpu_s"} <= set(e), e
            assert e["wall_s"] >= 0 and e["cpu_s"] >= 0
        elif e["ev"] == "counter":
            assert {"name", "inc"} <= set(e), e
        elif e["ev"] == "gauge":
            assert {"name", "value"} <= set(e), e
        elif e["ev"] == "manifest":
            assert e["run"]["schema"] == obs.SCHEMA


# ---------------------------------------------------------------------------
# recorder core
# ---------------------------------------------------------------------------


def test_recorder_writes_jsonl_events(tmp_path):
    p = str(tmp_path / "run.jsonl")
    rec = Recorder(p)
    rec.manifest(cmd="test", backend="cpu")
    with rec.span("outer", stage="a"):
        with rec.span("inner"):
            pass
        rec.counter("hits", 2, site="x")
        rec.gauge("rate", 123.5, unit="u/s")
    rec.close()
    events, torn = read_ledger(p)
    assert torn == 0
    _assert_schema_valid(events)
    kinds = [e["ev"] for e in events]
    assert kinds[0] == "manifest"
    assert kinds.count("span") == 2 and "counter" in kinds and "gauge" in kinds


def test_span_nesting_parent_ids(tmp_path):
    """Spans nest via a thread-local stack: the inner span's ``parent`` is
    the outer's id; the outer is top-level (parent null). The inner CLOSES
    first, so it appears first in the ledger."""
    p = str(tmp_path / "run.jsonl")
    rec = Recorder(p)
    with rec.span("outer"):
        with rec.span("inner"):
            pass
    rec.close()
    events, _ = read_ledger(p)
    inner, outer = events
    assert inner["name"] == "inner" and outer["name"] == "outer"
    assert inner["parent"] == outer["id"]
    assert outer["parent"] is None


def test_span_measures_wall_and_cpu(tmp_path):
    rec = Recorder(str(tmp_path / "r.jsonl"))
    with rec.span("sleepy") as sp:
        time.sleep(0.02)
    rec.close()
    # a sleeping span waited (wall ≫ cpu) — the diagnostic the split exists
    # for
    assert sp.wall_s >= 0.015
    assert sp.cpu_s < sp.wall_s


def test_span_imperative_start_stop_idempotent(tmp_path):
    p = str(tmp_path / "r.jsonl")
    rec = Recorder(p)
    sw = rec.span("imperative").start()
    sw.stop()
    w = sw.wall_s
    sw.stop()                                    # idempotent: no re-emit
    rec.close()
    events, _ = read_ledger(p)
    assert len(events) == 1 and sw.wall_s == w


def test_abandoned_child_span_does_not_misparent_later_spans(tmp_path):
    """An imperative start() whose stop() is skipped by an exception must
    not leave its id on the thread-local stack: the enclosing span's close
    unwinds it, so the next top-level span parents correctly."""
    p = str(tmp_path / "r.jsonl")
    rec = Recorder(p)
    with pytest.raises(RuntimeError):
        with rec.span("run"):
            rec.span("solver.hpr").start()       # never stopped
            raise RuntimeError("solver died")
    with rec.span("next_run"):
        pass
    rec.close()
    events, _ = read_ledger(p)
    nxt = next(e for e in events if e["name"] == "next_run")
    assert nxt["parent"] is None                 # not the leaked solver id


def test_solver_exception_emits_span_and_unwinds(tmp_path):
    """hpr_solve's imperative solver span closes on the exception path —
    the try/finally contract: the span event is in the ledger and the
    stack is clean."""
    import jax.numpy as jnp

    from graphdyn.config import HPRConfig
    from graphdyn.graphs import random_regular_graph
    from graphdyn.models.hpr import hpr_solve

    g = random_regular_graph(20, 3, seed=0)
    p = str(tmp_path / "r.jsonl")
    with obs.recording(p) as rec:
        with pytest.raises(TypeError):
            # chi0 of a nonsense type dies inside the solver body
            hpr_solve(g, config=HPRConfig(max_sweeps=2), chi0=object())
        with rec.span("after"):
            pass
    events, _ = read_ledger(p)
    assert any(e.get("name") == "solver.hpr" for e in events)
    after = next(e for e in events if e.get("name") == "after")
    assert after["parent"] is None


def test_span_attrs_set_before_close(tmp_path):
    p = str(tmp_path / "r.jsonl")
    rec = Recorder(p)
    with rec.span("chunk", chunk=0) as sp:
        sp.set(sweeps_advanced=17)
    rec.close()
    (e,), _ = read_ledger(p)
    assert e["attrs"] == {"chunk": 0, "sweeps_advanced": 17}


def test_non_json_attrs_serialize_via_str(tmp_path):
    """numpy scalars / Paths in attrs must not kill the emit."""
    p = str(tmp_path / "r.jsonl")
    rec = Recorder(p)
    rec.gauge("g", np.float32(1.5), path=tmp_path)
    rec.close()
    events, torn = read_ledger(p)
    assert torn == 0 and len(events) == 1


def test_read_ledger_tolerates_torn_final_line(tmp_path):
    p = tmp_path / "r.jsonl"
    p.write_text('{"ev":"counter","t":0.1,"name":"a","inc":1}\n{"ev":"cou')
    events, torn = read_ledger(str(p))
    assert len(events) == 1 and torn == 1


def test_requeue_reopen_seals_torn_tail(tmp_path):
    """A requeued run reusing the same GRAPHDYN_OBS path after a hard kill:
    the new recorder seals the torn fragment onto its own line, its first
    event survives intact, and read_ledger tolerates the seam (torn line
    followed by the new run's manifest)."""
    p = str(tmp_path / "requeue.jsonl")
    rec = Recorder(p)
    rec.counter("before_kill")
    rec.close()
    with open(p, "a") as f:
        f.write('{"ev":"counter","t":9')         # hard kill mid-write
    rec2 = Recorder(p)                           # the requeue
    rec2.manifest(cmd="entropy")
    rec2.counter("after_requeue")
    rec2.close()
    events, torn = read_ledger(p)
    assert torn == 1
    assert [e.get("name", e["ev"]) for e in events] == [
        "before_kill", "manifest", "after_requeue"]


def test_read_ledger_rejects_torn_middle_line(tmp_path):
    p = tmp_path / "r.jsonl"
    p.write_text('{"ev":"cou\n{"ev":"counter","t":0.1,"name":"a","inc":1}\n')
    with pytest.raises(ValueError, match="torn JSON line in the middle"):
        read_ledger(str(p))


# ---------------------------------------------------------------------------
# null recorder: the default must cost (almost) nothing
# ---------------------------------------------------------------------------


def test_null_recorder_is_default_and_allocation_free():
    assert obs.current() is NULL and not obs.enabled()
    # one shared no-op span object per call — no per-site allocation
    assert obs.span("pipeline.sa.chunk") is NULL_SPAN
    assert obs.span("other") is NULL_SPAN
    with obs.span("x") as sp:
        assert sp is NULL_SPAN
    obs.counter("c")
    obs.gauge("g", 1.0)
    assert obs.manifest(cmd="x") is None


def test_null_recorder_no_measurable_per_chunk_allocation():
    """The per-chunk instrumentation cost on an unrecorded run: net
    retained allocation over many span cycles is ~zero (the satellite's
    'no measurable per-chunk allocation' contract). Counter/gauge events
    additionally land in the bounded flight-recorder ring
    (graphdyn.obs.flight) — shrunk here so its (bounded, by-design)
    retained tail sits inside the budget while the 2000-event churn would
    blow it if the ring ever grew with the event count (the device-side
    ring contract proper: tests/test_obs_device.py)."""
    from graphdyn.obs import flight

    flight.configure(64)
    try:
        for _ in range(flight.capacity() + 100):  # warm caches + fill ring
            with obs.span("chunk"):
                pass
            obs.counter("c")
        tracemalloc.start()
        base = tracemalloc.take_snapshot()
        for _ in range(2000):
            with obs.span("chunk"):
                pass
            obs.counter("c")
        diff = tracemalloc.take_snapshot().compare_to(base, "filename")
        tracemalloc.stop()
        leaked = sum(d.size_diff for d in diff if d.size_diff > 0)
        assert leaked < 16_384, f"null-recorder path retained {leaked} B"
    finally:
        flight.configure(flight.DEFAULT_CAPACITY)


def test_timed_always_measures_even_unrecorded():
    assert not obs.enabled()
    with obs.timed("bench.row") as sw:
        time.sleep(0.01)
    assert sw.wall_s >= 0.008                    # real number, no ledger


# ---------------------------------------------------------------------------
# recording() scope
# ---------------------------------------------------------------------------


def test_recording_installs_and_restores(tmp_path):
    p = str(tmp_path / "run.jsonl")
    with obs.recording(p) as rec:
        assert obs.enabled() and obs.current() is rec
        obs.counter("inside")
    assert not obs.enabled() and obs.current() is NULL
    events, _ = read_ledger(p)
    assert events[0]["name"] == "inside"


def test_recording_env_var_fallback(tmp_path, monkeypatch):
    p = str(tmp_path / "env.jsonl")
    monkeypatch.setenv("GRAPHDYN_OBS", p)
    with obs.recording() as rec:
        assert rec.enabled
        obs.gauge("g", 1)
    events, _ = read_ledger(p)
    assert events[0]["ev"] == "gauge"


def test_recording_unset_is_noop(monkeypatch):
    monkeypatch.delenv("GRAPHDYN_OBS", raising=False)
    with obs.recording() as rec:
        assert rec is NULL


def test_nested_recording_with_path_is_an_error(tmp_path):
    with obs.recording(str(tmp_path / "a.jsonl")) as rec:
        with pytest.raises(RuntimeError, match="one ledger per run"):
            with obs.recording(str(tmp_path / "b.jsonl")):
                pass                             # pragma: no cover
        # pathless re-entry keeps the outer recorder
        with obs.recording() as inner:
            assert inner is rec


def test_recording_counts_compile_cache_misses(tmp_path):
    """The RecompileWatch reuse: a fresh XLA compile inside the scope emits
    one ``jax.compile`` counter event, live."""
    import jax
    import jax.numpy as jnp

    p = str(tmp_path / "run.jsonl")
    with obs.recording(p):
        # a shape/function pair no other test compiles
        jax.jit(lambda x: x * 3 + 11)(jnp.arange(53)).block_until_ready()
    events, _ = read_ledger(p)
    compiles = [e for e in events
                if e["ev"] == "counter" and e["name"] == "jax.compile"]
    assert compiles, "no jax.compile counter event for a fresh compile"


def test_manifest_fields(tmp_path):
    p = str(tmp_path / "run.jsonl")
    with obs.recording(p):
        run = obs.manifest(**obs.run_manifest_fields(cmd="test"))
    assert run["backend"] and run["jax"] and run["python"]
    assert run["git_sha"]                        # a checkout: sha resolves
    events, _ = read_ledger(p)
    man = [e for e in events if e["ev"] == "manifest"]
    assert len(man) == 1 and man[0]["run"]["cmd"] == "test"


# ---------------------------------------------------------------------------
# instrumented stack: clean + fault-injected runs produce valid ledgers
# ---------------------------------------------------------------------------

ENTROPY_ARGS = [
    "entropy", "--n", "50", "--deg", "1.5", "--num-rep", "1",
    "--lmbd-max", "0.3", "--lmbd-step", "0.1", "--max-sweeps", "200",
    "--eps", "1e-5", "--seed", "1",
]


def test_cli_entropy_grouped_clean_run_ledger(tmp_path, capsys):
    """Acceptance: a clean grouped entropy-grid run through the CLI writes
    a schema-valid ledger with the manifest, the run span, per-chunk
    pipeline spans carrying sweeps-advanced, and per-λ boundary counters."""
    from graphdyn.cli import main

    ledger = str(tmp_path / "entropy.jsonl")
    out = str(tmp_path / "res.npz")
    rc = main(["--obs-ledger", ledger, *ENTROPY_ARGS, "--out", out])
    capsys.readouterr()
    assert rc == 0
    events, torn = read_ledger(ledger)
    assert torn == 0
    _assert_schema_valid(events)
    man = [e for e in events if e["ev"] == "manifest"]
    assert len(man) == 1
    assert man[0]["run"]["cmd"] == "entropy"
    assert man[0]["run"]["backend"] and man[0]["run"]["jax"]
    assert man[0]["run"]["config"]["n"] == 50    # full parsed config rides
    spans = {e["name"] for e in events if e["ev"] == "span"}
    assert "run" in spans and "pipeline.entropy.chunk" in spans
    chunk = next(e for e in events if e.get("name") ==
                 "pipeline.entropy.chunk")
    assert "sweeps_advanced" in chunk["attrs"]
    assert chunk["attrs"]["cold"] is True        # compile/execute separated
    lam = [e for e in events if e.get("name") == "pipeline.lambda.boundary"]
    assert len(lam) == 4                         # λ ∈ {0.0, 0.1, 0.2, 0.3}


@pytest.mark.faultinject
def test_cli_entropy_fault_injected_run_ledger(tmp_path, capsys):
    """Acceptance: a seeded fault-injection run (sweep.nan) still produces
    a schema-valid ledger, now carrying the fault-site hit and the degrade
    decision — the post-mortem no longer needs the log text."""
    from graphdyn.cli import main
    from graphdyn.resilience.faults import FaultPlan, FaultSpec

    ledger = str(tmp_path / "faulty.jsonl")
    out = str(tmp_path / "res.npz")
    with FaultPlan([FaultSpec("sweep.nan", "nan", at=1)]):
        rc = main(["--obs-ledger", ledger, *ENTROPY_ARGS, "--out", out])
    capsys.readouterr()
    assert rc == 0                               # NaN degrades, not dies
    events, torn = read_ledger(ledger)
    assert torn == 0
    _assert_schema_valid(events)
    names = [e.get("name") for e in events if e["ev"] == "counter"]
    assert "resilience.fault" in names           # the injection itself
    assert "pipeline.sweep.nan" in names         # the degrade decision
    fault = next(e for e in events if e.get("name") == "resilience.fault")
    assert fault["attrs"]["site"] == "sweep.nan"


@pytest.mark.faultinject
def test_cli_sigterm_mid_chunk_leaves_parseable_ledger(tmp_path, capsys):
    """Satellite: preemption (SIGTERM-equivalent signal fault mid-ladder →
    exit 75) leaves a parseable, truncation-safe ledger — every line that
    made it to disk is a complete event, the shutdown decision included."""
    from graphdyn.cli import main
    from graphdyn.resilience.faults import FaultPlan, FaultSpec

    ledger = str(tmp_path / "preempted.jsonl")
    ck = str(tmp_path / "ck")
    out = str(tmp_path / "res.npz")
    args = ["--obs-ledger", ledger, *ENTROPY_ARGS, "--checkpoint", ck,
            "--checkpoint-interval", "0", "--out", out]
    with FaultPlan([FaultSpec("lambda.boundary", "signal", at=2)]):
        rc = main(args)
    capsys.readouterr()
    assert rc == 75
    events, torn = read_ledger(ledger)           # parseable prefix, always
    assert torn <= 1
    _assert_schema_valid(events)
    assert any(e["ev"] == "manifest" for e in events)
    # the preemption decision itself is in the ledger (resilience taxonomy)
    assert any(e.get("name") == "resilience.fault" for e in events)
    # the checkpoint write latency span landed too
    assert any(e.get("name") == "io.ckpt.write" for e in events)


def test_retry_counter_and_log_fields(tmp_path, caplog):
    """Satellite: a retried failure is diagnosable post-hoc — site key,
    attempt number, and cumulative backoff ride in BOTH the log record's
    fields and the obs counter."""
    import logging

    from graphdyn.resilience.retry import RetryPolicy, retry

    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    p = str(tmp_path / "retry.jsonl")
    with obs.recording(p):
        with caplog.at_level(logging.WARNING, logger="graphdyn.resilience"):
            out = retry(flaky, what="checkpoint save (/tmp/x)",
                        policy=RetryPolicy(tries=4, base_delay_s=0.01),
                        sleep=lambda s: None)
    assert out == "ok"
    recs = [r for r in caplog.records if hasattr(r, "retry_site")]
    assert [r.retry_attempt for r in recs] == [1, 2]
    assert recs[0].retry_site == "checkpoint save (/tmp/x)"
    assert recs[1].retry_cumulative_backoff_s == pytest.approx(0.03)
    events, _ = read_ledger(p)
    counters = [e for e in events
                if e["ev"] == "counter" and e["name"] == "resilience.retry"]
    assert [c["attrs"]["attempt"] for c in counters] == [1, 2]
    assert counters[1]["attrs"]["cumulative_backoff_s"] == pytest.approx(0.03)
    assert "OSError" in counters[0]["attrs"]["error"]


def test_prefetch_overlap_gauge(tmp_path):
    from graphdyn.pipeline.prefetch import HostPrefetcher

    p = str(tmp_path / "pf.jsonl")
    with obs.recording(p):
        with HostPrefetcher(lambda k: k * 2, range(6), depth=2) as pf:
            got = [pf.get(k) for k in range(6)]
    assert got == [k * 2 for k in range(6)]
    events, _ = read_ledger(p)
    g = next(e for e in events
             if e["ev"] == "gauge"
             and e["name"] == "pipeline.prefetch.overlap_util")
    assert 0.0 <= g["value"] <= 1.0
    assert g["attrs"]["items"] == 6


def test_sa_group_chunk_spans_and_rollout_gauge(tmp_path):
    """The grouped SA driver emits per-chunk spans (cold marks the
    compile-paying first chunk) and the ops.rollout.rate gauge — the same
    spin-updates/s unit bench.py reports."""
    from graphdyn.config import DynamicsConfig, SAConfig
    from graphdyn.models.sa import sa_ensemble

    cfg = SAConfig(dynamics=DynamicsConfig(p=1, c=1))
    p = str(tmp_path / "sa.jsonl")
    with obs.recording(p):
        sa_ensemble(30, 3, cfg, n_stat=2, seed=0, max_steps=5000)
    events, torn = read_ledger(p)
    assert torn == 0
    _assert_schema_valid(events)
    chunks = [e for e in events if e.get("name") == "pipeline.sa.chunk"]
    assert chunks and chunks[0]["attrs"]["cold"] is True
    assert all("steps_advanced" in c["attrs"] for c in chunks)
    rates = [e for e in events if e.get("name") == "ops.rollout.rate"]
    assert rates and rates[0]["value"] > 0
    assert rates[0]["attrs"]["solver"] == "sa_group"


# ---------------------------------------------------------------------------
# one timing idiom: the deprecated shims delegate to obs
# ---------------------------------------------------------------------------


def test_step_timer_shim_deprecated_but_working(tmp_path):
    from graphdyn.utils.profiling import StepTimer

    t = StepTimer()
    p = str(tmp_path / "shim.jsonl")
    with obs.recording(p):
        with pytest.warns(DeprecationWarning, match="obs.timed"):
            with t.measure(100):
                pass
        with t.measure(50):                      # warns once per instance
            pass
    assert t.updates == 150 and t.updates_per_sec > 0
    events, _ = read_ledger(p)
    shim_spans = [e for e in events
                  if e.get("name") == "profiling.step_timer"]
    assert len(shim_spans) == 2                  # the shim reaches the ledger


def test_wall_clock_shim_deprecated_but_working():
    from graphdyn.utils.profiling import wall_clock

    with pytest.warns(DeprecationWarning, match="obs.timed"):
        with wall_clock() as w:
            pass
    assert w["seconds"] >= 0.0


# ---------------------------------------------------------------------------
# roofline obscheck (the absolute CPU-proxy anchor)
# ---------------------------------------------------------------------------


def test_byte_models():
    from graphdyn.obs.roofline import (
        bdcm_bytes_per_edge_sweep, packed_bytes_per_update,
    )

    assert packed_bytes_per_update(3) == 0.5     # ARCHITECTURE.md: (d+1)/8
    assert packed_bytes_per_update(7) == 1.0
    # DP-lattice dominated: grows with both d and T
    assert bdcm_bytes_per_edge_sweep(4, 2) > bdcm_bytes_per_edge_sweep(3, 2)
    assert bdcm_bytes_per_edge_sweep(3, 3) > bdcm_bytes_per_edge_sweep(3, 2)


def test_roofline_obscheck_passes_on_cpu(tmp_path):
    """Acceptance: every headline program's measured CPU-proxy rate sits
    inside its committed byte-model band on this container. Rows also land
    as gauges when recording."""
    from graphdyn.obs.roofline import run_obscheck

    p = str(tmp_path / "roofline.jsonl")
    with obs.recording(p):
        rows = run_obscheck()
    assert {r.program for r in rows} == {
        "packed_rollout", "bdcm_sweep", "entropy_cell_chunk"}
    for r in rows:
        assert r.measured > 0 and r.model > 0
        assert r.ok, (f"{r.program}: measured/model frac {r.frac:.4f} "
                      f"outside [{r.lo}, {r.hi}]")
    events, _ = read_ledger(p)
    gauges = {e["name"] for e in events if e["ev"] == "gauge"}
    assert {"obs.roofline.packed_rollout", "obs.roofline.bdcm_sweep",
            "obs.roofline.entropy_cell_chunk"} <= gauges


# ---------------------------------------------------------------------------
# cross-round bench rate trend gate
# ---------------------------------------------------------------------------

PREV_ROW = {
    "backend": "cpu", "metric": "spin_updates_per_sec_n100000",
    "value": 2.0e9, "packed_rate_natural_order": 2.0e9,
    "ensemble_rate": 1.0e7, "int8_rate": 8.0e7,
}


def _new_row(**over):
    return {**PREV_ROW, **over}


def test_trend_gate_fails_slowed_row_with_pointed_message():
    """Acceptance: an artificially slowed headline row fails the gate with
    a message naming the row, the ratio, the band, and the bless path."""
    from graphdyn.obs.trend import diff_bench_rates

    slowed = _new_row(value=4.0e8, packed_rate_natural_order=4.0e8)
    findings = diff_bench_rates(PREV_ROW, slowed)
    assert {f.row for f in findings} == {"value",
                                         "packed_rate_natural_order"}
    f = next(x for x in findings if x.row == "value")
    assert f.code == "OBS201"
    assert "regressed 5.00x" in f.message
    assert "--bless" in f.message                # the update path is named


def test_trend_gate_flags_implausible_jump():
    from graphdyn.obs.trend import diff_bench_rates

    jumped = _new_row(int8_rate=8.0e7 * 40)
    (f,) = diff_bench_rates(PREV_ROW, jumped)
    assert f.row == "int8_rate" and f.code == "OBS202"
    assert "timing fence" in f.message


def test_trend_gate_stable_and_incomparable_rows():
    from graphdyn.obs.trend import comparable, diff_bench_rates

    assert diff_bench_rates(PREV_ROW, _new_row(value=2.1e9)) == []
    # different backend or metric: not comparable, no findings
    assert not comparable(PREV_ROW, _new_row(backend="tpu"))
    assert not comparable(PREV_ROW, _new_row(metric="other_n1000000"))
    assert diff_bench_rates(PREV_ROW, _new_row(backend="tpu",
                                               value=1.0)) == []
    # a null rate (explicit backend skip) is not a regression
    assert diff_bench_rates(PREV_ROW,
                            _new_row(ensemble_rate=None)) == []
    # an error round (value 0) is not a baseline
    assert diff_bench_rates(_new_row(value=0.0), PREV_ROW) == []


def test_check_trend_against_committed_rounds(tmp_path):
    """The full gate against round artifacts on disk — including the
    ``parsed`` wrapper the capture driver writes."""
    from graphdyn.obs.trend import check_trend

    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps({"cmd": "bench", "rc": 0, "parsed": PREV_ROW}))
    empty = {"classes": {}}
    _, status = check_trend(_new_row(value=2.2e9), root=str(tmp_path),
                            ledger=empty)
    assert status == "stable"
    findings, status = check_trend(_new_row(value=4.0e8), root=str(tmp_path),
                                   ledger=empty)
    assert status == "drift" and findings
    _, status = check_trend(_new_row(backend="tpu"), root=str(tmp_path),
                            ledger=empty)
    assert status == "no_baseline"


def test_trend_blessing_passes_deliberate_change(tmp_path):
    """Acceptance: a deliberate rate change committed to OBS_TREND.json
    (``--bless``) passes the gate as ``blessed``; the committed classes are
    (backend, metric)-scoped."""
    from graphdyn.obs.trend import (
        check_trend, load_trend_ledger, write_trend_ledger,
    )

    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps({"parsed": PREV_ROW}))
    new = _new_row(value=4.0e8, packed_rate_natural_order=4.0e8)
    lpath = str(tmp_path / "OBS_TREND.json")
    write_trend_ledger(new, lpath)
    ledger = load_trend_ledger(lpath)
    assert set(ledger["classes"]) == {"cpu|spin_updates_per_sec_n100000"}
    findings, status = check_trend(new, root=str(tmp_path), ledger=ledger)
    assert status == "blessed"
    assert findings                              # the drift is still named
    # a DIFFERENT unexplained drift — outside the blessed band too — is
    # not covered by the blessing
    _, status = check_trend(_new_row(value=1.0e7,
                                     packed_rate_natural_order=1.0e7),
                            root=str(tmp_path), ledger=ledger)
    assert status == "drift"


def test_bench_trend_gate_drift_end_to_end(monkeypatch):
    """Acceptance, through bench.py's own gate: a monkeypatched slowed
    headline row comes back status=drift with the pointed finding in the
    row — exactly what benchcheck fails on."""
    import bench
    from graphdyn.obs import trend as trend_mod

    monkeypatch.setattr(
        trend_mod, "latest_comparable_round",
        lambda new_row, root=None, pattern="BENCH_r*.json":
            ("BENCH_r99.json", dict(PREV_ROW)))
    monkeypatch.setattr(trend_mod, "load_trend_ledger", lambda path=None: None)
    out = bench.trend_gate(_new_row(value=4.0e8))
    assert out["obs_trend_status"] == "drift"
    (f,) = out["obs_trend_findings"]
    assert f["row"] == "value" and f["code"] == "OBS201"
    assert "regressed 5.00x" in f["message"] and "--bless" in f["message"]


def test_bench_trend_gate_rides_in_row(monkeypatch):
    """bench.py's helper: the verdict (or an explicit skip) rides in the
    row so benchcheck can assert the gate ran."""
    import bench

    monkeypatch.setenv("GRAPHDYN_SKIP_TRENDGATE", "1")
    out = bench.trend_gate({"backend": "cpu", "metric": "m", "value": 1.0})
    assert out["obs_trend_status"] == "skipped"
    assert "GRAPHDYN_SKIP_TRENDGATE" in out["obs_trend_skipped_reason"]
    monkeypatch.delenv("GRAPHDYN_SKIP_TRENDGATE")
    out = bench.trend_gate({"backend": "nowhere", "metric": "never",
                            "value": 1.0})
    assert out["obs_trend_status"] == "no_baseline"


# ---------------------------------------------------------------------------
# CLIs: report / check / trend (one JSON document on stdout — PR-6 contract)
# ---------------------------------------------------------------------------


def _make_ledger(path):
    rec = Recorder(str(path))
    rec.manifest(cmd="entropy", backend="cpu")
    with rec.span("run", cmd="entropy"):
        with rec.span("pipeline.entropy.chunk", chunk=0):
            pass
        with rec.span("pipeline.entropy.chunk", chunk=1):
            pass
        rec.counter("jax.compile", fn="chunk")
        rec.gauge("ops.rollout.rate", 1.5e9, solver="sa_group")
    rec.close()


def test_report_summarize_span_tree(tmp_path):
    from graphdyn.obs.report import load_summary

    p = tmp_path / "run.jsonl"
    _make_ledger(p)
    doc = load_summary(str(p))
    assert doc["manifest"]["cmd"] == "entropy"
    # name-path aggregation: the chunk span reports under its parent chain
    assert doc["spans"]["run > pipeline.entropy.chunk"]["count"] == 2
    assert doc["spans"]["run"]["count"] == 1
    assert doc["counters"]["jax.compile"]["total"] == 1
    g = doc["gauges"]["ops.rollout.rate"]
    assert g["last"] == g["max"] == pytest.approx(1.5e9)
    assert doc["torn_lines"] == 0


def test_report_cli_one_json_document(tmp_path):
    p = tmp_path / "run.jsonl"
    _make_ledger(p)
    # torn final line: diagnostics must go to stderr, stdout stays ONE doc
    with open(p, "a") as f:
        f.write('{"ev":"cou')
    proc = subprocess.run(
        [sys.executable, "-m", "graphdyn.obs", "report", str(p),
         "--format=json"],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    doc = json.loads(proc.stdout)                # exactly one document
    assert doc["torn_lines"] == 1
    assert "torn line" in proc.stderr
    text = subprocess.run(
        [sys.executable, "-m", "graphdyn.obs", "report", str(p)],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert text.returncode == 0
    assert "pipeline.entropy.chunk" in text.stdout


def test_trend_cli_diff_and_bless(tmp_path):
    rowfile = tmp_path / "row.json"
    rowfile.write_text(json.dumps(_new_row(value=1.9e9)))
    lpath = tmp_path / "OBS_TREND.json"
    proc = subprocess.run(
        [sys.executable, "-m", "graphdyn.obs", "trend", str(rowfile),
         "--bless", "--ledger", str(lpath), "--format=json"],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    assert json.loads(proc.stdout)["blessed"] is True
    assert lpath.exists()
    # the gate CLI: exit 0 on anything but unblessed drift
    proc = subprocess.run(
        [sys.executable, "-m", "graphdyn.obs", "trend", str(rowfile),
         "--ledger", str(lpath), "--format=json"],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    doc = json.loads(proc.stdout)
    assert doc["status"] in ("stable", "no_baseline", "blessed")
