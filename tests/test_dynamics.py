"""Dynamics-kernel tests: reference-formula equivalence, rule registry,
backend parity (SURVEY.md §4 items 1-2)."""

import numpy as np
import pytest

from graphdyn.graphs import erdos_renyi_graph, random_regular_graph
from graphdyn.ops.dynamics import end_state, run_dynamics, step_spins


def reference_majority_stay(nbr_regular, s):
    """The reference's exact formula, valid for regular graphs
    (`SA_RRG.py:18-20`): (1-|sign Σ|)·s + sign Σ."""
    sums = np.sum(s[nbr_regular], axis=1)
    return ((1 - np.abs(np.sign(sums))) * s + np.sign(sums)).astype(s.dtype)


def brute_force_step(g, s, rule, tie):
    """Direct per-node semantics: rule applied to the neighbor sum with an
    explicit tie branch."""
    out = np.empty_like(s)
    for i in range(g.n):
        nbrs = g.nbr[i][g.nbr[i] != g.n]
        tot = int(s[nbrs].sum())
        if tot != 0:
            val = np.sign(tot)
            if rule == "minority":
                val = -val
        else:
            val = s[i] if tie == "stay" else -s[i]
        out[i] = val
    return out


@pytest.mark.parametrize("rule", ["majority", "minority"])
@pytest.mark.parametrize("tie", ["stay", "change"])
def test_rule_registry_matches_brute_force(rule, tie, rng):
    g = erdos_renyi_graph(120, 3.0 / 119, seed=21)
    s = rng.choice(np.array([-1, 1], dtype=np.int8), size=g.n)
    got = np.asarray(step_spins(g.nbr, s, rule, tie))
    want = brute_force_step(g, s, rule, tie)
    np.testing.assert_array_equal(got, want)


def test_matches_reference_formula_on_rrg(rng):
    g = random_regular_graph(300, 4, seed=5)
    s = rng.choice(np.array([-1, 1], dtype=np.int64), size=g.n)
    # reference formula needs the unpadded table (regular: no ghosts)
    assert np.all(g.nbr < g.n)
    want = reference_majority_stay(g.nbr, s)
    got = np.asarray(step_spins(g.nbr, s.astype(np.int8)))
    np.testing.assert_array_equal(got, want.astype(np.int8))


def test_degree_grouped_form_equivalence(rng):
    """sign(2Σ + s) (notebook, `ipynb:113-117`) == gather form, incl.
    isolated nodes."""
    g = erdos_renyi_graph(200, 1.0 / 199, seed=8)
    s = rng.choice(np.array([-1, 1], dtype=np.int8), size=g.n)
    got = np.asarray(step_spins(g.nbr, s))
    s_ext = np.concatenate([s.astype(np.int64), [0]])
    sums = s_ext[g.nbr].sum(axis=1)
    want = np.sign(2 * sums + s).astype(np.int8)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("backend", ["cpu", "torch", "jax"])
def test_backend_parity(backend, rng):
    g = random_regular_graph(500, 3, seed=13)
    s = rng.choice(np.array([-1, 1], dtype=np.int8), size=g.n)
    ref = run_dynamics(g, s, 7, backend="cpu")
    got = np.asarray(run_dynamics(g, s, 7, backend=backend))
    np.testing.assert_array_equal(got, ref)


def test_end_state_reaches_consensus_from_near_consensus(rng):
    g = random_regular_graph(400, 5, seed=17)
    s = np.ones(g.n, dtype=np.int8)
    flip = rng.choice(g.n, size=5, replace=False)
    s[flip] = -1
    out = np.asarray(end_state(g, s, p=3, c=1))
    assert np.all(out == 1)


def test_all_plus_is_fixed_point():
    g = random_regular_graph(100, 3, seed=23)
    s = np.ones(g.n, dtype=np.int8)
    np.testing.assert_array_equal(np.asarray(run_dynamics(g, s, 4)), s)


def test_vmap_over_replicas(rng):
    import jax
    import jax.numpy as jnp
    from functools import partial

    g = random_regular_graph(150, 4, seed=3)
    S = rng.choice(np.array([-1, 1], dtype=np.int8), size=(8, g.n))
    step = jax.vmap(partial(step_spins, jnp.asarray(g.nbr)))
    got = np.asarray(step(jnp.asarray(S)))
    for r in range(8):
        np.testing.assert_array_equal(got[r], np.asarray(step_spins(g.nbr, S[r])))


def test_solvers_run_under_nondefault_rules():
    """The (rule, tie) axis wires through the full solvers, not just the
    factor tensors: SA under minority/change and the entropy sweep under
    minority dynamics with attr_value=-1 run end-to-end (`HPR:22,25`,
    `ipynb:70,74` — the reference's commented-out rule variants)."""
    import numpy as np

    from graphdyn.config import DynamicsConfig, EntropyConfig, SAConfig
    from graphdyn.graphs import erdos_renyi_graph, random_regular_graph
    from graphdyn.models.entropy import entropy_sweep
    from graphdyn.models.sa import simulated_annealing

    g = random_regular_graph(40, 3, seed=1)
    cfg = SAConfig(dynamics=DynamicsConfig(p=1, c=1, rule="minority", tie="change"))
    res = simulated_annealing(g, cfg, n_replicas=2, seed=0, max_steps=300)
    assert set(np.unique(res.m_final)).issubset({1.0, 2.0})

    # majority + always-change ties: all-+1 stays an attractor => finite curve
    er = erdos_renyi_graph(80, 1.2 / 79, seed=2)
    ecfg = EntropyConfig(
        dynamics=DynamicsConfig(p=1, c=1, tie="change"),
        lmbd_max=0.2, lmbd_step=0.1,
    )
    out = entropy_sweep(er, ecfg, seed=0)
    assert out.lambdas.size >= 1
    assert np.isfinite(out.m_init[0]) and np.isfinite(out.ent[0])

    # minority with a c=1 homogeneous endpoint has an EMPTY attractor set
    # (all-(-1) is not a minority fixed point): the framework reports
    # phi = -inf instead of crashing (class_update's zero-Z guard)
    mcfg = EntropyConfig(
        dynamics=DynamicsConfig(p=1, c=1, rule="minority", attr_value=-1),
        lmbd_max=0.3, lmbd_step=0.1, max_sweeps=50,
    )
    out2 = entropy_sweep(er, mcfg, seed=0)
    assert out2.ent[0] == -np.inf
    assert np.isfinite(out2.m_init[0])          # not NaN: zero-Z edges -> 0
    assert out2.ent1[0] == -np.inf
    # ent1 = -inf < ent_floor => the ladder early-exits after one point
    assert out2.lambdas.size == 1


def test_empty_attractor_guard_with_eps_clamp():
    """The -inf guard must hold with a nonzero eps_clamp too: the clamp
    floors vanished Z's AT eps_clamp, which previously slipped past a
    `<= 0` comparison and produced finite garbage entropy."""
    import numpy as np

    from graphdyn.config import DynamicsConfig, EntropyConfig
    from graphdyn.graphs import erdos_renyi_graph
    from graphdyn.models.entropy import entropy_sweep

    er = erdos_renyi_graph(80, 1.2 / 79, seed=2)
    cfg = EntropyConfig(
        dynamics=DynamicsConfig(p=1, c=1, rule="minority", attr_value=-1),
        lmbd_max=0.3, lmbd_step=0.1, max_sweeps=50, eps_clamp=1e-12,
    )
    out = entropy_sweep(er, cfg, seed=0)
    assert out.ent[0] == -np.inf
    assert np.isfinite(out.m_init[0])
    assert out.lambdas.size == 1                # early exit still fires


def test_int8_gather_schedules_bit_identical(rng):
    """fused vs per_slot int8 rollout schedules are alternative HBM orders of
    the same integer program — results must match exactly."""
    from graphdyn.graphs import erdos_renyi_graph
    from graphdyn.ops.dynamics import batched_rollout
    import jax.numpy as jnp

    g = erdos_renyi_graph(200, 5.0 / 199, seed=3)
    s = rng.choice(np.array([-1, 1], dtype=np.int8), size=(7, g.n))
    for rule in ("majority", "minority"):
        a = batched_rollout(jnp.asarray(g.nbr), jnp.asarray(s), 6, rule,
                            "stay", gather="fused")
        b = batched_rollout(jnp.asarray(g.nbr), jnp.asarray(s), 6, rule,
                            "stay", gather="per_slot")
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_majority_stay_monotone(rng):
    """Majority dynamics with stay ties is a monotone map: s <= s' pointwise
    implies step(s) <= step(s') — the lattice property behind the
    strategic-initialization search (raising any spin can only help reach
    the +1 consensus)."""
    from graphdyn.graphs import erdos_renyi_graph
    from graphdyn.ops.dynamics import batched_rollout

    g = erdos_renyi_graph(150, 4.0 / 149, seed=6)
    import jax.numpy as jnp

    for _ in range(5):
        s_lo = rng.choice(np.array([-1, 1], dtype=np.int8), size=g.n)
        raise_idx = rng.choice(g.n, size=g.n // 4, replace=False)
        s_hi = s_lo.copy()
        s_hi[raise_idx] = 1
        out = np.asarray(batched_rollout(
            jnp.asarray(g.nbr), jnp.asarray(np.stack([s_lo, s_hi])), 8
        ))
        assert np.all(out[0] <= out[1])


def test_consensus_states_absorbing(rng):
    """The homogeneous states are fixed points of majority/stay (all-+1 is
    the target attractor, `SA_RRG.py:23-26`); under minority/change they are
    NOT (checked so the test cannot pass vacuously)."""
    from graphdyn.graphs import random_regular_graph
    from graphdyn.ops.dynamics import run_dynamics

    g = random_regular_graph(100, 3, seed=4)
    for target in (1, -1):
        s = np.full(g.n, target, np.int8)
        out = np.asarray(run_dynamics(g, s, 5, "majority", "stay", backend="cpu"))
        np.testing.assert_array_equal(out, s)
        flipped = np.asarray(
            run_dynamics(g, s, 1, "minority", "change", backend="cpu")
        )
        assert np.all(flipped == -s)
