"""Forward opinion-consensus driver (graphdyn.models.consensus): ensemble
aggregation, artifact schema, and physics sanity (more bias ⇒ no less
consensus). The packed-domain first-passage bookkeeping itself is
oracle-tested in tests/test_packed.py."""

import numpy as np

from graphdyn.models.consensus import (
    consensus_curve_ensemble,
    consensus_doc,
    consensus_ensemble_doc,
    er_consensus_ensemble,
)


def test_ensemble_aggregate_matches_per_seed():
    m0s = (0.0, 0.1, 0.3)
    per_seed, agg = consensus_curve_ensemble(
        1500, 64, m0s, max_steps=200, graph_seeds=(0, 1, 2),
    )
    assert [ps["graph_seed"] for ps in per_seed] == [0, 1, 2]
    assert len(agg) == len(m0s)
    for j, row in enumerate(agg):
        fr = np.array([ps["rows"][j]["consensus_fraction"]
                       for ps in per_seed])
        assert row["m0"] == m0s[j]
        assert row["consensus_fraction_mean"] == float(fr.mean())
        assert row["consensus_fraction"] == row["consensus_fraction_mean"]
        np.testing.assert_allclose(
            row["consensus_fraction_std"], float(fr.std(ddof=1)), atol=1e-12
        )
        assert (row["consensus_fraction_min"]
                <= row["consensus_fraction_mean"]
                <= row["consensus_fraction_max"])
        assert row["instances"] == 3
    # physics: strong bias consenses essentially always, on every instance
    assert agg[-1]["consensus_fraction_min"] >= 0.95


def test_ensemble_doc_schema():
    per_seed, agg = consensus_curve_ensemble(
        800, 32, (0.2,), max_steps=100, graph_seeds=(4, 5),
    )
    doc = consensus_ensemble_doc(800, per_seed, agg, elapsed_s=1.0)
    assert doc["graph"]["graph_seeds"] == [4, 5]
    assert doc["rows"] is agg and doc["per_seed"] is per_seed
    assert "majority" in doc["what"]
    assert doc["backend"] == "cpu"
    assert doc["elapsed_s"] == 1.0
    # the single-run doc shares the same reader-facing keys
    g, n_iso, _, _ = er_consensus_ensemble(800, seed=4)
    single = consensus_doc(g, n_iso, per_seed[0]["rows"])
    for key in ("what", "graph", "dynamics", "near_consensus_def",
                "backend", "rows"):
        assert key in single and key in doc


def test_rrg_ensemble_dispatch_and_doc_provenance():
    """graph='rrg' routes to the d-regular ensemble and the shared doc
    writers record the right provenance for both kinds; unknown kinds are
    refused."""
    import pytest

    from graphdyn.models.consensus import rrg_consensus_ensemble

    g, n_iso, nbr, deg = rrg_consensus_ensemble(300, d=3, seed=1)
    assert (g.n, n_iso) == (300, 0)
    assert nbr.shape == (300, 3)

    per_seed, agg = consensus_curve_ensemble(
        300, 32, (0.6,), max_steps=100, graph="rrg", d=3, graph_seeds=(0,),
    )
    doc = consensus_ensemble_doc(300, per_seed, agg,
                                 kind="random_regular", d=3)
    assert doc["what"].startswith("RRG-d3-majority")
    assert doc["graph"]["kind"] == "random_regular"
    assert doc["graph"]["d"] == 3 and "c" not in doc["graph"]
    er_doc = consensus_ensemble_doc(300, per_seed, agg)
    assert er_doc["what"].startswith("ER-majority")
    assert er_doc["graph"]["c"] == 6.0 and "d" not in er_doc["graph"]

    with pytest.raises(ValueError, match="'er' or 'rrg'"):
        consensus_curve_ensemble(300, 32, (0.1,), max_steps=100,
                                 graph="cycle")


def test_ensemble_instances_draw_independent_replicas():
    """The replica-draw seed folds (graph_seed, k): two ensemble instances
    at the same m(0) point draw DIFFERENT initial replicas (pre-fix, every
    instance reused seed 1000+k and the instance spread under-measured the
    replica noise). Same instance + same point stays deterministic."""
    from graphdyn.models.consensus import consensus_curve, draw_seed

    assert draw_seed(0, 0) != draw_seed(1, 0)
    assert draw_seed(0, 0) != draw_seed(0, 1)
    assert draw_seed(3, 2) == draw_seed(3, 2)

    g, _, nbr, deg = er_consensus_ensemble(300, c=3.0, seed=0)
    kw = dict(nbr_dev=nbr, deg_dev=deg, max_steps=10, chunk=5)
    # SAME graph, different instance labels: only the draws differ — the
    # final magnetizations must not coincide
    a = consensus_curve(g, 128, [0.0], graph_seed=0, **kw)
    b = consensus_curve(g, 128, [0.0], graph_seed=1, **kw)
    assert a[0]["mean_abs_m_final"] != b[0]["mean_abs_m_final"]
    a2 = consensus_curve(g, 128, [0.0], graph_seed=0, **kw)
    assert a[0] == a2[0]
