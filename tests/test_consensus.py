"""Forward opinion-consensus driver (graphdyn.models.consensus): ensemble
aggregation, artifact schema, and physics sanity (more bias ⇒ no less
consensus). The packed-domain first-passage bookkeeping itself is
oracle-tested in tests/test_packed.py."""

import numpy as np

from graphdyn.models.consensus import (
    consensus_curve_ensemble,
    consensus_doc,
    consensus_ensemble_doc,
    er_consensus_ensemble,
)


def test_ensemble_aggregate_matches_per_seed():
    m0s = (0.0, 0.1, 0.3)
    per_seed, agg = consensus_curve_ensemble(
        1500, 64, m0s, max_steps=200, graph_seeds=(0, 1, 2),
    )
    assert [ps["graph_seed"] for ps in per_seed] == [0, 1, 2]
    assert len(agg) == len(m0s)
    for j, row in enumerate(agg):
        fr = np.array([ps["rows"][j]["consensus_fraction"]
                       for ps in per_seed])
        assert row["m0"] == m0s[j]
        assert row["consensus_fraction_mean"] == float(fr.mean())
        assert row["consensus_fraction"] == row["consensus_fraction_mean"]
        np.testing.assert_allclose(
            row["consensus_fraction_std"], float(fr.std(ddof=1)), atol=1e-12
        )
        assert (row["consensus_fraction_min"]
                <= row["consensus_fraction_mean"]
                <= row["consensus_fraction_max"])
        assert row["instances"] == 3
    # physics: strong bias consenses essentially always, on every instance
    assert agg[-1]["consensus_fraction_min"] >= 0.95


def test_ensemble_doc_schema():
    per_seed, agg = consensus_curve_ensemble(
        800, 32, (0.2,), max_steps=100, graph_seeds=(4, 5),
    )
    doc = consensus_ensemble_doc(800, per_seed, agg, elapsed_s=1.0)
    assert doc["graph"]["graph_seeds"] == [4, 5]
    assert doc["rows"] is agg and doc["per_seed"] is per_seed
    assert "majority" in doc["what"]
    assert doc["backend"] == "cpu"
    assert doc["elapsed_s"] == 1.0
    # the single-run doc shares the same reader-facing keys
    g, n_iso, _, _ = er_consensus_ensemble(800, seed=4)
    single = consensus_doc(g, n_iso, per_seed[0]["rows"])
    for key in ("what", "graph", "dynamics", "near_consensus_def",
                "backend", "rows"):
        assert key in single and key in doc
