"""Entropy λ-sweep tests: golden tolerance vs the notebook's stored triples
(BASELINE.md), early-exit semantics, grid driver."""

import numpy as np
import pytest

from graphdyn.config import EntropyConfig
from graphdyn.graphs import erdos_renyi_graph, graph_from_edges
from graphdyn.models.entropy import entropy_grid, entropy_sweep


@pytest.mark.slow
def test_golden_triples_tolerance():
    """Reference ground truth (`ER_BDCM_entropy.ipynb:18-46`, BASELINE.md):
    all ten stored (λ, m_init, ent1) triples at deg=1.0, n=1000, p=c=1,
    damp=0.1, eps=1e-6. The stored run is a single unseeded instance, so we
    check to within finite-size fluctuation, plus the exact monotone shape
    of the curve (m_init and ent1 strictly decrease along λ)."""
    golden = {
        0.0: (0.78598, 0.17207), 0.1: (0.76994, 0.17127), 0.2: (0.75455, 0.16897),
        0.3: (0.73998, 0.16534), 0.4: (0.72636, 0.16058), 0.5: (0.71376, 0.15492),
        0.6: (0.70224, 0.14859), 0.7: (0.69182, 0.14183), 0.8: (0.68249, 0.13484),
        0.9: (0.67421, 0.12780),
    }
    g = erdos_renyi_graph(1000, 1.0 / 999, seed=2)
    lambdas = np.round(np.arange(0.0, 0.95, 0.1), 2)
    res = entropy_sweep(g, EntropyConfig(), seed=2, lambdas=lambdas)
    assert res.lambdas.size == lambdas.size, "all ladder points must converge"
    for k, lam in enumerate(res.lambdas):
        m_g, e_g = golden[float(np.round(lam, 2))]
        assert abs(res.m_init[k] - m_g) < 0.03
        assert abs(res.ent1[k] - e_g) < 0.015
    assert np.all(np.diff(res.m_init) < 0)
    assert np.all(np.diff(res.ent1) < 0)
    # sweep counts in the reference's warm-started regime (~130-250)
    assert np.all(res.sweeps < 600)


def test_entropy_floor_early_exit():
    edges = np.array([[0, 1], [1, 2], [2, 3], [3, 0], [0, 4]])
    g = graph_from_edges(5, edges)
    # floor above any achievable ent1 => break after the first ladder point
    cfg = EntropyConfig(lmbd_max=3.0, lmbd_step=1.0, ent_floor=10.0)
    res = entropy_sweep(g, cfg, seed=0)
    assert res.lambdas.size == 1
    # floor below everything => full ladder is visited
    cfg2 = EntropyConfig(lmbd_max=3.0, lmbd_step=1.0, ent_floor=-1e9)
    res2 = entropy_sweep(g, cfg2, seed=0)
    assert res2.lambdas.size == 4 or res2.nonconverged > 0


def test_isolates_analytic_terms():
    """Isolated nodes contribute −λ·n_iso/n to φ and +n_iso/n to m_init."""
    edges = np.array([[0, 1], [1, 2]])
    g_iso = graph_from_edges(5, edges)      # nodes 3,4 isolated
    g_core = graph_from_edges(3, edges)
    lambdas = np.array([0.0, 0.5])
    r_iso = entropy_sweep(g_iso, EntropyConfig(), seed=1, lambdas=lambdas)
    r_core = entropy_sweep(g_core, EntropyConfig(), seed=1, lambdas=lambdas)
    for k, lam in enumerate(lambdas):
        # φ_iso·5 = φ_core·3 − λ·2 ; m_iso·5 = m_core·3 + 2
        np.testing.assert_allclose(
            r_iso.ent[k] * 5, r_core.ent[k] * 3 - lam * 2, atol=1e-4
        )
        np.testing.assert_allclose(
            r_iso.m_init[k] * 5, r_core.m_init[k] * 3 + 2, atol=1e-4
        )


def test_grid_driver_shapes():
    cfg = EntropyConfig(lmbd_max=0.2, lmbd_step=0.1, num_rep=2)
    res = entropy_grid(60, np.array([1.0, 1.5]), cfg, seed=3)
    assert res.ent.shape == (2, 2, 3)
    assert res.m_init.shape == (2, 2, 3)
    assert res.nodes_isolated.shape == (2, 2)
    # deg=1.5 instances have fewer isolates than deg=1.0 on average
    assert res.mean_degrees_total[1].mean() > res.mean_degrees_total[0].mean()


def test_warm_start_resume_state():
    g = erdos_renyi_graph(80, 1.5 / 79, seed=5)
    lambdas = np.array([0.0, 0.1, 0.2])
    full = entropy_sweep(g, EntropyConfig(), seed=5, lambdas=lambdas)
    # resume: run first two, then continue from chi at the third
    part = entropy_sweep(g, EntropyConfig(), seed=5, lambdas=lambdas[:2])
    cont = entropy_sweep(
        g, EntropyConfig(), seed=5, chi0=part.chi, lambdas=lambdas[2:]
    )
    np.testing.assert_allclose(cont.ent1[-1], full.ent1[-1], atol=5e-4)


def test_entropy_checkpointer_and_counts(tmp_path):
    """Time-triggered intermediate saves (`ipynb:439-445`) and the
    nonconvergence `counts` grid (`ipynb:429-431`)."""
    from graphdyn.utils.io import PeriodicCheckpointer

    g = erdos_renyi_graph(60, 1.5 / 59, seed=9)
    pc = PeriodicCheckpointer(str(tmp_path / "ck"), interval_s=0.0)
    res = entropy_sweep(
        g, EntropyConfig(lmbd_max=0.2, lmbd_step=0.1), seed=9, checkpointer=pc
    )
    arrays, meta = pc.ckpt.load()
    assert arrays["chi"].shape == res.chi.shape
    assert arrays["ent1"].size >= 1
    assert "lmbd" in meta

    grid = entropy_grid(
        50, np.array([1.2]), EntropyConfig(lmbd_max=0.1, lmbd_step=0.1, num_rep=1),
        seed=2, save_path=str(tmp_path / "grid.npz"),
        checkpoint_path=str(tmp_path / "grid_ck"), checkpoint_interval_s=0.0,
    )
    assert grid.counts.shape == (1, 1)
    import os

    from graphdyn.utils.io import load_results_npz
    saved = load_results_npz(str(tmp_path / "grid.npz"))
    assert "counts" in saved and "ent1" in saved
    # the grid checkpoint is cleanup-removed once the run completes
    assert not os.path.exists(str(tmp_path / "grid_ck") + ".npz")


def test_entropy_grid_resume_bit_exact(tmp_path, abort_after_save):
    """A grid interrupted mid-cell (the reference notebook's own fate,
    `ipynb:47-49`) resumes at the first unvisited λ with the saved
    warm-start chi and finishes with grids identical to the uninterrupted
    run; a mismatched-run checkpoint is refused."""
    import os

    from conftest import CheckpointAbort
    from graphdyn.models.entropy import entropy_grid
    from graphdyn.utils.io import Checkpoint

    cfg = EntropyConfig(lmbd_max=0.3, lmbd_step=0.1, num_rep=2)
    kw = dict(seed=3, checkpoint_interval_s=0.0)
    base = entropy_grid(50, np.array([1.2, 1.6]), cfg, seed=3)

    p = str(tmp_path / "grid_ck")
    # abort on the 3rd λ-level save: lands mid-cell with restored prefix
    with abort_after_save(n=3):
        with pytest.raises(CheckpointAbort):
            entropy_grid(50, np.array([1.2, 1.6]), cfg, checkpoint_path=p, **kw)
    assert os.path.exists(p + ".npz")
    _, meta = Checkpoint(p).load()
    assert {"deg_index", "rep", "lmbd", "lmbd_offset", "grid_id"} <= set(meta)

    resumed = entropy_grid(50, np.array([1.2, 1.6]), cfg, checkpoint_path=p, **kw)
    np.testing.assert_array_equal(base.ent, resumed.ent)
    np.testing.assert_array_equal(base.m_init, resumed.m_init)
    np.testing.assert_array_equal(base.ent1, resumed.ent1)
    np.testing.assert_array_equal(base.counts, resumed.counts)
    np.testing.assert_array_equal(base.nodes_isolated, resumed.nodes_isolated)
    assert not os.path.exists(p + ".npz")

    # a second interruption inside the SAME continued cell also resumes
    with abort_after_save(n=1):
        with pytest.raises(CheckpointAbort):
            entropy_grid(50, np.array([1.2, 1.6]), cfg, checkpoint_path=p, **kw)
    with abort_after_save(n=1):
        with pytest.raises(CheckpointAbort):
            entropy_grid(50, np.array([1.2, 1.6]), cfg, checkpoint_path=p, **kw)
    twice = entropy_grid(50, np.array([1.2, 1.6]), cfg, checkpoint_path=p, **kw)
    np.testing.assert_array_equal(base.ent1, twice.ent1)

    # different grid/seed: refused
    with abort_after_save(n=1):
        with pytest.raises(CheckpointAbort):
            entropy_grid(50, np.array([1.2, 1.6]), cfg, checkpoint_path=p, **kw)
    with pytest.raises(ValueError, match="refusing to resume"):
        entropy_grid(50, np.array([1.2, 1.6]), cfg, checkpoint_path=p, seed=99,
                     checkpoint_interval_s=0.0)


def test_entropy_ensemble_matches_serial():
    """One vmapped program over congruent RRGs == per-graph sweeps."""
    from graphdyn.graphs import random_regular_graph
    from graphdyn.models.entropy import entropy_ensemble

    graphs = [random_regular_graph(50, 3, seed=k) for k in range(3)]
    cfg = EntropyConfig(lmbd_max=0.2, lmbd_step=0.1)
    lambdas = np.array([0.0, 0.1, 0.2])
    res = entropy_ensemble(graphs, cfg, seed=5, lambdas=lambdas)
    assert res.ent1.shape == (3, 3)
    for k, g in enumerate(graphs):
        # serial reference needs the same chi0 stream as the stacked init
        one = entropy_sweep(g, cfg, seed=0, chi0=res.chi[k], lambdas=lambdas[-1:])
        np.testing.assert_allclose(one.ent1[-1], res.ent1[-1, k], atol=5e-4)


def test_entropy_ensemble_empty_attractor_no_nan():
    """Members whose attractor set vanishes degrade to ent=-inf with FINITE
    m_init (0/0 guard in make_ensemble_m_init, matching the single-graph
    path), so ent1=-inf and the 'all'-mode entropy floor still fires."""
    from graphdyn.config import DynamicsConfig
    from graphdyn.graphs import erdos_renyi_graph, remove_isolates
    from graphdyn.models.entropy import entropy_ensemble, entropy_sweep

    g, _ = remove_isolates(erdos_renyi_graph(80, 1.2 / 79, seed=2))
    cfg = EntropyConfig(
        dynamics=DynamicsConfig(p=1, c=1, rule="minority", attr_value=-1)
    )
    lambdas = np.array([0.0])
    res = entropy_ensemble([g, g], cfg, seed=5, lambdas=lambdas)
    one = entropy_sweep(g, cfg, seed=0, lambdas=lambdas)
    assert np.all(np.isneginf(res.ent))
    # m_init is FINITE (guarded 0/0), like the single-graph path; its exact
    # value on a vanished attractor set depends on the (unconverged) random
    # chi and is not physically meaningful, so only finiteness is pinned
    assert np.all(np.isfinite(res.m_init)), f"m_init {res.m_init}"
    assert np.isfinite(one.m_init[-1])
    assert np.all(np.isneginf(res.ent1)) and np.isneginf(one.ent1[-1])


@pytest.mark.slow
def test_golden_triples_tight_f64():
    """Tight golden anchor in float64 (the reference's precision — numpy
    default in `ER_BDCM_entropy.ipynb`).

    The reference's stored run used an *unseeded* `nx.fast_gnp_random_graph`
    (`ipynb:280`), so the exact instance is unrecoverable; seed 9425 is the
    networkx sampler seed whose instance matches the stored run's printed
    stats exactly (`ipynb:16`: 370 isolated nodes, avg_degree_total 0.97 ⇒
    E=485) and lands within ≤5e-3 of all ten stored (λ, m_init, ent1)
    triples — instance-to-instance spread among stat-matched graphs is
    ~1e-2, so this is regression-grade for the framework while staying
    honest about the irreproducible instance."""
    import jax

    golden = [
        (0.0, 0.7859766580538275, 0.1720699495590459),
        (0.1, 0.7699358367558866, 0.17127259171924963),
        (0.2, 0.7545492129205356, 0.16897079877838897),
        (0.3, 0.7399806499309954, 0.16533606458353123),
        (0.4, 0.7263552613663471, 0.1605754636000715),
        (0.5, 0.7137593656167142, 0.15491615729839237),
        (0.6, 0.7022428278329915, 0.14859118078564132),
        (0.7, 0.6918229572378949, 0.14182740343380668),
        (0.8, 0.6824890587925729, 0.13484592378355741),
        (0.9, 0.6742072244439773, 0.12780494062947345),
    ]
    g = erdos_renyi_graph(1000, 1.0 / 999, seed=9425, method="networkx")
    assert int((g.deg == 0).sum()) == 370 and g.edges.shape[0] == 485
    jax.config.update("jax_enable_x64", True)
    try:
        cfg = EntropyConfig(lmbd_max=0.9, lmbd_step=0.1, dtype="float64")
        res = entropy_sweep(g, cfg, seed=0)
    finally:
        jax.config.update("jax_enable_x64", False)
    assert res.lambdas.size == 10, "all ten ladder points must converge"
    assert res.chi.dtype == np.float64
    for k, (lam, m_g, e_g) in enumerate(golden):
        assert abs(res.m_init[k] - m_g) <= 5e-3, (lam, res.m_init[k], m_g)
        assert abs(res.ent1[k] - e_g) <= 5e-3, (lam, res.ent1[k], e_g)
    # warm-started sweep counts in the stored run's regime (`ipynb:18-46`:
    # 130-160 for λ≥0.1; measured here 127-163)
    assert np.all(res.sweeps <= 200) and np.all(res.sweeps >= 100)


def test_union_ensemble_matches_per_graph():
    """entropy_ensemble_union on heterogeneous ER members (different degree
    signatures, isolates included) reproduces the per-graph entropy_sweep
    results member by member."""
    from graphdyn.models.entropy import entropy_ensemble_union

    cfg = EntropyConfig()
    lambdas = np.round(np.arange(0.0, 0.35, 0.1), 2)
    graphs = [erdos_renyi_graph(200, 1.2 / 199, seed=s) for s in (1, 2, 3)]
    assert any((g.deg == 0).any() for g in graphs)      # isolates present
    res = entropy_ensemble_union(graphs, cfg, seed=0, lambdas=lambdas)
    assert res.lambdas.size == lambdas.size
    for k, g in enumerate(graphs):
        ref = entropy_sweep(g, cfg, seed=10 + k, lambdas=lambdas)
        np.testing.assert_allclose(res.ent[:, k], ref.ent, atol=2e-3)
        np.testing.assert_allclose(res.m_init[:, k], ref.m_init, atol=2e-3)
        np.testing.assert_allclose(res.ent1[:, k], ref.ent1, atol=2e-3)


def test_union_ensemble_all_isolate_member():
    """A member that is entirely isolated nodes gets the closed-form
    analytic entropy: φ = −λ·n_iso/n, m_init = 1."""
    from graphdyn.graphs import graph_from_edges
    from graphdyn.models.entropy import entropy_ensemble_union

    iso = graph_from_edges(5, np.empty((0, 2), dtype=np.int64))
    er = erdos_renyi_graph(60, 1.5 / 59, seed=4)
    cfg = EntropyConfig()
    lambdas = np.array([0.0, 0.5])
    res = entropy_ensemble_union([er, iso], cfg, seed=0, lambdas=lambdas)
    np.testing.assert_allclose(res.m_init[:, 1], 1.0, atol=1e-6)
    np.testing.assert_allclose(res.ent[:, 1], -lambdas * 1.0, atol=1e-6)


def test_union_ensemble_all_edgeless_closed_form():
    """A union whose every member is edgeless takes the analytic closed
    form — no BP machinery, full ladder, exact values."""
    from graphdyn.graphs import graph_from_edges
    from graphdyn.models.entropy import entropy_ensemble_union

    iso = graph_from_edges(5, np.empty((0, 2), dtype=np.int64))
    lambdas = np.array([0.0, 0.5, 1.0])
    res = entropy_ensemble_union([iso, iso], EntropyConfig(), lambdas=lambdas)
    assert res.lambdas.size == 3
    np.testing.assert_allclose(res.m_init, 1.0)
    np.testing.assert_allclose(res.ent, -lambdas[:, None] * np.ones((1, 2)))
    np.testing.assert_allclose(res.ent1, 0.0, atol=1e-12)


def test_union_ensemble_resume_chi0():
    """Passing a previous union result's chi back as chi0 warm-starts: the
    resumed first λ converges in far fewer sweeps than a cold start."""
    from graphdyn.models.entropy import entropy_ensemble_union

    cfg = EntropyConfig()
    graphs = [erdos_renyi_graph(150, 1.2 / 149, seed=s) for s in (5, 6)]
    r1 = entropy_ensemble_union(graphs, cfg, seed=0, lambdas=np.array([0.0, 0.1]))
    r2 = entropy_ensemble_union(
        graphs, cfg, chi0=r1.chi, lambdas=np.array([0.1])
    )
    assert r2.sweeps[0] < r1.sweeps[0] / 2
    np.testing.assert_allclose(r2.ent[0], r1.ent[1], atol=5e-4)


def test_union_ensemble_checkpointing(tmp_path):
    """The union ensemble saves resumable state through a
    PeriodicCheckpointer; restoring chi as chi0 continues the ladder."""
    from graphdyn.models.entropy import entropy_ensemble_union
    from graphdyn.utils.io import PeriodicCheckpointer

    cfg = EntropyConfig()
    graphs = [erdos_renyi_graph(100, 1.2 / 99, seed=s) for s in (7, 8)]
    ck = PeriodicCheckpointer(str(tmp_path / "union"), interval_s=0.0)
    res = entropy_ensemble_union(
        graphs, cfg, seed=0, lambdas=np.array([0.0, 0.1]), checkpointer=ck
    )
    arrays, meta = ck.ckpt.load()
    assert meta["lmbd"] == 0.1
    np.testing.assert_array_equal(arrays["chi"], res.chi)
    r2 = entropy_ensemble_union(
        graphs, cfg, chi0=arrays["chi"], lambdas=np.array([0.2])
    )
    assert r2.lambdas.size == 1 and np.isfinite(r2.ent1).all()


def test_union_ensemble_managed_resume_bit_exact(tmp_path, abort_after_save):
    """checkpoint_path mode: an interrupted union-ensemble ladder resumes at
    the first unvisited λ with the saved warm-start chi — identical results
    to the uninterrupted run, surviving a double interruption; mismatched
    runs refused."""
    import os

    from conftest import CheckpointAbort
    from graphdyn.graphs import erdos_renyi_graph
    from graphdyn.models.entropy import entropy_ensemble_union

    graphs = [erdos_renyi_graph(40, 1.3 / 39, seed=k) for k in range(3)]
    cfg = EntropyConfig(lmbd_max=0.4, lmbd_step=0.1)
    kw = dict(seed=5, checkpoint_interval_s=0.0)
    base = entropy_ensemble_union(graphs, cfg, seed=5)

    p = str(tmp_path / "uck")
    with abort_after_save(n=2):
        with pytest.raises(CheckpointAbort):
            entropy_ensemble_union(graphs, cfg, checkpoint_path=p, **kw)
    assert os.path.exists(p + ".npz")
    with abort_after_save(n=1):   # second interruption inside the continuation
        with pytest.raises(CheckpointAbort):
            entropy_ensemble_union(graphs, cfg, checkpoint_path=p, **kw)
    resumed = entropy_ensemble_union(graphs, cfg, checkpoint_path=p, **kw)
    np.testing.assert_array_equal(base.lambdas, resumed.lambdas)
    np.testing.assert_array_equal(base.ent, resumed.ent)
    np.testing.assert_array_equal(base.m_init, resumed.m_init)
    np.testing.assert_array_equal(base.ent1, resumed.ent1)
    assert base.nonconverged == resumed.nonconverged
    assert not os.path.exists(p + ".npz")

    # a different ensemble is refused
    with abort_after_save(n=1):
        with pytest.raises(CheckpointAbort):
            entropy_ensemble_union(graphs, cfg, checkpoint_path=p, **kw)
    with pytest.raises(ValueError, match="refusing to resume"):
        entropy_ensemble_union(graphs[:2], cfg, checkpoint_path=p, **kw)
    # both checkpoint modes at once is an error
    from graphdyn.utils.io import PeriodicCheckpointer
    with pytest.raises(ValueError, match="not both"):
        entropy_ensemble_union(graphs, cfg, checkpoint_path=p,
                               checkpointer=PeriodicCheckpointer(p), **kw)


def test_congruent_ensemble_managed_resume_bit_exact(tmp_path, abort_after_save):
    """checkpoint_path mode on the vmapped congruent-ensemble ladder mirrors
    the union path: interrupted runs resume λ-granularly to identical
    results; mismatched runs refused."""
    import os

    from conftest import CheckpointAbort
    from graphdyn.graphs import random_regular_graph
    from graphdyn.models.entropy import entropy_ensemble

    graphs = [random_regular_graph(40, 3, seed=k) for k in range(3)]
    cfg = EntropyConfig(lmbd_max=0.3, lmbd_step=0.1)
    base = entropy_ensemble(graphs, cfg, seed=4)

    p = str(tmp_path / "eck")
    with abort_after_save(n=2):
        with pytest.raises(CheckpointAbort):
            entropy_ensemble(graphs, cfg, seed=4, checkpoint_path=p,
                             checkpoint_interval_s=0.0)
    assert os.path.exists(p + ".npz")
    resumed = entropy_ensemble(graphs, cfg, seed=4, checkpoint_path=p,
                               checkpoint_interval_s=0.0)
    np.testing.assert_array_equal(base.lambdas, resumed.lambdas)
    np.testing.assert_array_equal(base.ent, resumed.ent)
    np.testing.assert_array_equal(base.ent1, resumed.ent1)
    np.testing.assert_array_equal(base.sweeps, resumed.sweeps)
    assert not os.path.exists(p + ".npz")

    with abort_after_save(n=1):
        with pytest.raises(CheckpointAbort):
            entropy_ensemble(graphs, cfg, seed=4, checkpoint_path=p,
                             checkpoint_interval_s=0.0)
    with pytest.raises(ValueError, match="refusing to resume"):
        entropy_ensemble(graphs, cfg, seed=99, checkpoint_path=p,
                         checkpoint_interval_s=0.0)


@pytest.mark.slow
def test_golden_f64_artifact_reproducible():
    """GOLDEN_r04.json (scripts/golden_curve_r04.py): the reference's ten
    stored triples (`ipynb:18-46`) must sit INSIDE the measured f64
    instance-to-instance spread (all flags true, |z| < 2), and the committed
    per-seed f64 curve must reproduce bit-tight when re-run — the artifact
    is a checkable claim, not a one-off printout."""
    import json
    import os

    import jax

    path = os.path.join(os.path.dirname(__file__), "..", "GOLDEN_r04.json")
    if not os.path.exists(path):
        pytest.skip("GOLDEN_r04.json not generated")
    with open(path) as f:
        art = json.load(f)
    for lam, s in art["spread_at_golden_lambdas"].items():
        assert s["golden_m_init_inside_spread"], f"m_init outside spread at λ={lam}"
        assert s["golden_ent1_inside_spread"], f"ent1 outside spread at λ={lam}"
        assert abs(s["golden_m_init_z"]) < 2.0
        assert abs(s["golden_ent1_z"]) < 2.0

    row = art["per_seed"][0]
    g = erdos_renyi_graph(1000, 1.0 / 999, seed=row["seed"], method="networkx")
    lambdas = np.asarray(row["lambdas"])[:10]       # first ten points suffice
    jax.config.update("jax_enable_x64", True)
    try:
        res = entropy_sweep(
            g, EntropyConfig(dtype="float64"), seed=row["seed"], lambdas=lambdas
        )
    finally:
        jax.config.update("jax_enable_x64", False)
    np.testing.assert_allclose(res.m_init, row["m_init"][:10], rtol=0, atol=1e-9)
    np.testing.assert_allclose(res.ent1, row["ent1"][:10], rtol=0, atol=1e-9)


def test_plateau_exit_opt_in():
    """plateau_eps > 0 stops the ladder after `patience` consecutive
    unmoved lambda points; the visited prefix is bit-identical to the
    reference-behavior (plateau_eps=0) run. Motivation: T>=3 curves floor
    at positive ent1, where the reference's ent_floor exit never fires."""
    edges = np.array([[0, 1], [1, 2], [2, 3], [3, 0], [0, 4]])
    g = graph_from_edges(5, edges)
    base = EntropyConfig(lmbd_max=5.0, lmbd_step=0.5, ent_floor=-1e9)
    full = entropy_sweep(g, base, seed=0)
    # an "everything counts as a plateau" tolerance: exits after the first
    # ladder point with two consecutive unmoved successors
    cfg = EntropyConfig(lmbd_max=5.0, lmbd_step=0.5, ent_floor=-1e9,
                        plateau_eps=1e9, plateau_patience=2)
    res = entropy_sweep(g, cfg, seed=0)
    assert res.lambdas.size == 3  # lambda 0 + 2 plateau-streak points
    np.testing.assert_array_equal(res.lambdas, full.lambdas[:3])
    np.testing.assert_array_equal(res.m_init, full.m_init[:3])
    np.testing.assert_array_equal(res.ent1, full.ent1[:3])
    # default config keeps the reference behavior: the full ladder is
    # visited (11 points for lmbd_max=5, step=0.5) unless a fixed point
    # failed first
    assert base.plateau_eps == 0.0
    assert full.lambdas.size == 11 or full.nonconverged > 0


def test_plateau_streak_resume_invariant():
    """Splitting the ladder (chi + prev_rows handoff, the checkpoint-resume
    shape) visits exactly the same λ set as the uninterrupted run — the
    plateau streak must not reset at the resume boundary."""
    edges = np.array([[0, 1], [1, 2], [2, 3], [3, 0], [0, 4]])
    g = graph_from_edges(5, edges)
    cfg = EntropyConfig(lmbd_max=5.0, lmbd_step=0.5, ent_floor=-1e9,
                        plateau_eps=1e9, plateau_patience=2)
    full = entropy_sweep(g, cfg, seed=0)
    assert full.lambdas.size == 3  # plateau exit fired

    lambdas = np.linspace(0.0, 5.0, 11)
    # interrupt after 2 points (streak = 1), resume the rest
    first = entropy_sweep(g, cfg, seed=0, lambdas=lambdas[:2])
    rest = entropy_sweep(
        g, cfg, seed=0, lambdas=lambdas[2:], chi0=first.chi,
        prev_rows=(first.m_init, first.ent1),
    )
    stitched = np.concatenate([first.lambdas, rest.lambdas])
    np.testing.assert_array_equal(stitched, full.lambdas)
    # interrupt INSIDE a completed streak: the resumed call must visit
    # nothing (the uninterrupted run had already exited)
    first3 = entropy_sweep(g, cfg, seed=0, lambdas=lambdas[:3])
    rest3 = entropy_sweep(
        g, cfg, seed=0, lambdas=lambdas[3:], chi0=first3.chi,
        prev_rows=(first3.m_init, first3.ent1),
    )
    assert rest3.lambdas.size == 0


# ---------------------------------------------------------------------------
# cell-parallel λ-ladders (graphdyn.pipeline.entropy_group)
# ---------------------------------------------------------------------------


def test_entropy_sweep_pre_refactor_anchor():
    """Regression anchor for the G=1 group-program refactor (the PR-3
    identity discipline): these values were captured from the PRE-refactor
    serial ladder (`_fixed_point_exec`'s fused while_loop) on two CPU
    shapes — unbucketed and class-bucketed — and the shared cell-group
    program at G=1 must reproduce them bit-for-bit, sweep counts and final
    chi state included."""
    from graphdyn.config import DynamicsConfig

    g = erdos_renyi_graph(60, 1.5 / 59, seed=3)
    cfg = EntropyConfig(dynamics=DynamicsConfig(p=1, c=1), lmbd_max=0.3,
                        lmbd_step=0.1, max_sweeps=300, eps=1e-5)
    r = entropy_sweep(g, cfg, seed=3)
    assert [float(x) for x in r.m_init] == [
        0.6456124782562256, 0.6203604340553284,
        0.5962358117103577, 0.5734946131706238,
    ]
    assert [float(x) for x in r.ent1] == [
        0.2942521274089813, 0.2929973900318146,
        0.289388507604599, 0.28371450304985046,
    ]
    assert r.sweeps.tolist() == [136, 84, 89, 94]
    assert float(r.chi.astype(np.float64).sum()) == 89.99998668581247

    g2 = erdos_renyi_graph(80, 2.0 / 79, seed=7)
    r2 = entropy_sweep(g2, EntropyConfig(lmbd_max=0.2, lmbd_step=0.1),
                       seed=7, class_bucket=64)
    assert [float(x) for x in r2.m_init] == [
        0.6252278685569763, 0.5984280705451965, 0.5722740888595581,
    ]
    assert [float(x) for x in r2.ent1] == [
        0.3218421936035156, 0.32050633430480957, 0.3165897727012634,
    ]
    assert r2.sweeps.tolist() == [177, 133, 140]


def _assert_grids_equal(a, b):
    for f in a._fields:
        av, bv = getattr(a, f), getattr(b, f)
        if av is None and bv is None:
            continue
        np.testing.assert_array_equal(av, bv, err_msg=f)


def test_entropy_grid_grouped_matches_serial_elementwise():
    """The grouped grid (cells advancing their λ-ladders in lockstep chunks
    through the stacked cell program) is element-wise IDENTICAL to the
    serial cell loop — group sizes 1 (vmapped singleton), 3 (non-divisor of
    the 4-cell grid: padded tail group), and the default."""
    cfg = EntropyConfig(lmbd_max=0.2, lmbd_step=0.1, num_rep=2)
    deg = np.array([1.2, 1.6])
    base = entropy_grid(40, deg, cfg, seed=3, group_size=0)
    for gs in (1, 3):
        res = entropy_grid(40, deg, cfg, seed=3, group_size=gs)
        _assert_grids_equal(base, res)


def test_entropy_grid_grouped_cells_stop_at_different_lambda():
    """Cells exiting at different ladder positions (entropy floor crossed
    by some cells only) stay frozen while the rest of the group runs on —
    per-cell rows, counts, and n_lambda all match the serial loop."""
    # ent_floor between the deg=1.2 and deg=1.6 ent1 levels: the low-deg
    # cells cross at λ0 while the high-deg cells visit the whole ladder
    cfg = EntropyConfig(lmbd_max=0.3, lmbd_step=0.1, num_rep=2,
                        ent_floor=0.2)
    deg = np.array([1.2, 1.6])
    base = entropy_grid(40, deg, cfg, seed=3, group_size=0)
    assert base.n_lambda.min() < base.n_lambda.max()   # exits actually differ
    res = entropy_grid(40, deg, cfg, seed=3, group_size=4)
    _assert_grids_equal(base, res)
    # and with the opt-in plateau exit active
    cfgp = EntropyConfig(lmbd_max=0.5, lmbd_step=0.1, num_rep=2,
                         ent_floor=-1e9, plateau_eps=1e9, plateau_patience=2)
    basep = entropy_grid(30, np.array([1.1, 1.4]), cfgp, seed=2, group_size=0)
    resp = entropy_grid(30, np.array([1.1, 1.4]), cfgp, seed=2, group_size=4)
    _assert_grids_equal(basep, resp)
    assert int(basep.n_lambda.max()) == 3              # plateau exit fired


def test_entropy_grid_resume_interop_across_paths(tmp_path, abort_after_save):
    """Snapshots are interchangeable between the serial and grouped cell
    paths, λ-granularly: a grouped-written snapshot resumes under
    group_size=0 and a serial-written snapshot resumes under grouping —
    both bit-exact vs the uninterrupted run (regrouping cannot change
    per-cell results: each cell's ladder depends only on its seed and λ
    cursor)."""
    from conftest import CheckpointAbort

    cfg = EntropyConfig(lmbd_max=0.2, lmbd_step=0.1, num_rep=2)
    deg = np.array([1.2, 1.6])
    kw = dict(seed=3, checkpoint_interval_s=0.0)
    base = entropy_grid(40, deg, cfg, seed=3)

    # grouped write → serial resume
    p = str(tmp_path / "g2s")
    with abort_after_save(n=2):
        with pytest.raises(CheckpointAbort):
            entropy_grid(40, deg, cfg, checkpoint_path=p, group_size=4, **kw)
    res = entropy_grid(40, deg, cfg, checkpoint_path=p, group_size=0, **kw)
    _assert_grids_equal(base, res)

    # serial write → grouped resume (different group sizes)
    p2 = str(tmp_path / "s2g")
    with abort_after_save(n=2):
        with pytest.raises(CheckpointAbort):
            entropy_grid(40, deg, cfg, checkpoint_path=p2, group_size=0, **kw)
    res2 = entropy_grid(40, deg, cfg, checkpoint_path=p2, group_size=3, **kw)
    _assert_grids_equal(base, res2)
