"""HPr solver tests: convergence to a consensus-flowing initialization on
small RRGs, reinforcement semantics, sentinel behavior."""

import jax
import numpy as np
import pytest

from graphdyn.config import DynamicsConfig, HPRConfig
from graphdyn.graphs import random_regular_graph
from graphdyn.models.hpr import hpr_solve
from graphdyn.ops.dynamics import end_state


def test_hpr_finds_consensus_flowing_init():
    g = random_regular_graph(60, 4, seed=1)
    cfg = HPRConfig(dynamics=DynamicsConfig(p=1, c=1), max_sweeps=3000)
    res = hpr_solve(g, cfg, seed=0)
    assert res.m_final == 1.0, f"did not converge in {res.num_steps} sweeps"
    out = end_state(g, res.s, p=1, c=1, backend="cpu")
    assert np.all(out == 1)
    # the point of HPr: a non-trivial (below-consensus) initialization
    assert res.mag_reached < 1.0
    assert res.num_steps >= 1


def test_hpr_timeout_sentinel():
    g = random_regular_graph(60, 4, seed=2)
    cfg = HPRConfig(max_sweeps=2)
    res = hpr_solve(g, cfg, seed=5)
    assert res.m_final in (1.0, 2.0)
    if res.m_final == 2.0:
        assert res.num_steps == 3  # t incremented past TT


def test_hpr_biases_polarized_after_convergence():
    g = random_regular_graph(40, 4, seed=3)
    cfg = HPRConfig(max_sweeps=3000)
    res = hpr_solve(g, cfg, seed=1)
    if res.m_final == 1.0:
        # reinforced biases are at (pie, 1-pie) or (1-pie, pie) rows
        b = res.biases
        polarized = np.isclose(b.max(axis=1), 1 - cfg.pie, atol=1e-5)
        assert polarized.mean() > 0.9
        np.testing.assert_allclose(b.sum(axis=1), 1.0, atol=1e-5)


def test_hpr_seed_reproducible():
    g = random_regular_graph(40, 4, seed=4)
    cfg = HPRConfig(max_sweeps=500)
    r1 = hpr_solve(g, cfg, seed=7)
    r2 = hpr_solve(g, cfg, seed=7)
    assert r1.num_steps == r2.num_steps
    np.testing.assert_array_equal(r1.s, r2.s)


def test_hpr_ensemble_driver(tmp_path):
    """Reference npz keys incl. wall-clock `time` (`HPR_pytorch_RRG.py:377`)."""
    from graphdyn.models.hpr import hpr_ensemble
    from graphdyn.utils.io import load_results_npz

    p = str(tmp_path / "hpr.npz")
    cfg = HPRConfig(max_sweeps=2000)
    out = hpr_ensemble(40, 4, cfg, n_rep=2, seed=0, save_path=p)
    assert out.conf.shape == (2, 40)
    assert np.all(out.time > 0)
    saved = load_results_npz(p)
    assert set(saved) == {"mag_reached", "conf", "num_steps", "graphs", "time"}


def test_hpr_batch_chains_converge():
    """Batched chains converge and report per-chain sentinels; converged
    trial solutions really flow to consensus; chains are independent."""
    from graphdyn.models.hpr import hpr_solve_batch

    g = random_regular_graph(40, 4, seed=5)
    cfg = HPRConfig(max_sweeps=3000)
    res = hpr_solve_batch(g, cfg, n_replicas=4, seed=2)
    assert res.s.shape == (4, 40)
    assert np.all((res.m_final == 1.0) | (res.m_final == 2.0))
    assert (res.m_final == 1.0).sum() >= 3      # most chains find consensus
    # converged chains really flow to all-+1 under the rollout
    from graphdyn.ops.dynamics import end_state
    for r in range(4):
        if res.m_final[r] == 1.0:
            out = np.asarray(end_state(g, res.s[r], 1, 1, backend="cpu"))
            assert np.all(out == 1)
    # per-chain step counts vary (chains are independent streams)
    assert len(set(res.num_steps.tolist())) > 1


def test_hpr_batch_sharded_replicas():
    """Replica-sharded batched HPr over the 8-device CPU mesh."""
    from graphdyn.models.hpr import hpr_solve_batch
    from graphdyn.parallel.mesh import device_pool, make_mesh

    g = random_regular_graph(30, 3, seed=1)
    mesh = make_mesh((8,), ("replica",), devices=device_pool(8))
    cfg = HPRConfig(max_sweeps=2000)
    res = hpr_solve_batch(g, cfg, n_replicas=8, seed=0, mesh=mesh)
    assert res.s.shape == (8, 30)
    assert np.all((res.m_final == 1.0) | (res.m_final == 2.0))


def test_hpr_checkpoint_resume_bit_exact(tmp_path, abort_after_save):
    """Chunked+checkpointed HPr equals the uninterrupted chain bit-for-bit,
    and resuming from a kept mid-flight checkpoint finishes identically
    (SURVEY.md §5.4 resume state: chi, biases, s, rng key, t)."""
    import os

    g = random_regular_graph(60, 4, seed=1)
    cfg = HPRConfig(dynamics=DynamicsConfig(p=1, c=1), max_sweeps=3000)
    base = hpr_solve(g, cfg, seed=0)

    p1 = str(tmp_path / "hpr_ck")
    chunked = hpr_solve(
        g, cfg, seed=0, checkpoint_path=p1,
        checkpoint_interval_s=0.0, chunk_sweeps=7,
    )
    assert chunked.num_steps == base.num_steps
    assert chunked.m_final == base.m_final
    np.testing.assert_array_equal(chunked.s, base.s)
    np.testing.assert_array_equal(chunked.biases, base.biases)
    np.testing.assert_array_equal(chunked.chi, base.chi)
    assert not os.path.exists(p1 + ".npz")      # removed on completion

    # mid-flight restart: force an abort after the first chunk by keeping the
    # checkpoint file, then resume from it
    from conftest import CheckpointAbort

    p2 = str(tmp_path / "hpr_ck2")
    with abort_after_save(n=1):
        with pytest.raises(CheckpointAbort):
            hpr_solve(g, cfg, seed=0, checkpoint_path=p2,
                      checkpoint_interval_s=0.0, chunk_sweeps=5)
    assert os.path.exists(p2 + ".npz")          # a mid-flight snapshot exists

    resumed = hpr_solve(g, cfg, seed=0, checkpoint_path=p2,
                        checkpoint_interval_s=1e9, chunk_sweeps=50)
    assert resumed.num_steps == base.num_steps
    assert resumed.m_final == base.m_final
    np.testing.assert_array_equal(resumed.s, base.s)
    np.testing.assert_array_equal(resumed.chi, base.chi)


def test_hpr_ensemble_driver_resume(tmp_path, abort_after_save):
    """Driver-level resume (completed reps kept, graphs re-derived) mirrors
    sa_ensemble's; abort lands between repetitions."""
    import os

    from conftest import CheckpointAbort
    from graphdyn.models.hpr import hpr_ensemble

    cfg = HPRConfig(dynamics=DynamicsConfig(p=1, c=1), max_sweeps=3000)
    kw = dict(n_rep=2, seed=1)
    base = hpr_ensemble(50, 4, cfg, **kw)

    p = str(tmp_path / "hpr_grid")
    with abort_after_save(when=lambda meta: meta.get("next_rep") == 1):
        with pytest.raises(CheckpointAbort):
            hpr_ensemble(50, 4, cfg, checkpoint_path=p,
                         checkpoint_interval_s=0.0, **kw)
    assert os.path.exists(p + ".npz")

    resumed = hpr_ensemble(50, 4, cfg, checkpoint_path=p,
                         checkpoint_interval_s=0.0, **kw)
    np.testing.assert_array_equal(base.conf, resumed.conf)
    np.testing.assert_array_equal(base.num_steps, resumed.num_steps)
    np.testing.assert_array_equal(base.graphs, resumed.graphs)
    assert not os.path.exists(p + ".npz")


def test_hpr_batch_checkpoint_resume_bit_exact(tmp_path, abort_after_save):
    """Chunked+checkpointed batch solver equals the uninterrupted run
    bit-for-bit; a kept mid-flight snapshot resumes identically; foreign
    checkpoints are refused."""
    import os

    from conftest import CheckpointAbort
    from graphdyn.models.hpr import hpr_solve_batch

    g = random_regular_graph(40, 4, seed=5)
    cfg = HPRConfig(max_sweeps=3000)
    base = hpr_solve_batch(g, cfg, n_replicas=4, seed=2)

    p1 = str(tmp_path / "hb_ck")
    chunked = hpr_solve_batch(
        g, cfg, n_replicas=4, seed=2, checkpoint_path=p1,
        checkpoint_interval_s=0.0, chunk_sweeps=9,
    )
    np.testing.assert_array_equal(base.s, chunked.s)
    np.testing.assert_array_equal(base.num_steps, chunked.num_steps)
    np.testing.assert_array_equal(base.m_final, chunked.m_final)
    assert not os.path.exists(p1 + ".npz")

    p2 = str(tmp_path / "hb_ck2")
    with abort_after_save(n=1):
        with pytest.raises(CheckpointAbort):
            hpr_solve_batch(g, cfg, n_replicas=4, seed=2, checkpoint_path=p2,
                            checkpoint_interval_s=0.0, chunk_sweeps=7)
    assert os.path.exists(p2 + ".npz")
    resumed = hpr_solve_batch(g, cfg, n_replicas=4, seed=2,
                              checkpoint_path=p2, chunk_sweeps=50)
    np.testing.assert_array_equal(base.s, resumed.s)
    np.testing.assert_array_equal(base.num_steps, resumed.num_steps)

    # wrong replica count: refused (R is part of the fingerprint)
    with abort_after_save(n=1):
        with pytest.raises(CheckpointAbort):
            hpr_solve_batch(g, cfg, n_replicas=4, seed=2, checkpoint_path=p2,
                            checkpoint_interval_s=0.0, chunk_sweeps=7)
    with pytest.raises(ValueError, match="refusing to resume"):
        hpr_solve_batch(g, cfg, n_replicas=5, seed=2, checkpoint_path=p2)


def test_hpr_batch_mesh_checkpoint_resume(tmp_path, abort_after_save):
    """Checkpointing composes with replica-mesh sharding: snapshots gather
    the sharded state to host, and a resumed run re-places it on the mesh
    with identical results (the config-2 preemption scenario)."""
    import os

    from conftest import CheckpointAbort
    from graphdyn.models.hpr import hpr_solve_batch
    from graphdyn.parallel.mesh import device_pool, make_mesh

    g = random_regular_graph(30, 3, seed=1)
    mesh = make_mesh((8,), ("replica",), devices=device_pool(8))
    cfg = HPRConfig(max_sweeps=2000)
    base = hpr_solve_batch(g, cfg, n_replicas=8, seed=0, mesh=mesh)

    p = str(tmp_path / "hbm_ck")
    with abort_after_save(n=1):
        with pytest.raises(CheckpointAbort):
            hpr_solve_batch(g, cfg, n_replicas=8, seed=0, mesh=mesh,
                            checkpoint_path=p, checkpoint_interval_s=0.0,
                            chunk_sweeps=5)
    assert os.path.exists(p + ".npz")
    resumed = hpr_solve_batch(g, cfg, n_replicas=8, seed=0, mesh=mesh,
                              checkpoint_path=p, chunk_sweeps=50)
    np.testing.assert_array_equal(base.s, resumed.s)
    np.testing.assert_array_equal(base.num_steps, resumed.num_steps)
    np.testing.assert_array_equal(base.m_final, resumed.m_final)
    assert not os.path.exists(p + ".npz")


def test_replicate_edge_tables_layout_equivalence():
    """The replica-major union tables (`graphdyn.graphs.replicate_edge_tables`)
    are a pure permutation of the canonical union tables: one biased sweep +
    marginals agree row-for-row under the layout permutation. This is the
    layout-equivalence guarantee behind the communication-free config-2
    replica sharding."""
    import jax.numpy as jnp

    from graphdyn.graphs import (
        build_edge_tables,
        replicate_disjoint,
        replicate_edge_tables,
    )
    from graphdyn.ops.bdcm import BDCMData, make_marginals, make_sweep

    g = random_regular_graph(12, 3, seed=3)
    n, E, R = g.n, g.num_edges, 3
    gu = replicate_disjoint(g, R)
    data_def = BDCMData(gu, p=1, c=1)                       # canonical layout
    tabs = replicate_edge_tables(build_edge_tables(g), R, n)
    data_new = BDCMData(gu, tabs, p=1, c=1)                 # replica-major

    # new directed id r*2E+e  <->  canonical id r*E+e (fwd) / R*E+r*E+(e-E)
    new2def = np.empty(2 * R * E, np.int64)
    for r in range(R):
        new2def[r * 2 * E : r * 2 * E + E] = r * E + np.arange(E)
        new2def[r * 2 * E + E : (r + 1) * 2 * E] = R * E + r * E + np.arange(E)
    assert np.array_equal(np.sort(new2def), np.arange(2 * R * E))
    np.testing.assert_array_equal(
        np.asarray(data_new.tables.src), np.asarray(data_def.tables.src)[new2def]
    )
    # rev consistency: reversing in the new layout matches the canonical rule
    np.testing.assert_array_equal(
        new2def[tabs.rev(np.arange(2 * R * E))],
        data_def.tables.rev(new2def),
    )

    rng = np.random.default_rng(0)
    chi_new = rng.random((2 * R * E, data_new.K, data_new.K)).astype(np.float32)
    bias_new = rng.random((2 * R * E, data_new.K)).astype(np.float32)
    chi_def = np.empty_like(chi_new)
    bias_def = np.empty_like(bias_new)
    chi_def[new2def] = chi_new
    bias_def[new2def] = bias_new

    kw = dict(damp=0.4, mask_invalid_src=False, with_bias=True)
    out_new = np.asarray(
        make_sweep(data_new, **kw)(jnp.asarray(chi_new), 25.0, jnp.asarray(bias_new))
    )
    out_def = np.asarray(
        make_sweep(data_def, **kw)(jnp.asarray(chi_def), 25.0, jnp.asarray(bias_def))
    )
    np.testing.assert_allclose(out_def[new2def], out_new, rtol=1e-6, atol=0)

    marg_new = np.asarray(make_marginals(data_new)(jnp.asarray(out_new)))
    marg_def = np.asarray(make_marginals(data_def)(jnp.asarray(out_def)))
    np.testing.assert_allclose(marg_def, marg_new, rtol=1e-6, atol=0)

    # the halves-slicing observables refuse the permuted layout
    from graphdyn.ops.bdcm import make_edge_partition

    with pytest.raises(ValueError, match="rev_map"):
        make_edge_partition(data_new)


def test_union_setup_device_bit_identical_to_host():
    """The ON-DEVICE union builders (`replicate_disjoint_device`,
    `replicate_edge_tables_device`, `replicate_bdcm_device` — the tunneled-
    link path that never ships union-sized tables host→device) produce the
    same tables and bit-identical sweep/marginals/bias as the host builders.
    An ER instance exercises ghost padding (ragged degrees, leaf edges)."""
    import jax.numpy as jnp

    from graphdyn.config import HPRConfig
    from graphdyn.graphs import (
        build_edge_tables,
        erdos_renyi_graph,
        remove_isolates,
        replicate_disjoint,
        replicate_disjoint_device,
        replicate_edge_tables,
        replicate_edge_tables_device,
    )
    from graphdyn.models.hpr import union_setup

    R = 3
    for g in (
        random_regular_graph(20, 3, seed=3),
        remove_isolates(erdos_renyi_graph(40, 2.0 / 39, seed=1))[0],
    ):
        th = replicate_edge_tables(build_edge_tables(g), R, g.n)
        td = replicate_edge_tables_device(build_edge_tables(g), R, g.n)
        for f in ("src", "dst", "edge_deg", "in_edges", "node_in_edges",
                  "node_out_edges", "rev_map"):
            np.testing.assert_array_equal(
                np.asarray(getattr(td, f)), np.asarray(getattr(th, f)),
                err_msg=f,
            )
        gh, gd = replicate_disjoint(g, R), replicate_disjoint_device(g, R)
        for f in ("nbr", "deg", "edges"):
            np.testing.assert_array_equal(
                np.asarray(getattr(gd, f)), np.asarray(getattr(gh, f)),
                err_msg=f,
            )

        cfg = HPRConfig()
        sh = union_setup(g, cfg, R)
        sd = union_setup(g, cfg, R, device=True)
        chi = sh.data.init_messages(0)
        bias = jnp.ones((sh.data.num_directed, sh.data.K), jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(sh.sweep(chi, jnp.float32(25.0), bias)),
            np.asarray(sd.sweep(chi, jnp.float32(25.0), bias)),
        )
        np.testing.assert_array_equal(
            np.asarray(sh.marginals(chi)), np.asarray(sd.marginals(chi))
        )
        nb = jnp.asarray(np.random.default_rng(0).random((sh.n, 2)), jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(sh.bias_to_edge(nb)), np.asarray(sd.bias_to_edge(nb))
        )
        # the on-device chi draw is row-normalized with the right shape
        chi_d = np.asarray(sd.data.init_messages_device(0))
        assert chi_d.shape == (sd.data.num_directed, sd.data.K, sd.data.K)
        np.testing.assert_allclose(chi_d.sum(axis=(1, 2)), 1.0, rtol=1e-5)


def test_hpr_batch_device_init():
    """`hpr_solve_batch(device_init=True)` — the tunneled-link path where
    tables and the initial state are built on device — solves chains and
    refuses the incompatible mesh/checkpoint combinations."""
    from graphdyn.models.hpr import hpr_solve_batch
    from graphdyn.parallel.mesh import make_mesh

    g = random_regular_graph(200, 3, seed=1)
    cfg = HPRConfig(dynamics=DynamicsConfig(p=1, c=1), max_sweeps=4000)
    res = hpr_solve_batch(g, cfg, n_replicas=3, seed=0, device_init=True)
    assert res.s.shape == (3, g.n)
    assert np.all((res.m_final == 1.0) | (res.m_final == 2.0))
    assert np.any(res.m_final == 1.0)           # at least one chain solves
    for s_k, mf in zip(res.s, res.m_final):
        if mf == 1.0:
            assert np.all(end_state(g, s_k, p=1, c=1, backend="cpu") == 1)

    with pytest.raises(ValueError, match="mesh"):
        hpr_solve_batch(
            g, cfg, n_replicas=2, device_init=True,
            mesh=make_mesh((1,), ("replica",)),
        )
    with pytest.raises(ValueError, match="checkpoint"):
        hpr_solve_batch(
            g, cfg, n_replicas=2, device_init=True, checkpoint_path="/tmp/x",
        )


@pytest.mark.parametrize("R", [8, 5])
def test_hpr_batch_sharded_bit_identical_to_unsharded(R):
    """The shard_map replica program equals the unsharded union program
    bit-for-bit over a bounded sweep horizon (every shard block computes
    exactly the unsharded per-replica arithmetic); R=5 exercises frozen
    pad chains on the 8-way mesh.

    The horizon is bounded at 256 sweeps because the CPU-simulated mesh
    cannot support *unbounded* bit-parity: LLVM vectorizes the per-shard
    ``[2E, ...]`` block program and the union ``[R·2E, ...]`` program
    differently, and the vectorized transcendentals can disagree by an ulp
    on rare inputs — reinforcement then amplifies the flip into divergent
    spins (first observed near sweep ~740 on this container; build-
    dependent, which is why earlier containers passed 2000 sweeps). Every
    *structural* break the test exists to catch — wrong block-diagonal
    tables, a freeze-mask or sweep-clock mismatch, a dropped psum — breaks
    parity at sweep 1, well inside the horizon. The unbounded contract is
    chip-only: ``test_hpr_batch_sharded_bit_identical_full_horizon``."""
    from graphdyn.models.hpr import hpr_solve_batch
    from graphdyn.parallel.mesh import device_pool, make_mesh

    g = random_regular_graph(30, 3, seed=1)
    mesh = make_mesh((8,), ("replica",), devices=device_pool(8))
    cfg = HPRConfig(max_sweeps=256)
    base = hpr_solve_batch(g, cfg, n_replicas=R, seed=0)
    sharded = hpr_solve_batch(g, cfg, n_replicas=R, seed=0, mesh=mesh)
    np.testing.assert_array_equal(base.s, sharded.s)
    np.testing.assert_array_equal(base.num_steps, sharded.num_steps)
    np.testing.assert_array_equal(base.m_final, sharded.m_final)


@pytest.mark.skipif(
    jax.default_backend() == "cpu",
    reason="full-horizon bit-parity is a chip contract: on the CPU-"
           "simulated mesh LLVM compiles the block and union shapes to "
           "different vector transcendentals (ulp-level, build-dependent) "
           "and reinforcement amplifies the drift over ~10^3 sweeps — see "
           "the bounded-horizon test above for the structural coverage",
)
@pytest.mark.parametrize("R", [8, 5])
def test_hpr_batch_sharded_bit_identical_full_horizon(R):
    """Chip-only: the sharded and unsharded programs agree bit-for-bit all
    the way to convergence/TT (identical vector units per shard, no
    shape-dependent transcendental codegen)."""
    from graphdyn.models.hpr import hpr_solve_batch
    from graphdyn.parallel.mesh import device_pool, make_mesh

    g = random_regular_graph(30, 3, seed=1)
    mesh = make_mesh((8,), ("replica",), devices=device_pool(8))
    cfg = HPRConfig(max_sweeps=2000)
    base = hpr_solve_batch(g, cfg, n_replicas=R, seed=0)
    sharded = hpr_solve_batch(g, cfg, n_replicas=R, seed=0, mesh=mesh)
    np.testing.assert_array_equal(base.s, sharded.s)
    np.testing.assert_array_equal(base.num_steps, sharded.num_steps)
    np.testing.assert_array_equal(base.m_final, sharded.m_final)


def test_hpr_float64_axis():
    """HPRConfig.dtype='float64' runs the whole solver in f64 — the
    reference's precision (`HPR_pytorch_RRG.py:11`,
    torch.set_default_dtype(torch.float64))."""
    import jax

    g = random_regular_graph(60, 4, seed=1)
    cfg64 = HPRConfig(
        dynamics=DynamicsConfig(p=1, c=1), max_sweeps=3000, dtype="float64"
    )
    jax.config.update("jax_enable_x64", True)
    try:
        res = hpr_solve(g, cfg64, seed=0)
        from graphdyn.models.hpr import hpr_solve_batch

        batch = hpr_solve_batch(g, cfg64, n_replicas=2, seed=0)
    finally:
        jax.config.update("jax_enable_x64", False)
    assert res.chi.dtype == np.float64
    assert res.biases.dtype == np.float64
    assert res.m_final == 1.0
    out = end_state(g, res.s, p=1, c=1, backend="cpu")
    assert np.all(out == 1)
    assert np.all((batch.m_final == 1.0) | (batch.m_final == 2.0))

    # f32 and f64 both solve the instance; trajectories may legitimately
    # diverge (reinforcement thresholds amplify rounding), which is exactly
    # why the axis exists
    cfg32 = HPRConfig(dynamics=DynamicsConfig(p=1, c=1), max_sweeps=3000)
    res32 = hpr_solve(g, cfg32, seed=0)
    assert res32.m_final == 1.0
