"""HPr solver tests: convergence to a consensus-flowing initialization on
small RRGs, reinforcement semantics, sentinel behavior."""

import numpy as np
import pytest

from graphdyn.config import DynamicsConfig, HPRConfig
from graphdyn.graphs import random_regular_graph
from graphdyn.models.hpr import hpr_solve
from graphdyn.ops.dynamics import end_state


def test_hpr_finds_consensus_flowing_init():
    g = random_regular_graph(60, 4, seed=1)
    cfg = HPRConfig(dynamics=DynamicsConfig(p=1, c=1), max_sweeps=3000)
    res = hpr_solve(g, cfg, seed=0)
    assert res.m_final == 1.0, f"did not converge in {res.num_steps} sweeps"
    out = end_state(g, res.s, p=1, c=1, backend="cpu")
    assert np.all(out == 1)
    # the point of HPr: a non-trivial (below-consensus) initialization
    assert res.mag_reached < 1.0
    assert res.num_steps >= 1


def test_hpr_timeout_sentinel():
    g = random_regular_graph(60, 4, seed=2)
    cfg = HPRConfig(max_sweeps=2)
    res = hpr_solve(g, cfg, seed=5)
    assert res.m_final in (1.0, 2.0)
    if res.m_final == 2.0:
        assert res.num_steps == 3  # t incremented past TT


def test_hpr_biases_polarized_after_convergence():
    g = random_regular_graph(40, 4, seed=3)
    cfg = HPRConfig(max_sweeps=3000)
    res = hpr_solve(g, cfg, seed=1)
    if res.m_final == 1.0:
        # reinforced biases are at (pie, 1-pie) or (1-pie, pie) rows
        b = res.biases
        polarized = np.isclose(b.max(axis=1), 1 - cfg.pie, atol=1e-5)
        assert polarized.mean() > 0.9
        np.testing.assert_allclose(b.sum(axis=1), 1.0, atol=1e-5)


def test_hpr_seed_reproducible():
    g = random_regular_graph(40, 4, seed=4)
    cfg = HPRConfig(max_sweeps=500)
    r1 = hpr_solve(g, cfg, seed=7)
    r2 = hpr_solve(g, cfg, seed=7)
    assert r1.num_steps == r2.num_steps
    np.testing.assert_array_equal(r1.s, r2.s)


def test_hpr_ensemble_driver(tmp_path):
    """Reference npz keys incl. wall-clock `time` (`HPR_pytorch_RRG.py:377`)."""
    from graphdyn.models.hpr import hpr_ensemble
    from graphdyn.utils.io import load_results_npz

    p = str(tmp_path / "hpr.npz")
    cfg = HPRConfig(max_sweeps=2000)
    out = hpr_ensemble(40, 4, cfg, n_rep=2, seed=0, save_path=p)
    assert out.conf.shape == (2, 40)
    assert np.all(out.time > 0)
    saved = load_results_npz(p)
    assert set(saved) == {"mag_reached", "conf", "num_steps", "graphs", "time"}


def test_hpr_batch_chains_converge():
    """Batched chains converge and report per-chain sentinels; converged
    trial solutions really flow to consensus; chains are independent."""
    from graphdyn.models.hpr import hpr_solve_batch

    g = random_regular_graph(40, 4, seed=5)
    cfg = HPRConfig(max_sweeps=3000)
    res = hpr_solve_batch(g, cfg, n_replicas=4, seed=2)
    assert res.s.shape == (4, 40)
    assert np.all((res.m_final == 1.0) | (res.m_final == 2.0))
    assert (res.m_final == 1.0).sum() >= 3      # most chains find consensus
    # converged chains really flow to all-+1 under the rollout
    from graphdyn.ops.dynamics import end_state
    for r in range(4):
        if res.m_final[r] == 1.0:
            out = np.asarray(end_state(g, res.s[r], 1, 1, backend="cpu"))
            assert np.all(out == 1)
    # per-chain step counts vary (chains are independent streams)
    assert len(set(res.num_steps.tolist())) > 1


def test_hpr_batch_sharded_replicas():
    """Replica-sharded batched HPr over the 8-device CPU mesh."""
    from graphdyn.models.hpr import hpr_solve_batch
    from graphdyn.parallel.mesh import device_pool, make_mesh

    g = random_regular_graph(30, 3, seed=1)
    mesh = make_mesh((8,), ("replica",), devices=device_pool(8))
    cfg = HPRConfig(max_sweeps=2000)
    res = hpr_solve_batch(g, cfg, n_replicas=8, seed=0, mesh=mesh)
    assert res.s.shape == (8, 30)
    assert np.all((res.m_final == 1.0) | (res.m_final == 2.0))


def test_hpr_checkpoint_resume_bit_exact(tmp_path, abort_after_save):
    """Chunked+checkpointed HPr equals the uninterrupted chain bit-for-bit,
    and resuming from a kept mid-flight checkpoint finishes identically
    (SURVEY.md §5.4 resume state: chi, biases, s, rng key, t)."""
    import os

    g = random_regular_graph(60, 4, seed=1)
    cfg = HPRConfig(dynamics=DynamicsConfig(p=1, c=1), max_sweeps=3000)
    base = hpr_solve(g, cfg, seed=0)

    p1 = str(tmp_path / "hpr_ck")
    chunked = hpr_solve(
        g, cfg, seed=0, checkpoint_path=p1,
        checkpoint_interval_s=0.0, chunk_sweeps=7,
    )
    assert chunked.num_steps == base.num_steps
    assert chunked.m_final == base.m_final
    np.testing.assert_array_equal(chunked.s, base.s)
    np.testing.assert_array_equal(chunked.biases, base.biases)
    np.testing.assert_array_equal(chunked.chi, base.chi)
    assert not os.path.exists(p1 + ".npz")      # removed on completion

    # mid-flight restart: force an abort after the first chunk by keeping the
    # checkpoint file, then resume from it
    from conftest import CheckpointAbort

    p2 = str(tmp_path / "hpr_ck2")
    with abort_after_save(n=1):
        with pytest.raises(CheckpointAbort):
            hpr_solve(g, cfg, seed=0, checkpoint_path=p2,
                      checkpoint_interval_s=0.0, chunk_sweeps=5)
    assert os.path.exists(p2 + ".npz")          # a mid-flight snapshot exists

    resumed = hpr_solve(g, cfg, seed=0, checkpoint_path=p2,
                        checkpoint_interval_s=1e9, chunk_sweeps=50)
    assert resumed.num_steps == base.num_steps
    assert resumed.m_final == base.m_final
    np.testing.assert_array_equal(resumed.s, base.s)
    np.testing.assert_array_equal(resumed.chi, base.chi)


def test_hpr_ensemble_driver_resume(tmp_path, abort_after_save):
    """Driver-level resume (completed reps kept, graphs re-derived) mirrors
    sa_ensemble's; abort lands between repetitions."""
    import os

    from conftest import CheckpointAbort
    from graphdyn.models.hpr import hpr_ensemble

    cfg = HPRConfig(dynamics=DynamicsConfig(p=1, c=1), max_sweeps=3000)
    kw = dict(n_rep=2, seed=1)
    base = hpr_ensemble(50, 4, cfg, **kw)

    p = str(tmp_path / "hpr_grid")
    with abort_after_save(when=lambda meta: meta.get("next_rep") == 1):
        with pytest.raises(CheckpointAbort):
            hpr_ensemble(50, 4, cfg, checkpoint_path=p, **kw)
    assert os.path.exists(p + ".npz")

    resumed = hpr_ensemble(50, 4, cfg, checkpoint_path=p, **kw)
    np.testing.assert_array_equal(base.conf, resumed.conf)
    np.testing.assert_array_equal(base.num_steps, resumed.num_steps)
    np.testing.assert_array_equal(base.graphs, resumed.graphs)
    assert not os.path.exists(p + ".npz")


def test_hpr_batch_checkpoint_resume_bit_exact(tmp_path, abort_after_save):
    """Chunked+checkpointed batch solver equals the uninterrupted run
    bit-for-bit; a kept mid-flight snapshot resumes identically; foreign
    checkpoints are refused."""
    import os

    from conftest import CheckpointAbort
    from graphdyn.models.hpr import hpr_solve_batch

    g = random_regular_graph(40, 4, seed=5)
    cfg = HPRConfig(max_sweeps=3000)
    base = hpr_solve_batch(g, cfg, n_replicas=4, seed=2)

    p1 = str(tmp_path / "hb_ck")
    chunked = hpr_solve_batch(
        g, cfg, n_replicas=4, seed=2, checkpoint_path=p1,
        checkpoint_interval_s=0.0, chunk_sweeps=9,
    )
    np.testing.assert_array_equal(base.s, chunked.s)
    np.testing.assert_array_equal(base.num_steps, chunked.num_steps)
    np.testing.assert_array_equal(base.m_final, chunked.m_final)
    assert not os.path.exists(p1 + ".npz")

    p2 = str(tmp_path / "hb_ck2")
    with abort_after_save(n=1):
        with pytest.raises(CheckpointAbort):
            hpr_solve_batch(g, cfg, n_replicas=4, seed=2, checkpoint_path=p2,
                            checkpoint_interval_s=0.0, chunk_sweeps=7)
    assert os.path.exists(p2 + ".npz")
    resumed = hpr_solve_batch(g, cfg, n_replicas=4, seed=2,
                              checkpoint_path=p2, chunk_sweeps=50)
    np.testing.assert_array_equal(base.s, resumed.s)
    np.testing.assert_array_equal(base.num_steps, resumed.num_steps)

    # wrong replica count: refused (R is part of the fingerprint)
    with abort_after_save(n=1):
        with pytest.raises(CheckpointAbort):
            hpr_solve_batch(g, cfg, n_replicas=4, seed=2, checkpoint_path=p2,
                            checkpoint_interval_s=0.0, chunk_sweeps=7)
    with pytest.raises(ValueError, match="refusing to resume"):
        hpr_solve_batch(g, cfg, n_replicas=5, seed=2, checkpoint_path=p2)


def test_hpr_batch_mesh_checkpoint_resume(tmp_path, abort_after_save):
    """Checkpointing composes with replica-mesh sharding: snapshots gather
    the sharded state to host, and a resumed run re-places it on the mesh
    with identical results (the config-2 preemption scenario)."""
    import os

    from conftest import CheckpointAbort
    from graphdyn.models.hpr import hpr_solve_batch
    from graphdyn.parallel.mesh import device_pool, make_mesh

    g = random_regular_graph(30, 3, seed=1)
    mesh = make_mesh((8,), ("replica",), devices=device_pool(8))
    cfg = HPRConfig(max_sweeps=2000)
    base = hpr_solve_batch(g, cfg, n_replicas=8, seed=0, mesh=mesh)

    p = str(tmp_path / "hbm_ck")
    with abort_after_save(n=1):
        with pytest.raises(CheckpointAbort):
            hpr_solve_batch(g, cfg, n_replicas=8, seed=0, mesh=mesh,
                            checkpoint_path=p, checkpoint_interval_s=0.0,
                            chunk_sweeps=5)
    assert os.path.exists(p + ".npz")
    resumed = hpr_solve_batch(g, cfg, n_replicas=8, seed=0, mesh=mesh,
                              checkpoint_path=p, chunk_sweeps=50)
    np.testing.assert_array_equal(base.s, resumed.s)
    np.testing.assert_array_equal(base.num_steps, resumed.num_steps)
    np.testing.assert_array_equal(base.m_final, resumed.m_final)
    assert not os.path.exists(p + ".npz")
