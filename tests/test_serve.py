"""graphdyn.serve: the durable spool state machine, byte-model admission,
shape-class bucketing, and the worker's evict/requeue/quarantine ladder.

The whole module carries the ``serve`` marker so ``scripts/lint.sh``'s
servecheck step can run it standalone (``pytest -m serve``); the fault-site
tests additionally carry ``faultinject`` so faultcheck sees the new
``serve.admit`` / ``serve.dispatch`` sites. The restarted-server recovery
regression (acceptance: a fresh process against an existing spool recovers
every pending job from disk alone) runs as a real subprocess.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from graphdyn.resilience.faults import FaultPlan, FaultSpec
from graphdyn.resilience.store import JOURNAL_NAME, validate_journal
from graphdyn.serve.admission import admit, chi_bound, device_budget_bytes
from graphdyn.serve.bucketing import BucketCache, graph_key, shape_key
from graphdyn.serve.spool import (
    DONE,
    PENDING,
    QUARANTINED,
    REFUSED,
    RUNNING,
    Spool,
    normalize_spec,
)
from graphdyn.serve.worker import Worker

pytestmark = pytest.mark.serve

SMALL = {"n": 24, "d": 3, "max_sweeps": 16, "chunk_sweeps": 8}


def _ops(root):
    events, problems = validate_journal(os.path.join(root, JOURNAL_NAME))
    assert not problems, problems
    return [e["op"] for e in events if e.get("ev") == "journal"]


# ---------------------------------------------------------------------------
# spool: the durable state machine
# ---------------------------------------------------------------------------


def test_normalize_spec_fills_defaults_and_rejects_unknown():
    spec = normalize_spec({"n": 10})
    assert spec["n"] == 10 and spec["d"] == 3 and spec["solver"] == "fused"
    with pytest.raises(ValueError, match="unknown job spec key"):
        normalize_spec({"banana": 1})


def test_spool_submit_claim_finish_roundtrip(tmp_path):
    sp = Spool(str(tmp_path))
    jid = sp.submit(dict(SMALL), "alice")
    assert sp.load(jid)["state"] == PENDING
    rec = sp.claim()
    assert rec["id"] == jid and sp.load(jid)["state"] == RUNNING
    sp.finish(jid)
    assert sp.load(jid)["state"] == DONE
    assert sp.claim() is None
    ops = _ops(str(tmp_path))
    assert ops == ["serve.submit", "serve.done"]


def test_spool_claim_order_is_submit_order(tmp_path):
    sp = Spool(str(tmp_path))
    ids = [sp.submit(dict(SMALL), t) for t in ("b", "a", "c")]
    claimed = [sp.claim()["id"] for _ in ids]
    assert claimed == ids


def test_spool_requeue_bumps_and_journals_reason(tmp_path):
    sp = Spool(str(tmp_path))
    jid = sp.submit(dict(SMALL), "alice")
    sp.claim()
    rec = sp.requeue(jid, "preempted mid-run")
    assert rec["state"] == PENDING and rec["requeues"] == 1
    assert rec["crashes"] == 0
    rec = sp.claim()
    sp.requeue(jid, "crashed", crashed=True)
    assert sp.load(jid)["crashes"] == 1
    events, _ = validate_journal(os.path.join(str(tmp_path), JOURNAL_NAME))
    requeues = [e for e in events if e.get("op") == "serve.requeue"]
    assert [e["requeues"] for e in requeues] == [1, 2]
    assert requeues[0]["reason"] == "preempted mid-run"


def test_spool_recover_requeues_only_running(tmp_path):
    """The restart contract: a killed worker's claimed job goes back to
    pending; settled and queued jobs are untouched."""
    sp = Spool(str(tmp_path))
    j_run = sp.submit(dict(SMALL), "alice")
    j_pend = sp.submit(dict(SMALL), "bob")
    j_done = sp.submit(dict(SMALL), "carol")
    sp.claim()                                   # j_run -> running
    for _ in range(2):
        sp.claim()
    sp.requeue(j_pend, "back to queue")
    sp.finish(j_done)
    assert Spool(str(tmp_path)).recover() == [j_run]
    assert sp.load(j_run)["state"] == PENDING
    assert sp.load(j_run)["requeues"] == 1
    assert sp.load(j_done)["state"] == DONE


def test_spool_records_survive_process_restart_subprocess(tmp_path):
    """ACCEPTANCE: a fresh PROCESS against an existing spool recovers every
    pending job from disk alone — no shared memory, no live server."""
    sp = Spool(str(tmp_path))
    ids = [sp.submit(dict(SMALL), "alice") for _ in range(3)]
    sp.claim()                                   # orphan one as running
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = "\n".join([
        "import sys, json",
        f"sys.path.insert(0, {repo!r})",
        "from graphdyn.serve.spool import Spool",
        f"sp = Spool({str(tmp_path)!r})",
        "recovered = sp.recover()",
        "print(json.dumps({'recovered': recovered,",
        "                  'counts': sp.counts()}))",
    ])
    proc = subprocess.run([sys.executable, "-c", script],
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["recovered"] == [ids[0]]
    assert out["counts"]["pending"] == 3
    assert out["counts"]["running"] == 0


# ---------------------------------------------------------------------------
# admission: the committed byte models
# ---------------------------------------------------------------------------


def test_admission_admits_small_shape_on_fused_kernel():
    d = admit(normalize_spec(dict(SMALL)))
    assert d.admitted and d.kernel == "auto" and d.reason is None
    assert 0 < d.model_bytes <= d.budget_bytes


def test_admission_refuses_oversized_with_byte_model_reason(monkeypatch):
    monkeypatch.setenv("GRAPHDYN_SERVE_HBM_BUDGET", str(1 << 30))
    d = admit(normalize_spec({"n": 200000, "d": 3, "replicas": 4096}))
    assert not d.admitted
    assert "exceeds the device budget" in d.reason
    assert str(d.model_bytes) in d.reason        # the numbers are IN the
    assert str(d.budget_bytes) in d.reason       # refusal, not a log file
    assert d.model_bytes > d.budget_bytes


def test_admission_env_budget_override(monkeypatch):
    monkeypatch.setenv("GRAPHDYN_SERVE_HBM_BUDGET", "12345")
    assert device_budget_bytes() == 12345
    assert not admit(normalize_spec(dict(SMALL))).admitted


def test_admission_mid_size_degrades_to_xla_twin(monkeypatch):
    """A shape whose model exceeds the Pallas VMEM budget but fits the
    device budget is ADMITTED on the XLA twin — the degrade moves
    throughput, never the verdict."""
    from graphdyn.ops.pallas_anneal import FUSED_VMEM_BUDGET, fused_vmem_bytes

    monkeypatch.setenv("GRAPHDYN_SERVE_HBM_BUDGET", str(1 << 30))
    spec = normalize_spec({"n": 20000, "d": 3, "replicas": 512})
    model = fused_vmem_bytes(20000, 16, chi_bound(3), 3)
    assert FUSED_VMEM_BUDGET < model <= (1 << 30)   # the premise
    d = admit(spec)
    assert d.admitted and d.kernel == "xla"


def test_admission_malformed_is_refusal_not_crash():
    for spec in ({"n": 1, "d": 3}, {"n": 24, "d": 0}, {"n": 4, "d": 4},
                 {"n": 24, "d": 3, "replicas": 0}):
        d = admit(normalize_spec(spec))
        assert not d.admitted and "malformed" in d.reason
    d = admit({**normalize_spec(dict(SMALL)), "solver": "bdcm"})
    assert not d.admitted and "unknown solver" in d.reason


@pytest.mark.faultinject
def test_admission_reject_storm_fault_site():
    """serve.admit 'raise' = the injected reject storm: admission stays up
    but refuses with an 'injected' reason — a worker crash would be the
    bug."""
    with FaultPlan([FaultSpec("serve.admit", action="raise", at=1,
                              count=2)]):
        d = admit(normalize_spec(dict(SMALL)))
        assert not d.admitted
        assert "injected reject storm" in d.reason
        d = admit(normalize_spec(dict(SMALL)))
        assert not d.admitted
    assert admit(normalize_spec(dict(SMALL))).admitted   # storm over


@pytest.mark.faultinject
def test_dispatch_transient_fault_retried_then_requeued(tmp_path):
    """serve.dispatch 'raise' is transient unavailability: one blip is
    absorbed by the seeded-backoff retry (job still finishes); a hard
    storm exhausts the budget and REQUEUES the job — the server survives
    either way."""
    from graphdyn.resilience.retry import RetryPolicy

    sp = Spool(str(tmp_path))
    jid = sp.submit(dict(SMALL), "alice")
    w = Worker(sp, retry=RetryPolicy(tries=3, base_delay_s=0.001,
                                     max_delay_s=0.002, jitter=True))
    with FaultPlan([FaultSpec("serve.dispatch", action="raise", at=1,
                              count=1)]):
        w.run_until_drained()
    assert sp.load(jid)["state"] == DONE

    jid2 = sp.submit(dict(SMALL), "bob")
    with FaultPlan([FaultSpec("serve.dispatch", action="raise", at=1,
                              count=99)]):
        assert w.step()                          # settles by requeueing
    rec = sp.load(jid2)
    assert rec["state"] == PENDING and rec["requeues"] == 1
    assert "dispatch retries exhausted" in rec["reason"]
    w.run_until_drained()                        # plan gone: finishes
    assert sp.load(jid2)["state"] == DONE


# ---------------------------------------------------------------------------
# bucketing
# ---------------------------------------------------------------------------


def test_shape_key_packs_replicas_and_drops_seeds():
    a = normalize_spec({**SMALL, "replicas": 1, "seed": 1, "graph_seed": 7})
    b = normalize_spec({**SMALL, "replicas": 32, "seed": 2, "graph_seed": 9})
    assert shape_key(a) == shape_key(b)          # same W=1 word
    assert graph_key(a) != graph_key(b)          # different graphs
    c = normalize_spec({**SMALL, "replicas": 33})
    assert shape_key(c) != shape_key(a)          # W=2


def test_bucket_cache_hits_and_eviction(tmp_path):
    cache = BucketCache(max_graphs=2)
    s0 = normalize_spec({**SMALL, "graph_seed": 0})
    s1 = normalize_spec({**SMALL, "graph_seed": 1})
    s2 = normalize_spec({**SMALL, "graph_seed": 2})
    g0a = cache.tables_for(s0)
    g0b = cache.tables_for(s0)
    assert g0a is g0b                            # the hit IS reuse
    cache.tables_for(s1)
    cache.tables_for(s2)                         # evicts s0 (oldest)
    st = cache.stats()
    assert st == {"hits": 1, "misses": 3, "hit_rate": 0.25,
                  "resident_graphs": 2}
    assert cache.tables_for(s0) is not g0a       # rebuilt after eviction


def test_bucket_tables_seeded_by_graph_not_job(tmp_path):
    """The soak-found invariant: the coloring inside the shared tables is
    the GRAPH's (graph_seed), so a served result cannot depend on which
    tenant's chain seed built the cache entry."""
    from graphdyn.serve.worker import Worker

    results = {}
    for order in ((3, 9), (9, 3)):               # build order swapped
        sp = Spool(str(tmp_path / f"order{order[0]}"))
        for s in order:
            sp.submit({**SMALL, "seed": s}, "t")
        Worker(sp).run_until_drained()
        for rec in sp.jobs():
            key = rec["spec"]["seed"]
            arr = np.load(rec["result"])["conf"]
            results.setdefault(key, []).append(arr)
    for key, (a, b) in results.items():
        assert np.array_equal(a, b), f"seed {key} depends on build order"


def test_bucket_warm_probes_hot_classes(tmp_path):
    cache = BucketCache()
    specs = [normalize_spec({**SMALL, "seed": i}) for i in range(3)]
    specs.append(normalize_spec({**SMALL, "n": 30, "seed": 9}))
    warmed = cache.warm(specs, top_k=1)
    assert warmed == [shape_key(specs[0])]       # the majority class
    assert cache.stats()["misses"] == 1          # probe built its tables


# ---------------------------------------------------------------------------
# worker ladder
# ---------------------------------------------------------------------------


def test_worker_drains_multi_tenant_queue_with_refusal(tmp_path,
                                                       monkeypatch):
    monkeypatch.setenv("GRAPHDYN_SERVE_HBM_BUDGET", str(1 << 30))
    sp = Spool(str(tmp_path))
    ok = [sp.submit({**SMALL, "seed": i}, t)
          for i, t in enumerate(("alice", "bob"))]
    bad = sp.submit({"n": 200000, "d": 3, "replicas": 4096}, "carol")
    assert Worker(sp).run_until_drained() == 3
    assert all(sp.load(j)["state"] == DONE for j in ok)
    rec = sp.load(bad)
    assert rec["state"] == REFUSED
    assert "exceeds the device budget" in rec["reason"]
    assert not os.path.exists(rec["result"])     # never reached the device
    ops = _ops(str(tmp_path))
    assert ops.count("serve.done") == 2 and ops.count("serve.refuse") == 1


def test_worker_timeout_evicts_then_escalates_to_done(tmp_path):
    """The eviction ladder: a 50 ms slice under a cold compile always
    evicts attempt 1 (journal serve.evict + a durable eviction checkpoint),
    escalation x4 finishes the replay, and the result is still written."""
    sp = Spool(str(tmp_path))
    jid = sp.submit({"n": 64, "d": 3, "rule": "minority", "max_sweeps": 256,
                     "chunk_sweeps": 2}, "tim", timeout_s=0.05)
    Worker(sp).run_until_drained()
    rec = sp.load(jid)
    assert rec["state"] == DONE and rec["requeues"] >= 1
    ops = _ops(str(tmp_path))
    assert ops.count("serve.evict") >= 1
    assert ops.count("serve.evict") == ops.count("serve.requeue")
    # the eviction evidence is durable: checkpoint + its own journal
    evict_dir = os.path.join(str(tmp_path), "evict")
    assert os.path.exists(os.path.join(evict_dir, jid + ".npz"))
    from graphdyn.resilience.shutdown import shutdown_requested

    assert not shutdown_requested()              # the flag was cleared


def test_worker_quarantines_poison_job_and_serves_on(tmp_path):
    """Crash containment: a spec that passes admission but crashes the
    solver is requeued once, quarantined at the bar — and the next
    tenant's job still runs."""
    sp = Spool(str(tmp_path))
    poison = sp.submit({**SMALL, "rule": "no-such-rule"}, "mallory")
    good = sp.submit(dict(SMALL), "alice")
    w = Worker(sp, quarantine_after=2)
    w.run_until_drained()
    rec = sp.load(poison)
    assert rec["state"] == QUARANTINED and rec["crashes"] == 1
    assert "crash(es) at serve.job:" in rec["reason"]
    assert sp.load(good)["state"] == DONE
    ops = _ops(str(tmp_path))
    assert "serve.quarantine" in ops


def test_worker_background_thread_face(tmp_path):
    """start()/stop(): the declared graphdyn-serve-worker thread drains
    submissions arriving while it runs, and stop() joins bounded."""
    import time

    sp = Spool(str(tmp_path))
    w = Worker(sp, poll_s=0.01).start()
    try:
        jid = sp.submit(dict(SMALL), "alice")
        deadline = time.monotonic() + 60.0
        while (sp.load(jid)["state"] != DONE
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert sp.load(jid)["state"] == DONE
    finally:
        w.stop(timeout_s=30.0)
    assert w._thread is None


def test_run_service_recovers_drains_and_exits_clean(tmp_path,
                                                     monkeypatch):
    """Boot order: recover the orphan, refuse the oversized, drain, exit
    0 on idle."""
    from graphdyn.serve.lifecycle import run_service

    monkeypatch.setenv("GRAPHDYN_SERVE_HBM_BUDGET", str(1 << 30))
    sp = Spool(str(tmp_path))
    orphan = sp.submit(dict(SMALL), "alice")
    sp.claim()                                   # killed worker's leftover
    sp.submit({"n": 200000, "d": 3, "replicas": 4096}, "carol")
    rc = run_service(str(tmp_path), idle_exit_s=0.1)
    assert rc == 0
    counts = sp.counts()
    assert counts[DONE] == 1 and counts[REFUSED] == 1
    assert sp.load(orphan)["requeues"] == 1
    events, problems = validate_journal(os.path.join(str(tmp_path),
                                                     JOURNAL_NAME))
    assert not problems, problems
    recovery = [e for e in events if e.get("op") == "serve.requeue"]
    assert any("recovered" in e["reason"] for e in recovery)


def test_serve_cli_submit_run_status_result(tmp_path, capsys):
    from graphdyn.cli import main

    root = str(tmp_path / "spool")
    assert main(["serve", "submit", "--root", root, "--tenant", "alice",
                 "--n", "24", "--max-sweeps", "16",
                 "--chunk-sweeps", "8"]) == 0
    jid = json.loads(capsys.readouterr().out.strip())["job"]
    assert main(["serve", "run", "--root", root, "--idle-exit", "0.1"]) == 0
    capsys.readouterr()
    assert main(["serve", "status", jid, "--root", root]) == 0
    assert json.loads(capsys.readouterr().out.strip())["state"] == DONE
    assert main(["serve", "result", jid, "--root", root]) == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["keys"] == ["conf", "m_end", "mag_reached",
                           "steps_to_target"]


# ---------------------------------------------------------------------------
# bucketed solver: the edge-proportional engine and its admission price
# ---------------------------------------------------------------------------

BUCKETED_SMALL = {"solver": "bucketed", "n": 24, "d": 2, "gamma": 2.5,
                  "max_sweeps": 8}


def test_normalize_spec_accepts_bucketed_solver_fields():
    spec = normalize_spec(
        {"solver": "bucketed", "n": 10, "edges": 40, "gamma": 2.2})
    assert spec["solver"] == "bucketed"
    assert spec["edges"] == 40 and spec["gamma"] == 2.2
    # the fused default: no declaration
    spec = normalize_spec({"n": 10})
    assert spec["solver"] == "fused" and spec["edges"] is None


def test_admission_bucketed_solver_prices_by_edges():
    from graphdyn.obs.memband import (
        bucketed_state_bytes,
        bucketed_table_entries_bound,
    )
    from graphdyn.ops.packed import WORD

    n, E, R = 50_000, 120_000, 64
    spec = normalize_spec(
        {"solver": "bucketed", "n": n, "d": 2, "replicas": R, "edges": E})
    d = admit(spec)
    assert d.admitted and d.kernel == "bucketed" and d.reason is None
    W = -(-R // WORD)
    assert d.model_bytes == bucketed_state_bytes(
        n, W, bucketed_table_entries_bound(n, E))
    assert d.model_bytes <= d.budget_bytes


def test_admission_fused_price_immune_to_declarations():
    """Regression for the under-pricing hole: a fused job whose padded
    model exceeds the budget STAYS refused no matter what edge count or
    degree CV it declares — the fused annealer's tables are
    padded-dmax/chi-bound under any node labeling, so a declaration that
    discounted the price would admit a job whose real resident set OOMs
    the shared worker. The same shape IS servable, but only on the
    engine whose memory the edge model describes (solver='bucketed')."""
    base = {"n": 50_000, "d": 900, "replicas": 64}
    refused = admit(normalize_spec(dict(base)))
    assert not refused.admitted
    assert "exceeds the device budget" in refused.reason
    declared = admit(normalize_spec(
        {**base, "edges": 120_000, "degree_cv": 3.2}))
    assert not declared.admitted
    assert declared.model_bytes == refused.model_bytes
    rerouted = admit(normalize_spec(
        {"solver": "bucketed", "n": 50_000, "d": 2, "replicas": 64,
         "edges": 120_000}))
    assert rerouted.admitted and rerouted.kernel == "bucketed"
    assert rerouted.model_bytes < refused.model_bytes


def test_admission_fused_declarations_inert():
    """Declarations never perturb a fused job's price or kernel choice."""
    spec = normalize_spec({**SMALL, "edges": 36, "degree_cv": 2.0})
    d = admit(spec)
    assert d.admitted and d.kernel == "auto"
    assert d.model_bytes == admit(normalize_spec(dict(SMALL))).model_bytes


def test_admission_bucketed_malformed_or_missing_edges_refused():
    d = admit(normalize_spec({**BUCKETED_SMALL, "edges": -5}))
    assert not d.admitted and "malformed" in d.reason
    d = admit(normalize_spec({**BUCKETED_SMALL, "edges": 10_000}))
    assert not d.admitted and "malformed" in d.reason   # > n(n-1)/2
    d = admit(normalize_spec(dict(BUCKETED_SMALL)))
    assert not d.admitted and "declared edge count" in d.reason


def test_worker_runs_bucketed_job_end_to_end(tmp_path):
    """A bucketed-solver job settles DONE through the worker: the server
    builds the power-law graph + degree-bucket layout, the declaration
    validates against the real table, and the bucketed rollout's result
    lands in the durable store."""
    from graphdyn.graphs import powerlaw_graph

    g = powerlaw_graph(24, gamma=2.5, dmin=2, seed=0)
    E = int(g.edges.shape[0])
    spool = Spool(str(tmp_path / "serve"))
    job = spool.submit(
        {**BUCKETED_SMALL, "edges": E, "replicas": 32}, tenant="t1")
    assert Worker(spool).run_until_drained() == 1
    rec = spool.load(job)
    assert rec["state"] == DONE, rec
    out = np.load(rec["result"])
    assert out["conf"].shape == (32, 24)
    assert set(np.unique(out["conf"])) <= {-1, 1}
    assert np.allclose(out["m_end"],
                       out["conf"].astype(np.float64).mean(axis=1))


def test_worker_refuses_underdeclared_bucketed_job(tmp_path):
    """The validation rung: a declaration small enough to pass admission
    but below the built graph's real table is refused by the worker
    before dispatch — the admitted byte model must cover what runs."""
    spool = Spool(str(tmp_path / "serve"))
    job = spool.submit(
        {**BUCKETED_SMALL, "edges": 1, "replicas": 32}, tenant="t1")
    assert Worker(spool).run_until_drained() == 1
    rec = spool.load(job)
    assert rec["state"] == REFUSED, rec
    assert "under-priced" in rec["reason"]


def test_streamed_twin_admitted_where_resident_twin_refused(tmp_path,
                                                            monkeypatch):
    """The ISSUE-19 admission story: under a clamped device budget the
    SAME declared graph shape is refused on the resident bucketed engine
    (modeled bytes in the reason) but admitted as ``solver='streamed'``
    and settles DONE through the worker — the out-of-core route deletes
    the device-memory cliff instead of re-pricing it."""
    from graphdyn.graphs import powerlaw_graph
    from graphdyn.obs.memband import (
        bucketed_state_bytes,
        bucketed_table_entries_bound,
        streamed_min_bytes,
    )

    n = 512
    g = powerlaw_graph(n, gamma=2.5, dmin=2, seed=0)
    E, dmax = int(g.edges.shape[0]), int(g.deg.max())
    resident = bucketed_state_bytes(n, 1, bucketed_table_entries_bound(n, E))
    budget = max(3 * resident // 4, 4 * streamed_min_bytes(dmax, 1))
    assert budget < resident                     # the clamp actually bites
    monkeypatch.setenv("GRAPHDYN_SERVE_HBM_BUDGET", str(budget))

    shape = {"n": n, "d": 2, "gamma": 2.5, "edges": E, "replicas": 32,
             "max_sweeps": 4}
    refused = admit(normalize_spec({**shape, "solver": "bucketed"}))
    assert not refused.admitted
    assert f"{refused.model_bytes} B" in refused.reason
    assert refused.model_bytes == resident > budget

    admitted = admit(normalize_spec(
        {**shape, "solver": "streamed", "dmax": dmax}))
    assert admitted.admitted and admitted.kernel == "streamed"
    assert admitted.model_bytes <= budget

    spool = Spool(str(tmp_path / "serve"))
    bad = spool.submit({**shape, "solver": "bucketed"}, tenant="t1")
    good = spool.submit({**shape, "solver": "streamed", "dmax": dmax},
                        tenant="t1")
    assert Worker(spool).run_until_drained() == 2
    rec_bad = spool.load(bad)
    assert rec_bad["state"] == REFUSED, rec_bad
    assert f"{resident} B" in rec_bad["reason"]
    rec_good = spool.load(good)
    assert rec_good["state"] == DONE, rec_good
    out = np.load(rec_good["result"])
    assert out["conf"].shape == (32, n)
    assert set(np.unique(out["conf"])) <= {-1, 1}
    assert int(out["chunks"]) >= 2               # it really streamed


def test_sharded_streamed_job_end_to_end(tmp_path, monkeypatch):
    """The ISSUE-20 serve story: a ``solver='streamed'`` job declaring
    ``shards`` is priced by the PER-SHARD byte model (the admission
    frontier scales ~S×: the sharded declaration admits under a budget
    the single-shard model refuses), the worker runs the sharded
    composition, and the result is bit-identical to the same job run
    unsharded — plus the refusal rungs: malformed shards, and a shard
    count beyond the worker's devices."""
    from graphdyn.graphs import powerlaw_graph
    from graphdyn.obs.memband import streamed_state_bytes

    n = 512
    g = powerlaw_graph(n, gamma=2.5, dmin=2, seed=0)
    E, dmax = int(g.edges.shape[0]), int(g.deg.max())
    shape = {"n": n, "d": 2, "gamma": 2.5, "edges": E, "dmax": dmax,
             "replicas": 32, "max_sweeps": 4, "solver": "streamed"}

    one = admit(normalize_spec(shape))
    two = admit(normalize_spec({**shape, "shards": 2}))
    assert one.admitted and two.admitted
    # the per-shard model prices ~n/S nodes and ~edges/S adjacency
    assert two.model_bytes < one.model_bytes
    assert two.model_bytes == streamed_state_bytes(
        -(-n // 2), 1, -(-E // 2),
        __import__("graphdyn.obs.memband", fromlist=["streamed_chunk_count"]
                   ).streamed_chunk_count(
            -(-n // 2), 1, -(-E // 2), two.budget_bytes))

    # refusal rungs (admission, before any spool traffic)
    assert not admit(normalize_spec({**shape, "shards": 0})).admitted
    assert not admit(normalize_spec({**shape, "shards": "many"})).admitted
    over = admit(normalize_spec({**shape, "shards": 99}))
    assert not over.admitted and "devices" in over.reason

    spool = Spool(str(tmp_path / "serve"))
    solo = spool.submit(shape, tenant="t1")
    duo = spool.submit({**shape, "shards": 2}, tenant="t1")
    assert Worker(spool).run_until_drained() == 2
    rec_solo, rec_duo = spool.load(solo), spool.load(duo)
    assert rec_solo["state"] == DONE, rec_solo
    assert rec_duo["state"] == DONE, rec_duo
    out_solo = np.load(rec_solo["result"])
    out_duo = np.load(rec_duo["result"])
    assert int(out_duo["shards"]) == 2
    # the sharded engine is bit-exact: same spec -> identical spins
    np.testing.assert_array_equal(out_duo["conf"], out_solo["conf"])


def test_worker_refuses_streamed_shards_beyond_devices(tmp_path):
    """A shards declaration that slipped past admission (e.g. admitted on
    a bigger host) is re-validated by the worker against ITS device count
    and refused before any device work."""
    from unittest import mock

    from graphdyn.graphs import powerlaw_graph

    n = 128
    g = powerlaw_graph(n, gamma=2.5, dmin=2, seed=0)
    spec = {"n": n, "d": 2, "gamma": 2.5,
            "edges": int(g.edges.shape[0]), "dmax": int(g.deg.max()),
            "replicas": 32,
            "max_sweeps": 4, "solver": "streamed", "shards": 2}
    spool = Spool(str(tmp_path / "serve"))
    job = spool.submit(spec, tenant="t1")
    with mock.patch("jax.devices", return_value=[object()]):
        assert Worker(spool).run_until_drained() == 1
    rec = spool.load(job)
    assert rec["state"] == REFUSED, rec
    assert "devices" in rec["reason"]
