"""Degree-bucketed layout + bucketed rollout (ROADMAP item 3): the
power-law fast path.

The contract: ``bucketed_rollout`` is **bit-exact** to the padded
``packed_rollout`` on every graph (ragged ER and seeded power-law, both
routes, the full rule/tie matrix) modulo the bucket permutation; the
layout's table is edge-count proportional where the padded table is
``n·dmax``; the degree-CV predicate routes the ``sa``/``fused`` drivers
automatically; and the measured bucketed rate on a seeded power-law
(hub degree ≥ 1e3) stays within 4× of the equal-edge RRG padded rate —
the acceptance criterion the ``powerlaw_rate`` bench row records.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from graphdyn.config import DynamicsConfig, SAConfig
from graphdyn.graphs import (
    degree_buckets,
    degree_cv,
    erdos_renyi_graph,
    powerlaw_graph,
    random_regular_graph,
)
from graphdyn.ops.bucketed import (
    BUCKETED_CV_THRESHOLD,
    UNROLL_MAX,
    auto_layout,
    bucketed_rollout,
    bucketed_rollout_global,
    lower_bucketed_rollout,
)
from graphdyn.ops.packed import pack_spins, packed_rollout


def _packed_spins(g, R=64, seed=0):
    rng = np.random.default_rng(seed)
    s = (2 * rng.integers(0, 2, size=(R, g.n)) - 1).astype(np.int8)
    return np.asarray(pack_spins(s))


# ---------------------------------------------------------------------------
# the oracle: bit-parity with the padded kernel
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("rule", ["majority", "minority"])
@pytest.mark.parametrize("tie", ["stay", "change"])
def test_bucketed_bit_exact_vs_padded(rule, tie):
    """Both routes equal the padded program bitwise on a ragged ER and a
    seeded power-law whose hub bucket takes the wide (arithmetic-count)
    path — any divergence is a layout/packing bug, not roundoff."""
    er = erdos_renyi_graph(200, 4.0 / 199, seed=3)
    pl = powerlaw_graph(600, gamma=2.3, dmin=2, seed=7)
    assert pl.dmax > UNROLL_MAX          # the wide path IS exercised
    for g in (er, pl):
        sp = _packed_spins(g)
        ref = np.asarray(packed_rollout(
            jnp.asarray(g.nbr), jnp.asarray(g.deg), jnp.asarray(sp), 6,
            rule, tie,
        ))
        for route in ("comparator", "lut"):
            got = bucketed_rollout_global(g, sp, 6, rule, tie, route)
            np.testing.assert_array_equal(
                got, ref, err_msg=f"n={g.n} route={route}"
            )


def test_bucketed_steps_zero_and_route_validation():
    g = powerlaw_graph(120, gamma=2.3, dmin=2, seed=1)
    b = degree_buckets(g)
    sp = _packed_spins(g, R=32)[b.order]
    out = np.asarray(bucketed_rollout(b, sp.copy(), 0))
    np.testing.assert_array_equal(out, sp)
    with pytest.raises(ValueError, match="route"):
        bucketed_rollout(b, sp.copy(), 2, route="nope")


def test_bucketed_global_wrapper_preserves_order():
    """The order-preserving wrapper returns caller-labeled rows: one step
    of an all-up state on a star graph flips exactly per the rule, in the
    ORIGINAL labeling."""
    g = powerlaw_graph(300, gamma=2.5, dmin=2, seed=9)
    b = degree_buckets(g)
    sp = _packed_spins(g, R=32, seed=4)
    ref = np.asarray(packed_rollout(
        jnp.asarray(g.nbr), jnp.asarray(g.deg), jnp.asarray(sp), 3,
    ))
    got = bucketed_rollout_global(g, sp, 3, buckets=b)
    np.testing.assert_array_equal(got, ref)


# ---------------------------------------------------------------------------
# routing predicate + lowering surface
# ---------------------------------------------------------------------------


def test_auto_layout_routing():
    rrg = random_regular_graph(64, 3, seed=0)
    assert degree_cv(rrg.deg) == pytest.approx(0.0)
    assert auto_layout(rrg.deg) == "padded"
    pl = powerlaw_graph(2000, gamma=2.3, dmin=2, seed=1)
    assert degree_cv(pl.deg) >= BUCKETED_CV_THRESHOLD
    assert auto_layout(pl.deg) == "bucketed"
    # the threshold is the one knob: force either verdict through it
    assert auto_layout(rrg.deg, threshold=0.0) == "bucketed"
    assert auto_layout(pl.deg, threshold=float("inf")) == "padded"


def test_lower_bucketed_rollout_surface():
    """The graftcheck-fingerprinted surface lowers without executing and
    names ONE while loop (the step loop — the one-program contract: no
    per-bucket dispatch, no per-slot loop)."""
    g = powerlaw_graph(256, gamma=2.5, dmin=2, seed=0)
    b = degree_buckets(g)
    txt = lower_bucketed_rollout(b, W=2, steps=3).as_text()
    assert txt.count("while(") == 1


# ---------------------------------------------------------------------------
# driver layout knobs (sa / fused)
# ---------------------------------------------------------------------------


def _sa_cfg():
    return SAConfig(dynamics=DynamicsConfig(p=1, c=1))


def test_sa_layout_knob_auto_routes_and_is_deterministic():
    from graphdyn.models.sa import simulated_annealing

    g = powerlaw_graph(150, gamma=2.3, dmin=2, seed=5)
    assert auto_layout(g.deg) == "bucketed"   # auto picks the fast path
    kw = dict(n_replicas=3, seed=0, max_steps=40)
    a = simulated_annealing(g, _sa_cfg(), layout="auto", **kw)
    b = simulated_annealing(g, _sa_cfg(), layout="bucketed", **kw)
    assert a.s.shape == b.s.shape == (3, g.n)
    np.testing.assert_array_equal(a.s, b.s)   # auto == explicit bucketed
    assert set(np.unique(a.s)) <= {-1, 1}
    p = simulated_annealing(g, _sa_cfg(), layout="padded", **kw)
    assert p.s.shape == (3, g.n)              # padded still runs


def test_sa_layout_knob_refusals():
    from graphdyn.models.sa import simulated_annealing

    g = powerlaw_graph(80, gamma=2.3, dmin=2, seed=5)
    with pytest.raises(ValueError, match="layout"):
        simulated_annealing(g, _sa_cfg(), layout="nope", max_steps=4)
    # node-indexed injected streams pin the caller's labeling
    props = np.zeros((1, 2), np.int32)
    with pytest.raises(ValueError, match="proposals"):
        simulated_annealing(
            g, _sa_cfg(), layout="bucketed", proposals=props,
            uniforms=np.zeros((1, 2)), max_steps=2,
        )


def test_sa_auto_layout_with_checkpoint_pins_padded(tmp_path):
    """Resume identity: run_fingerprint hashes the run's edge list, so a
    bucket-major relabel orphans every checkpoint written under the
    caller's labeling. layout='auto' with a checkpoint therefore pins the
    padded path — bit-identical to an explicit padded run, and a
    pre-layout checkpoint keeps resuming under the new auto default."""
    from graphdyn.models.sa import simulated_annealing

    g = powerlaw_graph(150, gamma=2.3, dmin=2, seed=5)
    assert auto_layout(g.deg) == "bucketed"   # auto WOULD relabel
    kw = dict(n_replicas=3, seed=0, max_steps=40)
    ck = str(tmp_path / "ck")
    a = simulated_annealing(g, _sa_cfg(), layout="auto",
                            checkpoint_path=ck, **kw)
    p = simulated_annealing(g, _sa_cfg(), layout="padded", **kw)
    np.testing.assert_array_equal(a.s, p.s)


def test_fused_layout_knob_and_table_refusal():
    from graphdyn.ops.pallas_anneal import build_fused_tables
    from graphdyn.search.fused import fused_anneal

    g = powerlaw_graph(90, gamma=2.3, dmin=2, seed=5)
    assert auto_layout(g.deg) == "bucketed"   # auto picks the fast path
    kw = dict(n_replicas=32, seed=0, max_sweeps=12, chunk_sweeps=4)
    a = fused_anneal(g, _sa_cfg(), layout="auto", **kw)
    b = fused_anneal(g, _sa_cfg(), layout="bucketed", **kw)
    assert a.s.shape == b.s.shape == (32, g.n)
    np.testing.assert_array_equal(a.s, b.s)   # auto == explicit bucketed
    tables = build_fused_tables(g, _sa_cfg())
    with pytest.raises(ValueError, match="tables"):
        fused_anneal(g, _sa_cfg(), layout="bucketed", tables=tables, **kw)
    # prebuilt tables pin the padded labeling: auto must fall back
    p = fused_anneal(g, _sa_cfg(), layout="auto", tables=tables, **kw)
    assert p.s.shape == (32, g.n)


# ---------------------------------------------------------------------------
# the acceptance rate bound (the powerlaw_rate bench row's in-suite twin)
# ---------------------------------------------------------------------------


def test_powerlaw_rate_within_4x_of_equal_edge_rrg():
    """ISSUE 18 acceptance: bucketed spin-updates/s on a seeded power-law
    with a ≥1e3-degree hub stays within 4× of the padded rate on an RRG
    with (approximately) the same edge count — the bucketed layout makes
    the heavy tail a fast path, not a 100× cliff. Measured through the
    same A/B the ``powerlaw_rate`` bench row records."""
    import bench

    out = bench.powerlaw_rate_row(
        True, n=100_000, R=64, steps=5, iters=2,
    )
    det = out["powerlaw_rate_detail"]
    assert det["hub_degree"] >= 1000, det
    assert det["table_entries"] < det["padded_entries"] / 50, det
    assert out["powerlaw_rate"] > 0 and det["rrg_padded_rate"] > 0
    assert det["rrg_over_bucketed_x"] <= 4.0, det
