"""graphdyn.resilience — every recovery path exercised by an injected fault.

Acceptance (ISSUE 2): each of the five fault classes — checkpoint write
failure, checkpoint read corruption, preemption, Pallas lowering failure,
NaN seeded into a sweep carry — is demonstrably *survived*: the run either
resumes bit-for-bit or degrades with an explicit logged decision, never a
raw traceback from numpy/zipfile/XLA internals; SIGTERM during a
checkpointed chain exits 75 with a loadable checkpoint no older than one
chunk.

The whole module carries the ``faultinject`` marker so ``scripts/lint.sh``'s
faultcheck step can run it standalone (``pytest -m faultinject``).
"""

import logging
import os
import signal
import time

import numpy as np
import pytest

import jax.numpy as jnp

from graphdyn.config import DynamicsConfig, EntropyConfig, HPRConfig, SAConfig
from graphdyn.graphs import erdos_renyi_graph, random_regular_graph
from graphdyn.models.entropy import entropy_grid, entropy_sweep
from graphdyn.models.hpr import hpr_solve
from graphdyn.models.sa import sa_ensemble, simulated_annealing
from graphdyn.resilience import (
    FaultPlan,
    FaultSpec,
    InjectedPreemption,
    InjectedUnavailable,
    InjectedWriteError,
    RetryPolicy,
    ShutdownRequested,
    check_fault,
    graceful_shutdown,
    retry,
    shutdown_requested,
    truncate_file,
)
from graphdyn.utils.io import Checkpoint, PeriodicCheckpointer

pytestmark = pytest.mark.faultinject

DYN11 = DynamicsConfig(p=1, c=1)


def _assert_sa_equal(a, b):
    np.testing.assert_array_equal(a.s, b.s)
    np.testing.assert_array_equal(a.mag_reached, b.mag_reached)
    np.testing.assert_array_equal(a.num_steps, b.num_steps)
    np.testing.assert_array_equal(a.m_final, b.m_final)


# ---------------------------------------------------------------------------
# FaultPlan mechanics
# ---------------------------------------------------------------------------


def test_fault_plan_at_count_match_and_reset():
    plan = FaultPlan([
        FaultSpec("s", at=2, count=2),
        FaultSpec("t", match="abc"),
    ])
    with plan:
        assert check_fault("s") is None          # hit 1 (< at)
        assert check_fault("s") is not None      # hit 2
        assert check_fault("s") is not None      # hit 3 (count=2 window)
        assert check_fault("s") is None          # hit 4 (spent)
        assert check_fault("t", key="xyz") is None
        assert check_fault("t", key="xx abc yy") is not None
    assert check_fault("s") is None              # no active plan: no-op
    with plan:                                   # re-entry resets counters
        assert check_fault("s") is None
        assert check_fault("s") is not None


def test_fault_plan_seeded_probability_is_deterministic():
    def pattern(seed):
        with FaultPlan([FaultSpec("s", count=100, p=0.5)], seed=seed):
            return [check_fault("s") is not None for _ in range(24)]

    a, b = pattern(7), pattern(7)
    assert a == b
    assert any(a) and not all(a)                 # actually probabilistic


def test_env_hook_fires_through_a_real_site(monkeypatch, tmp_path):
    """GRAPHDYN_FAULT_PLAN (JSON) drives injection with no in-process plan —
    the CLI-level hook."""
    from graphdyn.resilience import faults

    monkeypatch.setenv(faults.ENV_VAR, '[{"site": "checkpoint.write"}]')
    monkeypatch.setattr(faults, "_env_plan_cache", [])
    ck = Checkpoint(str(tmp_path / "s"))
    with pytest.raises(InjectedWriteError):
        ck.save({"x": np.zeros(1)}, {})
    ck.save({"x": np.zeros(1)}, {"t": 1})        # one-shot spec: spent
    assert ck.load()[1] == {"t": 1}


def test_env_hook_malformed_plan_fails_loudly(monkeypatch):
    from graphdyn.resilience import faults

    monkeypatch.setenv(faults.ENV_VAR, "{not json")
    with pytest.raises(ValueError):
        faults.FaultPlan.from_env()


# ---------------------------------------------------------------------------
# fault class 1: checkpoint write failure — retry, then degrade to skip-save
# ---------------------------------------------------------------------------


def test_write_failure_survived_by_retry(tmp_path, caplog):
    pc = PeriodicCheckpointer(str(tmp_path / "pc"), interval_s=0.0)
    with caplog.at_level(logging.WARNING, logger="graphdyn.resilience"):
        with FaultPlan([FaultSpec("checkpoint.write", count=1)]):
            assert pc.maybe_save({"x": np.arange(3)}, {"t": 1})
    assert pc.ckpt.load()[1] == {"t": 1}
    assert "retrying" in caplog.text


def test_write_failure_exhausted_degrades_to_skip_save(tmp_path, caplog):
    pc = PeriodicCheckpointer(str(tmp_path / "pc"), interval_s=0.0)
    with caplog.at_level(logging.WARNING):
        with FaultPlan([FaultSpec("checkpoint.write", count=99)]):
            assert not pc.maybe_save({"x": np.arange(3)}, {"t": 1})
    assert pc.ckpt.load() is None
    assert "SKIPPING" in caplog.text             # the explicit logged decision


def test_torn_temp_file_never_corrupts_published_checkpoint(tmp_path):
    ck = Checkpoint(str(tmp_path / "st"))
    ck.save({"x": np.arange(4)}, {"t": 0})
    with FaultPlan([FaultSpec("checkpoint.write", action="torn")]):
        with pytest.raises(InjectedWriteError):
            ck.save({"x": np.arange(4) + 1}, {"t": 1})
    assert os.path.exists(str(tmp_path / "st.tmp.npz"))   # torn temp left
    arrays, meta = ck.load()                     # published file: old state
    np.testing.assert_array_equal(arrays["x"], np.arange(4))
    assert meta == {"t": 0}
    ck.remove()                                  # cleans snapshot AND temp
    assert not os.path.exists(str(tmp_path / "st.tmp.npz"))


def test_chain_survives_persistent_write_failure(tmp_path, caplog):
    """An hours-long chain with a dead disk keeps computing: every save
    degrades to skip-save, results identical to the no-checkpoint run."""
    g = random_regular_graph(24, 3, seed=0)
    cfg = SAConfig(dynamics=DYN11)
    base = simulated_annealing(g, cfg, n_replicas=1, seed=0, max_steps=4000)
    with caplog.at_level(logging.WARNING):
        with FaultPlan([FaultSpec("checkpoint.write", count=9999)]):
            res = simulated_annealing(
                g, cfg, n_replicas=1, seed=0, max_steps=4000,
                checkpoint_path=str(tmp_path / "ck"), chunk_steps=1500,
                checkpoint_interval_s=0.0,
            )
    _assert_sa_equal(base, res)
    assert "SKIPPING" in caplog.text


def test_injected_signal_does_not_outlive_its_plan(tmp_path):
    """A fired 'signal' spec outside any graceful_shutdown scope must not
    leave the process-global flag set — later solver calls would all die at
    their first boundary."""
    cfg = SAConfig(dynamics=DYN11)
    kw = dict(n_stat=2, seed=0, max_steps=20_000)
    with FaultPlan([FaultSpec("rep.boundary", "signal", at=1)]):
        with pytest.raises(ShutdownRequested):
            sa_ensemble(40, 3, cfg, **kw,
                        checkpoint_path=str(tmp_path / "ck"),
                        checkpoint_interval_s=0.0)
    assert not shutdown_requested()              # plan exit cleared it
    sa_ensemble(40, 3, cfg, **kw)                # and the process still works


def test_preempt_is_honored_at_specialized_sites(tmp_path):
    """'preempt' at checkpoint.write must be a hard kill, never downgraded
    to the site's retryable ENOSPC error (which retry() would survive)."""
    ck = Checkpoint(str(tmp_path / "s"))
    with FaultPlan([FaultSpec("checkpoint.write", "preempt")]):
        with pytest.raises(InjectedPreemption):
            ck.save({"x": np.zeros(1)}, {})


def test_mismatched_action_at_transform_site_raises():
    """A plan naming a transform-only site with the wrong action must fail
    loudly, not silently no-op."""
    from graphdyn.resilience import InjectedFault, transform_spec

    with FaultPlan([FaultSpec("sweep.nan", action="raise")]):
        with pytest.raises(InjectedFault):
            transform_spec("sweep.nan", "nan")


def test_transient_read_oserror_propagates_not_quarantined(tmp_path, monkeypatch):
    """A transient OSError (EIO / network blip) on a perfectly good
    checkpoint must NOT destroy it via quarantine — only structural
    corruption is quarantined."""
    import graphdyn.utils.io as io_mod

    ck = Checkpoint(str(tmp_path / "s"))
    ck.save({"x": np.arange(4)}, {"t": 1})
    real_load = io_mod.np.load
    monkeypatch.setattr(io_mod.np, "load",
                        lambda *a, **k: (_ for _ in ()).throw(OSError(5, "EIO")))
    with pytest.raises(OSError):
        ck.load()
    monkeypatch.setattr(io_mod.np, "load", real_load)
    assert ck.load()[1] == {"t": 1}              # checkpoint intact
    assert not os.path.exists(str(tmp_path / "s.corrupt.npz"))


# ---------------------------------------------------------------------------
# fault class 2: checkpoint read corruption — quarantine + fresh start
# ---------------------------------------------------------------------------


def test_corrupt_checkpoint_quarantined_not_raised(tmp_path, caplog):
    ck = Checkpoint(str(tmp_path / "s"))
    ck.save({"x": np.arange(8.0)}, {"t": 3})
    with caplog.at_level(logging.WARNING, logger="graphdyn.io"):
        with FaultPlan([FaultSpec("checkpoint.read", action="truncate")]):
            assert ck.load() is None             # never zipfile.BadZipFile
    assert os.path.exists(str(tmp_path / "s.corrupt.1.npz"))  # monotonic suffix
    assert "quarantined" in caplog.text
    assert ck.load() is None                     # bad file moved aside


def test_chain_resumes_fresh_after_corruption(tmp_path):
    """Preempt a chain, corrupt its snapshot on disk, rerun: the corrupt
    file is quarantined and the chain still lands on the uninterrupted
    result (since the durable store, via a retained-version fallback when
    one survives — the truncation travels through the promote hard link to
    the newest version — else a fresh start; both are bit-exact)."""
    g = random_regular_graph(24, 3, seed=0)
    cfg = SAConfig(dynamics=DYN11)
    kw = dict(n_replicas=1, seed=0, max_steps=4000)
    ckw = dict(checkpoint_path=str(tmp_path / "ck"), chunk_steps=50,
               checkpoint_interval_s=0.0)
    base = simulated_annealing(g, cfg, **kw)
    with FaultPlan([FaultSpec("chunk.boundary", "preempt", at=4)]):
        with pytest.raises(InjectedPreemption):
            simulated_annealing(g, cfg, **kw, **ckw)
    truncate_file(str(tmp_path / "ck.npz"), 0.4)
    res = simulated_annealing(g, cfg, **kw, **ckw)
    _assert_sa_equal(base, res)
    assert os.path.exists(str(tmp_path / "ck.corrupt.1.npz"))
    assert not os.path.exists(str(tmp_path / "ck.npz"))   # removed on success


# ---------------------------------------------------------------------------
# fault class 3: preemption at chunk/rep/λ boundaries — bit-exact resume
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("boundary", [1, 3])
def test_sa_chunk_preemption_resume_bit_exact(tmp_path, boundary):
    g = random_regular_graph(24, 3, seed=0)
    cfg = SAConfig(dynamics=DYN11)
    kw = dict(n_replicas=1, seed=0, max_steps=4000)
    ckw = dict(checkpoint_path=str(tmp_path / "ck"), chunk_steps=50,
               checkpoint_interval_s=0.0)
    base = simulated_annealing(g, cfg, **kw)
    with FaultPlan([FaultSpec("chunk.boundary", "preempt", at=boundary)]):
        with pytest.raises(InjectedPreemption):
            simulated_annealing(g, cfg, **kw, **ckw)
    res = simulated_annealing(g, cfg, **kw, **ckw)       # resume
    _assert_sa_equal(base, res)
    assert not os.path.exists(str(tmp_path / "ck.npz"))  # remove() ran


@pytest.mark.parametrize("boundary", [2, 4])
def test_hpr_chunk_preemption_resume_bit_exact(tmp_path, boundary):
    g = random_regular_graph(30, 3, seed=1)
    cfg = HPRConfig(dynamics=DYN11, max_sweeps=400)
    ckw = dict(checkpoint_path=str(tmp_path / "ck"), chunk_sweeps=20,
               checkpoint_interval_s=0.0)
    base = hpr_solve(g, cfg, seed=0)
    with FaultPlan([FaultSpec("chunk.boundary", "preempt", at=boundary)]):
        with pytest.raises(InjectedPreemption):
            hpr_solve(g, cfg, seed=0, **ckw)
    res = hpr_solve(g, cfg, seed=0, **ckw)               # resume
    np.testing.assert_array_equal(base.s, res.s)
    np.testing.assert_array_equal(base.biases, res.biases)
    np.testing.assert_array_equal(base.chi, res.chi)
    assert base.num_steps == res.num_steps
    assert base.m_final == res.m_final
    assert not os.path.exists(str(tmp_path / "ck.npz"))


def test_sa_ensemble_rep_preemption_resume_parity(tmp_path):
    cfg = SAConfig(dynamics=DYN11)
    kw = dict(n_stat=3, seed=0, max_steps=20_000)
    base = sa_ensemble(40, 3, cfg, **kw)
    ck = str(tmp_path / "ck")
    with FaultPlan([FaultSpec("rep.boundary", "preempt", at=2)]):
        with pytest.raises(InjectedPreemption):
            sa_ensemble(40, 3, cfg, **kw, checkpoint_path=ck,
                        checkpoint_interval_s=0.0)
    res = sa_ensemble(40, 3, cfg, **kw, checkpoint_path=ck,
                      checkpoint_interval_s=0.0)
    np.testing.assert_array_equal(base.mag_reached, res.mag_reached)
    np.testing.assert_array_equal(base.num_steps, res.num_steps)
    np.testing.assert_array_equal(base.conf, res.conf)
    np.testing.assert_array_equal(base.graphs, res.graphs)
    np.testing.assert_array_equal(base.m_final, res.m_final)
    assert not os.path.exists(ck + ".npz")


def test_entropy_driver_lambda_preemption_resume_parity(tmp_path):
    cfg = EntropyConfig(
        dynamics=DYN11, lmbd_max=0.3, lmbd_step=0.1, max_sweeps=300,
        num_rep=1, eps=1e-5,
    )
    deg = np.array([1.5])
    kw = dict(seed=3, class_bucket=None)
    base = entropy_grid(60, deg, cfg, **kw)
    ck = str(tmp_path / "ck")
    with FaultPlan([FaultSpec("lambda.boundary", "preempt", at=2)]):
        with pytest.raises(InjectedPreemption):
            entropy_grid(60, deg, cfg, **kw, checkpoint_path=ck,
                         checkpoint_interval_s=0.0)
    res = entropy_grid(60, deg, cfg, **kw, checkpoint_path=ck,
                       checkpoint_interval_s=0.0)
    np.testing.assert_array_equal(base.ent, res.ent)
    np.testing.assert_array_equal(base.m_init, res.m_init)
    np.testing.assert_array_equal(base.ent1, res.ent1)
    np.testing.assert_array_equal(base.counts, res.counts)
    np.testing.assert_array_equal(base.n_lambda, res.n_lambda)
    assert not os.path.exists(ck + ".npz")


# ---------------------------------------------------------------------------
# fault class 4: Pallas lowering failure — runtime fallback to the XLA path
# ---------------------------------------------------------------------------


def test_pallas_lowering_failure_falls_back_to_xla(caplog):
    from graphdyn.ops.bdcm import BDCMData, make_sweep

    g = random_regular_graph(64, 4, seed=0)
    data = BDCMData(g, p=1, c=1)
    sweep_forced = make_sweep(data, damp=0.5, use_pallas=True)
    sweep_xla = make_sweep(data, damp=0.5, use_pallas=False)
    chi = data.init_messages(0)
    lmbd = jnp.asarray(0.25, data.dtype)
    with caplog.at_level(logging.WARNING, logger="graphdyn.ops"):
        with FaultPlan([FaultSpec("pallas.lower", count=99)]):
            out = sweep_forced(chi, lmbd)        # degrades, does NOT abort
    np.testing.assert_array_equal(
        np.asarray(out), np.asarray(sweep_xla(chi, lmbd))
    )
    assert "use_pallas=False" in caplog.text
    # the rebuilt program sticks: later calls run without re-failing
    out2 = sweep_forced(sweep_xla(chi, lmbd), lmbd)
    assert np.isfinite(np.asarray(out2)).all()


def test_preempt_at_pallas_site_kills_run_not_fallback():
    """InjectedPreemption's message mentions 'pallas' at this site, but a
    hard kill must never be downgraded to the Pallas→XLA fallback."""
    from graphdyn.ops.bdcm import BDCMData, make_sweep

    g = random_regular_graph(64, 4, seed=0)
    sweep = make_sweep(BDCMData(g, p=1, c=1), damp=0.5, use_pallas=True)
    data = BDCMData(g, p=1, c=1)
    chi = data.init_messages(0)
    with FaultPlan([FaultSpec("pallas.lower", "preempt")]):
        with pytest.raises(InjectedPreemption):
            sweep(chi, jnp.asarray(0.25, data.dtype))


def test_non_lowering_failure_is_not_swallowed():
    from graphdyn.ops.bdcm import _SweepSpec, pallas_fallback_spec

    spec = _SweepSpec(2, 4, 0.5, 0.0, True, False, False, (4,), ("interpret",))
    with pytest.raises(KeyError):
        pallas_fallback_spec(spec, KeyError("unrelated bug"))
    spec_off = spec._replace(pallas=("",))
    with pytest.raises(RuntimeError):
        # no Pallas mode to blame → nothing to fall back from
        pallas_fallback_spec(spec_off, RuntimeError("mosaic lowering failed"))


# ---------------------------------------------------------------------------
# fault class 5: NaN seeded into a sweep carry — explicit degrade, no NaN rows
# ---------------------------------------------------------------------------


def test_nan_in_sweep_carry_degrades_to_nonconvergence(caplog, tmp_path,
                                                       monkeypatch):
    # the degrade dumps the flight-recorder post-mortem into the workdir
    # (PR-8 contract, asserted in tests/test_obs_device.py) — keep it here
    monkeypatch.chdir(tmp_path)
    g = erdos_renyi_graph(60, 1.5 / 59, seed=0)
    cfg = EntropyConfig(
        dynamics=DYN11, lmbd_max=0.3, lmbd_step=0.1, max_sweeps=300, eps=1e-5,
    )
    base = entropy_sweep(g, cfg, seed=0)
    assert base.lambdas.size >= 3                # ladder normally runs on
    with caplog.at_level(logging.WARNING, logger="graphdyn.models"):
        with FaultPlan([FaultSpec("sweep.nan", action="nan", at=2)]):
            res = entropy_sweep(g, cfg, seed=0)  # no XLA/numpy traceback
    assert res.lambdas.size == 2                 # stopped AT the poisoned λ
    assert res.nonconverged == pytest.approx(base.lambdas[1])
    assert "non-finite" in caplog.text           # the logged decision


# ---------------------------------------------------------------------------
# preemption-safe shutdown: SIGTERM → checkpoint at chunk boundary → exit 75
# ---------------------------------------------------------------------------


def test_real_sigterm_sets_flag_and_second_signal_aborts():
    with graceful_shutdown():
        assert not shutdown_requested()
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 5.0
        while not shutdown_requested() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert shutdown_requested()
        with pytest.raises(KeyboardInterrupt):
            os.kill(os.getpid(), signal.SIGTERM)
            time.sleep(1.0)
    assert not shutdown_requested()              # scope exit clears the flag


def test_sigterm_chain_checkpoints_and_exits_then_resumes(tmp_path):
    """SIGTERM during a checkpointed chain: snapshot at the next chunk
    boundary (no older than one chunk), ShutdownRequested out, bit-exact
    completion on requeue."""
    g = random_regular_graph(24, 3, seed=0)
    cfg = SAConfig(dynamics=DYN11)
    kw = dict(n_replicas=1, seed=0, max_steps=4000)
    ckw = dict(checkpoint_path=str(tmp_path / "ck"), chunk_steps=50,
               checkpoint_interval_s=1e9)       # interval never due: the
    base = simulated_annealing(g, cfg, **kw)    # shutdown save must force
    with graceful_shutdown():
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 5.0
        while not shutdown_requested() and time.monotonic() < deadline:
            time.sleep(0.01)
        with pytest.raises(ShutdownRequested):
            simulated_annealing(g, cfg, **kw, **ckw)
    loaded = Checkpoint(str(tmp_path / "ck")).load()
    assert loaded is not None                    # loadable checkpoint…
    arrays, meta = loaded
    assert meta["kind"] == "sa_chain"
    assert int(np.asarray(arrays["t"])[0]) == 50  # …exactly one chunk old
    res = simulated_annealing(g, cfg, **kw, **ckw)
    _assert_sa_equal(base, res)
    assert not os.path.exists(str(tmp_path / "ck.npz"))


def test_sa_ensemble_shutdown_snapshots_prefix(tmp_path):
    cfg = SAConfig(dynamics=DYN11)
    kw = dict(n_stat=3, seed=0, max_steps=20_000)
    base = sa_ensemble(40, 3, cfg, **kw)
    ck = str(tmp_path / "ck")
    with graceful_shutdown():
        # the 'signal' action delivers a shutdown request exactly as the
        # SIGTERM handler would — deterministically, at rep boundary 1
        with FaultPlan([FaultSpec("rep.boundary", "signal", at=1)]):
            with pytest.raises(ShutdownRequested):
                sa_ensemble(40, 3, cfg, **kw, checkpoint_path=ck,
                            checkpoint_interval_s=1e9)
    arrays, meta = Checkpoint(ck).load()
    assert meta["next_rep"] == 1                 # rep 0 persisted
    res = sa_ensemble(40, 3, cfg, **kw, checkpoint_path=ck,
                      checkpoint_interval_s=0.0)
    np.testing.assert_array_equal(base.conf, res.conf)
    np.testing.assert_array_equal(base.num_steps, res.num_steps)
    assert not os.path.exists(ck + ".npz")


def test_cli_preemption_exits_75_and_resumes(tmp_path, capsys, monkeypatch):
    """End to end through the CLI: a shutdown request mid-λ-ladder exits
    EX_TEMPFAIL (75) with a loadable checkpoint; rerunning the same command
    resumes, completes with exit 0, and cleans the checkpoint up."""
    from graphdyn.cli import main

    # a no-ledger preempt dumps the flight post-mortem into the workdir
    # (PR-8 contract, asserted in tests/test_obs_device.py) — keep it here
    monkeypatch.chdir(tmp_path)

    ck = str(tmp_path / "ck")
    out = str(tmp_path / "res.npz")
    args = [
        "entropy", "--n", "50", "--deg", "1.5", "--num-rep", "1",
        "--lmbd-max", "0.3", "--lmbd-step", "0.1", "--max-sweeps", "200",
        "--eps", "1e-5", "--seed", "1",
        "--checkpoint", ck, "--checkpoint-interval", "0", "--out", out,
    ]
    with FaultPlan([FaultSpec("lambda.boundary", "signal", at=2)]):
        rc = main(args)
    capsys.readouterr()
    assert rc == 75
    loaded = Checkpoint(ck).load()
    assert loaded is not None and "grid_id" in loaded[1]
    rc2 = main(args)                             # requeue
    capsys.readouterr()
    assert rc2 == 0
    assert os.path.exists(out)
    assert not os.path.exists(ck + ".npz")


# ---------------------------------------------------------------------------
# retry primitive + init_multihost deadline
# ---------------------------------------------------------------------------


def test_retry_backs_off_then_succeeds():
    calls = {"n": 0}
    slept = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    out = retry(flaky, policy=RetryPolicy(tries=4, base_delay_s=0.01),
                sleep=slept.append)
    assert out == "ok" and calls["n"] == 3
    assert slept == [0.01, 0.02]                 # exponential backoff


def test_retry_exhaustion_reraises():
    with pytest.raises(OSError):
        retry(lambda: (_ for _ in ()).throw(OSError("dead")),
              policy=RetryPolicy(tries=2, base_delay_s=0.0),
              sleep=lambda s: None)


def test_retry_if_surfaces_deterministic_failures_immediately():
    calls = {"n": 0}

    def deterministic():
        calls["n"] += 1
        raise OSError("config error, retrying cannot help")

    with pytest.raises(OSError):
        retry(deterministic, policy=RetryPolicy(tries=5, base_delay_s=0.0),
              retry_if=lambda e: "transient" in str(e), sleep=lambda s: None)
    assert calls["n"] == 1                       # no pointless backoff


def test_init_multihost_deterministic_runtime_error_not_retried():
    """'backend already initialized'-style RuntimeErrors surface on the
    first attempt; only unavailability is waited out."""
    from unittest import mock

    import jax.distributed

    from graphdyn.parallel.mesh import init_multihost

    boom = RuntimeError("jax.distributed.initialize must be called before "
                        "any JAX computations")
    with mock.patch.object(jax.distributed, "initialize",
                           side_effect=boom) as m:
        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="before any JAX"):
            init_multihost(coordinator_address="127.0.0.1:1",
                           num_processes=1, process_id=0,
                           retry_deadline_s=30.0)
    assert m.call_count == 1
    assert time.monotonic() - t0 < 2.0


def test_retry_jitter_deterministic_per_key_and_spread_across_ranks():
    """Seeded full-jitter (RetryPolicy.jitter): the same site key replays
    the same schedule (tests stay deterministic), distinct rank keys draw
    de-correlated schedules (no retry storms against a shared coordinator
    or filesystem), and every delay stays within (0, exponential bound]."""
    pol = RetryPolicy(tries=6, base_delay_s=0.5, max_delay_s=8.0, jitter=True)
    a1 = list(pol.delays(key="jax.distributed.initialize(rank 0)"))
    a2 = list(pol.delays(key="jax.distributed.initialize(rank 0)"))
    b = list(pol.delays(key="jax.distributed.initialize(rank 1)"))
    assert a1 == a2                              # deterministic per key
    assert a1 != b                               # spread across ranks
    bounds = [0.5, 1.0, 2.0, 4.0, 8.0]
    for seq in (a1, b):
        assert len(seq) == 5
        assert all(0.0 < d <= hi for d, hi in zip(seq, bounds))
    # jitter off (the default) keeps the exact exponential schedule
    assert list(RetryPolicy(tries=4, base_delay_s=0.5).delays(key="x")) == \
        [0.5, 1.0, 2.0]


def test_retry_passes_site_key_to_jittered_policy():
    """retry() seeds the jitter from its `what` site string — two sites
    with the same policy sleep different schedules."""
    slept = {}
    for what in ("site-a", "site-b"):
        seq = []
        with pytest.raises(OSError):
            retry(lambda: (_ for _ in ()).throw(OSError("dead")),
                  policy=RetryPolicy(tries=4, base_delay_s=0.01, jitter=True),
                  what=what, sleep=seq.append)
        slept[what] = seq
    assert len(slept["site-a"]) == 3
    assert slept["site-a"] != slept["site-b"]


def test_second_signal_hard_abort_exit_code_no_snapshot_flight_dump(
        tmp_path, monkeypatch, capsys):
    """The second-SIGTERM hard-abort path end to end through the CLI: the
    first signal sets the flag, the second aborts immediately — exit 130
    (EX_ABORT, never 75: schedulers must NOT requeue an operator abort), no
    snapshot written by the abort, and the flight recorder's post-mortem
    carries an obs.crash event naming the site where the run died."""
    import threading

    import graphdyn.cli as cli_mod
    from graphdyn.obs.flight import POSTMORTEM_NAME
    from graphdyn.obs.recorder import read_ledger
    from graphdyn.resilience import EX_ABORT

    monkeypatch.chdir(tmp_path)                  # the post-mortem's workdir
    ck = str(tmp_path / "ck")

    def fake_run(args):
        # a long chunk that never reaches a save boundary: the abort, not
        # the driver, decides how this ends
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            time.sleep(0.005)
        raise AssertionError("signals never arrived")

    monkeypatch.setattr(cli_mod, "_run", fake_run)

    # the graceful handler is installed inside main(); firing before that
    # would hit pytest's default SIGTERM disposition and kill the whole
    # test process — wait until the handler visibly changes
    before = signal.getsignal(signal.SIGTERM)

    def killer():
        deadline = time.monotonic() + 5.0
        while (signal.getsignal(signal.SIGTERM) is before
               and time.monotonic() < deadline):
            time.sleep(0.005)
        os.kill(os.getpid(), signal.SIGTERM)     # 1st: flag
        while not shutdown_requested() and time.monotonic() < deadline:
            time.sleep(0.01)
        os.kill(os.getpid(), signal.SIGTERM)     # 2nd: immediate abort

    t = threading.Thread(target=killer)
    t.start()
    rc = cli_mod.main(["sa", "--n", "40", "--checkpoint", ck])
    t.join()
    capsys.readouterr()
    assert rc == EX_ABORT == 130
    assert not os.path.exists(ck + ".npz")       # nothing saved by the abort
    events, torn = read_ledger(str(tmp_path / POSTMORTEM_NAME))
    assert torn == 0
    crash = [e for e in events
             if e.get("ev") == "counter" and e.get("name") == "obs.crash"]
    assert crash, events
    attrs = crash[-1]["attrs"]
    assert attrs["reason"] == "abort"
    assert attrs["exc_type"] == "KeyboardInterrupt"
    assert "site" in attrs                       # innermost frame named


def test_init_multihost_retries_coordinator_with_deadline():
    """Coordinator not up at t=0 is a race, not an error: with multi-host
    intent the connection retries until the deadline, then surfaces."""
    from graphdyn.parallel.mesh import init_multihost

    plan = FaultPlan([FaultSpec("multihost.init", count=99)])
    t0 = time.monotonic()
    with plan:
        with pytest.raises(InjectedUnavailable):
            init_multihost(
                retry_deadline_s=1.2,
                coordinator_address="127.0.0.1:1", num_processes=1,
                process_id=0,
            )
    assert plan.specs[0].hits >= 2               # it actually retried
    assert time.monotonic() - t0 < 6.0           # …and honored the deadline
