"""graphdyn.search: replica-exchange tempering + chromatic block sweeps.

The contract (ISSUE 13 / ROADMAP item 3): a swap-free ladder IS the serial
reference chain (bit-exact vs ``simulated_annealing`` on the same a0/b0);
swap moves and color sweeps are seed-deterministic and bit-reproducible
across lane-shard counts; a preempted ladder requeues onto a different
shard count bit-exact to the fault-free oracle with the PR-9 journal
carrying the save + load; the chromatic class update equals the
brute-force single-flip Metropolis oracle exactly (the distance-2
disjoint-ball argument, tested on RRG and ragged ER); and both searches
reach the target magnetization ≥ 5× faster than the serial chain at fixed
seeds (the tta_* bench acceptance bar, pinned in-suite)."""

import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from graphdyn.config import DynamicsConfig, SAConfig
from graphdyn.graphs import erdos_renyi_graph, random_regular_graph
from graphdyn.parallel.mesh import device_pool, make_mesh
from graphdyn.search.chromatic import chromatic_anneal
from graphdyn.search.tempering import ladder_betas, temper_search


def _cfg():
    return SAConfig(dynamics=DynamicsConfig(p=1, c=1))


def _lane_mesh(P):
    return make_mesh((P,), ("lane",), devices=device_pool(P))


# ---------------------------------------------------------------------------
# tempering: identity, swap law, determinism, lane sharding
# ---------------------------------------------------------------------------


def test_temper_no_swaps_is_serial_sa_bit_exact():
    """A swap-free ladder is the replica-batched serial solver: same draw,
    same accept/anneal arithmetic, same key derivation — bit-exact against
    ``simulated_annealing`` on the same per-lane (a0, b0)."""
    from graphdyn.models.sa import simulated_annealing

    g = random_regular_graph(64, 3, seed=0)
    cfg = _cfg()
    K, n = 4, g.n
    betas = np.ones(K)
    a0 = betas * cfg.a0_frac * n
    b0 = betas * cfg.b0_frac * n
    ref = simulated_annealing(g, cfg, n_replicas=K, seed=3, a0=a0, b0=b0,
                              max_steps=5000)
    got = temper_search(g, cfg, betas=betas, seed=3, max_steps=5000,
                        swap_moves=False, swap_interval=137)
    np.testing.assert_array_equal(ref.s, got.s)
    np.testing.assert_array_equal(ref.num_steps, got.num_steps)
    np.testing.assert_array_equal(ref.m_final, got.m_final)


def test_temper_equal_temperature_swaps_all_accept():
    """At equal temperatures the swap energy difference is exactly zero, so
    every eligible even/odd pair swap accepts (u < exp(0) = 1 for u in
    [0,1)) — the detailed-balance sanity anchor for the swap arithmetic."""
    g = random_regular_graph(48, 3, seed=1)
    res = temper_search(g, _cfg(), betas=np.ones(4), seed=0,
                        max_steps=1000, swap_interval=100)
    assert res.swap_attempts > 0
    assert res.swap_accepts == res.swap_attempts
    assert res.swap_acceptance_rate == 1.0


def test_temper_bit_reproducible_and_swap_stats():
    g = random_regular_graph(96, 3, seed=0)
    kw = dict(n_lanes=6, seed=5, max_steps=30_000, swap_interval=200,
              m_target=0.9, stop_on_first=True)
    a = temper_search(g, _cfg(), **kw)
    b = temper_search(g, _cfg(), **kw)
    np.testing.assert_array_equal(a.s, b.s)
    np.testing.assert_array_equal(a.t_target, b.t_target)
    assert a.swap_attempts == b.swap_attempts
    assert a.swap_accepts == b.swap_accepts
    # a real ladder at distinct temperatures accepts SOME but not all
    assert 0 < a.swap_accepts <= a.swap_attempts
    assert a.steps_to_target >= 0 and a.target_lane >= 0


def test_temper_validations():
    g = random_regular_graph(32, 3, seed=0)
    with pytest.raises(ValueError, match="m_target"):
        temper_search(g, _cfg(), n_lanes=2, m_target=0.0)
    with pytest.raises(ValueError, match="swap_interval"):
        temper_search(g, _cfg(), n_lanes=2, swap_interval=0)
    with pytest.raises(ValueError, match="n_lanes"):
        ladder_betas(0)
    assert ladder_betas(1).tolist() == [1.0]


def test_temper_fixed_budget_nosync_bit_identical():
    """The ISSUE-14 rider: a fixed-budget ladder (no stop_on_first, no
    checkpoint) skips the per-chunk ``bool(jnp.any)`` readback — the
    host-computed chunk plan covers the whole budget, chunks after every
    lane stops are no-op dispatches, and results are BIT-identical to the
    synced drive loop. Auto mode picks no-sync for a plannable budget."""
    g = random_regular_graph(64, 3, seed=0)
    kw = dict(n_lanes=4, seed=2, max_steps=4000, swap_interval=250,
              m_target=0.9)
    synced = temper_search(g, _cfg(), sync_stop=True, **kw)
    nosync = temper_search(g, _cfg(), sync_stop=False, **kw)
    auto = temper_search(g, _cfg(), **kw)
    for other in (nosync, auto):
        np.testing.assert_array_equal(synced.s, other.s)
        np.testing.assert_array_equal(synced.num_steps, other.num_steps)
        np.testing.assert_array_equal(synced.t_target, other.t_target)
        np.testing.assert_array_equal(synced.m_final, other.m_final)
        assert synced.swap_attempts == other.swap_attempts
        assert synced.swap_accepts == other.swap_accepts


def test_temper_nosync_refusals():
    """sync_stop=False needs a plannable fixed budget: stop_on_first,
    checkpoints, and over-long plans all keep (or require) the poll."""
    g = random_regular_graph(32, 3, seed=0)
    with pytest.raises(ValueError, match="stop_on_first"):
        temper_search(g, _cfg(), n_lanes=2, max_steps=1000,
                      swap_interval=100, stop_on_first=True,
                      sync_stop=False)
    with pytest.raises(ValueError, match="checkpoint"):
        temper_search(g, _cfg(), n_lanes=2, max_steps=1000,
                      swap_interval=100, sync_stop=False,
                      checkpoint_path="/tmp/never-used")
    with pytest.raises(ValueError, match="plannable"):
        temper_search(g, _cfg(), n_lanes=2, max_steps=10_000_000,
                      swap_interval=100, sync_stop=False)


def test_temper_lane_shards_with_indivisible_n():
    """The neighbor table replicates over the lane mesh (its leading axis
    is the NODE axis): a graph size not divisible by the shard count must
    run — and stay bit-identical to the unsharded ladder."""
    g = random_regular_graph(95, 4, seed=1)          # 95 % 2 != 0
    kw = dict(n_lanes=4, seed=0, max_steps=3000, swap_interval=137,
              m_target=0.95)
    base = temper_search(g, _cfg(), **kw)
    got = temper_search(g, _cfg(), mesh=_lane_mesh(2), **kw)
    np.testing.assert_array_equal(base.s, got.s)
    np.testing.assert_array_equal(base.num_steps, got.num_steps)


def test_temper_lane_shard_bit_parity():
    """Lane sharding via shard_stack is bit-identical to the unsharded
    ladder at P ∈ {2, 4, 8} — integer rollout sums + elementwise float
    acceptance + a lane permutation are reassociation-immune (the PR-3
    grouped-driver precedent restated on the lane axis)."""
    g = random_regular_graph(96, 3, seed=0)
    kw = dict(n_lanes=8, seed=2, max_steps=50_000, swap_interval=111,
              m_target=0.95)
    base = temper_search(g, _cfg(), **kw)
    for P in (2, 4, 8):
        got = temper_search(g, _cfg(), mesh=_lane_mesh(P), **kw)
        np.testing.assert_array_equal(base.s, got.s, err_msg=f"P={P}")
        np.testing.assert_array_equal(base.num_steps, got.num_steps)
        np.testing.assert_array_equal(base.t_target, got.t_target)
        assert base.swap_accepts == got.swap_accepts


# ---------------------------------------------------------------------------
# tempering: durable resume across lane-shard counts (the requeue contract)
# ---------------------------------------------------------------------------


def test_temper_preempt_requeue_shard_change_journal(tmp_path):
    """The acceptance centerpiece: a K=8 ladder sharded one-lane-per-device
    is preempted by an injected SIGTERM-equivalent at a chunk (= swap)
    boundary and snapshots through the durable store; the REQUEUED episode
    comes up on a SHRUNK pool (4 lane-shards, two lanes per device),
    resumes from the GLOBAL snapshot and finishes bit-exact to the
    fault-free oracle — with the PR-9 run journal validating and carrying
    both the preempted episode's save and the requeue's load."""
    from graphdyn.resilience import ShutdownRequested
    from graphdyn.resilience.faults import FaultPlan, FaultSpec
    from graphdyn.resilience.store import journal_path_for, validate_journal

    g = random_regular_graph(96, 3, seed=0)
    kw = dict(n_lanes=8, seed=2, max_steps=50_000, swap_interval=111,
              m_target=0.95)
    oracle = temper_search(g, _cfg(), **kw)

    ck = str(tmp_path / "lad" / "ck")
    with FaultPlan([FaultSpec("chunk.boundary", "signal", at=2)]):
        with pytest.raises(ShutdownRequested):
            temper_search(g, _cfg(), mesh=_lane_mesh(8), checkpoint_path=ck,
                          checkpoint_interval_s=0.0, **kw)
    assert os.path.exists(ck + ".npz")           # the preemption snapshot

    resumed = temper_search(g, _cfg(), mesh=_lane_mesh(4),
                            checkpoint_path=ck, **kw)
    np.testing.assert_array_equal(oracle.s, resumed.s)
    np.testing.assert_array_equal(oracle.num_steps, resumed.num_steps)
    np.testing.assert_array_equal(oracle.t_target, resumed.t_target)
    assert oracle.swap_accepts == resumed.swap_accepts
    assert not os.path.exists(ck + ".npz")       # removed on completion

    events, problems = validate_journal(journal_path_for(ck))
    assert problems == [], problems
    ops = [e.get("op") for e in events if e.get("ev") == "journal"]
    assert "save" in ops and "load" in ops       # preempt saved, requeue loaded


def test_temper_resume_refuses_different_ladder(tmp_path, abort_after_save):
    """The swap law is part of the chain: a snapshot written under one
    (betas, swap_interval) must refuse a resume under another — a spliced
    chimera ladder would silently change every chain."""
    from conftest import CheckpointAbort

    g = random_regular_graph(48, 3, seed=0)
    ck = str(tmp_path / "ck")
    kw = dict(n_lanes=4, seed=1, max_steps=20_000, m_target=0.95)
    with abort_after_save(n=1):
        with pytest.raises(CheckpointAbort):
            temper_search(g, _cfg(), swap_interval=100, checkpoint_path=ck,
                          checkpoint_interval_s=0.0, **kw)
    with pytest.raises(ValueError, match="refusing to resume"):
        temper_search(g, _cfg(), swap_interval=200, checkpoint_path=ck, **kw)
    with pytest.raises(ValueError, match="refusing to resume"):
        temper_search(g, _cfg(), swap_interval=100, betas=ladder_betas(4, 1, 8),
                      checkpoint_path=ck, seed=1, max_steps=20_000,
                      m_target=0.95)


# ---------------------------------------------------------------------------
# chromatic: kernel exactness (brute-force oracle), chain behavior
# ---------------------------------------------------------------------------


def _end_sum_np(nbr, s):
    """One synchronous majority step (tie stay), per replica: the numpy
    oracle of the p=c=1 rollout's end-state sum."""
    s_ext = np.concatenate(
        [s.astype(np.int64), np.zeros((s.shape[0], 1), np.int64)], axis=1
    )
    sums = s_ext[:, nbr].sum(axis=2)
    return np.sign(2 * sums + s.astype(np.int64)).sum(axis=1)


@pytest.mark.parametrize("gname", ["rrg", "er"])
def test_chromatic_class_update_matches_bruteforce_oracle(gname):
    """One class step equals the product of per-site single-flip Metropolis
    kernels computed by brute force (full end-state re-evaluation per
    flip), under shared injected uniforms — including the additive
    ``Σs_end`` update the disjoint-ball (distance-2) argument licenses."""
    from graphdyn.ops.chromatic import (
        _threshold_words, build_chromatic_tables, class_update,
    )
    from graphdyn.ops.dynamics import Rule, TieBreak
    from graphdyn.ops.packed import WORD, pack_spins, unpack_spins

    g = (random_regular_graph(60, 3, seed=1) if gname == "rrg"
         else erdos_renyi_graph(50, 4.0 / 49, seed=2))
    tables = build_chromatic_tables(g, seed=0)
    n, dmax = g.n, tables.dmax
    R = 5
    W = -(-R // WORD)
    Rp = W * WORD
    rng = np.random.default_rng(3)
    s = (2 * rng.integers(0, 2, size=(R, n)) - 1).astype(np.int8)
    a = np.full(Rp, 0.7, np.float32)
    b = np.full(Rp, 1.3, np.float32)
    active = np.zeros(Rp, bool)
    active[:R] = True
    u = rng.random((n, Rp)).astype(np.float32)
    thr_bits, even_mask = _threshold_words(
        jnp.asarray(tables.deg_ext), max(dmax.bit_length(), 1)
    )
    sp_ext = jnp.concatenate(
        [jnp.asarray(pack_spins(s)), jnp.zeros((1, W), jnp.uint32)], axis=0
    )
    c = 1
    sp_new, dsend_tot, _, _, n_acc = class_update(
        sp_ext, jnp.asarray(u), jnp.asarray(tables.masks[c]),
        jnp.int32(tables.class_sizes[c]), jnp.asarray(a), jnp.asarray(b),
        jnp.asarray(active), jnp.asarray(tables.nbr_ext),
        jnp.asarray(tables.nbr_self), thr_bits, even_mask,
        n=n, dmax=dmax, rule=Rule("majority"), tie=TieBreak("stay"),
        par_a=1.0005, par_b=1.0005, a_cap=1e9, b_cap=1e9,
    )
    got_s = unpack_spins(np.asarray(sp_new[:n]), R)

    nbr = np.asarray(g.nbr)
    class_sites = np.where(tables.colors == c)[0]
    exp_s = s.copy()
    exp_dsend = np.zeros(R, np.int64)
    se0 = _end_sum_np(nbr, s)
    for r in range(R):
        for i in class_sites:
            s_flip = s[r:r + 1].copy()
            s_flip[0, i] = -s_flip[0, i]
            dsend = _end_sum_np(nbr, s_flip)[0] - se0[r]
            de = (np.float32(-2.0) * a[r] * np.float32(s[r, i])
                  - b[r] * np.float32(dsend)) / np.float32(n)
            if u[i, r] < np.exp(-de):
                exp_s[r, i] = -exp_s[r, i]
                exp_dsend[r] += dsend
    np.testing.assert_array_equal(got_s, exp_s)
    np.testing.assert_array_equal(np.asarray(dsend_tot)[:R], exp_dsend)
    # the additivity claim itself: recomputing Σs_end from the flipped
    # state matches the sum of single-flip deltas
    np.testing.assert_array_equal(_end_sum_np(nbr, exp_s), se0 + exp_dsend)
    assert int(n_acc) == int((exp_s != s).sum())


def test_chromatic_anneal_reaches_target_and_reproducible():
    g = random_regular_graph(128, 3, seed=0)
    kw = dict(n_replicas=8, seed=0, m_target=0.9, max_sweeps=2000)
    r = chromatic_anneal(g, _cfg(), **kw)
    assert (r.steps_to_target >= 0).all()        # every chain got there
    assert (r.m_end >= 0.9).all()
    assert r.chi >= 2 and r.device_steps == r.sweeps * r.chi
    assert r.accepted > 0
    r2 = chromatic_anneal(g, _cfg(), **kw)
    np.testing.assert_array_equal(r.s, r2.s)
    np.testing.assert_array_equal(r.steps_to_target, r2.steps_to_target)


def test_chromatic_reproducible_across_replica_counts():
    """The proposal stream is keyed per (class step, 32-replica WORD):
    growing the replica set adds words without perturbing existing ones,
    so replicas 0..31 of an R=64 run are bit-identical to the R=32 run —
    the 'bit-reproducible across lane counts' half of the acceptance
    criterion for color sweeps (word granularity)."""
    g = random_regular_graph(96, 3, seed=0)
    kw = dict(seed=4, m_target=0.9, max_sweeps=600)
    small = chromatic_anneal(g, _cfg(), n_replicas=32, **kw)
    big = chromatic_anneal(g, _cfg(), n_replicas=64, **kw)
    np.testing.assert_array_equal(small.s, big.s[:32])
    np.testing.assert_array_equal(small.steps_to_target,
                                  big.steps_to_target[:32])


def test_chromatic_first_passage_freezes():
    """A replica freezes at its first passage: its recorded step count is
    final and its configuration stops changing afterwards (run longer —
    identical first-passage records)."""
    g = random_regular_graph(96, 3, seed=1)
    short = chromatic_anneal(g, _cfg(), n_replicas=8, seed=3, m_target=0.9,
                             max_sweeps=400)
    longer = chromatic_anneal(g, _cfg(), n_replicas=8, seed=3, m_target=0.9,
                              max_sweeps=800)
    hit = short.steps_to_target >= 0
    assert hit.any()
    np.testing.assert_array_equal(short.steps_to_target[hit],
                                  longer.steps_to_target[hit])
    np.testing.assert_array_equal(short.s[hit], longer.s[hit])


def test_chromatic_validations():
    g = random_regular_graph(32, 3, seed=0)
    with pytest.raises(ValueError, match="p = c = 1"):
        chromatic_anneal(
            g, SAConfig(dynamics=DynamicsConfig(p=3, c=1)), n_replicas=2
        )
    with pytest.raises(ValueError, match="m_target"):
        chromatic_anneal(g, _cfg(), n_replicas=2, m_target=1.5)
    with pytest.raises(ValueError, match="chunk_sweeps"):
        chromatic_anneal(g, _cfg(), n_replicas=2, chunk_sweeps=0)
    with pytest.raises(ValueError, match="max_sweeps"):
        chromatic_anneal(g, _cfg(), n_replicas=2, max_sweeps=0)


def test_chromatic_exact_sweep_budget():
    """max_sweeps is honored to the sweep (host-side chunk plan): a budget
    that is not a chunk_sweeps multiple never overshoots."""
    g = random_regular_graph(64, 3, seed=0)
    r = chromatic_anneal(g, _cfg(), n_replicas=4, seed=9, m_target=1.0,
                         max_sweeps=100, chunk_sweeps=64)
    assert r.sweeps <= 100
    assert r.device_steps == r.sweeps * r.chi


def test_chromatic_tables_refuse_invalid_coloring():
    from graphdyn.ops.chromatic import ChromaticTables, build_chromatic_tables

    g = random_regular_graph(48, 3, seed=0)
    t = build_chromatic_tables(g, seed=0)
    assert t.chi <= g.dmax ** 2 + 1
    # a deliberately monochromatic coloring is refused at validation
    from graphdyn.graphs import power_graph, validate_coloring

    bad = np.zeros(g.n, np.int32)
    assert validate_coloring(power_graph(g, 2), bad) != []
    assert isinstance(t, ChromaticTables)


# ---------------------------------------------------------------------------
# the acceptance bar: >= 5x fewer device steps to target at fixed seeds
# ---------------------------------------------------------------------------


def test_tta_bench_contract_and_speedup_bar():
    """The ISSUE-13 acceptance criterion pinned in-suite: on the d=3 RRG
    smoke workload at fixed seeds, BOTH accelerated searches reach the
    target magnetization in ≥ 5× fewer device steps than the serial SA
    chain (per seed, not just on average), the ladder's swap acceptance is
    nonzero (a dead ladder must not bench as "fast"), and every chromatic
    chain actually hits the target. Counts are seed-deterministic, so this
    is a stable algorithmic assertion, not a flaky timing one."""
    import bench

    row = bench.tta_rows(smoke=True)
    assert row["tta_tempering"] is not None, row
    assert row["tta_chromatic"] is not None, row
    assert min(row["tta_tempering"]["per_seed_speedup"]) >= 5.0, row
    assert min(row["tta_chromatic"]["per_seed_speedup"]) >= 5.0, row
    assert row["swap_acceptance_rate"] > 0, row
    assert row["tta_chromatic"]["target_hit_fraction"] == 1.0, row
    assert row["tta_serial_timeouts"] == 0, row
    assert row["tta_chromatic"]["chi"] >= 2
    # the ISSUE-14 leg: the fused one-kernel annealer holds the same bar
    # (interleaved on the same seeds; device-step counts deterministic)
    assert row["tta_fused"] is not None, row
    assert min(row["tta_fused"]["per_seed_speedup"]) >= 5.0, row
    assert row["tta_fused"]["target_hit_fraction"] == 1.0, row
    # auto mode: XLA twin on CPU, the Pallas kernel on a chip — either
    # way the same chain (a 'pallas-interpret' here would mean auto
    # wrongly picked a test mode)
    assert row["tta_fused"]["kernel"] in ("xla", "pallas")
    # the rider A/B rode along: a fixed-budget ladder ran BOTH with and
    # without the per-chunk stop test (results bit-identical — pinned in
    # test_temper_fixed_budget_nosync_bit_identical)
    sab = row["tta_fixed_budget_sync"]
    assert sab["sync_s"] > 0 and sab["nosync_s"] > 0, row


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def test_cli_temper(tmp_path, capsys):
    from graphdyn.cli import main

    out = str(tmp_path / "t.npz")
    rc = main([
        "temper", "--n", "96", "--d", "3", "--lanes", "4",
        "--swap-interval", "200", "--m-target", "0.9", "--stop-on-first",
        "--max-steps", "100000", "--seed", "1", "--out", out,
    ])
    assert rc == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["solver"] == "temper" and line["lanes"] == 4
    assert line["steps_to_target"] >= 0
    assert 0.0 <= line["swap_acceptance_rate"] <= 1.0
    assert os.path.exists(out)
    with pytest.raises(SystemExit, match="lane-shards"):
        main(["temper", "--n", "32", "--lanes", "8", "--lane-shards", "3"])


def test_cli_temper_lane_shards(tmp_path, capsys):
    from graphdyn.cli import main

    rc = main([
        "temper", "--n", "96", "--d", "3", "--lanes", "4",
        "--lane-shards", "2", "--swap-interval", "200", "--m-target", "0.9",
        "--stop-on-first", "--max-steps", "100000", "--seed", "1",
    ])
    assert rc == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["lane_shards"] == 2 and line["steps_to_target"] >= 0


def test_cli_chromatic(tmp_path, capsys, monkeypatch):
    from graphdyn.cli import main

    out = str(tmp_path / "c.npz")
    rc = main([
        "chromatic", "--n", "96", "--d", "3", "--replicas", "8",
        "--m-target", "0.9", "--max-sweeps", "1500", "--seed", "1",
        "--out", out,
    ])
    assert rc == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["solver"] == "chromatic" and line["chi"] >= 2
    assert all(t >= 0 for t in line["steps_to_target"])
    assert os.path.exists(out)
    # p != 1 is refused loudly (the distance-2 coloring covers radius 2
    # exactly); the crash path dumps a flight post-mortem into cwd
    monkeypatch.chdir(tmp_path)
    with pytest.raises(ValueError, match="p = c = 1"):
        main(["chromatic", "--n", "32", "--p", "3"])


@pytest.mark.slow
def test_cli_temper_preempt_requeue_subprocess(tmp_path, multi_device_cpu):
    """The requeue contract across REAL process boundaries on the forced
    8-device CPU platform (the multi_device_cpu fixture): a --lane-shards 8
    ladder preempted by an injected signal exits 75 with a snapshot;
    rerunning the same command line on FEWER shards (4 — what a
    scheduler's requeue after a device loss does) resumes and produces the
    oracle's exact per-lane results."""
    from graphdyn.utils.io import load_results_npz

    ck = str(tmp_path / "ck" / "run")
    argv = ["temper", "--n", "96", "--d", "3", "--lanes", "8",
            "--swap-interval", "111", "--m-target", "0.95",
            "--max-steps", "50000", "--seed", "2"]
    ckpt = ["--checkpoint", ck, "--checkpoint-interval", "0"]

    oracle = multi_device_cpu(
        argv + ["--lane-shards", "8", "--out", str(tmp_path / "oracle.npz")],
    )
    assert oracle.returncode == 0, oracle.stderr[-2000:]

    plan = json.dumps(
        [{"site": "chunk.boundary", "action": "signal", "at": 2}]
    )
    ep1 = multi_device_cpu(
        argv + ckpt + ["--lane-shards", "8"],
        env={"GRAPHDYN_FAULT_PLAN": plan},
    )
    assert ep1.returncode == 75, (ep1.returncode, ep1.stderr[-2000:])
    assert os.path.exists(ck + ".npz")

    ep2 = multi_device_cpu(
        argv + ckpt + ["--lane-shards", "4",
                       "--out", str(tmp_path / "requeued.npz")],
    )
    assert ep2.returncode == 0, ep2.stderr[-2000:]
    a = load_results_npz(str(tmp_path / "oracle.npz"))
    b = load_results_npz(str(tmp_path / "requeued.npz"))
    np.testing.assert_array_equal(a["conf"], b["conf"])
    np.testing.assert_array_equal(a["num_steps"], b["num_steps"])
    np.testing.assert_array_equal(a["t_target"], b["t_target"])
