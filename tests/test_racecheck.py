"""graftrace — the host-concurrency auditor (graphdyn.analysis.racecheck).

Static half: GT001–GT005 each with bad/good/disable coverage, the
concurrency-ledger (GT004) declaration diff, and the shipped-package-clean
acceptance invocation. Runtime half: the TracedLock proxy (install/
uninstall, flight-ring evidence, ledger-asserted lock order, the fuzzer's
seeding contract, allocation bounds) plus the subprocess regression that a
``GRAPHDYN_RACECHECK=1`` CLI entropy smoke is finding-free. Satellite:
the GD/GC/GT rule-catalogue sync test against ARCHITECTURE.md (both
directions).
"""

import json
import os
import re
import subprocess
import sys
import threading
import tracemalloc
from pathlib import Path

import pytest

from graphdyn.analysis import racecheck as rc

pytestmark = pytest.mark.racecheck

REPO = Path(__file__).resolve().parent.parent

#: a fake in-package path so driver-scope heuristics apply
MOD = "graphdyn/fake/mod.py"


def findings(src, ledger=None, check=False):
    return rc.analyze_sources([(MOD, src)], ledger=ledger,
                              check_declarations=check)


def codes(src, **kw):
    return [f.code for f in findings(src, **kw)]


# ---------------------------------------------------------------------------
# GT001 — unguarded module-global writes from thread targets
# ---------------------------------------------------------------------------

GT001_BAD = """
import threading
_cache = {}
_lock = threading.Lock()
def _worker():
    _cache["k"] = 1
t = threading.Thread(target=_worker, name="w", daemon=True)
t.start()
t.join(timeout=1.0)
"""


def test_gt001_bad_unguarded_write():
    assert codes(GT001_BAD) == ["GT001"]


def test_gt001_good_guarded_write():
    good = GT001_BAD.replace(
        '    _cache["k"] = 1',
        '    with _lock:\n        _cache["k"] = 1')
    assert codes(good) == []


def test_gt001_reaches_module_local_callees_and_rebinds():
    src = """
import threading
_state = None
_lock = threading.Lock()
def _helper():
    global _state
    _state = 42
def _worker():
    _helper()
t = threading.Thread(target=_worker, name="w")
t.start(); t.join(1.0)
"""
    fs = findings(src)
    assert [f.code for f in fs] == ["GT001"]
    assert "_state" in fs[0].message and "rebinds" in fs[0].message


def test_gt001_mutator_methods_and_queue_exemption():
    src = """
import queue, threading
_seen = set()
_q = queue.Queue()
_lock = threading.Lock()
def _worker():
    _seen.add(1)        # GT001: set mutator, no lock
    _q.put(1)           # exempt: queue.Queue is internally synchronized
t = threading.Thread(target=_worker, name="w")
t.start(); t.join(1.0)
"""
    fs = findings(src)
    assert [f.code for f in fs] == ["GT001"]
    assert "_seen" in fs[0].message


def test_gt001_main_thread_writes_not_flagged():
    """The rule scopes to thread-target functions — a main-thread-only
    writer is not a data race by itself."""
    src = """
_cache = {}
def setup():
    _cache["k"] = 1
"""
    assert codes(src) == []


def test_gt001_disable_hatch():
    src = GT001_BAD.replace(
        '    _cache["k"] = 1',
        '    _cache["k"] = 1  # graftrace: disable=GT001  single-writer')
    assert codes(src) == []


# ---------------------------------------------------------------------------
# GT002 — lock-order hazards
# ---------------------------------------------------------------------------

GT002_CYCLE = """
import threading
_a = threading.Lock()
_b = threading.Lock()
def f():
    with _a:
        with _b:
            pass
def g():
    with _b:
        with _a:
            pass
"""


def test_gt002_static_cycle():
    fs = findings(GT002_CYCLE)
    assert [f.code for f in fs] == ["GT002"]
    assert "CYCLE" in fs[0].message


def test_gt002_one_order_is_clean():
    src = GT002_CYCLE.replace("    with _b:\n        with _a:",
                              "    with _a:\n        with _b:")
    assert codes(src) == []


def test_gt002_callee_acquisition_edge():
    """Acquiring through a module-local call chain builds the same edge
    as a lexically nested with-block."""
    src = """
import threading
_a = threading.Lock()
_b = threading.Lock()
def takes_b():
    with _b:
        pass
def f():
    with _a:
        takes_b()
def g():
    with _b:
        with _a:
            pass
"""
    fs = findings(src)
    assert [f.code for f in fs] == ["GT002"]


def test_gt002_inversion_against_ledger():
    src = """
import threading
_a = threading.Lock()
_b = threading.Lock()
def f():
    with _a:
        with _b:
            pass
"""
    ledger = {
        "version": 1, "threads": {},
        "locks": {f"{MOD}::_a": {"kind": "lock", "scope": "module"},
                  f"{MOD}::_b": {"kind": "lock", "scope": "module"}},
        "globals": {},
        "lock_order": [[f"{MOD}::_b", f"{MOD}::_a"]],
    }
    fs = findings(src, ledger=ledger, check=True)
    assert "GT002" in [f.code for f in fs]
    inv = next(f for f in fs if f.code == "GT002")
    assert "INVERSION" in inv.message


# ---------------------------------------------------------------------------
# GT003 — unbounded threads
# ---------------------------------------------------------------------------


def test_gt003_bad_no_join():
    src = """
import threading
def work(): pass
def go():
    t = threading.Thread(target=work, name="t")
    t.start()
"""
    assert codes(src) == ["GT003"]


def test_gt003_bad_unbounded_join():
    src = """
import threading
def work(): pass
def go():
    t = threading.Thread(target=work, name="t")
    t.start()
    t.join()
"""
    assert codes(src) == ["GT003"]


def test_gt003_good_bounded_join():
    src = """
import threading
def work(): pass
def go():
    t = threading.Thread(target=work, name="t")
    t.start()
    t.join(timeout=2.0)
"""
    assert codes(src) == []


def test_gt003_instance_thread_attr():
    src = """
import threading
class Runner:
    def start(self):
        self._thread = threading.Thread(target=self._run, name="r")
        self._thread.start()
    def stop(self):
        self._thread.join(timeout=5.0)
    def _run(self): pass
"""
    assert codes(src) == []


def test_gt003_disable_names_the_invariant():
    src = """
import threading
def work(): pass
def go():
    # graftrace: disable-next-line=GT003  daemon loop drained by flush(timeout)
    t = threading.Thread(target=work, name="t", daemon=True)
    t.start()
"""
    assert codes(src) == []


# ---------------------------------------------------------------------------
# GT005 — sleep-based synchronization
# ---------------------------------------------------------------------------


def test_gt005_bad_dotted_and_from_import():
    src = """
import time
from time import sleep
def wait_a():
    time.sleep(0.1)
def wait_b():
    sleep(0.1)
"""
    assert codes(src) == ["GT005", "GT005"]


def test_gt005_disable_file():
    src = """# graftrace: disable-file=GT005  oracle timing module
import time
def wait():
    time.sleep(0.1)
"""
    assert codes(src) == []


# ---------------------------------------------------------------------------
# GT004 — the declaration ledger
# ---------------------------------------------------------------------------

DECLARED_SRC = """
import threading
_cache = {}
_lock = threading.Lock()
def _worker():
    with _lock:
        _cache["k"] = 1
t = threading.Thread(target=_worker, name="w", daemon=True)
t.start()
t.join(timeout=1.0)
"""

DECLARED_LEDGER = {
    "version": 1,
    "threads": {f"{MOD}::w": {"target": "_worker", "daemon": True}},
    "locks": {f"{MOD}::_lock": {"kind": "lock", "scope": "module"}},
    "globals": {f"{MOD}::_cache": {"kind": "dict"}},
    "lock_order": [],
}


def test_gt004_missing_ledger_is_a_finding():
    fs = findings(DECLARED_SRC, ledger=None, check=True)
    assert [f.code for f in fs] == ["GT004"]
    assert "--update-ledger" in fs[0].message


def test_gt004_declared_surface_is_clean():
    assert codes(DECLARED_SRC, ledger=DECLARED_LEDGER, check=True) == []


def test_gt004_undeclared_thread_and_stale_row():
    extra = DECLARED_SRC + """
t2 = threading.Thread(target=_worker, name="w2")
t2.start(); t2.join(timeout=1.0)
"""
    fs = findings(extra, ledger=DECLARED_LEDGER, check=True)
    assert [f.code for f in fs] == ["GT004"]
    assert "w2" in fs[0].message and "undeclared" in fs[0].message
    # stale: ledger row with no live site
    ledger = {**DECLARED_LEDGER,
              "globals": {**DECLARED_LEDGER["globals"],
                          f"{MOD}::_gone": {"kind": "list"}}}
    fs = findings(DECLARED_SRC, ledger=ledger, check=True)
    assert [f.code for f in fs] == ["GT004"]
    assert "stale" in fs[0].message


def test_ledger_roundtrip_via_inventory():
    inv, fs = rc.collect_inventory(sources=[(MOD, DECLARED_SRC)])
    assert fs == []
    assert rc.check_ledger(inv, rc.inventory_to_ledger(inv)) == []


def test_constant_tables_stay_out_of_the_inventory():
    """A module-level dict/set nobody writes is a constant, not shared
    mutable state — inventorying it would churn the ledger on every new
    rule table."""
    src = """
RULES = {"a": 1}
_NAMES = {"x", "y"}
_written = {}
def touch():
    _written["k"] = 1
"""
    inv, _ = rc.collect_inventory(sources=[(MOD, src)])
    names = {g.name for g in inv.globals_}
    assert names == {"_written"}


# ---------------------------------------------------------------------------
# rule catalogue + docs sync (satellite: GD/GC/GT <-> ARCHITECTURE.md)
# ---------------------------------------------------------------------------


def test_gt_rule_catalogue_complete():
    assert sorted(rc.RULES) == ["GT001", "GT002", "GT003", "GT004", "GT005"]
    assert all(rc.RULES[k] for k in rc.RULES)


def test_rule_catalogue_synced_with_architecture_md():
    """Every GD/GC/GT rule id defined in graftlint/graftcheck/racecheck
    appears in ARCHITECTURE.md, and every such token ARCHITECTURE.md
    mentions is a defined rule — both directions, so the catalogue tables
    can no longer drift from the code by hand (today's 15 GD rules were
    drift-checked manually)."""
    from graphdyn.analysis.graftcheck import RULES as GC_RULES
    from graphdyn.analysis.graftcost import RULES as GB_RULES
    from graphdyn.analysis.graftlint import RULES as GD_RULES

    defined = set(GD_RULES) | set(GC_RULES) | set(rc.RULES) | set(GB_RULES)
    doc = (REPO / "ARCHITECTURE.md").read_text()
    doc_tokens = set(re.findall(r"\b(?:GD|GC|GT|GB)\d{3}\b", doc))
    undocumented = sorted(defined - doc_tokens)
    assert not undocumented, (
        f"rules defined in code but absent from ARCHITECTURE.md's "
        f"catalogue: {undocumented}"
    )
    # GD000/GT000 are the linters' syntax-error sentinels, not rules
    phantom = sorted(
        doc_tokens - defined - {"GD000", "GT000", "GC000", "GB000"}
    )
    assert not phantom, (
        f"ARCHITECTURE.md mentions rule ids no linter defines: {phantom}"
    )


# ---------------------------------------------------------------------------
# the shipped package is clean, and the ledger is committed + current
# ---------------------------------------------------------------------------


def test_shipped_package_clean_json_cli():
    """The acceptance-criterion invocation: the static pass over the
    package + the committed ledger exits 0 with zero findings (every
    remaining GT hit is reasoned-disabled in-source), and JSON mode emits
    exactly one document on stdout."""
    proc = subprocess.run(
        [sys.executable, "-m", "graphdyn.analysis.racecheck",
         "--format=json"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    doc = json.loads(proc.stdout)
    assert proc.returncode == 0, f"undisabled findings: {doc['findings']}"
    assert doc["findings"] == []
    inv = doc["inventory"]
    # the known thread surface is inventoried
    assert {"graphdyn/pipeline/prefetch.py::graphdyn-prefetch",
            "graphdyn/resilience/store.py::graphdyn-ckpt-mirror",
            "graphdyn/resilience/supervisor.py::graphdyn-watchdog"} \
        <= set(inv["threads"])
    assert "graphdyn/resilience/store.py::_journal_lock" in inv["locks"]


def test_committed_ledger_matches_live_inventory():
    ledger = rc.load_ledger()
    assert ledger is not None, f"{rc.LEDGER_NAME} is not committed"
    inv, rule_findings = rc.collect_inventory()
    assert rule_findings == [], rule_findings
    diffs = rc.check_ledger(inv, ledger)
    assert diffs == [], diffs


def test_update_ledger_writes_current_surface(tmp_path):
    target = tmp_path / "ledger.json"
    proc = subprocess.run(
        [sys.executable, "-m", "graphdyn.analysis.racecheck",
         "--update-ledger", "--ledger", str(target)],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    written = json.loads(target.read_text())
    assert written == rc.load_ledger(), (
        "freshly written ledger differs from the committed one — "
        "re-run --update-ledger and commit"
    )


def test_exit_code_counts_findings(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import time\ndef w():\n    time.sleep(1)\n")
    proc = subprocess.run(
        [sys.executable, "-m", "graphdyn.analysis.racecheck", str(bad)],
        cwd=REPO, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 1, proc.stdout
    assert "GT005" in proc.stdout


# ---------------------------------------------------------------------------
# runtime half — the TracedLock proxy
# ---------------------------------------------------------------------------


@pytest.fixture
def runtime():
    """Installed proxies for the scope of a test, always uninstalled."""
    rc.uninstall()
    names = rc.install(fuzz_seed=None)
    try:
        yield names
    finally:
        rc.uninstall()


def test_install_wraps_inventoried_module_locks(runtime):
    from graphdyn.obs import flight
    from graphdyn.resilience import store, supervisor

    assert "graphdyn/resilience/store.py::_journal_lock" in runtime
    assert isinstance(store._journal_lock, rc.TracedLock)
    assert isinstance(store._mirror_thread_lock, rc.TracedLock)
    assert isinstance(supervisor._beat_lock, rc.TracedLock)
    assert isinstance(flight._lock, rc.TracedLock)
    # its own bookkeeping lock is never wrapped (reentrancy firewall)
    assert not isinstance(rc._book_lock, rc.TracedLock)


def test_uninstall_restores_plain_locks():
    rc.uninstall()
    rc.install()
    rc.uninstall()
    from graphdyn.resilience import store

    assert not isinstance(store._journal_lock, rc.TracedLock)
    assert not rc.installed()


def test_off_mode_has_no_proxy(monkeypatch):
    """Racecheck OFF is the default and pays nothing per acquire: with
    the env unset maybe_install is a no-op and the module locks stay the
    plain threading objects — no wrapper exists at all, which is
    strictly cheaper than the one-attribute-check budget."""
    monkeypatch.delenv(rc.ENV_VAR, raising=False)
    assert rc.maybe_install() == []
    from graphdyn.resilience import store

    assert not isinstance(store._journal_lock, rc.TracedLock)


def test_env_opt_in(monkeypatch):
    monkeypatch.setenv(rc.ENV_VAR, "1")
    try:
        names = rc.maybe_install()
        assert names, "GRAPHDYN_RACECHECK=1 did not install the proxies"
    finally:
        rc.uninstall()


def test_acquire_events_reach_the_flight_ring(runtime):
    from graphdyn.obs import flight
    from graphdyn.resilience import supervisor

    flight.clear()
    supervisor.beat("racecheck.test")
    events = [e for e in flight.snapshot()
              if e.get("name") == "racecheck.acquire"]
    assert events, "no racecheck.acquire event reached the flight ring"
    attrs = events[0]["attrs"]
    assert attrs["lock"].endswith("::_beat_lock")
    assert attrs["thread"] == threading.current_thread().name


def test_observed_order_records_nesting(runtime):
    a = rc.TracedLock(threading.Lock(), "A")
    b = rc.TracedLock(threading.Lock(), "B")
    with a:
        with b:
            pass
    assert ("A", "B") in rc.observed_order()
    assert rc.assert_observed_against_ledger() == []


def test_ledgered_inversion_raises_lock_order_error(runtime):
    # the ledger commits the order B-before-A (outer B, inner A)
    rc._runtime["pairs"] = frozenset({("B", "A")})
    a = rc.TracedLock(threading.Lock(), "A")
    b = rc.TracedLock(threading.Lock(), "B")
    with b:
        with a:
            pass                        # declared order honored: fine
    with a:
        with pytest.raises(rc.LockOrderError) as ei:
            b.acquire()
    assert "inversion" in str(ei.value)
    # the refused acquire never took the inner lock
    assert b._inner.acquire(blocking=False)
    b._inner.release()


def test_reentrant_rlock_through_the_proxy(runtime):
    r = rc.TracedLock(threading.RLock(), "R")
    with r:
        with r:
            pass
    assert r._inner.acquire(blocking=False)
    r._inner.release()


def test_wrapped_acquire_is_allocation_bounded(runtime):
    """The flight-ring precedent: steady-state acquire/release through
    the proxy must not grow the heap (the ring is bounded; the held
    stack drains to empty)."""
    from graphdyn.obs import flight

    lock = rc.TracedLock(threading.Lock(), "tm-probe")
    # warm PAST the flight ring's capacity: until the 512-slot deque is
    # full, every acquire's counter event grows the ring — steady state
    # (one dict in, one dict out) starts only after that
    for _ in range(flight.capacity() + 64):
        with lock:
            pass
    tracemalloc.start()
    before = tracemalloc.take_snapshot()
    for _ in range(500):
        with lock:
            pass
    after = tracemalloc.take_snapshot()
    tracemalloc.stop()
    growth = sum(s.size_diff for s in after.compare_to(before, "filename")
                 if s.size_diff > 0)
    assert growth < 64 * 1024, f"proxy allocated {growth} B over 500 acquires"


def test_fuzz_seeding_contract():
    """The documented contract: jitter is a pure function of (seed, lock,
    thread, op) — identical across calls, different across seeds, capped
    by max_ms."""
    d1 = rc._fuzz_delay_s(7, "L", "MainThread", "acquire", 100.0)
    assert d1 == rc._fuzz_delay_s(7, "L", "MainThread", "acquire", 100.0)
    others = [rc._fuzz_delay_s(s, "L", "MainThread", "acquire", 100.0)
              for s in range(8) if s != 7]
    assert any(d != d1 for d in others)
    assert 0.0 <= d1 <= 0.1


def test_mirror_save_works_under_proxies_and_fuzz(tmp_path, runtime):
    """A real durable save + write-behind mirror under wrapped locks and
    small jitter: the worker thread drains through the proxy without
    deadlock and the replica lands."""
    import numpy as np

    from graphdyn.resilience import store

    rc._runtime["fuzz"] = {"seed": 5, "max_ms": 2.0}
    try:
        store.configure_store(mirror=str(tmp_path / "mirror"), keep=4)
        ck = store.DurableCheckpoint(str(tmp_path / "primary" / "ck"))
        for i in range(3):
            ck.save({"a": np.arange(8) + i}, {"i": i})
        store.flush_mirror()
        replicas = list((tmp_path / "mirror").glob("*/ck.v3.npz"))
        assert replicas, "mirror replica missing under the lock proxy"
    finally:
        rc._runtime["fuzz"] = None
        store.configure_store(mirror=None)


def test_crash_dump_names_held_locks(tmp_path, runtime, monkeypatch):
    """The post-mortem story: a wedged run's obs.crash event stamps what
    every thread currently HOLDS (locks_held), independent of whether the
    per-acquire ring events survived rotation — the heartbeat-stamp
    precedent applied to locks."""
    from graphdyn.obs import flight
    from graphdyn.obs.recorder import read_ledger

    monkeypatch.chdir(tmp_path)
    flight.clear()
    lock = rc.TracedLock(threading.Lock(), "wedge-probe")
    lock.acquire()
    try:
        path = flight.dump("stall", site="test-wedge")
        assert path is not None
        events, _ = read_ledger(path)
        crash = [e for e in events if e.get("name") == "obs.crash"][-1]
        held = crash["attrs"]["locks_held"]
        assert any("wedge-probe" in v for v in held.values()), held
    finally:
        lock.release()
    assert not rc.held_locks(), "released lock still in the held snapshot"


# ---------------------------------------------------------------------------
# the CLI smoke under GRAPHDYN_RACECHECK=1 (subprocess regression)
# ---------------------------------------------------------------------------


def test_cli_entropy_smoke_finding_free_under_racecheck(tmp_path):
    """A real CLI run with the runtime auditor armed (plus a small fuzz
    seed) completes finding-free: exit 0, results written, no
    LockOrderError, no post-mortem — pins that the production lock
    discipline holds under the proxy and that the proxy never deadlocks
    the obs/journal/heartbeat paths it wraps."""
    out = tmp_path / "res.npz"
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "GRAPHDYN_RACECHECK": "1", "GRAPHDYN_RACEFUZZ": "1",
           "GRAPHDYN_RACEFUZZ_MAX_MS": "3"}
    proc = subprocess.run(
        [sys.executable, "-m", "graphdyn", "entropy", "--n", "50",
         "--deg", "1.5", "--num-rep", "1", "--lmbd-max", "0.3",
         "--lmbd-step", "0.1", "--max-sweeps", "200", "--eps", "1e-5",
         "--seed", "1", "--out", str(out)],
        cwd=REPO, capture_output=True, text=True, timeout=240, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert out.exists()
    assert "LockOrderError" not in proc.stderr
    assert not (tmp_path / "obs_postmortem.jsonl").exists()
