"""Merge contract of scripts/collect_tpu_session.py.

The collector folds a chip-session output directory into the round's
benchmark doc; it is the last hop between scarce chip measurements and the
committed artifact, so its guards are pinned: never stamp 'captured' over
an empty session, never let fallback-backend rates masquerade as chip
numbers, and tolerate the partial files a wedge-killed session leaves.
"""

import importlib.util
import json
import os

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "collect_tpu_session", os.path.join(ROOT, "scripts", "collect_tpu_session.py"))
cts = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(cts)

HEADLINE = {"metric": "spin_updates_per_sec_per_chip_d3_rrg_n1000000",
            "value": 3.0e11, "unit": "spin-updates/s", "backend": "tpu"}


@pytest.fixture
def session(tmp_path):
    sdir = tmp_path / "session"
    sdir.mkdir()
    doc_path = tmp_path / "bench_configs.json"
    doc_path.write_text(json.dumps({"round": 4, "status": "smoke captured"}))
    return sdir, str(doc_path)


def _write_headline(sdir, row=HEADLINE):
    (sdir / "bench_headline.json").write_text(json.dumps(row) + "\n")


def test_merges_headline_and_stamps_idempotently(session):
    sdir, doc_path = session
    _write_headline(sdir)
    assert cts.main(str(sdir), doc_path) == 0
    doc = json.loads(open(doc_path).read())
    assert doc["tpu_full"]["headline"]["value"] == 3.0e11
    assert "tpu_full captured from session" in doc["status"]
    # second merge must not duplicate the stamp
    assert cts.main(str(sdir), doc_path) == 0
    doc2 = json.loads(open(doc_path).read())
    assert doc2["status"].count("tpu_full captured from session") == 1


def test_refuses_empty_session(session):
    sdir, doc_path = session
    before = open(doc_path).read()
    assert cts.main(str(sdir), doc_path) == 1
    assert open(doc_path).read() == before


def test_refuses_startup_flush_only_configs_doc(session):
    """The aggregator writes a valid-but-empty doc before config 1 runs; a
    session killed right there must not count as captured."""
    sdir, doc_path = session
    (sdir / "configs_tpu.json").write_text(json.dumps(
        {"backend": "unknown", "mode": "full", "configs": [], "ok": False}))
    before = open(doc_path).read()
    assert cts.main(str(sdir), doc_path) == 1
    assert open(doc_path).read() == before


def test_warns_on_fallback_backend_headline_and_configs(session):
    sdir, doc_path = session
    _write_headline(sdir, {**HEADLINE, "backend": "cpu"})
    (sdir / "configs_tpu.json").write_text(json.dumps(
        {"backend": "cpu", "mode": "full", "ok": True,
         "configs": [{"config": "config1_sa_rrg", "rc": 0, "metrics": [{}]}]}))
    assert cts.main(str(sdir), doc_path) == 0
    doc = json.loads(open(doc_path).read())
    assert "NOT chip numbers" in doc["tpu_full"]["warning"]
    assert "NOT chip numbers" in doc["tpu_full"]["configs_warning"]


def test_chip_backends_do_not_warn(session):
    sdir, doc_path = session
    _write_headline(sdir)
    (sdir / "configs_tpu.json").write_text(json.dumps(
        {"backend": "axon", "mode": "full", "ok": True,
         "configs": [{"config": "config1_sa_rrg", "rc": 0, "metrics": [{}]}]}))
    assert cts.main(str(sdir), doc_path) == 0
    doc = json.loads(open(doc_path).read())
    assert "warning" not in doc["tpu_full"]
    assert "configs_warning" not in doc["tpu_full"]


def test_truncated_physics_recorded_without_killing_merge(session):
    sdir, doc_path = session
    _write_headline(sdir)
    (sdir / "physics_tpu.json").write_text('{"m_final": 1.0, "sw')  # cut mid-dump
    assert cts.main(str(sdir), doc_path) == 0
    doc = json.loads(open(doc_path).read())
    assert "unparseable physics_tpu.json" in doc["tpu_full"]["physics_error"]
    assert doc["tpu_full"]["headline"]["value"] == 3.0e11
