"""BDCM tests (SURVEY.md §4): encoding bijectivity, factor tensors vs direct
scalar evaluation of the reference conditions, and the strongest anchor — BP
exactness on trees vs brute-force enumeration of all initial configurations."""

import itertools

import numpy as np
import pytest

import jax.numpy as jnp

from graphdyn.attractors import (
    edge_factor_tensor,
    leaf_factor_tensor,
    node_factor_tensor,
    order_index,
    rho_lattice,
    trajectories01,
)
from graphdyn.graphs import graph_from_edges, random_regular_graph
from graphdyn.ops.bdcm import (
    BDCMData,
    make_free_entropy,
    make_leaf_setter,
    make_marginals,
    make_mean_m_init,
    make_sweep,
)


# --- reference-style scalar conditions (direct transcription of semantics,
# --- used as the oracle for the vectorized tensors) -------------------------

def ref_atr(xi, xj, rho, p, c):
    tot = rho[p + c - 1] + xj[p + c - 1]
    if xi[p] == np.sign(tot):
        return 1
    if tot == 0 and xi[p] == xi[p + c - 1]:
        return 1
    return 0


def ref_traj(xi, xj, rho, p, c):
    for t in range(p + c - 1):
        tot = rho[t] + xj[t]
        if xi[t + 1] == np.sign(tot):
            continue
        if tot == 0 and xi[t + 1] == xi[t]:
            continue
        return 0
    return 1


def test_trajectory_enumeration_order():
    for T in (1, 2, 3):
        want = np.array(list(itertools.product([1, 0], repeat=T)))
        np.testing.assert_array_equal(trajectories01(T), want)


def test_order_index_bijective_and_allones_zero():
    T = 2
    X = trajectories01(T)
    seen = set()
    for i, xi in enumerate(X):
        for j, xj in enumerate(X):
            idx = order_index(xi, xj)
            # matches position in the double enumeration
            assert idx == i * len(X) + j
            seen.add(idx)
    assert seen == set(range(len(X) ** 2))
    assert order_index(np.ones(T, int), np.ones(T, int)) == 0


@pytest.mark.parametrize("d,p,c", [(1, 1, 1), (2, 1, 1), (3, 1, 1), (2, 2, 1), (3, 3, 1), (2, 1, 2)])
def test_edge_factor_matches_scalar_reference(d, p, c):
    T = p + c
    A = edge_factor_tensor(d, p, c, attr_value=1)
    X = 2 * trajectories01(T) - 1
    Rho = 2 * rho_lattice(d, T) - d
    for i, xi in enumerate(X):
        for j, xj in enumerate(X):
            for r, rho in enumerate(Rho):
                want = (
                    ref_atr(xi, xj, rho, p, c)
                    * ref_traj(xi, xj, rho, p, c)
                    * (xi[T - 1] == 1)
                )
                assert A[i, j, r] == want, (xi, xj, rho)


def test_node_factor_matches_scalar_reference():
    p = c = 1
    T = 2
    for d in (1, 2, 3):
        Ai = node_factor_tensor(d, p, c, attr_value=1)
        X = 2 * trajectories01(T) - 1
        Rho = 2 * rho_lattice(d, T) - d
        for i, xi in enumerate(X):
            for r, rho in enumerate(Rho):
                # node variant: total includes all neighbors, no xj
                zero = np.zeros(T, dtype=int)
                want = (
                    ref_atr(xi, zero, rho, p, c)
                    * ref_traj(xi, zero, rho, p, c)
                    * (xi[T - 1] == 1)
                )
                assert Ai[i, r] == want


def test_leaf_factor_is_zero_rho_edge_factor():
    A0 = edge_factor_tensor(0, 1, 1)
    L = leaf_factor_tensor(1, 1)
    np.testing.assert_array_equal(A0[:, :, 0], L)


# --- BP exactness on trees --------------------------------------------------

def brute_force_phi_minit(graph, p, c, lmbd, attr_value=1):
    """Enumerate all 2^n initial configs; dynamics are deterministic so the
    trajectory measure reduces to a sum over valid initializations."""
    from graphdyn.ops.dynamics import run_dynamics

    n = graph.n
    T = p + c
    Z = 0.0
    M0 = 0.0
    for bits in range(2**n):
        s0 = np.array([1 if (bits >> k) & 1 else -1 for k in range(n)], np.int8)
        traj = [s0]
        s = s0
        for _ in range(T):
            s = run_dynamics(graph, s, 1, backend="cpu")
            traj.append(s)
        ok = np.all(traj[T] == traj[p]) and np.all(traj[T - 1] == attr_value)
        if ok:
            w = np.exp(-lmbd * float(s0.sum()))
            Z += w
            M0 += w * float(s0.sum())
    return np.log(Z) / n, M0 / Z / n


def run_fixed_point(data, lmbd, damp=0.3, eps=1e-12, max_iter=4000, seed=0):
    sweep = make_sweep(data, damp=damp)
    set_leaves = make_leaf_setter(data)
    chi = data.init_messages(seed)
    chi = set_leaves(chi, jnp.float32(lmbd))
    for _ in range(max_iter):
        new = sweep(chi, jnp.float32(lmbd))
        delta = float(jnp.abs(new - chi).max())
        chi = new
        if delta < eps:
            break
    return chi


TREES = {
    "path4": [(0, 1), (1, 2), (2, 3)],
    "star4": [(0, 1), (0, 2), (0, 3)],
    "caterpillar8": [(0, 1), (1, 2), (2, 3), (1, 4), (2, 5), (0, 6), (3, 7)],
}


@pytest.mark.parametrize("name", list(TREES))
@pytest.mark.parametrize("lmbd", [0.0, 0.4, 1.1])
def test_bp_exact_on_trees(name, lmbd):
    edges = np.array(TREES[name])
    n = int(edges.max()) + 1
    g = graph_from_edges(n, edges)
    p = c = 1
    data = BDCMData(g, p=p, c=c)
    chi = run_fixed_point(data, lmbd)
    phi_fn = make_free_entropy(data, n_total=n, n_iso=0)
    minit_fn = make_mean_m_init(data, n_total=n, n_iso=0)
    phi = float(phi_fn(chi, jnp.float32(lmbd)))
    m0 = float(minit_fn(chi))
    phi_ex, m0_ex = brute_force_phi_minit(g, p, c, lmbd)
    assert abs(phi - phi_ex) < 5e-5, (phi, phi_ex)
    assert abs(m0 - m0_ex) < 5e-5, (m0, m0_ex)


def test_bp_exact_on_tree_p2():
    edges = np.array(TREES["caterpillar8"])
    g = graph_from_edges(8, edges)
    data = BDCMData(g, p=2, c=1)
    chi = run_fixed_point(data, 0.3)
    phi = float(make_free_entropy(data, n_total=8, n_iso=0)(chi, jnp.float32(0.3)))
    m0 = float(make_mean_m_init(data, n_total=8, n_iso=0)(chi))
    phi_ex, m0_ex = brute_force_phi_minit(g, 2, 1, 0.3)
    assert abs(phi - phi_ex) < 5e-5
    assert abs(m0 - m0_ex) < 5e-5


def test_sweep_preserves_normalization():
    g = random_regular_graph(24, 3, seed=1)
    data = BDCMData(g, p=1, c=1)
    sweep = make_sweep(data, damp=0.4)
    chi = data.init_messages(2)
    for _ in range(5):
        chi = sweep(chi, jnp.float32(0.5))
    sums = np.asarray(chi.sum(axis=(1, 2)))
    np.testing.assert_allclose(sums, 1.0, atol=1e-5)


def test_marginals_normalized_and_shaped():
    g = random_regular_graph(24, 3, seed=3)
    data = BDCMData(g, p=1, c=1)
    chi = data.init_messages(4)
    marg = np.asarray(make_marginals(data)(chi))
    assert marg.shape == (24, 2)
    np.testing.assert_allclose(marg.sum(axis=1), 1.0, atol=1e-6)
    assert np.all(marg >= 0)


class TestEnsemble:
    """Vmapped congruent-ensemble path == serial per-graph path."""

    def _datas(self, G=3, n=60, d=3):
        from graphdyn.ops.bdcm import BDCMData
        from graphdyn.graphs import random_regular_graph

        graphs = [random_regular_graph(n, d, seed=k) for k in range(G)]
        return graphs, [BDCMData(g, p=1, c=1) for g in graphs]

    def test_ensemble_sweep_matches_serial(self):
        import jax.numpy as jnp
        from graphdyn.ops.bdcm import EnsembleBDCM, make_ensemble_sweep, make_sweep

        graphs, datas = self._datas()
        ens = EnsembleBDCM(datas)
        esweep = make_ensemble_sweep(ens, damp=0.2)
        chi = np.asarray(ens.init_messages(seed=1))
        lam = jnp.float32(0.6)
        out_e = np.asarray(esweep(jnp.asarray(chi), lam))
        for k, data in enumerate(datas):
            sw = make_sweep(data, damp=0.2, use_pallas=False)
            want = np.asarray(sw(jnp.asarray(chi[k]), lam))
            np.testing.assert_allclose(out_e[k], want, rtol=2e-5, atol=1e-7)

    def test_ensemble_observables_match_serial(self):
        import jax.numpy as jnp
        from graphdyn.ops.bdcm import (
            EnsembleBDCM,
            make_ensemble_free_entropy,
            make_ensemble_m_init,
            make_free_entropy,
            make_mean_m_init,
        )

        graphs, datas = self._datas()
        ens = EnsembleBDCM(datas)
        chi = ens.init_messages(seed=2)
        lam = jnp.float32(0.3)
        phis = np.asarray(make_ensemble_free_entropy(ens)(chi, lam))
        ms = np.asarray(make_ensemble_m_init(ens)(chi))
        for k, (g, data) in enumerate(zip(graphs, datas)):
            phi1 = float(make_free_entropy(data, n_total=g.n, n_iso=0)(chi[k], lam))
            m1 = float(make_mean_m_init(data, n_total=g.n, n_iso=0)(chi[k]))
            np.testing.assert_allclose(phis[k], phi1, rtol=1e-5, atol=1e-6)
            np.testing.assert_allclose(ms[k], m1, rtol=1e-5, atol=1e-6)

    def test_incongruent_rejected(self):
        import pytest
        from graphdyn.ops.bdcm import BDCMData, EnsembleBDCM
        from graphdyn.graphs import random_regular_graph

        a = BDCMData(random_regular_graph(40, 3, seed=0), p=1, c=1)
        b = BDCMData(random_regular_graph(40, 4, seed=0), p=1, c=1)
        with pytest.raises(ValueError, match="congruent"):
            EnsembleBDCM([a, b])

    def test_mismatched_dynamics_rejected(self):
        import pytest
        from graphdyn.ops.bdcm import BDCMData, EnsembleBDCM
        from graphdyn.graphs import random_regular_graph

        g = random_regular_graph(40, 3, seed=0)
        a = BDCMData(g, p=1, c=2)
        b = BDCMData(random_regular_graph(40, 3, seed=1), p=2, c=1)
        with pytest.raises(ValueError, match="dynamics parameters"):
            EnsembleBDCM([a, b])


class TestBucketedClasses:
    """class_bucket ghost padding: identical math, shared compiled programs."""

    def test_bucketed_sweep_matches_unbucketed(self):
        import jax.numpy as jnp
        from graphdyn.graphs import erdos_renyi_graph
        from graphdyn.ops.bdcm import BDCMData, make_sweep

        g = erdos_renyi_graph(300, 3.0 / 299, seed=7)
        a = BDCMData(g, p=1, c=1)
        b = BDCMData(g, p=1, c=1, class_bucket=64)
        sa_ = make_sweep(a, damp=0.2, use_pallas=False)
        sb = make_sweep(b, damp=0.2, use_pallas=False)
        chi = a.init_messages(seed=0)
        lam = jnp.float32(0.5)
        ca, cb = chi, chi
        for _ in range(3):
            ca = sa_(ca, lam)
            cb = sb(cb, lam)
        np.testing.assert_allclose(np.asarray(cb), np.asarray(ca), rtol=1e-6, atol=1e-8)

    def test_bucketed_partitions_match(self):
        import jax.numpy as jnp
        from graphdyn.graphs import erdos_renyi_graph, remove_isolates
        from graphdyn.ops.bdcm import (
            BDCMData, make_free_entropy, make_mean_m_init,
        )

        g, _ = remove_isolates(erdos_renyi_graph(200, 2.0 / 199, seed=3))
        a = BDCMData(g, p=1, c=1)
        b = BDCMData(g, p=1, c=1, class_bucket=32)
        chi = a.init_messages(seed=2)
        lam = jnp.float32(0.3)
        pa = float(make_free_entropy(a, n_total=g.n, n_iso=0)(chi, lam))
        pb = float(make_free_entropy(b, n_total=g.n, n_iso=0)(chi, lam))
        np.testing.assert_allclose(pb, pa, rtol=1e-6)
        ma = float(make_mean_m_init(a, n_total=g.n, n_iso=0)(chi))
        mb = float(make_mean_m_init(b, n_total=g.n, n_iso=0)(chi))
        np.testing.assert_allclose(mb, ma, rtol=1e-6)

    def test_entropy_sweep_bucketed_matches(self):
        from graphdyn.config import EntropyConfig
        from graphdyn.graphs import erdos_renyi_graph
        from graphdyn.models.entropy import entropy_sweep

        g = erdos_renyi_graph(150, 1.8 / 149, seed=4)
        lambdas = np.array([0.0, 0.2])
        r0 = entropy_sweep(g, EntropyConfig(), seed=1, lambdas=lambdas)
        r1 = entropy_sweep(
            g, EntropyConfig(), seed=1, lambdas=lambdas, class_bucket=64
        )
        np.testing.assert_allclose(r1.ent1, r0.ent1, atol=1e-5)
        np.testing.assert_allclose(r1.m_init, r0.m_init, atol=1e-5)

    def test_compile_cache_shared_across_instances(self):
        """Two same-signature graphs (RRG seeds) must reuse one compiled
        fixed-point program — the whole point of the shared executors."""
        from graphdyn.config import EntropyConfig
        from graphdyn.graphs import random_regular_graph
        from graphdyn.models.entropy import _fixed_point_exec, make_fixed_point
        from graphdyn.ops.bdcm import BDCMData

        import jax.numpy as jnp

        cfg = EntropyConfig()
        before = _fixed_point_exec._cache_size()
        sizes = []
        for seed in (11, 12):
            g = random_regular_graph(60, 3, seed=seed)
            data = BDCMData(g, p=1, c=1)
            fp = make_fixed_point(data, cfg)
            fp(data.init_messages(seed), jnp.float32(0.1))
            sizes.append(_fixed_point_exec._cache_size())
        assert sizes[0] <= before + 1
        assert sizes[1] == sizes[0], "second instance must hit the jit cache"


class TestStackBDCM:
    """stack_bdcm: ragged per-cell tables → the padded [G, Ed_max, …]
    cell-group layout (ghost-row machinery lifted to the cell axis)."""

    def _cells(self):
        from graphdyn.graphs import erdos_renyi_graph, remove_isolates

        graphs = [
            erdos_renyi_graph(40, 1.0 / 39, seed=1),
            erdos_renyi_graph(60, 2.5 / 59, seed=2),   # different n, E, classes
            erdos_renyi_graph(24, 1.2 / 23, seed=5),
        ]
        datas = []
        for g in graphs:
            sub, _ = remove_isolates(g)
            datas.append(BDCMData(sub, p=1, c=1))
        return datas

    def test_ragged_padding_layout(self):
        from graphdyn.ops.bdcm import stack_bdcm

        datas = self._cells()
        stk = stack_bdcm(datas)
        ghost = stk.twoE_max
        assert stk.twoE_max == max(d.num_directed for d in datas)
        # union of the cells' degree classes, each padded to its max
        # population; pad entries gather from/scatter to the ghost row
        union_ds = sorted({c.d for d in datas for c in d.edge_classes})
        assert [d for d, _, _, _ in stk.edge_classes] == union_ds
        for d, idx, ie, A in stk.edge_classes:
            assert idx.shape[0] == len(datas) and ie.shape[2] == d
            for g, data in enumerate(datas):
                cls = next((c for c in data.edge_classes if c.d == d), None)
                m = cls.idx.shape[0] if cls is not None else 0
                if cls is not None:
                    np.testing.assert_array_equal(idx[g, :m], cls.idx)
                    np.testing.assert_array_equal(ie[g, :m], cls.in_edges)
                # a cell missing the class (or its padded tail) is all-ghost
                assert (idx[g, m:] == ghost).all()
                assert (ie[g, m:] == ghost).all()
                # real entries never alias the ghost row
                assert (idx[g, :m] < data.num_directed).all()

    def test_bucketed_ghost_references_remapped(self):
        """class_bucket padding points at each CELL's own ghost row 2E_g;
        stacking must remap those to the stacked ghost 2E_max."""
        from graphdyn.graphs import erdos_renyi_graph, remove_isolates
        from graphdyn.ops.bdcm import stack_bdcm

        datas = []
        for s, n in ((1, 40), (2, 60)):
            sub, _ = remove_isolates(erdos_renyi_graph(n, 1.5 / (n - 1), seed=s))
            datas.append(BDCMData(sub, p=1, c=1, class_bucket=32))
        stk = stack_bdcm(datas)
        ghost = stk.twoE_max
        for g, data in enumerate(datas):
            for d, idx, ie, _ in stk.edge_classes:
                own = np.concatenate([idx[g], ie[g].ravel()])
                # nothing points at the CELL-local ghost of the smaller cell
                if data.num_directed != ghost:
                    real = own[own != ghost]
                    assert (real < data.num_directed).all()

    def test_stacked_sweep_matches_per_cell(self):
        """One chunk of the stacked fixed point reproduces each cell's own
        serial sweep trajectory bit-for-bit; chi pad rows stay untouched."""
        import jax.numpy as jnp

        from graphdyn.config import EntropyConfig
        from graphdyn.graphs import erdos_renyi_graph, remove_isolates
        from graphdyn.pipeline.entropy_group import EntropyCellExec

        cfg = EntropyConfig(lmbd_max=0.2, lmbd_step=0.1, max_sweeps=7)
        cells, chis = [], []
        for s, n in ((1, 40), (2, 60), (5, 24)):
            g = erdos_renyi_graph(n, 1.5 / (n - 1), seed=s)
            sub, n_iso = remove_isolates(g)
            data = BDCMData(sub, p=1, c=1)
            cells.append((data, g.n, n_iso))
            chis.append(np.asarray(data.init_messages(s)))
        ex = EntropyCellExec(cells, cfg, group_size=4)   # padded tail lane
        chi0 = ex.stack_chi(chis)
        lm = jnp.asarray(np.full(4, 0.1), ex.dtype)
        act = jnp.asarray(np.array([True, True, True, False]))
        d0 = jnp.full((4,), jnp.inf, ex.dtype)
        t0 = jnp.zeros((4,), jnp.int32)
        out, t_v, _ = ex.fixed_point_chunk(chi0, lm, act, d0, t0)
        assert np.asarray(t_v)[:3].tolist() == [7, 7, 7]  # ran to the budget
        for g, (data, _, _) in enumerate(cells):
            sweep = make_sweep(data, damp=cfg.damp, use_pallas=False)
            ref = jnp.asarray(chis[g])
            for _ in range(7):
                ref = sweep(ref, jnp.asarray(0.1, data.dtype))
            np.testing.assert_array_equal(
                np.asarray(ex.unstack_chi(out, g)), np.asarray(ref),
                err_msg=f"cell {g}",
            )
            # pad rows beyond the cell's own 2E never moved
            e2 = data.num_directed
            np.testing.assert_array_equal(
                np.asarray(out[g, e2:]), np.asarray(chi0[g, e2:]),
            )
        # the inactive pad lane froze entirely
        np.testing.assert_array_equal(np.asarray(out[3]), np.asarray(chi0[3]))

    def test_mismatched_dynamics_rejected(self):
        from graphdyn.graphs import erdos_renyi_graph, remove_isolates
        from graphdyn.ops.bdcm import stack_bdcm

        sub1, _ = remove_isolates(erdos_renyi_graph(40, 1.5 / 39, seed=1))
        sub2, _ = remove_isolates(erdos_renyi_graph(40, 1.5 / 39, seed=2))
        a = BDCMData(sub1, p=1, c=1)
        b = BDCMData(sub2, p=2, c=1)
        with pytest.raises(ValueError, match="dynamics parameters"):
            stack_bdcm([a, b])
        with pytest.raises(ValueError, match="empty"):
            stack_bdcm([])

    def test_stack_chi_validates_shapes(self):
        from graphdyn.graphs import erdos_renyi_graph, remove_isolates
        from graphdyn.ops.bdcm import stack_bdcm

        datas = self._cells()
        stk = stack_bdcm(datas)
        chis = [np.asarray(d.init_messages(0)) for d in datas]
        out = np.asarray(stk.stack_chi(chis))
        assert out.shape == (3, stk.twoE_max, stk.K, stk.K)
        with pytest.raises(ValueError, match="chi shape"):
            stk.stack_chi([chis[1], chis[0], chis[2]])
        with pytest.raises(ValueError, match="chi arrays"):
            stk.stack_chi(chis[:2])
