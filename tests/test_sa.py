"""SA solver tests: bit-parity vs the numpy oracle under common random
numbers (SURVEY.md §4.2), semantics of sentinels/annealing, replica batching."""

import numpy as np
import pytest

from graphdyn.config import DynamicsConfig, SAConfig
from graphdyn.graphs import random_regular_graph
from graphdyn.models.sa import simulated_annealing
from graphdyn.ops.dynamics import end_state


def _small_setup(n=60, d=3, R=3, L=1500, seed=5):
    g = random_regular_graph(n, d, seed=seed)
    rng = np.random.default_rng(seed + 1)
    s0 = (2 * rng.integers(0, 2, size=(R, n)) - 1).astype(np.int8)
    proposals = rng.integers(0, n, size=(R, L)).astype(np.int32)
    uniforms = rng.random(size=(R, L))
    return g, s0, proposals, uniforms


def test_parity_jax_vs_numpy_oracle():
    cfg = SAConfig(dynamics=DynamicsConfig(p=3, c=1))
    g, s0, proposals, uniforms = _small_setup()
    r_jax = simulated_annealing(
        g, cfg, s0=s0, proposals=proposals, uniforms=uniforms, backend="jax"
    )
    r_cpu = simulated_annealing(
        g, cfg, s0=s0, proposals=proposals, uniforms=uniforms, backend="cpu"
    )
    np.testing.assert_array_equal(r_jax.num_steps, r_cpu.num_steps)
    np.testing.assert_array_equal(r_jax.s, r_cpu.s)
    np.testing.assert_array_equal(r_jax.m_final, r_cpu.m_final)
    np.testing.assert_allclose(r_jax.mag_reached, r_cpu.mag_reached, atol=1e-6)


def test_success_means_consensus_rollout():
    cfg = SAConfig(dynamics=DynamicsConfig(p=3, c=1))
    g, s0, proposals, uniforms = _small_setup(R=2, L=3000, seed=9)
    r = simulated_annealing(
        g, cfg, s0=s0, proposals=proposals, uniforms=uniforms, backend="jax"
    )
    for k in range(2):
        if r.m_final[k] == 1.0:
            out = end_state(g, r.s[k], p=3, c=1, backend="cpu")
            assert np.all(out == 1)
            # strategic init: below-consensus initial magnetization
            assert r.mag_reached[k] < 1.0


def test_timeout_sentinel():
    cfg = SAConfig(dynamics=DynamicsConfig(p=3, c=1))
    g, s0, proposals, uniforms = _small_setup(R=2, L=40)
    # acceptance stream of ones => никогда accept unless exp(-dH) > 1
    uniforms = np.full_like(uniforms, 0.999999)
    r = simulated_annealing(
        g, cfg, s0=s0, proposals=proposals, uniforms=uniforms,
        max_steps=10, backend="jax",
    )
    assert np.all((r.m_final == 2.0) | (r.m_final == 1.0))
    done = r.m_final == 2.0
    assert np.all(r.num_steps[done] == 11)  # t incremented past max_steps


def test_prng_mode_converges_small():
    cfg = SAConfig(dynamics=DynamicsConfig(p=2, c=1))
    g = random_regular_graph(40, 3, seed=2)
    r = simulated_annealing(g, cfg, n_replicas=4, seed=3, max_steps=20_000)
    assert np.all(r.m_final == 1.0)
    for k in range(4):
        out = end_state(g, r.s[k], p=2, c=1, backend="cpu")
        assert np.all(out == 1)


def test_temperature_ladder_axis():
    cfg = SAConfig(dynamics=DynamicsConfig(p=1, c=1))
    g = random_regular_graph(30, 3, seed=7)
    a0 = np.linspace(0.01, 0.2, 5) * g.n
    b0 = np.linspace(0.01, 0.15, 5) * g.n
    r = simulated_annealing(
        g, cfg, n_replicas=5, seed=1, a0=a0, b0=b0, max_steps=20_000
    )
    assert r.s.shape == (5, g.n)
    # every ladder point either converged or hit the sentinel; most converge
    assert np.all((r.m_final == 1.0) | (r.m_final == 2.0))
    assert (r.m_final == 1.0).sum() >= 4


def test_already_converged_takes_zero_steps():
    cfg = SAConfig(dynamics=DynamicsConfig(p=1, c=1))
    g = random_regular_graph(30, 3, seed=7)
    s0 = np.ones((1, g.n), dtype=np.int8)
    r = simulated_annealing(g, cfg, s0=s0, seed=0)
    assert r.num_steps[0] == 0
    assert r.m_final[0] == 1.0
    assert r.mag_reached[0] == 1.0


def test_energy_observable():
    """E = (a·Σs(0) − b·Σs(end))/n (`SA_RRG.py:28-30`) vs a direct rollout."""
    from graphdyn.models.sa import energy
    from graphdyn.ops.dynamics import end_state

    g = random_regular_graph(50, 3, seed=2)
    rng = np.random.default_rng(0)
    s = (2 * rng.integers(0, 2, size=g.n) - 1).astype(np.int8)
    a, b, p, c = 3.0, 2.0, 2, 1
    e = energy(g, s, a, b, p, c, backend="cpu")
    s_end = end_state(g, s, p, c, backend="cpu")
    want = (a * s.astype(np.float64).sum() - b * s_end.astype(np.float64).sum()) / g.n
    assert abs(e - want) < 1e-12
    # batched form
    eb = energy(g, np.stack([s, -s]), a, b, p, c, backend="cpu")
    assert eb.shape == (2,)
    assert abs(eb[0] - want) < 1e-12
    # jax batched path == cpu oracle (integer dynamics -> exact)
    ej = energy(g, np.stack([s, -s]), a, b, p, c, backend="jax")
    np.testing.assert_allclose(ej, eb, rtol=0, atol=1e-12)


def test_sa_ensemble_driver(tmp_path):
    """Fresh graph per repetition + reference npz keys (`SA_RRG.py:58-92`)."""
    from graphdyn.models.sa import sa_ensemble
    from graphdyn.utils.io import load_results_npz

    p = str(tmp_path / "mcmc.npz")
    cfg = SAConfig(dynamics=DynamicsConfig(p=1, c=1))
    out = sa_ensemble(30, 3, cfg, n_stat=3, seed=0, max_steps=20_000, save_path=p)
    assert out.conf.shape == (3, 30)
    assert out.graphs.shape == (3, 30, 3)
    # different repetitions sampled different graphs
    assert not np.array_equal(out.graphs[0], out.graphs[1])
    saved = load_results_npz(p)
    assert set(saved) == {"mag_reached", "num_steps", "conf", "graphs"}


def test_checkpoint_resume_bit_exact(tmp_path, abort_after_save):
    """Chunked + checkpointed runs equal the uninterrupted run bit-for-bit,
    and a run restarted from a mid-flight checkpoint continues the same chain
    (SURVEY.md §5.4 exact SA-chain resume)."""
    import os

    cfg = SAConfig(dynamics=DynamicsConfig(p=1, c=1))
    g, s0, proposals, uniforms = _small_setup(n=50, R=3, L=4000, seed=9)
    kw = dict(s0=s0, proposals=proposals, uniforms=uniforms, backend="jax")
    base = simulated_annealing(g, cfg, **kw)

    # (a) chunking alone (checkpoint file written every chunk) changes nothing
    p1 = str(tmp_path / "sa_ck1")
    chunked = simulated_annealing(
        g, cfg, checkpoint_path=p1, checkpoint_interval_s=0.0, chunk_steps=37, **kw
    )
    np.testing.assert_array_equal(base.s, chunked.s)
    np.testing.assert_array_equal(base.num_steps, chunked.num_steps)
    np.testing.assert_array_equal(base.m_final, chunked.m_final)
    assert not os.path.exists(p1 + ".npz")      # removed on completion

    # (b) resume from a mid-flight snapshot: abort right after the first
    # checkpoint write, keep the file, restart from it and finish
    from conftest import CheckpointAbort

    p2 = str(tmp_path / "sa_ck2")
    with abort_after_save(n=1):
        with pytest.raises(CheckpointAbort):
            simulated_annealing(
                g, cfg, checkpoint_path=p2,
                checkpoint_interval_s=0.0, chunk_steps=50, **kw
            )
    assert os.path.exists(p2 + ".npz")          # a mid-flight snapshot exists
    resumed = simulated_annealing(
        g, cfg, checkpoint_path=p2, chunk_steps=64, **kw
    )
    np.testing.assert_array_equal(base.s, resumed.s)
    np.testing.assert_array_equal(base.num_steps, resumed.num_steps)
    np.testing.assert_array_equal(base.m_final, resumed.m_final)

    # (c) a checkpoint from a DIFFERENT graph/config is refused even when
    # seed/R/shape all match (full-identity fingerprint)
    g2 = random_regular_graph(50, 3, seed=77)   # same n, different edges
    with abort_after_save(n=1):
        with pytest.raises(CheckpointAbort):
            simulated_annealing(
                g, cfg, checkpoint_path=p2,
                checkpoint_interval_s=0.0, chunk_steps=50, **kw
            )
    with pytest.raises(ValueError, match="refusing to resume"):
        simulated_annealing(g2, cfg, checkpoint_path=p2, **kw)


def test_int64_step_budget_under_x64():
    """With x64 enabled a >2³¹ step budget (the 2n³ sentinel regime,
    `SA_RRG.py:84`) passes through UNCLAMPED into the device comparison —
    PRNG mode, so no injected-stream clamp shortens it — and the chains still
    converge with int64 counters."""
    import jax

    from graphdyn.config import DynamicsConfig

    cfg = SAConfig(dynamics=DynamicsConfig(p=2, c=1))
    g = random_regular_graph(40, 3, seed=2)
    jax.config.update("jax_enable_x64", True)
    try:
        res = simulated_annealing(g, cfg, n_replicas=4, seed=3, max_steps=2**40)
    finally:
        jax.config.update("jax_enable_x64", False)
    assert res.num_steps.dtype == np.int64
    assert np.all(res.m_final == 1.0)           # converged, not timed out
    assert np.all(res.num_steps < 2**31)        # finite steps under big budget


def test_sa_ensemble_driver_resume(tmp_path, abort_after_save):
    """A driver interrupted between repetitions resumes with completed reps
    intact and produces the same results and graphs as an uninterrupted run."""
    import os

    from conftest import CheckpointAbort
    from graphdyn.models.sa import sa_ensemble
    from graphdyn.utils.io import Checkpoint

    cfg = SAConfig(dynamics=DynamicsConfig(p=1, c=1))
    kw = dict(n_stat=3, seed=4, max_steps=30_000, backend="jax")
    base = sa_ensemble(30, 3, cfg, **kw)

    p = str(tmp_path / "sa_grid")
    with abort_after_save(when=lambda meta: meta.get("next_rep") == 2):
        with pytest.raises(CheckpointAbort):    # die after rep 2 of 3 lands
            sa_ensemble(30, 3, cfg, checkpoint_path=p,
                        checkpoint_interval_s=0.0, **kw)
    assert os.path.exists(p + ".npz")

    resumed = sa_ensemble(30, 3, cfg, checkpoint_path=p,
                        checkpoint_interval_s=0.0, **kw)
    np.testing.assert_array_equal(base.conf, resumed.conf)
    np.testing.assert_array_equal(base.num_steps, resumed.num_steps)
    np.testing.assert_array_equal(base.graphs, resumed.graphs)
    assert not os.path.exists(p + ".npz")

    # a mismatched-run checkpoint is refused, not silently misapplied
    Checkpoint(p).save({"mag_reached": base.mag_reached}, {"seed": 99,
                                                          "n_stat": 3,
                                                          "next_rep": 1})
    with pytest.raises(ValueError, match="different"):
        sa_ensemble(30, 3, cfg, checkpoint_path=p,
                        checkpoint_interval_s=0.0, **kw)


def test_lightcone_bit_parity_with_full():
    """Light-cone candidate evaluation (O(ball) per step) is bit-identical
    to the full-rollout solver under injected common-random-number streams —
    spins, step counts, sentinels — on RRG and ragged ER graphs."""
    from graphdyn.graphs import erdos_renyi_graph

    for gname, g in [
        ("rrg", random_regular_graph(60, 3, seed=5)),
        ("er", erdos_renyi_graph(70, 3.0 / 69, seed=8)),   # ragged + isolates
    ]:
        rng = np.random.default_rng(11)
        R, L = 3, 3000
        s0 = (2 * rng.integers(0, 2, size=(R, g.n)) - 1).astype(np.int8)
        proposals = rng.integers(0, g.n, size=(R, L)).astype(np.int32)
        uniforms = rng.random(size=(R, L))
        for p, c, rule, tie, budget in [
            # majority/stay configs keep the full L-step parity coverage
            (1, 1, "majority", "stay", None),
            (3, 1, "majority", "stay", None),
            (2, 2, "majority", "stay", None),
            # one hop per step holds for ANY local synchronous rule — the
            # cone argument is rule-independent; these chains may never
            # consense, so bound them (sentinel fires identically)
            (2, 1, "minority", "change", 1500),
            (2, 1, "majority", "change", 1500),
        ]:
            cfg = SAConfig(dynamics=DynamicsConfig(p=p, c=c, rule=rule, tie=tie))
            kw = dict(s0=s0, proposals=proposals, uniforms=uniforms,
                      backend="jax", max_steps=budget)
            full = simulated_annealing(g, cfg, rollout_mode="full", **kw)
            lc = simulated_annealing(g, cfg, rollout_mode="lightcone", **kw)
            np.testing.assert_array_equal(
                full.s, lc.s, err_msg=f"{gname} p={p} c={c} {rule}/{tie}"
            )
            np.testing.assert_array_equal(full.num_steps, lc.num_steps)
            np.testing.assert_array_equal(full.m_final, lc.m_final)
            np.testing.assert_array_equal(full.mag_reached, lc.mag_reached)


def test_lightcone_checkpoint_resume(tmp_path, abort_after_save):
    """Light-cone mode composes with exact resume: the trajectory cache is
    derived state, recomputed on restore, and the chain continues
    bit-for-bit."""
    import os

    from conftest import CheckpointAbort

    cfg = SAConfig(dynamics=DynamicsConfig(p=2, c=1))
    g, s0, proposals, uniforms = _small_setup(n=50, R=3, L=4000, seed=13)
    kw = dict(s0=s0, proposals=proposals, uniforms=uniforms, backend="jax",
              rollout_mode="lightcone")
    base = simulated_annealing(g, cfg, **kw)

    p = str(tmp_path / "lc_ck")
    with abort_after_save(n=1):
        with pytest.raises(CheckpointAbort):
            simulated_annealing(g, cfg, checkpoint_path=p,
                                checkpoint_interval_s=0.0, chunk_steps=40, **kw)
    assert os.path.exists(p + ".npz")
    resumed = simulated_annealing(g, cfg, checkpoint_path=p, chunk_steps=64, **kw)
    np.testing.assert_array_equal(base.s, resumed.s)
    np.testing.assert_array_equal(base.num_steps, resumed.num_steps)
    np.testing.assert_array_equal(base.m_final, resumed.m_final)


def test_lightcone_device_tables_bit_parity():
    """Device-built ball tables (gather/sort/searchsorted — no host BFS, no
    table upload) drive the light-cone solver to bit-identical chains vs the
    host-BFS tables AND vs the full rollout, on RRG and ragged ER. Slot
    order differs between the builders; the per-slot DP is order-independent
    so the chains must not."""
    from graphdyn.graphs import erdos_renyi_graph
    from graphdyn.ops.lightcone import (
        build_lightcone_tables,
        build_lightcone_tables_device,
    )

    for gname, g in [
        ("rrg", random_regular_graph(60, 3, seed=5)),
        ("er", erdos_renyi_graph(70, 3.0 / 69, seed=8)),   # ragged + isolates
    ]:
        rng = np.random.default_rng(21)
        R, L = 3, 2000
        s0 = (2 * rng.integers(0, 2, size=(R, g.n)) - 1).astype(np.int8)
        proposals = rng.integers(0, g.n, size=(R, L)).astype(np.int32)
        uniforms = rng.random(size=(R, L))
        for p, c in [(3, 1), (2, 2)]:
            cfg = SAConfig(dynamics=DynamicsConfig(p=p, c=c))
            radius = p + c - 1
            kw = dict(s0=s0, proposals=proposals, uniforms=uniforms,
                      backend="jax", rollout_mode="lightcone")
            host = simulated_annealing(
                g, cfg, lc_tables=build_lightcone_tables(g, radius), **kw
            )
            dev = simulated_annealing(
                g, cfg, lc_tables=build_lightcone_tables_device(g, radius),
                **kw
            )
            for f in ("s", "num_steps", "m_final", "mag_reached"):
                np.testing.assert_array_equal(
                    getattr(host, f), getattr(dev, f),
                    err_msg=f"{gname} p={p} c={c} field={f}",
                )
