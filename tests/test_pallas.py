"""Pallas fused BDCM kernel: interpret-mode equivalence with the XLA sweep.

The kernel (graphdyn/ops/pallas_bdcm.py) must reproduce the XLA path
(_neighbor_dp + einsum + clamp/normalize/damp) up to f32 accumulation order —
the flat mixed-radix ρ-shift must equal the per-axis rolls for every (d, T)
the reference targets, including the no-shift (all-ones trajectory) and
full-shift combos.

Marked ``pallas_interpret``: scripts/lint.sh pallascheck runs this file (and
tests/test_pallas_group.py, the grouped-kernel half) standalone.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from graphdyn.graphs import erdos_renyi_graph, random_regular_graph
from graphdyn.ops.bdcm import BDCMData, _neighbor_dp, make_sweep
from graphdyn.ops.pallas_bdcm import _flat_offsets, dp_contract, pallas_supported
from graphdyn.attractors import rho_lattice, trajectories01

pytestmark = pytest.mark.pallas_interpret


@pytest.mark.parametrize("d,T", [(1, 2), (2, 2), (3, 2), (4, 2), (3, 3), (2, 4)])
def test_flat_offsets_match_per_axis_rolls(d, T):
    """off_k applied to a flat index equals adding the trajectory bits per
    lattice axis, for every reachable (ρ, k) pair (no radix carry)."""
    X01 = trajectories01(T)
    Rho = rho_lattice(d, T)
    offs = _flat_offsets(d, T)
    radix = (d + 1) ** np.arange(T - 1, -1, -1)
    for k in range(2**T):
        reachable = (Rho + X01[k]).max(axis=1) <= d
        flat_from = (Rho * radix).sum(axis=1)
        flat_to = ((Rho + X01[k]) * radix).sum(axis=1)
        np.testing.assert_array_equal(
            flat_to[reachable], flat_from[reachable] + offs[k]
        )


@pytest.mark.parametrize("d,T,eps", [(3, 2, 0.0), (2, 2, 1e-10), (4, 2, 0.0), (3, 3, 0.0)])
def test_dp_contract_matches_xla(d, T, eps):
    rng = np.random.default_rng(7)
    K = 2**T
    M = (d + 1) ** T
    Ed = 200
    chi_in = jnp.asarray(rng.random((Ed, d, K, K)), jnp.float32)
    A = jnp.asarray(rng.random((K, K, M)), jnp.float32)
    chi_old = jnp.asarray(rng.random((Ed, K, K)), jnp.float32)
    damp = 0.3

    LL = _neighbor_dp(chi_in, d, T, K)
    chi2 = jnp.maximum(jnp.einsum("xym,exm->exy", A, LL), eps)
    z = chi2.sum(axis=(1, 2), keepdims=True)
    ref = damp * chi2 / jnp.maximum(z, jnp.finfo(jnp.float32).tiny) + (1 - damp) * chi_old

    out = dp_contract(
        chi_in, A, chi_old, d=d, T=T, damp=damp, eps_clamp=eps, interpret=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=5e-3, atol=1e-6)


def test_sweep_pallas_vs_xla_er():
    """Full sweep equivalence on a ragged ER instance (mixed degree classes;
    small classes fall back to XLA inside the same sweep)."""
    g = erdos_renyi_graph(500, 3.0 / 499, seed=3)
    data = BDCMData(g, p=1, c=1)
    sw_x = make_sweep(data, damp=0.2, use_pallas=False)
    sw_p = make_sweep(data, damp=0.2, use_pallas=True)
    chi = data.init_messages(seed=0)
    lam = jnp.float32(0.4)
    cx, cp = chi, chi
    for _ in range(3):
        cx = sw_x(cx, lam)
        cp = sw_p(cp, lam)
    np.testing.assert_allclose(np.asarray(cp), np.asarray(cx), rtol=5e-3, atol=1e-5)


def test_sweep_pallas_with_bias_rrg():
    g = random_regular_graph(300, 4, seed=1)
    data = BDCMData(g, p=1, c=1)
    kw = dict(damp=0.4, mask_invalid_src=False, with_bias=True)
    sw_x = make_sweep(data, use_pallas=False, **kw)
    sw_p = make_sweep(data, use_pallas=True, **kw)
    rng = np.random.default_rng(0)
    chi = data.init_messages(seed=5)
    bias = jnp.asarray(rng.random((2 * data.num_edges, data.K)), jnp.float32)
    lam = jnp.float32(25.0)
    cx = sw_x(chi, lam, bias)
    cp = sw_p(chi, lam, bias)
    np.testing.assert_allclose(np.asarray(cp), np.asarray(cx), rtol=5e-3, atol=1e-5)


def test_pallas_supported_gate():
    assert pallas_supported(3, 2, 1000)
    assert not pallas_supported(3, 2, 16)        # too few edges to fill lanes
    assert not pallas_supported(3, 5, 100000)    # horizon beyond reference regime
    assert not pallas_supported(12, 2, 100000)   # degree class too wide
