"""Sharded-vs-unsharded SA solver equivalence on the simulated CPU mesh.

The full multi-chip solver (`graphdyn.parallel.sa_sharded.sa_sharded`) must
reproduce the unsharded solver (`graphdyn.models.sa.simulated_annealing`)
*bitwise* — spins, step counts, sentinels — under both injected proposal
streams and the shared PRNG derivation, on replica×node meshes. This is the
SURVEY §4.4 fake-backend analogue of a multi-chip integration test.
"""

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from graphdyn.config import DynamicsConfig, SAConfig
from graphdyn.graphs import random_regular_graph
from graphdyn.models.sa import simulated_annealing
from graphdyn.parallel.mesh import device_pool, make_mesh
from graphdyn.parallel.sa_sharded import sa_sharded


def _mesh(rep, node):
    return make_mesh((rep, node), ("replica", "node"), devices=device_pool(rep * node))


def _setup(n=60, d=3, R=4, L=2000, seed=5):
    g = random_regular_graph(n, d, seed=seed)
    rng = np.random.default_rng(seed + 1)
    s0 = (2 * rng.integers(0, 2, size=(R, n)) - 1).astype(np.int8)
    proposals = rng.integers(0, n, size=(R, L)).astype(np.int32)
    uniforms = rng.random(size=(R, L))
    return g, s0, proposals, uniforms


@pytest.mark.parametrize("rep,node", [(4, 2), (2, 4), (8, 1), (1, 8)])
def test_injected_stream_bit_parity(rep, node):
    g, s0, proposals, uniforms = _setup()
    cfg = SAConfig()
    ref = simulated_annealing(g, cfg, s0=s0, proposals=proposals, uniforms=uniforms)
    got = sa_sharded(
        g, cfg, mesh=_mesh(rep, node), s0=s0, proposals=proposals, uniforms=uniforms
    )
    np.testing.assert_array_equal(got.s, ref.s)
    np.testing.assert_array_equal(got.num_steps, ref.num_steps)
    np.testing.assert_array_equal(got.m_final, ref.m_final)
    np.testing.assert_allclose(got.mag_reached, ref.mag_reached, rtol=1e-6)


def test_prng_mode_bit_parity():
    """The sharded solver derives (i, u) with the identical fold_in/split
    chain as the unsharded one, so PRNG mode is bit-equal too."""
    g, s0, _, _ = _setup(n=40, R=4, seed=7)
    cfg = SAConfig()
    ref = simulated_annealing(g, cfg, s0=s0, seed=3, max_steps=5000)
    got = sa_sharded(g, cfg, mesh=_mesh(4, 2), s0=s0, seed=3, max_steps=5000)
    np.testing.assert_array_equal(got.s, ref.s)
    np.testing.assert_array_equal(got.num_steps, ref.num_steps)
    np.testing.assert_array_equal(got.m_final, ref.m_final)


def test_replica_padding_and_timeout_sentinel():
    """R not divisible by the replica shards pads with frozen dummies; the
    timeout sentinel fires per replica exactly as unsharded (`SA_RRG.py:84`)."""
    g, s0, proposals, uniforms = _setup(n=60, R=3, L=40, seed=11)
    cfg = SAConfig()
    ref = simulated_annealing(
        g, cfg, s0=s0, proposals=proposals, uniforms=uniforms, max_steps=30
    )
    got = sa_sharded(
        g, cfg, mesh=_mesh(4, 2), s0=s0, proposals=proposals, uniforms=uniforms,
        max_steps=30,
    )
    assert got.s.shape == (3, g.n)
    np.testing.assert_array_equal(got.s, ref.s)
    np.testing.assert_array_equal(got.m_final, ref.m_final)
    np.testing.assert_array_equal(got.num_steps, ref.num_steps)


def test_temperature_ladder_axis_sharded():
    """Per-replica (a0, b0) — the config-5 temperature ladder — rides the
    replica axis of the mesh."""
    g, s0, proposals, uniforms = _setup(n=60, R=4, L=1500, seed=13)
    cfg = SAConfig()
    a0 = np.linspace(0.5, 2.0, 4) * g.n * 0.015
    ref = simulated_annealing(
        g, cfg, s0=s0, a0=a0, proposals=proposals, uniforms=uniforms
    )
    got = sa_sharded(
        g, cfg, mesh=_mesh(2, 2), s0=s0, a0=a0, proposals=proposals, uniforms=uniforms
    )
    np.testing.assert_array_equal(got.s, ref.s)
    np.testing.assert_array_equal(got.num_steps, ref.num_steps)


def test_ragged_degree_graph_bit_parity():
    """Ragged-degree (ER) graph with node padding: `Graph.nbr`'s ghost index
    n must keep reading spin 0 after `pad_nodes` moves the zero slot to
    n + n_pad (regression: ghost gathers aliased onto pad-column spins)."""
    from graphdyn.graphs import erdos_renyi_graph

    g = erdos_renyi_graph(59, 4.0 / 58, seed=3)     # n=59: pads on any mesh
    assert (g.deg < g.dmax).any()                   # ragged rows exist
    rng = np.random.default_rng(4)
    R, L = 4, 600
    s0 = (2 * rng.integers(0, 2, size=(R, g.n)) - 1).astype(np.int8)
    proposals = rng.integers(0, g.n, size=(R, L)).astype(np.int32)
    uniforms = rng.random(size=(R, L))
    cfg = SAConfig()
    ref = simulated_annealing(
        g, cfg, s0=s0, proposals=proposals, uniforms=uniforms, max_steps=500
    )
    got = sa_sharded(
        g, cfg, mesh=_mesh(2, 4), s0=s0, proposals=proposals, uniforms=uniforms,
        max_steps=500,
    )
    np.testing.assert_array_equal(got.s, ref.s)
    np.testing.assert_array_equal(got.num_steps, ref.num_steps)
    np.testing.assert_array_equal(got.m_final, ref.m_final)


def test_sharded_checkpoint_resume_bit_exact(tmp_path, abort_after_save):
    """Chunked+checkpointed mesh runs equal the uninterrupted mesh run (and
    therefore the unsharded solver) bit-for-bit; a mid-flight snapshot kept
    by an aborted run resumes to the identical result — including on a
    DIFFERENT mesh shape (state is saved unpadded/global)."""
    import os

    from graphdyn.utils.io import Checkpoint

    g, s0, proposals, uniforms = _setup()
    cfg = SAConfig(dynamics=DynamicsConfig(p=1, c=1))
    kw = dict(s0=s0, proposals=proposals, uniforms=uniforms)
    base = sa_sharded(g, cfg, mesh=_mesh(4, 2), **kw)

    p1 = str(tmp_path / "shck1")
    chunked = sa_sharded(
        g, cfg, mesh=_mesh(4, 2), checkpoint_path=p1,
        checkpoint_interval_s=0.0, chunk_steps=41, **kw
    )
    np.testing.assert_array_equal(base.s, chunked.s)
    np.testing.assert_array_equal(base.num_steps, chunked.num_steps)
    np.testing.assert_array_equal(base.m_final, chunked.m_final)
    assert not os.path.exists(p1 + ".npz")

    # abort after the first snapshot, then resume — on another mesh shape
    from conftest import CheckpointAbort

    p2 = str(tmp_path / "shck2")
    with abort_after_save(n=1):
        with pytest.raises(CheckpointAbort):
            sa_sharded(g, cfg, mesh=_mesh(4, 2), checkpoint_path=p2,
                       checkpoint_interval_s=0.0, chunk_steps=37, **kw)
    assert os.path.exists(p2 + ".npz")

    resumed = sa_sharded(g, cfg, mesh=_mesh(2, 4), checkpoint_path=p2,
                         checkpoint_interval_s=1e9, chunk_steps=64, **kw)
    np.testing.assert_array_equal(base.s, resumed.s)
    np.testing.assert_array_equal(base.num_steps, resumed.num_steps)
    np.testing.assert_array_equal(base.m_final, resumed.m_final)
    assert not os.path.exists(p2 + ".npz")

    # a foreign checkpoint is refused
    Checkpoint(p2).save({"s": s0}, {"kind": "sa_sharded_chain", "seed": 999,
                                    "R": 4})
    with pytest.raises(ValueError, match="refusing to resume"):
        sa_sharded(g, cfg, mesh=_mesh(4, 2), checkpoint_path=p2, **kw)


def test_lightcone_sharded_bit_parity_and_resume(tmp_path, abort_after_save):
    """rollout_mode='lightcone' on a replica-only mesh is bit-identical to
    BOTH full-rollout solvers under injected streams; a checkpoint written
    by the full-mode mesh solver resumes under lightcone mode (the snapshot
    is mode-agnostic: spins + chain scalars); a node-sharded mesh is
    refused."""
    import os

    g, s0, proposals, uniforms = _setup(n=60, d=4, R=4, L=2000, seed=21)
    cfg = SAConfig()                      # p=3, c=1 — radius-3 light cones
    kw = dict(s0=s0, proposals=proposals, uniforms=uniforms)

    ref = simulated_annealing(g, cfg, **kw)
    lc = sa_sharded(g, cfg, mesh=_mesh(8, 1), rollout_mode="lightcone", **kw)
    np.testing.assert_array_equal(ref.s, lc.s)
    np.testing.assert_array_equal(ref.num_steps, lc.num_steps)
    np.testing.assert_array_equal(ref.m_final, lc.m_final)

    with pytest.raises(ValueError, match="replica-only"):
        sa_sharded(g, cfg, mesh=_mesh(4, 2), rollout_mode="lightcone", **kw)

    # cross-mode resume: interrupt a full-mode run, finish it in lightcone
    # mode — identical to the uninterrupted chain
    from conftest import CheckpointAbort

    p = str(tmp_path / "lc_ck")
    with abort_after_save(n=1):
        with pytest.raises(CheckpointAbort):
            sa_sharded(g, cfg, mesh=_mesh(8, 1), checkpoint_path=p,
                       checkpoint_interval_s=0.0, chunk_steps=25, **kw)
    assert os.path.exists(p + ".npz")
    resumed = sa_sharded(g, cfg, mesh=_mesh(8, 1), rollout_mode="lightcone",
                         checkpoint_path=p, chunk_steps=5000, **kw)
    np.testing.assert_array_equal(ref.s, resumed.s)
    np.testing.assert_array_equal(ref.num_steps, resumed.num_steps)
    assert not os.path.exists(p + ".npz")

