"""Bit-packed replica kernel: exactness vs the int8 path on regular and
ragged graphs, all rules/ties, pack/unpack round trip."""

import numpy as np
import pytest

from graphdyn.graphs import erdos_renyi_graph, random_regular_graph
from graphdyn.ops.dynamics import run_dynamics
from graphdyn.ops.packed import pack_spins, packed_end_state, unpack_spins


def test_pack_unpack_round_trip(rng):
    s = rng.choice(np.array([-1, 1], dtype=np.int8), size=(70, 33))
    np.testing.assert_array_equal(unpack_spins(pack_spins(s), 70), s)


@pytest.mark.parametrize("rule", ["majority", "minority"])
@pytest.mark.parametrize("tie", ["stay", "change"])
def test_packed_matches_int8_rrg(rule, tie, rng):
    g = random_regular_graph(200, 4, seed=5)  # even degree: ties happen
    s = rng.choice(np.array([-1, 1], dtype=np.int8), size=(64, g.n))
    got = packed_end_state(g, s, 6, rule, tie)
    for r in range(64):
        want = run_dynamics(g, s[r], 6, rule, tie, backend="cpu")
        np.testing.assert_array_equal(got[r], want)


@pytest.mark.parametrize("rule", ["majority", "minority"])
@pytest.mark.parametrize("tie", ["stay", "change"])
def test_packed_matches_int8_ragged(rule, tie, rng):
    g = erdos_renyi_graph(300, 3.0 / 299, seed=7)  # ragged degrees + isolates
    R = 40  # not a multiple of 32: exercises replica padding
    s = rng.choice(np.array([-1, 1], dtype=np.int8), size=(R, g.n))
    got = packed_end_state(g, s, 5, rule, tie)
    for r in range(R):
        want = run_dynamics(g, s[r], 5, rule, tie, backend="cpu")
        np.testing.assert_array_equal(got[r], want)


def test_packed_high_degree(rng):
    g = erdos_renyi_graph(150, 12.0 / 149, seed=2)  # deg up to ~25: 5 planes
    s = rng.choice(np.array([-1, 1], dtype=np.int8), size=(32, g.n))
    got = packed_end_state(g, s, 3)
    for r in range(4):
        want = run_dynamics(g, s[r], 3, backend="cpu")
        np.testing.assert_array_equal(got[r], want)


@pytest.mark.parametrize("rule", ["majority", "minority"])
@pytest.mark.parametrize("tie", ["stay", "change"])
def test_gather_variants_bit_identical(rule, tie, rng):
    """The two HBM gather formulations (fused [n,dmax,W] buffer vs per-slot
    fused-into-CSA) are alternative schedules of the same bitwise program."""
    import jax.numpy as jnp

    from graphdyn.ops.packed import packed_rollout

    g = erdos_renyi_graph(250, 4.0 / 249, seed=11)
    sp = rng.integers(0, 2**32, size=(g.n, 3), dtype=np.uint32)
    a = packed_rollout(jnp.asarray(g.nbr), jnp.asarray(g.deg), jnp.asarray(sp),
                       7, rule, tie, gather="fused")
    b = packed_rollout(jnp.asarray(g.nbr), jnp.asarray(g.deg), jnp.asarray(sp),
                       7, rule, tie, gather="per_slot")
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_packed_consensus_fraction_matches_unpacked():
    from graphdyn.graphs import erdos_renyi_graph
    from graphdyn.observe import consensus_fraction
    from graphdyn.ops.packed import (
        pack_spins,
        packed_consensus_fraction,
        packed_rollout,
        unpack_spins,
    )
    import jax.numpy as jnp

    g = erdos_renyi_graph(200, 6.0 / 199, seed=3)
    rng = np.random.default_rng(0)
    R = 70  # not a multiple of 32: exercises pad-replica exclusion
    s = (2 * rng.integers(0, 2, size=(R, g.n)) - 1).astype(np.int8)
    sp = packed_rollout(
        jnp.asarray(g.nbr), jnp.asarray(g.deg), jnp.asarray(pack_spins(s)), 8
    )
    want_p1 = float(consensus_fraction(unpack_spins(np.asarray(sp), R), target=1))
    want_m1 = float(consensus_fraction(unpack_spins(np.asarray(sp), R), target=-1))
    assert abs(packed_consensus_fraction(sp, R, target=1) - want_p1) < 1e-6
    assert abs(packed_consensus_fraction(sp, R, target=-1) - want_m1) < 1e-6
    # sanity: majority dynamics on dense ER from random init reaches some
    # +1-consensus replicas after 8 steps (or the test is vacuous)
    assert want_p1 + want_m1 > 0


def test_packed_many_words_matches_int8(rng):
    """Multi-word replica axis (W=7 here; the bench's wide-replica lever
    runs W=512): per-word arithmetic is identical, so a direct parity spot
    check over several words pins the W-genericity."""
    g = random_regular_graph(120, 3, seed=9)
    R = 224                                  # 7 full words
    s = rng.choice(np.array([-1, 1], dtype=np.int8), size=(R, g.n))
    got = packed_end_state(g, s, 5, "majority", "stay")
    for r in (0, 31, 32, 63, 100, 223):      # word boundaries + interior
        want = run_dynamics(g, s[r], 5, "majority", "stay", backend="cpu")
        np.testing.assert_array_equal(got[r], want)


def test_draw_packed_biased_mean_bias():
    """Device-resident biased draw: bit density matches (1+m0)/2 and the
    per-replica magnetization estimator agrees with the unpacked mean."""
    from graphdyn.ops.packed import _replica_magnetization, draw_packed_biased

    n, W = 4000, 4
    for m0 in (0.0, 0.2, -0.3):
        sp = np.asarray(draw_packed_biased(5, n, W, m0))
        s = unpack_spins(sp, W * 32)                   # int8[R, n]
        assert abs(float(s.mean()) - m0) < 0.02
        m = np.asarray(_replica_magnetization(sp, W * 32))
        np.testing.assert_allclose(m, s.mean(axis=1), atol=1e-6)


def test_packed_consensus_scan_matches_unpacked_oracle(rng):
    """First-passage bookkeeping vs a step-by-step unpacked oracle: strict
    flags, chunk-resolution first-passage steps, and m_final all agree."""
    import jax.numpy as jnp

    from graphdyn.ops.packed import packed_consensus_scan

    g = erdos_renyi_graph(120, 6.0 / 120, seed=3)
    R, chunk, max_steps = 64, 5, 60
    s0 = (2 * rng.integers(0, 2, size=(R, g.n)) - 1).astype(np.int8)
    # bias half the replicas so both converged and unconverged cases occur
    s0[: R // 2] = np.where(
        rng.random((R // 2, g.n)) < 0.65, np.int8(1), np.int8(-1)
    )

    out = packed_consensus_scan(
        jnp.asarray(g.nbr), jnp.asarray(g.deg), jnp.asarray(pack_spins(s0)),
        R=R, max_steps=max_steps, chunk=chunk,
    )

    # oracle: roll the int8 kernel chunk by chunk, flag all-equal states
    s = s0.copy()
    strict_step = np.full(R, -1)
    for t in range(chunk, max_steps + 1, chunk):
        s = packed_end_state(g, s, chunk)
        cons = np.all(s == s[:, :1], axis=1)
        strict_step = np.where((strict_step < 0) & cons, t, strict_step)
        if int(out["steps_run"]) == t:
            break                                     # scan early-exited here

    np.testing.assert_array_equal(
        np.asarray(out["strict_step"]), strict_step
    )
    np.testing.assert_array_equal(
        np.asarray(out["strict"]), strict_step >= 0
    )
    np.testing.assert_allclose(
        np.asarray(out["m_final"]), s.mean(axis=1), atol=1e-6
    )
