"""Bit-packed replica kernel: exactness vs the int8 path on regular and
ragged graphs, all rules/ties, pack/unpack round trip."""

import numpy as np
import pytest

from graphdyn.graphs import erdos_renyi_graph, random_regular_graph
from graphdyn.ops.dynamics import run_dynamics
from graphdyn.ops.packed import pack_spins, packed_end_state, unpack_spins


def test_pack_unpack_round_trip(rng):
    s = rng.choice(np.array([-1, 1], dtype=np.int8), size=(70, 33))
    np.testing.assert_array_equal(unpack_spins(pack_spins(s), 70), s)


@pytest.mark.parametrize("rule", ["majority", "minority"])
@pytest.mark.parametrize("tie", ["stay", "change"])
def test_packed_matches_int8_rrg(rule, tie, rng):
    g = random_regular_graph(200, 4, seed=5)  # even degree: ties happen
    s = rng.choice(np.array([-1, 1], dtype=np.int8), size=(64, g.n))
    got = packed_end_state(g, s, 6, rule, tie)
    for r in range(64):
        want = run_dynamics(g, s[r], 6, rule, tie, backend="cpu")
        np.testing.assert_array_equal(got[r], want)


@pytest.mark.parametrize("rule", ["majority", "minority"])
@pytest.mark.parametrize("tie", ["stay", "change"])
def test_packed_matches_int8_ragged(rule, tie, rng):
    g = erdos_renyi_graph(300, 3.0 / 299, seed=7)  # ragged degrees + isolates
    R = 40  # not a multiple of 32: exercises replica padding
    s = rng.choice(np.array([-1, 1], dtype=np.int8), size=(R, g.n))
    got = packed_end_state(g, s, 5, rule, tie)
    for r in range(R):
        want = run_dynamics(g, s[r], 5, rule, tie, backend="cpu")
        np.testing.assert_array_equal(got[r], want)


def test_packed_high_degree(rng):
    g = erdos_renyi_graph(150, 12.0 / 149, seed=2)  # deg up to ~25: 5 planes
    s = rng.choice(np.array([-1, 1], dtype=np.int8), size=(32, g.n))
    got = packed_end_state(g, s, 3)
    for r in range(4):
        want = run_dynamics(g, s[r], 3, backend="cpu")
        np.testing.assert_array_equal(got[r], want)
