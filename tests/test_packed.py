"""Bit-packed replica kernel: exactness vs the int8 path on regular and
ragged graphs, all rules/ties, pack/unpack round trip."""

import numpy as np
import pytest

from graphdyn.graphs import erdos_renyi_graph, random_regular_graph
from graphdyn.ops.dynamics import run_dynamics
from graphdyn.ops.packed import pack_spins, packed_end_state, unpack_spins


def test_pack_unpack_round_trip(rng):
    s = rng.choice(np.array([-1, 1], dtype=np.int8), size=(70, 33))
    np.testing.assert_array_equal(unpack_spins(pack_spins(s), 70), s)


@pytest.mark.parametrize("rule", ["majority", "minority"])
@pytest.mark.parametrize("tie", ["stay", "change"])
def test_packed_matches_int8_rrg(rule, tie, rng):
    g = random_regular_graph(200, 4, seed=5)  # even degree: ties happen
    s = rng.choice(np.array([-1, 1], dtype=np.int8), size=(64, g.n))
    got = packed_end_state(g, s, 6, rule, tie)
    for r in range(64):
        want = run_dynamics(g, s[r], 6, rule, tie, backend="cpu")
        np.testing.assert_array_equal(got[r], want)


@pytest.mark.parametrize("rule", ["majority", "minority"])
@pytest.mark.parametrize("tie", ["stay", "change"])
def test_packed_matches_int8_ragged(rule, tie, rng):
    g = erdos_renyi_graph(300, 3.0 / 299, seed=7)  # ragged degrees + isolates
    R = 40  # not a multiple of 32: exercises replica padding
    s = rng.choice(np.array([-1, 1], dtype=np.int8), size=(R, g.n))
    got = packed_end_state(g, s, 5, rule, tie)
    for r in range(R):
        want = run_dynamics(g, s[r], 5, rule, tie, backend="cpu")
        np.testing.assert_array_equal(got[r], want)


def test_packed_high_degree(rng):
    g = erdos_renyi_graph(150, 12.0 / 149, seed=2)  # deg up to ~25: 5 planes
    s = rng.choice(np.array([-1, 1], dtype=np.int8), size=(32, g.n))
    got = packed_end_state(g, s, 3)
    for r in range(4):
        want = run_dynamics(g, s[r], 3, backend="cpu")
        np.testing.assert_array_equal(got[r], want)


@pytest.mark.parametrize("rule", ["majority", "minority"])
@pytest.mark.parametrize("tie", ["stay", "change"])
def test_gather_variants_bit_identical(rule, tie, rng):
    """The two HBM gather formulations (fused [n,dmax,W] buffer vs per-slot
    fused-into-CSA) are alternative schedules of the same bitwise program."""
    import jax.numpy as jnp

    from graphdyn.ops.packed import packed_rollout

    g = erdos_renyi_graph(250, 4.0 / 249, seed=11)
    sp = rng.integers(0, 2**32, size=(g.n, 3), dtype=np.uint32)
    a = packed_rollout(jnp.asarray(g.nbr), jnp.asarray(g.deg), jnp.asarray(sp),
                       7, rule, tie, gather="fused")
    b = packed_rollout(jnp.asarray(g.nbr), jnp.asarray(g.deg), jnp.asarray(sp),
                       7, rule, tie, gather="per_slot")
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_packed_consensus_fraction_matches_unpacked():
    from graphdyn.graphs import erdos_renyi_graph
    from graphdyn.observe import consensus_fraction
    from graphdyn.ops.packed import (
        pack_spins,
        packed_consensus_fraction,
        packed_rollout,
        unpack_spins,
    )
    import jax.numpy as jnp

    g = erdos_renyi_graph(200, 6.0 / 199, seed=3)
    rng = np.random.default_rng(0)
    R = 70  # not a multiple of 32: exercises pad-replica exclusion
    s = (2 * rng.integers(0, 2, size=(R, g.n)) - 1).astype(np.int8)
    sp = packed_rollout(
        jnp.asarray(g.nbr), jnp.asarray(g.deg), jnp.asarray(pack_spins(s)), 8
    )
    want_p1 = float(consensus_fraction(unpack_spins(np.asarray(sp), R), target=1))
    want_m1 = float(consensus_fraction(unpack_spins(np.asarray(sp), R), target=-1))
    assert abs(packed_consensus_fraction(sp, R, target=1) - want_p1) < 1e-6
    assert abs(packed_consensus_fraction(sp, R, target=-1) - want_m1) < 1e-6
    # sanity: majority dynamics on dense ER from random init reaches some
    # +1-consensus replicas after 8 steps (or the test is vacuous)
    assert want_p1 + want_m1 > 0


def test_packed_many_words_matches_int8(rng):
    """Multi-word replica axis (W=7 here; the bench's wide-replica lever
    runs W=512): per-word arithmetic is identical, so a direct parity spot
    check over several words pins the W-genericity."""
    g = random_regular_graph(120, 3, seed=9)
    R = 224                                  # 7 full words
    s = rng.choice(np.array([-1, 1], dtype=np.int8), size=(R, g.n))
    got = packed_end_state(g, s, 5, "majority", "stay")
    for r in (0, 31, 32, 63, 100, 223):      # word boundaries + interior
        want = run_dynamics(g, s[r], 5, "majority", "stay", backend="cpu")
        np.testing.assert_array_equal(got[r], want)
