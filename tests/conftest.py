"""Test harness: force CPU JAX with an 8-device simulated mesh (SURVEY.md §4.4
— the TPU-native analogue of a fake backend). Must run before jax imports."""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(1234)
