"""Test harness: force CPU JAX with an 8-device simulated mesh (SURVEY.md §4.4
— the TPU-native analogue of a fake backend). Must run before jax imports."""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

# a sitecustomize plugin may have pinned jax_platforms (e.g. 'axon,cpu');
# force CPU-only so the suite is hermetic and the 8-device mesh is default
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


class CheckpointAbort(Exception):
    """Raised by the abort_after_save fixture to simulate a preemption."""


@pytest.fixture
def abort_after_save():
    """Monkeypatch ``Checkpoint.save`` to raise :class:`CheckpointAbort`
    AFTER the n-th successful write — simulating a preemption that leaves a
    valid mid-flight snapshot on disk. Usage::

        with abort_after_save(n=1):
            with pytest.raises(CheckpointAbort):
                solver(..., checkpoint_path=p, checkpoint_interval_s=0.0)

    The original ``save`` is restored on context exit."""
    import contextlib

    from graphdyn.utils.io import Checkpoint

    @contextlib.contextmanager
    def patcher(n: int = 1, when=None):
        """Abort after the n-th write, or after the first write whose
        ``meta`` satisfies ``when(meta)`` (e.g. a driver's next_rep)."""
        saved_save = Checkpoint.save
        calls = {"n": 0}

        def counting_save(self, arrays, meta):
            saved_save(self, arrays, meta)
            calls["n"] += 1
            if (when(meta) if when is not None else calls["n"] == n):
                raise CheckpointAbort

        Checkpoint.save = counting_save
        try:
            yield
        finally:
            Checkpoint.save = saved_save

    return patcher


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running correctness anchors")
