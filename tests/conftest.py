"""Test harness: force CPU JAX with an 8-device simulated mesh (SURVEY.md §4.4
— the TPU-native analogue of a fake backend). Must run before jax imports."""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

# a sitecustomize plugin may have pinned jax_platforms (e.g. 'axon,cpu');
# force CPU-only so the suite is hermetic and the 8-device mesh is default
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


class CheckpointAbort(Exception):
    """Raised by the abort_after_save fixture to simulate a preemption."""


@pytest.fixture
def abort_after_save():
    """Monkeypatch ``Checkpoint.save`` to raise :class:`CheckpointAbort`
    AFTER the n-th successful write — simulating a preemption that leaves a
    valid mid-flight snapshot on disk. Usage::

        with abort_after_save(n=1):
            with pytest.raises(CheckpointAbort):
                solver(..., checkpoint_path=p, checkpoint_interval_s=0.0)

    The original ``save`` is restored on context exit."""
    import contextlib

    from graphdyn.utils.io import Checkpoint

    @contextlib.contextmanager
    def patcher(n: int = 1, when=None):
        """Abort after the n-th write, or after the first write whose
        ``meta`` satisfies ``when(meta)`` (e.g. a driver's next_rep)."""
        saved_save = Checkpoint.save
        calls = {"n": 0}

        def counting_save(self, arrays, meta):
            saved_save(self, arrays, meta)
            calls["n"] += 1
            if (when(meta) if when is not None else calls["n"] == n):
                raise CheckpointAbort

        Checkpoint.save = counting_save
        try:
            yield
        finally:
            Checkpoint.save = saved_save

    return patcher


@pytest.fixture
def multi_device_cpu():
    """Run ``python -m graphdyn ...`` in a SUBPROCESS on a forced
    multi-device CPU host platform (``JAX_PLATFORMS=cpu`` +
    ``XLA_FLAGS=--xla_force_host_platform_device_count=8``) — the
    fake-backend analogue of a multi-chip host for CLI-level sharded
    tests. In-process tests inherit this harness's own 8 simulated
    devices (header above), but subprocess episodes — kill/requeue
    chains, supervisor runs, anything whose process boundary is the point
    — previously saw 1 device on this CPU-only container and had to skip
    their sharded legs. Returns ``run(argv, *, env=None, devices=8,
    timeout=600, cwd=None) -> CompletedProcess`` (text mode, output
    captured)."""
    import subprocess
    import sys

    def run(argv, *, env=None, devices=8, timeout=600, cwd=None):
        e = dict(os.environ)
        e.update(env or {})
        e["JAX_PLATFORMS"] = "cpu"
        flags = e.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            e["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={devices}"
            ).strip()
        return subprocess.run(
            [sys.executable, "-m", "graphdyn", *argv],
            env=e, capture_output=True, text=True, timeout=timeout, cwd=cwd,
        )

    return run


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running correctness anchors")


# Two-tier suite (README "Running the tests"): the default developer/CI run
# is `pytest tests/ -m "not slow"` (<5 min); the full run — every
# correctness anchor, ~25 min on this host — is `pytest tests/` (what the
# round judge executes). Tests measured ≥7 s on the shared 8-device CPU
# mesh are marked slow HERE, centrally, so the tier boundary is one
# reviewable list; regenerate with
#   pytest tests/ -q --durations=0 2>&1 | awk '$1+0>=7 && $2=="call"'
# Parametrized ids pin only the slow parameter combos; the rest stay fast.
_SLOW = {
    ("test_bdcm.py", "test_bucketed_partitions_match"),
    ("test_bdcm.py", "test_bucketed_sweep_matches_unbucketed"),
    ("test_bdcm.py", "test_entropy_sweep_bucketed_matches"),
    ("test_bench_contract.py", "test_bench_smoke_emits_one_json_line"),
    # the ISSUE-18 acceptance A/B at the full n=1e5 shape (~3 min: the
    # bucketed compile + two graph builds); the same ratio machinery runs
    # tier-1 through the bench-contract smoke row
    ("test_bucketed.py", "test_powerlaw_rate_within_4x_of_equal_edge_rrg"),
    ("test_cli.py", "test_cli_consensus"),
    ("test_cli.py", "test_cli_entropy"),
    ("test_cli.py", "test_cli_entropy_union"),
    ("test_cli.py", "test_cli_hpr_batch_device_init"),
    ("test_cli.py", "test_cli_sa_sharded"),
    ("test_consensus.py", "test_ensemble_aggregate_matches_per_seed"),
    ("test_consensus.py", "test_ensemble_doc_schema"),
    ("test_dynamics.py", "test_solvers_run_under_nondefault_rules"),
    ("test_entropy.py", "test_congruent_ensemble_managed_resume_bit_exact"),
    ("test_entropy.py", "test_entropy_checkpointer_and_counts"),
    ("test_entropy.py", "test_entropy_ensemble_empty_attractor_no_nan"),
    ("test_entropy.py", "test_entropy_grid_resume_bit_exact"),
    ("test_entropy.py", "test_golden_f64_artifact_reproducible"),
    ("test_entropy.py", "test_golden_triples_tight_f64"),
    ("test_entropy.py", "test_golden_triples_tolerance"),
    ("test_entropy.py", "test_grid_driver_shapes"),
    # the halo bit-parity matrix and resume interop compile several mesh
    # programs each; the preempt/requeue JOURNAL proof (the acceptance
    # centerpiece) deliberately stays tier-1 despite ~10 s
    ("test_halo.py", "test_cli_sa_shards_halo"),
    ("test_halo.py", "test_sa_halo_bit_parity_vs_unsharded_and_gather"),
    ("test_halo.py", "test_sa_halo_resume_across_modes_and_shard_counts"),
    ("test_entropy.py", "test_union_ensemble_all_isolate_member"),
    ("test_entropy.py", "test_union_ensemble_checkpointing"),
    ("test_entropy.py", "test_union_ensemble_managed_resume_bit_exact"),
    ("test_entropy.py", "test_union_ensemble_matches_per_graph"),
    ("test_entropy.py", "test_union_ensemble_resume_chi0"),
    ("test_entropy.py", "test_warm_start_resume_state"),
    ("test_hpr.py", "test_hpr_batch_checkpoint_resume_bit_exact"),
    ("test_hpr.py", "test_hpr_batch_device_init"),
    ("test_hpr.py", "test_hpr_batch_mesh_checkpoint_resume"),
    ("test_hpr.py", "test_hpr_batch_sharded_bit_identical_to_unsharded[5]"),
    ("test_hpr.py", "test_hpr_batch_sharded_bit_identical_to_unsharded[8]"),
    ("test_hpr.py", "test_hpr_batch_sharded_replicas"),
    ("test_hpr.py", "test_hpr_checkpoint_resume_bit_exact"),
    ("test_hpr.py", "test_hpr_ensemble_driver"),
    ("test_hpr.py", "test_hpr_ensemble_driver_resume"),
    ("test_hpr.py", "test_hpr_float64_axis"),
    ("test_hpr.py", "test_union_setup_device_bit_identical_to_host"),
    ("test_hpr_oracle.py", "test_iterated_sweep_matches_oracle"),
    ("test_hpr_oracle.py", "test_sweep_matches_bruteforce_oracle[14-3-2-1-2.0]"),
    ("test_packed.py", "test_draw_packed_biased_mean_bias"),
    ("test_pallas_group.py", "test_entropy_exec_pallas_matches_xla_ragged"),
    ("test_pallas_group.py",
     "test_entropy_exec_pallas_grouped_equals_g1_bit_exact"),
    ("test_pallas_group.py", "test_entropy_grid_kernel_pallas_end_to_end"),
    ("test_pallas_group.py", "test_grouped_equals_g1_bit_exact_both_variants"),
    ("test_pallas_group.py", "test_serial_dp_contract_is_g1_of_grouped"),
    ("test_pallas_group.py", "test_grouped_kernel_matches_xla_per_group_a[2-3]"),
    ("test_pallas_group.py", "test_grouped_kernel_matches_xla_shared_a[2-3]"),
    ("test_pallas_group.py", "test_grouped_kernel_matches_xla_shared_a[3-2]"),
    ("test_pallas_group.py",
     "test_entropy_exec_pallas_freezes_inactive_lanes"),
    ("test_pallas_group.py",
     "test_grouped_kernel_nondivisor_tail_and_tiling_invariance"),
    ("test_pallas.py", "test_dp_contract_matches_xla[2-2-1e-10]"),
    ("test_pallas.py", "test_dp_contract_matches_xla[3-2-0.0]"),
    ("test_pallas.py", "test_dp_contract_matches_xla[3-3-0.0]"),
    ("test_pallas.py", "test_dp_contract_matches_xla[4-2-0.0]"),
    ("test_pallas.py", "test_sweep_pallas_vs_xla_er"),
    ("test_pallas.py", "test_sweep_pallas_with_bias_rrg"),
    ("test_pallas_packed.py", "test_pallas_packed_general_matches_xla[change-majority]"),
    ("test_pallas_packed.py", "test_pallas_packed_general_matches_xla[change-minority]"),
    ("test_pallas_packed.py", "test_pallas_packed_general_matches_xla[stay-majority]"),
    ("test_pallas_packed.py", "test_pallas_packed_general_matches_xla[stay-minority]"),
    ("test_parallel.py", "test_consensus_scan_word_sharded_bit_parity"),
    ("test_parallel.py", "test_sharded_sweep_f64_matches_unsharded"),
    ("test_parallel.py", "test_sharded_sweep_matches_unsharded[er]"),
    ("test_parallel.py", "test_union_entropy_mesh_matches_unsharded"),
    ("test_parallel.py", "test_vmapped_entropy_mesh_matches_unsharded"),
    ("test_sa.py", "test_lightcone_bit_parity_with_full"),
    ("test_sa.py", "test_lightcone_checkpoint_resume"),
    ("test_sa.py", "test_lightcone_device_tables_bit_parity"),
    ("test_sa.py", "test_sa_ensemble_driver_resume"),
    ("test_sa_sharded.py", "test_lightcone_sharded_bit_parity_and_resume"),
    ("test_sa_sharded.py", "test_prng_mode_bit_parity"),
    ("test_sa_sharded.py", "test_sharded_checkpoint_resume_bit_exact"),
    # the lane-shard parity matrix compiles three mesh programs; the
    # preempt/requeue JOURNAL proof and the tta speedup bar (the ISSUE-13
    # acceptance criteria) deliberately stay tier-1 at ~6 s each
    ("test_search.py", "test_temper_lane_shard_bit_parity"),
}


def pytest_collection_modifyitems(config, items):
    collected = set()
    for item in items:
        key = (item.fspath.basename, item.name)
        collected.add(key)
        if key in _SLOW:
            item.add_marker(pytest.mark.slow)
    # a renamed test (or changed parametrize id) must not silently fall out
    # of the slow tier: flag _SLOW entries whose FILE was collected but
    # whose test no longer matches. Warning, not error — and only for
    # whole-file/dir invocations: -k filters and `file.py::test` selections
    # legitimately collect a subset.
    if config.getoption("-k") or any("::" in a for a in config.args):
        return
    files = {f for f, _ in collected}
    stale = sorted(e for e in _SLOW if e[0] in files and e not in collected)
    if stale:
        import warnings

        warnings.warn(
            f"conftest._SLOW entries match no collected test "
            f"(renamed/reparametrized?): {stale}", stacklevel=1,
        )
