"""Test harness: force CPU JAX with an 8-device simulated mesh (SURVEY.md §4.4
— the TPU-native analogue of a fake backend). Must run before jax imports."""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

# a sitecustomize plugin may have pinned jax_platforms (e.g. 'axon,cpu');
# force CPU-only so the suite is hermetic and the 8-device mesh is default
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running correctness anchors")
