"""graftcost: HLO-derived byte/FLOP cost models and the committed cost
ledger.

The acceptance contract (ISSUE 16): the committed ``COST_LEDGER.json``
must match the live derivations (GB101 fails tier-1 on unblessed cost
drift); every registered hand-written byte model must track its derived
counterpart at the blessed ratio (GB102 — perturbing a hand coefficient
fails, demonstrated below by monkeypatching ``fused_vmem_bytes``); every
graftcheck-ledgered entry point must carry a cost row (GB103); and the
measured scaling exponents must match their declarations (GB104). The
fitted models are *functions*, not point samples: the held-out-shape
tests below compile each entry at a size the fit never saw and assert the
model predicts it. All tests carry the ``graftcost`` marker so
``scripts/lint.sh`` costcheck can run the subset standalone.
"""

import copy
import json
import subprocess
import sys
from pathlib import Path

import pytest

from graphdyn.analysis import graftcheck as gc
from graphdyn.analysis import graftcost as gcst

REPO = Path(__file__).resolve().parent.parent

pytestmark = pytest.mark.graftcost


@pytest.fixture(scope="module")
def live_cost():
    """Live cost derivations for every entry at every calibration point,
    computed once per module (27 small compiles, ~20 s on CPU)."""
    return gcst.collect_cost_samples()


@pytest.fixture(scope="module")
def ledger():
    led = gcst.load_ledger()
    assert led is not None, (
        f"{gcst.LEDGER_NAME} missing — run --update-ledger and commit it"
    )
    return led


# ---------------------------------------------------------------------------
# the ledger gate
# ---------------------------------------------------------------------------


def test_ledger_matches_live(live_cost, ledger):
    """THE tier-1 cost gate: live derivations diff clean against the
    committed ledger across GB101/GB102/GB103/GB104. A failing diff means
    a program's cost moved — fix the regression, or (if deliberate)
    re-run ``python -m graphdyn.analysis.graftcost --update-ledger`` and
    commit the reviewed ledger + hand-model updates in the same PR."""
    findings = gcst.check_ledger(live_cost, ledger)
    assert findings == [], "\n".join(
        f"{f.entry}: {f.code} {f.message}" for f in findings
    )


def test_cost_entries_cover_graftcheck_entries(ledger):
    """GB103's premise holds on the shipped tree: the cost calibration
    plan covers exactly the graftcheck entry points, the committed ledger
    has a row for each, and the coverage check itself is clean."""
    assert set(gcst.COST_ENTRIES) == set(gc.ENTRIES)
    assert set(ledger["entries"]) == set(gc.ENTRIES)
    assert ledger["backend"] == "cpu"   # the hardware-free contract
    assert gcst.check_coverage(ledger) == []


def test_missing_ledger_fails_closed(live_cost):
    """No ledger file -> a GB103 finding per live entry, never a silent
    pass."""
    findings = gcst.check_ledger(live_cost, None)
    assert {f.code for f in findings} == {"GB103"}
    assert len(findings) == len(live_cost)


def test_update_ledger_roundtrip(tmp_path, live_cost):
    path = tmp_path / "ledger.json"
    gcst.write_ledger(live_cost, path)
    assert gcst.check_ledger(live_cost, gcst.load_ledger(path)) == []


# ---------------------------------------------------------------------------
# falsifiability: each GB rule must fail when its invariant is broken
# ---------------------------------------------------------------------------


def test_gb101_doctored_sample_fails(live_cost, ledger):
    """Inflating a live peak-bytes sample 3x past the band is a GB101."""
    name = "packed_rollout"
    doctored = copy.deepcopy(live_cost[name])
    k = str(gcst.COST_ENTRIES[name].points[0])
    doctored[k]["peak_bytes"] *= 3
    findings = gcst.diff_cost_samples(
        name, ledger["entries"][name], doctored
    )
    assert "GB101" in {f.code for f in findings}
    assert any("peak_bytes" in f.message for f in findings)


def test_gb101_resident_set_change_fails(ledger):
    """The acceptance-criterion break: actually changing a lowered
    program's resident set (doubling the packed rollout's replica extent
    R) without blessing fails GB101 — the derived facts move past every
    byte band."""
    name = "packed_rollout"
    n = gcst.COST_ENTRIES[name].points[0]
    fat = gcst.derive_cost(gc.lower_entry(name, n=n, R=256))
    findings = gcst.diff_cost_samples(
        name, ledger["entries"][name], {str(n): fat}
    )
    assert "GB101" in {f.code for f in findings}


def test_gb102_hand_coefficient_perturbation_fails(ledger, monkeypatch):
    """The acceptance-criterion break: doubling ``fused_vmem_bytes``
    (the Pallas annealer's VMEM formula) fails GB102 against the blessed
    ratio — with NO compilation, because both sides of the check are
    committed-model/host-table arithmetic."""
    import graphdyn.ops.pallas_anneal as pa

    assert gcst.check_hand_models(ledger) == []   # clean before
    orig = pa.fused_vmem_bytes
    monkeypatch.setattr(
        pa, "fused_vmem_bytes", lambda *a, **k: 2 * orig(*a, **k)
    )
    findings = gcst.check_hand_models(ledger)
    assert [f.code for f in findings].count("GB102") >= 1
    assert all(f.entry == "fused_anneal" for f in findings)
    assert any("fused_vmem_bytes" in f.message for f in findings)


def test_gb102_unblessed_hand_model_fails(ledger):
    """A registered hand model with no blessed ratio row is a GB102 (the
    adapter table and the ledger must move together)."""
    stripped = copy.deepcopy(ledger)
    del stripped["hand_models"]["fused_vmem_bytes"]
    findings = gcst.check_hand_models(stripped)
    assert [f.code for f in findings] == ["GB102"]
    assert "not blessed" in findings[0].message


def test_gb103_dropped_row_fails(ledger):
    stripped = copy.deepcopy(ledger)
    del stripped["entries"]["bdcm_sweep"]
    findings = gcst.check_coverage(stripped)
    assert [f.code for f in findings] == ["GB103"]
    assert findings[0].entry == "bdcm_sweep"


def test_gb104_broken_scaling_fails(live_cost):
    """Flattening the samples (same cost at every n) breaks the declared
    linear exponent; bending the middle point breaks the affine-residual
    check — both are GB104."""
    name = "packed_rollout"
    spec = gcst.COST_ENTRIES[name]
    flat = copy.deepcopy(live_cost[name])
    first = flat[str(spec.points[0])]
    for n in spec.points[1:]:
        flat[str(n)] = copy.deepcopy(first)    # exponent 0, declared 1.0
    findings = gcst.check_exponents(name, spec, flat)
    assert "GB104" in {f.code for f in findings}
    assert any("scaling exponent" in f.message for f in findings)

    bent = copy.deepcopy(live_cost[name])
    bent[str(spec.points[1])]["peak_bytes"] *= 2.0   # off the affine line
    findings = gcst.check_exponents(name, spec, bent)
    assert any(
        f.code == "GB104" and "residual" in f.message for f in findings
    )


# ---------------------------------------------------------------------------
# the models are functions: held-out-shape prediction (never fitted)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("entry", sorted(gcst.COST_ENTRIES))
def test_holdout_prediction_within_band(entry, ledger):
    """Compile the entry at its held-out size — a shape the affine fit
    never saw — and assert the committed model predicts every fitted
    quantity within 15% (+4 KiB floor for the small-absolute fields).
    This is what makes the ledger a cost *model* rather than a cache of
    point samples."""
    spec = gcst.COST_ENTRIES[entry]
    assert spec.holdout not in spec.points
    facts = gcst.derive_cost(gc.lower_entry(entry, n=spec.holdout))
    models = ledger["entries"][entry]["models"]
    for q in gcst.FIT_QUANTITIES:
        model = models.get(q)
        got = gcst._quantity(facts, q)
        if model is None or gcst.predict(model, spec.holdout) <= 0:
            continue   # quantity absent from this entry (e.g. collectives)
        want = gcst.predict(model, spec.holdout)
        band = max(4096.0, 0.15 * want)
        assert abs(got - want) <= band, (
            f"{entry}.{q} at held-out n={spec.holdout}: derived {got:.6g} "
            f"vs model prediction {want:.6g} (band ±{band:.6g})"
        )


def test_declared_exponents_match_ledger_fits(ledger):
    """Every declared exponent sits within the GB104 band of the
    exponent recorded in the committed ledger fit — the declarations are
    measurements rounded to a claim, not aspirations."""
    for name, spec in gcst.COST_ENTRIES.items():
        models = ledger["entries"][name]["models"]
        for q, declared in spec.declared.items():
            exp = models[q].get("exponent")
            assert exp is not None, (name, q)
            assert abs(exp - declared) <= gcst.EXPONENT_TOL, (
                f"{name}.{q}: declared {declared}, ledger fit {exp:.3f}"
            )


# ---------------------------------------------------------------------------
# hand-model adapter table ↔ ARCHITECTURE.md (single source of truth)
# ---------------------------------------------------------------------------


def test_hand_model_table_synced_with_architecture_md():
    """Both directions: every registered ``HAND_MODELS`` adapter is a row
    of ARCHITECTURE.md's byte-model adapter table (name, module, entry,
    quantity all rendered), and every table row names a registered
    adapter — the doc cannot drift from the code or vice versa."""
    import re

    doc = (REPO / "ARCHITECTURE.md").read_text()
    rows = re.findall(
        r"^\| *`([\w.]+)` *\| *`([\w.]+)` *\| *(\w+) *\| *(\w+) *\|",
        doc, re.MULTILINE,
    )
    doc_rows = {r[0]: r[1:] for r in rows}
    registered = {
        hm.name: (hm.module, hm.entry, hm.quantity)
        for hm in gcst.HAND_MODELS
    }
    assert set(doc_rows) == set(registered), (
        "ARCHITECTURE.md byte-model adapter table out of sync with "
        "graftcost.HAND_MODELS: "
        f"doc-only={sorted(set(doc_rows) - set(registered))}, "
        f"code-only={sorted(set(registered) - set(doc_rows))}"
    )
    for name, want in registered.items():
        assert doc_rows[name] == want, (
            f"adapter row {name!r}: doc says {doc_rows[name]}, "
            f"code says {want}"
        )


# ---------------------------------------------------------------------------
# consumers: memcheck cross-check rows + bench columns
# ---------------------------------------------------------------------------


def test_memcheck_emits_derived_rows():
    """obs memcheck cross-checks the measured peak against the DERIVED
    models too: both ``derived:*`` rows are present and pass (structurally
    on a stats-less CPU backend: model positive, explicit reason)."""
    from graphdyn.obs.memband import run_memcheck

    rows = {r.program: r for r in run_memcheck()}
    for prog in gcst.DERIVED_MEM_BANDS:
        assert prog in rows, sorted(rows)
        r = rows[prog]
        assert r.ok, r
        assert r.model > 0
        if r.measured is None:
            assert r.reason, r      # the null+reason contract


def test_bench_cost_columns_positive_with_ledger(ledger):
    cols = gcst.bench_cost_columns(4096, ledger)
    assert cols["derived_bytes"] > 0
    assert cols["arithmetic_intensity"] > 0
    assert "derived_bytes_skipped_reason" not in cols


def test_bench_cost_columns_null_plus_reason():
    """Wrong backend or unusable row -> explicit nulls with reasons,
    never zeros and never missing columns."""
    for bad in ({"backend": "tpu", "entries": {}},
                {"backend": "cpu", "entries": {}}):
        cols = gcst.bench_cost_columns(4096, bad)
        assert cols["derived_bytes"] is None
        assert cols["arithmetic_intensity"] is None
        assert cols["derived_bytes_skipped_reason"]
        assert cols["arithmetic_intensity_skipped_reason"]


def test_derived_peak_bytes_contract(ledger):
    v, reason = gcst.derived_peak_bytes("packed_rollout", 32768, ledger)
    assert v is not None and v > 0 and reason is None
    v, reason = gcst.derived_peak_bytes(
        "packed_rollout", 32768, {"backend": "tpu"}
    )
    assert v is None and "backend" in reason


# ---------------------------------------------------------------------------
# CLI contract (mirrors graftlint/graftcheck/racecheck)
# ---------------------------------------------------------------------------


def test_cli_json_is_one_document_stdout_only():
    proc = subprocess.run(
        [sys.executable, "-m", "graphdyn.analysis.graftcost",
         "--format=json", "--entries", "bdcm_sweep"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    doc = json.loads(proc.stdout)        # the whole stdout parses
    assert proc.returncode == 0, doc["findings"]
    assert doc["findings"] == []
    assert set(doc["cost"]) == {"bdcm_sweep"}
    assert "graftcost" in proc.stderr    # diagnostics went to stderr
    assert "graftcost" not in proc.stdout


def test_cli_unknown_entry_rejected():
    proc = subprocess.run(
        [sys.executable, "-m", "graphdyn.analysis.graftcost",
         "--entries", "nope"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 2
    assert "unknown entries" in proc.stderr


def test_cli_update_refuses_entry_subset():
    proc = subprocess.run(
        [sys.executable, "-m", "graphdyn.analysis.graftcost",
         "--update-ledger", "--entries", "bdcm_sweep"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 2
    assert "WHOLE ledger" in proc.stderr
