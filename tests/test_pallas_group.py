"""Grouped Pallas BDCM kernel — interpret-mode parity + the kernel-mode
executors (ISSUE 5 acceptance tests).

The contracts under test (ARCHITECTURE.md "Kernel selection"):

- grouped-Pallas ≈ grouped-XLA within the documented tolerance (the
  Pallas-vs-XLA numeric MODE, ~1e-3 max rel err on chip; interpret mode
  here reproduces the same accumulation order);
- grouped-Pallas == serial-Pallas (G=1) BIT-exact — one kernel body, the
  group axis a grid dimension, per-lane work elementwise across lanes and
  tile widths;
- non-divisor edge tails and pad lanes are inert (sliced off / never
  indexed);
- the VMEM byte model (``vmem_block_edges``) is LANE-multiple, maximal
  within budget, and 0 exactly when nothing fits — for the serial model
  and the group-resident ``(d, T, G)`` variant;
- a spec the model rejects resolves to the XLA path statically; a kernel
  lowering failure at run time degrades via ``pallas_fallback_spec``.

Every test runs ``interpret=True`` on CPU (marker ``pallas_interpret`` —
``scripts/lint.sh`` pallascheck runs the subset standalone); compiled-mode
equivalence on a real chip is scripts/pallas_tpu_validate.py's job.
"""

import logging

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from graphdyn.config import DynamicsConfig, EntropyConfig, HPRConfig
from graphdyn.graphs import erdos_renyi_graph, remove_isolates
from graphdyn.ops.bdcm import (
    BDCMData,
    class_update,
    resolve_group_pallas_modes,
)
from graphdyn.ops.pallas_bdcm import (
    LANE,
    MAX_BLOCK_EDGES,
    VMEM_BUDGET,
    dp_contract,
    dp_contract_grouped,
    pallas_group_supported,
    vmem_block_edges,
)
from graphdyn.resilience.faults import FaultPlan, FaultSpec

pytestmark = pytest.mark.pallas_interpret


def _group_inputs(d, T, G, Ed, seed=7):
    rng = np.random.default_rng(seed)
    K, M = 2**T, (d + 1) ** T
    chi_in = jnp.asarray(rng.random((G, Ed, d, K, K)), jnp.float32)
    A = jnp.asarray(rng.random((K, K, M)), jnp.float32)
    chi_old = jnp.asarray(rng.random((G, Ed, K, K)), jnp.float32)
    tilts = jnp.asarray(rng.random((G, K)) + 0.5, jnp.float32)
    return chi_in, A, chi_old, tilts


# ---------------------------------------------------------------------------
# kernel: grouped vs XLA (tolerance) and across group extents (bit-exact)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("d,T", [(3, 2), (2, 3)])
def test_grouped_kernel_matches_xla_shared_a(d, T):
    K = 2**T
    chi_in, A, chi_old, _ = _group_inputs(d, T, G=3, Ed=200)
    tilt = jnp.ones((K,), jnp.float32)
    ref = jax.vmap(
        lambda ci, co: class_update(
            ci, A, tilt, co, d=d, T=T, K=K, damp=0.3, eps_clamp=0.0
        )
    )(chi_in, chi_old)
    out = dp_contract_grouped(
        chi_in, A, chi_old, d=d, T=T, damp=0.3, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=5e-3, atol=1e-6
    )


@pytest.mark.parametrize("d,T", [(3, 2), (2, 3)])
def test_grouped_kernel_matches_xla_per_group_a(d, T):
    """The group-resident A_tilted variant: each lane contracts against its
    OWN tilted rows (the entropy cell groups' per-cell λ shape)."""
    K = 2**T
    chi_in, A, chi_old, tilts = _group_inputs(d, T, G=3, Ed=200)
    a_stack = A[None] * tilts[:, :, None, None]        # [G, K, K, M]
    ref = jax.vmap(
        lambda ci, co, tl: class_update(
            ci, A, tl, co, d=d, T=T, K=K, damp=0.3, eps_clamp=0.0
        )
    )(chi_in, chi_old, tilts)
    out = dp_contract_grouped(
        chi_in, a_stack, chi_old, d=d, T=T, damp=0.3, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=5e-3, atol=1e-6
    )


def test_grouped_equals_g1_bit_exact_both_variants():
    """Lane g of a G>1 launch equals the G=1 launch of lane g's data
    bit-for-bit, for the shared AND the group-resident A variant — the
    'grouped == serial within the same kernel' identity."""
    d, T = 3, 2
    chi_in, A, chi_old, tilts = _group_inputs(d, T, G=4, Ed=200)
    a_stack = A[None] * tilts[:, :, None, None]
    shared = dp_contract_grouped(
        chi_in, A, chi_old, d=d, T=T, damp=0.3, interpret=True
    )
    grouped = dp_contract_grouped(
        chi_in, a_stack, chi_old, d=d, T=T, damp=0.3, interpret=True
    )
    for g in range(4):
        one_s = dp_contract_grouped(
            chi_in[g : g + 1], A, chi_old[g : g + 1],
            d=d, T=T, damp=0.3, interpret=True,
        )
        one_g = dp_contract_grouped(
            chi_in[g : g + 1], a_stack[g : g + 1], chi_old[g : g + 1],
            d=d, T=T, damp=0.3, interpret=True,
        )
        np.testing.assert_array_equal(np.asarray(shared[g]), np.asarray(one_s[0]))
        np.testing.assert_array_equal(np.asarray(grouped[g]), np.asarray(one_g[0]))


def test_serial_dp_contract_is_g1_of_grouped():
    """The serial entry point IS the G=1 instance (shared-A) — bit-equal to
    the matching grouped lane."""
    d, T = 4, 2
    chi_in, A, chi_old, _ = _group_inputs(d, T, G=2, Ed=150)
    grouped = dp_contract_grouped(
        chi_in, A, chi_old, d=d, T=T, damp=0.4, interpret=True
    )
    ser = dp_contract(
        chi_in[1], A, chi_old[1], d=d, T=T, damp=0.4, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(ser), np.asarray(grouped[1]))


def test_grouped_kernel_nondivisor_tail_and_tiling_invariance():
    """Ed that is neither a lane multiple nor a tile multiple: pad lanes are
    sliced off, and an explicit narrower tile width changes nothing (per-
    lane work is elementwise across lanes — the bit-exactness substrate)."""
    d, T = 3, 2
    chi_in, A, chi_old, tilts = _group_inputs(d, T, G=2, Ed=130)
    a_stack = A[None] * tilts[:, :, None, None]
    wide = dp_contract_grouped(
        chi_in, a_stack, chi_old, d=d, T=T, damp=0.3, interpret=True
    )
    narrow = dp_contract_grouped(
        chi_in, a_stack, chi_old, d=d, T=T, damp=0.3, block_edges=LANE,
        interpret=True,
    )
    assert wide.shape == (2, 130, 2**T, 2**T)
    np.testing.assert_array_equal(np.asarray(wide), np.asarray(narrow))


# ---------------------------------------------------------------------------
# VMEM byte model: LANE-multiple, maximal within budget, honest 0-fallback
# ---------------------------------------------------------------------------


def _model_bytes(d, T, G, eb):
    """The documented model, restated independently of the implementation."""
    K, M = 2**T, (d + 1) ** T
    fixed = (4 * G * K * K * M) if G else (8 * K * K * M)
    per_edge = 8 * (K * K * (d + 2) + K * M)
    return fixed + eb * per_edge


@pytest.mark.parametrize("G", [0, 1, 2, 8, 32])
def test_vmem_block_edges_model_property(G):
    """For a sweep of (d, T) and (d, T, G): the returned width's modeled
    working set fits the budget, the width is LANE-multiple and maximal
    (one more lane would overflow, unless capped), and 0 is returned
    exactly when even one lane does not fit."""
    for d in range(1, 9):
        for T in range(2, 5):
            eb = vmem_block_edges(d, T, G=G)
            assert eb % LANE == 0
            assert 0 <= eb <= MAX_BLOCK_EDGES
            if eb == 0:
                # honest 0-fallback: even a single lane-width tile overflows
                assert _model_bytes(d, T, G, LANE) > VMEM_BUDGET, (d, T, G)
            else:
                assert _model_bytes(d, T, G, eb) <= VMEM_BUDGET, (d, T, G)
                if eb < MAX_BLOCK_EDGES:
                    assert _model_bytes(d, T, G, eb + LANE) > VMEM_BUDGET, \
                        (d, T, G)


def test_vmem_group_resident_shrinks_with_g():
    """The group-resident A stack is charged linearly in G: the admitted
    tile width is non-increasing in G and eventually hits the 0-fallback,
    while the shared model (G=0) is unaffected. (d=3, T=4 is the shape
    where the resident stack dominates: K²M = 64 Ki floats.)"""
    widths = [vmem_block_edges(3, 4, G=g) for g in (1, 4, 8, 16, 32)]
    assert all(a >= b for a, b in zip(widths, widths[1:]))
    assert widths[0] > 0
    assert vmem_block_edges(3, 4, G=32) == 0      # stack crowds out the tile
    assert vmem_block_edges(3, 4) > 0             # shared model unaffected


def test_pallas_group_supported_gate():
    assert pallas_group_supported(3, 2, 1000, 8, per_group_a=True)
    assert pallas_group_supported(3, 2, 1000, 8, per_group_a=False)
    # too few edges to fill one lane tile
    assert not pallas_group_supported(3, 2, 16, 8, per_group_a=True)
    # beyond the reference regime
    assert not pallas_group_supported(3, 5, 100000, 2, per_group_a=True)
    # group-resident A stack overflows at large G; shared variant survives
    assert not pallas_group_supported(3, 4, 100000, 32, per_group_a=True)
    assert pallas_group_supported(3, 4, 100000, 32, per_group_a=False)


def test_resolve_group_pallas_modes_contract():
    f32, f64 = jnp.float32, jnp.float64
    # CPU backend: auto keeps the XLA path, pallas forces interpret
    assert resolve_group_pallas_modes(
        [3], [1000], T=2, dtype=f32, kernel="auto", G=4, per_group_a=True
    ) == ("",)
    assert resolve_group_pallas_modes(
        [3, 9], [1000, 1000], T=2, dtype=f32, kernel="pallas", G=4,
        per_group_a=True,
    ) == ("interpret", "")          # d=9 beyond the regime -> XLA per class
    assert resolve_group_pallas_modes(
        [3], [1000], T=2, dtype=f32, kernel="xla", G=4, per_group_a=True
    ) == ("",)
    # f64 is XLA-only; forcing the f32 kernel is refused loudly
    assert resolve_group_pallas_modes(
        [3], [1000], T=2, dtype=f64, kernel="auto", G=4, per_group_a=True
    ) == ("",)
    with pytest.raises(ValueError, match="f32-only"):
        resolve_group_pallas_modes(
            [3], [1000], T=2, dtype=f64, kernel="pallas", G=4,
            per_group_a=True,
        )
    with pytest.raises(ValueError, match="kernel"):
        resolve_group_pallas_modes(
            [3], [1000], T=2, dtype=f32, kernel="fused", G=4,
            per_group_a=True,
        )


# ---------------------------------------------------------------------------
# executors: kernel="pallas" parity with kernel="xla", bit-exact across G
# ---------------------------------------------------------------------------


def _entropy_cells(n=260, c=3.0, seeds=(0, 1, 2)):
    cells, chis = [], []
    for i, s in enumerate(seeds):
        g = erdos_renyi_graph(n, c / (n - 1), seed=s)
        sub, n_iso = remove_isolates(g)
        data = BDCMData(sub, p=1, c=1)
        cells.append((data, g.n, n_iso))
        chis.append(data.init_messages(7 + i))
    return cells, chis


def _entropy_cfg(**kw):
    kw.setdefault("damp", 0.2)
    kw.setdefault("eps", 1e-4)
    kw.setdefault("max_sweeps", 50)
    return EntropyConfig(dynamics=DynamicsConfig(p=1, c=1), **kw)


def test_entropy_exec_pallas_matches_xla_ragged():
    """Grouped-Pallas ≈ grouped-XLA on RAGGED cells (mixed per-class modes:
    small union classes stay XLA inside the Pallas-mode program)."""
    from graphdyn.pipeline.entropy_group import EntropyCellExec

    cfg = _entropy_cfg()
    cells, chis = _entropy_cells()
    lm = jnp.asarray([0.1, 0.3, 0.2], jnp.float32)
    act = jnp.ones(3, bool)
    d0 = jnp.full(3, jnp.inf, jnp.float32)
    t0 = jnp.zeros(3, jnp.int32)
    outs = {}
    for kern in ("pallas", "xla"):
        ex = EntropyCellExec(cells, cfg, chunk_sweeps=4, kernel=kern)
        outs[kern] = ex.fixed_point_chunk(ex.stack_chi(chis), lm, act, d0, t0)
    assert any(m == "interpret" for m in ex.spec.pallas) is False  # xla exec
    cp, tp, dp = outs["pallas"]
    cx, tx, dx = outs["xla"]
    np.testing.assert_array_equal(np.asarray(tp), np.asarray(tx))
    np.testing.assert_allclose(
        np.asarray(cp), np.asarray(cx), rtol=5e-3, atol=1e-5
    )


def test_entropy_exec_pallas_grouped_equals_g1_bit_exact():
    """Grouped-Pallas == serial-Pallas (G=1) bit-exact, per cell — the
    executor-level identity (same kernel, same per-class modes: the cells
    share one graph so the union class shapes cannot straddle the gate;
    each cell still solves its OWN λ)."""
    from graphdyn.pipeline.entropy_group import EntropyCellExec

    cfg = _entropy_cfg()
    g = erdos_renyi_graph(260, 3.0 / 259, seed=0)
    sub, n_iso = remove_isolates(g)
    data = BDCMData(sub, p=1, c=1)
    cells = [(data, g.n, n_iso)] * 3
    chis = [data.init_messages(7 + i) for i in range(3)]
    lm = jnp.asarray([0.1, 0.3, 0.2], jnp.float32)
    d0 = jnp.full(3, jnp.inf, jnp.float32)
    t0 = jnp.zeros(3, jnp.int32)

    ex = EntropyCellExec(cells, cfg, chunk_sweeps=5, kernel="pallas")
    assert any(m == "interpret" for m in ex.spec.pallas)
    cp, tp, dp = ex.fixed_point_chunk(
        ex.stack_chi(chis), lm, jnp.ones(3, bool), d0, t0
    )
    for g_i in range(3):
        e1 = EntropyCellExec([cells[g_i]], cfg, chunk_sweeps=5,
                             kernel="pallas")
        assert e1.spec.pallas == ex.spec.pallas
        c1, t1, d1 = e1.fixed_point_chunk(
            e1.stack_chi([chis[g_i]]), lm[g_i : g_i + 1],
            jnp.ones(1, bool), d0[:1], t0[:1],
        )
        np.testing.assert_array_equal(np.asarray(cp[g_i]), np.asarray(c1[0]))
        assert int(tp[g_i]) == int(t1[0])
        assert float(dp[g_i]) == float(d1[0])


def test_entropy_exec_pallas_freezes_inactive_lanes():
    """Pad/stopped lanes under the Pallas chunk keep their state bit-for-bit
    (the joint-while select is the same freeze the vmapped XLA path's
    batching rule applies)."""
    from graphdyn.pipeline.entropy_group import EntropyCellExec

    cfg = _entropy_cfg()
    cells, chis = _entropy_cells(seeds=(0, 1))
    lm = jnp.asarray([0.1, 0.3], jnp.float32)
    act = jnp.asarray([True, False])
    d0 = jnp.full(2, jnp.inf, jnp.float32)
    t0 = jnp.zeros(2, jnp.int32)
    ex = EntropyCellExec(cells, cfg, chunk_sweeps=3, kernel="pallas")
    stacked = ex.stack_chi(chis)
    c, t, dlt = ex.fixed_point_chunk(stacked, lm, act, d0, t0)
    np.testing.assert_array_equal(np.asarray(c[1]), np.asarray(stacked[1]))
    assert int(t[1]) == 0 and int(t[0]) == 3


def test_entropy_exec_mesh_refuses_forced_pallas():
    from jax.sharding import Mesh

    from graphdyn.pipeline.entropy_group import EntropyCellExec

    cfg = _entropy_cfg()
    cells, _ = _entropy_cells(seeds=(0, 1))
    mesh = Mesh(np.array(jax.devices("cpu")[:2]), ("cell",))
    with pytest.raises(ValueError, match="mesh"):
        EntropyCellExec(cells, cfg, kernel="pallas", mesh=mesh)


def _hpr_items(n=64, d=4, reps=3, seed0=100):
    cfg = HPRConfig(dynamics=DynamicsConfig(p=1, c=1), max_sweeps=6)
    from graphdyn.pipeline.hpr_group import _build_rep

    return cfg, [_build_rep(n, d, cfg, seed0 + k, "pairing")
                 for k in range(reps)]


def _run_hpr(cfg, items, kernel, chunk=3, seeds=None):
    from graphdyn.pipeline.hpr_group import HPRGroupExec

    ex = HPRGroupExec(items, cfg, kernel=kernel)
    st = ex.init_state(
        [it[2] for it in items], [it[3] for it in items],
        [it[4] for it in items],
        seeds if seeds is not None
        else [100 + k for k in range(len(items))],
    )
    return ex, ex.run(st, chunk_sweeps=chunk)


def test_hpr_exec_pallas_matches_xla():
    cfg, items = _hpr_items()
    exp, sp = _run_hpr(cfg, items, "pallas")
    exx, sx = _run_hpr(cfg, items, "xla")
    assert exp.spec.pallas == ("interpret",)
    assert exx.spec.pallas == ("",)
    np.testing.assert_allclose(
        np.asarray(sp.chi), np.asarray(sx.chi), rtol=5e-3, atol=1e-5
    )
    np.testing.assert_array_equal(np.asarray(sp.steps), np.asarray(sx.steps))


def test_hpr_exec_pallas_grouped_equals_g1_bit_exact():
    """Grouped-Pallas HPr == serial-Pallas (G=1) bit-exact per repetition —
    full chains to completion, chi AND the discrete reinforcement state."""
    cfg, items = _hpr_items()
    _, sp = _run_hpr(cfg, items, "pallas")
    for g in range(len(items)):
        _, s1 = _run_hpr(cfg, [items[g]], "pallas", seeds=[100 + g])
        np.testing.assert_array_equal(
            np.asarray(sp.chi[g]), np.asarray(s1.chi[0])
        )
        np.testing.assert_array_equal(
            np.asarray(sp.biases[g]), np.asarray(s1.biases[0])
        )
        np.testing.assert_array_equal(np.asarray(sp.s[g]), np.asarray(s1.s[0]))
        assert int(sp.steps[g]) == int(s1.steps[0])


# ---------------------------------------------------------------------------
# resilience: runtime Pallas -> XLA degrade through the grouped executors
# ---------------------------------------------------------------------------


@pytest.mark.faultinject
def test_entropy_exec_lowering_failure_degrades_to_xla(caplog):
    from graphdyn.pipeline.entropy_group import EntropyCellExec

    cfg = _entropy_cfg()
    cells, chis = _entropy_cells(seeds=(0, 0))
    lm = jnp.asarray([0.1, 0.3], jnp.float32)
    act = jnp.ones(2, bool)
    d0 = jnp.full(2, jnp.inf, jnp.float32)
    t0 = jnp.zeros(2, jnp.int32)
    exx = EntropyCellExec(cells, cfg, chunk_sweeps=4, kernel="xla")
    cx, tx, dx = exx.fixed_point_chunk(exx.stack_chi(chis), lm, act, d0, t0)
    exp = EntropyCellExec(cells, cfg, chunk_sweeps=4, kernel="pallas")
    assert any(exp.spec.pallas)
    with caplog.at_level(logging.WARNING, logger="graphdyn.ops"):
        with FaultPlan([FaultSpec("pallas.lower", count=99)]):
            cp, tp, dp = exp.fixed_point_chunk(
                exp.stack_chi(chis), lm, act, d0, t0
            )
    # degraded, not aborted; the rebuilt XLA spec sticks and matches the
    # pure-XLA program bit-for-bit
    assert not any(exp.spec.pallas)
    assert "use_pallas=False" in caplog.text
    np.testing.assert_array_equal(np.asarray(cp), np.asarray(cx))
    cp2, _, _ = exp.fixed_point_chunk(exp.stack_chi(chis), lm, act, d0, t0)
    np.testing.assert_array_equal(np.asarray(cp2), np.asarray(cx))


@pytest.mark.faultinject
def test_hpr_exec_lowering_failure_degrades_to_xla():
    cfg, items = _hpr_items(reps=2)
    exx, sx = _run_hpr(cfg, items, "xla", chunk=2)
    from graphdyn.pipeline.hpr_group import HPRGroupExec

    exp = HPRGroupExec(items, cfg, kernel="pallas")
    st = exp.init_state(
        [it[2] for it in items], [it[3] for it in items],
        [it[4] for it in items], [100, 101],
    )
    with FaultPlan([FaultSpec("pallas.lower", count=99)]):
        sp = exp.run(st, chunk_sweeps=2)
    assert not any(exp.spec.pallas)
    np.testing.assert_array_equal(np.asarray(sp.chi), np.asarray(sx.chi))
    np.testing.assert_array_equal(np.asarray(sp.s), np.asarray(sx.s))


# ---------------------------------------------------------------------------
# driver + CLI plumbing
# ---------------------------------------------------------------------------


def test_entropy_grid_kernel_pallas_end_to_end():
    """entropy_grid(kernel='pallas') runs the grouped ladder through the
    fused kernel (interpret) and lands within the documented tolerance of
    the XLA grid on every visited λ."""
    from graphdyn.models.entropy import entropy_grid

    cfg = _entropy_cfg(lmbd_max=0.2, lmbd_step=0.1, num_rep=1,
                       eps=1e-3, max_sweeps=40)
    kw = dict(seed=0, group_size=2, class_bucket=16)
    rx = entropy_grid(220, np.asarray([2.8, 3.2]), cfg, kernel="xla", **kw)
    rp = entropy_grid(220, np.asarray([2.8, 3.2]), cfg, kernel="pallas", **kw)
    np.testing.assert_array_equal(rp.n_lambda, rx.n_lambda)
    np.testing.assert_allclose(rp.ent, rx.ent, rtol=5e-3, atol=1e-4)
    np.testing.assert_allclose(rp.m_init, rx.m_init, rtol=5e-3, atol=1e-4)


def test_cli_kernel_flag_parses():
    from graphdyn.cli import build_parser

    ap = build_parser()
    a = ap.parse_args(["entropy", "--kernel", "pallas"])
    assert a.kernel == "pallas"
    a = ap.parse_args(["hpr", "--kernel", "xla"])
    assert a.kernel == "xla"
    a = ap.parse_args(["entropy"])
    assert a.kernel == "auto"
    with pytest.raises(SystemExit):
        ap.parse_args(["entropy", "--kernel", "fused"])
