"""graphdyn.analysis regression tests.

Per acceptance criteria: every GD rule must (a) fire on a minimal bad
example and (b) stay silent on the matching good example; the @contract
decorator must catch shape/dtype violations at trace time and cost nothing
on conforming calls.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from graphdyn.analysis import ContractError, contract, lint_sources
from graphdyn.analysis.graftlint import RULES


def _codes(src, path="x.py"):
    return [f.code for f in lint_sources([(path, src)])]


# ---------------------------------------------------------------------------
# graftlint rules: minimal bad example fires, matching good example doesn't
# ---------------------------------------------------------------------------


class TestGD001HostNumpy:
    def test_bad_np_call_in_jitted_fn(self):
        src = (
            "import jax, numpy as np\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return np.tanh(x)\n"
        )
        assert "GD001" in _codes(src)

    def test_bad_np_call_in_loop_body(self):
        src = (
            "import numpy as np\n"
            "from jax import lax\n"
            "def body(i, s):\n"
            "    return np.roll(s, 1)\n"
            "def run(s):\n"
            "    return lax.fori_loop(0, 10, body, s)\n"
        )
        assert "GD001" in _codes(src)

    def test_good_jnp_call(self):
        src = (
            "import jax, jax.numpy as jnp\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return jnp.tanh(x)\n"
        )
        assert _codes(src) == []

    def test_good_np_outside_jit(self):
        src = (
            "import numpy as np\n"
            "def f(x):\n"
            "    return np.tanh(x)\n"
        )
        assert _codes(src) == []

    def test_good_np_dtype_ctor_is_exempt(self):
        src = (
            "import jax, numpy as np\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x + np.int32(3)\n"
        )
        assert _codes(src) == []


class TestGD002TracedBranch:
    BAD = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x, n):\n"
        "    if n > 0:\n"
        "        return x\n"
        "    return -x\n"
    )

    def test_bad_if_on_traced_param(self):
        assert "GD002" in _codes(self.BAD)

    def test_good_if_on_static_param(self):
        src = (
            "import jax\n"
            "from functools import partial\n"
            "@partial(jax.jit, static_argnames=('n',))\n"
            "def f(x, n):\n"
            "    if n > 0:\n"
            "        return x\n"
            "    return -x\n"
        )
        assert _codes(src) == []

    def test_good_static_argnums(self):
        src = (
            "import jax\n"
            "from functools import partial\n"
            "@partial(jax.jit, static_argnums=(1,))\n"
            "def f(x, n):\n"
            "    while n > 0:\n"
            "        n -= 1\n"
            "    return x\n"
        )
        assert _codes(src) == []

    def test_bad_for_over_traced(self):
        src = (
            "import jax\n"
            "@jax.jit\n"
            "def f(xs):\n"
            "    acc = 0\n"
            "    for x in xs:\n"
            "        acc = acc + x\n"
            "    return acc\n"
        )
        assert "GD002" in _codes(src)


class TestGD003HostSync:
    def test_bad_item(self):
        src = (
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x.sum().item()\n"
        )
        assert "GD003" in _codes(src)

    def test_bad_float_cast(self):
        src = (
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return float(x)\n"
        )
        assert "GD003" in _codes(src)

    def test_bad_np_asarray(self):
        src = (
            "import jax, numpy as np\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return np.asarray(x)\n"
        )
        assert "GD003" in _codes(src)

    def test_good_float_of_static(self):
        src = (
            "import jax\n"
            "from functools import partial\n"
            "@partial(jax.jit, static_argnames=('damp',))\n"
            "def f(x, damp):\n"
            "    return x * float(damp)\n"
        )
        assert _codes(src) == []

    def test_good_outside_jit(self):
        src = "def f(x):\n    return float(x)\n"
        assert _codes(src) == []


class TestGD004DtypeContract:
    def test_bad_float64_literal_anywhere(self):
        src = "import numpy as np\nA = np.zeros(3, np.float64)\n"
        assert "GD004" in _codes(src, "graphdyn/models/foo.py")

    def test_bad_dtypeless_zeros_in_ops(self):
        src = "import jax.numpy as jnp\ndef f(n):\n    return jnp.zeros(n)\n"
        assert "GD004" in _codes(src, "graphdyn/ops/foo.py")

    def test_good_dtypeless_zeros_outside_ops(self):
        src = "import jax.numpy as jnp\ndef f(n):\n    return jnp.zeros(n)\n"
        assert _codes(src, "graphdyn/models/foo.py") == []

    def test_good_explicit_dtype_in_ops(self):
        src = (
            "import jax.numpy as jnp\n"
            "def f(n):\n"
            "    return jnp.zeros(n, jnp.int32) + jnp.arange(n, dtype=jnp.int8)\n"
        )
        assert _codes(src, "graphdyn/ops/foo.py") == []

    def test_good_positional_dtype(self):
        src = (
            "import jax.numpy as jnp\n"
            "def f(n):\n"
            "    return jnp.ones((n, 2), jnp.float32)\n"
        )
        assert _codes(src, "graphdyn/parallel/foo.py") == []


class TestGD005JitHygiene:
    def test_bad_string_param_not_static(self):
        src = (
            "import jax\n"
            "@jax.jit\n"
            "def f(x, rule='majority'):\n"
            "    return x\n"
        )
        assert "GD005" in _codes(src)

    def test_bad_enum_annotation_not_static(self):
        src = (
            "import enum, jax\n"
            "class Rule(str, enum.Enum):\n"
            "    A = 'a'\n"
            "@jax.jit\n"
            "def f(x, rule: Rule):\n"
            "    return x\n"
        )
        assert "GD005" in _codes(src)

    def test_enum_names_shared_across_files(self):
        """The enum may be defined in a sibling module of the lint run."""
        enum_src = (
            "import enum\n"
            "class Rule(str, enum.Enum):\n"
            "    A = 'a'\n"
        )
        use_src = (
            "import jax\nfrom other import Rule\n"
            "@jax.jit\n"
            "def f(x, rule: Rule):\n"
            "    return x\n"
        )
        codes = [
            f.code
            for f in lint_sources([("other.py", enum_src), ("use.py", use_src)])
        ]
        assert "GD005" in codes

    def test_good_string_param_static(self):
        src = (
            "import jax\n"
            "from functools import partial\n"
            "@partial(jax.jit, static_argnames=('rule',))\n"
            "def f(x, rule='majority'):\n"
            "    return x\n"
        )
        assert _codes(src) == []

    def test_bad_unhashable_static_default(self):
        src = (
            "import jax\n"
            "from functools import partial\n"
            "@partial(jax.jit, static_argnames=('shape',))\n"
            "def f(x, shape=[3, 4]):\n"
            "    return x\n"
        )
        assert "GD005" in _codes(src)


class TestGD006Donation:
    BAD = (
        "import jax\nfrom jax import lax\n"
        "@jax.jit\n"
        "def rollout(s):\n"
        "    return lax.fori_loop(0, 8, lambda i, x: -x, s)\n"
    )

    def test_bad_rollout_without_donate(self):
        assert "GD006" in _codes(self.BAD)

    def test_good_rollout_with_donate(self):
        src = (
            "import jax\nfrom jax import lax\n"
            "from functools import partial\n"
            "@partial(jax.jit, donate_argnums=(0,))\n"
            "def rollout(s):\n"
            "    return lax.fori_loop(0, 8, lambda i, x: -x, s)\n"
        )
        assert _codes(src) == []

    def test_good_non_rollout_jit(self):
        src = (
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x + 1\n"
        )
        assert _codes(src) == []


class TestGD008HostLoopTransfers:
    """Per-iteration host→device transfer in a driver-module for-loop —
    the serial-ensemble anti-pattern the pipeline removes."""

    DRIVER = "graphdyn/models/driver.py"
    BAD_ASARRAY = (
        "import jax.numpy as jnp\n"
        "def ensemble(graphs):\n"
        "    out = []\n"
        "    for g in graphs:\n"
        "        nbr = jnp.asarray(g.nbr)\n"     # one transfer per rep
        "        out.append(run(nbr))\n"
        "    return out\n"
    )
    BAD_DEVICE_PUT = (
        "import jax\n"
        "def ensemble(tables):\n"
        "    for t in tables:\n"
        "        jax.device_put(t)\n"
    )

    def test_bad_asarray_in_driver_loop(self):
        assert "GD008" in _codes(self.BAD_ASARRAY, path=self.DRIVER)

    def test_bad_device_put_in_driver_loop(self):
        assert "GD008" in _codes(self.BAD_DEVICE_PUT, path=self.DRIVER)

    def test_good_hoisted_stack(self):
        # the pipeline fix: stack once, transfer once, run one program
        src = (
            "import numpy as np\nimport jax.numpy as jnp\n"
            "def ensemble(graphs):\n"
            "    nbr = jnp.asarray(np.stack([g.nbr for g in graphs]))\n"
            "    return run(nbr)\n"
        )
        assert _codes(src, path=self.DRIVER) == []

    def test_good_loop_without_transfer(self):
        src = (
            "def ensemble(graphs):\n"
            "    out = []\n"
            "    for g in graphs:\n"
            "        out.append(g.n)\n"
            "    return out\n"
        )
        assert _codes(src, path=self.DRIVER) == []

    def test_non_driver_module_exempt(self):
        # ops/tests/benchmarks may stage per-iteration buffers freely
        assert "GD008" not in _codes(self.BAD_ASARRAY, path="graphdyn/ops/x.py")

    def test_jitted_for_loop_exempt(self):
        # a for-loop inside a jit context unrolls at trace time — there is
        # no per-iteration host->device transfer to flag
        src = (
            "import jax\nimport jax.numpy as jnp\n"
            "@jax.jit\n"
            "def body(s):\n"
            "    for j in range(3):\n"
            "        s = s + jnp.asarray(1)\n"
            "    return s\n"
        )
        assert "GD008" not in _codes(src, path=self.DRIVER)

    def test_disable_comment(self):
        src = (
            "import jax.numpy as jnp\n"
            "def ladder(lambdas):\n"
            "    for lmbd in lambdas:\n"
            "        # graftlint: disable-next-line=GD008  one scalar per step\n"
            "        run(jnp.asarray(lmbd))\n"
        )
        assert _codes(src, path=self.DRIVER) == []

    def test_catalogued(self):
        assert "GD008" in RULES


class TestGD009VmapOverPallas:
    """jax.vmap over a pallas_call-backed callable — lowers to a serial
    loop of kernel launches instead of a batched grid."""

    BAD_DIRECT = (
        "import jax\n"
        "from jax.experimental import pallas as pl\n"
        "def kernel(x_ref, o_ref):\n"
        "    o_ref[:] = x_ref[:] + 1\n"
        "def fused(x):\n"
        "    return pl.pallas_call(kernel, out_shape=x)(x)\n"
        "batched = jax.vmap(fused)\n"
    )
    BAD_TRANSITIVE = (
        "import jax\n"
        "from jax.experimental import pallas as pl\n"
        "def fused(x):\n"
        "    return pl.pallas_call(k, out_shape=x)(x)\n"
        "def wrapper(x):\n"
        "    return fused(x) * 2\n"
        "out = jax.vmap(wrapper)(xs)\n"
    )
    BAD_PARTIAL = (
        "import jax\n"
        "from functools import partial\n"
        "from jax.experimental import pallas as pl\n"
        "def fused(x, d):\n"
        "    return pl.pallas_call(k, out_shape=x)(x)\n"
        "f3 = partial(fused, d=3)\n"
        "out = jax.vmap(f3)(xs)\n"
    )
    BAD_LAMBDA = (
        "import jax\n"
        "from jax.experimental import pallas as pl\n"
        "def fused(x):\n"
        "    return pl.pallas_call(k, out_shape=x)(x)\n"
        "out = jax.vmap(lambda x: fused(x))(xs)\n"
    )

    def test_bad_vmap_of_kernel_fn(self):
        assert "GD009" in _codes(self.BAD_DIRECT)

    def test_bad_vmap_of_transitive_wrapper(self):
        assert "GD009" in _codes(self.BAD_TRANSITIVE)

    def test_bad_vmap_of_partial(self):
        assert "GD009" in _codes(self.BAD_PARTIAL)

    def test_bad_vmap_of_lambda_wrapper(self):
        assert "GD009" in _codes(self.BAD_LAMBDA)

    def test_good_grid_axis(self):
        # the fix: the batch axis is a grid dimension of ONE kernel launch
        src = (
            "import jax\n"
            "from jax.experimental import pallas as pl\n"
            "def kernel(x_ref, o_ref):\n"
            "    o_ref[:] = x_ref[:] + 1\n"
            "def fused_grouped(x):\n"
            "    return pl.pallas_call(kernel, grid=(x.shape[0],),\n"
            "                          out_shape=x)(x)\n"
        )
        assert _codes(src) == []

    def test_good_vmap_of_plain_fn(self):
        # vmap over XLA-only callables stays legal, even in a module that
        # also defines a kernel-backed function
        src = (
            "import jax\n"
            "from jax.experimental import pallas as pl\n"
            "def fused(x):\n"
            "    return pl.pallas_call(k, out_shape=x)(x)\n"
            "def plain(x):\n"
            "    return x + 1\n"
            "out = jax.vmap(plain)(xs)\n"
        )
        assert _codes(src) == []

    def test_disable_comment(self):
        src = (
            "import jax\n"
            "from jax.experimental import pallas as pl\n"
            "def fused(x):\n"
            "    return pl.pallas_call(k, out_shape=x)(x)\n"
            "# graftlint: disable-next-line=GD009  measured: G<=2, launch overhead negligible\n"
            "out = jax.vmap(fused)(xs)\n"
        )
        assert _codes(src) == []

    def test_catalogued(self):
        assert "GD009" in RULES


class TestGD010AliasCrossing:
    """jnp.asarray of a host buffer the same function mutates (the PR-4
    alias race: on CPU the device array may alias the numpy buffer for its
    whole lifetime, so the mutation races async device reads)."""

    DRIVER = "graphdyn/pipeline/driver.py"
    BAD = (
        "import numpy as np\n"
        "import jax.numpy as jnp\n"
        "def ladder(G):\n"
        "    lam = np.zeros(G, np.float32)\n"
        "    lam[0] = 0.1\n"                       # mutated host buffer
        "    dev = jnp.asarray(lam)\n"             # aliasing crossing
        "    lam[1] = 0.2\n"
        "    return dev\n"
    )
    GOOD_COPY = (
        "import numpy as np\n"
        "import jax.numpy as jnp\n"
        "def ladder(G):\n"
        "    lam = np.zeros(G, np.float32)\n"
        "    lam[0] = 0.1\n"
        "    dev = jnp.array(lam)\n"               # explicit copy: safe
        "    lam[1] = 0.2\n"
        "    return dev\n"
    )
    GOOD_UNMUTATED = (
        "import numpy as np\n"
        "import jax.numpy as jnp\n"
        "def ship(tables):\n"
        "    t = np.stack(tables)\n"
        "    return jnp.asarray(t)\n"              # never mutated: fine
    )

    def test_bad_asarray_of_mutated_buffer(self):
        assert "GD010" in _codes(self.BAD, path=self.DRIVER)

    def test_bad_inplace_method_counts_as_mutation(self):
        src = (
            "import numpy as np\n"
            "import jax.numpy as jnp\n"
            "def ladder(G):\n"
            "    lam = np.zeros(G, np.float32)\n"
            "    lam.fill(0.5)\n"
            "    return jnp.asarray(lam)\n"
        )
        assert "GD010" in _codes(src, path=self.DRIVER)

    def test_good_copy_crossing(self):
        assert _codes(self.GOOD_COPY, path=self.DRIVER) == []

    def test_good_unmutated_buffer(self):
        assert _codes(self.GOOD_UNMUTATED, path=self.DRIVER) == []

    def test_non_driver_module_exempt(self):
        # ops/ kernels stage read-only tables; the rule targets the driver
        # layer where the PR-4 race lived
        assert _codes(self.BAD, path="graphdyn/ops/tables.py") == []

    def test_shadowed_local_in_nested_fn_does_not_flag_outer(self):
        # the inner function mutates its OWN `lam`; the outer crossing of
        # a never-mutated same-named buffer is safe (scope-correct)
        src = (
            "import numpy as np\n"
            "import jax.numpy as jnp\n"
            "def outer(G):\n"
            "    lam = np.zeros(G, np.float32)\n"
            "    dev = jnp.asarray(lam)\n"
            "    def inner(H):\n"
            "        lam = np.zeros(H, np.float32)\n"
            "        lam[0] = 1.0\n"
            "        return lam\n"
            "    return dev, inner\n"
        )
        assert _codes(src, path=self.DRIVER) == []

    def test_nested_fn_own_mutation_still_flagged(self):
        # the same pattern INSIDE one scope still fires
        src = (
            "import numpy as np\n"
            "import jax.numpy as jnp\n"
            "def outer(G):\n"
            "    def inner(H):\n"
            "        lam = np.zeros(H, np.float32)\n"
            "        lam[0] = 1.0\n"
            "        return jnp.asarray(lam)\n"
            "    return inner\n"
        )
        assert "GD010" in _codes(src, path=self.DRIVER)

    def test_disable_comment(self):
        src = self.BAD.replace(
            "    dev = jnp.asarray(lam)\n",
            "    # graftlint: disable-next-line=GD010  device read synced above\n"
            "    dev = jnp.asarray(lam)\n",
        )
        assert _codes(src, path=self.DRIVER) == []

    def test_catalogued(self):
        assert "GD010" in RULES


class TestGD011BareTiming:
    """Bare ``time.time()``/``time.perf_counter()`` brackets in driver
    modules bypass the obs event ledger — the one timing idiom is
    ``graphdyn.obs.timed``/``obs.span`` (ARCHITECTURE.md "Runtime
    telemetry")."""

    DRIVER = "graphdyn/pipeline/driver.py"
    BAD_PERF_COUNTER = (
        "import time\n"
        "def run(reps):\n"
        "    t0 = time.perf_counter()\n"           # GD011
        "    work(reps)\n"
        "    return time.perf_counter() - t0\n"    # GD011
    )
    BAD_TIME_TIME = (
        "import time\n"
        "def run(reps):\n"
        "    t0 = time.time()\n"                   # GD011
        "    work(reps)\n"
        "    return time.time() - t0\n"            # GD011
    )
    BAD_BARE_IMPORT = (
        "from time import perf_counter\n"
        "def run(reps):\n"
        "    t0 = perf_counter()\n"                # GD011
        "    work(reps)\n"
        "    return perf_counter() - t0\n"         # GD011
    )
    GOOD_OBS = (
        "from graphdyn import obs\n"
        "def run(reps):\n"
        "    with obs.timed('pipeline.group', reps=reps) as sw:\n"
        "        work(reps)\n"
        "    return sw.wall_s\n"
    )
    GOOD_MONOTONIC = (
        "import time\n"
        "def wait(q):\n"
        "    t0 = time.monotonic()\n"    # bookkeeping clock: allowed
        "    q.get()\n"
        "    return time.monotonic() - t0\n"
    )

    def test_bad_perf_counter(self):
        assert _codes(self.BAD_PERF_COUNTER, path=self.DRIVER).count(
            "GD011") == 2

    def test_bad_time_time(self):
        assert "GD011" in _codes(self.BAD_TIME_TIME, path=self.DRIVER)

    def test_bad_bare_from_import(self):
        assert "GD011" in _codes(self.BAD_BARE_IMPORT, path=self.DRIVER)

    def test_bad_bare_time_from_import(self):
        src = (
            "from time import time\n"
            "def run(reps):\n"
            "    t0 = time()\n"                   # GD011
            "    work(reps)\n"
            "    return time() - t0\n"            # GD011
        )
        assert _codes(src, path=self.DRIVER).count("GD011") == 2

    def test_good_obs_timed(self):
        assert _codes(self.GOOD_OBS, path=self.DRIVER) == []

    def test_good_monotonic_exempt(self):
        assert _codes(self.GOOD_MONOTONIC, path=self.DRIVER) == []

    def test_models_and_cli_and_bench_in_scope(self):
        for path in ("graphdyn/models/solver.py", "graphdyn/cli.py",
                     "bench.py"):
            assert "GD011" in _codes(self.BAD_PERF_COUNTER, path=path), path

    def test_non_driver_module_exempt(self):
        # the obs implementation and the deprecated profiling shim ARE the
        # timing layer; ops/utils are out of the driver scope
        for path in ("graphdyn/obs/roofline.py",
                     "graphdyn/utils/profiling.py",
                     "graphdyn/ops/bdcm.py"):
            assert _codes(self.BAD_PERF_COUNTER, path=path) == [], path

    def test_strftime_not_flagged(self):
        # time.strftime / time.monotonic / time.process_time are not the
        # wall-clock measurement idiom GD011 polices
        src = (
            "import time\n"
            "def mark(msg):\n"
            "    return time.strftime('%H:%M:%S') + msg\n"
        )
        assert _codes(src, path=self.DRIVER) == []

    def test_disable_comment(self):
        src = self.BAD_TIME_TIME.replace(
            "    t0 = time.time()\n",
            "    # graftlint: disable-next-line=GD011  epoch stamp for a filename, not a measurement\n"
            "    t0 = time.time()\n",
        ).replace(
            "    return time.time() - t0\n",
            "    return t0  # graftlint: disable=GD011  ditto\n",
        )
        assert _codes(src, path=self.DRIVER) == []

    def test_catalogued(self):
        assert "GD011" in RULES


class TestGD012BareProfiler:
    """Bare ``jax.profiler`` capture/annotation calls outside
    ``graphdyn/obs/`` fork the device-timeline vocabulary away from the
    event ledger's — the one profiling idiom is
    ``graphdyn.obs.trace.profiling`` (CLI ``--profile``), whose span-named
    ``TraceAnnotation``s keep the two aligned."""

    DRIVER = "graphdyn/pipeline/driver.py"
    BAD_START_STOP = (
        "import jax\n"
        "def run(logdir):\n"
        "    jax.profiler.start_trace(logdir)\n"      # GD012
        "    work()\n"
        "    jax.profiler.stop_trace()\n"             # GD012
    )
    BAD_ANNOTATION = (
        "import jax\n"
        "def chunk(i):\n"
        "    with jax.profiler.TraceAnnotation(f'chunk{i}'):\n"  # GD012
        "        work(i)\n"
    )
    BAD_BARE_IMPORT = (
        "from jax.profiler import start_trace, stop_trace\n"
        "def run(logdir):\n"
        "    start_trace(logdir)\n"                   # GD012
        "    work()\n"
        "    stop_trace()\n"                          # GD012
    )
    BAD_TRACE_CTX = (
        "import jax\n"
        "def run(logdir):\n"
        "    with jax.profiler.trace(logdir):\n"      # GD012
        "        work()\n"
    )
    BAD_BARE_DECORATOR = (
        "import jax\n"
        "@jax.profiler.annotate_function\n"           # GD012
        "def step(x):\n"
        "    return x + 1\n"
    )
    BAD_ALIASED_MODULE = (
        "import jax.profiler as jp\n"
        "def run(logdir):\n"
        "    jp.start_trace(logdir)\n"                # GD012
        "    work()\n"
        "    jp.stop_trace()\n"                       # GD012
    )
    BAD_TRACE_FROM_IMPORT = (
        "from jax.profiler import trace\n"            # GD012 (the import)
        "def run(logdir):\n"
        "    with trace(logdir):\n"
        "        work()\n"
    )
    GOOD_OBS_TRACE = (
        "from graphdyn import obs\n"
        "def run(logdir):\n"
        "    with obs.trace.profiling(logdir):\n"
        "        with obs.span('run'):\n"
        "            work()\n"
    )

    def test_bad_start_stop(self):
        assert _codes(self.BAD_START_STOP, path=self.DRIVER).count(
            "GD012") == 2

    def test_bad_trace_annotation(self):
        assert "GD012" in _codes(self.BAD_ANNOTATION, path=self.DRIVER)

    def test_bad_bare_from_import(self):
        assert _codes(self.BAD_BARE_IMPORT, path=self.DRIVER).count(
            "GD012") == 2

    def test_bad_trace_context_manager(self):
        assert "GD012" in _codes(self.BAD_TRACE_CTX, path=self.DRIVER)

    def test_bad_trace_from_import_flagged_at_import(self):
        # the bare `trace` call can't be policed syntactically, so the
        # `from jax.profiler import trace` statement itself is the gate
        assert "GD012" in _codes(self.BAD_TRACE_FROM_IMPORT,
                                 path=self.DRIVER)

    def test_bad_aliased_module_import(self):
        # `import jax.profiler as jp; jp.start_trace(...)` — the final
        # attribute matches under any parent, so the alias can't hide it
        assert _codes(self.BAD_ALIASED_MODULE, path=self.DRIVER).count(
            "GD012") == 2

    def test_bad_bare_decorator_form(self):
        # @jax.profiler.annotate_function without parentheses is an
        # Attribute in decorator_list, not a Call — must still be caught
        assert "GD012" in _codes(self.BAD_BARE_DECORATOR, path=self.DRIVER)

    def test_good_obs_trace_profiling(self):
        assert _codes(self.GOOD_OBS_TRACE, path=self.DRIVER) == []

    def test_bare_trace_name_not_flagged(self):
        # `trace` is only matched dotted under `profiler` — the bare name
        # is far too common (jaxprs, graph traces) to police syntactically
        src = (
            "def run(g):\n"
            "    return trace(g)\n"
        )
        assert _codes(src, path=self.DRIVER) == []

    def test_in_scope_everywhere_but_obs(self):
        # unlike GD011's driver scope, GD012 polices ops/utils too: there
        # is no legitimate private capture anywhere outside the obs layer
        for path in ("graphdyn/ops/bdcm.py", "graphdyn/cli.py",
                     "graphdyn/utils/helpers.py", "bench.py"):
            assert "GD012" in _codes(self.BAD_START_STOP, path=path), path

    def test_obs_layer_exempt(self):
        for path in ("graphdyn/obs/trace.py", "graphdyn/obs/recorder.py"):
            assert _codes(self.BAD_START_STOP, path=path) == [], path

    def test_disable_comment(self):
        src = self.BAD_ANNOTATION.replace(
            "    with jax.profiler.TraceAnnotation(f'chunk{i}'):\n",
            "    # graftlint: disable-next-line=GD012  profiler-internals test fixture\n"
            "    with jax.profiler.TraceAnnotation(f'chunk{i}'):\n",
        )
        assert _codes(src, path=self.DRIVER) == []

    def test_catalogued(self):
        assert "GD012" in RULES


class TestGD013ShardMapFullGather:
    """``lax.all_gather`` (or a ``jnp.take`` over its result) inside a
    shard-mapped body of ``graphdyn/parallel/``: the halo exchange moves
    only the partition's boundary spin words per step — a full-node-axis
    gather is the O(n)-bytes collective the node sharding exists to
    remove (ARCHITECTURE.md "Node-axis sharding & halo exchange")."""

    PARALLEL = "graphdyn/parallel/solver.py"
    BAD_GATHER = (
        "from jax import lax\n"
        "from graphdyn.parallel.mesh import shard_map\n"
        "def make(mesh, steps):\n"
        "    def rollout(nbr, s):\n"
        "        def body(_, s_loc):\n"
        "            s_full = lax.all_gather(s_loc, 'node', axis=1, tiled=True)\n"  # GD013
        "            return step(nbr, s_full, s_loc)\n"
        "        return lax.fori_loop(0, steps, body, s)\n"
        "    return shard_map(rollout, mesh=mesh, in_specs=(), out_specs=())\n"
    )
    BAD_TAKE_OVER_GATHER = (
        "import jax.numpy as jnp\n"
        "from jax import lax\n"
        "from graphdyn.parallel.mesh import shard_map\n"
        "def make(mesh):\n"
        "    def body(nbr, s_loc):\n"
        "        s_full = lax.all_gather(s_loc, 'node', axis=1, tiled=True)\n"   # GD013
        "        return jnp.take(s_full, nbr.reshape(-1), axis=1)\n"             # GD013
        "    return shard_map(body, mesh=mesh, in_specs=(), out_specs=())\n"
    )
    BAD_TRANSITIVE_CALLEE = (
        "from jax import lax\n"
        "from graphdyn.parallel.mesh import shard_map\n"
        "def helper(s_loc):\n"
        "    return lax.all_gather(s_loc, 'node', axis=1, tiled=True)\n"  # GD013 (called from the body)
        "def make(mesh):\n"
        "    def body(nbr, s_loc):\n"
        "        return helper(s_loc)\n"
        "    return shard_map(body, mesh=mesh, in_specs=(), out_specs=())\n"
    )
    GOOD_HALO = (
        "import jax.numpy as jnp\n"
        "from jax import lax\n"
        "from graphdyn.parallel.mesh import shard_map\n"
        "def make(mesh, perms, steps):\n"
        "    def rollout(nbr, send_idx, recv_idx, s):\n"
        "        def body(_, s_loc):\n"
        "            out = update(nbr, s_loc)\n"
        "            buf = jnp.take(out, send_idx, axis=0)\n"     # boundary slab only
        "            buf = lax.ppermute(buf, 'node', perms)\n"
        "            return out.at[recv_idx].set(buf)\n"
        "        return lax.fori_loop(0, steps, body, s)\n"
        "    return shard_map(rollout, mesh=mesh, in_specs=(), out_specs=())\n"
    )
    GOOD_GATHER_OUTSIDE_SHARD_MAP = (
        "from jax import lax\n"
        "def host_helper(s):\n"
        "    return lax.all_gather(s, 'node', axis=1, tiled=True)\n"
    )

    def test_bad_all_gather_in_body(self):
        assert "GD013" in _codes(self.BAD_GATHER, path=self.PARALLEL)

    def test_bad_take_over_gather_result(self):
        assert _codes(self.BAD_TAKE_OVER_GATHER, path=self.PARALLEL).count(
            "GD013") == 2

    def test_bad_transitive_module_local_callee(self):
        assert "GD013" in _codes(self.BAD_TRANSITIVE_CALLEE,
                                 path=self.PARALLEL)

    def test_good_halo_exchange(self):
        assert _codes(self.GOOD_HALO, path=self.PARALLEL) == []

    def test_good_gather_outside_shard_map_scope(self):
        assert _codes(self.GOOD_GATHER_OUTSIDE_SHARD_MAP,
                      path=self.PARALLEL) == []

    def test_non_parallel_module_exempt(self):
        for path in ("graphdyn/ops/packed.py", "graphdyn/models/sa.py",
                     "graphdyn/pipeline/sa_group.py"):
            assert _codes(self.BAD_GATHER, path=path) == [], path

    def test_disable_comment(self):
        src = self.BAD_GATHER.replace(
            "            s_full = lax.all_gather",
            "            # graftlint: disable-next-line=GD013  legacy gather mode: parity baseline\n"
            "            s_full = lax.all_gather",
        )
        assert _codes(src, path=self.PARALLEL) == []

    def test_catalogued(self):
        assert "GD013" in RULES


class TestGD014SearchLoopSync:
    """Host round-trips inside a ``graphdyn/search/`` drive loop: the
    tempering chunk+swap and chromatic sweep loops stay one device program
    per chunk — a per-chunk ``np.asarray``/``.item()`` materialization
    serializes the ladder on the host link (ARCHITECTURE.md "Search
    acceleration")."""

    SEARCH = "graphdyn/search/driver.py"
    BAD_ASARRAY = (
        "import numpy as np\n"
        "def drive(state, advance):\n"
        "    rates = []\n"
        "    while bool(state.active.any()):\n"
        "        state = advance(state)\n"
        "        rates.append(np.asarray(state.swap_acc))\n"   # GD014
        "    return state, rates\n"
    )
    BAD_ITEM = (
        "def drive(state, advance, chunks):\n"
        "    for _ in range(chunks):\n"
        "        state = advance(state)\n"
        "        if state.swap_acc.item() == 0:\n"             # GD014
        "            break\n"
        "    return state\n"
    )
    BAD_DEVICE_GET = (
        "import jax\n"
        "def drive(state, advance, chunks):\n"
        "    for _ in range(chunks):\n"
        "        state = advance(state)\n"
        "        log(jax.device_get(state.m_final))\n"         # GD014
        "    return state\n"
    )
    GOOD_STOP_TEST = (
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def drive(state, advance):\n"
        "    while bool(jnp.any(state.active)):\n"   # the sanctioned sync
        "        state = advance(state)\n"
        "    return np.asarray(state.s)\n"           # ONE post-loop readback
    )
    GOOD_JIT_LOOP = (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def body(x):\n"
        "    for j in range(4):\n"                   # unrolls at trace time
        "        x = x + np.float32(j)\n"
        "    return x\n"
    )

    def test_bad_asarray_in_while(self):
        assert "GD014" in _codes(self.BAD_ASARRAY, path=self.SEARCH)

    def test_bad_item_in_for(self):
        assert "GD014" in _codes(self.BAD_ITEM, path=self.SEARCH)

    def test_bad_device_get(self):
        assert "GD014" in _codes(self.BAD_DEVICE_GET, path=self.SEARCH)

    BAD_INT_COERCE = (
        "def drive(state, advance, max_sweeps):\n"
        "    while bool(state.active.any()):\n"
        "        if int(state.sweeps) >= max_sweeps:\n"   # GD014
        "            break\n"
        "        state = advance(state)\n"
        "    return state\n"
    )
    BAD_BARE_ASARRAY = (
        "from numpy import asarray\n"
        "def drive(state, advance, chunks):\n"
        "    logs = []\n"
        "    for _ in range(chunks):\n"
        "        state = advance(state)\n"
        "        logs.append(asarray(state.m_final))\n"   # GD014
        "    return state\n"
    )

    def test_bad_int_coercion(self):
        assert "GD014" in _codes(self.BAD_INT_COERCE, path=self.SEARCH)

    def test_bad_bare_asarray_import_alias(self):
        assert "GD014" in _codes(self.BAD_BARE_ASARRAY, path=self.SEARCH)

    def test_good_stop_test_and_post_loop_readback(self):
        assert _codes(self.GOOD_STOP_TEST, path=self.SEARCH) == []

    def test_good_jit_loop_exempt(self):
        assert "GD014" not in _codes(self.GOOD_JIT_LOOP, path=self.SEARCH)

    def test_non_search_module_exempt(self):
        for path in ("graphdyn/models/sa.py", "graphdyn/pipeline/groups.py",
                     "bench.py"):
            assert "GD014" not in _codes(self.BAD_ASARRAY, path=path), path

    def test_disable_comment(self):
        src = self.BAD_ASARRAY.replace(
            "        rates.append(np.asarray(state.swap_acc))",
            "        # graftlint: disable-next-line=GD014  debug probe\n"
            "        rates.append(np.asarray(state.swap_acc))",
        )
        assert _codes(src, path=self.SEARCH) == []

    def test_catalogued(self):
        assert "GD014" in RULES

    def test_search_drivers_clean(self):
        """The shipped drivers honor their own rule (no disables needed)."""
        from pathlib import Path

        root = Path(__file__).resolve().parent.parent
        sources = [
            (str(p), p.read_text())
            for p in sorted((root / "graphdyn" / "search").glob("*.py"))
        ]
        assert [f for f in lint_sources(sources) if f.code == "GD014"] == []


class TestGD015AnnealLoopSync:
    """Per-temperature-step host syncs in a ``graphdyn/models/`` anneal
    drive loop: the schedule advances inside the device program
    (``metropolis_anneal_update``; the fused annealer keeps whole runs on
    device), so a drive loop reading the device back per step caps
    time-to-target on the host link (ARCHITECTURE.md "One-kernel
    annealing")."""

    MODELS = "graphdyn/models/annealer.py"
    BAD_ITEM = (
        "def anneal(state, step, n_temps):\n"
        "    for t in range(n_temps):\n"
        "        state = step(state)\n"
        "        if state.m_final.item() >= 1.0:\n"     # GD015
        "            break\n"
        "    return state\n"
    )
    BAD_DEVICE_GET = (
        "import jax\n"
        "def anneal(state, step, n_temps):\n"
        "    for t in range(n_temps):\n"
        "        state = step(state)\n"
        "        log(jax.device_get(state.energy))\n"   # GD015
        "    return state\n"
    )
    BAD_BOOL_SYNC = (
        "import jax.numpy as jnp\n"
        "def anneal(state, step):\n"
        "    while True:\n"
        "        state = step(state)\n"
        "        if not bool(jnp.any(state.active)):\n"  # GD015
        "            break\n"
        "    return state\n"
    )
    BAD_BLOCK = (
        "def anneal(state, step, n_temps):\n"
        "    for t in range(n_temps):\n"
        "        state = step(state)\n"
        "        state.s.block_until_ready()\n"          # GD015
        "    return state\n"
    )
    GOOD_HOST_BOOKKEEPING = (
        "def anneal(state, step, metas):\n"
        "    out = []\n"
        "    for meta in metas:\n"
        "        state = step(state)\n"
        "        out.append(bool(meta.get('failed')))\n"  # host value
        "    return state, out\n"
    )
    GOOD_JIT_LOOP = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def body(x):\n"
        "    for j in range(4):\n"                   # unrolls at trace time
        "        x = x + jnp.float32(j)\n"
        "    return x\n"
    )
    GOOD_POST_LOOP_READBACK = (
        "import numpy as np\n"
        "def anneal(state, step, n_temps):\n"
        "    for t in range(n_temps):\n"
        "        state = step(state)\n"
        "    return np.asarray(state.s)\n"           # ONE readback, after
    )

    def test_bad_item(self):
        assert "GD015" in _codes(self.BAD_ITEM, path=self.MODELS)

    def test_bad_device_get(self):
        assert "GD015" in _codes(self.BAD_DEVICE_GET, path=self.MODELS)

    def test_bad_bool_of_device_value(self):
        assert "GD015" in _codes(self.BAD_BOOL_SYNC, path=self.MODELS)

    BAD_INT_SYNC = (
        "import jax.numpy as jnp\n"
        "def anneal(state, step, n_temps, target):\n"
        "    for t in range(n_temps):\n"
        "        state = step(state)\n"
        "        if int(jnp.sum(state.sum_end)) >= target:\n"  # GD015
        "            break\n"
        "    return state\n"
    )
    BAD_FLOAT_SYNC = (
        "import jax.numpy as jnp\n"
        "def anneal(state, step, n_temps):\n"
        "    for t in range(n_temps):\n"
        "        state = step(state)\n"
        "        log(float(jnp.max(state.m)))\n"               # GD015
        "    return state\n"
    )

    def test_bad_int_float_of_device_call(self):
        assert "GD015" in _codes(self.BAD_INT_SYNC, path=self.MODELS)
        assert "GD015" in _codes(self.BAD_FLOAT_SYNC, path=self.MODELS)

    def test_bad_block_until_ready(self):
        assert "GD015" in _codes(self.BAD_BLOCK, path=self.MODELS)

    def test_good_host_bookkeeping_bool(self):
        assert "GD015" not in _codes(self.GOOD_HOST_BOOKKEEPING,
                                     path=self.MODELS)

    def test_good_jit_loop_exempt(self):
        assert "GD015" not in _codes(self.GOOD_JIT_LOOP, path=self.MODELS)

    def test_good_post_loop_readback(self):
        assert "GD015" not in _codes(self.GOOD_POST_LOOP_READBACK,
                                     path=self.MODELS)

    def test_non_models_module_exempt(self):
        for path in ("graphdyn/search/tempering.py",
                     "graphdyn/pipeline/groups.py", "bench.py"):
            assert "GD015" not in _codes(self.BAD_ITEM, path=path), path

    def test_disable_comment(self):
        src = self.BAD_ITEM.replace(
            "        if state.m_final.item() >= 1.0:",
            "        # graftlint: disable-next-line=GD015  debug probe\n"
            "        if state.m_final.item() >= 1.0:",
        )
        assert _codes(src, path=self.MODELS) == []

    def test_catalogued(self):
        assert "GD015" in RULES

    def test_shipped_models_clean(self):
        """The shipped solvers honor the rule with no disables: their
        schedules advance inside the device loops, and the only drive
        polls are chunk-granular (utils/io — out of scope)."""
        from pathlib import Path

        root = Path(__file__).resolve().parent.parent
        sources = [
            (str(p), p.read_text())
            for p in sorted((root / "graphdyn" / "models").glob("*.py"))
        ]
        assert [f for f in lint_sources(sources) if f.code == "GD015"] == []


class TestGD016ByteModelArith:
    """Hand-rolled byte-size arithmetic outside the sanctioned cost
    modules: an itemsize literal (4/8) multiplying two or more shape
    variables, or ``.nbytes`` aggregated through ``sum()``/arithmetic.
    Byte formulas belong where graftcost's GB102 gates them against the
    HLO-derived models (ARCHITECTURE.md "Cost-model contracts")."""

    OPS = "graphdyn/ops/tables.py"
    BAD_ITEMSIZE = (
        "def footprint(n, W):\n"
        "    return 4 * n * W\n"                    # GD016
    )
    BAD_ITEMSIZE8 = (
        "def footprint(n, chi, dmax):\n"
        "    total = 8 * n * chi * (1 + dmax)\n"    # GD016
        "    return total\n"
    )
    BAD_NBYTES_SUM = (
        "def footprint(tables):\n"
        "    return sum(t.nbytes for t in tables)\n"  # GD016
    )
    BAD_NBYTES_ARITH = (
        "def footprint(a, b):\n"
        "    return a.nbytes + b.nbytes\n"            # GD016
    )
    GOOD_SINGLE_VAR = (
        "def stride(n):\n"
        "    return 4 * n\n"                # one shape var: an offset, not a model
    )
    GOOD_NON_ITEMSIZE = (
        "def degree_pairs(E, K):\n"
        "    return 2 * E * K\n"            # 2 is a count, not an itemsize
    )
    GOOD_BARE_NBYTES = (
        "def report(arr):\n"
        "    return arr.nbytes\n"           # reading one buffer is not a model
    )

    def test_bad_itemsize_chain(self):
        assert "GD016" in _codes(self.BAD_ITEMSIZE, path=self.OPS)
        assert "GD016" in _codes(self.BAD_ITEMSIZE8, path=self.OPS)

    def test_bad_nbytes_aggregation(self):
        assert "GD016" in _codes(self.BAD_NBYTES_SUM, path=self.OPS)
        assert "GD016" in _codes(self.BAD_NBYTES_ARITH, path=self.OPS)

    def test_one_finding_per_chain(self):
        """A nested a*b*c*d chain flags once at the outermost Mult, not
        once per BinOp."""
        codes = _codes(self.BAD_ITEMSIZE8, path=self.OPS)
        assert codes == ["GD016"]

    def test_good_examples(self):
        for src in (self.GOOD_SINGLE_VAR, self.GOOD_NON_ITEMSIZE,
                    self.GOOD_BARE_NBYTES):
            assert _codes(src, path=self.OPS) == [], src

    def test_sanctioned_modules_exempt(self):
        for path in ("graphdyn/obs/memband.py", "graphdyn/obs/roofline.py",
                     "graphdyn/parallel/halo.py",
                     "graphdyn/analysis/graftcost.py",
                     "graphdyn/ops/pallas_bdcm.py", "bench.py"):
            assert "GD016" not in _codes(self.BAD_ITEMSIZE, path=path), path

    def test_disable_comment(self):
        src = self.BAD_ITEMSIZE.replace(
            "    return 4 * n * W",
            "    # graftlint: disable-next-line=GD016  refusal guard\n"
            "    return 4 * n * W",
        )
        assert _codes(src, path=self.OPS) == []

    def test_catalogued(self):
        assert "GD016" in RULES


class TestGD017PaddedTableFull:
    """Ghost-padded node-table construction (``np.full`` with a
    dimension-sized ghost-id fill) outside ``graphs.py``: the padded
    ``nbr[n, dmax]`` idiom hand-rolled at a call site bypasses the
    degree-bucketed layout routing (ROADMAP item 3) — layouts are built
    through the ``graphs.py`` builders / ``degree_buckets``."""

    OPS = "graphdyn/ops/tables.py"
    BAD_GHOST_FULL = (
        "import numpy as np\n"
        "def build(n, dmax):\n"
        "    return np.full((n, dmax), n, np.int32)\n"   # GD017
    )
    GOOD_CONST_FILL = (
        "import numpy as np\n"
        "def build(n, dmax):\n"
        "    return np.full((n, dmax), -1, np.int32)\n"  # sentinel, not ghost id
    )
    GOOD_OTHER_FILL = (
        "import numpy as np\n"
        "def build(n, dmax, ghost):\n"
        "    return np.full((n, dmax), ghost)\n"   # fill is not a dimension
    )
    GOOD_1D = (
        "import numpy as np\n"
        "def build(n):\n"
        "    return np.full(n, n)\n"               # not a 2-D node table
    )

    def test_bad_ghost_padded_table(self):
        assert "GD017" in _codes(self.BAD_GHOST_FULL, path=self.OPS)

    def test_good_examples(self):
        for src in (self.GOOD_CONST_FILL, self.GOOD_OTHER_FILL,
                    self.GOOD_1D):
            assert _codes(src, path=self.OPS) == [], src

    def test_graphs_and_out_of_tree_exempt(self):
        for path in ("graphdyn/graphs.py", "bench.py", "tests/test_x.py"):
            assert "GD017" not in _codes(self.BAD_GHOST_FULL, path=path), path

    def test_disable_comment(self):
        src = self.BAD_GHOST_FULL.replace(
            "    return np.full((n, dmax), n, np.int32)",
            "    # graftlint: disable-next-line=GD017  ball-table build\n"
            "    return np.full((n, dmax), n, np.int32)",
        )
        assert _codes(src, path=self.OPS) == []

    def test_catalogued(self):
        assert "GD017" in RULES


class TestGD007AtomicPersistence:
    BAD_SAVEZ = (
        "import numpy as np\n"
        "def persist(path, arr):\n"
        "    np.savez(path, arr=arr)\n"
    )
    BAD_OPEN = (
        "import json\n"
        "def persist(path, doc):\n"
        "    with open(path, 'w') as f:\n"
        "        json.dump(doc, f)\n"
    )

    def test_bad_direct_savez(self):
        assert "GD007" in _codes(self.BAD_SAVEZ)

    def test_bad_open_for_write(self):
        assert "GD007" in _codes(self.BAD_OPEN)

    def test_good_temp_then_replace(self):
        src = (
            "import os\nimport numpy as np\n"
            "def persist(path, arr):\n"
            "    tmp = path + '.tmp.npz'\n"
            "    np.savez(tmp, arr=arr)\n"
            "    os.replace(tmp, path + '.npz')\n"
        )
        assert _codes(src) == []

    def test_good_open_for_read(self):
        src = (
            "def read(path):\n"
            "    with open(path) as f:\n"
            "        return f.read()\n"
        )
        assert _codes(src) == []

    def test_utils_io_exempt(self):
        # the atomic-write implementation itself may touch raw write APIs
        assert _codes(self.BAD_SAVEZ, path="graphdyn/utils/io.py") == []

    def test_temp_is_a_token_not_a_substring(self):
        # 'attempt_path'/'template' contain 'temp' but are not temp paths
        src = (
            "import numpy as np\n"
            "def persist(attempt_path, template, arr):\n"
            "    np.savez(attempt_path, arr=arr)\n"
            "    with open(template, 'w') as f:\n"
            "        f.write('x')\n"
        )
        assert _codes(src) == ["GD007", "GD007"]

    def test_tempfile_module_is_exempt(self):
        src = (
            "import tempfile\n"
            "def scratch(doc):\n"
            "    with open(tempfile.mktemp(), 'w') as f:\n"
            "        f.write(doc)\n"
        )
        # tempfile.mktemp: 'tempfile' token → exempt. (mktemp ends in
        # 'temp' as a substring only, but the module name already exempts.)
        assert _codes(src) == []

    def test_disable_escape_hatch(self):
        src = (
            "import numpy as np\n"
            "def persist(path, arr):\n"
            "    np.savez(path, arr=arr)  # graftlint: disable=GD007  "
            "scratch file, never resumed\n"
        )
        assert _codes(src) == []


class TestDisableComments:
    BAD_LINE = "    return np.tanh(x)"

    def _src(self, line):
        return (
            "import jax, numpy as np\n"
            "@jax.jit\n"
            "def f(x):\n"
            f"{line}\n"
        )

    def test_same_line_disable(self):
        src = self._src(
            self.BAD_LINE + "  # graftlint: disable=GD001  parity oracle"
        )
        assert _codes(src) == []

    def test_next_line_disable(self):
        src = self._src(
            "    # graftlint: disable-next-line=GD001  parity oracle\n"
            + self.BAD_LINE
        )
        assert _codes(src) == []

    def test_file_disable(self):
        src = "# graftlint: disable-file=GD001  oracle module\n" + self._src(
            self.BAD_LINE
        )
        assert _codes(src) == []

    def test_disable_wrong_code_does_not_silence(self):
        src = self._src(self.BAD_LINE + "  # graftlint: disable=GD004  nope")
        assert "GD001" in _codes(src)

    def test_disable_list(self):
        src = self._src(
            "    return int(np.ceil(x))"
            "  # graftlint: disable=GD001,GD003  trace-time"
        )
        assert _codes(src) == []

    def test_single_space_before_reason_still_disables(self):
        """A one-space separator between code and reason must not corrupt
        the code list (regression: the old parser needed two spaces)."""
        src = self._src(
            self.BAD_LINE + "  # graftlint: disable=GD001 parity oracle"
        )
        assert _codes(src) == []

    def test_reason_words_are_not_parsed_as_codes(self):
        src = self._src(
            self.BAD_LINE + "  # graftlint: disable=GD004 host, staging"
        )
        assert "GD001" in _codes(src)  # only GD004 disabled, not GD001


class TestScoping:
    def test_nested_fn_params_do_not_leak_to_siblings(self):
        """Params of a nested loop body must not poison GD002 checks on
        plain-Python sibling statements reusing the same names."""
        src = (
            "import jax\nfrom jax import lax\n"
            "from functools import partial\n"
            "@partial(jax.jit, donate_argnums=(0,))\n"
            "def f(x):\n"
            "    def body(i, s):\n"
            "        return s + 1\n"
            "    y = lax.fori_loop(0, 8, body, x)\n"
            "    i = 0\n"
            "    while i < 3:\n"       # plain host loop on a local int
            "        i += 1\n"
            "    return y\n"
        )
        assert _codes(src) == []

    def test_nested_fn_branch_on_own_param_still_fires(self):
        src = (
            "import jax\nfrom jax import lax\n"
            "from functools import partial\n"
            "@partial(jax.jit, donate_argnums=(0,))\n"
            "def f(x):\n"
            "    def body(i, s):\n"
            "        if s > 0:\n"      # traced loop-carry
            "            return s\n"
            "        return -s\n"
            "    return lax.fori_loop(0, 8, body, x)\n"
        )
        assert "GD002" in _codes(src)


def test_unreadable_file_is_a_finding(tmp_path):
    """The gate fails closed: a .py path that cannot be read counts as a
    finding instead of silently passing."""
    from graphdyn.analysis import lint_paths

    bad = tmp_path / "broken.py"
    bad.symlink_to(tmp_path / "does-not-exist.py")
    findings = lint_paths([str(tmp_path)])
    assert [f.code for f in findings] == ["GD000"]
    assert "cannot read" in findings[0].message


def test_rules_registry_complete():
    assert set(RULES) == {f"GD{i:03d}" for i in range(1, 18)}


def test_cli_json_is_one_document_stdout_only(tmp_path):
    """CI pipes ``python -m graphdyn.analysis --format=json``: stdout must
    be EXACTLY one parseable JSON document (findings list), with every
    diagnostic — including the findings summary — on stderr only."""
    import subprocess
    import sys

    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\nimport numpy as np\n\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return np.tanh(x)\n"   # GD001
    )
    proc = subprocess.run(
        [sys.executable, "-m", "graphdyn.analysis", str(bad),
         "--format=json"],
        capture_output=True, text=True, timeout=120,
    )
    # the WHOLE stdout is one JSON document — nothing before or after it
    findings = json.loads(proc.stdout)
    assert [f["code"] for f in findings] == ["GD001"]
    assert proc.returncode == 1
    # the summary is a diagnostic: stderr, never stdout
    assert "finding(s)" in proc.stderr
    assert "finding(s)" not in proc.stdout


def test_repo_package_is_clean():
    """The smoke test from the issue: graftlint over graphdyn/ reports zero
    undisabled findings (in-process — the subprocess variant lives in
    test_lint_gate.py)."""
    from pathlib import Path

    from graphdyn.analysis import lint_paths

    pkg = Path(__file__).resolve().parent.parent / "graphdyn"
    findings = lint_paths([str(pkg)])
    assert findings == [], findings


# ---------------------------------------------------------------------------
# @contract
# ---------------------------------------------------------------------------


class TestContract:
    def test_pass_and_symbol_binding(self):
        @contract(a="int8[r,n]", b="int32[n]", ret="int32[r]")
        def f(a, b):
            return (a.astype(jnp.int32) * b[None, :]).sum(axis=1)

        out = f(jnp.ones((4, 7), jnp.int8), jnp.ones((7,), jnp.int32))
        assert out.shape == (4,)

    def test_dtype_mismatch(self):
        @contract(a="int8[n]")
        def f(a):
            return a

        with pytest.raises(ContractError, match="dtype"):
            f(jnp.ones((3,), jnp.int32))

    def test_rank_mismatch(self):
        @contract(a="int8[r,n]")
        def f(a):
            return a

        with pytest.raises(ContractError, match="rank"):
            f(jnp.ones((3,), jnp.int8))

    def test_symbol_conflict_across_args(self):
        @contract(a="int32[n]", b="int32[n]")
        def f(a, b):
            return a + b

        with pytest.raises(ContractError, match="bound"):
            f(jnp.ones((3,), jnp.int32), jnp.ones((4,), jnp.int32))

    def test_return_checked_against_bound_symbols(self):
        @contract(a="int32[n]", ret="int32[n]")
        def f(a):
            return jnp.concatenate([a, a])

        with pytest.raises(ContractError, match="bound"):
            f(jnp.ones((3,), jnp.int32))

    def test_union_dtypes(self):
        @contract(a="float32|float64[n]")
        def f(a):
            return a

        f(jnp.ones((3,), jnp.float32))
        with pytest.raises(ContractError, match="dtype"):
            f(jnp.ones((3,), jnp.int32))

    def test_wildcards(self):
        @contract(a="*[_,n]", b="int32[n]")
        def f(a, b):
            return b

        f(jnp.ones((9, 5)), jnp.ones((5,), jnp.int32))

    def test_python_scalar_kind(self):
        @contract(lmbd="float32|float64[]")
        def f(x, lmbd):
            return x * lmbd

        f(jnp.ones(3), 0.5)                       # weak Python float OK
        f(jnp.ones(3), jnp.float32(0.5))
        with pytest.raises(ContractError):
            f(jnp.ones(3), jnp.ones((2,)))        # rank 1, wants scalar

    def test_checks_run_at_trace_time_only(self):
        """Under jit the wrapper runs per *trace*, not per call: conforming
        repeated calls hit the compile cache without re-entering it."""
        calls = {"n": 0}

        def spy(a):
            calls["n"] += 1
            return a * 2

        f = jax.jit(contract(a="int32[n]")(spy))
        x = jnp.ones((5,), jnp.int32)
        np.testing.assert_array_equal(f(x), 2 * np.ones(5))
        f(x)
        f(x)
        assert calls["n"] == 1  # traced once; checks cost nothing after

    def test_trace_time_rejection_under_jit(self):
        f = jax.jit(contract(a="int8[n]")(lambda a: a))
        with pytest.raises(ContractError):
            f(jnp.ones((3,), jnp.float32))

    def test_unknown_param_rejected_at_decoration(self):
        with pytest.raises(ValueError, match="unknown"):
            contract(nope="int8[n]")(lambda a: a)

    def test_tuple_return_spec(self):
        @contract(a="int32[n]", ret=("int32[n]", None))
        def f(a):
            return a, "aux"

        f(jnp.ones((3,), jnp.int32))

    def test_malformed_spec_rejected(self):
        with pytest.raises(ValueError):
            contract(a="int8[n")(lambda a: a)
        with pytest.raises(ValueError):
            contract(a="int8[n,,m]")(lambda a: a)


class TestContractedEntryPoints:
    """The shipped kernels carry their contracts."""

    def test_batched_rollout_rejects_wrong_spin_dtype(self):
        from graphdyn.graphs import random_regular_graph
        from graphdyn.ops.dynamics import batched_rollout

        g = random_regular_graph(32, 3, seed=0)
        s = np.ones((2, 32), np.int32)            # should be int8
        with pytest.raises(ContractError, match="int8"):
            batched_rollout(jnp.asarray(g.nbr), jnp.asarray(s), 2)

    def test_packed_rollout_rejects_mismatched_rows(self):
        from graphdyn.graphs import random_regular_graph
        from graphdyn.ops.packed import packed_rollout

        g = random_regular_graph(32, 3, seed=0)
        sp = jnp.zeros((31, 1), jnp.uint32)       # n mismatch vs nbr rows
        with pytest.raises(ContractError, match="bound"):
            packed_rollout(jnp.asarray(g.nbr), jnp.asarray(g.deg), sp, 2)

    def test_sweep_exec_rejects_nonsquare_chi(self):
        from graphdyn.graphs import random_regular_graph
        from graphdyn.ops.bdcm import BDCMData, make_sweep

        g = random_regular_graph(24, 3, seed=0)
        data = BDCMData(g, p=1, c=1)
        sweep = make_sweep(data, damp=0.3, use_pallas=False)
        chi = data.init_messages(seed=0)
        bad = jnp.concatenate([chi, chi], axis=2)  # [2E, K, 2K]
        with pytest.raises(ContractError):
            sweep(bad, jnp.float32(0.1))


class TestGD007ScriptsScope:
    """GD007 gates scripts/ too (the capture scripts persist round
    artifacts): a direct open-for-write there is a finding; routing the
    write through graphdyn.utils.io (or a temp + os.replace pair) is
    clean."""

    BAD = (
        "import json\n"
        "def persist(path, doc):\n"
        "    with open(path, \"w\") as f:\n"
        "        json.dump(doc, f)\n"
    )
    GOOD = (
        "from graphdyn.utils.io import write_json_atomic\n"
        "def persist(path, doc):\n"
        "    write_json_atomic(path, doc)\n"
    )
    GOOD_INLINE = (
        "import json, os\n"
        "def persist(path, doc):\n"
        "    tmp = path + \".tmp\"\n"
        "    with open(tmp, \"w\") as f:\n"
        "        json.dump(doc, f)\n"
        "    os.replace(tmp, path)\n"
    )

    def test_bad_script_write_flagged(self):
        assert "GD007" in _codes(self.BAD, path="scripts/capture_foo.py")

    def test_good_script_writes_clean(self):
        assert _codes(self.GOOD, path="scripts/capture_foo.py") == []
        assert _codes(self.GOOD_INLINE, path="scripts/capture_foo.py") == []

    def test_repo_scripts_are_clean(self):
        """The gate's own scope: every checked-in scripts/*.py lints clean
        (the same invocation scripts/lint.sh now runs by default)."""
        import subprocess
        import sys
        from pathlib import Path

        repo = Path(__file__).resolve().parent.parent
        proc = subprocess.run(
            [sys.executable, "-m", "graphdyn.analysis", "scripts/",
             "--format=json"],
            cwd=repo, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout[-2000:]
