"""Profiling/tracing hooks (SURVEY.md §5.1)."""

import numpy as np

from graphdyn.utils.profiling import StepTimer, device_trace, wall_clock


def test_step_timer_accumulates_and_rates():
    t = StepTimer()
    with t.measure(100):
        pass
    with t.measure(50):
        pass
    assert t.updates == 150
    assert t.seconds > 0
    assert t.updates_per_sec > 0
    assert StepTimer().updates_per_sec == 0.0    # no division by zero


def test_wall_clock_bracket():
    import time

    with wall_clock() as w:
        time.sleep(0.02)
    assert w["seconds"] >= 0.015        # a real measurement, not a zero


def test_device_trace_writes_profile(tmp_path):
    import jax.numpy as jnp

    logdir = str(tmp_path / "trace")
    with device_trace(logdir):
        jnp.arange(16).sum().block_until_ready()
    import os

    found = any(
        f.endswith((".pb", ".json.gz", ".trace.json.gz", ".xplane.pb"))
        for _, _, files in os.walk(logdir)
        for f in files
    )
    assert found, "no profiler artifact written"
